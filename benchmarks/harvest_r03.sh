#!/bin/bash
# Harvest the r03 TPU queue outputs (/tmp/tpu_r03) into checked-in
# artifacts. Run after `tpu_r03_queue.sh` reports steps OK. Idempotent;
# prints what it found and what it wrote. Commit separately after review.

set -u
cd "$(dirname "$0")/.."
IN=/tmp/tpu_r03
OUT=benchmarks/results

copy_json() {  # copy_json <src> <dst> <must-contain>
  local src=$1 dst=$2 needle=$3
  if [ -s "$src" ] && grep -q "$needle" "$src"; then
    cp "$src" "$dst"
    echo "wrote $dst"
  else
    echo "SKIP $dst ($src missing or lacks '$needle')"
  fi
}

echo "== headline =="
# bench_default.json is the full driver-shaped line; keep it verbatim as
# the round's recorded hardware evidence
copy_json "$IN/bench_default.json" "$OUT/r03_tpu_headline.json" reps_per_sec

echo "== gauss A/B =="
for f in pallas_boxmuller pallas_ndtri; do
  copy_json "$IN/$f.json" "$OUT/r03_$f.json" reps_per_sec
done
if [ -s "$OUT/r03_pallas_boxmuller.json" ] && [ -s "$OUT/r03_pallas_ndtri.json" ]; then
  python - <<'EOF'
import json
bm = json.load(open("benchmarks/results/r03_pallas_boxmuller.json"))
nd = json.load(open("benchmarks/results/r03_pallas_ndtri.json"))
b, n = bm["value"], nd["value"]
print(f"gauss A/B: boxmuller {b:.0f} vs ndtri {n:.0f} reps/sec "
      f"-> {'NDTRI WINS: flip the kernel default' if n > 1.02*b else 'keep boxmuller'}")
EOF
fi

echo "== config5 / suite =="
# the queue already tees these into benchmarks/results/ — just verify
for f in r03_tpu_config5.jsonl r03_tpu_suite.jsonl; do
  if [ -s "$OUT/$f" ]; then echo "present: $OUT/$f ($(wc -l < "$OUT/$f") lines)"
  else echo "MISSING: $OUT/$f"; fi
done

echo "== roofline =="
if [ -s "$OUT/r03_roofline.json" ]; then
  python -c "import json; d=json.load(open('$OUT/r03_roofline.json')); print('roofline:', d['summary'])"
else
  echo "MISSING: $OUT/r03_roofline.json"
fi
if [ -d "$OUT/trace_r03" ]; then
  du -sh "$OUT/trace_r03"
  echo "note: review trace size before committing (trim to the .trace/.json summary if huge)"
fi

echo "== reminders =="
echo "- update docs/STATUS_r03.md + docs/PERFORMANCE.md with the numbers"
echo "- decide subG fused: win -> keep, else retire fused='all' citing r03 A/B"
echo "- stop the watcher before session end: pgrep -fa r03_queue"
