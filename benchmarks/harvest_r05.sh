#!/bin/bash
# Harvest the r05 TPU queue outputs (/tmp/tpu_r05) into checked-in
# artifacts. Run after `tpu_r05_queue.sh` reports steps OK (the queue
# also runs it after every recovery pass). Idempotent; prints what it
# found and what it wrote. Commit separately after review.
#
# Every promotion passes a validity gate: queue steps write ONLY into
# the $IN quarantine, and nothing reaches benchmarks/results/ without
# (a) a completeness check (the step's expected terminal content) and
# (b) a device check where the artifact claims TPU evidence — a tunnel
# wedge mid-step must never leave a truncated or CPU-fallback file
# where a later commit could bank it.

set -u
cd "$(dirname "$0")/.."
# overridable for tests (tests/test_benchmarks.py harvests a fixture dir)
IN=${TPU_R05_IN:-/tmp/tpu_r05}
OUT=${TPU_R05_OUT:-benchmarks/results}

copy_json() {  # copy_json <src> <dst> <must-contain>
  local src=$1 dst=$2 needle=$3
  # a degraded CPU-fallback line still contains reps_per_sec — it must
  # never be banked as TPU evidence (bench.py cites these files back as
  # "recorded_tpu_evidence", which would become circular)
  if [ -s "$src" ] && grep -q "$needle" "$src" \
     && ! grep -q '"degraded"' "$src"; then
    cp "$src" "$dst"
    echo "wrote $dst"
  else
    echo "SKIP $dst ($src missing, lacks '$needle', or is degraded)"
  fi
}

copy_tpu_jsonl() {  # copy_tpu_jsonl <src> <dst> <final-needle>
  # run_all streams JSON lines; the first carries "device" and the
  # <final-needle> only appears in the last config's output, so its
  # presence certifies the stream ran to completion. Every line must
  # parse (a killed tee can truncate the final line mid-write).
  local src=$1 dst=$2 needle=$3
  if [ -s "$src" ] && grep -q "$needle" "$src" \
     && SRC="$src" python - <<'PY'
import json, os, sys

lines = [ln for ln in open(os.environ["SRC"]).read().splitlines() if ln.strip()]
try:
    parsed = [json.loads(ln) for ln in lines]
except json.JSONDecodeError:
    sys.exit(1)
dev = str(parsed[0].get("device", ""))
sys.exit(0 if ("TPU" in dev or "axon" in dev.lower()) else 1)
PY
  then
    cp "$src" "$dst"
    echo "wrote $dst"
  else
    echo "SKIP $dst ($src missing, truncated, incomplete, or not TPU)"
  fi
}

echo "== headline =="
# bench_default.json is the full driver-shaped line; keep it verbatim as
# the round's recorded hardware evidence
copy_json "$IN/bench_default.json" "$OUT/r05_tpu_headline.json" reps_per_sec

echo "== gauss A/B =="
for f in pallas_boxmuller pallas_ndtri; do
  copy_json "$IN/$f.json" "$OUT/r05_$f.json" reps_per_sec
done
if [ -s "$OUT/r05_pallas_boxmuller.json" ] && [ -s "$OUT/r05_pallas_ndtri.json" ]; then
  RES="$OUT" python - <<'PY'
import json
import os

res = os.environ["RES"]
bm = json.load(open(os.path.join(res, "r05_pallas_boxmuller.json")))
nd = json.load(open(os.path.join(res, "r05_pallas_ndtri.json")))
b, n = bm["value"], nd["value"]
print(f"gauss A/B: boxmuller {b:.0f} vs ndtri {n:.0f} reps/sec -> "
      + ("NDTRI WINS: flip the kernel default" if n > 1.02 * b
         else "keep boxmuller"))
PY
fi

echo "== config5 / suite =="
copy_tpu_jsonl "$IN/config5.jsonl" "$OUT/r05_tpu_config5.jsonl" stress_n1e6
copy_tpu_jsonl "$IN/suite.jsonl" "$OUT/r05_tpu_suite.jsonl" stress_n1e6

copy_checked_json() {  # copy_checked_json <src> <dst> <required-key>
  # ONE parse + TPU-device gate for every whole-JSON artifact: the file
  # must parse, contain <required-key> (only written when the producer
  # ran to completion), and carry a TPU/axon device stamp — a truncated
  # or CPU-fallback file must never be promoted under a _tpu name.
  local src=$1 dst=$2 key=$3
  if [ -s "$src" ] && SRC="$src" KEY="$key" python - <<'PY'
import json, os, sys

try:
    t = json.load(open(os.environ["SRC"]))
except json.JSONDecodeError:
    sys.exit(1)
dev = str(t.get("device", ""))
ok = (os.environ["KEY"] in t and ("TPU" in dev or "axon" in dev.lower()))
sys.exit(0 if ok else 1)
PY
  then
    cp "$src" "$dst"
    echo "wrote $dst"
  else
    echo "SKIP $dst ($src missing, truncated, incomplete, or not TPU)"
  fi
}

echo "== acceptance2 =="
# the campaign writer is atomic per point (.partial.tmp until complete)
copy_checked_json "$IN/acceptance_r05_tpu.json" \
  "$OUT/acceptance_r05_tpu.json" det_mc_pass

echo "== grid_merge A/B =="
copy_checked_json "$IN/grid_merge.json" \
  "$OUT/r05_grid_merge_tpu.json" merge_speedup_wall
if [ -s "$OUT/r05_grid_merge_tpu.json" ]; then
  SRC="$OUT/r05_grid_merge_tpu.json" python -c \
    'import json, os; d = json.load(open(os.environ["SRC"])); print("merge speedup:", d["merge_speedup_wall"], "x")'
fi

echo "== roofline =="
if [ -s "$IN/roofline.json" ] \
   && SRC="$IN/roofline.json" python -c \
     'import json, os, sys; t = json.load(open(os.environ["SRC"])); sys.exit(0 if "summary" in t and t.get("platform") in ("tpu", "axon") else 1)' 2>/dev/null
then
  cp "$IN/roofline.json" "$OUT/r05_roofline.json"
  echo "wrote $OUT/r05_roofline.json"
  if [ -d "$IN/trace_r05" ]; then
    rm -rf "$OUT/trace_r05"
    cp -r "$IN/trace_r05" "$OUT/trace_r05"
    du -sh "$OUT/trace_r05"
    echo "note: review trace size before committing (trim to the .trace/.json summary if huge)"
  fi
else
  echo "SKIP $OUT/r05_roofline.json (missing or truncated)"
fi

echo "== reminders =="
echo "- update docs/STATUS_r05.md + docs/PERFORMANCE.md with the numbers"
echo "- stop the watcher before session end: pgrep -fa r05_queue"
