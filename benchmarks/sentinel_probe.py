"""Sentinel live-detection probe: chaos-clean × tamper-hot (ISSUE 17).

The acceptance surface for the live invariant sentinel
(dpcorr.obs.sentinel / ``dpcorr obs watch``), proven against real
processes and real durable artifacts, in four arms:

1. **stream chaos-clean** — for every registered ``stream.*`` chaos
   point: a sentinel tails the workdir while the live server is
   killed at the point, restarted, and fed the *full* batch plan again
   (acked-batch dedup replay included). Gate: **zero** violations,
   from the attached sentinel and from a cold sentinel replaying the
   final artifacts.
2. **serve chaos-clean** — a serve replica under estimate traffic with
   its audit trail tailed and its ledger gauges scraped (the live
   ε-conservation check), killed with SIGKILL mid-run and restarted
   on the same trail. Gate: zero violations.
3. **tamper matrix** — per tamper class (WAL byte flip, duplicated
   charge line, re-noised release substitution, release-seq rewind):
   a fresh copy of a clean reference workdir is served by a live
   stream instance with a flight recorder armed; the sentinel polls
   clean, the tamper is injected, and the gate asserts the class is
   detected within 2 s as the expected typed violation naming the
   offending artifact, that the offender's recorder dumped with
   reason ``sentinel_violation``, and that a *restarted* sentinel on
   the same checkpoint stays silent (no re-alert).
4. **jax-free proof** — ``dpcorr obs watch`` runs to rc 0 in a
   subprocess where ``sys.modules['jax'] = None`` (any jax import
   explodes), and this driver itself never imports jax.

The JSON artifact carries every gate; CI (``sentinel-smoke``)
re-asserts from the artifact alone.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import stream_load as sl  # noqa: E402  (the reusable stream harness)

from dpcorr.obs.recorder import read_dump  # noqa: E402
from dpcorr.obs.sentinel import Sentinel  # noqa: E402

REPO = sl.REPO

#: tamper class → (expected violation kind, offending artifact suffix)
TAMPER_CLASSES = {
    "wal_byte_flip": ("wal-regression", "wal.jsonl"),
    "duplicate_charge": ("double-charged-artifact", "audit.jsonl"),
    "renoised_release": ("re-noised-artifact", "releases.jsonl"),
    "seq_rewind": ("wal-regression", "releases.jsonl"),
}

DETECT_WITHIN_S = 2.0


class _BatchArgs:
    seed = 2025
    windows = 4
    batches_per_window = 3
    rows_per_batch = 48


def _poll_n(sent, n, interval_s=0.2):
    for _ in range(n):
        sent.poll()
        time.sleep(interval_s)


# ------------------------------------------------ arm 1: stream chaos ----
def stream_chaos_clean(root: str) -> list[dict]:
    cases = []
    batches = sl._batches(_BatchArgs())
    for point in sl.STREAM_POINTS:
        tag = point.split(".")[-1]
        wd = os.path.join(root, f"chaos-{tag}")
        sent = Sentinel(os.path.join(root, f"ck-{tag}.json"))
        sent.add_stream("stream1", wd)
        proc, base, _ = sl._start(wd, f"point={point},hit=2,mode=exit")
        # everything but the far-future heartbeat: enough closed
        # windows that per-release points reach their second hit
        died, _ = sl._drive(base, batches[:-1])
        _poll_n(sent, 3)
        sl._stop(proc)
        # recover on the same workdir, then resend the FULL plan —
        # every already-acked batch replays as a dedup
        proc, base, _ = sl._start(wd, None)
        sent.poll()
        sl._drive(base, batches)
        _poll_n(sent, 5)
        sl._stop(proc)
        cold = Sentinel(os.path.join(root, f"ck-{tag}-cold.json"))
        cold.add_stream("stream1", wd)
        cold.poll()
        cases.append({
            "point": point, "died": bool(died),
            "violations": [v.to_dict() for v in sent.violations],
            "cold_violations": [v.to_dict() for v in cold.violations],
            "ok": (bool(died) and not sent.violations
                   and not cold.violations),
        })
    return cases


# ------------------------------------------------- arm 2: serve chaos ----
def _start_serve(audit: str, log_path: str):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DPCORR_CHAOS", None)
    log = open(log_path, "a")
    # the persisted ledger snapshot is what makes the scraped gauge
    # comparable to the trail fold ACROSS restarts — without it a
    # restarted replica legitimately starts its gauge from zero
    ledger = audit.replace("audit.jsonl", "ledger.json")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dpcorr", "serve", "--port", "0",
         "--platform", "cpu", "--budget", "1e9", "--audit", audit,
         "--ledger", ledger,
         "--aot", "off", "--max-delay-ms", "5"],
        cwd=REPO, env=env, text=True,
        stdout=subprocess.PIPE, stderr=log)
    banner = json.loads(proc.stdout.readline())["serving"]
    return proc, f"http://127.0.0.1:{banner['port']}", log


def _estimate(base: str, seed: int) -> dict:
    import random

    rs = random.Random(seed)
    x = [rs.gauss(0.0, 1.0) for _ in range(64)]
    y = [xi * 0.5 + rs.gauss(0.0, 1.0) for xi in x]
    req = urllib.request.Request(
        base + "/estimate",
        data=json.dumps({"family": "ni_sign", "x": x, "y": y,
                         "eps1": 0.5, "eps2": 0.5, "seed": seed}
                        ).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as resp:
        return json.loads(resp.read())


def serve_chaos_clean(root: str) -> dict:
    audit = os.path.join(root, "serve-audit.jsonl")
    log_path = os.path.join(root, "serve.log")
    sent = Sentinel(os.path.join(root, "ck-serve.json"))
    proc, base, log = _start_serve(audit, log_path)
    sent.add_audit("serve1", audit, url=base)
    try:
        for seed in range(3):
            _estimate(base, seed)
        _poll_n(sent, 3)
        # hard kill mid-service: the trail may carry a torn tail
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        _poll_n(sent, 2)
    finally:
        sl._stop(proc)
        log.close()
    # restart on the same trail (seq resumes contiguously), more load;
    # the sentinel keeps its fold across the restart, so the scraped
    # gauge (which resets to the persisted ledger) is exercised too
    proc, base, log = _start_serve(audit, log_path)
    try:
        sent2 = Sentinel(os.path.join(root, "ck-serve.json"))
        sent2.add_audit("serve1", audit, url=base)
        for seed in range(3, 6):
            _estimate(base, seed + 100)
        _poll_n(sent2, 4)
    finally:
        sl._stop(proc)
        log.close()
    violations = ([v.to_dict() for v in sent.violations]
                  + [v.to_dict() for v in sent2.violations])
    return {"violations": violations, "ok": not violations}


# ------------------------------------------------ arm 3: tamper matrix ----
def _inject(cls: str, wd: str) -> str:
    """Apply one tamper class to a quiescent workdir; returns the
    tampered artifact path."""
    wal = os.path.join(wd, "wal.jsonl")
    audit = os.path.join(wd, "audit.jsonl")
    journal = os.path.join(wd, "releases.jsonl")
    if cls == "wal_byte_flip":
        with open(wal, "r+b") as f:
            f.seek(4)
            byte = f.read(1)
            f.seek(4)
            f.write(b"X" if byte != b"X" else b"Y")
        return wal
    if cls == "duplicate_charge":
        with open(audit, encoding="utf-8") as f:
            for line in f:
                if '"charge"' in line:
                    break
        with open(audit, "a", encoding="utf-8") as f:
            f.write(line)
        return audit
    with open(journal, encoding="utf-8") as f:
        entries = [json.loads(line) for line in f if line.strip()]
    if cls == "renoised_release":
        sub = dict(entries[0])
        sub["releases"] = {k: {"tampered": 1}
                           for k in sub.get("releases", {})} or \
            {"ni_sign": {"tampered": 1}}
        sub["release_seq"] = max(e["release_seq"] for e in entries) + 1
    else:  # seq_rewind: fresh window id, stale seq
        sub = dict(entries[-1])
        sub["window_id"] = "999000-999999"
        sub["charge_id"] = "stream:bench:999000-999999"
        sub["release_seq"] = 1
    with open(journal, "a", encoding="utf-8") as f:
        f.write(json.dumps(sub) + "\n")
    return journal


def _make_reference(root: str) -> str:
    """One clean completed stream run — the tamper arms each copy it."""
    ref = os.path.join(root, "reference")
    proc, base, _ = sl._start(ref, None)
    sl._drive(base, sl._batches(_BatchArgs()))
    time.sleep(0.5)
    sl._stop(proc)
    return ref

def tamper_matrix(root: str, interval_s: float = 0.25) -> list[dict]:
    ref = _make_reference(root)
    cases = []
    for cls, (want_kind, want_artifact) in TAMPER_CLASSES.items():
        wd = os.path.join(root, f"tamper-{cls}")
        shutil.copytree(ref, wd)
        rec = os.path.join(root, f"rec-{cls}.json")
        ck = os.path.join(root, f"ck-{cls}.json")
        # a live server on the copied workdir, flight recorder armed —
        # the offender the sentinel must page and arm
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("DPCORR_CHAOS", None)
        proc = subprocess.Popen(
            sl._server_argv(wd) + ["--flight-recorder", rec,
                                   "--instance", f"stream-{cls}"],
            cwd=REPO, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        banner = json.loads(proc.stdout.readline())["streaming"]
        base = f"http://127.0.0.1:{banner['port']}"
        try:
            sent = Sentinel(ck, urls={"stream1": base})
            sent.add_stream("stream1", wd, url=base)
            _poll_n(sent, 2, interval_s)
            clean = not sent.violations
            artifact = _inject(cls, wd)
            t0 = time.monotonic()
            detected_s = None
            while time.monotonic() - t0 < DETECT_WITHIN_S + 1.0:
                if sent.poll():
                    detected_s = time.monotonic() - t0
                    break
                time.sleep(interval_s)
            kinds = sorted({v.kind for v in sent.violations})
            named = any(v.artifact == artifact or "party" in v.artifact
                        for v in sent.violations)
            time.sleep(0.3)  # let the trigger POST land + dump fsync
            try:
                armed = read_dump(rec).get("reason") == \
                    "sentinel_violation"
            except (OSError, ValueError):
                armed = False
            # crash-exactness of the auditor itself: a restarted
            # sentinel on the same checkpoint never re-alerts
            resumed = Sentinel(ck, urls={"stream1": base})
            resumed.add_stream("stream1", wd, url=base)
            resumed.poll()
            silent_after_restart = not resumed.violations
        finally:
            sl._stop(proc)
        cases.append({
            "class": cls, "expected_kind": want_kind,
            "expected_artifact": want_artifact,
            "clean_before_tamper": clean,
            "detected_s": detected_s, "kinds": kinds,
            "artifact_named": named,
            "recorder_armed": armed,
            "silent_after_restart": silent_after_restart,
            "violations": [v.to_dict() for v in sent.violations],
            "ok": (clean and detected_s is not None
                   and detected_s <= DETECT_WITHIN_S
                   and want_kind in kinds and named and armed
                   and silent_after_restart),
        })
    return cases


# ---------------------------------------------------- arm 4: jax-free ----
def jax_free_proof(root: str) -> dict:
    wd = os.path.join(root, "reference")
    ck = os.path.join(root, "ck-jaxfree.json")
    script = (
        "import sys\n"
        "sys.modules['jax'] = None\n"  # any jax import explodes
        "sys.argv = ['dpcorr', 'obs', 'watch', '--checkpoint', %r,"
        " '--stream', 'ize=%s', '--once', '--json']\n"
        "from dpcorr.__main__ import main\n"
        "main()\n" % (ck, wd))
    run = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                         capture_output=True, text=True, timeout=300)
    return {"rc": run.returncode, "stderr": run.stderr[-2000:],
            "ok": run.returncode == 0}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="sentinel-probe-artifacts")
    ap.add_argument("--out-json", dest="out_json", default=None)
    ap.add_argument("--skip-serve", action="store_true",
                    help="skip the serve arm (it needs the jax stack; "
                         "the stream arms are jax-free end to end)")
    args = ap.parse_args()
    root = os.path.abspath(args.workdir)
    os.makedirs(root, exist_ok=True)

    t0 = time.monotonic()
    chaos = stream_chaos_clean(root)
    tampers = tamper_matrix(root)
    jaxfree = jax_free_proof(root)
    serve = ({"skipped": True, "ok": True} if args.skip_serve
             else serve_chaos_clean(root))

    doc = {
        "bench": "sentinel_probe", "version": 1,
        "wall_s": time.monotonic() - t0,
        "detect_within_s": DETECT_WITHIN_S,
        "stream_chaos": chaos,
        "serve_chaos": serve,
        "tampers": tampers,
        "jax_free": jaxfree,
        "ok": (all(c["ok"] for c in chaos)
               and all(c["ok"] for c in tampers)
               and jaxfree["ok"] and serve["ok"]),
    }
    # the driver itself must never have pulled in jax: the sentinel is
    # an operator tool, usable where no accelerator stack exists
    doc["driver_jax_free"] = "jax" not in sys.modules
    doc["ok"] = doc["ok"] and doc["driver_jax_free"]

    if args.out_json:
        with open(args.out_json, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    print(json.dumps({k: doc[k] for k in
                      ("bench", "ok", "wall_s", "driver_jax_free")},
                     indent=2))
    for c in chaos:
        print(f"  chaos {c['point']}: "
              f"{'clean' if c['ok'] else 'VIOLATIONS'}")
    for c in tampers:
        print(f"  tamper {c['class']}: kinds={c['kinds']} "
              f"in {c['detected_s'] if c['detected_s'] is not None else '—'}s "
              f"armed={c['recorder_armed']} "
              f"restart-silent={c['silent_after_restart']}")
    print(f"  serve: {'clean' if serve['ok'] else 'VIOLATIONS'}"
          f"{' (skipped)' if serve.get('skipped') else ''}")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
