"""Load + chaos benchmark for the dpcorr stream subsystem (ISSUE 16).

Three arms, one JSON document, exit 1 if any gate fails:

1. **Sketch associativity** (in-process) — for every family,
   ``release_window`` over several shard partitions of the chunk grid
   must be *bitwise* identical to the monolithic pass
   (``json.dumps(..., sort_keys=True)`` equality).
2. **Reference run** (real process) — ``python -m dpcorr stream`` over
   HTTP, a single-threaded client interleaving two shards' batches in a
   fixed order plus a far-future heartbeat; records the release feed,
   the ledger, and the windows/s throughput stamp.
3. **Kill / restart** — for each registered ``stream.*`` chaos point,
   a fresh server with ``DPCORR_CHAOS=point=...,mode=exit`` dies mid-run
   (``os._exit(42)`` — no flushes, the honest kill); the harness
   restarts the identical command line and the client re-sends ALL
   batches in the same fixed order (acked ones dedup via the WAL
   seen-set). Gates, per case:

   - the server actually died with rc 42 at the planned point;
   - the recovered ``/releases`` feed is **byte-identical** to the
     uninterrupted reference;
   - exact ε: every party's ledger spend equals
     ``released_windows x per-window charge`` — the idempotent
     ``stream:<id>:<window>`` charge ids absorbed every replay;
   - the jax-free ``dpcorr obs budget`` audit replay reproduces the
     ledger's spent table exactly (the laptop-auditor contract).

Usage:
    python benchmarks/stream_load.py [--rows-per-batch 48]
        [--batches-per-window 3] [--windows 4] [--out-json PATH]
        [--stamp PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAMILIES = ("ni_sign", "ni_subg", "int_sign", "int_subg")
STREAM_POINTS = ("stream.mid_window", "stream.pre_release",
                 "stream.post_journal")
WINDOW_S = 2.0
EPS = 0.4


# ---------------------------------------------------------- clients ----
def _post(base: str, path: str, payload: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(base: str, path: str, timeout: float = 30.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as resp:
        return resp.read()


def _batches(args) -> list[tuple[str, float, list]]:
    """The fixed batch plan: two shards' batches interleaved
    deterministically across ``--windows`` tumbling windows, then one
    far-future heartbeat that closes everything. The SAME list, in the
    SAME order, is what every arm (and every recovery re-send) plays —
    fixed order is what makes the feed a pure function of the plan."""
    import numpy as np

    r = np.random.default_rng(args.seed)
    out = []
    for w in range(args.windows):
        for b in range(args.batches_per_window):
            shard = "a" if b % 2 == 0 else "b"
            ts = w * WINDOW_S + (b + 0.5) * WINDOW_S \
                / (args.batches_per_window + 1)
            xy = r.multivariate_normal(
                [0.0, 0.0], [[1.0, 0.6], [0.6, 1.0]],
                size=args.rows_per_batch)
            rows = [[round(float(x), 6), round(float(y), 6)]
                    for x, y in np.clip(xy, -3.0, 3.0)]
            out.append((f"shard-{shard}:w{w}b{b}", ts, rows))
    out.append(("heartbeat:final", args.windows * WINDOW_S + 1e6, []))
    return out


# ----------------------------------------------------------- server ----
def _server_argv(workdir: str) -> list[str]:
    return [sys.executable, "-m", "dpcorr", "stream",
            "--workdir", workdir, "--port", "0",
            "--window-s", str(WINDOW_S),
            "--families", "ni_sign,int_subg",
            "--eps1", str(EPS), "--eps2", str(EPS),
            "--normalise", "on", "--budget", "100", "--seed", "2025"]


def _start(workdir: str, chaos_spec: str | None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DPCORR_CHAOS", None)
    if chaos_spec:
        env["DPCORR_CHAOS"] = chaos_spec
    proc = subprocess.Popen(
        _server_argv(workdir), cwd=REPO, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    banner = json.loads(proc.stdout.readline())["streaming"]
    return proc, f"http://127.0.0.1:{banner['port']}", banner


def _stop(proc) -> None:
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    if proc.stdout:
        proc.stdout.close()


def _drive(base: str, batches) -> tuple[bool, float]:
    """Send the full plan; returns (server_died_mid_send, wall_s). A
    dropped connection means the chaos kill fired — the real-client
    contract is simply 'anything unacked gets re-sent after restart'."""
    t0 = time.perf_counter()
    for bid, ts, rows in batches:
        try:
            _post(base, "/ingest",
                  {"batch_id": bid, "ts": ts, "rows": rows})
        except (urllib.error.URLError, ConnectionError, OSError):
            return True, time.perf_counter() - t0
    return False, time.perf_counter() - t0


def _feed_and_stats(base: str) -> tuple[str, dict]:
    feed = json.loads(_get(base, "/releases?since=0"))["releases"]
    stats = json.loads(_get(base, "/stats"))
    return json.dumps(feed, sort_keys=True), stats


def _audit_replay_spent(workdir: str) -> dict:
    """The jax-free laptop audit: ``dpcorr obs budget`` replays the
    durable trail with nothing but a checkout."""
    out = subprocess.run(
        [sys.executable, "-m", "dpcorr", "obs", "budget",
         "--audit", os.path.join(workdir, "audit.jsonl"), "--json"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, check=True)
    return json.loads(out.stdout)["spent"]


# ------------------------------------------------------------- arms ----
def _assoc_arm(args) -> dict:
    from dpcorr.stream import sketch
    from dpcorr.utils.rng import master_key

    import numpy as np

    r = np.random.default_rng(args.seed)
    n = args.assoc_n
    xy = np.clip(r.normal(size=(n, 2)), -3.0, 3.0).astype(np.float32)
    wkey_master = master_key(args.seed)
    out = {}
    for family in FAMILIES:
        params = sketch.ReleaseParams(family, 0.9, 0.7, normalise=True,
                                      target_chunk=args.assoc_chunk)
        grid = sketch.grid_for(params, n)
        wkey = sketch.window_key(wkey_master, "0-2000")
        t0 = time.perf_counter()
        ref = json.dumps(sketch.release_window(xy, params, wkey),
                         sort_keys=True)
        dt = time.perf_counter() - t0
        ids = list(range(grid.n_chunks))
        splits = {"even_odd": [ids[0::2], ids[1::2]],
                  "head_tail": [ids[:1], ids[1:]],
                  "singletons_reversed": [[c] for c in reversed(ids)]}
        ok = all(
            json.dumps(sketch.release_window(xy, params, wkey,
                                             shards=s),
                       sort_keys=True) == ref
            for s in splits.values())
        out[family] = {"n": n, "chunks": grid.n_chunks,
                       "partitions": len(splits),
                       "monolithic_s": round(dt, 4), "bitwise_ok": ok}
    return out


def _expected_spent(released: int) -> dict:
    """windows x per-window charge, from the same release_factor math
    the service itself uses (an independent derivation would be a
    second place the cost model could drift)."""
    from dpcorr.stream.service import window_charges

    per = window_charges(["ni_sign", "int_subg"], EPS, EPS, True,
                         "party/x", "party/y")
    return {p: released * v for p, v in per.items()}


def _eps_gates(stats: dict, workdir: str, released: int) -> dict:
    want = _expected_spent(released)
    ledger = {p: v["spent"]
              for p, v in stats["ledger"]["parties"].items()}
    replay = _audit_replay_spent(workdir)
    exact = all(abs(ledger.get(p, 0.0) - e) < 1e-9
                for p, e in want.items()) and set(ledger) == set(want)
    replay_eq = (set(replay) == set(ledger)
                 and all(abs(replay[p] - ledger[p]) < 1e-9
                         for p in ledger))
    return {"expected": want, "ledger": ledger, "audit_replay": replay,
            "eps_exact": exact, "audit_replay_equal": replay_eq}


def _reference_arm(args, workdir: str, batches) -> dict:
    proc, base, _banner = _start(workdir, None)
    try:
        died, wall = _drive(base, batches)
        assert not died, "reference run lost its server"
        feed, stats = _feed_and_stats(base)
    finally:
        _stop(proc)
    released = stats["released"]
    return {"feed": feed, "stats": stats, "ingest_wall_s": wall,
            "released": released,
            "windows_per_sec": round(released / wall, 3) if wall else None,
            "eps": _eps_gates(stats, workdir, released)}


def _chaos_case(args, workdir: str, batches, point: str,
                ref_feed: str) -> dict:
    spec = f"point={point},hit=1,mode=exit"
    proc, base, _ = _start(workdir, spec)
    died, _ = _drive(base, batches)
    rc = proc.wait(timeout=60)
    if proc.stdout:
        proc.stdout.close()
    case = {"point": point, "server_died_mid_send": died,
            "kill_rc": rc, "kill_rc_42": rc == 42}
    # identical command line, no chaos: recovery + full re-send
    proc2, base2, banner2 = _start(workdir, None)
    try:
        died2, _ = _drive(base2, batches)
        assert not died2, f"{point}: recovered server died again"
        feed, stats = _feed_and_stats(base2)
    finally:
        _stop(proc2)
    case["recovered_preexisting_releases"] = banner2["released"]
    case["feed_bit_identical"] = feed == ref_feed
    case.update(_eps_gates(stats, workdir, stats["released"]))
    case["ok"] = bool(case["kill_rc_42"] and case["feed_bit_identical"]
                      and case["eps_exact"]
                      and case["audit_replay_equal"])
    return case


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows-per-batch", type=int, default=48)
    ap.add_argument("--batches-per-window", type=int, default=3)
    ap.add_argument("--windows", type=int, default=4)
    ap.add_argument("--seed", type=int, default=777)
    ap.add_argument("--assoc-n", type=int, default=2000)
    ap.add_argument("--assoc-chunk", type=int, default=512)
    ap.add_argument("--out-json", default=None)
    ap.add_argument("--stamp", default=None,
                    help="write a bench-trajectory point "
                         "(stream_windows_per_sec) to this path")
    args = ap.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    batches = _batches(args)
    doc = {"benchmark": "stream_load",
           "config": {"rows_per_batch": args.rows_per_batch,
                      "batches_per_window": args.batches_per_window,
                      "windows": args.windows, "window_s": WINDOW_S,
                      "families": ["ni_sign", "int_subg"], "eps": EPS,
                      "seed": args.seed},
           "ok": True}

    doc["associativity"] = _assoc_arm(args)
    assoc_ok = all(f["bitwise_ok"] for f in doc["associativity"].values())
    print("associativity: " + " ".join(
        f"{f}={v['bitwise_ok']}" for f, v in doc["associativity"].items()),
        file=sys.stderr)

    with tempfile.TemporaryDirectory() as td:
        ref = _reference_arm(args, os.path.join(td, "ref"), batches)
        doc["reference"] = {k: v for k, v in ref.items()
                            if k not in ("feed", "stats")}
        print(f"reference: released={ref['released']} "
              f"windows/s={ref['windows_per_sec']} "
              f"eps_exact={ref['eps']['eps_exact']} "
              f"replay_equal={ref['eps']['audit_replay_equal']}",
              file=sys.stderr)
        doc["chaos"] = []
        for point in STREAM_POINTS:
            case = _chaos_case(args, os.path.join(td, point), batches,
                               point, ref["feed"])
            doc["chaos"].append(case)
            print(f"{point}: rc42={case['kill_rc_42']} "
                  f"feed_identical={case['feed_bit_identical']} "
                  f"eps_exact={case['eps_exact']} "
                  f"replay_equal={case['audit_replay_equal']}",
                  file=sys.stderr)

    doc["ok"] = bool(
        assoc_ok
        and ref["released"] == args.windows
        and ref["eps"]["eps_exact"] and ref["eps"]["audit_replay_equal"]
        and all(c["ok"] for c in doc["chaos"]))

    if args.stamp and doc["ok"] and ref["windows_per_sec"]:
        stamp = {"metric": "stream_windows_per_sec",
                 "value": ref["windows_per_sec"],
                 "unit": "windows/s", "device_kind": "cpu",
                 "detail": {"windows": args.windows,
                            "rows_per_window": args.rows_per_batch
                            * args.batches_per_window,
                            "families": ["ni_sign", "int_subg"],
                            "benchmark": "stream_load"}}
        with open(args.stamp, "w") as f:
            json.dump(stamp, f, indent=2)
            f.write("\n")

    print(json.dumps(doc, indent=2))
    if args.out_json:
        with open(args.out_json, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
