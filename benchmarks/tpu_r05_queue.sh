#!/bin/bash
# Round-5 TPU validation queue (supersedes tpu_r04_queue.sh; kill any
# stale r04 watcher before launching — two watchers would race for the
# exclusive TPU client).
#
# Ordering contract (VERDICT r2-r4): bank the headline FIRST; everything
# that has ever wedged the tunnel (fresh Mosaic compiles) runs strictly
# after every pure-XLA evidence step.
#
# Steps, in order:
#   1. bench_default  — `python bench.py` headline. THE r05 deliverable.
#   2. config5        — streaming subG n=10^6 stress, first on-chip
#                       (VERDICT r4 ask #2).
#   3. acceptance2    — HRS-shape (n=19433, eps=2) B=2^20 det/mc twin
#                       (VERDICT r4 ask #3; the CPU B=2^18 insurance twin
#                       acceptance_r04_hrs_cpu_2e18.json measured diff
#                       1.03e-3 at MC SE 4.3e-4 — this halves the SE).
#   4. suite          — full 5-config BASELINE suite (VERDICT r4 ask #2).
#   5. roofline       — refresh the roofline + trace at r05 HEAD.
#   6. grid_merge     — eps-merged subG bucket A/B (bucket_merge="eps",
#                       pure XLA: 15 vs 5 compiles through the tunnel;
#                       CPU already measured 1.28x, PERFORMANCE.md).
#   7. pallas_boxmuller — gauss A/B baseline arm (usually compile-cached).
#   8. pallas_ndtri   — gauss A/B's other arm, LEASHED to 480 s total
#                       (VERDICT r4 ask #4: its uncached Mosaic compile
#                       hung 900 s and wedged the tunnel at r04 03:36Z —
#                       one bounded attempt, then the cap below retires
#                       it). boxmuller stays the kernel default either
#                       way (r04_pallas_boxmuller.json: 953,775 >= XLA).
#   9. grid_fused_smoke — fused CLI grid end-to-end (--b 8; fused=auto
#                       Mosaic-compiles, so it lives in this block).
#
# grid_fused_subg is GONE: STATUS_r04's written deadline decision
# ("if the tunnel stays dead through this round, fused='all' is retired
# citing r02_grid_fused_subg_tpu.json") triggered — the tunnel died at
# 03:36Z and stayed dead through round end — so round 5 executed the
# retirement surgery instead of re-gambling chip time on a kernel
# measured at 0.98x XLA.
#
# Wedge cap (see tpu_r04_queue.sh history): a Mosaic-risky step that
# wedges the tunnel THREE times is marked .fail as the wedge's cause;
# pure-XLA steps are never capped.
#
# Results land in /tmp/tpu_r05/; harvest with benchmarks/harvest_r05.sh.

set -u -o pipefail
OUT=${TPU_R05_IN:-/tmp/tpu_r05}
mkdir -p "$OUT"

sweep_strays() {
  # Shell mirror of the canonical dpcorr.utils.doctor rule: a bench
  # worker reparented to init holds the exclusive TPU client forever and
  # masquerades as a wedged tunnel (observed live in r04).
  local pid
  for pid in $(pgrep -f "bench\.py --worker" 2>/dev/null); do
    [ "$pid" = "$$" ] && continue
    if [ "$(ps -o ppid= -p "$pid" 2>/dev/null | tr -d ' ')" = "1" ]; then
      kill -9 "$pid" 2>/dev/null && echo "swept stray TPU client $pid ($(date -u +%H:%M:%SZ))"
    fi
  done
}

probe() {
  if [ -n "${TPU_R05_PROBE:-}" ]; then eval "$TPU_R05_PROBE"; return; fi
  sweep_strays
  # Fast gate: when the relay endpoint is dead every relay port refuses
  # TCP instantly and the full jax probe can only burn its 150 s
  # timeout. The port list and check live canonically in
  # dpcorr.utils.doctor (DPCORR_RELAY_PORTS overrides). rc semantics
  # (ADVICE r04): ONLY an explicit ports-refused verdict (rc 1) counts
  # as a gate negative — a timeout-124 (slow interpreter start; the
  # site hook preloads JAX) or an import error is INCONCLUSIVE and
  # falls through to the authoritative jax probe, so a live tunnel can
  # never be reported dead by a slow gate.
  timeout 20 python - <<'PY' >/dev/null 2>&1
import sys

from dpcorr.utils.doctor import check_relay

sys.exit(0 if check_relay()["alive"] else 1)
PY
  local rc=$?
  if [ "$rc" -eq 1 ]; then
    # Because the port list is infra-owned and could go stale, every
    # 8th consecutive gate-negative runs the full jax probe anyway — a
    # wrong port list degrades to slow polling, never to evidence loss.
    local g=0
    [ -s "$OUT/.gate_negatives" ] && g=$(cat "$OUT/.gate_negatives")
    g=$((g + 1)); echo "$g" > "$OUT/.gate_negatives"
    [ $((g % 8)) -ne 0 ] && return 1
  fi
  timeout 150 python -c \
    "import jax; assert jax.devices()[0].platform in ('tpu','axon'); import jax.numpy as jnp; print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))" \
    >/dev/null 2>&1
}

WEDGED=0
run_step() {  # run_step <name> <cmd...>: honor markers, classify failures
  local name=$1; shift
  [ "$WEDGED" = 1 ] && return
  if [ -e "$OUT/$name.ok" ]; then
    echo "-- $name: already done, skipping"
    return
  fi
  if [ -e "$OUT/$name.fail" ]; then
    echo "-- $name: failed genuinely earlier, not retrying"
    return
  fi
  echo "== $name ($(date -u +%H:%M:%SZ)) =="
  if "$@"; then
    touch "$OUT/$name.ok"
    echo "-- $name: OK ($(date -u +%H:%M:%SZ))"
  elif probe; then
    # tunnel alive -> the step itself is broken; don't burn retries on it
    touch "$OUT/$name.fail"
    echo "-- $name: FAILED genuinely ($(date -u +%H:%M:%SZ))"
  else
    # tunnel wedged mid-queue -> normally no marker; resume here on next
    # recovery. Mosaic-risky steps are capped at 3 wedges (the step IS
    # the wedge cause, Mosaic-compile-hang class); pure-XLA steps are
    # never capped (load-induced outages are the tunnel's fault).
    WEDGED=1
    if [[ " $MOSAIC_STEPS " == *" $name "* ]]; then
      local w=0
      [ -s "$OUT/$name.wedges" ] && w=$(cat "$OUT/$name.wedges")
      w=$((w + 1)); echo "$w" > "$OUT/$name.wedges"
      if [ "$w" -ge 3 ]; then
        echo "wedged the tunnel ${w}x; classified as wedge cause" > "$OUT/$name.fail"
        echo "-- $name: wedged the tunnel ${w}x; marked .fail, skipping henceforth ($(date -u +%H:%M:%SZ))"
        return
      fi
    fi
    echo "-- $name: tunnel wedged mid-step; back to polling ($(date -u +%H:%M:%SZ))"
  fi
}

all_steps() {
  run_step bench_default bash -c \
    'timeout 1800 python bench.py 2>"'$OUT'/bench_default.err" \
     | tail -1 | tee "'$OUT'/bench_default.json" \
     | grep "reps_per_sec" | grep -qv "\"degraded\""'
  # (a degraded CPU-fallback line still prints reps_per_sec — only an
  # undegraded line counts as the banked headline)

  # --- pure-XLA evidence block: no fresh Mosaic compiles, safe ---
  # Every step writes into $OUT quarantine; only harvest_r05.sh's
  # validity gates promote outputs into checked-in benchmarks/results/
  # (a tunnel wedge mid-step must never leave a truncated artifact
  # where a later commit could bank it).

  run_step config5 bash -c \
    'set -o pipefail; timeout 3000 python -m benchmarks.run_all --config 5 \
     2>"'$OUT'/config5.err" \
     | tee "'$OUT'/config5.jsonl" \
     | grep -q stress_n1e6'

  run_step acceptance2 bash -c \
    'timeout 5400 python benchmarks/acceptance_point2.py --n 19433 \
     --eps 2.0 --log2b 20 \
     --out "'$OUT'/acceptance_r05_tpu.json" \
     2>"'$OUT'/acceptance2.err" | tail -1 | grep -q det_mc'

  run_step suite bash -c \
    'set -o pipefail; timeout 7200 python -m benchmarks.run_all --full \
     2>"'$OUT'/suite.err" \
     | tee "'$OUT'/suite.jsonl" \
     | grep -q stress_n1e6'

  run_step roofline bash -c \
    'timeout 1200 python -m benchmarks.roofline --budget 15 \
     --trace "'$OUT'/trace_r05" \
     --out "'$OUT'/roofline.json" \
     2>"'$OUT'/roofline.err" | tail -1 | grep -q reps_per_sec'

  run_step grid_merge bash -c \
    'timeout 2400 python benchmarks/grid_merge_tpu.py \
     --out "'$OUT'/grid_merge.json" \
     2>"'$OUT'/grid_merge.err" | tail -2 | grep -q wrote'

  # --- Mosaic-risky block: fresh kernel compiles, wedge suspects ---

  run_step pallas_boxmuller bash -c \
    'timeout 900 python bench.py --worker tpu-pallas --budget 20 \
     2>"'$OUT'/pallas_bm.err" | tail -1 \
     | tee "'$OUT'/pallas_boxmuller.json" | grep -q "reps_per_sec"'

  run_step pallas_ndtri bash -c \
    'DPCORR_BENCH_PALLAS_GAUSS=ndtri \
     timeout 480 python bench.py --worker tpu-pallas --budget 20 \
     2>"'$OUT'/pallas_nd.err" | tail -1 \
     | tee "'$OUT'/pallas_ndtri.json" | grep -q "reps_per_sec"'

  run_step grid_fused_smoke bash -c \
    'timeout 900 python -m dpcorr grid --backend bucketed --fused auto \
     --b 8 2>"'$OUT'/grid.err" | tail -2 \
     | tee "'$OUT'/grid_fused_smoke.txt" | grep -q "INT"'
}

STEP_NAMES="bench_default config5 acceptance2 suite roofline grid_merge \
pallas_boxmuller pallas_ndtri grid_fused_smoke"

# Steps whose own fresh Mosaic compile is the plausible wedge CAUSE; only
# these are subject to the wedge cap. pallas_boxmuller belongs here too:
# usually compile-cached, but on a cold cache it Mosaic-compiles exactly
# like the others.
MOSAIC_STEPS="pallas_boxmuller pallas_ndtri grid_fused_smoke"

finished() {  # every step has a terminal marker
  local s
  for s in $STEP_NAMES; do
    [ -e "$OUT/$s.ok" ] || [ -e "$OUT/$s.fail" ] || return 1
  done
  return 0
}

# sourcing (tests) stops here: the functions above are the testable
# surface; the cwd change and polling loop below only apply when
# executed directly
if [ "${BASH_SOURCE[0]}" != "$0" ]; then return 0; fi

cd "$(dirname "$0")/.."
# No DPCORR_COMPILE_CACHE export: bench.py steps use their per-user
# default cache on their own (pre-warming the driver's round-end run),
# while the grid/run_all steps stay COLD so their wall-times remain
# comparable to the r02 cold-start numbers.

for i in $(seq 1 300); do
  if probe; then
    echo "tunnel healthy at attempt $i ($(date -u +%H:%M:%SZ))"
    WEDGED=0
    all_steps
    # harvest whatever is banked so far (idempotent; rejects degraded
    # lines) — evidence must reach benchmarks/results/ the moment it
    # exists, not only after a full queue pass survives the tunnel
    bash benchmarks/harvest_r05.sh || true
    if finished; then
      ok=0; fail=0
      for s in $STEP_NAMES; do
        if [ -e "$OUT/$s.ok" ]; then ok=$((ok + 1)); else fail=$((fail + 1)); fi
      done
      cat "$OUT"/*.json 2>/dev/null
      echo "r05 queue finished ($(date -u +%H:%M:%SZ)): $ok OK, $fail failed"
      exit $fail
    fi
    echo "queue interrupted by wedge; resuming poll ($(date -u +%H:%M:%SZ))"
  fi
  sleep 110
done
echo "tunnel never recovered within the polling window"
exit 1
