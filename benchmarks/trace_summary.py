"""Summarize a trace — a ``jax.profiler`` dir OR an obs span JSONL log.

Two input shapes, one CLI:

- a profiler **directory**: turns the Perfetto-style ``*.trace.json.gz``
  that ``jax.profiler.trace`` writes (under
  ``<dir>/plugins/profile/<ts>/``) into the numbers PERFORMANCE.md
  §roofline cites — total wall window, device-resident time of the
  jit'd program, and the top fusions by accumulated duration;
- a span **JSONL file** (``dpcorr.obs.trace`` output, e.g. ``serve
  --trace``): per-span-name count / total / p50 / p99 durations via
  :func:`summarize_spans`, using the serving stack's own nearest-rank
  percentile implementation so a p99 here means the same thing as the
  ``/stats`` p99.

The reference has no profiling at all (SURVEY.md §5 "Tracing/profiling:
absent"); this is the TPU build's observability half of that subsystem —
`benchmarks/roofline.py` captures, this file reduces.

Usage::

    python -m benchmarks.trace_summary benchmarks/results/trace_r04
    python -m benchmarks.trace_summary <dir> --top 10 --json
    python -m benchmarks.trace_summary /tmp/serve_spans.jsonl --json

Heuristics (kept deliberately simple and assert-guarded): JAX emits the
compiled program as a ``jit_<name>(...)`` slice with XLA ops
(``fusion.N``, ``while.N``, ...) nested under it; Python-side frames
carry ``$file.py:line`` names. We classify a slice as *device op* when
its name matches an XLA opcode pattern and as *program* when it matches
``jit_`` / ``while`` wrappers.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re

# XLA HLO-ish slice names: fusion.12, select_multiply_fusion.2, copy.3,
# while.6, dynamic-update-slice.1 ...
_XLA_RE = re.compile(r"^[a-z][a-z0-9_.-]*\.\d+$")
_PROGRAM_RE = re.compile(r"^(jit_?|while\.)")


def find_trace_file(trace_dir: str) -> str:
    """Locate the newest ``*.trace.json.gz`` under a profiler dir."""
    pats = [os.path.join(trace_dir, "plugins", "profile", "*", "*.trace.json.gz"),
            os.path.join(trace_dir, "*.trace.json.gz")]
    hits: list[str] = []
    for p in pats:
        hits.extend(glob.glob(p))
    if not hits:
        raise FileNotFoundError(f"no *.trace.json.gz under {trace_dir!r}")
    return max(hits, key=os.path.getmtime)


def summarize_trace(trace_dir: str, top: int = 8) -> dict:
    """Reduce a trace dir to {window_ms, program_ms, device_busy_frac,
    top_ops: [{name, ms, frac_of_program}]}."""
    path = find_trace_file(trace_dir)
    with gzip.open(path, "rt") as f:
        events = json.load(f).get("traceEvents", [])

    dur = collections.Counter()   # name -> total usec (complete events)
    t0, t1 = float("inf"), 0.0
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        ts, d = e.get("ts", 0.0), e["dur"]
        t0, t1 = min(t0, ts), max(t1, ts + d)
        dur[e.get("name", "?")] += d

    window_us = max(t1 - t0, 0.0) if events else 0.0
    # jit_* wrapper and its while body both cover the same wall span;
    # take the max single program slice family, not the sum of nestings
    program_us = max((d for name, d in dur.items()
                      if _PROGRAM_RE.match(name)), default=0.0)

    ops = [(name, d) for name, d in dur.items()
           if _XLA_RE.match(name) and not _PROGRAM_RE.match(name)]
    ops.sort(key=lambda kv: kv[1], reverse=True)

    return {
        "trace_file": path,
        "window_ms": round(window_us / 1e3, 2),
        "program_ms": round(program_us / 1e3, 2),
        "device_busy_frac": round(program_us / window_us, 3) if window_us else 0.0,
        "top_ops": [
            {"name": name, "ms": round(d / 1e3, 2),
             "frac_of_program": round(d / program_us, 3) if program_us else 0.0}
            for name, d in ops[:top]
        ],
    }


def summarize_spans(path_or_spans, top: int = 0,
                    by_attr: str | None = None) -> dict:
    """Reduce an obs span JSONL log (or pre-loaded span list) to
    per-span-name aggregates: {spans, names: {name: {count, total_s,
    p50_s, p99_s}}}, names ordered by total time descending (all of
    them unless ``top`` truncates). ``by_attr`` splits each name by a
    span attribute value — ``by_attr="link"`` turns a federation
    spool into per-pair-session rows (``federation.round[p0-p1]``).
    Strict input: a bad line raises (obs.trace.read_spans), matching
    the CI artifact gate."""
    from dpcorr.obs.trace import read_spans
    from dpcorr.serve.stats import percentiles

    spans = (read_spans(path_or_spans) if isinstance(path_or_spans, str)
             else path_or_spans)
    by_name: dict[str, list[float]] = collections.defaultdict(list)
    for sp in spans:
        name = sp["name"]
        if by_attr is not None:
            val = (sp.get("attrs") or {}).get(by_attr)
            if val is not None:
                name = f"{name}[{val}]"
        by_name[name].append(float(sp["dur_s"]))
    rows = []
    for name, durs in by_name.items():
        pct = percentiles(durs)
        rows.append((name, {
            "count": len(durs),
            "total_s": round(sum(durs), 6),
            "p50_s": round(pct["p50"], 6),
            "p99_s": round(pct["p99"], 6),
        }))
    rows.sort(key=lambda kv: kv[1]["total_s"], reverse=True)
    if top:
        rows = rows[:top]
    return {"spans": len(spans), "names": dict(rows)}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_dir",
                    help="jax.profiler trace dir, an obs span JSONL "
                         "file (dpcorr serve --trace), or a directory "
                         "of federation spools (trace.*.jsonl) to "
                         "union")
    ap.add_argument("--top", type=int, default=8)
    ap.add_argument("--by-attr", dest="by_attr", default=None,
                    help="split span rows by this span attribute "
                         "(e.g. 'link' for per-pair-session rows from "
                         "a federation spool)")
    ap.add_argument("--json", action="store_true",
                    help="print the full summary as one JSON object")
    args = ap.parse_args()

    spools = (sorted(glob.glob(os.path.join(args.trace_dir,
                                            "trace.*.jsonl")))
              if os.path.isdir(args.trace_dir) else [])
    if os.path.isfile(args.trace_dir) or spools:
        if spools:
            from dpcorr.obs.trace import read_spans
            spans: list = []
            for p in spools:
                spans.extend(read_spans(p))
            s = summarize_spans(spans, by_attr=args.by_attr)
        else:
            s = summarize_spans(args.trace_dir, by_attr=args.by_attr)
        if args.json:
            print(json.dumps(s))
            return
        print(f"{s['spans']} spans")
        print(f"{'name':<24} {'count':>7} {'total_s':>10} "
              f"{'p50_s':>10} {'p99_s':>10}")
        for name, r in s["names"].items():
            print(f"{name:<24} {r['count']:>7} {r['total_s']:>10.4f} "
                  f"{r['p50_s']:>10.6f} {r['p99_s']:>10.6f}")
        return

    s = summarize_trace(args.trace_dir, args.top)
    if args.json:
        print(json.dumps(s))
        return
    print(f"trace   : {s['trace_file']}")
    print(f"window  : {s['window_ms']:.1f} ms   "
          f"program: {s['program_ms']:.1f} ms   "
          f"device-busy: {100 * s['device_busy_frac']:.0f}%")
    for op in s["top_ops"]:
        print(f"  {op['ms']:10.2f} ms  {100 * op['frac_of_program']:5.1f}%  "
              f"{op['name']}")


if __name__ == "__main__":
    main()
