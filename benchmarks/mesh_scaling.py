"""Mesh-sharded rep-pipeline scaling curve (ISSUE 19).

Measures ``sim.RepBlockPipeline`` throughput under the plan layer's
mesh placement at 1, 2 and 4 (simulated) devices and writes one
metric-bearing JSON artifact that ``dpcorr obs trajectory`` picks up as
its **own** series: the stamp carries ``detail.device_count`` and
``detail.mesh``, so the point lands in the ``cpux4`` series, never
folded into the 1-device headline.

Each device count runs in its own subprocess (a jax backend's device
count is fixed at first init; ``jax.config.update("jax_num_cpu_devices",
N)`` must happen before any backend touch, which a fresh interpreter
guarantees even under site hooks that preload jax). Every worker also
re-proves the two hard gates the mesh path ships with:

- **bit-identity** — the sharded program's per-rep outputs
  (``block_detail``) are byte-for-byte the 1-device placement's;
- **single fetch** — one ``run()`` = exactly one host sync on a
  private transfer-counter bundle.

Honesty notes (stamped into the artifact): on a 1-physical-core
container the N simulated devices time-slice one core, so wall-clock
"scaling" measures XLA's partitioning overhead, not speedup — the
curve's *shape* is a null wall there, and the artifact says so
(``physical_cpu_count``, ``null_wall``). The meaningful, core-count-
independent claims are the gates above plus the curve machinery itself
(the artifact schema a real multi-chip run fills in).

Run: python benchmarks/mesh_scaling.py [--n 10000] [--block 256]
         [--blocks 4] [--devices 1,2,4]
Writes benchmarks/results/r19_mesh_scaling_cpu.json by default.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

METRIC = "mc_reps_per_sec_mesh_ni_sign_n10k"


def worker(n: int, n_dev: int, block: int, blocks: int,
           seed: int) -> None:
    """Child: init a CPU backend with ``n_dev`` simulated devices,
    measure the mesh pipeline, prove the gates, print one JSON line."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_dev)
    except AttributeError:  # jax < 0.5: flag-based fallback
        pass

    import jax.numpy as jnp
    import numpy as np

    from dpcorr import sim
    from dpcorr.obs import transfer as transfer_mod
    from dpcorr.obs.metrics import Registry
    from dpcorr.parallel.mesh import rep_mesh
    from dpcorr.utils import rng

    got = jax.device_count()
    assert got == n_dev, f"wanted {n_dev} devices, backend gave {got} " \
        "(XLA_FLAGS must be set before the backend initializes)"

    cfg = sim.SimConfig(n=n, rho=0.35, eps1=1.0, eps2=1.0,
                        use_subg=False)
    rho = jnp.float32(cfg.rho)

    def rep_fn(k):
        row = sim._one_rep(k, rho, cfg)
        return (row[0], row[2], row[8])  # ni_hat, ni_se2, ni_cover

    key = rng.master_key(seed)
    ctr = transfer_mod.TransferCounters(Registry())

    def mk(placement, mesh=None):
        return sim.RepBlockPipeline(
            rep_fn, 3, key=key, block_reps=block, chunk_size=4,
            family="mesh-scaling", placement=placement, mesh=mesh,
            counters=ctr)

    if n_dev == 1:
        pipe = mk("local")
        bit_identical = None  # the 1-device run IS the reference
    else:
        pipe = mk("mesh", rep_mesh(n_dev))
        ref = mk("local")
        # the bit-identity gate is a proof at the measurement boundary,
        # outside the timed region — the sync here is the point
        bit_identical = all(
            np.asarray(a).tobytes()  # dpcorr-lint: ignore[sync-in-loop]
            == np.asarray(b).tobytes()  # dpcorr-lint: ignore[sync-in-loop]
            for a, b in zip(ref.block_detail(0), pipe.block_detail(0)))

    pipe.run(1)  # warm: compile + first donation excluded
    before = ctr.snapshot()
    t0 = time.perf_counter()
    _sums, n_reps = pipe.run(blocks)
    wall = time.perf_counter() - t0
    delta = transfer_mod.diff(ctr.snapshot(), before)

    print(json.dumps({
        "device_count": n_dev,
        "reps_per_sec": round(n_reps / wall, 1),
        "wall_s": round(wall, 3),
        "n_reps": n_reps,
        "bit_identical_vs_1dev": bit_identical,
        "fetches_per_run": delta.get("fetches"),
        "donated_blocks": delta.get("donated_blocks"),
        "aot_ok": pipe.aot_ok,
        "donation_engaged": pipe.donation_engaged,
        "placement": pipe.placement.describe(),
    }), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10000)
    ap.add_argument("--block", type=int, default=256)
    ap.add_argument("--blocks", type=int, default=4)
    ap.add_argument("--devices", type=str, default="1,2,4")
    ap.add_argument("--seed", type=int, default=20240807)
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "benchmarks", "results",
                                         "r19_mesh_scaling_cpu.json"))
    ap.add_argument("--worker", type=int, default=0,
                    help="internal: run as the N-device child")
    args = ap.parse_args()

    if args.worker:
        worker(args.n, args.worker, args.block, args.blocks, args.seed)
        return

    counts = [int(d) for d in args.devices.split(",") if d.strip()]
    curve = []
    for nd in counts:
        # the device count must be fixed before the child's backend
        # initializes; XLA_FLAGS at spawn is early even under site
        # hooks that preload jax at interpreter startup
        inherited = [t for t in os.environ.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in t]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=" ".join(
                       inherited
                       + [f"--xla_force_host_platform_device_count={nd}"]))
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--worker", str(nd), "--n", str(args.n),
             "--block", str(args.block), "--blocks", str(args.blocks),
             "--seed", str(args.seed)],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=1200)
        if proc.returncode != 0:
            print(proc.stdout, file=sys.stderr)
            print(proc.stderr, file=sys.stderr)
            raise SystemExit(f"{nd}-device worker failed "
                             f"(exit {proc.returncode})")
        # last stdout line is the worker's JSON (jax may log above it)
        curve.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        print(f"  {nd} device(s): {curve[-1]['reps_per_sec']} reps/s "
              f"(bit_identical={curve[-1]['bit_identical_vs_1dev']}, "
              f"fetches={curve[-1]['fetches_per_run']})", flush=True)

    for pt in curve:
        if pt["device_count"] > 1:
            assert pt["bit_identical_vs_1dev"] is True, pt
            assert pt["fetches_per_run"] == 1, pt

    phys = os.cpu_count()
    top = curve[-1]
    base = curve[0]["reps_per_sec"]
    artifact = {
        "metric": METRIC,
        "value": top["reps_per_sec"],
        "unit": "reps/sec",
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime()),
        "detail": {
            "n": args.n,
            "block_reps": args.block,
            "device_kind": "cpu",
            "device_count": top["device_count"],
            "mesh": {"rep": top["device_count"]},
            "curve": curve,
            "speedup_vs_1dev": {
                str(pt["device_count"]):
                    round(pt["reps_per_sec"] / base, 3)
                for pt in curve},
            "physical_cpu_count": phys,
            "null_wall": phys is not None and phys < max(counts),
            "notes": [
                "devices are host-simulated (jax_num_cpu_devices); on "
                f"{phys} physical core(s) the wall-clock curve measures "
                "XLA partitioning overhead, not speedup — a null wall "
                "for the scaling *shape*",
                "the load-bearing claims are core-count-independent: "
                "per-rep bit-identity of the sharded program vs the "
                "1-device placement, and exactly one host fetch per "
                "run (transfer-counter-proven, per point above)",
            ],
        },
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
