"""Multi-host grid fan-out at realistic scale (VERDICT r3 #7).

`dpcorr/parallel/multihost.py` claims its bucket-granular host slicing
keeps the bucketed backend's one-kernel-per-bucket speedup intact across
the split ("no two hosts ever compile the same kernel") — asserted since
round 2, measured never. This script runs the reference's FULL 144-point
v1 grid (vert-cor.R:488-511) both ways and records the evidence:

- single-host: `run_grid(backend="bucketed")`;
- multi-host:  `run_grid_multihost(distributed=True, n_hosts=2)` — a real
  `jax.distributed` cluster of worker processes over the shared cache;
- per-host bucket ownership (from `grid_slice` — the partition every host
  derives independently), wall-clocks, and a bit-identity check between
  the two runs' merged detail tables (same master key ⇒ the fan-out must
  not change a single number).

Honesty note: the artifact records `cpu_count`; on a 1-core container the
wall-clock ratio measures process contention, not scaling — the
meaningful scaling claims are the disjoint per-host kernel compiles and
merged-result identity, which are core-count-independent.

Run: python benchmarks/multihost_scaling.py [--b 250] [--n-hosts 2]
Writes benchmarks/results/r04_multihost_scaling.json by default.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=250)
    ap.add_argument("--n-hosts", dest="n_hosts", type=int, default=2)
    ap.add_argument("--platform", type=str, default="cpu",
                    help="JAX platform for parent AND workers ('' keeps "
                         "the site default)")
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "benchmarks", "results",
                                         "r04_multihost_scaling.json"))
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from dpcorr.grid import GridConfig, run_grid
    from dpcorr.parallel.multihost import grid_slice, run_grid_multihost

    out: dict = {"b": args.b, "n_hosts": args.n_hosts,
                 "cpu_count": os.cpu_count(),
                 "platform": args.platform or "site-default",
                 "grid": "v1 144-point (vert-cor.R:488-511)"}

    base = GridConfig(b=args.b, backend="bucketed")
    design = base.design_points()
    out["design_points"] = len(design)

    # the partition every host derives independently: whole (n, ε) buckets
    owners = {}
    for h in range(args.n_hosts):
        mine = grid_slice(design, h, args.n_hosts)
        owners[h] = sorted(mine[["n", "eps1", "eps2"]]
                           .drop_duplicates().itertuples(index=False))
    flat = [b for bs in owners.values() for b in bs]
    out["buckets_per_host"] = {h: len(bs) for h, bs in owners.items()}
    out["bucket_overlap"] = len(flat) - len(set(flat))
    assert out["bucket_overlap"] == 0, "two hosts own the same bucket!"

    with tempfile.TemporaryDirectory() as d1:
        t0 = time.perf_counter()
        res_single = run_grid(GridConfig(b=args.b, backend="bucketed",
                                         out_dir=d1))
        out["single_host_wall_s"] = round(time.perf_counter() - t0, 1)

    with tempfile.TemporaryDirectory() as d2:
        t0 = time.perf_counter()
        res_multi = run_grid_multihost(
            GridConfig(b=args.b, backend="bucketed", out_dir=d2),
            n_hosts=args.n_hosts, platform=args.platform or None,
            distributed=True, local_device_count=1)
        out["multi_host_wall_s"] = round(time.perf_counter() - t0, 1)
        out["host_reports"] = res_multi.timings.attrs.get("hosts")

    out["multi_over_single"] = round(
        out["multi_host_wall_s"] / out["single_host_wall_s"], 3)

    # same master key ⇒ the fan-out must not change a single number
    a = res_single.detail_all.sort_values(["n", "eps1", "eps2",
                                           "rho_true", "repl"])
    b = res_multi.detail_all.sort_values(["n", "eps1", "eps2",
                                          "rho_true", "repl"])
    # the A/B equality check IS the fetch boundary here
    # dpcorr-lint: ignore[sync-in-loop]
    for col in ("ni_hat", "int_hat", "ni_cover", "int_cover"):
        np.testing.assert_array_equal(  # dpcorr-lint: ignore[sync-in-loop]
            np.asarray(a[col]),  # dpcorr-lint: ignore[sync-in-loop]
            np.asarray(b[col]), col)  # dpcorr-lint: ignore[sync-in-loop]
    out["merged_detail_bit_identical"] = True

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1, default=str)
        f.write("\n")
    print(json.dumps({k: out[k] for k in
                      ("single_host_wall_s", "multi_host_wall_s",
                       "multi_over_single", "bucket_overlap",
                       "merged_detail_bit_identical", "cpu_count")}))


if __name__ == "__main__":
    main()
