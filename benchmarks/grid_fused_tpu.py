"""Config-3-scale TPU comparison: bucketed grid, XLA kernels vs fused
Pallas buckets (VERDICT r1 item 4 "done" criterion — show which buckets
ran fused and the speedup).

Runs the reference's full v1 grid (144 design points: 6n × 8ρ × 3ε-pairs,
vert-cor.R:488-511) at its own B=250 twice on the live TPU through the
bucketed backend — ``fused="off"`` (XLA `jit(vmap)` kernels) then
``fused="auto"`` (eligible (n, ε) buckets through the fused on-chip-PRNG
Pallas kernel) — and records wall-clocks, per-bucket fused flags, and
grid-level statistical summaries of both runs.

Run: python benchmarks/grid_fused_tpu.py [--b 250]
Writes benchmarks/results/r02_grid_fused_tpu.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: The "subg" family was removed with the r05 fused="all" retirement
#: (GridConfig.fused): its recorded r02 measurement
#: (r02_grid_fused_subg_tpu.json, 0.98x XLA) is the retirement's cited
#: evidence and stays checked in.
RESULTS = {
    "sign": os.path.join(REPO, "benchmarks", "results",
                         "r02_grid_fused_tpu.json"),
}


def _summ_stats(res):
    s = res.summ_all
    return {
        "mean_coverage_NI": round(
            float(s[s.method == "NI"]["coverage"].mean()), 4),
        "mean_coverage_INT": round(
            float(s[s.method == "INT"]["coverage"].mean()), 4),
        "mean_mse_NI": round(float(s[s.method == "NI"]["mse"].mean()), 6),
        "mean_mse_INT": round(float(s[s.method == "INT"]["mse"].mean()), 6),
    }


def run_record(res, wall: float) -> dict:
    """The shared per-arm record of a grid A/B artifact (this script and
    grid_merge_tpu.py): wall, steady-state rate, bucket/point counts,
    summary stats. Script-specific extras are added by the caller; the
    harvest gates consume these shapes, so the common core lives once."""
    t = res.timings
    return {
        "wall_s": round(wall, 1),
        "grid_reps_per_sec": round(float(t["grid_reps_per_sec"].iloc[0]), 1),
        "buckets": len(t),
        "points": int(t["points"].sum()),
        **_summ_stats(res),
    }


def ab_coverage_diffs(out: dict, a: str, b: str) -> None:
    """Record |coverage difference| between two arms — both runs must
    look like the same calibrated construction."""
    ra, rb = out["runs"][a], out["runs"][b]
    out["coverage_diff_NI"] = round(
        abs(ra["mean_coverage_NI"] - rb["mean_coverage_NI"]), 4)
    out["coverage_diff_INT"] = round(
        abs(ra["mean_coverage_INT"] - rb["mean_coverage_INT"]), 4)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=250)
    ap.add_argument("--out", type=str, default=None,
                    help="output JSON path (default: the family's r02 "
                         "artifact — pass an r0N name to keep old "
                         "evidence intact)")
    ap.add_argument("--family", choices=["sign"], default="sign",
                    help="sign: v1 Gaussian grid (vert-cor.R:488-511). "
                         "(The 'subg' family went with the r05 "
                         "fused='all' retirement; its r02 measurement "
                         "r02_grid_fused_subg_tpu.json stays checked in)")
    args = ap.parse_args()

    import jax

    from dpcorr.grid import GridConfig, run_grid

    dev = jax.devices()[0]
    out = {"device": str(dev), "b": args.b, "family": args.family,
           "runs": {}}
    family_kw = {}

    fused_mode = "auto"
    for fused in ("off", fused_mode):
        gcfg = GridConfig(b=args.b, backend="bucketed", fused=fused,
                          **family_kw)
        t0 = time.perf_counter()
        res = run_grid(gcfg)
        wall = time.perf_counter() - t0
        rec = run_record(res, wall)
        rec["fused_buckets"] = int(res.timings["fused"].astype(bool).sum())
        rec["total_reps"] = rec["points"] * args.b
        out["runs"][fused] = rec
        print(fused, "->", json.dumps(rec), flush=True)

    o, a = out["runs"]["off"], out["runs"][fused_mode]
    out["fused_speedup_wall"] = round(o["wall_s"] / a["wall_s"], 3)
    out["fused_speedup_rps"] = round(
        a["grid_reps_per_sec"] / o["grid_reps_per_sec"], 3)
    ab_coverage_diffs(out, "off", fused_mode)

    path = args.out or RESULTS[args.family]
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
