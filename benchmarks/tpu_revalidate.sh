#!/bin/bash
# Tunnel-recovery watcher + queued TPU validations (2026-07-30 session).
#
# The remote TPU tunnel intermittently wedges under sustained load
# (docs/STATUS_r02.md "Ops note"). This script polls a bounded health
# probe and, on recovery, runs the validations queued behind the wedge:
#
#   1. `python bench.py` at the new shipped defaults (block_reps=2^19) —
#      revalidates the 235x headline on the current revision, including
#      the refactored kernels (two-word seeds, shared scaffolding).
#   2. Pallas gauss A/B: the tpu-pallas worker with Box-Muller vs the
#      inline-ndtri sampler, same budget — settles whether the VPU-bound
#      generate step is cheaper as an inverse-CDF polynomial.
#   3. A --b 8 fused CLI grid smoke (end-to-end grid wiring on-chip).
#
# Results land in /tmp/tpu_revalidate/; summarized on stdout.

set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=/tmp/tpu_revalidate
mkdir -p "$OUT"
FAILED=0

step() {  # step <name> <cmd...>: run, record status, keep going
  local name=$1; shift
  if "$@"; then
    echo "-- $name: OK"
  else
    echo "-- $name: FAILED (rc=$?)"
    FAILED=$((FAILED + 1))
  fi
}

probe() {
  timeout 150 python -c \
    "import jax, jax.numpy as jnp; print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))" \
    >/dev/null 2>&1
}

for i in $(seq 1 120); do
  if probe; then
    echo "tunnel healthy at attempt $i ($(date -u +%H:%M:%SZ))"

    echo "== 1. bench.py at shipped defaults =="
    step bench_default bash -c \
      'timeout 1200 python bench.py 2>"'$OUT'/bench_default.err" \
       | tail -1 | tee "'$OUT'/bench_default.json" | grep -q "reps_per_sec"'

    echo "== 2. pallas gauss A/B (worker-only, budget 20s each) =="
    step pallas_boxmuller bash -c \
      'timeout 900 python bench.py --worker tpu-pallas --budget 20 \
       2>"'$OUT'/pallas_bm.err" | tail -1 \
       | tee "'$OUT'/pallas_boxmuller.json" | grep -q "reps_per_sec"'
    step pallas_ndtri bash -c \
      'DPCORR_BENCH_PALLAS_GAUSS=ndtri \
       timeout 900 python bench.py --worker tpu-pallas --budget 20 \
       2>"'$OUT'/pallas_nd.err" | tail -1 \
       | tee "'$OUT'/pallas_ndtri.json" | grep -q "reps_per_sec"'

    echo "== 3. fused CLI grid smoke (--b 8) =="
    step grid_fused_smoke bash -c \
      'timeout 900 python -m dpcorr grid --backend bucketed --fused auto \
       --b 8 2>"'$OUT'/grid.err" | tail -2 \
       | tee "'$OUT'/grid_fused_smoke.txt" | grep -q "INT"'

    cat "$OUT"/*.json 2>/dev/null
    echo "revalidation finished ($(date -u +%H:%M:%SZ)): $((4 - FAILED))/4 steps OK"
    exit $FAILED
  fi
  sleep 110
done
echo "tunnel never recovered within the polling window"
exit 1
