"""Feature-level bisection of the NI Pallas kernel's Mosaic compile hang.

Round-2 finding (docs/STATUS_r02.md): a minimal on-chip-PRNG kernel
compiles and runs on the tunneled TPU in seconds, but the *full* fused
kernel (`dpcorr.ops.pallas_ni`) hung the server-side Mosaic compile and
wedged the backend for every subsequent process. This harness identifies
the culprit increment by compiling a ladder of kernels, each adding one
feature of the full kernel, under hard process-group-killed timeouts:

    L1  prng       seed + prng_random_bits + sum
    L2  boxmuller  + uniform conversion + Box-Muller (log/sqrt/cos/sin)
    L3  genmask    + bivariate x,y + iota position masks
    L4  center     + DP centering (laplace noise + masked moment sums)
    L5  matmul     + sign + (rows,128)@(128,128) MXU aggregation
    L6  full       the real kernel via ni_sign_pallas, b=8
    L7  fullbig    the real kernel, b=4096 (bench-shaped grid)

Orchestrator protocol (the tunnel is a shared, wedgeable resource):
health-check → probe → on success next level; on timeout kill the
process group, health-check again, and STOP — every higher level
contains the culprit, and further compiles of it only risk re-wedging
the backend. Results land in benchmarks/results/pallas_bisect.json.

Run: python benchmarks/pallas_bisect.py            (orchestrator)
     python benchmarks/pallas_bisect.py --level N  (one probe, in-proc)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, REPO)
RESULTS = os.path.join(REPO, "benchmarks", "results", "pallas_bisect.json")

N = int(os.environ.get("DPCORR_BISECT_N", 10_000))
EPS1, EPS2, RHO = 1.0, 1.0, 0.5
LEVELS = ["prng", "boxmuller", "genmask", "center", "matmul", "full",
          "fullbig"]

HEALTH_TIMEOUT = 240.0   # fresh backend init through the tunnel is ~60-90s
PROBE_TIMEOUT = 330.0    # init + Mosaic compile + tiny run; hang >> this


# --------------------------------------------------------------------------
# Probe worker: compile + run ONE ladder level in this process.
# --------------------------------------------------------------------------

def probe_level(level: str) -> dict:
    import math

    import jax

    if os.environ.get("DPCORR_BISECT_INTERPRET"):
        # CPU smoke test. The axon site hook preloads jax at interpreter
        # startup, so JAX_PLATFORMS in the environment is captured too
        # late — only jax.config reliably keeps the tunnel out of the way.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from dpcorr.ops.pallas_ni import (LANES, _laplace_from_uniform, _layout,
                                      _uniform, ni_sign_pallas)

    m, m_pad, k, leftover, rows = _layout(N, EPS1, EPS2)
    t0 = time.perf_counter()

    if level in ("full", "fullbig"):
        if os.environ.get("DPCORR_BISECT_INTERPRET"):
            # the real kernel's on-chip PRNG has no interpreter stand-in
            # (ni_sign_pallas requires external uniforms off-TPU), and the
            # ladder below already smoke-covers all of its pieces
            return {"level": level, "ok": True,
                    "skipped": "interpret smoke mode covers L1-L5 only"}
        b = 4096 if level == "fullbig" else 8
        seeds = jnp.arange(b, dtype=jnp.int32)
        r = ni_sign_pallas(seeds, RHO, N, EPS1, EPS2, interpret=False)
        finite = bool(jnp.all(jnp.isfinite(r.rho_hat))
                      & jnp.all(jnp.isfinite(r.ci_low))
                      & jnp.all(jnp.isfinite(r.ci_high)))
        return {"level": level, "ok": True, "finite": finite,
                "secs": round(time.perf_counter() - t0, 1),
                "mean_rho_hat": round(float(jnp.mean(r.rho_hat)), 4)}

    want = LEVELS.index(level)
    l_clip = math.sqrt(2.0 * math.log(N))
    two_pi = 2.0 * math.pi
    import numpy as np
    gmat_np = ((np.arange(LANES)[:, None] // m_pad)
               == np.arange(LANES)[None, :]).astype(np.float32)

    def kernel(seed_ref, gmat_ref, out_ref):
        pltpu.prng_seed(seed_ref[0, 0, 0])
        acc = jnp.float32(0.0)

        bits1 = pltpu.prng_random_bits((rows, LANES))
        bits2 = pltpu.prng_random_bits((rows, LANES))
        if want == 0:  # L1: raw bits only
            acc = (jnp.sum(bits1.astype(jnp.float32))
                   + jnp.sum(bits2.astype(jnp.float32)))
        else:
            u1 = _uniform(bits1)
            u2 = _uniform(bits2)
            r = jnp.sqrt(-2.0 * jnp.log(u1))
            z1 = r * jnp.cos(two_pi * u2)
            z2 = r * jnp.sin(two_pi * u2)
            if want == 1:  # L2: Box-Muller
                acc = jnp.sum(z1) + jnp.sum(z2)
        if want >= 2:  # L3: bivariate pair + position masks
            rho = jnp.float32(RHO)
            x = z1
            y = rho * z1 + jnp.sqrt(1.0 - rho * rho) * z2
            pos = (jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
                   * LANES
                   + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1))
            batch_elem = (pos % m_pad < m) & (pos // m_pad < k)
            in_leftover = (pos >= k * m_pad) & (pos < k * m_pad + leftover)
            w = (batch_elem | in_leftover).astype(jnp.float32)
            if want == 2:
                acc = jnp.sum(x * w) + jnp.sum(y * w)
        if want >= 3:  # L4: DP centering
            lap4 = _laplace_from_uniform(
                _uniform(pltpu.prng_random_bits((8, LANES))), 1.0)

            def center(v, eps, mu_noise):
                vc = jnp.clip(v, -l_clip, l_clip)
                mu_p = (jnp.sum(vc * w) / N
                        + mu_noise * 2.0 * l_clip / (N * (eps / 2.0)))
                return vc - mu_p

            x_c = center(x, EPS1, lap4[0, 0])
            y_c = center(y, EPS2, lap4[1, 0])
            if want == 3:
                acc = jnp.sum(x_c * w) + jnp.sum(y_c * w)
        if want >= 4:  # L5: sign + MXU aggregation matmul
            bmask = batch_elem.astype(jnp.float32)
            sx = jnp.sign(x_c) * bmask
            sy = jnp.sign(y_c) * bmask
            g = gmat_ref[...]
            xb = jnp.dot(sx, g, preferred_element_type=jnp.float32) / m
            yb = jnp.dot(sy, g, preferred_element_type=jnp.float32) / m
            acc = jnp.sum(xb) + jnp.sum(yb)

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        out_ref[0, 0, :] = jnp.where(lane == 0, acc, 0.0)[0, :]

    b = 8
    seeds = jnp.arange(b, dtype=jnp.int32).reshape(b, 1, 1)
    # DPCORR_BISECT_INTERPRET=1: CPU shape/trace smoke test of the ladder
    # itself (the interpreter stubs the PRNG to zeros, so values are NaN
    # garbage — only "does it trace and execute" is checked off-TPU).
    interpret = (pltpu.InterpretParams()
                 if os.environ.get("DPCORR_BISECT_INTERPRET") else False)
    out = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((LANES, LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 1, LANES), jnp.float32),
        interpret=interpret,
    )(seeds, jnp.asarray(gmat_np))
    vals = out[:, 0, 0]
    return {"level": level, "ok": True,
            "finite": bool(jnp.all(jnp.isfinite(vals))),
            "secs": round(time.perf_counter() - t0, 1),
            "sample": round(float(vals[0]), 3)}


# --------------------------------------------------------------------------
# Orchestrator
# --------------------------------------------------------------------------

def _run(cmd: list[str], timeout_s: float):
    """Run cmd in its own process group; kill the whole group on timeout.
    Returns (rc | None-on-timeout, stdout, stderr, elapsed).

    The group must also die with *us* (bench.py's r04 stranded-client
    lesson): a probe orphaned by an external SIGTERM/ctrl-C would hold
    the exclusive TPU client and read as a wedged tunnel afterwards."""
    t0 = time.time()
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True,
                         start_new_session=True)
    try:
        try:
            so, se = p.communicate(timeout=timeout_s)
            return p.returncode, so, se, time.time() - t0
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            # drain whatever the probe printed before the kill — the
            # hanging probe is exactly the one whose partial output matters
            so, se = p.communicate()
            return None, so or "", se or "", time.time() - t0
    finally:
        if p.poll() is None:  # abnormal exit path (signal, bug): reap
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            except PermissionError:
                p.kill()
            try:  # bounded: an unkillable probe must not hang shutdown
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


def health_check() -> tuple[bool, float]:
    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.ones((256, 256)); "
            "print('HEALTH-OK', float((x @ x).sum()), jax.devices()[0])")
    rc, so, _, dt = _run([sys.executable, "-c", code], HEALTH_TIMEOUT)
    return rc == 0 and "HEALTH-OK" in so, dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--level", choices=LEVELS)
    ap.add_argument("--start", default="prng", choices=LEVELS,
                    help="first ladder level to probe (skip known-good)")
    args = ap.parse_args()

    if args.level:
        # probe worker: keep SIG_DFL — a Python handler could never run
        # while wedged inside a native Mosaic compile, which would make
        # the probe unkillable by SIGTERM (bench.py's r04 lesson)
        print(json.dumps(probe_level(args.level)), flush=True)
        return

    # orchestrator only — same contract as bench.py: SIGTERM must run
    # _run's finally so a killed bisect can't strand a probe holding the
    # TPU client (latched against double delivery)
    def _sigterm_to_exit(signum, frame):
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        raise SystemExit(128 + signum)

    signal.signal(signal.SIGTERM, _sigterm_to_exit)

    report = {"config": {"n": N, "eps1": EPS1, "eps2": EPS2},
              "probes": [], "culprit": None, "wedged": False}

    ok, dt = health_check()
    print(f"initial health: {'OK' if ok else 'FAILED'} ({dt:.0f}s)",
          flush=True)
    report["initial_health_s"] = round(dt, 1)
    if not ok:
        report["wedged"] = True
        _write(report)
        return

    for level in LEVELS[LEVELS.index(args.start):]:
        print(f"probe {level} ...", flush=True)
        rc, so, se, dt = _run(
            [sys.executable, os.path.abspath(__file__), "--level", level],
            PROBE_TIMEOUT)
        entry = {"level": level, "elapsed_s": round(dt, 1)}
        if rc is None:
            entry["result"] = "TIMEOUT (killed)"
            if se.strip():
                entry["stderr_tail"] = " | ".join(
                    se.strip().splitlines()[-3:])[:400]
        elif rc != 0:
            entry["result"] = "ERROR"
            entry["stderr_tail"] = " | ".join(
                (se or "").strip().splitlines()[-3:])[:400]
        else:
            for line in reversed((so or "").strip().splitlines()):
                try:
                    entry["result"] = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            else:
                entry["result"] = "NO-JSON"
        report["probes"].append(entry)
        print(f"  -> {entry['result']}", flush=True)

        if rc != 0:  # timeout or error: identify culprit, verify health, stop
            report["culprit"] = level
            ok, dt = health_check()
            report["post_hang_health"] = {"ok": ok, "secs": round(dt, 1)}
            print(f"post-hang health: {'OK' if ok else 'WEDGED'} ({dt:.0f}s)",
                  flush=True)
            if not ok:
                report["wedged"] = True
            break
    _write(report)


def _write(report: dict) -> None:
    with open(RESULTS, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {RESULTS}", flush=True)


if __name__ == "__main__":
    main()
