"""On-chip A/B for the ε-merged subG grid buckets (GridConfig.bucket_merge).

CPU already measured the r05 progression (PERFORMANCE.md §bucket_merge:
0.56× → 0.80× → 1.28× at the reference 120-point B=250 shape); the mode's
real target is the TPU tunnel, where every compile costs 10-40 s and the
r02 subG grid was compile-dominated (75.2 s wall for ~2 s of compute,
r02_grid_fused_subg_tpu.json's "off" arm). This script runs the
reference subG grid (ver-cor-subG.R:245-269) twice — ``bucket_merge="off"``
(15 compiles) then ``"eps"`` (5) — and records walls, bucket counts, and
grid-level statistical summaries of both runs.

Run: python benchmarks/grid_merge_tpu.py [--b 250] [--out ...]
Default output lands in /tmp quarantine — NEVER directly in
benchmarks/results/: only harvest_r05.sh's validity gates (complete
JSON + TPU device stamp) promote it to the checked-in
r05_grid_merge_tpu.json, so a CPU smoke run can't overwrite banked TPU
evidence under a _tpu-named file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from benchmarks.grid_fused_tpu import (  # noqa: E402  (one impl of each)
    ab_coverage_diffs,
    run_record,
)

QUARANTINE = os.path.join(os.environ.get("TPU_R05_IN", "/tmp/tpu_r05"),
                          "grid_merge.json")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=250)
    ap.add_argument("--out", type=str, default=QUARANTINE)
    args = ap.parse_args()
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)

    import jax

    from dpcorr.grid import GridConfig, run_grid

    out = {"device": str(jax.devices()[0]), "b": args.b, "runs": {}}
    for merge in ("off", "eps"):
        gcfg = GridConfig(n_grid=(2500, 4000, 6000, 9000, 12000),
                          dgp="bounded_factor", use_subg=True,
                          b=args.b, backend="bucketed", bucket_merge=merge)
        t0 = time.perf_counter()
        res = run_grid(gcfg)
        wall = time.perf_counter() - t0
        out["runs"][merge] = run_record(res, wall)
        print(merge, "->", json.dumps(out["runs"][merge]), flush=True)

    o, m = out["runs"]["off"], out["runs"]["eps"]
    out["merge_speedup_wall"] = round(o["wall_s"] / m["wall_s"], 3)
    ab_coverage_diffs(out, "off", "eps")

    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print("wrote", args.out, flush=True)


if __name__ == "__main__":
    main()
