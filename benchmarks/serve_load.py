"""Load generator for the serving subsystem (ISSUE 1 acceptance).

Drives ≥ 1,000 concurrent estimation requests from many client threads
through an in-process :class:`dpcorr.serve.DpcorrServer` and verifies
the three serving invariants end to end:

1. **real coalescing** — batch-fill ratio (live requests per flushed
   launch) > 1;
2. **bit-identity** — every response equals the direct single-request
   estimator call (``jit(single)``) on the same key-tree address; holds
   exactly under the default ``exact`` batch engine for every family
   (estimators.registry contract);
3. **ledger refusal** — with the spend known in advance, the first
   query that would overdraw a party's ε budget is refused and every
   earlier one admitted;
4. **metrics consistency** (ISSUE 2) — the Prometheus ``GET /metrics``
   exposition scraped over real HTTP agrees numerically with the
   ``GET /stats`` snapshot (both views read the same obs registry);
5. **tracing** (with ``--trace``) — the span JSONL log parses strictly
   and is non-empty, the same gate CI applies to the uploaded artifact.
6. **warm boot** (with ``--warmup``, ISSUE 4) — the server compiles its
   signature set behind ``/readyz`` before any traffic; the run then
   gates on ZERO kernel compilations during traffic and records the
   first-request latency and its ratio to the steady-state p50 (the
   compile-ahead pipeline's whole point: no client pays a compile).

Prints one JSON document: serving stats snapshot + latency percentiles
+ throughput + the verification verdicts. Exit code 1 if any invariant
fails, so the unattended queue can gate on it.

Usage:
    python benchmarks/serve_load.py [--requests 1000] [--clients 32]
        [--n 500] [--max-batch 64] [--max-delay-ms 20] [--verify 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=1000)
    ap.add_argument("--clients", type=int, default=32,
                    help="concurrent client threads")
    ap.add_argument("--n", type=int, default=500,
                    help="observations per request")
    ap.add_argument("--family", default="ni_sign")
    ap.add_argument("--eps1", type=float, default=1.0)
    ap.add_argument("--eps2", type=float, default=0.5)
    ap.add_argument("--max-batch", dest="max_batch", type=int, default=64)
    ap.add_argument("--max-delay-ms", dest="max_delay_ms", type=float,
                    default=20.0)
    ap.add_argument("--verify", type=int, default=64,
                    help="responses to bit-check against direct calls")
    ap.add_argument("--batch-mode", dest="batch_mode", default="exact",
                    choices=["exact", "vector"],
                    help="'vector' trades CI-endpoint bit-identity "
                         "(≤1 ulp) for batch throughput; the bit check "
                         "then verifies rho_hat only")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--out-json", dest="out_json", default=None)
    ap.add_argument("--trace", default=None,
                    help="span-trace JSONL path: enables the obs tracer "
                         "for the run and gates on a non-empty, "
                         "parseable span log (the CI artifact check)")
    ap.add_argument("--warmup", default=None,
                    help="warmup spec (serve.warmup syntax), or 'auto' to "
                         "derive the exact signature set this load hits "
                         "(family at pad_n(n), every power-of-two b_pad "
                         "up to --max-batch). The server compiles it "
                         "behind /readyz before traffic; the run then "
                         "gates on zero compiles during traffic "
                         "(ok.warm_boot) and records first-request "
                         "latency vs steady p50")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    from dpcorr.models.estimators.registry import serving_entry
    from dpcorr.serve import (
        DpcorrServer,
        EstimateRequest,
        InProcessClient,
        pinned_request_key,
    )
    from dpcorr.serve.ledger import BudgetExceededError, request_charges
    from dpcorr.utils import rng

    if args.trace:
        from dpcorr.obs import trace as obs_trace

        obs_trace.configure(args.trace)

    warm_spec = None
    if args.warmup:
        # kernel signatures carry the request's raw n (padding is a
        # coalescing concern, not a kernel-shape one — serve.request)
        warm_spec = (f"{args.family}:{args.n}:{args.eps1}:"
                     f"{args.eps2}:auto" if args.warmup == "auto"
                     else args.warmup)

    # Budget sized so the load itself always fits: the refusal probe
    # below runs against dedicated parties with a tiny budget instead.
    srv = DpcorrServer(budget=1e9, max_batch=args.max_batch,
                       max_delay_s=args.max_delay_ms / 1000.0,
                       max_queue=4 * args.requests,
                       batch_mode=args.batch_mode,
                       warmup=warm_spec)
    cli = InProcessClient(srv)

    # wait-for-ready hook: what a load balancer polling GET /readyz
    # does, in process. Compile counts after this point are traffic's.
    t_warm0 = time.perf_counter()
    warm_ready = cli.wait_ready(timeout=900)
    warmup_s = time.perf_counter() - t_warm0
    compiles_after_warmup = srv.stats.kernel_compiles
    readiness = cli.readiness()

    first_request_s = None
    if warm_spec:
        # one isolated request before the load: on a warm server its
        # latency is queueing + execution only — no compile. Recorded
        # against the steady-state p50 below.
        rs0 = np.random.RandomState(99)
        probe0 = EstimateRequest(
            args.family, rs0.randn(args.n).astype(np.float32),
            rs0.randn(args.n).astype(np.float32), args.eps1, args.eps2,
            party_x="warm-x", party_y="warm-y", seed=999983)
        t_f0 = time.perf_counter()
        srv.estimate(probe0, timeout=300)
        first_request_s = time.perf_counter() - t_f0

    rs = np.random.RandomState(7)
    reqs = [EstimateRequest(
        args.family,
        rs.randn(args.n).astype(np.float32),
        rs.randn(args.n).astype(np.float32),
        args.eps1, args.eps2,
        party_x=f"px{i % 8}", party_y=f"py{i % 8}", seed=i)
        for i in range(args.requests)]

    responses: dict[int, object] = {}
    errors: list[str] = []
    lock = threading.Lock()
    per_client = -(-args.requests // args.clients)

    def client(c: int) -> None:
        futs = []
        for i in range(c * per_client,
                       min((c + 1) * per_client, args.requests)):
            try:
                futs.append((i, cli.submit(reqs[i])))
            except Exception as e:  # refusal/overload is a failure here
                with lock:
                    errors.append(f"submit {i}: {type(e).__name__}: {e}")
        for i, f in futs:
            try:
                r = f.result(timeout=300)
                with lock:
                    responses[i] = r
            except Exception as e:
                with lock:
                    errors.append(f"result {i}: {type(e).__name__}: {e}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    srv.close()

    stats = cli.stats()
    fill = stats["batch_fill_ratio"]

    # -- single source of truth: /metrics must agree with /stats ---------
    # (ISSUE 2 acceptance) scrape the real HTTP endpoints — same server,
    # same registry — and cross-check counter/gauge values numerically.
    import urllib.request

    from dpcorr.obs import parse_exposition
    from dpcorr.serve.server import make_http_server

    httpd = make_http_server(srv, port=0)
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    http_thread.start()
    port = httpd.server_address[1]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics") as resp:
        metrics_text = resp.read().decode()
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats") as resp:
        stats_http = json.load(resp)
    httpd.shutdown()
    series = parse_exposition(metrics_text)
    completed = (stats_http["batched_requests"]
                 + stats_http["unbatched_requests"])
    expected = {
        "dpcorr_serve_requests_total": stats_http["requests_total"],
        "dpcorr_serve_batches_flushed_total":
            stats_http["batches_flushed"],
        'dpcorr_serve_requests_completed_total{mode="batched"}':
            stats_http["batched_requests"],
        'dpcorr_serve_requests_completed_total{mode="unbatched"}':
            stats_http["unbatched_requests"],
        "dpcorr_serve_latency_seconds_count": completed,
        "dpcorr_serve_kernel_compiles_total":
            stats_http["kernel_compiles"],
        "dpcorr_serve_kernel_cache_hits_total":
            stats_http["kernel_hits"],
        "dpcorr_serve_kernel_cache_size":
            stats_http["kernel_cache_size"],
        "dpcorr_serve_queue_depth": stats_http["queue_depth"],
    }
    # a zero-valued labelled child may legitimately be absent from the
    # exposition (never incremented), hence the 0.0 default
    metrics_mismatches = {
        k: {"metrics": series.get(k, 0.0), "stats": float(want)}
        for k, want in expected.items()
        if series.get(k, 0.0) != float(want)}

    trace_spans = None
    if args.trace:
        from dpcorr.obs import read_spans

        # strict parse: an unparseable line raises and fails the run
        trace_spans = len(read_spans(args.trace))

    # -- invariant 2: bit-identity on a sample of responses --------------
    single = jax.jit(serving_entry(args.family, args.eps1, args.eps2,
                                   alpha=0.05, normalise=True))
    master = rng.master_key(srv.seed)
    step = max(1, len(responses) // max(args.verify, 1))
    checked = mismatches = 0
    check_ci = args.batch_mode == "exact"
    for i in sorted(responses)[::step]:
        r = responses[i]
        # requests pin their seeds, so the reference recomputes the
        # content-bound pinned-subtree key (serve.server contract)
        d = single(pinned_request_key(master, reqs[i], r.seed),
                   reqs[i].x, reqs[i].y)
        checked += 1
        if float(d[0]) != r.rho_hat or (check_ci and (
                float(d[1]) != r.ci_low or float(d[2]) != r.ci_high)):
            mismatches += 1

    # -- invariant 3: refusal exactly at budget exhaustion ---------------
    probe = EstimateRequest(args.family, reqs[0].x, reqs[0].y,
                            args.eps1, args.eps2,
                            party_x="probe-x", party_y="probe-y")
    spend = request_charges(probe)["probe-x"]
    admit_budget = 3 * spend  # fits exactly 3 queries
    srv2 = DpcorrServer(budget=1e9,
                        per_party_budget={"probe-x": admit_budget,
                                          "probe-y": admit_budget},
                        max_delay_s=0.001)
    admitted = 0
    refused_at = None
    for q in range(5):
        try:
            srv2.estimate(probe)
            admitted += 1
        except BudgetExceededError:
            refused_at = q
            break
    srv2.close()

    ok = {
        "completed": len(responses) == args.requests and not errors,
        "coalesced": fill > 1.0,
        "bit_identical": checked > 0 and mismatches == 0,
        "ledger_refusal": admitted == 3 and refused_at == 3,
        "metrics_consistent": not metrics_mismatches,
    }
    if args.trace:
        ok["traced"] = trace_spans is not None and trace_spans > 0
    warmup_doc = None
    if warm_spec:
        compiles_during_traffic = (stats["kernel_compiles"]
                                   - compiles_after_warmup)
        p50 = stats.get("latency_s", {}).get("p50")
        warmup_doc = {
            "spec": warm_spec,
            "ready": warm_ready,
            "warmup_s": round(warmup_s, 3),
            "readiness": readiness,
            "kernel_compiles_warmup": compiles_after_warmup,
            "kernel_compiles_during_traffic": compiles_during_traffic,
            "first_request_s": (round(first_request_s, 4)
                                if first_request_s is not None else None),
            "steady_p50_s": p50,
            "first_request_vs_p50": (round(first_request_s / p50, 2)
                                     if first_request_s and p50 else None),
        }
        # the compile-ahead acceptance: a warmed server serves the whole
        # load without a single fresh compilation
        ok["warm_boot"] = warm_ready and compiles_during_traffic == 0
    out = {
        "metric": "serve_load",
        "requests": args.requests,
        "clients": args.clients,
        "n": args.n,
        "family": args.family,
        "batch_mode": args.batch_mode,
        "wall_s": round(wall, 3),
        "requests_per_sec": round(args.requests / wall, 1),
        "batch_fill_ratio": round(fill, 2),
        "bit_checked": checked,
        "bit_mismatches": mismatches,
        "refusal_probe": {"admitted": admitted, "refused_at": refused_at},
        "metrics_mismatches": metrics_mismatches,
        "trace": args.trace,
        "trace_spans": trace_spans,
        "warmup": warmup_doc,
        "ok": ok,
        "errors": errors[:5],
        "stats": stats,
    }
    blob = json.dumps(out, indent=2)
    print(blob)
    if args.out_json:
        with open(args.out_json, "w") as f:
            f.write(blob)
    return 0 if all(ok.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
