"""Load generator for the serving subsystem (ISSUE 1 acceptance).

Drives ≥ 1,000 concurrent estimation requests from many client threads
through an in-process :class:`dpcorr.serve.DpcorrServer` and verifies
the three serving invariants end to end:

1. **real coalescing** — batch-fill ratio (live requests per flushed
   launch) > 1;
2. **bit-identity** — every response equals the direct single-request
   estimator call (``jit(single)``) on the same key-tree address; holds
   exactly under the default ``exact`` batch engine for every family
   (estimators.registry contract);
3. **ledger refusal** — with the spend known in advance, the first
   query that would overdraw a party's ε budget is refused and every
   earlier one admitted;
4. **metrics consistency** (ISSUE 2) — the Prometheus ``GET /metrics``
   exposition scraped over real HTTP agrees numerically with the
   ``GET /stats`` snapshot (both views read the same obs registry);
5. **tracing** (with ``--trace``) — the span JSONL log parses strictly
   and is non-empty, the same gate CI applies to the uploaded artifact.
6. **warm boot** (with ``--warmup``, ISSUE 4) — the server compiles its
   signature set behind ``/readyz`` before any traffic; the run then
   gates on ZERO kernel compilations during traffic and records the
   first-request latency and its ratio to the steady-state p50 (the
   compile-ahead pipeline's whole point: no client pays a compile).

7. **overload resilience** (with ``--overload``, ISSUE 8) — a separate
   scenario against a deliberately small server (tight queue, slow- and
   failing-kernel chaos faults) at ~4× capacity through
   :class:`~dpcorr.serve.RetryingClient`:
   every logical request eventually succeeds; sheds/evictions happened
   and refunded (exact ledger balance + jax-free audit replay — zero ε
   consumed by any shed or expired request); admitted-request latency
   holds the SLO; the circuit breaker trips (``/readyz`` degrades, open
   refusals charge-free) and recovers to bit-identical answers; a
   16-way duplicate storm of one pinned request lands ONE charge with
   15 idempotent hits.

Prints one JSON document: serving stats snapshot + latency percentiles
+ throughput + the verification verdicts. Exit code 1 if any invariant
fails, so the unattended queue can gate on it.

Usage:
    python benchmarks/serve_load.py [--requests 1000] [--clients 32]
        [--n 500] [--max-batch 64] [--max-delay-ms 20] [--verify 64]
    python benchmarks/serve_load.py --overload [--requests 192]
        [--slo-ms 2000] [--out-json overload.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=1000)
    ap.add_argument("--clients", type=int, default=32,
                    help="concurrent client threads")
    ap.add_argument("--n", type=int, default=500,
                    help="observations per request")
    ap.add_argument("--family", default="ni_sign")
    ap.add_argument("--eps1", type=float, default=1.0)
    ap.add_argument("--eps2", type=float, default=0.5)
    ap.add_argument("--max-batch", dest="max_batch", type=int, default=64)
    ap.add_argument("--max-delay-ms", dest="max_delay_ms", type=float,
                    default=20.0)
    ap.add_argument("--verify", type=int, default=64,
                    help="responses to bit-check against direct calls")
    ap.add_argument("--batch-mode", dest="batch_mode", default="exact",
                    choices=["exact", "vector"],
                    help="'vector' trades CI-endpoint bit-identity "
                         "(≤1 ulp) for batch throughput; the bit check "
                         "then verifies rho_hat only")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--out-json", dest="out_json", default=None)
    ap.add_argument("--trace", default=None,
                    help="span-trace JSONL path: enables the obs tracer "
                         "for the run and gates on a non-empty, "
                         "parseable span log (the CI artifact check)")
    ap.add_argument("--warmup", default=None,
                    help="warmup spec (serve.warmup syntax), or 'auto' to "
                         "derive the exact signature set this load hits "
                         "(family at pad_n(n), every power-of-two b_pad "
                         "up to --max-batch). The server compiles it "
                         "behind /readyz before traffic; the run then "
                         "gates on zero compiles during traffic "
                         "(ok.warm_boot) and records first-request "
                         "latency vs steady p50")
    ap.add_argument("--overload", action="store_true",
                    help="run the ISSUE 8 overload-resilience scenario "
                         "instead of the standard load: chaos faults + "
                         "~4x capacity through RetryingClient, gating "
                         "on eventual success, refunded sheds, breaker "
                         "trip/recovery and the duplicate storm")
    ap.add_argument("--slo-ms", dest="slo_ms", type=float, default=2000.0,
                    help="overload mode: server-side p99 latency SLO "
                         "for ADMITTED requests")
    ap.add_argument("--cost", action="store_true",
                    help="gate per-request cost attribution (ISSUE 9): "
                         "the sum of per-request kernel-time shares "
                         "must match the server-side kernel histogram "
                         "total, and every refused request's cost "
                         "record must show zero ε net of refunds")
    ap.add_argument("--recorder", default=None, metavar="PATH",
                    help="attach a flight recorder dumping to PATH; in "
                         "--overload mode the phase-B breaker trip "
                         "must produce a dump from which the faulting "
                         "request's span chain + cost record "
                         "reconstruct jax-free (the CI obs-smoke gate)")
    ap.add_argument("--recorder-ab", dest="recorder_ab",
                    action="store_true",
                    help="interleaved A/B overhead gate: admitted-"
                         "request p50 with the recorder's span capture "
                         "attached must stay within 3%% of detached")
    ap.add_argument("--fault", action="append", default=None,
                    metavar="SPEC",
                    help="install a chaos fault before traffic (spec "
                         "as in `dpcorr serve --fault`; testing only)")
    ap.add_argument("--users", type=int, default=0, metavar="N",
                    help="run the PR 10 budget-directory scale drill "
                         "instead of the standard load: N distinct "
                         "synthetic users through the CompositeLedger, "
                         "gating on EXACT ledger balance (dyadic ε), "
                         "zero ε for refused requests, eviction + "
                         "rehydration > 0, and recording admission "
                         "p50/p99 (no kernels execute)")
    ap.add_argument("--users-shards", dest="users_shards", type=int,
                    default=64, help="--users mode: directory shards")
    ap.add_argument("--users-max-resident", dest="users_max_resident",
                    type=int, default=2048,
                    help="--users mode: LRU cap per shard (small "
                         "enough that evictions are guaranteed)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the ISSUE 20 horizontally-scaled fleet "
                         "scenario: a jax-free front end routing over "
                         "--replicas real `dpcorr serve` replicas "
                         "sharing one leased budget directory; gates "
                         "on exact aggregate==Σ per-replica admission "
                         "counts (pre-kill), qps(N)/qps(1) reported, "
                         "and a SIGKILL of one replica mid-traffic "
                         "losing zero ε: fleet-wide conservation "
                         "binary-exact (no double-spend on re-leased "
                         "shards, no lost charges) with 100% eventual "
                         "client success through the front end")
    ap.add_argument("--fleet-page", dest="fleet_page",
                    action="store_true",
                    help="run the ISSUE 11 fleet-telemetry scenario: "
                         "N real `dpcorr serve` subprocesses (one with "
                         "a slow-kernel chaos fault), driven over HTTP "
                         "and scraped by the fleet collector; gates on "
                         "exact aggregate==Σ per-instance counts out "
                         "of the merged registry, fleet ε conservation "
                         "via merged audit replay, and the burn-rate "
                         "page firing for exactly the faulted instance "
                         "and dumping its flight recorder (reason "
                         "slo_page, reconstructed jax-free)")
    ap.add_argument("--replicas", type=int, default=3,
                    help="--fleet mode: serve replicas behind the "
                         "front end (stamped into the artifact)")
    ap.add_argument("--fleet-users", dest="fleet_users", type=int,
                    default=64,
                    help="--fleet mode: distinct principals in the "
                         "shared leased budget directory")
    ap.add_argument("--fleet-shards", dest="fleet_shards", type=int,
                    default=8,
                    help="--fleet mode: budget directory shard count "
                         "(= lease granularity)")
    ap.add_argument("--lease-ttl-s", dest="lease_ttl_s", type=float,
                    default=1.5,
                    help="--fleet mode: lease TTL — bounds failover "
                         "convergence after the SIGKILL")
    ap.add_argument("--fleet-instances", dest="fleet_instances",
                    type=int, default=3,
                    help="--fleet-page mode: serve subprocesses to launch")
    ap.add_argument("--fleet-requests", dest="fleet_requests",
                    type=int, default=24,
                    help="--fleet/--fleet-page: requests per replica per phase (healthy instance for --fleet-page) "
                         "(the faulted one gets fewer — its point is "
                         "latency, not volume)")
    ap.add_argument("--fleet-dir", dest="fleet_dir",
                    default="fleet_artifacts",
                    help="--fleet/--fleet-page: artifact directory (span "
                         "spools, audit spools, recorder dumps, the "
                         "merged trace + fleet snapshot)")
    args = ap.parse_args()

    if args.users:
        # no kernels, no traffic — pure admission arithmetic; runs
        # before any jax configuration on purpose
        return run_users(args)
    if args.fleet_page:
        # the driver itself never needs jax: the kernels run inside
        # the serve subprocesses, the collector speaks HTTP + stdlib
        return run_fleet(args)
    if args.fleet:
        # jax-free too: supervisor + front end + retrying HTTP client
        return run_fleet_scale(args)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    if args.overload:
        return run_overload(args)
    import numpy as np

    from dpcorr.models.estimators.registry import serving_entry
    from dpcorr.serve import (
        DpcorrServer,
        EstimateRequest,
        InProcessClient,
        pinned_request_key,
    )
    from dpcorr.serve.ledger import BudgetExceededError, request_charges
    from dpcorr.utils import rng

    if args.trace:
        from dpcorr.obs import trace as obs_trace

        obs_trace.configure(args.trace)
    if args.fault:
        from dpcorr import chaos

        for spec in args.fault:
            chaos.install_fault(chaos.fault_from_spec(spec))

    warm_spec = None
    if args.warmup:
        # kernel signatures carry the request's raw n (padding is a
        # coalescing concern, not a kernel-shape one — serve.request)
        warm_spec = (f"{args.family}:{args.n}:{args.eps1}:"
                     f"{args.eps2}:auto" if args.warmup == "auto"
                     else args.warmup)

    # Budget sized so the load itself always fits: the refusal probe
    # below runs against dedicated parties with a tiny budget instead.
    srv = DpcorrServer(budget=1e9, max_batch=args.max_batch,
                       max_delay_s=args.max_delay_ms / 1000.0,
                       max_queue=4 * args.requests,
                       batch_mode=args.batch_mode,
                       warmup=warm_spec)
    recorder = None
    if args.recorder:
        from dpcorr.obs.recorder import FlightRecorder

        recorder = FlightRecorder(args.recorder)
        srv.attach_recorder(recorder)
    cli = InProcessClient(srv)

    # wait-for-ready hook: what a load balancer polling GET /readyz
    # does, in process. Compile counts after this point are traffic's.
    t_warm0 = time.perf_counter()
    warm_ready = cli.wait_ready(timeout=900)
    warmup_s = time.perf_counter() - t_warm0
    compiles_after_warmup = srv.stats.kernel_compiles
    readiness = cli.readiness()

    first_request_s = None
    warm_probe_resp = None
    if warm_spec:
        # one isolated request before the load: on a warm server its
        # latency is queueing + execution only — no compile. Recorded
        # against the steady-state p50 below.
        rs0 = np.random.RandomState(99)
        probe0 = EstimateRequest(
            args.family, rs0.randn(args.n).astype(np.float32),
            rs0.randn(args.n).astype(np.float32), args.eps1, args.eps2,
            party_x="warm-x", party_y="warm-y", seed=999983)
        t_f0 = time.perf_counter()
        warm_probe_resp = srv.estimate(probe0, timeout=300)
        first_request_s = time.perf_counter() - t_f0

    rs = np.random.RandomState(7)
    reqs = [EstimateRequest(
        args.family,
        rs.randn(args.n).astype(np.float32),
        rs.randn(args.n).astype(np.float32),
        args.eps1, args.eps2,
        party_x=f"px{i % 8}", party_y=f"py{i % 8}", seed=i)
        for i in range(args.requests)]

    responses: dict[int, object] = {}
    errors: list[str] = []
    lock = threading.Lock()
    per_client = -(-args.requests // args.clients)

    def client(c: int) -> None:
        futs = []
        for i in range(c * per_client,
                       min((c + 1) * per_client, args.requests)):
            try:
                futs.append((i, cli.submit(reqs[i])))
            except Exception as e:  # refusal/overload is a failure here
                with lock:
                    errors.append(f"submit {i}: {type(e).__name__}: {e}")
        for i, f in futs:
            try:
                r = f.result(timeout=300)
                with lock:
                    responses[i] = r
            except Exception as e:
                with lock:
                    errors.append(f"result {i}: {type(e).__name__}: {e}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    srv.close()

    stats = cli.stats()
    fill = stats["batch_fill_ratio"]

    # -- single source of truth: /metrics must agree with /stats ---------
    # (ISSUE 2 acceptance) scrape the real HTTP endpoints — same server,
    # same registry — and cross-check counter/gauge values numerically.
    import urllib.request

    from dpcorr.obs import parse_exposition
    from dpcorr.serve.server import make_http_server

    httpd = make_http_server(srv, port=0)
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    http_thread.start()
    port = httpd.server_address[1]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics") as resp:
        metrics_text = resp.read().decode()
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats") as resp:
        stats_http = json.load(resp)
    httpd.shutdown()
    series = parse_exposition(metrics_text)
    completed = (stats_http["batched_requests"]
                 + stats_http["unbatched_requests"])
    expected = {
        "dpcorr_serve_requests_total": stats_http["requests_total"],
        "dpcorr_serve_batches_flushed_total":
            stats_http["batches_flushed"],
        'dpcorr_serve_requests_completed_total{mode="batched"}':
            stats_http["batched_requests"],
        'dpcorr_serve_requests_completed_total{mode="unbatched"}':
            stats_http["unbatched_requests"],
        "dpcorr_serve_latency_seconds_count": completed,
        "dpcorr_serve_kernel_compiles_total":
            stats_http["kernel_compiles"],
        "dpcorr_serve_kernel_cache_hits_total":
            stats_http["kernel_hits"],
        "dpcorr_serve_kernel_cache_size":
            stats_http["kernel_cache_size"],
        "dpcorr_serve_queue_depth": stats_http["queue_depth"],
    }
    # a zero-valued labelled child may legitimately be absent from the
    # exposition (never incremented), hence the 0.0 default
    metrics_mismatches = {
        k: {"metrics": series.get(k, 0.0), "stats": float(want)}
        for k, want in expected.items()
        if series.get(k, 0.0) != float(want)}

    trace_spans = None
    if args.trace:
        from dpcorr.obs import read_spans

        # strict parse: an unparseable line raises and fails the run
        trace_spans = len(read_spans(args.trace))

    # -- invariant 2: bit-identity on a sample of responses --------------
    single = jax.jit(serving_entry(args.family, args.eps1, args.eps2,
                                   alpha=0.05, normalise=True))
    master = rng.master_key(srv.seed)
    step = max(1, len(responses) // max(args.verify, 1))
    checked = mismatches = 0
    check_ci = args.batch_mode == "exact"
    for i in sorted(responses)[::step]:
        r = responses[i]
        # requests pin their seeds, so the reference recomputes the
        # content-bound pinned-subtree key (serve.server contract)
        d = single(pinned_request_key(master, reqs[i], r.seed),
                   reqs[i].x, reqs[i].y)
        checked += 1
        if float(d[0]) != r.rho_hat or (check_ci and (
                float(d[1]) != r.ci_low or float(d[2]) != r.ci_high)):
            mismatches += 1

    # -- invariant 3: refusal exactly at budget exhaustion ---------------
    probe = EstimateRequest(args.family, reqs[0].x, reqs[0].y,
                            args.eps1, args.eps2,
                            party_x="probe-x", party_y="probe-y")
    spend = request_charges(probe)["probe-x"]
    admit_budget = 3 * spend  # fits exactly 3 queries
    srv2 = DpcorrServer(budget=1e9,
                        per_party_budget={"probe-x": admit_budget,
                                          "probe-y": admit_budget},
                        max_delay_s=0.001)
    admitted = 0
    refused_at = None
    for q in range(5):
        try:
            srv2.estimate(probe)
            admitted += 1
        except BudgetExceededError:
            refused_at = q
            break
    srv2_cost_records = list(srv2.costs.to_dict().values())
    srv2.close()

    # -- ISSUE 9: per-request cost attribution gates ---------------------
    cost_doc = None
    if args.cost:
        # (a) conservation: the per-request kernel-time shares (response
        # metadata) sum to the server-side kernel histogram total — the
        # same seconds, attributed instead of aggregated
        hist_total = float(stats.get("kernel_histogram", {})
                           .get("sum", 0.0))
        cost_resps = [r for r in list(responses.values())
                      + ([warm_probe_resp] if warm_probe_resp else [])
                      if r.cost is not None]
        share_total = sum(r.cost["kernel_s"] for r in cost_resps)
        tol = 0.01 * max(hist_total, share_total) + 1e-4
        conserved = (len(cost_resps) == len(responses)
                     + (1 if warm_probe_resp else 0)
                     and abs(share_total - hist_total) <= tol)
        # (b) refusals are free: every refused request's cost record
        # nets zero ε after refunds (the budget-refusal probe's server)
        refused_records = [r for r in srv2_cost_records
                           if any(str(e).startswith("refused")
                                  for e in r["events"])]
        refused_zero = (len(refused_records) >= 1 and all(
            all(v == 0.0 for v in r["eps_net"].values())
            for r in refused_records))
        cost_doc = {
            "responses_with_cost": len(cost_resps),
            "kernel_share_total_s": round(share_total, 6),
            "kernel_histogram_total_s": round(hist_total, 6),
            "tolerance_s": round(tol, 6),
            "conserved": conserved,
            "refused_records": len(refused_records),
            "refused_zero_eps": refused_zero,
            "cost_aggregate": stats.get("costs"),
        }

    # -- ISSUE 9: recorder overhead A/B ----------------------------------
    ab_doc = recorder_ab(args) if args.recorder_ab else None

    ok = {
        "completed": len(responses) == args.requests and not errors,
        "coalesced": fill > 1.0,
        "bit_identical": checked > 0 and mismatches == 0,
        "ledger_refusal": admitted == 3 and refused_at == 3,
        "metrics_consistent": not metrics_mismatches,
    }
    if args.trace:
        ok["traced"] = trace_spans is not None and trace_spans > 0
    if cost_doc is not None:
        ok["cost_attribution"] = (cost_doc["conserved"]
                                  and cost_doc["refused_zero_eps"])
    if ab_doc is not None:
        ok["recorder_overhead"] = ab_doc["ok"]
    recorder_doc = None
    if recorder is not None:
        # publish a final dump so the run always leaves an artifact
        recorder.dump("cli", source="serve_load")
        recorder_doc = {"path": args.recorder, "dumps": recorder.dumps,
                        "reasons": recorder.reasons}
    warmup_doc = None
    if warm_spec:
        compiles_during_traffic = (stats["kernel_compiles"]
                                   - compiles_after_warmup)
        p50 = stats.get("latency_s", {}).get("p50")
        warmup_doc = {
            "spec": warm_spec,
            "ready": warm_ready,
            "warmup_s": round(warmup_s, 3),
            "readiness": readiness,
            "kernel_compiles_warmup": compiles_after_warmup,
            "kernel_compiles_during_traffic": compiles_during_traffic,
            "first_request_s": (round(first_request_s, 4)
                                if first_request_s is not None else None),
            "steady_p50_s": p50,
            "first_request_vs_p50": (round(first_request_s / p50, 2)
                                     if first_request_s and p50 else None),
        }
        # the compile-ahead acceptance: a warmed server serves the whole
        # load without a single fresh compilation
        ok["warm_boot"] = warm_ready and compiles_during_traffic == 0
    out = {
        "metric": "serve_load",
        "requests": args.requests,
        "clients": args.clients,
        "n": args.n,
        "family": args.family,
        "batch_mode": args.batch_mode,
        "wall_s": round(wall, 3),
        "requests_per_sec": round(args.requests / wall, 1),
        "batch_fill_ratio": round(fill, 2),
        "bit_checked": checked,
        "bit_mismatches": mismatches,
        "refusal_probe": {"admitted": admitted, "refused_at": refused_at},
        "metrics_mismatches": metrics_mismatches,
        "trace": args.trace,
        "trace_spans": trace_spans,
        "warmup": warmup_doc,
        "cost": cost_doc,
        "recorder_ab": ab_doc,
        "recorder": recorder_doc,
        "ok": ok,
        "errors": errors[:5],
        "stats": stats,
    }
    blob = json.dumps(out, indent=2)
    print(blob)
    if args.out_json:
        with open(args.out_json, "w") as f:
            f.write(blob)
    return 0 if all(ok.values()) else 1


def recorder_ab(args) -> dict:
    """Interleaved A/B recorder-overhead measurement (ISSUE 9
    acceptance): one warmed server, alternating rounds with the flight
    recorder's span capture attached ("on") vs detached ("off");
    admitted-request p50 with capture on must stay within 3% (+1 ms
    timing-jitter slack) of capture off. Interleaving round-robins the
    arms so clock drift and cache effects land on both equally."""
    from statistics import median

    import numpy as np

    from dpcorr.obs.recorder import FlightRecorder
    from dpcorr.serve import DpcorrServer, EstimateRequest, InProcessClient

    rounds, per_round = 16, 24
    srv = DpcorrServer(budget=1e9, max_batch=per_round,
                       max_delay_s=0.002,
                       warmup=f"{args.family}:{args.n}:{args.eps1}:"
                              f"{args.eps2}:auto")
    srv.wait_ready(timeout=900)
    cli = InProcessClient(srv)
    rec = FlightRecorder(args.recorder or "serve_ab_flightrec.json")
    rec.watch_registry(srv.stats.registry)
    rs = np.random.RandomState(3)
    lat: dict[str, list[float]] = {"on": [], "off": []}
    seed = 1_000_000

    def burst(sink: list[float] | None) -> None:
        nonlocal seed
        futs = []
        for _ in range(per_round):
            x = rs.randn(args.n).astype(np.float32)
            y = rs.randn(args.n).astype(np.float32)
            futs.append(cli.submit(EstimateRequest(
                args.family, x, y, args.eps1, args.eps2,
                party_x="ab-x", party_y="ab-y", seed=seed)))
            seed += 1
        for f in futs:
            r = f.result(timeout=300)
            if sink is not None:
                sink.append(r.latency_s)

    burst(None)  # throwaway: absorb any first-flush residue
    for rd in range(rounds):
        arm = "on" if rd % 2 == 0 else "off"
        if arm == "on":
            # exactly what attach/detach toggles on the request hot
            # path: span production + the recorder's ring append
            srv.tracer.add_observer(rec.record_span)
        try:
            burst(lat[arm])
        finally:
            if arm == "on":
                srv.tracer.remove_observer(rec.record_span)
    srv.close()
    p50_on = median(lat["on"])
    p50_off = median(lat["off"])
    return {"rounds": rounds, "per_round": per_round,
            "p50_on_s": round(p50_on, 6), "p50_off_s": round(p50_off, 6),
            "overhead_ratio": round(p50_on / p50_off, 4)
            if p50_off > 0 else None,
            "ok": p50_on <= p50_off * 1.03 + 1e-3}


def run_users(args) -> int:
    """PR 10 scale drill: N distinct users (≥ 1M in CI) through one
    :class:`~dpcorr.serve.budget_dir.CompositeLedger` admission path.

    Every ε in the scenario is dyadic (party legs 2^-4 each, user leg
    2^-3, user budget 2^-2), so every balance gate is EXACT float
    equality, not a tolerance: spent == Σ per-user charges net of
    refunds at both the directory and the party ledger, refused
    requests consume zero ε at every level, and per-user spot checks
    land on their class's exact balance. The directory runs with a
    deliberately small residency cap so the LRU spill/rehydrate path
    is exercised at scale (counters gated > 0). ``fsync`` is off —
    the drill measures admission arithmetic and the journaling write
    path, not the disk; the chaos harness owns durability proof."""
    import shutil
    import tempfile

    from dpcorr.serve.budget_dir import BudgetDirectory, CompositeLedger
    from dpcorr.serve.ledger import BudgetExceededError, PrivacyLedger
    from dpcorr.serve.stats import percentiles

    n_users = args.users
    # dyadic legs: party 2^-4 per side, user leg = their sum = 2^-3,
    # user budget 2^-2 — every user fits exactly two charges
    leg = 0.0625
    user_leg = 2 * leg
    user_budget = 2 * user_leg
    root = tempfile.mkdtemp(prefix="dpcorr-users-")
    directory = BudgetDirectory(
        os.path.join(root, "dir"), shards=args.users_shards,
        user_budget=user_budget,
        max_resident=args.users_max_resident,
        # compaction folds the WHOLE user table per cycle — amortised
        # fine at serving rates, pathological in a tight 1M-user loop;
        # the WAL alone is the authoritative journal either way
        compact_every=None, fsync=False)
    comp = CompositeLedger(PrivacyLedger(1e9), directory)
    charges = {"pa": leg, "pb": leg}

    lat: list[float] = []
    admitted = 0
    refused = 0
    refused_levels: dict[str, int] = {}
    t0 = time.perf_counter()

    def charge(i: int, k: int) -> None:
        nonlocal admitted, refused
        aug = comp.augment(charges, user=f"u{i:07d}")
        t = time.perf_counter()
        try:
            comp.charge(aug, charge_id=f"c:{i}:{k}")
        except BudgetExceededError as e:
            refused += 1
            refused_levels[e.level] = refused_levels.get(e.level, 0) + 1
        else:
            admitted += 1
        lat.append(time.perf_counter() - t)

    # phase 1: every user charges once; phase 2: every 8th user again
    # (their window is now full); phase 3: every 64th user attempts a
    # third — refused at the user level, charge-free; phase 4: every
    # 16th user's second charge is refunded (shed-path arithmetic)
    for i in range(n_users):
        charge(i, 0)
    for i in range(0, n_users, 8):
        charge(i, 1)
    for i in range(0, n_users, 64):
        charge(i, 2)
    n_refunds = 0
    for i in range(0, n_users, 16):
        comp.refund(comp.augment(charges, user=f"u{i:07d}"),
                    charge_id=f"c:{i}:1", reason="shed")
        n_refunds += 1
    wall = time.perf_counter() - t0

    expect_admitted = n_users + -(-n_users // 8)
    expect_refused = -(-n_users // 64)
    counters = directory.counters()
    # EXACT: dyadic sums accumulate with no rounding
    dir_balance = (counters["charged_eps"]
                   == user_leg * expect_admitted
                   and counters["refunded_eps"] == user_leg * n_refunds)
    ledger_balance = (
        comp.ledger.spent("pa") == leg * (expect_admitted - n_refunds)
        and comp.ledger.spent("pb") == leg * (expect_admitted
                                              - n_refunds))
    # spot checks: each sampled user sits on its class's exact balance
    spot_every = max(1, n_users // 1000)
    spot_checked = spot_mismatches = 0
    for i in range(0, n_users, spot_every):
        want = (user_leg if i % 16 == 0
                else user_budget if i % 8 == 0 else user_leg)
        spot_checked += 1
        if directory.spent(f"u{i:07d}") != want:
            spot_mismatches += 1
    pct = percentiles(lat, (0.5, 0.99))
    ok = {
        "admitted_expected": admitted == expect_admitted,
        "refused_expected": refused == expect_refused
                            and refused_levels == {"user":
                                                   expect_refused},
        "directory_balance_exact": dir_balance,
        "ledger_balance_exact": ledger_balance,
        "spot_checks_exact": spot_checked > 0 and spot_mismatches == 0,
        "refusals_charge_free": comp.refusals_by_level()["user"]
                                == expect_refused,
        "evictions": counters["evictions"] > 0,
        "rehydrations": counters["rehydrations"] > 0,
    }
    out = {
        "metric": "serve_users",
        "users": n_users,
        "shards": directory.n_shards,
        "max_resident_per_shard": args.users_max_resident,
        "charges_admitted": admitted,
        "charges_refused": refused,
        "refused_by_level": refused_levels,
        "refunds": n_refunds,
        "wall_s": round(wall, 3),
        "admissions_per_sec": round(len(lat) / wall, 1),
        "admission_p50_s": round(pct["p50"], 9),
        "admission_p99_s": round(pct["p99"], 9),
        "spot_checks": spot_checked,
        "spot_mismatches": spot_mismatches,
        "directory": comp.directory_snapshot(),
        "ok": ok,
    }
    comp.close()
    shutil.rmtree(root)
    blob = json.dumps(out, indent=2)
    print(blob)
    if args.out_json:
        with open(args.out_json, "w") as f:
            f.write(blob)
    return 0 if all(ok.values()) else 1


def run_fleet(args) -> int:
    """ISSUE 11 acceptance: the fleet telemetry plane against REAL
    processes. Launches ``--fleet-instances`` serve subprocesses on
    ephemeral ports (port discovery via the boot banner), installs the
    ``serve.kernel_slow`` chaos fault in the last one, drives HTTP
    traffic at all of them, and proves the whole plane end to end:

    - **federated counting** — the aggregate series out of the merged
      registry equals the sorted-instance sum of every per-instance
      series AND the client-side success count, exactly (integer
      counters, no tolerance);
    - **fleet ε conservation** — replaying the union of the audit
      spools reproduces the fold of the per-instance ledger snapshots,
      binary-exact (same sorted-instance addition order on both sides);
    - **deterministic paging** — the multi-window burn-rate engine,
      fed the two scrapes under a scripted clock, pages exactly the
      faulted instance (every healthy one stays ``ok``), and the page
      arms THAT instance's flight recorder over ``POST /obs/trigger``:
      the dump lands with reason ``slo_page`` and reconstructs in a
      jax-free subprocess;
    - **span union** — the merged Chrome trace carries one pid per
      instance.

    Artifacts (``--fleet-dir``): per-instance span/audit spools and
    recorder dumps, ``fleet_snapshot.json`` (the collector document),
    ``fleet_trace.json`` (the merged Chrome trace) — what CI uploads.
    """
    import subprocess
    import urllib.request

    from dpcorr.obs import fleet as obs_fleet
    from dpcorr.obs import slo as obs_slo

    n_inst = args.fleet_instances
    if n_inst < 2:
        print("--fleet-page needs at least 2 instances (one healthy, "
              "one faulted)", file=sys.stderr)
        return 2
    fdir = os.path.abspath(args.fleet_dir)
    os.makedirs(fdir, exist_ok=True)
    names = [f"fleet-{i}" for i in range(n_inst)]
    faulted = names[-1]
    spools = {n: os.path.join(fdir, f"{n}_spans.jsonl") for n in names}
    audits = {n: os.path.join(fdir, f"{n}_audit.jsonl") for n in names}
    recs = {n: os.path.join(fdir, f"{n}_flightrec.json") for n in names}
    for path in (*spools.values(), *audits.values(), *recs.values()):
        if os.path.exists(path):
            os.remove(path)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs: dict[str, subprocess.Popen] = {}
    logs = {}
    urls: dict[str, str] = {}
    parties = {n: (f"{n}-x", f"{n}-y") for n in names}
    #: every request >= 600 ms on the faulted instance — strictly above
    #: the 0.5 s bucket bound the latency objective pins
    fault_spec = "point=serve.kernel_slow,mode=sleep,delay_ms=600"
    try:
        for name in names:
            cmd = [sys.executable, "-m", "dpcorr", "serve",
                   "--port", "0", "--instance", name,
                   "--platform", "cpu", "--budget", "1e9",
                   "--span-spool", spools[name],
                   "--audit", audits[name],
                   "--flight-recorder", recs[name],
                   "--aot", "off", "--max-delay-ms", "5"]
            if name == faulted:
                cmd += ["--fault", fault_spec]
            logs[name] = open(os.path.join(fdir, f"{name}.log"), "w")
            procs[name] = subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=logs[name],
                text=True, env=env, cwd=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
        # ---- port discovery: the boot banner prints AFTER bind --------
        deadline = time.monotonic() + 300
        for name in names:
            line = ""
            while time.monotonic() < deadline:
                line = procs[name].stdout.readline()
                if line.strip() or procs[name].poll() is not None:
                    break
            if not line.strip():
                raise RuntimeError(
                    f"{name}: no boot banner (rc="
                    f"{procs[name].poll()}; see {name}.log)")
            banner = json.loads(line)["serving"]
            urls[name] = f"http://127.0.0.1:{banner['port']}"

        def post_estimate(name: str, seed: int,
                          timeout: float = 120.0) -> dict:
            import random as _random

            px, py = parties[name]
            rs = _random.Random(seed)
            x = [rs.gauss(0.0, 1.0) for _ in range(64)]
            y = [xi * 0.5 + rs.gauss(0.0, 1.0) for xi in x]
            blob = json.dumps({
                "family": args.family, "x": x, "y": y,
                "eps1": args.eps1, "eps2": args.eps2,
                "party_x": px, "party_y": py, "seed": seed}).encode()
            req = urllib.request.Request(
                f"{urls[name]}/estimate", data=blob,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.load(r)

        # ---- warm-up: compile latency lands BEFORE the t0 scrape ------
        def warm(name: str) -> None:
            for k in range(2):
                post_estimate(name, seed=900_000 + k, timeout=600)

        warm_threads = [threading.Thread(target=warm, args=(n,))
                        for n in names]
        for t in warm_threads:
            t.start()
        for t in warm_threads:
            t.join()

        collector = obs_fleet.FleetCollector(
            [(n, urls[n]) for n in names])
        snap0 = collector.scrape(timeout_s=30)
        if snap0.errors():
            raise RuntimeError(f"t0 scrape errors: {snap0.errors()}")

        # ---- traffic --------------------------------------------------
        plan = {n: (args.fleet_requests if n != faulted
                    else max(4, args.fleet_requests // 4))
                for n in names}
        successes: dict[str, int] = {n: 0 for n in names}
        errors: list[str] = []
        lock = threading.Lock()

        def drive(name: str) -> None:
            for k in range(plan[name]):
                try:
                    post_estimate(name, seed=1000 * names.index(name) + k)
                    with lock:
                        successes[name] += 1
                except Exception as e:
                    with lock:
                        errors.append(
                            f"{name}#{k}: {type(e).__name__}: {e}")

        t0 = time.perf_counter()
        threads = [threading.Thread(target=drive, args=(n,))
                   for n in names]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0

        snap1 = collector.scrape(timeout_s=30)
        if snap1.errors():
            raise RuntimeError(f"t1 scrape errors: {snap1.errors()}")

        # ---- gate 1: aggregate == Σ per-instance == client count ------
        fams1 = snap1.families()
        agg = obs_fleet.families_to_flat(snap1.aggregate())
        merged = obs_fleet.families_to_flat(snap1.merged())
        total_series = "dpcorr_serve_requests_total"
        per_inst = {
            n: merged.get(f'{total_series}{{instance="{n}"}}', 0.0)
            for n in names}
        expected = {n: plan[n] + 2 for n in names}  # +2 warm-ups
        stats1 = snap1.stats()
        counts_exact = (
            agg.get(total_series) == sum(per_inst[n] for n in sorted(names))
            and all(per_inst[n] == expected[n] == successes[n] + 2
                    for n in names)
            and all(stats1[n]["requests_total"] == expected[n]
                    for n in names))

        # ---- gate 2: burn-rate page, exactly the faulted instance -----
        paged: list = []
        objective = obs_slo.Objective(
            name="latency-slo", kind="latency", target=0.05,
            threshold_s=0.5)
        hook = obs_slo.http_trigger_hook(urls, timeout_s=30)
        engine = obs_slo.BurnRateEngine(
            [objective],
            on_page=lambda alert: (paged.append(alert), hook(alert)))
        # scripted clock: the two scrapes ARE the burn window — the
        # engine's arithmetic is a pure function of (deltas, clock)
        fams0 = snap0.families()
        engine.observe(fams0, at=0.0)
        engine.observe(fams1, at=60.0)
        alerts = engine.evaluate(at=60.0)
        paged_instances = sorted({a.instance for a in paged})
        page_exact = (paged_instances == [faulted]
                      and all(engine.state("latency-slo", n) == "ok"
                              for n in names if n != faulted))

        # ---- gate 3: the page dumped the faulted recorder, jax-free ---
        dump_doc = None
        dump_jax_free = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not os.path.exists(
                recs[faulted]):
            time.sleep(0.2)
        if os.path.exists(recs[faulted]):
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import json, sys\n"
                 "from dpcorr.obs.recorder import read_dump\n"
                 "d = read_dump(sys.argv[1])\n"
                 "assert 'jax' not in sys.modules, 'jax leaked'\n"
                 "print(json.dumps({'reason': d['reason'],"
                 " 'detail': d.get('detail'),"
                 " 'spans': len(d['spans'])}))",
                 recs[faulted]],
                capture_output=True, text=True, env=env,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
            if probe.returncode == 0:
                dump_doc = json.loads(probe.stdout)
                dump_jax_free = True
            else:
                errors.append(f"dump probe: {probe.stderr.strip()}")
        recorder_ok = (dump_jax_free and dump_doc is not None
                       and dump_doc["reason"] == "slo_page"
                       and dump_doc["detail"].get("instance") == faulted)

        # ---- gate 4: fleet ε conservation via merged audit replay -----
        # the serve subprocesses must flush their audit spools; they do
        # so synchronously per event, so the files are already complete
        ledgers = {n: obs_fleet.ledger_parties(stats1[n]) for n in names}
        cons = obs_fleet.conservation(audits, ledgers)
        eps_positive = all(
            cons["fleet"].get(p, 0.0) > 0.0
            for n in names for p in parties[n])

        # ---- gate 5: span union — one pid per instance ----------------
        trace_doc = obs_fleet.fleet_chrome_trace(spools)
        pids = {ev["pid"] for ev in trace_doc["traceEvents"]}
        trace_ok = len(pids) == n_inst

        # ---- artifacts ------------------------------------------------
        snap_path = os.path.join(fdir, "fleet_snapshot.json")
        with open(snap_path, "w") as f:
            json.dump(snap1.to_doc(), f, indent=2)
        trace_path = os.path.join(fdir, "fleet_trace.json")
        obs_fleet.write_fleet_chrome_trace(spools, trace_path)

        ok = {
            "fleet_up": not snap1.errors() and not errors,
            "aggregate_counts_exact": counts_exact,
            "burn_rate_page_exact": page_exact,
            "recorder_armed_jax_free": recorder_ok,
            "eps_conservation": cons["ok"] and eps_positive,
            "trace_union": trace_ok,
        }
        out = {
            "metric": "serve_fleet",
            "instances": n_inst,
            "faulted": faulted,
            "fault": fault_spec,
            "requests_per_instance": plan,
            "successes": successes,
            "wall_s": round(wall, 3),
            "aggregate_qps": round(
                sum(successes.values()) / wall, 2) if wall else None,
            "per_instance_requests_total": per_inst,
            "aggregate_requests_total": agg.get(total_series),
            "alerts": [a.to_dict() for a in engine.alerts],
            "paged_instances": paged_instances,
            "slo_states": engine.states(),
            "flight_recorder": {"path": recs[faulted],
                                "dump": dump_doc,
                                "jax_free": dump_jax_free},
            "conservation": cons,
            "trace_pids": sorted(pids),
            "artifacts": {"snapshot": snap_path, "trace": trace_path,
                          "spools": spools, "audits": audits},
            "ok": ok,
            "errors": errors[:5],
        }
    finally:
        for name, p in procs.items():
            p.terminate()
        for name, p in procs.items():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
            if p.stdout is not None:
                p.stdout.close()
        for fh in logs.values():
            fh.close()

    blob = json.dumps(out, indent=2)
    print(blob)
    if args.out_json:
        with open(args.out_json, "w") as f:
            f.write(blob)
    return 0 if all(ok.values()) else 1


def run_fleet_scale(args) -> int:
    """ISSUE 20 acceptance: the horizontally scaled serve fleet.

    Boots two cells of REAL ``dpcorr serve`` replicas under the
    :mod:`dpcorr.serve.fleet` supervisor — one replica (the qps
    baseline), then ``--replicas`` of them sharing ONE leased budget
    directory behind the jax-free :class:`FleetFrontend` — and drives
    every request through the front end with the stock
    :class:`RetryingClient`. Three claim groups:

    - **scale** — aggregate qps at N replicas vs 1, same offered
      concurrency; the ~linear gate is asserted only when the box has
      the cores to make it meaningful (≥ 4 per replica), else reported
      as ``null`` (measured, not asserted).
    - **exact counting (pre-kill)** — with the fleet healthy, client
      successes == Σ per-replica ``requests_total`` deltas, integer-
      exact: the front end admits each logical request exactly once.
    - **zero-ε failover** — SIGKILL one replica mid-traffic; the
      supervisor relaunches it with identical argv; its shards are
      re-leased on demand; every client request still succeeds. Then,
      binary-exact: the fleet-wide merged audit replay of the shared
      budget directory equals the on-disk per-user balances equals the
      incremental expectation (charge-id dedup over the shared shard
      WALs makes this kill-point-independent — no double spend on a
      re-leased shard, no lost charge). Per-party ledgers are
      instance-local: survivors must be replay==ledger exact; the
      victim's trail may trail its ledger by AT MOST the one charge
      in flight at the kill (the ledger's documented spend-then-audit
      durability order — the audit under-reports, never the budget).
    """
    import shutil
    import urllib.request

    from dpcorr.obs import fleet as obs_fleet
    from dpcorr.obs.audit import read_events
    from dpcorr.obs.audit import replay as audit_replay
    from dpcorr.obs.budget_replay import fold_levels, read_user_balances
    from dpcorr.serve.client import (
        HttpEstimateClient,
        RetryingClient,
        RetryPolicy,
    )
    from dpcorr.serve.fleet import ReplicaSpec, Supervisor, lease_table
    from dpcorr.serve.fleet.frontend import (
        FleetFrontend,
        make_frontend_http_server,
    )
    from dpcorr.serve.ledger import request_charges
    from dpcorr.serve.request import EstimateRequest

    n_rep = args.replicas
    if n_rep < 2:
        print("--fleet needs --replicas >= 2 (a kill victim and at "
              "least one survivor)", file=sys.stderr)
        return 2
    fdir = os.path.abspath(args.fleet_dir)
    os.makedirs(fdir, exist_ok=True)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    shards = args.fleet_shards
    users = [f"user-{u}" for u in range(args.fleet_users)]
    errors: list[str] = []

    def spec_for(name: str, subdir: str, target: int) -> ReplicaSpec:
        argv = [sys.executable, "-m", "dpcorr", "serve",
                "--port", "0", "--instance", name,
                "--platform", "cpu", "--budget", "1e9",
                "--ledger", os.path.join(subdir, f"{name}_ledger.json"),
                "--audit", os.path.join(subdir, f"{name}_audit.jsonl"),
                "--user-dir", os.path.join(subdir, "budget"),
                "--user-shards", str(shards),
                "--user-budget", "1e9",
                "--lease-dir", os.path.join(subdir, "leases"),
                "--lease-ttl-s", str(args.lease_ttl_s),
                "--lease-target", str(target),
                "--aot", "off", "--max-batch", "8",
                "--max-delay-ms", "5"]
        return ReplicaSpec(name=name, argv=argv, env=env, cwd=repo_root,
                           stderr_path=os.path.join(subdir,
                                                    f"{name}.log"))

    class Cell:
        """One booted fleet: supervisor + front end + HTTP server +
        background health poller."""

        def __init__(self, tag: str, n: int):
            self.subdir = os.path.join(fdir, tag)
            shutil.rmtree(self.subdir, ignore_errors=True)
            os.makedirs(self.subdir)
            self.names = [f"rep-{i}" for i in range(n)]
            target = -(-shards // n)
            self.fe = FleetFrontend(
                {}, lease_dir=os.path.join(self.subdir, "leases"),
                cooldown_s=0.5, table_ttl_s=0.25)
            self.sup = Supervisor(
                [spec_for(nm, self.subdir, target) for nm in self.names],
                on_up=lambda name, url, banner:
                    self.fe.set_replica(name, url))
            self.sup.start()
            self.httpd = make_frontend_http_server(self.fe,
                                                   "127.0.0.1", 0)
            threading.Thread(target=self.httpd.serve_forever,
                             daemon=True).start()
            self.front_url = (f"http://127.0.0.1:"
                              f"{self.httpd.server_address[1]}")
            deadline = time.monotonic() + 600
            ready: dict = {}
            while time.monotonic() < deadline:
                ready = self.fe.poll_ready()
                if len(ready) == n and all(ready.values()):
                    break
                time.sleep(0.25)
            else:
                raise RuntimeError(f"{tag}: replicas never ready: "
                                   f"{ready}")
            self._stop = threading.Event()

            def health():
                while not self._stop.is_set():
                    try:
                        self.fe.poll_ready()
                    except Exception:
                        pass
                    self._stop.wait(0.25)

            threading.Thread(target=health, daemon=True).start()

        def replica_stats(self) -> dict[str, dict]:
            out = {}
            for name in self.names:
                with urllib.request.urlopen(
                        f"{self.sup.url(name)}/stats", timeout=30) as r:
                    out[name] = json.load(r)
            return out

        def audits(self) -> dict[str, str]:
            return {n: os.path.join(self.subdir, f"{n}_audit.jsonl")
                    for n in self.names}

        def stop(self) -> None:
            self._stop.set()
            self.httpd.shutdown()
            self.sup.stop()

    sent: dict[str, int] = {}  # fleet cell only: user -> logical reqs

    def drive(cell: Cell, n_requests: int, n_threads: int, base: int,
              *, count: bool, policy: RetryPolicy,
              kill_after: int | None = None,
              victim: str | None = None) -> dict:
        """Drive ``n_requests`` logical requests through the front
        end; each eventually succeeds or lands in ``errs``. With
        ``kill_after``, SIGKILL ``victim`` once that many completed."""
        cli = RetryingClient(HttpEstimateClient(cell.front_url,
                                                timeout_s=120.0),
                             policy)
        done = [0]
        errs: list[str] = []
        lock = threading.Lock()
        killed = threading.Event()

        def one(i: int) -> None:
            import random as _random

            rs = _random.Random(base + i)
            x = [rs.gauss(0.0, 1.0) for _ in range(32)]
            y = [xi * 0.5 + rs.gauss(0.0, 1.0) for xi in x]
            user = users[i % len(users)]
            req = EstimateRequest(
                args.family, x, y, args.eps1, args.eps2,
                party_x="fleet-x", party_y="fleet-y", user=user)
            try:
                cli.estimate(req, timeout=120.0)
                with lock:
                    done[0] += 1
                    if count:
                        sent[user] = sent.get(user, 0) + 1
            except Exception as e:
                with lock:
                    errs.append(f"#{i}: {type(e).__name__}: {e}")

        def worker(ids: list[int]) -> None:
            for i in ids:
                one(i)
                if kill_after is not None and not killed.is_set():
                    with lock:
                        due = done[0] >= kill_after
                    if due and not killed.is_set():
                        killed.set()
                        cell.sup.kill(victim)

        lanes: list[list[int]] = [[] for _ in range(n_threads)]
        for i in range(n_requests):
            lanes[i % n_threads].append(i)
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(lane,))
                   for lane in lanes if lane]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return {"done": done[0], "wall_s": wall, "errors": errs,
                "client": cli.stats()}

    threads_n = min(16, 4 * n_rep)
    per_phase = args.fleet_requests * n_rep
    steady = RetryPolicy(max_attempts=6, base_delay_s=0.05,
                         max_delay_s=1.0, deadline_s=120.0)
    failover = RetryPolicy(max_attempts=20, base_delay_s=0.1,
                           max_delay_s=1.0, deadline_s=240.0)

    # ---- phase A: single-replica qps baseline ---------------------
    solo = Cell("solo", 1)
    try:
        drive(solo, 2 * len(users), threads_n, 500_000,
              count=False, policy=steady)  # warm: compiles + leases
        a = drive(solo, per_phase, threads_n, 600_000,
                  count=False, policy=steady)
    finally:
        solo.stop()
    if a["errors"]:
        errors.extend(f"solo {e}" for e in a["errors"][:3])
    qps1 = a["done"] / a["wall_s"] if a["wall_s"] else None

    # ---- phase B: N replicas, exact counting + qps ----------------
    fleet = Cell("fleet", n_rep)
    victim = fleet.names[-1]
    try:
        warm = drive(fleet, 2 * len(users), threads_n, 700_000,
                     count=True, policy=steady)
        stats0 = fleet.replica_stats()
        b = drive(fleet, per_phase, threads_n, 800_000,
                  count=True, policy=steady)
        stats1 = fleet.replica_stats()
        qps_n = b["done"] / b["wall_s"] if b["wall_s"] else None
        admitted_delta = {
            n: (stats1[n]["requests_total"]
                - stats0[n]["requests_total"])
            for n in fleet.names}
        counts_exact = (not warm["errors"] and not b["errors"]
                        and b["done"] == per_phase
                        and sum(admitted_delta.values()) == per_phase)

        # ---- phase C: SIGKILL mid-traffic -------------------------
        owners_before = {s: rec.get("owner") for s, rec in
                         lease_table(os.path.join(fleet.subdir,
                                                  "leases")).items()}
        c = drive(fleet, per_phase, threads_n, 900_000,
                  count=True, policy=failover,
                  kill_after=per_phase // 3, victim=victim)
        restarted = fleet.sup.wait_restarted(victim, 1, timeout_s=300)
        # let the restarted replica finish boot + re-lease its share
        time.sleep(2.0 * args.lease_ttl_s)
        owners_after = {s: rec.get("owner") for s, rec in
                        lease_table(os.path.join(fleet.subdir,
                                                 "leases")).items()}
        stats2 = fleet.replica_stats()
        lease_snaps = {n: stats2[n].get("leases") for n in fleet.names}
    finally:
        fleet.stop()

    kill_ok = (not c["errors"] and c["done"] == per_phase and restarted
               and fleet.sup.restarts.get(victim, 0) == 1)
    victim_shards = sorted(s for s, o in owners_before.items()
                           if o == victim)
    released_ok = all(owners_after.get(s) is not None
                      for s in victim_shards)

    # ---- gate: per-party conservation (instance-local ledgers) ----
    def party_only(events: list[dict]) -> list[dict]:
        out = []
        for ev in events:
            ch = {p: e for p, e in ev["charges"].items()
                  if not p.startswith("user/")
                  and not p.startswith("global/")}
            if ch:
                out.append({**ev, "charges": ch})
        return out

    trails = {n: read_events(path)
              for n, path in fleet.audits().items()}
    survivors = [n for n in fleet.names if n != victim]
    cons = obs_fleet.conservation(
        {n: party_only(trails[n]) for n in survivors},
        {n: obs_fleet.ledger_parties(stats2[n]) for n in survivors})
    # victim: spend persists before the audit line, so the kill can
    # orphan AT MOST the one in-flight charge out of its trail (and a
    # later same-id retry on the restarted victim repairs even that)
    v_replay = audit_replay(party_only(trails[victim]))
    v_ledger = obs_fleet.ledger_parties(stats2[victim])
    per_req = request_charges(EstimateRequest(
        args.family, [0.0, 1.0], [0.0, 1.0], args.eps1, args.eps2,
        party_x="fleet-x", party_y="fleet-y"))
    v_gap = {p: v_ledger.get(p, 0.0) - v_replay.get(p, 0.0)
             for p in set(v_ledger) | set(v_replay)}
    victim_ok = all(g == 0.0 or g == per_req.get(p)
                    for p, g in v_gap.items())

    # ---- gate: fleet-wide user-level zero-ε (the leased shards) ---
    merged = sorted((ev for evs in trails.values() for ev in evs),
                    key=lambda ev: ev["ts"])
    user_replay = fold_levels(audit_replay(merged))["user"]
    balances = read_user_balances(os.path.join(fleet.subdir, "budget"))
    disk = {u: rec["l"] for u, rec in balances.items()}
    user_eps = sum(per_req.values())
    expected = {u: k * user_eps for u, k in sent.items()}
    user_exact = user_replay == disk == expected

    cpu = os.cpu_count() or 1
    ratio = (qps_n / qps1) if qps1 and qps_n else None
    # assert ~linear scaling only where the cores exist to deliver it
    linear_ok = (ratio is not None and ratio >= 0.5 * n_rep) \
        if cpu >= 4 * n_rep else None

    ok = {
        "fleet_up": not errors,
        "prekill_counts_exact": counts_exact,
        "kill_all_succeeded": kill_ok,
        "victim_shards_releases": released_ok,
        "party_conservation_survivors": cons["ok"],
        "victim_audit_within_one_charge": victim_ok,
        "user_conservation_exact": user_exact,
    }
    if linear_ok is not None:
        ok["qps_linearish"] = linear_ok
    out = {
        "metric": "serve_fleet_scale",
        "replicas": n_rep,
        "shards": shards,
        "users": len(users),
        "lease_ttl_s": args.lease_ttl_s,
        "requests_per_phase": per_phase,
        "client_threads": threads_n,
        "qps": {"one": round(qps1, 2) if qps1 else None,
                "n": round(qps_n, 2) if qps_n else None,
                "ratio": round(ratio, 3) if ratio else None,
                "linear_ok": linear_ok, "cpu_count": cpu},
        "prekill": {"done": b["done"],
                    "admitted_delta": admitted_delta,
                    "client": b["client"]},
        "kill": {"victim": victim,
                 "restarts": dict(fleet.sup.restarts),
                 "done": c["done"], "wall_s": round(c["wall_s"], 3),
                 "client": c["client"],
                 "victim_shards_before": victim_shards,
                 "owners_after": owners_after},
        "party_conservation": cons,
        "victim_audit_gap": v_gap,
        "user_conservation": {
            "exact": user_exact,
            "per_request_user_eps": user_eps,
            "replay_total": sum(user_replay.values()),
            "disk_total": sum(disk.values()),
            "expected_total": sum(expected.values()),
        },
        "lease_snapshots": lease_snaps,
        "ok": ok,
        "errors": (errors + warm["errors"] + b["errors"]
                   + c["errors"])[:8],
    }
    blob = json.dumps(out, indent=2)
    print(blob)
    if args.out_json:
        with open(args.out_json, "w") as f:
            f.write(blob)
    return 0 if all(v for v in ok.values() if v is not None) else 1


def run_overload(args) -> int:
    """The ISSUE 8 scenario: a deliberately small server under chaos
    faults at ~4x capacity, driven through RetryingClient. Every gate
    is exact — eventual success is 100%, shed/expired requests consume
    zero ε (binary-exact ledger balance + jax-free audit replay),
    breaker recovery is bit-identical, the duplicate storm charges
    once."""
    import jax
    import numpy as np

    from dpcorr import chaos
    from dpcorr.models.estimators.registry import serving_entry
    from dpcorr.obs.audit import AuditTrail, replay
    from dpcorr.serve import (
        CircuitOpenError,
        DeadlineExpiredError,
        DpcorrServer,
        EstimateRequest,
        InProcessClient,
        RetryingClient,
        RetryPolicy,
        ServerOverloadedError,
        pinned_request_key,
        request_charges,
    )
    from dpcorr.utils import rng

    n_req = args.requests
    n_obs = 128
    trail = AuditTrail()
    # Small on purpose: a 16-deep queue against 32 client threads is
    # guaranteed overflow, and threshold-3 breaker trips fast.
    srv = DpcorrServer(budget=1e9, max_batch=8, max_delay_s=0.002,
                       max_queue=16, batch_mode=args.batch_mode,
                       audit=trail, breaker_threshold=3,
                       breaker_reset_s=0.75, brownout_exit_s=0.5,
                       # compile-ahead (ISSUE 4): the SLO gate measures
                       # overload behaviour, not first-flush compiles
                       warmup=f"{args.family}:{n_obs}:{args.eps1}:"
                              f"{args.eps2}:auto")
    recorder = None
    if args.recorder:
        from dpcorr.obs.recorder import FlightRecorder

        recorder = FlightRecorder(args.recorder)
        srv.attach_recorder(recorder)
    srv.wait_ready(timeout=900)
    rc = RetryingClient(
        InProcessClient(srv),
        RetryPolicy(max_attempts=16, base_delay_s=0.02,
                    max_delay_s=0.5, deadline_s=120.0))

    # ---------------- phase A: overload storm under a slow kernel ------
    chaos.clear_faults()
    chaos.install_fault(chaos.fault_from_spec(
        "point=serve.kernel_slow,mode=sleep,delay_ms=25"))
    rs = np.random.RandomState(7)
    reqs = [EstimateRequest(
        args.family, rs.randn(n_obs).astype(np.float32),
        rs.randn(n_obs).astype(np.float32), args.eps1, args.eps2,
        party_x="ld-x", party_y="ld-y", seed=i,
        priority=i % 3 - 1,  # mixed -1 / 0 / 1
        deadline_s=0.5 if i % 4 == 0 else None)
        for i in range(n_req)]
    per_req = request_charges(reqs[0])

    responses: dict[int, object] = {}
    failures: list[str] = []
    lock = threading.Lock()
    per_client = -(-n_req // args.clients)

    def client(c: int) -> None:
        for i in range(c * per_client,
                       min((c + 1) * per_client, n_req)):
            try:
                r = rc.estimate(reqs[i], timeout=60)
                with lock:
                    responses[i] = r
            except Exception as e:
                with lock:
                    failures.append(f"{i}: {type(e).__name__}: {e}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    # deterministic prioritized-shed probe: slow each flush to 120ms so
    # the single flush thread drains at most one max_batch round while
    # we saturate the queue with high-priority work, then offer
    # lower-priority requests. A lower-priority arrival at a full queue
    # outranks nothing, so admission MUST refuse it with a Retry-After
    # hint and refund its charge — by pigeonhole within max_queue + 1
    # attempts, since every admitted probe deepens the queue and the
    # drain is two orders of magnitude slower than the attempt loop.
    chaos.clear_faults()
    chaos.install_fault(chaos.fault_from_spec(
        "point=serve.kernel_slow,mode=sleep,delay_ms=120"))
    fill_futs = []
    for j in range(srv.coalescer.max_queue + 8):
        try:
            fill_futs.append(srv.submit(EstimateRequest(
                args.family, reqs[0].x, reqs[0].y, args.eps1,
                args.eps2, party_x="rf-x", party_y="rf-y",
                seed=20_000 + j, priority=1)))
        except ServerOverloadedError:
            pass  # equal-rank spill among the fillers themselves
    probe_refused = False
    probe_retry_after = None
    for k in range(srv.coalescer.max_queue + 8):
        try:
            fill_futs.append(srv.submit(EstimateRequest(
                args.family, reqs[0].x, reqs[0].y, args.eps1,
                args.eps2, party_x="rf-x", party_y="rf-y",
                seed=30_000 + k, priority=0)))
        except ServerOverloadedError as e:
            probe_refused = True
            probe_retry_after = e.retry_after_s
            break
    fill_ok = 0
    for f in fill_futs:
        try:
            f.result(timeout=60)
            fill_ok += 1
        except ServerOverloadedError:
            pass
    # every refused/spilled filler and the probe were refunded exactly:
    # the rf parties paid for completed work and nothing else
    rf_exact = (srv.ledger.spent("rf-x") == fill_ok * per_req["ld-x"]
                and srv.ledger.spent("rf-y") == fill_ok * per_req["ld-y"])

    # a guaranteed expiry: queued with an already-hopeless deadline,
    # dropped before launch, charge refunded (net-zero on the ledger)
    try:
        srv.submit(EstimateRequest(
            args.family, reqs[0].x, reqs[0].y, args.eps1, args.eps2,
            party_x="ld-x", party_y="ld-y", seed=n_req + 1,
            deadline_s=1e-6)).result(timeout=30)
        expiry_probe_expired = False
    except DeadlineExpiredError:
        expiry_probe_expired = True
    chaos.clear_faults()

    snap_a = srv.stats_snapshot()
    rc_stats = rc.stats()
    shed_total = sum(snap_a["shed"].values())
    refused_total = sum(snap_a["refused"].values())
    p99 = snap_a.get("latency_s", {}).get("p99")
    # binary-exact ε accounting: every success charged exactly once,
    # every shed/expired/abandoned attempt refunded exactly
    ledger_exact = (
        srv.ledger.spent("ld-x") == len(responses) * per_req["ld-x"]
        and srv.ledger.spent("ld-y") == len(responses) * per_req["ld-y"])

    # ---------------- phase B: breaker trip → recover, bit-identical ---
    rsb = np.random.RandomState(11)
    chaos.install_fault(chaos.fault_from_spec(
        "point=serve.kernel,mode=fail,times=6"))
    # each whole-request failure traverses the fault twice (batched
    # attempt + unbatched fallback): times=6 → exactly 3 failures,
    # tripping the threshold-3 breaker, then the plan is spent
    executed_failures = 0
    for j in range(3):
        try:
            srv.estimate(EstimateRequest(
                args.family, rsb.randn(n_obs).astype(np.float32),
                rsb.randn(n_obs).astype(np.float32),
                args.eps1, args.eps2, party_x="bk-x", party_y="bk-y",
                seed=5000 + j), timeout=60)
        except chaos.SimulatedFault:
            executed_failures += 1
    tripped = srv.readiness()
    spent_bk = srv.ledger.spent("bk-x")
    breaker_refused = False
    probe_req = EstimateRequest(
        args.family, reqs[0].x, reqs[0].y, args.eps1, args.eps2,
        party_x="bk-x", party_y="bk-y", seed=777)
    try:
        srv.estimate(probe_req, timeout=60)
    except CircuitOpenError:
        breaker_refused = True
    refusal_charge_free = srv.ledger.spent("bk-x") == spent_bk
    time.sleep(0.9)  # cooldown: the next admission is the probe
    recovered_resp = srv.estimate(probe_req, timeout=60)
    recovered = srv.readiness()
    single = jax.jit(serving_entry(args.family, args.eps1, args.eps2,
                                   alpha=0.05, normalise=True))
    ref = single(pinned_request_key(rng.master_key(srv.seed),
                                    probe_req, 777),
                 probe_req.x, probe_req.y)
    check_ci = args.batch_mode == "exact"
    recovery_bit_identical = (
        recovered_resp.rho_hat == float(ref[0])
        and (not check_ci or (recovered_resp.ci_low == float(ref[1])
                              and recovered_resp.ci_high == float(ref[2]))))

    # ---------------- phase C: duplicate storm, charge-once ------------
    storm_req = EstimateRequest(
        args.family, reqs[1].x, reqs[1].y, args.eps1, args.eps2,
        party_x="dup-x", party_y="dup-y", seed=31337)
    hits_before = (srv.stats.idempotent_hits_completed
                   + srv.stats.idempotent_hits_inflight)
    storm_out: list[object] = []
    barrier = threading.Barrier(16)

    def dup_client() -> None:
        barrier.wait()
        try:
            r = rc.estimate(storm_req, timeout=60)
            with lock:
                storm_out.append(r)
        except Exception as e:
            with lock:
                failures.append(f"storm: {type(e).__name__}: {e}")

    storm_threads = [threading.Thread(target=dup_client)
                     for _ in range(16)]
    for t in storm_threads:
        t.start()
    for t in storm_threads:
        t.join()
    idem_hits = (srv.stats.idempotent_hits_completed
                 + srv.stats.idempotent_hits_inflight - hits_before)
    storm_single_charge = (
        srv.ledger.spent("dup-x") == request_charges(storm_req)["dup-x"])
    storm_identical = (len(storm_out) == 16 and len(
        {(r.rho_hat, r.ci_low, r.ci_high, r.seed)
         for r in storm_out}) == 1)

    srv.close()
    # the ε story end to end, reproducible WITHOUT jax or the server:
    # folding the audit trail reproduces the ledger's final balances
    replayed = replay(trail.events())
    parties = srv.ledger.snapshot()["parties"]
    audit_matches = (set(replayed) == set(parties) and all(
        replayed[p] == parties[p]["spent"] for p in replayed))

    # ---------------- ISSUE 9: flight-recorder end-to-end ---------------
    # the phase-B breaker trip must have auto-dumped; from the artifact
    # alone (jax-free: obs.recorder) the faulting request's span chain,
    # CostRecord and ε trail must reconstruct, and the trail must agree
    # with the ledger (an executed-then-failed request keeps its charge)
    recorder_doc = None
    if recorder is not None:
        from dpcorr.obs.recorder import read_dump, reconstruct
        fault_trace = None
        chain: list[str] = []
        cost_rec = eps_net = None
        parse_ok = False
        try:
            dump = read_dump(args.recorder)
            parse_ok = True
            fault_spans = [
                sp for sp in dump["spans"]
                if sp.get("attrs", {}).get("error") == "SimulatedFault"
                and sp.get("name") == "serve.request"]
            if fault_spans:
                fault_trace = fault_spans[-1]["trace_id"]
                story = reconstruct(dump, fault_trace)
                chain = [s["name"] for s in story["spans"]]
                cost_rec = story["cost"]
                eps_net = story["eps_net"]
        except Exception as e:  # a broken artifact fails the gate below
            failures.append(f"recorder: {type(e).__name__}: {e}")
        eps_consistent = (
            eps_net is not None
            and eps_net.get("bk-x") == per_req["ld-x"]
            and eps_net.get("bk-y") == per_req["ld-y"])
        recorder_doc = {
            "path": args.recorder,
            "dumps": recorder.dumps,
            "reasons": recorder.reasons,
            "parse_ok": parse_ok,
            "fault_trace_id": fault_trace,
            "span_chain": chain,
            "cost_record": cost_rec,
            "eps_net": eps_net,
            "eps_consistent": eps_consistent,
        }

    ok = {
        "eventual_success": len(responses) == n_req and not failures,
        "overload_exercised": shed_total > 0
                              and rc_stats.get("retryable", 0) > 0,
        "priority_shed": probe_refused and rf_exact
                         and (probe_retry_after or 0) > 0,
        "expiry_refunded": expiry_probe_expired
                           and snap_a["shed"]["expired"] >= 1,
        "latency_slo": p99 is not None and p99 <= args.slo_ms / 1e3,
        "ledger_exact": ledger_exact,
        "audit_replay": audit_matches,
        "breaker_tripped": executed_failures == 3
                           and tripped["ready"] is False
                           and tripped["breakers_open"] is True
                           and breaker_refused and refusal_charge_free,
        "breaker_recovered": recovered["ready"] is True
                             and recovery_bit_identical,
        "idempotent_storm": storm_identical and idem_hits == 15
                            and storm_single_charge,
    }
    if recorder_doc is not None:
        ok["flight_recorder"] = (
            recorder_doc["parse_ok"]
            and "breaker_open" in recorder_doc["reasons"]
            and recorder_doc["fault_trace_id"] is not None
            and "serve.request" in recorder_doc["span_chain"]
            and recorder_doc["cost_record"] is not None
            and recorder_doc["eps_consistent"])
    out = {
        "metric": "serve_overload",
        "requests": n_req,
        "clients": args.clients,
        "n": n_obs,
        "family": args.family,
        "wall_s": round(wall, 3),
        "eventual_success_rate": round(len(responses) / n_req, 4),
        "client_stats": rc_stats,
        "shed": snap_a["shed"],
        "refused": snap_a["refused"],
        "abandoned": snap_a["abandoned"],
        "p99_s": p99,
        "slo_s": args.slo_ms / 1e3,
        "breaker": {"tripped_readiness": tripped,
                    "recovered_readiness": recovered,
                    "executed_failures": executed_failures,
                    "transitions": srv.stats_snapshot().get("breaker")},
        "duplicate_storm": {"fanout": 16, "idempotent_hits": idem_hits,
                            "single_charge": storm_single_charge},
        "priority_probe": {"refused": probe_refused,
                           "retry_after_s": probe_retry_after,
                           "fill_completed": fill_ok,
                           "refund_exact": rf_exact},
        "flight_recorder": recorder_doc,
        "ok": ok,
        "errors": failures[:5],
        "stats": srv.stats_snapshot(),
    }
    blob = json.dumps(out, indent=2)
    print(blob)
    if args.out_json:
        with open(args.out_json, "w") as f:
            f.write(blob)
    return 0 if all(ok.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
