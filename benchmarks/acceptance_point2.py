"""Second acceptance point for the real-data subG variant (VERDICT r3 #5).

The r03 campaign pinned det-vs-MC INT coverage agreement at ONE config
point — (n=4000, ε=(1,1)) — and spent 93% of the 1e-3 budget doing it
(diff 9.28e-4 at B=2²⁰, `acceptance_r03_subg_real.json`). One point can't
say whether that margin is MC noise or a real det-mode bias of the
real-data construction (real-data-sims.R:115-252). This script runs the
same B=2²⁰ det/mc twin — identical replicate keys, so NI coverage must
agree exactly and the INT diff isolates the mixquant construction — at a
caller-chosen (n, ε), defaulting to the HRS-like shape (wave-2 complete
cases n=19,433, ε_corr=2.0; dpcorr/hrs.py).

Reuses the campaign machinery (`dpcorr.acceptance`): one AccPoint with
``both_mixquant=True`` in the real-data flavor, which makes the MC twin
draw at the real-data script's nsim=2000 (real-data-sims.R:161-164).

Run: python benchmarks/acceptance_point2.py [--n 19433] [--eps 2.0]
         [--log2b 20] [--platform cpu] [--out ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=19_433,
                    help="sample size (default: HRS wave-2 complete cases)")
    ap.add_argument("--eps", type=float, default=2.0,
                    help="ε1 (= ε2 unless --eps2; default: the HRS "
                         "pipeline's ε_corr)")
    ap.add_argument("--eps2", type=float, default=None,
                    help="ε2 when the pair is asymmetric (e.g. the grid "
                         "scripts' (1.5, 0.5) pair)")
    ap.add_argument("--rho", type=float, default=0.5)
    ap.add_argument("--variant", choices=["real", "grid"], default="real",
                    help="subG estimator flavor: 'real' (the HRS/"
                         "real-data construction, nsim=2000 mc) or "
                         "'grid' (ver-cor-subG.R's, nsim=1000 mc — for "
                         "extra points in the det/mc nsim-scaling "
                         "attribution, tests/test_acceptance.py)")
    ap.add_argument("--coverage-tol", dest="coverage_tol", type=float,
                    default=0.0,
                    help="widened |coverage-nominal| tolerance for "
                         "constructions with intrinsic finite-n "
                         "under-coverage (requires --tol-reason)")
    ap.add_argument("--tol-reason", dest="tol_reason", default="")
    ap.add_argument("--log2b", type=int, default=20,
                    help="log2 of replications per mode (20 ⇒ MC SE ≈ "
                         "2.1e-4 on a 0.95 coverage)")
    ap.add_argument("--block", type=int, default=32_768)
    ap.add_argument("--chunk", type=int, default=1024,
                    help="vmap chunk (smaller than the campaign's 4096: "
                         "n here is ~5× the campaign's largest)")
    ap.add_argument("--platform", type=str, default=None,
                    help="force a JAX platform (the site hook ignores "
                         "JAX_PLATFORMS env; this applies config.update "
                         "before backend init)")
    ap.add_argument("--out", type=str, default=None,
                    help="output table path. Default: a variant-named "
                         "file in the /tmp quarantine (TPU_R05_IN) — "
                         "NEVER a checked-in benchmarks/results/ name, "
                         "so a forgotten --out can't clobber banked "
                         "evidence; promotion goes through harvest "
                         "validity gates or an explicit reviewed copy")
    args = ap.parse_args()

    # pure usage errors fail before the expensive jax import
    if args.coverage_tol and not args.tol_reason:
        ap.error("--coverage-tol requires --tol-reason (the acceptance "
                 "table test insists on a recorded reason)")
    if args.tol_reason and not args.coverage_tol:
        ap.error("--tol-reason without --coverage-tol would be silently "
                 "dropped from the artifact (run_campaign records the "
                 "reason only for a nonzero tolerance)")
    if args.out is None:
        args.out = os.path.join(
            os.environ.get("TPU_R05_IN", "/tmp/tpu_r05"),
            f"acceptance_point_{args.variant}.json")
        os.makedirs(os.path.dirname(args.out), exist_ok=True)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from dpcorr.acceptance import AccPoint, run_campaign
    eps2 = args.eps if args.eps2 is None else args.eps2
    if args.variant == "real":
        name = "subg_real_p2"
        regime = ("real-data (v2) estimator pair at the HRS-like shape — "
                  "second det/mc calibration point (VERDICT r3 #5); same "
                  "construction as subg_real (real-data-sims.R:115-252)")
    else:
        name = "subg_grid_extra"
        regime = ("grid (v1) subG estimator pair — extra det/mc point for "
                  "the nsim=1000 flavor of the nsim-scaling attribution "
                  "(ver-cor-subG.R:25-108; mc draws nsim=1000)")
    pt = AccPoint(
        name, regime,
        {"n": args.n, "rho": args.rho, "eps1": args.eps, "eps2": eps2,
         "dgp": "bounded_factor", "use_subg": True,
         "subg_variant": args.variant},
        both_mixquant=True,
        coverage_tol=args.coverage_tol,
        tol_reason=args.tol_reason,
    )
    table = run_campaign(b=1 << args.log2b, block=args.block,
                         points=(pt,), chunk_size=args.chunk,
                         out=args.out)
    if (1 << args.log2b) < 1_000_000:
        # tests/test_acceptance.py requires every checked-in table with
        # b_per_run < 1e6 to DECLARE itself a reduced-B artifact; the
        # producer writes the note so provenance is machine-generated,
        # never a hand edit (ADVICE r04)
        import datetime

        table["reduced_b_note"] = (
            f"reduced-B run (b_per_run={1 << args.log2b} < 1e6), "
            f"generated {datetime.date.today().isoformat()} by "
            "acceptance_point2.py --log2b "
            f"{args.log2b}; typically a CPU insurance twin run while the "
            "TPU tunnel endpoint was dead (STATUS_r04.md) — the B=2^20 "
            "on-chip twin supersedes this table when it lands")
        from dpcorr.acceptance import dumps

        with open(args.out, "w") as fh:
            fh.write(dumps(table))
    row = table["points"][0]
    print(json.dumps({
        "point": row["point"],
        "n": args.n, "eps": args.eps, "b": row["det"]["b"],
        "det_INT": row["det"]["INT"]["coverage"],
        "mc_INT": row["mc"]["INT"]["coverage"],
        "det_mc_diff_INT": row["int_det_mc_diff"],
        "det_mc_diff_NI": row["ni_det_mc_diff"],
        "within_1e3": bool(row["int_det_mc_diff"] <= 1e-3),
    }))


if __name__ == "__main__":
    main()
