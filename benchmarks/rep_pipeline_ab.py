"""Interleaved A/B: legacy block loop vs the donated rep-block pipeline.

The r08 tentpole replaced the bench's hot path (``bench.make_xla_block``
measured by ``bench.measure_steady_state``) with the donated,
pre-sharded, chained-key executor (``dpcorr.sim.RepBlockPipeline``
measured by ``bench.measure_pipeline``). This script is the committed
evidence that the swap is (a) a speedup and (b) not a semantic change:

- **interleaved** rounds — A, B, A, B, … on the same process and box,
  so slow drift (thermal, competing load on the 1-core box) hits both
  arms equally instead of biasing whichever ran second;
- **bit-identity** — before timing anything, one block of per-rep
  (se², cover, ci_len) triples is computed by both arms from the same
  key addresses and compared with ``np.testing.assert_array_equal``
  (exact, not approximate). A pipeline that drifted by one ulp fails
  here and writes no artifact.

Both arms run the same threefry+erf⁻¹ rep at the same (chunk × block)
geometry — this isolates the pipeline machinery (donation, explicit
shardings, on-device keygen, single fetch). The Box–Muller sampler win
is a separate, statistically-gated path (``xla_bm``) and is deliberately
NOT part of this comparison.

Usage::

    python -m benchmarks.rep_pipeline_ab [--rounds 5] [--budget 6]
        [--block 4096] [--chunk 4]
        [--out benchmarks/results/r08_rep_pipeline_ab_cpu.json]

The profiled-vs-unprofiled A/B (ISSUE 15) rides the same interleaving
discipline: alternating rounds on two pipelines at the same geometry,
one with an armed ``obs.prof.BlockProfiler`` and one without, gating
the profiler's p50 throughput cost at ≤3% — and proving with the
transfer counters that the unprofiled arm still performs exactly one
host fetch per run (profiler syncs are accounted separately in
``dpcorr_prof_syncs_total``, never as fetches). ``--profiler-only``
runs just this gate (the CI ``prof-smoke`` job's fast path).
"""

from __future__ import annotations

import argparse
import json
import statistics
import time
from pathlib import Path


def profiler_ab(args, key, counters) -> dict:
    """Interleaved profiled-vs-unprofiled rounds on RepBlockPipeline."""
    import bench
    from dpcorr.obs import prof as prof_mod

    prof = prof_mod.BlockProfiler(max_syncs=8)
    pipe_off = bench.make_pipeline(args.chunk, args.block, key=key,
                                   counters=counters)
    pipe_on = bench.make_pipeline(args.chunk, args.block, key=key,
                                  counters=counters, profiler=prof)

    # -- zero-extra-sync proof: each arm's run() bumps fetches by
    # exactly 1 (the reduction boundary). The profiled arm's extra
    # cadence syncs land in dpcorr_prof_syncs_total, NOT in fetches.
    s0 = counters.snapshot()
    pipe_off.run(4, start_block=0)
    s1 = counters.snapshot()
    syncs_before = int(prof.syncs_total.value())
    pipe_on.run(4, start_block=0)
    s2 = counters.snapshot()
    off_fetches = s1["fetches"] - s0["fetches"]
    on_fetches = s2["fetches"] - s1["fetches"]
    prof_syncs = int(prof.syncs_total.value()) - syncs_before
    assert off_fetches == 1, \
        f"unprofiled run performed {off_fetches} fetches, expected 1"
    assert on_fetches == 1, \
        f"profiled run performed {on_fetches} fetches, expected 1 " \
        f"(profiler syncs must not count as fetches)"
    assert prof_syncs >= 1, "armed profiler recorded no cadence syncs"

    rps_off, rps_on = [], []
    for r in range(args.rounds):
        a, _ = bench.measure_pipeline(pipe_off, args.budget)
        rps_off.append(a)
        b, _ = bench.measure_pipeline(pipe_on, args.budget)
        rps_on.append(b)
        print(f"prof round {r}: off {a:.1f} vs on {b:.1f} "
              f"({(1 - b / a) * 100:+.2f}% overhead)", flush=True)

    p50_off = statistics.median(rps_off)
    p50_on = statistics.median(rps_on)
    overhead_pct = (1.0 - p50_on / p50_off) * 100.0
    return {
        "rounds": args.rounds,
        "off_reps_per_sec": [round(v, 1) for v in rps_off],
        "on_reps_per_sec": [round(v, 1) for v in rps_on],
        "p50_off": round(p50_off, 1),
        "p50_on": round(p50_on, 1),
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": prof_mod.OVERHEAD_BUDGET_PCT,
        "ok": overhead_pct <= prof_mod.OVERHEAD_BUDGET_PCT,
        "profiler_syncs": prof_syncs,
        "unprofiled_fetches_per_run": off_fetches,
        "profiled_fetches_per_run": on_fetches,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--budget", type=float, default=6.0,
                    help="per-arm, per-round measurement budget (seconds)")
    ap.add_argument("--block", type=int, default=4096)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--out", type=str,
                    default="benchmarks/results/r08_rep_pipeline_ab_cpu.json")
    ap.add_argument("--platform", type=str, default=None)
    ap.add_argument("--profiler-only", action="store_true",
                    help="run only the profiled-vs-unprofiled gate and "
                         "write a profiler_ab-only artifact (CI prof-smoke)")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    import bench
    from dpcorr.obs import transfer as transfer_mod
    from dpcorr.utils import rng

    counters = transfer_mod.default_counters()
    key = rng.master_key()

    if args.profiler_only:
        prof_section = profiler_ab(args, key, counters)
        out = {
            "metric": "rep_pipeline_profiler_ab_ni_sign_n10k",
            "device": str(jax.devices()[0]),
            "platform": jax.devices()[0].platform,
            "block_reps": args.block,
            "chunk_size": args.chunk,
            "profiler_ab": prof_section,
            "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()),
        }
        Path(args.out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.out).write_text(json.dumps(out, indent=1))
        print(json.dumps({"profiler_overhead_pct":
                          prof_section["overhead_pct"],
                          "ok": prof_section["ok"], "out": args.out}))
        return

    legacy_block = bench.make_xla_block(args.chunk)
    pipe = bench.make_pipeline(args.chunk, args.block, key=key,
                               counters=counters)

    # ---- bit-identity first: same key addresses, exact equality -------
    rep_fn = bench.make_rep_fn()
    from dpcorr.sim import chunked_vmap

    block_idx = 0
    keys = rng.rep_keys(rng.design_key(key, block_idx), args.block)
    plain = jax.jit(lambda k: chunked_vmap(rep_fn, k, args.chunk))(keys)
    piped = pipe.block_detail(block_idx)
    for name, a, b in zip(("se2", "cover", "ci_len"), plain, piped,
                          strict=True):
        np.testing.assert_array_equal(np.asarray(a),  # dpcorr-lint: ignore[sync-in-loop]
                                      np.asarray(b),  # dpcorr-lint: ignore[sync-in-loop]
                                      err_msg=f"pipeline diverged on {name}")
    # and the legacy arm's own reduction agrees with the per-rep means
    legacy_means = tuple(float(x) for x in legacy_block(
        rng.design_key(key, block_idx), args.block))
    np.testing.assert_allclose(
        legacy_means,
        [float(np.mean(np.asarray(a)))  # dpcorr-lint: ignore[sync-in-loop]
         for a in plain],
        rtol=1e-6, err_msg="legacy block disagrees with its own rep table")

    # ---- interleaved steady-state rounds ------------------------------
    legacy_rps, pipeline_rps = [], []
    for r in range(args.rounds):
        rps_a, _, _ = bench.measure_steady_state(
            legacy_block, lambda i: rng.design_key(key, i),
            args.block, args.budget)
        legacy_rps.append(rps_a)
        rps_b, _ = bench.measure_pipeline(pipe, args.budget)
        pipeline_rps.append(rps_b)
        print(f"round {r}: legacy {rps_a:.1f} vs pipeline {rps_b:.1f} "
              f"({rps_b / rps_a:.3f}x)", flush=True)

    med_a = statistics.median(legacy_rps)
    med_b = statistics.median(pipeline_rps)
    out = {
        "metric": "rep_pipeline_ab_ni_sign_n10k",
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "block_reps": args.block,
        "chunk_size": args.chunk,
        "rounds": args.rounds,
        "budget_s_per_arm_per_round": args.budget,
        "bit_identical": True,  # assert_array_equal above, or no artifact
        "legacy_reps_per_sec": [round(v, 1) for v in legacy_rps],
        "pipeline_reps_per_sec": [round(v, 1) for v in pipeline_rps],
        "legacy_median": round(med_a, 1),
        "pipeline_median": round(med_b, 1),
        "speedup": round(med_b / med_a, 3),
        "donation_engaged": pipe.donation_engaged,
        "aot": pipe.aot_ok,
        "transfer": counters.snapshot(),
        "profiler_ab": profiler_ab(args, key, counters),
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    Path(args.out).write_text(json.dumps(out, indent=1))
    print(json.dumps({"legacy_median": out["legacy_median"],
                      "pipeline_median": out["pipeline_median"],
                      "speedup": out["speedup"], "out": args.out}))


if __name__ == "__main__":
    main()
