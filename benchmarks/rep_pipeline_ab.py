"""Interleaved A/B: legacy block loop vs the donated rep-block pipeline.

The r08 tentpole replaced the bench's hot path (``bench.make_xla_block``
measured by ``bench.measure_steady_state``) with the donated,
pre-sharded, chained-key executor (``dpcorr.sim.RepBlockPipeline``
measured by ``bench.measure_pipeline``). This script is the committed
evidence that the swap is (a) a speedup and (b) not a semantic change:

- **interleaved** rounds — A, B, A, B, … on the same process and box,
  so slow drift (thermal, competing load on the 1-core box) hits both
  arms equally instead of biasing whichever ran second;
- **bit-identity** — before timing anything, one block of per-rep
  (se², cover, ci_len) triples is computed by both arms from the same
  key addresses and compared with ``np.testing.assert_array_equal``
  (exact, not approximate). A pipeline that drifted by one ulp fails
  here and writes no artifact.

Both arms run the same threefry+erf⁻¹ rep at the same (chunk × block)
geometry — this isolates the pipeline machinery (donation, explicit
shardings, on-device keygen, single fetch). The Box–Muller sampler win
is a separate, statistically-gated path (``xla_bm``) and is deliberately
NOT part of this comparison.

Usage::

    python -m benchmarks.rep_pipeline_ab [--rounds 5] [--budget 6]
        [--block 4096] [--chunk 4]
        [--out benchmarks/results/r08_rep_pipeline_ab_cpu.json]
"""

from __future__ import annotations

import argparse
import json
import statistics
import time
from pathlib import Path


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--budget", type=float, default=6.0,
                    help="per-arm, per-round measurement budget (seconds)")
    ap.add_argument("--block", type=int, default=4096)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--out", type=str,
                    default="benchmarks/results/r08_rep_pipeline_ab_cpu.json")
    ap.add_argument("--platform", type=str, default=None)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    import bench
    from dpcorr.obs import transfer as transfer_mod
    from dpcorr.utils import rng

    counters = transfer_mod.default_counters()
    key = rng.master_key()
    legacy_block = bench.make_xla_block(args.chunk)
    pipe = bench.make_pipeline(args.chunk, args.block, key=key,
                               counters=counters)

    # ---- bit-identity first: same key addresses, exact equality -------
    rep_fn = bench.make_rep_fn()
    from dpcorr.sim import chunked_vmap

    block_idx = 0
    keys = rng.rep_keys(rng.design_key(key, block_idx), args.block)
    plain = jax.jit(lambda k: chunked_vmap(rep_fn, k, args.chunk))(keys)
    piped = pipe.block_detail(block_idx)
    for name, a, b in zip(("se2", "cover", "ci_len"), plain, piped,
                          strict=True):
        np.testing.assert_array_equal(np.asarray(a),  # dpcorr-lint: ignore[sync-in-loop]
                                      np.asarray(b),  # dpcorr-lint: ignore[sync-in-loop]
                                      err_msg=f"pipeline diverged on {name}")
    # and the legacy arm's own reduction agrees with the per-rep means
    legacy_means = tuple(float(x) for x in legacy_block(
        rng.design_key(key, block_idx), args.block))
    np.testing.assert_allclose(
        legacy_means,
        [float(np.mean(np.asarray(a)))  # dpcorr-lint: ignore[sync-in-loop]
         for a in plain],
        rtol=1e-6, err_msg="legacy block disagrees with its own rep table")

    # ---- interleaved steady-state rounds ------------------------------
    legacy_rps, pipeline_rps = [], []
    for r in range(args.rounds):
        rps_a, _, _ = bench.measure_steady_state(
            legacy_block, lambda i: rng.design_key(key, i),
            args.block, args.budget)
        legacy_rps.append(rps_a)
        rps_b, _ = bench.measure_pipeline(pipe, args.budget)
        pipeline_rps.append(rps_b)
        print(f"round {r}: legacy {rps_a:.1f} vs pipeline {rps_b:.1f} "
              f"({rps_b / rps_a:.3f}x)", flush=True)

    med_a = statistics.median(legacy_rps)
    med_b = statistics.median(pipeline_rps)
    out = {
        "metric": "rep_pipeline_ab_ni_sign_n10k",
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "block_reps": args.block,
        "chunk_size": args.chunk,
        "rounds": args.rounds,
        "budget_s_per_arm_per_round": args.budget,
        "bit_identical": True,  # assert_array_equal above, or no artifact
        "legacy_reps_per_sec": [round(v, 1) for v in legacy_rps],
        "pipeline_reps_per_sec": [round(v, 1) for v in pipeline_rps],
        "legacy_median": round(med_a, 1),
        "pipeline_median": round(med_b, 1),
        "speedup": round(med_b / med_a, 3),
        "donation_engaged": pipe.donation_engaged,
        "aot": pipe.aot_ok,
        "transfer": counters.snapshot(),
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    Path(args.out).write_text(json.dumps(out, indent=1))
    print(json.dumps({"legacy_median": out["legacy_median"],
                      "pipeline_median": out["pipeline_median"],
                      "speedup": out["speedup"], "out": args.out}))


if __name__ == "__main__":
    main()
