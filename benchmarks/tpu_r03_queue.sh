#!/bin/bash
# Round-3 TPU validation queue (supersedes tpu_revalidate.sh's r02 queue).
#
# VERDICT r2 ordering contract: bank the headline FIRST, quarantine
# anything that has ever wedged the tunnel (limit probes, new Mosaic
# features) to AFTER it. Steps, in order:
#
#   1. `python bench.py` at shipped defaults -> the 235x headline on the
#      current (post-refactor) kernels. THE round-3 deliverable.
#   2. Roofline + profiler trace of the same kernel (VERDICT r2 #4).
#   3. Pallas gauss A/B (boxmuller vs ndtri) -> decides the kernel default.
#   4. Fused CLI grid smoke (--b 8) -> end-to-end grid wiring on-chip.
#   5. BASELINE config 5 stress: streaming subG at n=10^6 on the chip
#      (VERDICT r2 #2) via benchmarks.run_all --configs 5.
#   6. Full 5-config suite incl. HRS bootstrap (VERDICT r2 #3) -- longest,
#      last, so a mid-run wedge costs the least.
#
# Results land in /tmp/tpu_r03/; summarized on stdout.

set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=/tmp/tpu_r03
mkdir -p "$OUT"
FAILED=0
TOTAL=0
# persistent compile cache, keyed by revision (honest timings: the first
# run of this revision still pays compile; later steps/retries skip it)
export DPCORR_COMPILE_CACHE="$OUT/xla_cache_$(git rev-parse --short HEAD)"

step() {  # step <name> <cmd...>: run, record status, keep going
  local name=$1; shift
  TOTAL=$((TOTAL + 1))
  if "$@"; then
    echo "-- $name: OK ($(date -u +%H:%M:%SZ))"
  else
    echo "-- $name: FAILED (rc=$?) ($(date -u +%H:%M:%SZ))"
    FAILED=$((FAILED + 1))
  fi
}

probe() {
  timeout 150 python -c \
    "import jax; assert jax.devices()[0].platform in ('tpu','axon'); import jax.numpy as jnp; print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))" \
    >/dev/null 2>&1
}

for i in $(seq 1 200); do
  if probe; then
    echo "tunnel healthy at attempt $i ($(date -u +%H:%M:%SZ))"

    echo "== 1. bench.py at shipped defaults (the headline) =="
    step bench_default bash -c \
      'timeout 1800 python bench.py 2>"'$OUT'/bench_default.err" \
       | tail -1 | tee "'$OUT'/bench_default.json" | grep -q "reps_per_sec"'

    echo "== 2. roofline + trace (same kernel) =="
    step roofline bash -c \
      'timeout 1200 python -m benchmarks.roofline --budget 15 \
       --trace benchmarks/results/trace_r03 \
       --out benchmarks/results/r03_roofline.json \
       2>"'$OUT'/roofline.err" | tail -1 | grep -q reps_per_sec'

    echo "== 3. pallas gauss A/B (worker-only, budget 20s each) =="
    step pallas_boxmuller bash -c \
      'timeout 900 python bench.py --worker tpu-pallas --budget 20 \
       2>"'$OUT'/pallas_bm.err" | tail -1 \
       | tee "'$OUT'/pallas_boxmuller.json" | grep -q "reps_per_sec"'
    step pallas_ndtri bash -c \
      'DPCORR_BENCH_PALLAS_GAUSS=ndtri \
       timeout 900 python bench.py --worker tpu-pallas --budget 20 \
       2>"'$OUT'/pallas_nd.err" | tail -1 \
       | tee "'$OUT'/pallas_ndtri.json" | grep -q "reps_per_sec"'

    echo "== 4. fused CLI grid smoke (--b 8) =="
    step grid_fused_smoke bash -c \
      'timeout 900 python -m dpcorr grid --backend bucketed --fused auto \
       --b 8 2>"'$OUT'/grid.err" | tail -2 \
       | tee "'$OUT'/grid_fused_smoke.txt" | grep -q "INT"'

    echo "== 5. BASELINE config 5 stress (streaming n=10^6) =="
    step config5 bash -c \
      'set -o pipefail; timeout 3000 python -m benchmarks.run_all --config 5 \
       2>"'$OUT'/config5.err" \
       | tee benchmarks/results/r03_tpu_config5.jsonl \
       | grep -q stress_n1e6'

    echo "== 6. full 5-config suite, BASELINE rep counts (longest, last) =="
    step suite bash -c \
      'set -o pipefail; timeout 7200 python -m benchmarks.run_all --full \
       2>"'$OUT'/suite.err" \
       | tee benchmarks/results/r03_tpu_suite.jsonl \
       | grep -q stress_n1e6'

    cat "$OUT"/*.json 2>/dev/null
    echo "r03 queue finished ($(date -u +%H:%M:%SZ)): $((TOTAL - FAILED))/$TOTAL steps OK"
    exit $FAILED
  fi
  sleep 110
done
echo "tunnel never recovered within the polling window"
exit 1
