"""MFU/roofline measurement of the headline kernel (VERDICT r2 #4).

Instruments the exact program ``bench.py`` measures (``bench.make_xla_block``)
with three independent lenses and writes one JSON artifact:

1. XLA ``cost_analysis`` of the compiled block → FLOPs / bytes per rep as
   the compiler counts them (post-fusion);
2. the analytic hand model (``dpcorr.utils.roofline.analytic_rep_model``)
   as a sanity bound;
3. a short steady-state throughput measurement through the donated
   rep-block pipeline (``bench.make_pipeline``/``measure_pipeline`` —
   the r08 hot path, transfer counters stamped into the artifact) →
   achieved FLOP/s and B/s as %-of-peak for the platform's chip
   (``ChipPeaks``).

Optionally captures a ``jax.profiler`` trace of a few blocks
(``--trace DIR``) — the checked-in trace PERFORMANCE.md cites.

Usage::

    python -m benchmarks.roofline [--block 65536] [--chunk 16384]
        [--budget 10] [--trace benchmarks/results/trace_r03]
        [--out benchmarks/results/r03_roofline.json]

Runs on any platform (peaks table degrades to an order-of-magnitude CPU
estimate off-TPU; the artifact records which chip model applied).

``--from-artifact PATH`` (ISSUE 15) replaces lenses 1 and 3 with the
*measured* numbers a bench artifact already carries — the winning
kernel's ``detail.cost_analysis`` FLOPs/bytes (stamped by the bench
worker from the compiled executable) and its measured reps/s — so the
roofline summary reflects the headline run's arithmetic intensity, not
hand-derived constants, and the command runs jax-free.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path


def from_artifact(path: str, out_path: str) -> dict:
    """Jax-free roofline summary over a bench artifact's measured
    cost_analysis + throughput. Raises ValueError when the artifact
    carries no cost stamp."""
    from dpcorr.utils.roofline import peaks_for, summarize

    with open(path, encoding="utf-8") as f:
        art = json.load(f)
    payload = art.get("parsed") if isinstance(art.get("parsed"), dict) \
        else art
    detail = payload.get("detail") or {}
    cost = detail.get("cost_analysis") or {}
    value = payload.get("value")
    if not cost or "flops_per_rep" not in cost:
        raise ValueError(
            f"{path}: no detail.cost_analysis stamp (re-run bench.py "
            f"with an AOT-compiled pipeline to capture it)")
    if not isinstance(value, (int, float)) or value <= 0:
        raise ValueError(f"{path}: no positive measured value")
    platform = detail.get("device_kind") or "cpu"
    peaks = peaks_for("tpu" if platform == "tpu" else platform)
    summary = summarize(float(value), cost["flops_per_rep"],
                        cost.get("bytes_per_rep", 0.0), peaks)
    out = {
        "metric": "roofline_ni_sign_n10k",
        "source_artifact": path,
        "platform": platform,
        "measured_reps_per_sec": float(value),
        "cost_analysis": cost,
        "summary": summary,
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    Path(out_path).parent.mkdir(parents=True, exist_ok=True)
    Path(out_path).write_text(json.dumps(out, indent=1))
    print(json.dumps(summary | {"out": out_path}))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--block", type=int, default=None,
                    help="reps per dispatched block (default: platform "
                         "bench shape)")
    ap.add_argument("--chunk", type=int, default=None)
    ap.add_argument("--budget", type=float, default=10.0)
    ap.add_argument("--trace", type=str, default=None,
                    help="capture a jax.profiler trace into this dir")
    ap.add_argument("--out", type=str,
                    default="benchmarks/results/r03_roofline.json")
    ap.add_argument("--platform", type=str, default=None,
                    help="force a JAX platform (e.g. 'cpu'); the image's "
                         "site hook ignores JAX_PLATFORMS env, so an "
                         "in-process config.update is the only override")
    ap.add_argument("--from-artifact", type=str, default=None,
                    help="derive the summary jax-free from a bench "
                         "artifact's detail.cost_analysis + value")
    args = ap.parse_args()

    if args.from_artifact:
        try:
            from_artifact(args.from_artifact, args.out)
        except (OSError, ValueError) as e:
            print(f"roofline: {e}", file=sys.stderr)
            sys.exit(1)
        return

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import bench
    from dpcorr.obs import transfer as transfer_mod
    from dpcorr.utils import geometry, rng
    from dpcorr.utils.roofline import (analytic_rep_model, peaks_for,
                                       summarize, xla_cost)

    platform = jax.devices()[0].platform
    is_tpu = platform in ("tpu", "axon")
    # the bench worker's shape resolution — the artifact must describe
    # the same compiled program as the headline: the autotuned geometry
    # when this host has one cached, else the measured constants
    block, chunk = bench._worker_shape("tpu" if is_tpu else "cpu")
    geo = geometry.lookup("bench-icdf", bench.N,
                          device_kind="tpu" if is_tpu else platform,
                          eps_pairs=[(bench.EPS1, bench.EPS2)],
                          env_pin=is_tpu)
    if geo is not None:
        block, chunk = geo.block_reps, geo.chunk_size
    block = args.block or block
    chunk = args.chunk or chunk

    fn = bench.make_xla_block(chunk)
    key = rng.master_key()

    # --- lens 1: the compiler's own count of the compiled block ---------
    cost = xla_cost(fn, rng.design_key(key, 0), block)
    per_rep = {"flops": cost["flops"] / block, "bytes": cost["bytes"] / block}

    # --- lens 2: analytic hand model ------------------------------------
    model = analytic_rep_model(bench.N, bench.EPS1, bench.EPS2)

    # --- lens 3: steady-state throughput (the bench's own protocol: the
    # donated rep-block pipeline, with its transfer counters recorded) ---
    counters = transfer_mod.default_counters()
    before = counters.snapshot()
    pipe = bench.make_pipeline(chunk, block, key=key, counters=counters)
    rps, _ = bench.measure_pipeline(pipe, args.budget)
    transfer = transfer_mod.diff(counters.snapshot(), before)

    peaks = peaks_for(platform)
    # the compiler count is the headline work model; fall back to the
    # analytic model when cost_analysis is empty on this backend
    flops_per_rep = per_rep["flops"] or model["flops_per_rep"]
    bytes_per_rep = per_rep["bytes"] or model["bytes_per_rep_floor"]
    summary = summarize(rps, flops_per_rep, bytes_per_rep, peaks)

    out = {
        "metric": "roofline_ni_sign_n10k",
        "device": str(jax.devices()[0]),
        "platform": platform,
        "block_reps": block,
        "chunk": chunk,
        "xla_cost_per_rep": per_rep,
        "analytic_model": model,
        "xla_vs_analytic_flops_ratio": (
            round(per_rep["flops"] / model["flops_per_rep"], 2)
            if per_rep["flops"] else None),
        "summary": summary,
        "geometry_source": geo.source if geo is not None else "default",
        "transfer": transfer,
        "donation_engaged": pipe.donation_engaged,
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    if args.trace:
        tdir = Path(args.trace)
        tdir.mkdir(parents=True, exist_ok=True)
        with jax.profiler.trace(str(tdir)):
            futs = [fn(rng.design_key(key, 100 + i), block)
                    for i in range(3)]
            for f in futs:
                tuple(float(x) for x in f)
        out["trace_dir"] = str(tdir)

    Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    Path(args.out).write_text(json.dumps(out, indent=1))
    print(json.dumps(out["summary"] | {"out": args.out}))


if __name__ == "__main__":
    main()
