"""The five BASELINE.md benchmark configs, each printing one JSON line.

    python -m benchmarks.run_all           # all five, smoke-sized reps
    python -m benchmarks.run_all --full    # the full BASELINE.md rep counts
    python -m benchmarks.run_all --config 2 5

Configs (BASELINE.md / BASELINE.json):

1. Gaussian NI estimator, n=1000, ε=1.0, 100 MC reps (the single
   vert-cor.R grid point).
2. Bernoulli INT estimator, n=1000, ε ∈ {0.5, 1, 2}, 1000 MC reps.
3. Full grid {gaussian, bernoulli} × n ∈ {1e3, 1e4} × ε sweep, 10k reps
   per design point (the vert-cor.R grid shape, both DGPs).
4. HRS BMI-vs-Age DP correlation with 10k bootstrap reps (row resampling
   + fresh DP noise per rep; the reference's sweep replicates noise only).
5. Stress: n=1e6 MC reps of the sub-Gaussian estimators over a λ_n (η)
   sweep through the streaming n-blocked kernels; reports measured
   reps/sec/chip and the projected 1M-rep wall-clock.

``--full`` sizes match BASELINE.md; the default is a smoke run sized to
finish in a few minutes on one chip. The headline driver metric stays in
``bench.py``.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import numpy as np


def _emit(config: int, metric: str, value, unit: str, detail: dict):
    print(json.dumps({"config": config, "metric": metric,
                      "value": round(float(value), 2), "unit": unit,
                      "detail": detail}), flush=True)


def _timed_sim(cfg):
    """Run one design point twice (compile pass with a shifted seed — seed
    is outside the jit cache key — then timed) and return (result, steady
    seconds)."""
    import dataclasses

    from dpcorr.sim import run_sim_one

    run_sim_one(dataclasses.replace(cfg, seed=cfg.seed + 1))
    t0 = time.perf_counter()
    res = run_sim_one(cfg)
    return res, time.perf_counter() - t0


def config1(full: bool, b_override=None):
    from dpcorr.sim import SimConfig

    b = b_override or 100
    cfg = SimConfig(n=1000, rho=0.5, eps1=1.0, eps2=1.0, b=b)
    res, dt = _timed_sim(cfg)
    _emit(1, "gaussian_ni_n1000_reps_per_sec", b / dt, "reps/sec", {
        "b": b, "seconds": round(dt, 3),
        "ni": {k: round(v, 4) for k, v in res.summary["NI"].items()},
    })


def config2(full: bool, b_override=None):
    from dpcorr.sim import SimConfig

    b = b_override or (1000 if full else 250)
    for eps in (0.5, 1.0, 2.0):
        cfg = SimConfig(n=1000, rho=0.3, eps1=eps, eps2=eps, b=b,
                        dgp="bernoulli")
        res, dt = _timed_sim(cfg)
        _emit(2, f"bernoulli_int_n1000_eps{eps}_reps_per_sec", b / dt,
              "reps/sec", {
                  "b": b, "eps": eps, "seconds": round(dt, 3),
                  "int": {k: round(v, 4)
                          for k, v in res.summary["INT"].items()},
                  # The sign estimators assume the Gaussian arcsine identity
                  # E[sign·sign] = (2/π)asin(ρ); on Bernoulli data η = ρ, so
                  # the sine link biases ρ̂ toward sin(πρ/2) by construction
                  # (the reference's gen_bernoulli is likewise never wired
                  # to its drivers — SURVEY.md Appendix A #7).
                  "note": "sine-link bias expected under Bernoulli DGP",
              })


def config3(full: bool, b_override=None):
    from dpcorr.grid import GridConfig, run_grid

    b = b_override or (10_000 if full else 200)
    summaries = {}
    t0 = time.perf_counter()
    rows = 0
    steady = []
    for dgp in ("gaussian", "bernoulli"):
        gcfg = GridConfig(n_grid=(1000, 10_000), dgp=dgp, b=b,
                          backend="bucketed")
        res = run_grid(gcfg)
        rows += len(res.detail_all)
        cov = res.summ_all.groupby("method")["coverage"].mean()
        summaries[dgp] = {m: round(float(c), 4) for m, c in cov.items()}
        # one scalar per grid: total reps over that grid's whole pipelined
        # (dispatch-ahead) wall clock — constant across its timings rows
        steady.append(float(res.timings["grid_reps_per_sec"].iloc[0]))
    dt = time.perf_counter() - t0

    # kernels compile once per (n, ε, dgp) bucket — 12 of the 96 points pay
    # compile; the median of the per-grid rates is the steady-state number
    steady_rps = float(np.median(steady))
    _emit(3, "full_grid_2dgp_reps_per_sec", steady_rps, "reps/sec", {
        "design_points": 2 * 2 * 8 * 3, "b": b, "replicate_rows": rows,
        "wall_seconds_incl_compile": round(dt, 2),
        "wall_reps_per_sec": round(rows / dt, 1),
        "mean_coverage": summaries,
    })


def config4(full: bool, b_override=None):
    from dpcorr import hrs

    reps = b_override or (10_000 if full else 500)
    cfg = hrs.HrsConfig()
    cols = hrs.load_panel(cfg.panel_path)
    # compile pass at the same reps (keys shape is part of the trace key)
    hrs.bootstrap(cfg, cols=cols, reps=reps)
    t0 = time.perf_counter()
    df = hrs.bootstrap(cfg, cols=cols, reps=reps)
    dt = time.perf_counter() - t0
    _emit(4, "hrs_bootstrap_reps_per_sec", reps / dt, "reps/sec", {
        "reps": reps, "seconds": round(dt, 2),
        "rho_np": round(df.attrs["rho_np"], 4),
        "summary": {m: {k: round(v, 4) for k, v in s.items()}
                    for m, s in df.attrs["summary"].items()},
    })


def config5(full: bool, b_override=None):
    from dpcorr.sim import SimConfig

    from dpcorr.sim import stress_chunk_size

    n = 1_000_000
    b = b_override or (256 if full else 32)
    target = 1_000_000  # BASELINE.md: 1M reps
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    chunk_size = stress_chunk_size(b, on_tpu)
    # λ_n(n, η) = min(2η√(log n), 2√3) caps at 2√3 for every η ≳ 0.47 at
    # n=1e6 (ver-cor-subG.R:1), so sweep the region where the clip binds.
    for eta in (0.1, 0.25, 0.5):
        cfg = SimConfig(n=n, rho=0.5, eps1=1.0, eps2=1.0, b=b,
                        dgp="bounded_factor", use_subg=True,
                        eta1=eta, eta2=eta, stream_n_chunk=65536,
                        chunk_size=chunk_size)
        res, dt = _timed_sim(cfg)
        rps = b / dt
        _emit(5, f"stress_n1e6_subg_eta{eta}_reps_per_sec", rps,
              "reps/sec/chip", {
                  "n": n, "b": b, "eta": eta, "seconds": round(dt, 2),
                  "projected_1M_reps_hours": round(target / rps / 3600, 2),
                  "ni": {k: round(v, 5)
                         for k, v in res.summary["NI"].items()},
                  "int": {k: round(v, 5)
                          for k, v in res.summary["INT"].items()},
                  # Coverage at stress scale is a CONSTRUCTION property,
                  # recorded honestly rather than tuned away: the clip
                  # thresholds introduce a fixed (n-independent) bias —
                  # at η=0.1, λ_n=0.74 truncates the bounded-factor data
                  # itself (hard NI clip-bias ⇒ coverage → 0); and for
                  # INT even mild product clipping biases ρ̂ by ~1e-3
                  # while its CI width shrinks as 1/√n, so at n=10⁶ the
                  # interval is narrower than the bias (coverage → 0).
                  # The reference never ran n=10⁶ (max n=12,000,
                  # ver-cor-subG.R:245); at its scales the same widths
                  # dominate the same biases and coverage is nominal
                  # (see acceptance_r02.json subg points at n=4000).
                  "coverage_note": "fixed clip-bias vs 1/sqrt(n) width; "
                                   "see detail comment in benchmarks/"
                                   "run_all.py config5",
              })


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main(argv=None):
    ap = argparse.ArgumentParser(prog="benchmarks.run_all")
    ap.add_argument("--config", type=int, nargs="+", default=None,
                    choices=sorted(CONFIGS),
                    help="subset of configs to run (default: all)")
    ap.add_argument("--full", action="store_true",
                    help="full BASELINE.md rep counts (slow)")
    ap.add_argument("--b", type=int, default=None,
                    help="override rep counts (smoke testing)")
    ap.add_argument("--platform", type=str, default=None,
                    help="force a JAX platform (the site hook ignores "
                         "JAX_PLATFORMS env; this applies config.update "
                         "before the backend initializes)")
    args = ap.parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    which = args.config or sorted(CONFIGS)
    print(json.dumps({"device": str(jax.devices()[0]),
                      "n_devices": jax.device_count(),
                      "full": args.full}), flush=True)
    for c in which:
        CONFIGS[c](args.full, args.b)


if __name__ == "__main__":
    main()
