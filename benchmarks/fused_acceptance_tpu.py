"""B ≥ 10⁶ coverage campaign for the fused Pallas kernels on real TPU.

The acceptance table (`benchmarks/results/acceptance_r02.json`, VERDICT r1
item 3) pins the XLA estimator pairs at the 1e-3 criterion. The fused
kernels draw from the on-chip PRNG — a different stream family — so their
calibration needs its own B=2²⁰ measurement per family:

- ``sign``: `sim_detail_pallas` (NI sign-batch + INT sign-flip, Gaussian,
  n=10 000, ε=(1,1), ρ=0.5 — the bench/acceptance headline point).

(The ``subg`` campaign went with the r05 ``fused="all"`` retirement —
GridConfig.fused has the decision record; its recorded r02 measurement
in `r02_fused_acceptance.json` stays checked in and test-pinned.)

Writes benchmarks/results/r02_fused_acceptance.json with per-estimator
coverage, its MC standard error (≈ 2.1e-4 at B=2²⁰), and the diff from
the XLA campaign's matching points where available.

Run: python benchmarks/fused_acceptance_tpu.py [--log2b 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# Default output is an r05-named FRESH artifact: the r02 table
# (r02_fused_acceptance.json) carries the retirement decision's pinned
# subg evidence and must never be clobbered by a sign-only re-run —
# recorded measurements are immutable history, new runs get new names.
RESULTS = os.path.join(REPO, "benchmarks", "results",
                       "r05_fused_acceptance.json")
RHO = 0.5
BLOCK = 32_768


def _campaign(fn, n, log2b):
    import jax.numpy as jnp
    import numpy as np

    from dpcorr.sim import DETAIL_FIELDS
    from dpcorr.utils import rng

    b_total = 1 << log2b
    n_blocks = b_total // BLOCK
    key = rng.master_key()
    covers = {"ni_cover": 0.0, "int_cover": 0.0}
    t0 = time.perf_counter()
    outs = []
    for blk in range(n_blocks):  # async dispatch, one drain
        seeds = rng.pallas_seeds(rng.design_key(key, blk), BLOCK)
        raw = fn(seeds, jnp.float32(RHO))
        d = dict(zip(DETAIL_FIELDS, raw, strict=True))
        outs.append((jnp.mean(d["ni_cover"]), jnp.mean(d["int_cover"])))
    for ni_c, int_c in outs:
        covers["ni_cover"] += float(ni_c)
        covers["int_cover"] += float(int_c)
    wall = time.perf_counter() - t0
    se = float(np.sqrt(0.95 * 0.05 / b_total))
    return {
        "n": n, "rho": RHO, "eps": [1.0, 1.0], "B": b_total,
        "coverage_NI": round(covers["ni_cover"] / n_blocks, 5),
        "coverage_INT": round(covers["int_cover"] / n_blocks, 5),
        "mc_se": round(se, 6),
        "reps_per_sec": round(b_total / wall, 1),
        "wall_s": round(wall, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log2b", type=int, default=20)
    ap.add_argument("--out", type=str, default=RESULTS)
    args = ap.parse_args()

    import jax

    from dpcorr.ops.pallas_ni import sim_detail_pallas

    out = {"device": str(jax.devices()[0]), "nominal": 0.95, "families": {}}

    out["families"]["sign"] = _campaign(
        lambda s, r: sim_detail_pallas(s, r, 10_000, 1.0, 1.0,
                                       interpret=False),
        10_000, args.log2b)
    print("sign ->", json.dumps(out["families"]["sign"]), flush=True)

    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print("wrote", args.out, flush=True)


if __name__ == "__main__":
    main()
