#!/bin/bash
# Round-4 TPU validation queue (supersedes tpu_r03_queue.sh; the r03
# watcher was stopped at r04 session start per VERDICT r3 Weak #8).
#
# Ordering contract (VERDICT r2/r3): bank the headline FIRST; everything
# that has ever wedged the tunnel (limit probes, new Mosaic features,
# 2^20-rep blocks) runs strictly after it. Steps:
#
#   1. `python bench.py` at shipped defaults -> the driver-shaped headline
#      line. THE round-4 deliverable (3rd consecutive ask).
#   2. Roofline + profiler trace of the same kernel -> r04_roofline.json
#      (turns PERFORMANCE.md's %-of-peak model into a measurement).
#   3. Pallas gauss A/B (boxmuller vs ndtri) -> decides the kernel default
#      (VERDICT r3 #3 deadline: this round or retire).
#   4. subG fused decisive A/B at reference scale -> beat XLA or retire
#      fused="all" (VERDICT r3 #3).
#   5. Fused CLI grid smoke (--b 8) -> end-to-end on-chip grid wiring.
#   6. BASELINE config 5 stress: streaming subG at n=10^6 with the fused
#      single-pass pair (first-ever on-chip number for config 5).
#   7. Acceptance point 2 on-chip (HRS-like shape, B=2^20 det+mc twin) —
#      fast on TPU; the CPU fallback twin runs separately in-session.
#   8. Full 5-config suite incl. HRS bootstrap at 10k reps (longest, last,
#      so a mid-run wedge costs the least).
#
# Results land in /tmp/tpu_r04/; harvest with benchmarks/harvest_r04.sh.

set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=/tmp/tpu_r04
mkdir -p "$OUT"
FAILED=0
TOTAL=0
# persistent compile cache, keyed by revision (honest timings: the first
# run of this revision still pays compile; later steps/retries skip it)
export DPCORR_COMPILE_CACHE="$OUT/xla_cache_$(git rev-parse --short HEAD)"

step() {  # step <name> <cmd...>: run, record status, keep going
  local name=$1; shift
  TOTAL=$((TOTAL + 1))
  if "$@"; then
    echo "-- $name: OK ($(date -u +%H:%M:%SZ))"
  else
    echo "-- $name: FAILED (rc=$?) ($(date -u +%H:%M:%SZ))"
    FAILED=$((FAILED + 1))
  fi
}

probe() {
  timeout 150 python -c \
    "import jax; assert jax.devices()[0].platform in ('tpu','axon'); import jax.numpy as jnp; print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))" \
    >/dev/null 2>&1
}

for i in $(seq 1 300); do
  if probe; then
    echo "tunnel healthy at attempt $i ($(date -u +%H:%M:%SZ))"

    echo "== 1. bench.py at shipped defaults (the headline) =="
    # a degraded CPU-fallback line still prints reps_per_sec — only an
    # undegraded line counts as the banked headline
    step bench_default bash -c \
      'timeout 1800 python bench.py 2>"'$OUT'/bench_default.err" \
       | tail -1 | tee "'$OUT'/bench_default.json" \
       | grep "reps_per_sec" | grep -qv "\"degraded\""'

    echo "== 2. roofline + trace (same kernel) =="
    step roofline bash -c \
      'timeout 1200 python -m benchmarks.roofline --budget 15 \
       --trace benchmarks/results/trace_r04 \
       --out benchmarks/results/r04_roofline.json \
       2>"'$OUT'/roofline.err" | tail -1 | grep -q reps_per_sec'

    echo "== 3. pallas gauss A/B (worker-only, budget 20s each) =="
    step pallas_boxmuller bash -c \
      'timeout 900 python bench.py --worker tpu-pallas --budget 20 \
       2>"'$OUT'/pallas_bm.err" | tail -1 \
       | tee "'$OUT'/pallas_boxmuller.json" | grep -q "reps_per_sec"'
    step pallas_ndtri bash -c \
      'DPCORR_BENCH_PALLAS_GAUSS=ndtri \
       timeout 900 python bench.py --worker tpu-pallas --budget 20 \
       2>"'$OUT'/pallas_nd.err" | tail -1 \
       | tee "'$OUT'/pallas_ndtri.json" | grep -q "reps_per_sec"'

    echo "== 4. subG fused decisive A/B (beat XLA or retire, ref scale) =="
    step grid_fused_subg bash -c \
      'timeout 2400 python benchmarks/grid_fused_tpu.py --family subg \
       --out benchmarks/results/r04_grid_fused_subg_tpu.json \
       2>"'$OUT'/fused_subg.err" | tail -2 | grep -q wrote'

    echo "== 5. fused CLI grid smoke (--b 8) =="
    step grid_fused_smoke bash -c \
      'timeout 900 python -m dpcorr grid --backend bucketed --fused auto \
       --b 8 2>"'$OUT'/grid.err" | tail -2 \
       | tee "'$OUT'/grid_fused_smoke.txt" | grep -q "INT"'

    echo "== 6. BASELINE config 5 stress (streaming n=10^6, fused pair) =="
    step config5 bash -c \
      'set -o pipefail; timeout 3000 python -m benchmarks.run_all --config 5 \
       2>"'$OUT'/config5.err" \
       | tee benchmarks/results/r04_tpu_config5.jsonl \
       | grep -q stress_n1e6'

    echo "== 7. acceptance point 2 on-chip (HRS-like, B=2^20 twin) =="
    step acceptance2 bash -c \
      'timeout 5400 python benchmarks/acceptance_point2.py --n 19433 \
       --eps 2.0 --log2b 20 \
       --out benchmarks/results/acceptance_r04_tpu.json \
       2>"'$OUT'/acceptance2.err" | tail -1 | grep -q det_mc'

    echo "== 8. full 5-config suite, BASELINE rep counts (longest, last) =="
    step suite bash -c \
      'set -o pipefail; timeout 7200 python -m benchmarks.run_all --full \
       2>"'$OUT'/suite.err" \
       | tee benchmarks/results/r04_tpu_suite.jsonl \
       | grep -q stress_n1e6'

    cat "$OUT"/*.json 2>/dev/null
    echo "r04 queue finished ($(date -u +%H:%M:%SZ)): $((TOTAL - FAILED))/$TOTAL steps OK"
    exit $FAILED
  fi
  sleep 110
done
echo "tunnel never recovered within the polling window"
exit 1
