#!/bin/bash
# Round-4 TPU validation queue (supersedes tpu_r03_queue.sh; the r03
# watcher was stopped at r04 session start per VERDICT r3 Weak #8).
#
# Ordering contract (VERDICT r2/r3): bank the headline FIRST; everything
# that has ever wedged the tunnel (limit probes, new Mosaic features,
# 2^20-rep blocks) runs strictly after it.
#
# Resumability (new in r04): the tunnel's observed failure mode is
# wedging UNDER SUSTAINED LOAD — i.e. mid-queue. Each step records a
# done-marker in $OUT; when a step fails, a re-probe decides whether it
# was a genuine failure (marked .fail, not retried) or a wedge (no
# marker — the queue drops back to polling and, on the next recovery,
# resumes from the first unfinished step instead of burning every
# remaining step's timeout against a dead tunnel).
#
# Steps, in order:
#   1. bench_default  — `python bench.py` headline. THE r04 deliverable.
#   2. roofline       — roofline + profiler trace -> r04_roofline.json.
#   3. config5        — streaming subG n=10^6 stress (first on-chip).
#   4. acceptance2    — HRS-like (n=19433, eps=2) B=2^20 det/mc twin.
#   5. suite          — full 5-config BASELINE suite (longest XLA step).
#   6. pallas_boxmuller — gauss A/B baseline arm (usually compile-cached,
#                       but Mosaic-compiles cold like the others).
#   7. pallas_ndtri   — gauss A/B's other arm. UNCACHED Mosaic compile;
#                       wedged the tunnel on 2026-07-31 (hung its full
#                       900 s) — all Mosaic-risky steps now run AFTER the
#                       pure-XLA evidence is banked.
#   8. grid_fused_subg — decisive subG fused A/B: beat XLA or retire.
#   9. grid_fused_smoke — fused CLI grid end-to-end (--b 8; fused=auto
#                       also Mosaic-compiles, so it lives in this block).
#
# Wedge cap (new after the 03:36Z ndtri wedge): a Mosaic-risky step that
# wedges the tunnel THREE times is classified as the wedge's cause and
# marked .fail — otherwise a deterministically-wedging Mosaic compile
# livelocks the queue, burning every healing window on the same step and
# starving the steps behind it. The cap is 3, not 2, so that one
# unrelated load-induced outage during a long Mosaic step (e.g. minute
# 35 of grid_fused_subg's 40-minute run) cannot combine with a single
# compile hang to fail the decisive A/B; and since the Mosaic block runs
# last, its burned healing windows cost no XLA evidence.
#
# Results land in /tmp/tpu_r04/; harvest with benchmarks/harvest_r04.sh.

set -u -o pipefail
OUT=${TPU_R04_IN:-/tmp/tpu_r04}
mkdir -p "$OUT"

sweep_strays() {
  # A bench worker whose orchestrator is gone (reparented to init) holds
  # the exclusive TPU client forever and is indistinguishable from a
  # wedged tunnel (observed live in r04: a SIGKILLed orchestrator
  # stranded its setsid worker). bench.py now reaps its workers on every
  # catchable death; this sweeps the uncatchable (SIGKILL) leftovers.
  # The ppid==1 test is the real guard: every live harness/driver shell
  # has a live parent, and the adjacent "bench.py --worker" token pair
  # appears in no driver command line — so no interpreter-path anchor,
  # which would silently no-op wherever the venv lives elsewhere and
  # miss the queue's own direct 'python bench.py --worker' steps.
  local pid
  for pid in $(pgrep -f "bench\.py --worker" 2>/dev/null); do
    [ "$pid" = "$$" ] && continue
    if [ "$(ps -o ppid= -p "$pid" 2>/dev/null | tr -d ' ')" = "1" ]; then
      kill -9 "$pid" 2>/dev/null && echo "swept stray TPU client $pid ($(date -u +%H:%M:%SZ))"
    fi
  done
}

probe() {
  if [ -n "${TPU_R04_PROBE:-}" ]; then eval "$TPU_R04_PROBE"; return; fi
  sweep_strays
  # Fast gate (diagnosed 2026-07-31, STATUS_r04.md): the tunnel's local
  # relay listens on 127.0.0.1:8082/8083/8087; when the relay process is
  # dead every one of them refuses TCP instantly and the full jax probe
  # can only burn its 150 s timeout. Sub-second check first; any open
  # port falls through to the authoritative jax probe. Because the port
  # list is owned by external infra and could go stale, every 8th
  # consecutive gate-negative runs the full jax probe anyway — a wrong
  # port list degrades to slow polling, never to total evidence loss.
  if ! timeout 10 python - <<'PY' >/dev/null 2>&1
import socket, sys
for p in (8082, 8083, 8087):
    s = socket.socket(); s.settimeout(2)
    try:
        s.connect(("127.0.0.1", p)); s.close(); sys.exit(0)
    except OSError:
        pass
sys.exit(1)
PY
  then
    local g=0
    [ -s "$OUT/.gate_negatives" ] && g=$(cat "$OUT/.gate_negatives")
    g=$((g + 1)); echo "$g" > "$OUT/.gate_negatives"
    [ $((g % 8)) -ne 0 ] && return 1
  fi
  timeout 150 python -c \
    "import jax; assert jax.devices()[0].platform in ('tpu','axon'); import jax.numpy as jnp; print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))" \
    >/dev/null 2>&1
}

WEDGED=0
run_step() {  # run_step <name> <cmd...>: honor markers, classify failures
  local name=$1; shift
  [ "$WEDGED" = 1 ] && return
  if [ -e "$OUT/$name.ok" ]; then
    echo "-- $name: already done, skipping"
    return
  fi
  if [ -e "$OUT/$name.fail" ]; then
    echo "-- $name: failed genuinely earlier, not retrying"
    return
  fi
  echo "== $name ($(date -u +%H:%M:%SZ)) =="
  if "$@"; then
    touch "$OUT/$name.ok"
    echo "-- $name: OK ($(date -u +%H:%M:%SZ))"
  elif probe; then
    # tunnel alive -> the step itself is broken; don't burn retries on it
    touch "$OUT/$name.fail"
    echo "-- $name: FAILED genuinely ($(date -u +%H:%M:%SZ))"
  else
    # tunnel wedged mid-queue -> normally no marker; resume here on next
    # recovery. For MOSAIC-RISKY steps only, cap it: a third wedge on
    # the same step marks .fail (the step is the wedge's cause,
    # Mosaic-compile-hang class; see the header for why 3). Pure-XLA
    # steps are never capped — a wedge during a 2 h suite run is the
    # tunnel's documented load-induced flakiness, not the step's fault,
    # and .fail-ing the round's deliverable evidence on unrelated
    # outages hours apart would be worse than retrying.
    WEDGED=1
    if [[ " $MOSAIC_STEPS " == *" $name "* ]]; then
      local w=0
      [ -s "$OUT/$name.wedges" ] && w=$(cat "$OUT/$name.wedges")
      w=$((w + 1)); echo "$w" > "$OUT/$name.wedges"
      if [ "$w" -ge 3 ]; then
        echo "wedged the tunnel ${w}x; classified as wedge cause" > "$OUT/$name.fail"
        echo "-- $name: wedged the tunnel ${w}x; marked .fail, skipping henceforth ($(date -u +%H:%M:%SZ))"
        return
      fi
    fi
    echo "-- $name: tunnel wedged mid-step; back to polling ($(date -u +%H:%M:%SZ))"
  fi
}

all_steps() {
  run_step bench_default bash -c \
    'timeout 1800 python bench.py 2>"'$OUT'/bench_default.err" \
     | tail -1 | tee "'$OUT'/bench_default.json" \
     | grep "reps_per_sec" | grep -qv "\"degraded\""'
  # (a degraded CPU-fallback line still prints reps_per_sec — only an
  # undegraded line counts as the banked headline)

  run_step roofline bash -c \
    'timeout 1200 python -m benchmarks.roofline --budget 15 \
     --trace benchmarks/results/trace_r04 \
     --out benchmarks/results/r04_roofline.json \
     2>"'$OUT'/roofline.err" | tail -1 | grep -q reps_per_sec'

  # --- pure-XLA evidence block: no fresh Mosaic compiles, safe ---

  run_step config5 bash -c \
    'set -o pipefail; timeout 3000 python -m benchmarks.run_all --config 5 \
     2>"'$OUT'/config5.err" \
     | tee benchmarks/results/r04_tpu_config5.jsonl \
     | grep -q stress_n1e6'

  run_step acceptance2 bash -c \
    'timeout 5400 python benchmarks/acceptance_point2.py --n 19433 \
     --eps 2.0 --log2b 20 \
     --out benchmarks/results/acceptance_r04_tpu.json \
     2>"'$OUT'/acceptance2.err" | tail -1 | grep -q det_mc'

  run_step suite bash -c \
    'set -o pipefail; timeout 7200 python -m benchmarks.run_all --full \
     2>"'$OUT'/suite.err" \
     | tee benchmarks/results/r04_tpu_suite.jsonl \
     | grep -q stress_n1e6'

  # --- Mosaic-risky block: fresh kernel compiles, wedge suspects ---

  run_step pallas_boxmuller bash -c \
    'timeout 900 python bench.py --worker tpu-pallas --budget 20 \
     2>"'$OUT'/pallas_bm.err" | tail -1 \
     | tee "'$OUT'/pallas_boxmuller.json" | grep -q "reps_per_sec"'

  run_step pallas_ndtri bash -c \
    'DPCORR_BENCH_PALLAS_GAUSS=ndtri \
     timeout 900 python bench.py --worker tpu-pallas --budget 20 \
     2>"'$OUT'/pallas_nd.err" | tail -1 \
     | tee "'$OUT'/pallas_ndtri.json" | grep -q "reps_per_sec"'

  run_step grid_fused_subg bash -c \
    'timeout 2400 python benchmarks/grid_fused_tpu.py --family subg \
     --out benchmarks/results/r04_grid_fused_subg_tpu.json \
     2>"'$OUT'/fused_subg.err" | tail -2 | grep -q wrote'

  run_step grid_fused_smoke bash -c \
    'timeout 900 python -m dpcorr grid --backend bucketed --fused auto \
     --b 8 2>"'$OUT'/grid.err" | tail -2 \
     | tee "'$OUT'/grid_fused_smoke.txt" | grep -q "INT"'
}

STEP_NAMES="bench_default roofline config5 acceptance2 suite \
pallas_boxmuller pallas_ndtri grid_fused_subg grid_fused_smoke"

# Steps whose own fresh Mosaic compile is the plausible wedge CAUSE; only
# these are subject to the wedge cap above. pallas_boxmuller belongs here
# too: its kernel is usually compile-cached, but on a cold cache (fresh
# host, cache eviction, kernel code change) it Mosaic-compiles exactly
# like the others.
MOSAIC_STEPS="pallas_boxmuller pallas_ndtri grid_fused_subg grid_fused_smoke"

finished() {  # every step has a terminal marker
  local s
  for s in $STEP_NAMES; do
    [ -e "$OUT/$s.ok" ] || [ -e "$OUT/$s.fail" ] || return 1
  done
  return 0
}

# sourcing (tests) stops here: the functions above are the testable
# surface; the cwd change, compile cache, and polling loop below only
# apply when executed directly
if [ "${BASH_SOURCE[0]}" != "$0" ]; then return 0; fi

cd "$(dirname "$0")/.."
# No DPCORR_COMPILE_CACHE export: bench.py steps use their per-user
# default cache on their own (pre-warming the driver's round-end run —
# bench measurement excludes compile via the warm-up block), while the
# grid/run_all steps stay COLD so their wall-times remain comparable to
# the r02 cold-start numbers instead of reporting cache warmth as a
# speedup.

for i in $(seq 1 300); do
  if probe; then
    echo "tunnel healthy at attempt $i ($(date -u +%H:%M:%SZ))"
    WEDGED=0
    all_steps
    # harvest whatever is banked so far (idempotent; rejects degraded
    # lines) — evidence must reach benchmarks/results/ the moment it
    # exists, not only after a full queue pass survives the tunnel
    bash benchmarks/harvest_r04.sh || true
    if finished; then
      ok=0; fail=0
      for s in $STEP_NAMES; do
        if [ -e "$OUT/$s.ok" ]; then ok=$((ok + 1)); else fail=$((fail + 1)); fi
      done
      cat "$OUT"/*.json 2>/dev/null
      echo "r04 queue finished ($(date -u +%H:%M:%SZ)): $ok OK, $fail failed"
      exit $fail
    fi
    echo "queue interrupted by wedge; resuming poll ($(date -u +%H:%M:%SZ))"
  fi
  sleep 110
done
echo "tunnel never recovered within the polling window"
exit 1
