#!/bin/bash
# Harvest the r04 TPU queue outputs (/tmp/tpu_r04) into checked-in
# artifacts. Run after `tpu_r04_queue.sh` reports steps OK. Idempotent;
# prints what it found and what it wrote. Commit separately after review.

set -u
cd "$(dirname "$0")/.."
# overridable for tests (tests/test_benchmarks.py harvests a fixture dir)
IN=${TPU_R04_IN:-/tmp/tpu_r04}
OUT=${TPU_R04_OUT:-benchmarks/results}

copy_json() {  # copy_json <src> <dst> <must-contain>
  local src=$1 dst=$2 needle=$3
  # a degraded CPU-fallback line still contains reps_per_sec — it must
  # never be banked as TPU evidence (bench.py cites these files back as
  # "recorded_tpu_evidence", which would become circular)
  if [ -s "$src" ] && grep -q "$needle" "$src" \
     && ! grep -q '"degraded"' "$src"; then
    cp "$src" "$dst"
    echo "wrote $dst"
  else
    echo "SKIP $dst ($src missing, lacks '$needle', or is degraded)"
  fi
}

echo "== headline =="
# bench_default.json is the full driver-shaped line; keep it verbatim as
# the round's recorded hardware evidence
copy_json "$IN/bench_default.json" "$OUT/r04_tpu_headline.json" reps_per_sec

echo "== gauss A/B =="
for f in pallas_boxmuller pallas_ndtri; do
  copy_json "$IN/$f.json" "$OUT/r04_$f.json" reps_per_sec
done
if [ -s "$OUT/r04_pallas_boxmuller.json" ] && [ -s "$OUT/r04_pallas_ndtri.json" ]; then
  python - <<'EOF'
import json
bm = json.load(open("benchmarks/results/r04_pallas_boxmuller.json"))
nd = json.load(open("benchmarks/results/r04_pallas_ndtri.json"))
b, n = bm["value"], nd["value"]
print(f"gauss A/B: boxmuller {b:.0f} vs ndtri {n:.0f} reps/sec "
      f"-> {'NDTRI WINS: flip the kernel default' if n > 1.02*b else 'keep boxmuller'}")
EOF
fi

echo "== subG fused decisive A/B =="
if [ -s "$OUT/r04_grid_fused_subg_tpu.json" ]; then
  python - <<'EOF'
import json
d = json.load(open("benchmarks/results/r04_grid_fused_subg_tpu.json"))
s = d.get("fused_speedup_rps", 0)
print(f"subG fused vs XLA: {s}x "
      f"-> {'KEEP fused=all' if s > 1.05 else 'RETIRE fused=all (cite this file)'}")
EOF
else
  echo "MISSING: $OUT/r04_grid_fused_subg_tpu.json (if the tunnel never"
  echo "healed, retire fused='all' citing r02_grid_fused_subg_tpu.json)"
fi

echo "== config5 / suite / acceptance =="
for f in r04_tpu_config5.jsonl r04_tpu_suite.jsonl acceptance_r04_tpu.json; do
  if [ -s "$OUT/$f" ]; then echo "present: $OUT/$f ($(wc -c < "$OUT/$f") bytes)"
  else echo "MISSING: $OUT/$f"; fi
done

echo "== roofline =="
if [ -s "$OUT/r04_roofline.json" ]; then
  python -c "import json; d=json.load(open('$OUT/r04_roofline.json')); print('roofline:', d['summary'])"
else
  echo "MISSING: $OUT/r04_roofline.json"
fi
if [ -d "$OUT/trace_r04" ]; then
  du -sh "$OUT/trace_r04"
  echo "note: review trace size before committing (trim to the .trace/.json summary if huge)"
fi

echo "== reminders =="
echo "- update docs/STATUS_r04.md + docs/PERFORMANCE.md with the numbers"
echo "- stop the watcher before session end: pgrep -fa r04_queue"
