"""A/B benchmark for the grid compile-ahead pipeline (ISSUE 4).

Runs the same ≥4-bucket grid twice in FRESH subprocesses — once with
``precompile="off"`` (inline jit at first dispatch, the pre-ISSUE-4
behaviour) and once with ``precompile="on"`` (phase-0 thread-pool AOT
compilation overlapped with dispatch; forced rather than "auto" so the
measurement runs on any host — auto backs off on one core) — and
verifies:

1. **bit-identity** — both arms hash to the same ``detail_all`` (the
   compile-ahead layer reuses the exact jitted callables, so AOT vs
   lazy jit must not perturb a single bit);
2. **precompile flags** — every bucket in the ``on`` arm reports
   ``precompiled=True`` in the timings frame and every bucket in the
   ``off`` arm reports ``False`` (the knob actually switches paths);
3. **wall-clock reduction** — the ``on`` arm's dispatch+fetch wall
   (the repo's ``grid_reps_per_sec`` basis: the part of the run
   requests actually wait on) is below the ``off`` arm's, because the
   compiles moved out of the dispatch critical path into phase-0 pool
   threads. The gate only applies with ≥ 2 cores: overlap needs
   somewhere to run, and on a 1-core host total CPU work is conserved
   — the thread-pool overhead makes both walls slightly WORSE there,
   so the gate is recorded as null and both arms' walls are kept for
   honesty (the recorded ``cpu_count`` says which regime a result
   came from).

Fresh subprocesses matter: within one process the second arm would hit
jax's in-memory jit cache and measure nothing. Each arm pays its own
tracing + XLA compilation from zero.

Prints one JSON document with both arms' walls, the speedup, per-bucket
timings, and the verdicts; exit 1 if any gate fails.

Usage:
    python benchmarks/grid_precompile.py [--b 32] [--reps 1]
        [--n-grid 200,400,600,800] [--out-json benchmarks/results/...]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Runs in a fresh interpreter per arm; reads the grid config from the
# DPCORR_GRID_AB env var, prints one JSON line on the last stdout line.
_CHILD = r"""
import hashlib, json, os, sys, time
import pandas as pd
from dpcorr.grid import GridConfig, run_grid

spec = json.loads(os.environ["DPCORR_GRID_AB"])
gcfg = GridConfig(**spec)
t0 = time.perf_counter()
res = run_grid(gcfg)
wall = time.perf_counter() - t0

df = res.detail_all.reset_index(drop=True)
h = hashlib.sha256()
h.update(",".join(df.columns).encode())
h.update(pd.util.hash_pandas_object(df, index=False).values.tobytes())

tm = res.timings
print(json.dumps({
    "wall_s": round(wall, 3),
    # the repo's own grid wall (grid_reps_per_sec basis): dispatch +
    # fetch phases — the part of the run requests actually wait on,
    # and the part compile-ahead moves work out of
    "grid_wall_s": round(float(tm["points_run"].sum() * gcfg.b
                               / tm["grid_reps_per_sec"].iloc[0]), 3),
    "detail_sha256": h.hexdigest(),
    "rows": int(len(df)),
    "buckets": int(len(tm)),
    "precompiled": [bool(v) for v in tm["precompiled"]],
    "timings": json.loads(tm.to_json(orient="records")),
}))
"""


def _run_arm(spec: dict) -> dict:
    env = dict(os.environ, DPCORR_GRID_AB=json.dumps(spec),
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                          capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(f"arm {spec['precompile']!r} failed:\n"
                           f"{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-grid", dest="n_grid", default="200,400,600,800",
                    help="comma-separated n values: one bucket each "
                         "(>= 4 for the acceptance run)")
    ap.add_argument("--rho-grid", dest="rho_grid", default="0.0,0.5")
    ap.add_argument("--b", type=int, default=32,
                    help="replications per design point")
    ap.add_argument("--eps1", type=float, default=1.0)
    ap.add_argument("--eps2", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=9)
    ap.add_argument("--reps", type=int, default=1,
                    help="repeats per arm; best (min) wall is compared")
    ap.add_argument("--out-json", dest="out_json", default=None)
    args = ap.parse_args()

    base = dict(
        n_grid=[int(t) for t in args.n_grid.split(",")],
        rho_grid=[float(t) for t in args.rho_grid.split(",")],
        eps_pairs=[(args.eps1, args.eps2)],
        b=args.b, seed=args.seed, backend="bucketed",
    )
    # interleaved (off, on, off, on, ...) so slow drift in the host's
    # background load hits both arms evenly; best-of-reps compared
    runs: dict[str, list] = {"off": [], "on": []}
    for _ in range(args.reps):
        for mode in ("off", "on"):
            runs[mode].append(_run_arm(dict(base, precompile=mode)))
    arms = {}
    for mode, rs in runs.items():
        best = min(rs, key=lambda r: r["wall_s"])
        best["walls_s"] = [r["wall_s"] for r in rs]
        arms[mode] = best

    speedup = arms["off"]["wall_s"] / arms["on"]["wall_s"]
    grid_speedup = arms["off"]["grid_wall_s"] / arms["on"]["grid_wall_s"]
    ok = {
        "bit_identical":
            arms["off"]["detail_sha256"] == arms["on"]["detail_sha256"],
        "precompile_flags":
            all(arms["on"]["precompiled"])
            and not any(arms["off"]["precompiled"]),
        "enough_buckets": arms["on"]["buckets"] >= 4,
        # the reduction gate needs somewhere for the overlap to run: on
        # a 1-core host total CPU work is conserved, pool scheduling
        # interleaves the bucket compiles (delaying the first), and BOTH
        # walls come out slightly worse — a physical limit, not a bug.
        # Recorded as null there (exit code ignores it) so a 1-core
        # result is honest rather than silently green or spuriously red.
        "faster": (grid_speedup > 1.0
                   if (os.cpu_count() or 1) >= 2 else None),
    }
    out = {
        "metric": "grid_precompile_ab",
        "grid": base,
        "cpu_count": os.cpu_count(),
        "wall_off_s": arms["off"]["wall_s"],
        "wall_on_s": arms["on"]["wall_s"],
        "speedup": round(speedup, 3),
        "grid_wall_off_s": arms["off"]["grid_wall_s"],
        "grid_wall_on_s": arms["on"]["grid_wall_s"],
        "grid_speedup": round(grid_speedup, 3),
        "detail_sha256": arms["on"]["detail_sha256"],
        "rows": arms["on"]["rows"],
        "buckets": arms["on"]["buckets"],
        "ok": ok,
        "arms": arms,
    }
    if ok["faster"] is None:
        out["note"] = ("single-core host: overlap has no second core to "
                       "run on, so the wall gate is skipped (recorded "
                       "walls show the ~5-10% thread-pool overhead the "
                       "off arm avoids here); run on >= 2 cores for the "
                       "reduction measurement")
    blob = json.dumps(out, indent=2)
    print(blob)
    if args.out_json:
        with open(args.out_json, "w") as f:
            f.write(blob)
    return 0 if all(v for v in ok.values() if v is not None) else 1


if __name__ == "__main__":
    sys.exit(main())
