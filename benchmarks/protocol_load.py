"""Load + chaos benchmark for the two-party protocol (ISSUE 5).

Runs full protocol sessions for every estimator family over three
arms — in-process queue transport, loopback TCP, and TCP under fault
injection (default 10% frame drop + 50 ms delay) — and verifies the
protocol acceptance invariants end to end:

1. **transport equivalence** — for a fixed spec, the (rho, lo, hi)
   triple is bit-identical across all three arms: retries, duplicate
   deliveries and reordering must never perturb the estimate (the
   chaos RNG is stdlib, the estimator key tree is jax — disjoint by
   construction).
2. **monolithic equivalence** — the protocol result equals the direct
   ``jit(serving_entry)`` call on the same master key (replay key
   layout), i.e. splitting the estimator across a wire cost zero bits.
3. **chaos actually bites** — the faulted arm must record retransmits
   (otherwise the "fault" arm proved nothing).
4. **transcript + ledger audit** — one session per arm writes both
   parties' transcripts; ``protocol.scan`` must pass the schema and
   no-raw-columns checks against the true columns, and the ε charged
   on the wire must balance the durable audit trail exactly.

Prints one JSON document: per-arm session latency stats, message
throughput, retry counts, and the verdicts. Exit code 1 if any
invariant fails, so the unattended queue can gate on it.

Usage:
    python benchmarks/protocol_load.py [--sessions 8] [--n 2000]
        [--fault-drop 0.10] [--fault-delay-ms 50] [--out-json PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FAMILIES = ("ni_sign", "int_sign", "ni_subg", "int_subg")


def _percentiles(xs: list[float]) -> dict:
    if not xs:
        return {}
    s = sorted(xs)

    def q(p):
        return s[min(len(s) - 1, int(p * len(s)))]

    return {"p50": q(0.50), "p90": q(0.90), "max": s[-1],
            "mean": sum(s) / len(s)}


def _run_arm(arm: str, spec, x, y, fault, sessions: int,
             timeout_s: float, transcript_dir: str | None) -> dict:
    from dpcorr.protocol import run_inproc, run_tcp

    run = run_tcp if arm.startswith("tcp") else run_inproc
    lat, msgs, retries = [], 0, 0
    bits = None
    for i in range(sessions):
        tdir = transcript_dir if i == 0 else None
        t0 = time.perf_counter()
        res = run(spec, x, y, fault=fault, transcript_dir=tdir,
                  timeout_s=timeout_s, max_retries=10)
        lat.append(time.perf_counter() - t0)
        triple = (res["x"].rho_hat, res["x"].ci_low, res["x"].ci_high)
        assert triple == (res["y"].rho_hat, res["y"].ci_low,
                          res["y"].ci_high), "role results diverged"
        if bits is None:
            bits = triple
        elif triple != bits:
            raise AssertionError(f"{arm}: session {i} drifted: "
                                 f"{triple} != {bits}")
        for r in res.values():
            msgs += r.stats["sent_msgs"]
            retries += r.stats["total_retries"]
    wall = sum(lat)
    return {"bits": bits, "sessions": sessions,
            "session_latency_s": _percentiles(lat),
            "messages": msgs,
            "msgs_per_sec": round(msgs / wall, 2) if wall else None,
            "total_retries": retries}


def _audit_arm(spec, x, y, transcript_dir: str) -> dict:
    """Scan both parties' transcripts from the recorded session and
    balance them against fresh audit trails from a re-run (the timing
    arms don't carry trails; the balance check needs one)."""
    from dpcorr.obs.audit import AuditTrail
    from dpcorr.protocol import run_inproc, scan_transcript
    from dpcorr.protocol.scan import ledger_balance
    from dpcorr.serve.ledger import PrivacyLedger

    out = {}
    for role in ("x", "y"):
        path = os.path.join(transcript_dir,
                            f"{spec.session}.{role}.jsonl")
        rep = scan_transcript(path, raw_x=x, raw_y=y)
        out[role] = {"scan_ok": rep["ok"],
                     "violations": rep["violations"],
                     "releases": rep["releases"],
                     "gated_eps": rep["gated_eps"]}
    trails = {r: AuditTrail() for r in ("x", "y")}
    with tempfile.TemporaryDirectory() as td:
        run_inproc(spec, x, y,
                   ledger_x=PrivacyLedger(1e6, audit=trails["x"]),
                   ledger_y=PrivacyLedger(1e6, audit=trails["y"]),
                   transcript_dir=td)
        for role in ("x", "y"):
            path = os.path.join(td, f"{spec.session}.{role}.jsonl")
            bal = ledger_balance(path, trails[role].events())
            out[role]["balance_ok"] = bal["ok"]
            out[role]["spent"] = bal["spent"]
    return out


MATRIX_PARTIES = [("p0", ["a", "b"]), ("p1", ["c"]), ("p2", ["d"])]


def _merged_cells(results: dict) -> dict:
    """Union of every party's cell view; parties sharing a cell must
    agree bitwise (the wire result IS the finisher's result)."""
    cells: dict = {}
    for res in results.values():
        for key, val in res.cells.items():
            if key in cells:
                assert cells[key] == val, f"parties disagree on {key}"
            cells.setdefault(key, val)
    return cells


def _matrix_resume_verdict(plan, data, workdir: str) -> dict:
    """Raise-mode kill of p0 at ``federation.pre_release``, then resume
    on the same endpoints/journals/persistent ledgers — the benchmark's
    in-process form of the chaos CLI's kill-any-party case. Verdict:
    the resumed matrix is bit-identical to the clean one and every
    party's ε was spent exactly once."""
    import threading

    from dpcorr import chaos
    from dpcorr.protocol.federation import make_federation_parties
    from dpcorr.serve.ledger import PrivacyLedger

    def ledgers():
        return {name: PrivacyLedger(
            1e6, path=os.path.join(workdir, f"ledger.{name}.json"))
            for name, _ in plan.parties}

    from dpcorr.protocol import InProcTransport

    endpoints = {lk: InProcTransport() for lk in plan.links()}
    parties = make_federation_parties(plan, data, ledgers=ledgers(),
                                      endpoints=endpoints,
                                      journal_dir=workdir)
    chaos.install(chaos.ChaosPlan("federation.pre_release", hit=1,
                                  mode="raise",
                                  thread_name="party-p0"))
    results: dict = {}
    errors: dict = {}

    def drive(name, party):
        try:
            results[name] = party.run()
        except BaseException as e:  # SimulatedCrash is a BaseException
            errors[name] = e

    threads = {name: threading.Thread(target=drive, args=(name, p),
                                      name=f"party-{name}")
               for name, p in parties.items()}
    for t in threads.values():
        t.start()
    threads["p0"].join()
    chaos.install(None)
    crashed = isinstance(errors.get("p0"), chaos.SimulatedCrash)
    # restart: fresh party objects on the surviving queue pairs, same
    # journals, ledgers reloaded from their files — the exact manual
    # form of "rerun the identical command"
    fresh = make_federation_parties(plan, data, ledgers=ledgers(),
                                    endpoints=endpoints,
                                    journal_dir=workdir)
    rerun = threading.Thread(
        target=drive, args=("p0", fresh["p0"]), name="party-p0")
    rerun.start()
    rerun.join()
    for name in ("p1", "p2"):
        threads[name].join()
    resumed_ok = crashed and "p0" in results and not (
        set(errors) - {"p0"})
    eps_once = True
    final = ledgers()
    for name, _ in plan.parties:
        if abs(final[name].spent(name) - plan.party_eps()[name]) > 1e-9:
            eps_once = False
    return {"crashed_at": "federation.pre_release",
            "victim": "p0", "crash_fired": crashed,
            "resumed": resumed_ok, "eps_exactly_once": eps_once,
            "cells": _merged_cells(results) if resumed_ok else None}


def _trace_overhead(plan, data, pairs: int) -> dict:
    """Interleaved traced-vs-untraced A/B over the same in-process
    matrix (ISSUE 13): alternating run order per pair cancels drift,
    and the span spool must carry ONE trace ID — the plan's. The
    verdict upstream gates tracing at ≤3% on the p50."""
    from dpcorr.obs import trace as obs_trace
    from dpcorr.protocol.federation import run_federation_inproc

    traced: list[float] = []
    untraced: list[float] = []
    with tempfile.TemporaryDirectory() as td:
        spool = os.path.join(td, "spans.jsonl")
        for i in range(pairs):
            order = (("traced", "untraced") if i % 2
                     else ("untraced", "traced"))
            for mode in order:
                if mode == "traced":
                    obs_trace.configure(spool)
                try:
                    t0 = time.perf_counter()
                    run_federation_inproc(plan, data)
                    dt = time.perf_counter() - t0
                finally:
                    obs_trace.configure(None)
                (traced if mode == "traced" else untraced).append(dt)
        spans = obs_trace.read_spans(spool)
    p50_t = _percentiles(traced)["p50"]
    p50_u = _percentiles(untraced)["p50"]
    return {"pairs": pairs,
            "traced_s": _percentiles(traced),
            "untraced_s": _percentiles(untraced),
            "overhead": round(p50_t / p50_u - 1.0, 4) if p50_u else None,
            "spans": len(spans),
            "trace_ids": sorted({s["trace_id"] for s in spans})}


def _matrix_family(family: str, args) -> dict:
    """One family's federation arms: timed in-process matrices
    (cells/s), one TCP matrix (transport equivalence), the
    k·(k−1)/2-independent-sessions equivalence, the ledger's ε against
    the release-reuse optimum vs the naive per-cell baseline, and the
    kill/resume verdict."""
    import numpy as np

    from dpcorr.__main__ import _federation_columns
    from dpcorr.protocol import run_inproc
    from dpcorr.protocol.federation import (
        run_federation_inproc,
        run_federation_tcp,
    )
    from dpcorr.protocol.matrix import FederationPlan
    from dpcorr.serve.ledger import PrivacyLedger

    plan = FederationPlan(family=family, n=args.n, eps=args.eps1,
                          parties=MATRIX_PARTIES, seed=args.seed)
    data = _federation_columns(plan, 0.6)
    lat, cells_ref = [], None
    for _ in range(args.sessions):
        t0 = time.perf_counter()
        res = run_federation_inproc(plan, data)
        lat.append(time.perf_counter() - t0)
        cells = _merged_cells(res)
        if cells_ref is None:
            cells_ref = cells
        assert cells == cells_ref, "matrix drifted across sessions"
    wall = sum(lat)
    n_cells = len(plan.cells())
    t0 = time.perf_counter()
    tcp_cells = _merged_cells(run_federation_tcp(plan, data))
    tcp_s = time.perf_counter() - t0
    # the acceptance contract: bit-identical to k·(k−1)/2 independent
    # two-party sessions over the same per-column key labels
    independent_ok = True
    for i, j in plan.cells():
        r = run_inproc(plan.cell_spec(i, j), data[plan.label(i)],
                       data[plan.label(j)])["x"]
        want = cells_ref[f"{i},{j}"]
        if (np.float32(r.rho_hat), np.float32(r.ci_low),
                np.float32(r.ci_high)) != (np.float32(want["rho_hat"]),
                                           np.float32(want["ci_low"]),
                                           np.float32(want["ci_high"])):
            independent_ok = False
    ledgers = {name: PrivacyLedger(1e6) for name, _ in plan.parties}
    run_federation_inproc(plan, data, ledgers=ledgers)
    spent = {name: ledgers[name].spent(name)
             for name, _ in plan.parties}
    eps_ok = (abs(sum(spent.values()) - plan.optimal_eps()) < 1e-9
              and all(abs(spent[p] - e) < 1e-9
                      for p, e in plan.party_eps().items())
              and plan.optimal_eps() < plan.naive_eps())
    with tempfile.TemporaryDirectory() as td:
        resume = _matrix_resume_verdict(plan, data, td)
    ab = _trace_overhead(plan, data, max(3, args.sessions // 2))
    fam = {
        "plan": {"fed": plan.fed, "k": plan.k, "cells": n_cells,
                 "parties": [[p, list(c)] for p, c in plan.parties]},
        "matrix_latency_s": _percentiles(lat),
        "cells_per_sec": round(n_cells * args.sessions / wall, 2)
        if wall else None,
        "tcp_matrix_s": tcp_s,
        "eps": {"optimal": plan.optimal_eps(),
                "naive_per_cell": plan.naive_eps(),
                "spent": spent,
                "saving_vs_naive": round(
                    1.0 - plan.optimal_eps() / plan.naive_eps(), 4)},
        "resume": {k: v for k, v in resume.items() if k != "cells"},
        "trace_ab": ab,
        "verdicts": {
            "tcp_bit_identical": tcp_cells == cells_ref,
            "matches_independent_runs": independent_ok,
            "eps_at_optimum": eps_ok,
            "trace_overhead_le_3pct": (ab["overhead"] is not None
                                       and ab["overhead"] <= 0.03),
            "traced_single_trace_id": ab["trace_ids"] == [
                plan.trace_id()],
            "kill_resume_exactly_once": bool(
                resume["crash_fired"] and resume["resumed"]
                and resume["eps_exactly_once"]
                and resume["cells"] == cells_ref),
        },
    }
    return fam


def run_matrix(args) -> int:
    """The ``--matrix`` arm: federation benchmarks for every family,
    one JSON document (committed as
    ``benchmarks/results/r12_federation_cpu.json``)."""
    doc = {"benchmark": "federation_matrix",
           "config": {"n": args.n, "eps": args.eps1, "seed": args.seed,
                      "sessions": args.sessions,
                      "parties": MATRIX_PARTIES},
           "families": {}, "ok": True}
    for family in FAMILIES:
        fam = _matrix_family(family, args)
        if not all(fam["verdicts"].values()):
            doc["ok"] = False
        doc["families"][family] = fam
        print(f"{family}: cells/s={fam['cells_per_sec']} " + " ".join(
            f"{k}={v}" for k, v in fam["verdicts"].items()),
            file=sys.stderr)
    print(json.dumps(doc, indent=2))
    if args.out_json:
        with open(args.out_json, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    return 0 if doc["ok"] else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--matrix", action="store_true",
                    help="benchmark the N-party federation matrix "
                         "(protocol.federation) instead of the "
                         "two-party arms: cells/s, ε at the "
                         "release-reuse optimum vs naive per-cell, "
                         "bit-identity to independent runs, the "
                         "kill/resume verdict, and the interleaved "
                         "traced-vs-untraced A/B (≤3% overhead, one "
                         "plan-derived trace ID)")
    ap.add_argument("--sessions", type=int, default=8,
                    help="timed sessions per clean arm (the fault arm "
                         "runs half, floor 2)")
    ap.add_argument("--n", type=int, default=2000)
    ap.add_argument("--eps1", type=float, default=1.0)
    ap.add_argument("--eps2", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=777)
    ap.add_argument("--fault-drop", dest="fault_drop", type=float,
                    default=0.10)
    ap.add_argument("--fault-delay-ms", dest="fault_delay_ms",
                    type=float, default=50.0)
    ap.add_argument("--fault-duplicate", dest="fault_duplicate",
                    type=float, default=0.05)
    ap.add_argument("--out-json", dest="out_json", default=None)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.matrix:
        return run_matrix(args)
    import jax
    import numpy as np

    from dpcorr.models.estimators.registry import serving_entry
    from dpcorr.protocol import ProtocolSpec
    from dpcorr.utils import rng

    r = np.random.default_rng(args.seed)
    xy = r.multivariate_normal([0.0, 0.0], [[1.0, 0.6], [0.6, 1.0]],
                               size=args.n)
    x = np.asarray(xy[:, 0], np.float32)
    y = np.asarray(xy[:, 1], np.float32)
    fault = {"drop": args.fault_drop,
             "delay_s": args.fault_delay_ms / 1000.0,
             "duplicate": args.fault_duplicate}
    fault_sessions = max(2, args.sessions // 2)

    doc = {"config": {"n": args.n, "eps": [args.eps1, args.eps2],
                      "seed": args.seed, "sessions": args.sessions,
                      "fault": fault,
                      "fault_sessions": fault_sessions},
           "families": {}, "ok": True}
    for family in FAMILIES:
        spec = ProtocolSpec(family=family, n=args.n, eps1=args.eps1,
                            eps2=args.eps2, seed=args.seed)
        mono = jax.jit(serving_entry(family, args.eps1, args.eps2,
                                     0.05, True))(
            rng.master_key(args.seed), x, y)
        mono_bits = tuple(float(np.float32(v)) for v in mono)
        fam = {"monolithic_bits": list(mono_bits), "arms": {}}
        with tempfile.TemporaryDirectory() as td:
            arms = [("inproc", None, args.sessions, 10.0, None),
                    ("tcp", None, args.sessions, 10.0, None),
                    ("tcp_fault", fault, fault_sessions, 0.5, td)]
            for arm, f, n_sess, to, tdir in arms:
                fam["arms"][arm] = _run_arm(arm, spec, x, y, f, n_sess,
                                            to, tdir)
            fam["audit"] = _audit_arm(spec, x, y, td)
        bits = {a: tuple(fam["arms"][a]["bits"]) for a in fam["arms"]}
        fam["verdicts"] = {
            "arms_bit_identical": len(set(bits.values())) == 1,
            "matches_monolithic": bits["inproc"] == mono_bits,
            "chaos_retried": fam["arms"]["tcp_fault"]
                                ["total_retries"] > 0,
            "audit_ok": all(fam["audit"][r]["scan_ok"]
                            and fam["audit"][r]["balance_ok"]
                            for r in ("x", "y")),
        }
        for a in fam["arms"]:
            fam["arms"][a]["bits"] = list(fam["arms"][a]["bits"])
        if not all(fam["verdicts"].values()):
            doc["ok"] = False
        doc["families"][family] = fam
        print(f"{family}: " + " ".join(
            f"{k}={v}" for k, v in fam["verdicts"].items()),
            file=sys.stderr)

    print(json.dumps(doc, indent=2))
    if args.out_json:
        with open(args.out_json, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
