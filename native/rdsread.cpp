// Native RDS reader: the framework's data-loader fast path.
//
// Parses R serialization format (XDR v2/v3, the `saveRDS` output consumed at
// real-data-sims.R:13 in the reference) straight from the gzip stream into
// columnar buffers, with the same output contract as the portable Python
// implementation in dpcorr/io/rds_py.py:
//   - numeric/logical/factor columns -> double arrays, NA -> NaN
//   - string columns -> one '\0'-joined blob + offsets (-1 = NA)
//   - factor levels, haven value-labels and variable labels preserved.
//
// Exposed as a C API (loaded via ctypes from dpcorr/io/rds.py); no Python.h
// dependency so it builds with nothing but g++ and zlib.

#include <zlib.h>

#include <cstdint>
#include <cstring>
#include <cmath>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace {

// ----- SEXP type codes ------------------------------------------------------
enum {
  NILSXP = 0, SYMSXP = 1, LISTSXP = 2, LANGSXP = 6, CHARSXP = 9,
  LGLSXP = 10, INTSXP = 13, REALSXP = 14, CPLXSXP = 15, STRSXP = 16,
  VECSXP = 19, EXPRSXP = 20, RAWSXP = 24,
  ALTREP_SXP = 238, ATTRLISTSXP = 239, ATTRLANGSXP = 240,
  BASEENV_SXP = 241, EMPTYENV_SXP = 242, PERSISTSXP = 247,
  PACKAGESXP = 248, NAMESPACESXP = 249, GLOBALENV_SXP = 253,
  NILVALUE_SXP = 254, REFSXP = 255,
};

constexpr int32_t kNaInt = INT32_MIN;
// R's NA_real_ is itself a NaN (payload 1954), so REALSXP bytes pass through
// unchanged; only integer/logical NA needs explicit NaN mapping.

// ----- generic SEXP tree ----------------------------------------------------
struct Sexp;
using SexpPtr = std::shared_ptr<Sexp>;

struct Sexp {
  int type = NILSXP;
  std::vector<double> reals;                   // REALSXP; INT/LGL promoted
  std::vector<std::string> strs;               // STRSXP values
  std::vector<uint8_t> str_na;                 // STRSXP NA mask
  std::vector<SexpPtr> vec;                    // VECSXP elements
  std::string sym;                             // SYMSXP name
  std::vector<std::pair<std::string, SexpPtr>> attrs;

  const Sexp* attr(const char* name) const {
    for (const auto& kv : attrs)
      if (kv.first == name) return kv.second.get();
    return nullptr;
  }
  bool has_class(const char* cls) const {
    const Sexp* c = attr("class");
    if (!c) return false;
    for (const auto& s : c->strs)
      if (s == cls) return true;
    return false;
  }
};

// ----- stream reader --------------------------------------------------------
class Reader {
 public:
  Reader(const uint8_t* buf, size_t len) : buf_(buf), len_(len) {}

  void header() {
    if (len_ < 2 || buf_[0] != 'X' || buf_[1] != '\n')
      throw std::runtime_error("unsupported RDS encoding (need XDR 'X\\n')");
    pos_ = 2;
    int version = i32();
    i32();  // writer version
    i32();  // min reader version
    if (version >= 3) {
      int n = i32();
      take(n);  // native encoding name; payload CHARSXPs carry their own flag
    } else if (version != 2) {
      throw std::runtime_error("unsupported RDS version");
    }
  }

  SexpPtr item() {
    int32_t flags = i32();
    int type = flags & 0xFF;
    bool has_attr = flags & 0x200;
    bool has_tag = flags & 0x400;

    switch (type) {
      case NILVALUE_SXP:
      case NILSXP:
      case GLOBALENV_SXP:
      case EMPTYENV_SXP:
      case BASEENV_SXP:
        return mk(NILSXP);
      case REFSXP: {
        int idx = flags >> 8;
        if (idx == 0) idx = i32();
        if (idx < 1 || (size_t)idx > refs_.size())
          throw std::runtime_error("bad RDS reference index");
        return refs_[idx - 1];
      }
      case SYMSXP: {
        SexpPtr chr = item();
        SexpPtr s = mk(SYMSXP);
        s->sym = chr->strs.empty() ? "" : chr->strs[0];
        refs_.push_back(s);
        return s;
      }
      case NAMESPACESXP:
      case PACKAGESXP:
      case PERSISTSXP: {
        SexpPtr s = mk(type);
        i32();  // InStringVec compatibility zero
        int n = i32();
        for (int j = 0; j < n; ++j) item();  // name strings, discarded
        refs_.push_back(s);
        return s;
      }
      case LISTSXP:
      case LANGSXP:
      case ATTRLISTSXP:
      case ATTRLANGSXP:
        return pairlist(has_attr, has_tag);
      case ALTREP_SXP:
        return altrep();
      case CHARSXP: {
        int32_t n = i32();
        SexpPtr s = mk(STRSXP);
        if (n == -1) {
          s->strs.emplace_back();
          s->str_na.push_back(1);
        } else {
          const uint8_t* p = take(n);
          s->strs.emplace_back(reinterpret_cast<const char*>(p), (size_t)n);
          s->str_na.push_back(0);
        }
        return s;
      }
      default:
        break;
    }

    SexpPtr s = mk(type);
    switch (type) {
      case LGLSXP:
      case INTSXP: {
        int64_t n = length();
        s->reals.resize(n);
        for (int64_t j = 0; j < n; ++j) {
          int32_t v = i32();
          s->reals[j] = (v == kNaInt) ? std::nan("") : (double)v;
        }
        break;
      }
      case REALSXP: {
        int64_t n = length();
        s->reals.resize(n);
        for (int64_t j = 0; j < n; ++j) s->reals[j] = f64();
        break;
      }
      case CPLXSXP: {
        int64_t n = length();
        s->reals.resize(n);  // keep the real part only; unused by tables
        for (int64_t j = 0; j < n; ++j) { s->reals[j] = f64(); f64(); }
        break;
      }
      case RAWSXP: {
        int64_t n = length();
        take(n);
        break;
      }
      case STRSXP: {
        int64_t n = length();
        s->strs.reserve(n);
        s->str_na.reserve(n);
        for (int64_t j = 0; j < n; ++j) {
          SexpPtr c = item();
          s->strs.push_back(std::move(c->strs[0]));
          s->str_na.push_back(c->str_na[0]);
        }
        break;
      }
      case VECSXP:
      case EXPRSXP: {
        int64_t n = length();
        s->vec.reserve(n);
        for (int64_t j = 0; j < n; ++j) s->vec.push_back(item());
        break;
      }
      default:
        throw std::runtime_error("unsupported SEXP type " +
                                 std::to_string(type));
    }
    if (has_attr) read_attrs(*s);
    return s;
  }

 private:
  SexpPtr mk(int type) {
    auto s = std::make_shared<Sexp>();
    s->type = type;
    return s;
  }

  const uint8_t* take(int64_t n) {
    if (pos_ + (size_t)n > len_) throw std::runtime_error("truncated RDS");
    const uint8_t* p = buf_ + pos_;
    pos_ += n;
    return p;
  }
  int32_t i32() {
    const uint8_t* p = take(4);
    return (int32_t)(((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                     ((uint32_t)p[2] << 8) | (uint32_t)p[3]);
  }
  double f64() {
    const uint8_t* p = take(8);
    uint64_t b = 0;
    for (int j = 0; j < 8; ++j) b = (b << 8) | p[j];
    double d;
    std::memcpy(&d, &b, 8);
    return d;
  }
  int64_t length() {
    int32_t n = i32();
    if (n == -1) {
      int64_t hi = i32(), lo = (uint32_t)i32();
      return (hi << 32) + lo;
    }
    return n;
  }

  void read_attrs(Sexp& s) {
    SexpPtr plist = item();
    if (plist->type == LISTSXP) s.attrs = std::move(plist->attrs);
  }

  SexpPtr pairlist(bool has_attr, bool has_tag) {
    SexpPtr s = mk(LISTSXP);
    if (has_attr) read_attrs(*s);  // attrs on the pairlist itself: rare, drop
    while (true) {
      std::string tag;
      if (has_tag) tag = item()->sym;
      s->attrs.emplace_back(std::move(tag), item());
      int32_t flags = i32();
      int nxt = flags & 0xFF;
      if (nxt == NILVALUE_SXP || nxt == NILSXP) break;
      if (nxt != LISTSXP && nxt != LANGSXP && nxt != ATTRLISTSXP &&
          nxt != ATTRLANGSXP) {
        pos_ -= 4;
        s->attrs.emplace_back(std::string(), item());
        break;
      }
      if (flags & 0x200) { Sexp scratch; read_attrs(scratch); }
      has_tag = flags & 0x400;
    }
    return s;
  }

  SexpPtr altrep() {
    SexpPtr info = item();
    SexpPtr state = item();
    SexpPtr attr = item();
    std::string cls =
        (info->type == LISTSXP && !info->attrs.empty())
            ? info->attrs[0].second->sym
            : "";
    SexpPtr out;
    if (cls == "compact_intseq" || cls == "compact_realseq") {
      double n = state->reals.at(0), start = state->reals.at(1),
             step = state->reals.at(2);
      out = mk(cls == "compact_intseq" ? INTSXP : REALSXP);
      out->reals.resize((int64_t)n);
      for (int64_t j = 0; j < (int64_t)n; ++j)
        out->reals[j] = start + step * (double)j;
    } else if (cls.rfind("wrap_", 0) == 0) {
      // wrapper state is CONS(wrapped, metadata) — a pairlist; a VECSXP
      // form also exists
      if (state->type == LISTSXP && !state->attrs.empty())
        out = state->attrs[0].second;
      else if (state->type == VECSXP && !state->vec.empty())
        out = state->vec[0];
      else
        out = state;
    } else {
      throw std::runtime_error("unsupported ALTREP class '" + cls + "'");
    }
    if (attr->type == LISTSXP) out->attrs = std::move(attr->attrs);
    return out;
  }

  const uint8_t* buf_;
  size_t len_;
  size_t pos_ = 0;
  std::vector<SexpPtr> refs_;
};

// ----- gzip/zlib/plain file slurp ------------------------------------------
std::vector<uint8_t> slurp(const char* path) {
  gzFile f = gzopen(path, "rb");  // transparently handles uncompressed too
  if (!f) throw std::runtime_error(std::string("cannot open ") + path);
  std::vector<uint8_t> out;
  out.reserve(1 << 22);
  uint8_t chunk[1 << 20];
  int n;
  while ((n = gzread(f, chunk, sizeof(chunk))) > 0)
    out.insert(out.end(), chunk, chunk + n);
  bool bad = n < 0;
  gzclose(f);
  if (bad) throw std::runtime_error("gzip read error");
  return out;
}

// ----- columnar table -------------------------------------------------------
struct Column {
  std::string name;
  std::string kind;  // double | integer | logical | string | factor
  std::vector<double> num;          // numeric values / factor codes
  std::string str_blob;             // '\0'-joined strings
  std::vector<int64_t> str_off;     // offsets into blob, -1 = NA
  std::vector<std::string> levels;
  std::vector<std::string> label_names;
  std::vector<double> label_values;
  std::string var_label;
  bool has_var_label = false;
};

struct Table {
  int64_t nrows = 0;
  std::vector<Column> cols;
  std::string err;
};

Column make_column(const std::string& name, const SexpPtr& c) {
  Column col;
  col.name = name;
  if (const Sexp* lab = c->attr("label")) {
    if (!lab->strs.empty()) {
      col.var_label = lab->strs[0];
      col.has_var_label = true;
    }
  }
  if (const Sexp* labels = c->attr("labels")) {
    if (const Sexp* nm = labels->attr("names"))
      col.label_names = nm->strs;
    col.label_values = labels->reals;
  }
  if (c->has_class("factor")) {
    col.kind = "factor";
    col.num = c->reals;
    if (const Sexp* lv = c->attr("levels")) col.levels = lv->strs;
    return col;
  }
  switch (c->type) {
    case REALSXP: col.kind = "double"; col.num = c->reals; return col;
    case INTSXP: col.kind = "integer"; col.num = c->reals; return col;
    case LGLSXP: col.kind = "logical"; col.num = c->reals; return col;
    case STRSXP: {
      col.kind = "string";
      col.str_off.reserve(c->strs.size());
      for (size_t j = 0; j < c->strs.size(); ++j) {
        if (c->str_na[j]) {
          col.str_off.push_back(-1);
        } else {
          col.str_off.push_back((int64_t)col.str_blob.size());
          col.str_blob += c->strs[j];
          col.str_blob.push_back('\0');
        }
      }
      return col;
    }
    default:
      throw std::runtime_error("column '" + name + "': unsupported type " +
                               std::to_string(c->type));
  }
}

}  // namespace

// ----- C API ----------------------------------------------------------------
extern "C" {

void* rds_read_table(const char* path, char* errbuf, int errlen) {
  auto t = std::make_unique<Table>();
  try {
    std::vector<uint8_t> buf = slurp(path);
    Reader rd(buf.data(), buf.size());
    rd.header();
    SexpPtr root = rd.item();
    if (root->type != VECSXP || !root->has_class("data.frame"))
      throw std::runtime_error("not a data.frame");
    const Sexp* names = root->attr("names");
    if (!names || names->strs.size() != root->vec.size())
      throw std::runtime_error("malformed data.frame names");
    for (size_t j = 0; j < root->vec.size(); ++j)
      t->cols.push_back(make_column(names->strs[j], root->vec[j]));
    if (!t->cols.empty()) {
      const Column& c0 = t->cols[0];
      t->nrows = c0.kind == "string" ? (int64_t)c0.str_off.size()
                                     : (int64_t)c0.num.size();
    }
    return t.release();
  } catch (const std::exception& e) {
    if (errbuf && errlen > 0) {
      std::strncpy(errbuf, e.what(), errlen - 1);
      errbuf[errlen - 1] = '\0';
    }
    return nullptr;
  }
}

int rds_table_ncols(void* h) { return (int)((Table*)h)->cols.size(); }
int64_t rds_table_nrows(void* h) { return ((Table*)h)->nrows; }

const char* rds_col_name(void* h, int j) {
  return ((Table*)h)->cols[j].name.c_str();
}
const char* rds_col_kind(void* h, int j) {
  return ((Table*)h)->cols[j].kind.c_str();
}
const double* rds_col_num(void* h, int j) {
  return ((Table*)h)->cols[j].num.data();
}
int64_t rds_col_num_len(void* h, int j) {
  return (int64_t)((Table*)h)->cols[j].num.size();
}
const char* rds_col_str_blob(void* h, int j, int64_t* blob_len) {
  const Column& c = ((Table*)h)->cols[j];
  if (blob_len) *blob_len = (int64_t)c.str_blob.size();
  return c.str_blob.data();
}
const int64_t* rds_col_str_offsets(void* h, int j, int64_t* n) {
  const Column& c = ((Table*)h)->cols[j];
  if (n) *n = (int64_t)c.str_off.size();
  return c.str_off.data();
}
int rds_col_nlevels(void* h, int j) {
  return (int)((Table*)h)->cols[j].levels.size();
}
const char* rds_col_level(void* h, int j, int k) {
  return ((Table*)h)->cols[j].levels[k].c_str();
}
int rds_col_nlabels(void* h, int j) {
  return (int)((Table*)h)->cols[j].label_values.size();
}
const char* rds_col_label_name(void* h, int j, int k) {
  const Column& c = ((Table*)h)->cols[j];
  return k < (int)c.label_names.size() ? c.label_names[k].c_str() : "";
}
double rds_col_label_value(void* h, int j, int k) {
  return ((Table*)h)->cols[j].label_values[k];
}
const char* rds_col_var_label(void* h, int j) {
  const Column& c = ((Table*)h)->cols[j];
  return c.has_var_label ? c.var_label.c_str() : nullptr;
}
void rds_table_free(void* h) { delete (Table*)h; }

}  // extern "C"
