# One-command proof of the reticulate seam (docs/R_BRIDGE.md).
#
# The reference's only process boundary is the mclapply fan-out over
# design rows (vert-cor.R:534-554). r/backend.R swaps that seam for the
# dpcorr TPU backend via reticulate; this script proves the marshalling
# round trip in any environment that has R + reticulate + this repo:
#
#   Rscript r/validate_bridge.R          # CPU JAX is fine
#
# It runs the fixed 4-point grid TWICE —
#   (a) through reticulate:  run_grid_backend(..., backend = "tpu")
#   (b) through a subprocess: python r/validate_bridge_helper.py, whose
#       output comes back as detail_all.rds via this repo's own RDS writer
# — and diffs the two frames cell by cell. Both sides are the identical
# computation (same seeds, same kernels), so ANY difference is a
# marshalling defect: type coercion, row reordering, precision loss, NA
# mangling. It finishes by pushing the bridge frame through the
# reference's grouped-summary recipe (vert-cor.R:575-597).

# run from the repo root: Rscript r/validate_bridge.R
source(file.path("r", "backend.R"))

design_df <- expand.grid(n = c(400L, 800L), rho = c(0.2, 0.6))
design_df <- design_df[order(design_df$n, design_df$rho), ]
design_df$eps1 <- 1.0
design_df$eps2 <- 1.0
B <- 16L
SEED <- 2025L

message("== (a) 4-point grid through reticulate (backend='tpu') ==")
bridge_df <- run_grid_backend(design_df, B = B, seed = SEED,
                              backend = "tpu", py_backend = "bucketed")
stopifnot(nrow(bridge_df) == nrow(design_df) * B)

message("== (b) same grid via subprocess -> detail_all.rds ==")
rds_path <- tempfile(fileext = ".rds")
helper <- file.path("r", "validate_bridge_helper.py")
rc <- system2(Sys.getenv("RETICULATE_PYTHON", "python"),
              c(helper, "--out", shQuote(rds_path)))
stopifnot(rc == 0L)
subproc_df <- readRDS(rds_path)

message("== diff ==")
stopifnot(identical(dim(bridge_df), dim(subproc_df)))
stopifnot(identical(sort(names(bridge_df)), sort(names(subproc_df))))
subproc_df <- subproc_df[names(bridge_df)]
max_abs_diff <- 0
for (col in names(bridge_df)) {
  a <- bridge_df[[col]]
  b <- subproc_df[[col]]
  if (is.numeric(a)) {
    # NA placement must agree BEFORE the numeric diff — an NA-vs-value
    # mismatch is exactly the marshalling defect class this script exists
    # to catch, and na.rm would silently drop it
    stopifnot(identical(is.na(a), is.na(b)))
    live <- !is.na(a)
    d <- if (any(live)) {
      max(abs(as.numeric(a[live]) - as.numeric(b[live])))
    } else 0
    max_abs_diff <- max(max_abs_diff, d)
    if (d != 0) message(sprintf("  col %-12s max |diff| = %.3g", col, d))
  } else {
    stopifnot(identical(as.character(a), as.character(b)))
  }
}
stopifnot(max_abs_diff == 0)  # bit-identity: same computation both ways

message("== reference summary recipe on the bridge frame ==")
# vert-cor.R:575-597 shape: grouped coverage / mse by design cell
agg <- aggregate(cbind(ni_cover, int_cover) ~ n + rho_true + eps1 + eps2,
                 data = bridge_df, FUN = mean)
print(agg)
stopifnot(all(agg$ni_cover >= 0 & agg$ni_cover <= 1))

message("BRIDGE VALIDATION PASSED: reticulate round trip is bit-exact")
