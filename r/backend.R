# R front-end for the TPU backend (SURVEY.md §7 step 6).
#
# The reference fans its design grid out with parallel::mclapply
# (vert-cor.R:534-554, ver-cor-subG.R:271-296). This shim wraps that exact
# seam with a `backend=` switch:
#
#   source("r/backend.R")
#   detail_all <- run_grid_backend(design_df, run_row_fun, B = 250,
#                                  backend = "tpu")     # or "mclapply"
#
# backend = "mclapply" reproduces the reference behavior verbatim (fork on
# Unix, serial on Windows). backend = "tpu" ships the design rows to the
# dpcorr JAX backend via reticulate and returns the same metadata-joined
# replicate-level data.frame the reference builds at vert-cor.R:557-568, so
# downstream data.table summaries and ggplot figures run unchanged.
#
# Requires: install.packages("reticulate"); a Python env with dpcorr on
# PYTHONPATH (reticulate::use_python(...) or RETICULATE_PYTHON).

run_grid_backend <- function(design_df, run_row_fun = NULL, B = 250,
                             seed = 2025,
                             backend = c("tpu", "mclapply"),
                             dgp = "gaussian", use_subG = FALSE,
                             alpha = 0.05, normalise = TRUE,
                             py_backend = "bucketed",
                             fused = "off",
                             bucket_merge = "off",
                             mc_cores = max(1L, parallel::detectCores() - 1L)) {
  backend <- match.arg(backend)

  if (backend == "mclapply") {
    # The reference's own path (vert-cor.R:513-554), unchanged.
    stopifnot(is.function(run_row_fun))
    runner <- if (.Platform$OS.type == "windows") {
      function(i) run_row_fun(design_df[i, ], seed = 1e6 + i)
    } else {
      NULL
    }
    results <- if (.Platform$OS.type == "windows") {
      lapply(seq_len(nrow(design_df)), runner)
    } else {
      parallel::mclapply(seq_len(nrow(design_df)), function(i) {
        run_row_fun(design_df[i, ], seed = 1e6 + i)
      }, mc.cores = mc_cores)
    }
    return(results)
  }

  # backend == "tpu": one call across the whole grid; replications are
  # vmapped/sharded on-device instead of forked across host cores.
  if (!requireNamespace("reticulate", quietly = TRUE)) {
    stop("backend='tpu' needs the reticulate package")
  }
  bridge <- reticulate::import("dpcorr.rbridge")
  rows <- lapply(seq_len(nrow(design_df)), function(i) {
    as.list(design_df[i, c("n", "rho", "eps1", "eps2")])
  })
  # py_backend = "bucketed" is the grid fast path (one compiled kernel per
  # (n, eps) shape bucket); results are bit-identical to "local" per point.
  # fused = "auto" additionally runs eligible buckets through the fused
  # Pallas TPU kernels (different PRNG stream family; statistically
  # identical, measured 4.5x end-to-end on the v1 grid).
  # bucket_merge = "eps" merges subG compile buckets across eps-pairs
  # (one kernel per n; statistically identical, separate resume stamps).
  detail <- bridge$run_design_rows(rows, b = as.integer(B),
                                   seed = as.integer(seed), dgp = dgp,
                                   use_subg = use_subG, alpha = alpha,
                                   normalise = normalise,
                                   backend = py_backend,
                                   fused = fused,
                                   bucket_merge = bucket_merge)
  as.data.frame(detail)
}

# HRS ε-sweep through the same backend (real-data-sims.R:342-448 seam).
run_hrs_sweep_backend <- function(eps_grid = seq(0.25, 2.5, by = 0.1),
                                  R = 200, seed = 2025) {
  bridge <- reticulate::import("dpcorr.rbridge")
  as.data.frame(bridge$run_hrs_sweep(eps_grid, reps = as.integer(R),
                                     seed = as.integer(seed)))
}
