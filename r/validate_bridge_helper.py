"""Python half of the R-bridge validation (r/validate_bridge.R).

Runs the fixed 4-point validation grid through ``dpcorr.rbridge`` — the
same function the reticulate path calls — and writes the detail frame as
``detail_all.rds``. The R script readRDS()es this file and diffs it
against the frame it received through reticulate: any marshalling defect
(type coercion, row reordering, NA mangling) shows up as a non-empty
diff, because both sides are the identical computation
(vert-cor.R:534-554 seam; SURVEY.md §7 step 6).

tests/test_rbridge.py runs this helper directly, so the Python half is
executed evidence even in images without an R runtime.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: The validation grid (2n x 2rho x one eps-pair) and rep count. Small
#: enough for seconds on CPU JAX; shared verbatim with validate_bridge.R.
ROWS = [{"n": 400, "rho": 0.2, "eps1": 1.0, "eps2": 1.0},
        {"n": 400, "rho": 0.6, "eps1": 1.0, "eps2": 1.0},
        {"n": 800, "rho": 0.2, "eps1": 1.0, "eps2": 1.0},
        {"n": 800, "rho": 0.6, "eps1": 1.0, "eps2": 1.0}]
B = 16
SEED = 2025


def run_validation_grid(backend: str = "bucketed"):
    from dpcorr import rbridge

    return rbridge.run_design_rows(ROWS, b=B, seed=SEED, backend=backend)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True, help="detail_all.rds path")
    ap.add_argument("--backend", default="bucketed")
    ap.add_argument("--platform", default="cpu",
                    help="JAX platform (the site hook ignores "
                         "JAX_PLATFORMS env; '' keeps the default)")
    args = ap.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from dpcorr.io.rds_write import write_rds_frame

    detail = run_validation_grid(args.backend)
    write_rds_frame(args.out, detail)
    print(f"wrote {args.out}: {len(detail)} rows x "
          f"{len(detail.columns)} cols")


if __name__ == "__main__":
    main()
