"""Performance observability plane (ISSUE 15): trajectory math, HLO
introspection, block profiler, recompile-cause attribution, geometry CLI.

Contracts under test:

- trajectory: an injected 0.4× artifact is NAMED (path + ratio) as the
  first regression; series never mix device kinds; malformed / zero /
  parsed-null artifacts become skip notes, never crashes.
- prof: the unprofiled pipeline performs exactly one host fetch per
  ``run()`` and zero profiler syncs; the profiled pipeline still
  performs exactly one *fetch* (cadence syncs are accounted separately
  in ``dpcorr_prof_syncs_total``), at a bounded sync count, and its
  per-run record folds the transfer-counter deltas.
- hlo: compile records round-trip through a persisted dump, and
  ``diff_dumps`` reports fingerprint / cost / op-count deltas.
- compile: ``dpcorr_compile_recompile_total{cause}`` attributes
  new-signature vs cache-evict vs jit-fallback, surfaces in
  ``ServeStats.snapshot()["recompiles"]`` and the obs console frame.
- geometry: strict cache reads raise on corruption (the CLI's rc=1
  path) where the hot path's lenient loader shrugs.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpcorr import sim
from dpcorr.obs import hlo as hlo_mod
from dpcorr.obs import prof as prof_mod
from dpcorr.obs import trajectory as traj_mod
from dpcorr.obs.metrics import Registry
from dpcorr.obs import transfer as transfer_mod
from dpcorr.utils import compile as compile_mod
from dpcorr.utils import geometry, rng

METRIC = "mc_reps_per_sec_chip_ni_sign_n10k"


def _artifact(path, value, device_kind="cpu", metric=METRIC, **extra):
    doc = {"metric": metric, "value": value, "unit": "reps/sec/chip",
           "detail": {"device_kind": device_kind}}
    doc.update(extra)
    path.write_text(json.dumps(doc))


# ---------------------------------------------------------------- trajectory


class TestTrajectory:
    def test_injected_regression_is_named(self, tmp_path):
        _artifact(tmp_path / "BENCH_r01.json", 100.0)
        _artifact(tmp_path / "BENCH_r02.json", 110.0)
        _artifact(tmp_path / "BENCH_r03.json", 44.0)  # 0.4x of best
        rep = traj_mod.build_report([str(tmp_path)])
        assert len(rep.regressions) == 1
        reg = rep.regressions[0]
        assert reg.path.endswith("BENCH_r03.json")
        assert reg.best_path.endswith("BENCH_r02.json")
        assert reg.ratio == pytest.approx(0.4)
        assert reg.series == ("cpu", METRIC)

    def test_regression_names_first_offender_not_worst(self, tmp_path):
        _artifact(tmp_path / "BENCH_r01.json", 100.0)
        _artifact(tmp_path / "BENCH_r02.json", 80.0)   # first below floor
        _artifact(tmp_path / "BENCH_r03.json", 40.0)   # worse, but later
        rep = traj_mod.build_report([str(tmp_path)])
        assert [os.path.basename(r.path) for r in rep.regressions] == \
            ["BENCH_r02.json"]

    def test_mixed_device_kind_series_isolation(self, tmp_path):
        # a slow CPU round must never regress the fast TPU series
        _artifact(tmp_path / "BENCH_r01.json", 50_000.0, device_kind="tpu")
        _artifact(tmp_path / "BENCH_r02.json", 5_000.0, device_kind="cpu")
        _artifact(tmp_path / "BENCH_r03.json", 4_900.0, device_kind="cpu")
        rep = traj_mod.build_report([str(tmp_path)])
        assert set(rep.series) == {("tpu", METRIC), ("cpu", METRIC)}
        assert rep.regressions == []

    def test_device_kind_derived_from_device_string(self):
        assert traj_mod.derive_device_kind(
            {"device": "TFRT_CPU_0"}, {}) == "cpu"
        assert traj_mod.derive_device_kind(
            {"device": "TPU v5 lite0"}, {}) == "tpu"
        assert traj_mod.derive_device_kind({}, {"device_kind": "cpu"}) \
            == "cpu"
        assert traj_mod.derive_device_kind({}, {}) == "unknown"

    def test_multi_device_count_suffixes_the_series_label(self):
        """device_count > 1 gets its own series label; absent/1 keeps
        the historical bare kind (no series migration)."""
        assert traj_mod.derive_device_kind(
            {"device_kind": "cpu", "device_count": 4}, {}) == "cpux4"
        assert traj_mod.derive_device_kind(
            {"device_kind": "cpu", "device_count": 1}, {}) == "cpu"
        assert traj_mod.derive_device_kind(
            {"device_kind": "tpu"}, {}) == "tpu"

    def test_mesh_series_never_folds_into_single_device(self, tmp_path):
        """A 4-device sharded round must neither regress nor be walked
        against the 1-device series of the same metric."""
        _artifact(tmp_path / "BENCH_r01.json", 5_000.0)
        doc = {"metric": METRIC, "value": 900.0, "unit": "reps/sec/chip",
               "detail": {"device_kind": "cpu", "device_count": 4,
                          "mesh": {"rep": 4}}}
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(doc))
        rep = traj_mod.build_report([str(tmp_path)])
        assert set(rep.series) == {("cpu", METRIC), ("cpux4", METRIC)}
        assert rep.regressions == []
        # gate attribution against the 1-device series ignores the
        # mesh point entirely
        assert traj_mod.gate_attribution(
            [str(tmp_path)], metric=METRIC, device_kind="cpu",
            measured_value=4_900.0) is None

    def test_malformed_zero_and_null_tolerance(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text("{not json")
        _artifact(tmp_path / "BENCH_r02.json", 0.0)           # zero value
        _artifact(tmp_path / "BENCH_r03.json", -5.0)          # negative
        (tmp_path / "BENCH_r04.json").write_text(
            json.dumps({"n": 10_000, "cmd": "bench", "rc": 1,
                        "parsed": None}))                      # failed run
        (tmp_path / "BENCH_r05.json").write_text(json.dumps([1, 2]))
        (tmp_path / "BENCH_r06.json").mkdir()                  # a directory
        _artifact(tmp_path / "BENCH_r07.json", 123.0)
        rep = traj_mod.build_report([str(tmp_path)])           # never raises
        assert [p.value for p in rep.points] == [123.0]
        assert len(rep.notes) == 5
        assert any("parsed is null (rc=1)" in n for n in rep.notes)

    def test_wrapper_and_status_shapes(self, tmp_path):
        (tmp_path / "BENCH_r08.json").write_text(json.dumps({
            "n": 10_000, "cmd": "x", "rc": 0,
            "parsed": {"metric": METRIC, "value": 5121.5,
                       "unit": "reps/sec/chip",
                       "detail": {"device": "TFRT_CPU_0"}}}))
        (tmp_path / "MULTICHIP_r02.json").write_text(json.dumps({
            "n_devices": 4, "rc": 0, "ok": False, "skipped": True,
            "tail": "no tpu"}))
        rep = traj_mod.build_report([str(tmp_path)])
        assert len(rep.points) == 1 and len(rep.statuses) == 1
        pt = rep.points[0]
        assert (pt.device_kind, pt.round, pt.value) == ("cpu", 8, 5121.5)
        assert rep.statuses[0].skipped is True

    def test_gate_attribution_names_historical_offender(self, tmp_path):
        _artifact(tmp_path / "BENCH_r01.json", 100.0)
        _artifact(tmp_path / "BENCH_r02.json", 44.0)
        first = traj_mod.gate_attribution(
            [str(tmp_path)], metric=METRIC, device_kind="cpu",
            measured_value=42.0)
        assert first is not None
        assert first["path"].endswith("BENCH_r02.json")  # not this run

    def test_gate_attribution_names_this_run_on_fresh_drop(self, tmp_path):
        _artifact(tmp_path / "BENCH_r01.json", 100.0)
        first = traj_mod.gate_attribution(
            [str(tmp_path)], metric=METRIC, device_kind="cpu",
            measured_value=40.0, measured_path="<this run>")
        assert first is not None and first["path"] == "<this run>"
        clean = traj_mod.gate_attribution(
            [str(tmp_path)], metric=METRIC, device_kind="cpu",
            measured_value=99.0)
        assert clean is None

    def test_render_formats(self, tmp_path):
        _artifact(tmp_path / "BENCH_r01.json", 100.0)
        _artifact(tmp_path / "BENCH_r02.json", 40.0)
        rep = traj_mod.build_report([str(tmp_path)])
        console = traj_mod.render_console(rep)
        assert "REGRESSIONS" in console and "BENCH_r02.json" in console
        doc = json.loads(traj_mod.render_json(rep))
        assert doc["regressions"][0]["path"].endswith("BENCH_r02.json")
        md = traj_mod.render_markdown(rep)
        assert "| round |" in md and "BENCH_r02.json" in md

    def test_cli_trajectory_jax_free_subprocess(self, tmp_path):
        _artifact(tmp_path / "BENCH_r01.json", 100.0)
        _artifact(tmp_path / "BENCH_r02.json", 44.0)
        code = (
            "import json, subprocess, sys\n"
            "import dpcorr.__main__ as m\n"
            "sys.argv = ['dpcorr', 'obs', 'trajectory', '--root', "
            f"{str(tmp_path)!r}, '--format', 'json', '--check']\n"
            "try:\n"
            "    m.main()\n"
            "except SystemExit as e:\n"
            "    assert e.code == 1, e.code\n"
            "assert 'jax' not in sys.modules, 'trajectory imported jax'\n"
        )
        subprocess.run([sys.executable, "-c", code], check=True,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))


# --------------------------------------------------------------------- prof


def _tiny_pipeline(counters, profiler=None, block=8):
    key = rng.master_key(7)
    return sim.RepBlockPipeline(
        lambda k: (jax.random.uniform(k),), 1, key=key,
        block_reps=block, chunk_size=4, family="test-prof",
        counters=counters, profiler=profiler)


class TestBlockProfiler:
    def test_unprofiled_run_single_fetch_zero_prof_syncs(self):
        reg = Registry()
        counters = transfer_mod.TransferCounters(registry=reg)
        prof = prof_mod.BlockProfiler(registry=reg)  # exists, NOT attached
        pipe = _tiny_pipeline(counters)
        before = counters.snapshot()
        pipe.run(6, start_block=0)
        diff = transfer_mod.diff(counters.snapshot(), before)
        assert diff["fetches"] == 1
        assert int(prof.syncs_total.value()) == 0

    def test_profiled_run_bounded_syncs_not_counted_as_fetches(self,
                                                               tmp_path):
        reg = Registry()
        counters = transfer_mod.TransferCounters(registry=reg)
        art = tmp_path / "profile.json"
        prof = prof_mod.BlockProfiler(cadence=2, registry=reg,
                                      artifact_path=str(art))
        pipe = _tiny_pipeline(counters, profiler=prof)
        before = counters.snapshot()
        pipe.run(6, start_block=0)
        diff = transfer_mod.diff(counters.snapshot(), before)
        assert diff["fetches"] == 1  # profiler syncs are NOT fetches
        assert int(prof.syncs_total.value()) == 3  # blocks 1,3,5 at cadence 2
        data = prof_mod.read_profile(str(art))
        (run,) = data["runs"]
        assert run["sync_count"] == 3 and run["n_blocks"] == 6
        assert len(run["samples"]) == 3
        assert sum(s["blocks"] for s in run["samples"]) <= 6
        assert run["transfer"]["fetches"] == 1
        assert run["reps_per_sec"] > 0

    def test_auto_cadence_bounds_sync_count(self):
        reg = Registry()
        prof = prof_mod.BlockProfiler(max_syncs=4, registry=reg)
        state = prof.run_start(family="t", block_reps=8, n_blocks=100)
        assert state["cadence"] == 25  # 100 blocks / 4 syncs

    def test_phase_metrics_and_module_noop(self):
        reg = Registry()
        prof = prof_mod.BlockProfiler(registry=reg)
        with prof.phase("grid.dispatch", buckets=3):
            pass
        assert prof.phase_seconds.value(phase="grid.dispatch") >= 0.0
        assert prof.as_artifact()["phases"][0]["name"] == "grid.dispatch"
        # module-level helpers no-op when nothing is active
        prof_mod.activate(None)
        with prof_mod.phase("anything"):
            pass
        prof_mod.note_phase("anything", 1.0)
        prof_mod.activate(prof)
        try:
            prof_mod.note_phase("armed", 0.5)
            assert prof.phase_seconds.value(phase="armed") == 0.5
        finally:
            prof_mod.activate(None)


# ---------------------------------------------------------------------- hlo


class TestHlo:
    def test_record_dump_and_diff(self, tmp_path):
        jitted = jax.jit(lambda x: jnp.sin(x) + 1.0)
        compiled = jitted.lower(
            jax.ShapeDtypeStruct((64,), jnp.float32)).compile()
        store = hlo_mod.HloStore()
        rec = store.record({"kernel": "k", "n": 64}, compiled,
                           seconds=0.1, cause="new-signature")
        assert rec["fingerprint"]
        assert rec["ops"]  # optimized HLO has at least one instruction
        a_path = tmp_path / "a.json"
        store.dump(str(a_path))
        sigs_a = hlo_mod.load_dump(str(a_path))
        assert list(sigs_a.values())[0]["signature"]["n"] == 64

        # same signature, different program → fingerprint/cost delta
        jitted2 = jax.jit(lambda x: jnp.sin(jnp.cos(x)) * 2.0 + 1.0)
        compiled2 = jitted2.lower(
            jax.ShapeDtypeStruct((64,), jnp.float32)).compile()
        store2 = hlo_mod.HloStore()
        store2.record({"kernel": "k", "n": 64}, compiled2,
                      seconds=0.1, cause="new-signature")
        b_path = tmp_path / "b.json"
        store2.dump(str(b_path))
        diff = hlo_mod.diff_dumps(sigs_a, hlo_mod.load_dump(str(b_path)))
        assert diff["added"] == [] and diff["removed"] == []
        (changed,) = diff["changed"]
        assert "fingerprint" in changed
        rendered = hlo_mod.render_diff(diff)
        assert "fingerprint" in rendered and "kernel=k" in changed["label"]

    def test_diff_added_removed(self):
        a = {"k1": {"signature": {"n": 1}, "fingerprint": "x",
                    "cost": {}, "memory": {}, "ops": {}}}
        b = {"k2": {"signature": {"n": 2}, "fingerprint": "y",
                    "cost": {}, "memory": {}, "ops": {}}}
        diff = hlo_mod.diff_dumps(a, b)
        assert diff["added"][0]["signature"] == {"n": 2}
        assert diff["removed"][0]["signature"] == {"n": 1}

    def test_load_dump_rejects_wrong_kind(self, tmp_path):
        p = tmp_path / "x.json"
        p.write_text(json.dumps({"kind": "other"}))
        with pytest.raises(ValueError):
            hlo_mod.load_dump(str(p))

    def test_op_histogram_marks_layout_ops(self):
        text = ("ENTRY %main {\n"
                "  %p0 = f32[64]{0} parameter(0)\n"
                "  %copy.1 = f32[64]{0} copy(%p0)\n"
                "  %transpose.2 = f32[64]{0} transpose(%copy.1)\n"
                "  %fusion.3 = f32[64]{0} fusion(%transpose.2), kind=kLoop\n"
                "}\n")
        hist = hlo_mod.op_histogram(text)
        assert hist["copy"] == 1 and hist["transpose"] == 1
        assert hist["fusion"] == 1

    def test_aot_compile_records_into_default_store(self):
        before = len(hlo_mod.default_store())
        jitted = jax.jit(lambda x: x * 3.0)
        fn, ok = compile_mod.aot_compile(
            jitted, (jax.ShapeDtypeStruct((8,), jnp.float32),),
            signature={"kernel": "store-probe", "n": 8},
            observer=compile_mod.CompileObserver(registry=Registry()))
        assert ok
        recs = hlo_mod.default_store().records()
        assert len(recs) >= before
        assert any(r["signature"].get("kernel") == "store-probe"
                   for r in recs.values())


# ---------------------------------------------------- recompile attribution


class TestRecompileCauses:
    def test_new_signature_then_evict_then_fallback(self):
        reg = Registry()
        obs = compile_mod.CompileObserver(registry=reg)
        jitted = jax.jit(lambda x: x + 1.0)
        aval = (jax.ShapeDtypeStruct((4,), jnp.float32),)
        sig = {"kernel": "t", "n": 4}
        compile_mod.aot_compile(jitted, aval, signature=sig, observer=obs)
        assert int(obs.recompiles.value(cause="new-signature")) == 1
        # the cache dropped the entry; the re-compile is attributed
        obs.note_evicted(compile_mod.signature_key(sig))
        compile_mod.aot_compile(jitted, aval, signature=sig, observer=obs)
        assert int(obs.recompiles.value(cause="cache-evict")) == 1

        class _Broken:
            def lower(self, *a):
                raise RuntimeError("no lowering")

        fn, ok = compile_mod.aot_compile(_Broken(), aval,
                                         signature={"kernel": "b"},
                                         observer=obs)
        assert not ok
        assert int(obs.recompiles.value(cause="jit-fallback")) == 1

    def test_repeat_compile_without_evict_marker_is_cache_evict(self):
        # same observer seeing the same signature again can only mean
        # its consumer lost the entry — attributed to eviction
        reg = Registry()
        obs = compile_mod.CompileObserver(registry=reg)
        key = compile_mod.signature_key({"kernel": "r"})
        assert obs.classify(key, True) == "new-signature"
        assert obs.classify(key, True) == "cache-evict"

    def test_stats_snapshot_and_console_surface_recompiles(self):
        from dpcorr.obs.console import render_frame
        from dpcorr.serve.stats import ServeStats

        stats = ServeStats()
        obs = compile_mod.CompileObserver(registry=stats.registry)
        obs.classify(compile_mod.signature_key({"k": 1}), True)
        obs.classify(compile_mod.signature_key({"k": 1}), True)
        snap = stats.snapshot()
        assert snap["recompiles"] == {"new-signature": 1,
                                      "cache-evict": 1,
                                      "jit-fallback": 0}
        frame = render_frame(snap, "")
        assert "recompiles" in frame and "1 cache-evict" in frame

    def test_snapshot_before_any_compile_is_empty(self):
        from dpcorr.serve.stats import ServeStats

        assert ServeStats().snapshot()["recompiles"] == {}


# ----------------------------------------------------------- geometry CLI


class TestGeometryCli:
    def test_entries_decompose_and_staleness(self):
        state = {"cpu|bench-icdf|n=10000|f32": {
            "chunk_size": 4, "block_reps": 4096, "reps_per_sec": 5121.5,
            "captured_utc": "2026-08-01T00:00:00Z"},
            "weird-key": {"chunk_size": 1}}
        rows = geometry.entries(state, now=1787616000.0)  # > captured
        by_key = {r["key"]: r for r in rows}
        good = by_key["cpu|bench-icdf|n=10000|f32"]
        assert (good["device_kind"], good["family"], good["n"],
                good["dtype"]) == ("cpu", "bench-icdf", "10000", "f32")
        assert good["age_s"] > 0
        assert by_key["weird-key"]["note"] == "unrecognized key shape"

    def test_cache_key_multi_device_axis(self):
        """1-device keys keep the historical 4-part shape (old caches
        stay valid); multi-device keys grow a dev= axis and entries()
        parses both."""
        assert geometry._cache_key("cpu", "f", 100, "f32") == \
            "cpu|f|n=100|f32"
        assert geometry._cache_key("cpu", "f", 100, "f32",
                                   device_count=1) == "cpu|f|n=100|f32"
        k4 = geometry._cache_key("cpu", "f", 100, "f32",
                                 device_count=4, mesh_shape={"rep": 4})
        assert k4 == "cpu|f|n=100|f32|dev=4:rep=4"
        rows = geometry.entries({k4: {"chunk_size": 4, "block_reps": 64,
                                      "reps_per_sec": 1.0}})
        assert rows[0]["devices"] == "4:rep=4"
        assert rows[0]["family"] == "f" and "note" not in rows[0]

    def test_load_strict_raises_where_load_shrugs(self, tmp_path):
        p = tmp_path / "geometry.json"
        p.write_text("{broken")
        assert geometry._load(str(p)) == {}  # hot path: lenient
        with pytest.raises(ValueError):
            geometry.load_strict(str(p))

    def test_cli_rc1_on_corrupt_rc0_on_valid(self, tmp_path):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        bad = tmp_path / "bad.json"
        bad.write_text("{broken")
        r = subprocess.run(
            [sys.executable, "-m", "dpcorr", "obs", "geometry",
             "--path", str(bad)], cwd=repo, capture_output=True, text=True)
        assert r.returncode == 1 and "corrupt" in r.stderr
        good = tmp_path / "good.json"
        good.write_text(json.dumps({"cpu|bench-icdf|n=10000|f32": {
            "chunk_size": 4, "block_reps": 4096, "reps_per_sec": 5000.0,
            "captured_utc": "2026-08-01T00:00:00Z"}}))
        r = subprocess.run(
            [sys.executable, "-m", "dpcorr", "obs", "geometry",
             "--path", str(good), "--json"],
            cwd=repo, capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        doc = json.loads(r.stdout)
        assert doc["entries"][0]["device_kind"] == "cpu"


# --------------------------------------------------- profiler overhead A/B


@pytest.mark.slow
def test_profiler_ab_harness_structure():
    """The rep_pipeline_ab profiler gate end to end on a tiny budget:
    the sync-accounting asserts inside profiler_ab are the invariant;
    the ≤3% verdict itself is asserted with real budgets in CI."""
    import argparse

    from benchmarks.rep_pipeline_ab import profiler_ab
    from dpcorr.obs import transfer as transfer_mod

    args = argparse.Namespace(chunk=4, block=64, rounds=1, budget=0.2)
    counters = transfer_mod.default_counters()
    key = rng.master_key(11)
    section = profiler_ab(args, key, counters)
    assert set(section) >= {"p50_off", "p50_on", "overhead_pct", "ok",
                            "profiler_syncs",
                            "unprofiled_fetches_per_run",
                            "profiled_fetches_per_run"}
    assert section["unprofiled_fetches_per_run"] == 1
    assert section["profiled_fetches_per_run"] == 1
    assert section["profiler_syncs"] >= 1
