"""Overload resilience (ISSUE 8): deadlines, prioritized shedding,
circuit breaker, brownout and the retrying client.

The unifying invariant under test: a request that is REFUSED or SHED —
at admission, in the queue, or at shutdown — consumes zero ε. Either it
was never charged (breaker / brownout-floor refusals run before the
ledger) or its charge was reversed before any kernel launched (deadline
expiry, priority eviction, close-drain), and the audit trail replays to
the same balances the ledger holds. The retrying client layers on top:
one idempotency key across attempts makes retries charge-once and
byte-identical.
"""

import threading
import time

import numpy as np
import pytest

from dpcorr import chaos
from dpcorr.models.estimators.registry import serving_entry
from dpcorr.obs import audit as obs_audit
from dpcorr.serve import (
    BrownoutController,
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExpiredError,
    DpcorrServer,
    EstimateRequest,
    InProcessClient,
    RetriableTransportError,
    RetryingClient,
    RetryPolicy,
    ServerOverloadedError,
    pinned_request_key,
)
from dpcorr.serve.request import bucket_key
from dpcorr.utils import rng


def _mk_req(n=96, family="ni_sign", seed=None, i=0, **kw):
    rs = np.random.RandomState(100 + i)
    return EstimateRequest(family, rs.randn(n).astype(np.float32),
                          rs.randn(n).astype(np.float32),
                          1.0, 0.5, seed=seed, **kw)


@pytest.fixture(autouse=True)
def _clean_faults():
    chaos.clear_faults()
    yield
    chaos.clear_faults()


class _Clock:
    """Scripted monotonic clock for the state-machine units."""

    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _assert_replay_matches(events, ledger):
    """The acceptance identity: a jax-free fold over the audit trail
    reproduces the ledger's per-party balances exactly."""
    spent = obs_audit.replay(events)
    parties = ledger.snapshot()["parties"]
    assert set(spent) == set(parties)
    for p, s in spent.items():
        assert s == parties[p]["spent"]


# ------------------------------------------------------- breaker unit ----

def test_breaker_trips_after_consecutive_failures():
    clk = _Clock()
    cb = CircuitBreaker(fail_threshold=3, reset_after_s=10.0, clock=clk)
    bkey = bucket_key(_mk_req())
    for _ in range(2):
        cb.record_failure(bkey)
    assert cb.state(bkey) == "closed"
    cb.allow(bkey)  # still admitting below the threshold
    cb.record_failure(bkey)
    assert cb.state(bkey) == "open"
    assert cb.any_open()
    with pytest.raises(CircuitOpenError) as ei:
        cb.allow(bkey)
    assert 0.0 < ei.value.retry_after_s <= 10.0


def test_breaker_success_resets_consecutive_count():
    cb = CircuitBreaker(fail_threshold=3, clock=_Clock())
    bkey = bucket_key(_mk_req())
    for _ in range(2):
        cb.record_failure(bkey)
    cb.record_success(bkey)  # non-consecutive failures never trip
    for _ in range(2):
        cb.record_failure(bkey)
    assert cb.state(bkey) == "closed"


def test_breaker_half_open_single_probe_then_close():
    clk = _Clock()
    cb = CircuitBreaker(fail_threshold=1, reset_after_s=5.0, clock=clk)
    bkey = bucket_key(_mk_req())
    cb.record_failure(bkey)
    assert cb.state(bkey) == "open"
    clk.t = 6.0
    cb.allow(bkey)  # cooldown elapsed: this caller is the probe
    assert cb.state(bkey) == "half_open"
    with pytest.raises(CircuitOpenError):
        cb.allow(bkey)  # one probe at a time
    cb.record_success(bkey)
    assert cb.state(bkey) == "closed"
    assert not cb.any_open()
    cb.allow(bkey)  # back to normal admission


def test_breaker_failed_probe_reopens():
    clk = _Clock()
    cb = CircuitBreaker(fail_threshold=1, reset_after_s=5.0, clock=clk)
    bkey = bucket_key(_mk_req())
    cb.record_failure(bkey)
    clk.t = 6.0
    cb.allow(bkey)
    cb.record_failure(bkey)  # the probe failed
    assert cb.state(bkey) == "open"
    with pytest.raises(CircuitOpenError):
        cb.allow(bkey)  # a fresh cooldown started at t=6
    clk.t = 12.0
    cb.allow(bkey)
    assert cb.state(bkey) == "half_open"


def test_breaker_stale_probe_cannot_deadlock_recovery():
    clk = _Clock()
    cb = CircuitBreaker(fail_threshold=1, reset_after_s=5.0, clock=clk)
    bkey = bucket_key(_mk_req())
    cb.record_failure(bkey)
    clk.t = 6.0
    cb.allow(bkey)  # probe admitted ... and its client vanishes
    clk.t = 12.0  # one more cooldown later a new probe is allowed
    cb.allow(bkey)
    assert cb.state(bkey) == "half_open"


def test_breaker_isolates_buckets():
    cb = CircuitBreaker(fail_threshold=1, clock=_Clock())
    sick, healthy = bucket_key(_mk_req(n=96)), bucket_key(_mk_req(n=200))
    cb.record_failure(sick)
    with pytest.raises(CircuitOpenError):
        cb.allow(sick)
    cb.allow(healthy)  # other buckets unaffected
    snap = cb.snapshot()
    assert snap["open"] == 1 and snap["half_open"] == 0
    assert list(snap["tripped_buckets"].values()) == ["open"]


def test_breaker_validation():
    with pytest.raises(ValueError, match="fail_threshold"):
        CircuitBreaker(fail_threshold=0)
    with pytest.raises(ValueError, match="reset_after_s"):
        CircuitBreaker(reset_after_s=0.0)


# ------------------------------------------------------ brownout unit ----

def test_brownout_enters_after_sustained_pressure_only():
    clk = _Clock()
    bo = BrownoutController(queue_frac=0.75, enter_after_s=1.0,
                            exit_after_s=2.0, clock=clk)
    bo.observe(0.9, 0.0)
    assert not bo.active()  # a burst is not sustained pressure
    clk.t = 0.5
    bo.observe(0.9, 0.0)
    assert not bo.active()
    clk.t = 1.1
    bo.observe(0.9, 0.0)
    assert bo.active()


def test_brownout_hysteresis_on_exit():
    clk = _Clock()
    bo = BrownoutController(queue_frac=0.75, enter_after_s=0.0,
                            exit_after_s=2.0, clock=clk)
    bo.observe(0.9, 0.0)
    assert bo.active()
    clk.t = 1.0
    bo.observe(0.1, 0.0)  # calm, but not for long enough
    assert bo.active()
    clk.t = 2.0
    bo.observe(0.9, 0.0)  # pressure returns: the calm window resets
    clk.t = 3.5
    bo.observe(0.1, 0.0)
    assert bo.active()
    clk.t = 6.0
    bo.observe(0.1, 0.0)  # 2.5 s of sustained calm
    assert not bo.active()


def test_brownout_flush_slo_is_a_pressure_signal():
    clk = _Clock()
    bo = BrownoutController(queue_frac=1.0, flush_slo_s=0.1,
                            enter_after_s=0.0, clock=clk)
    bo.observe(0.0, 0.05)
    assert not bo.active()
    bo.observe(0.0, 0.5)  # queue empty but flushes are slow
    assert bo.active()


def test_brownout_validation():
    with pytest.raises(ValueError, match="queue_frac"):
        BrownoutController(queue_frac=1.5)


# ------------------------------------------------- retry policy unit ----

def test_retry_policy_delay_shape():
    import random

    pol = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, multiplier=2.0,
                      jitter=0.5)
    r = random.Random(0)
    for attempt, base in ((1, 0.1), (2, 0.2), (3, 0.4), (7, 1.0)):
        for _ in range(20):
            d = pol.delay_for(attempt, None, r)
            assert 0.5 * base <= d <= 1.5 * base
    # Retry-After floors the jittered backoff — never retry early
    assert pol.delay_for(1, 3.0, r) >= 3.0


def test_retry_policy_validation():
    with pytest.raises(ValueError, match="max_attempts"):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError, match="jitter"):
        RetryPolicy(jitter=1.5)


# -------------------------------------------------- deadline expiry ----

def test_deadline_expiry_refunds_and_audits():
    """A request whose deadline passes while queued resolves to
    DeadlineExpiredError BEFORE any kernel launches; its ε charge is
    reversed and the audit trail carries the refund with its reason —
    a jax-free replay lands on the ledger's own balances."""
    trail = obs_audit.AuditTrail()
    srv = DpcorrServer(budget=1e6, max_delay_s=0.25, shard="off",
                       audit=trail)
    try:
        fut = srv.submit(_mk_req(seed=7, deadline_s=0.01))
        with pytest.raises(DeadlineExpiredError):
            fut.result(timeout=30)
        assert srv.ledger.spent("party-x") == 0.0
        assert srv.ledger.spent("party-y") == 0.0
        snap = srv.stats.snapshot()
        assert snap["shed"]["expired"] == 1
        refunds = [e for e in trail.events() if e["kind"] == "refund"]
        assert len(refunds) == 1
        assert refunds[0]["reason"] == "expired"
        _assert_replay_matches(trail.events(), srv.ledger)
    finally:
        srv.close()


def test_deadline_zero_consumption_is_exact():
    """Exact-binary ε (2.0 + 1.0 per request after the normalise
    release factor) so the refund check is == not ≈."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.25, shard="off")
    try:
        ok = srv.submit(_mk_req(seed=1, i=0))  # same flush window
        with pytest.raises(DeadlineExpiredError):
            srv.submit(_mk_req(seed=2, i=1,
                               deadline_s=1e-9)).result(timeout=30)
        ok.result(timeout=60)  # the live rider still gets served
        assert srv.ledger.spent("party-x") == 2.0
        assert srv.ledger.spent("party-y") == 1.0
    finally:
        srv.close()


def test_request_deadline_validation():
    with pytest.raises(ValueError, match="deadline_s"):
        _mk_req(deadline_s=0.0)
    with pytest.raises(ValueError, match="priority"):
        _mk_req(priority=True)


# ------------------------------------------------ priority eviction ----

def test_priority_eviction_sheds_lowest_rank():
    srv = DpcorrServer(budget=1e6, max_delay_s=30.0, max_queue=2,
                       shard="off")
    try:
        low = srv.submit(_mk_req(seed=1, i=0, priority=-1))
        mid = srv.submit(_mk_req(seed=2, i=1, priority=0))
        urgent = srv.submit(_mk_req(seed=3, i=2, priority=5))
        with pytest.raises(ServerOverloadedError) as ei:
            low.result(timeout=5)
        assert ei.value.retry_after_s is not None
        assert not mid.done() and not urgent.done()
        snap = srv.stats.snapshot()
        assert snap["shed"]["queue_evict"] == 1
        # the victim's charge came back: two admitted requests remain
        # (2.0 ε each on party-x under the normalise release factor)
        assert srv.ledger.spent("party-x") == 4.0
    finally:
        srv.close()


def test_equal_rank_arrival_is_refused_not_evicting():
    """FIFO fairness within a priority class: a newcomer only evicts
    when it STRICTLY outranks the victim."""
    srv = DpcorrServer(budget=1e6, max_delay_s=30.0, max_queue=2,
                       shard="off")
    try:
        futs = [srv.submit(_mk_req(seed=i, i=i)) for i in range(2)]
        with pytest.raises(ServerOverloadedError) as ei:
            srv.submit(_mk_req(seed=9, i=9))
        assert ei.value.retry_after_s is not None
        assert not any(f.done() for f in futs)
        assert srv.stats.requests_refused_overload == 1
        assert srv.ledger.spent("party-x") == 4.0  # refusal refunded
    finally:
        srv.close()


def test_deadline_slack_breaks_priority_ties():
    srv = DpcorrServer(budget=1e6, max_delay_s=30.0, max_queue=2,
                       shard="off")
    try:
        tight = srv.submit(_mk_req(seed=1, i=0, deadline_s=60.0))
        loose = srv.submit(_mk_req(seed=2, i=1, deadline_s=600.0))
        srv.submit(_mk_req(seed=3, i=2, priority=1))
        # within a priority class the LEAST-slack rider is shed first:
        # it is the one most likely to expire unanswered anyway, and
        # evicting it now lets its client retry soonest
        with pytest.raises(ServerOverloadedError):
            tight.result(timeout=5)
        assert not loose.done()
    finally:
        srv.close()


# ------------------------------------------------- estimate timeout ----

def test_estimate_timeout_cancels_and_refunds():
    from concurrent.futures import TimeoutError as FuturesTimeout

    srv = DpcorrServer(budget=1e6, max_delay_s=30.0, shard="off")
    try:
        with pytest.raises((TimeoutError, FuturesTimeout)):
            srv.estimate(_mk_req(seed=1), timeout=0.05)
        assert srv.stats.snapshot()["abandoned"]["cancelled"] == 1
    finally:
        srv.close()
    # the cancelled pending is dropped at drain/claim time and refunded
    assert srv.ledger.spent("party-x") == 0.0


# ------------------------------------------------------- breaker e2e ----

def _fault(spec):
    chaos.install_fault(chaos.fault_from_spec(spec))


def test_breaker_trips_and_recovers_bit_identical():
    """Consecutive injected kernel failures trip the request's bucket
    breaker: admission then fail-fasts with ZERO charge and /readyz
    degrades. After the cooldown the half-open probe heals the bucket
    and the post-recovery answer is bit-identical to the direct
    single-request reference — recovery changed availability, not
    results."""
    import jax

    req = _mk_req(seed=42)
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off",
                       breaker_threshold=2, breaker_reset_s=0.3)
    try:
        # times=4: each failing request traverses the fault point twice
        # (batched-path attempt + unbatched fallback) — 2 whole-request
        # failures, then the plan is spent and the probe can heal
        _fault("point=serve.kernel,mode=fail,times=4")
        for i in range(2):
            with pytest.raises(chaos.SimulatedFault):
                # distinct data per attempt: failures must be
                # consecutive in the BUCKET, not retries of one request
                srv.estimate(_mk_req(seed=i, i=i), timeout=30)
        spent_after_failures = srv.ledger.spent("party-x")
        r = srv.readiness()
        assert r["ready"] is False and r["breakers_open"] is True
        with pytest.raises(CircuitOpenError) as ei:
            srv.estimate(req, timeout=30)
        assert ei.value.retry_after_s > 0.0
        # fail-fast means fail-FREE: the refused request never charged
        assert srv.ledger.spent("party-x") == spent_after_failures
        snap = srv.stats_snapshot()
        assert snap["refused"]["breaker"] == 1
        assert snap["breaker"]["open"] == 1
        time.sleep(0.35)  # cooldown: next admission is the probe
        resp = srv.estimate(req, timeout=60)
        assert srv.readiness()["ready"] is True
        assert not srv.breaker.any_open()
        # bit-identity against the plain jitted reference program
        single = serving_entry(req.family, req.eps1, req.eps2,
                               alpha=req.alpha, normalise=req.normalise)
        key = pinned_request_key(rng.master_key(srv.seed), req, req.seed)
        ref = jax.jit(single)(key, req.x, req.y)
        assert resp.rho_hat == float(ref[0])
        assert resp.ci_low == float(ref[1])
        assert resp.ci_high == float(ref[2])
    finally:
        srv.close()


def test_breaker_failures_do_not_leak_charges():
    """A request that EXECUTES and fails keeps its charge (the kernel
    ran; ε was exposed) — but every breaker-refused request after the
    trip is charge-free. The audit replay stays in lockstep with the
    ledger through the whole storm."""
    trail = obs_audit.AuditTrail()
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off",
                       breaker_threshold=2, breaker_reset_s=30.0,
                       audit=trail)
    try:
        _fault("point=serve.kernel,mode=fail")
        for i in range(2):
            with pytest.raises(chaos.SimulatedFault):
                srv.estimate(_mk_req(seed=i, i=i), timeout=30)
        for i in range(5):
            with pytest.raises(CircuitOpenError):
                srv.estimate(_mk_req(seed=10 + i, i=10 + i), timeout=30)
        assert srv.ledger.spent("party-x") == 4.0  # executed failures only
        _assert_replay_matches(trail.events(), srv.ledger)
    finally:
        srv.close()


# --------------------------------------------------- brownout e2e ----

def test_brownout_forces_unbatched_flushes():
    """With the pressure threshold at zero the server is permanently
    browned out: multi-request flushes take the unbatched path."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.05, shard="off",
                       shed_queue_frac=0.0, brownout_enter_s=0.0)
    try:
        futs = [srv.submit(_mk_req(seed=i)) for i in range(4)]
        out = [f.result(timeout=60) for f in futs]
        assert all(not r.batched for r in out)
        assert srv.stats.snapshot()["brownout_active"] is True
    finally:
        srv.close()


def test_brownout_floor_rejects_low_priority_uncharged():
    srv = DpcorrServer(budget=1e6, max_delay_s=30.0, max_queue=64,
                       shard="off", shed_queue_frac=0.0,
                       brownout_enter_s=0.0, brownout_min_priority=0)
    try:
        held = srv.submit(_mk_req(seed=1, i=0))  # arms the pressure signal
        spent = srv.ledger.spent("party-x")
        with pytest.raises(ServerOverloadedError) as ei:
            srv.submit(_mk_req(seed=2, i=1, priority=-1))
        assert ei.value.retry_after_s is not None
        assert srv.ledger.spent("party-x") == spent  # never charged
        snap = srv.stats.snapshot()
        assert snap["refused"]["brownout"] == 1
        assert snap["shed"]["admission"] == 1
        srv.submit(_mk_req(seed=3, i=2, priority=0))  # at the floor: admitted
        assert not held.done()
    finally:
        srv.close()


def test_brownout_gate_observes_pressure_so_it_cannot_latch():
    """The admission gate itself feeds the brownout controller: after
    the queue drains, a lone low-priority arrival must see brownout
    exit (via its own pressure observation) instead of being refused
    by a state nothing else would ever update."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.02, max_queue=2,
                       shard="off", shed_queue_frac=0.5,
                       brownout_enter_s=0.0, brownout_exit_s=0.2,
                       brownout_min_priority=0)
    try:
        futs = [srv.submit(_mk_req(seed=i, i=i)) for i in range(2)]
        assert srv.brownout.active()
        for f in futs:
            f.result(timeout=60)
        time.sleep(0.3)  # the calm window elapses with NO traffic at all
        r = srv.estimate(_mk_req(seed=9, i=9, priority=-1), timeout=30)
        assert np.isfinite(r.rho_hat)
    finally:
        srv.close()


# --------------------------------------------------- retrying client ----

class _Flaky:
    """Client wrapper that injects failures around a real client."""

    def __init__(self, inner, plan):
        self.inner = inner
        self.plan = list(plan)  # per-attempt: None=pass through, exc=raise
        self.lock = threading.Lock()

    def estimate(self, req, timeout=None):
        with self.lock:
            step = self.plan.pop(0) if self.plan else None
        if step is not None:
            if getattr(step, "_after_execute", False):
                # the server DID answer; the response was lost on the
                # wire — the nastiest retry case
                self.inner.estimate(req, timeout=timeout)
            raise step
        return self.inner.estimate(req, timeout=timeout)


def test_retrying_client_recovers_and_counts():
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        flaky = _Flaky(InProcessClient(srv), [
            ServerOverloadedError("shed", retry_after_s=0.01),
            ServerOverloadedError("shed", retry_after_s=0.01),
        ])
        rc = RetryingClient(flaky, RetryPolicy(base_delay_s=0.001),
                            seed=0)
        resp = rc.estimate(_mk_req(seed=5), timeout=30)
        assert np.isfinite(resp.rho_hat)  # a real response landed
        st = rc.stats()
        assert st["attempts"] == 3 and st["successes"] == 1
        assert st["retryable"] == 2 and st["recovered"] == 1
        assert st["retryable:ServerOverloadedError"] == 2
    finally:
        srv.close()


def test_retrying_client_charges_once_for_lost_response():
    """Attempt 1 executes server-side but the response is lost in
    transit; the retry replays the idempotency cache — ONE charge, ONE
    noise draw, byte-identical bytes."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        lost = RetriableTransportError("connection reset mid-response")
        lost._after_execute = True
        rc = RetryingClient(_Flaky(InProcessClient(srv), [lost]),
                            RetryPolicy(base_delay_s=0.001), seed=0)
        req = _mk_req(seed=77)
        resp = rc.estimate(req, timeout=30)
        direct = srv.estimate(req, timeout=30)  # third replay, same bytes
        assert resp == direct
        snap = srv.stats.snapshot()
        assert snap["requests_total"] == 1
        assert snap["idempotent_hits_completed"] == 2
        assert srv.ledger.spent("party-x") == 2.0  # exactly one charge
    finally:
        srv.close()


def test_retrying_client_generates_identity_for_assigned_streams():
    """An assigned-stream request (no seed, no key) has no natural
    retry identity — the client mints one so its retries are
    charge-once too, and distinct logical requests stay distinct."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        lost = RetriableTransportError("reset")
        lost._after_execute = True
        rc = RetryingClient(_Flaky(InProcessClient(srv), [lost]),
                            RetryPolicy(base_delay_s=0.001), seed=0)
        rc.estimate(_mk_req(seed=None), timeout=30)
        assert srv.ledger.spent("party-x") == 2.0  # one charge, one draw
        assert srv.stats.snapshot()["idempotent_hits_completed"] == 1
        # a SECOND logical request gets a fresh identity → fresh draw
        rc.estimate(_mk_req(seed=None), timeout=30)
        assert srv.ledger.spent("party-x") == 4.0
    finally:
        srv.close()


def test_retrying_client_budget_refusal_is_terminal():
    srv = DpcorrServer(budget=0.75, max_delay_s=0.001, shard="off")
    try:
        rc = RetryingClient(InProcessClient(srv),
                            RetryPolicy(base_delay_s=0.001), seed=0)
        from dpcorr.serve import BudgetExceededError
        with pytest.raises(BudgetExceededError):
            rc.estimate(_mk_req(seed=1), timeout=30)
        st = rc.stats()
        assert st == {"attempts": 1, "terminal": 1}  # no retry happened
    finally:
        srv.close()


def test_retrying_client_gives_up_at_deadline_budget():
    sleeps = []
    rc = RetryingClient(
        _Flaky(None, [ServerOverloadedError("full", retry_after_s=10.0)]
               * 10),
        RetryPolicy(max_attempts=10, base_delay_s=0.01, deadline_s=5.0),
        clock=time.monotonic, sleep=sleeps.append, seed=0)
    with pytest.raises(ServerOverloadedError):
        rc.estimate(_mk_req(seed=1), timeout=1)
    st = rc.stats()
    # Retry-After=10 s > the 5 s budget: give up before the first sleep
    assert st["gave_up"] == 1 and st["attempts"] == 1
    assert sleeps == []


def test_retrying_client_honors_retry_after_floor():
    sleeps = []
    clk = _Clock()
    rc = RetryingClient(
        _Flaky(None, [ServerOverloadedError("full", retry_after_s=0.5)]
               * 3),
        RetryPolicy(max_attempts=3, base_delay_s=0.001, deadline_s=60.0),
        clock=clk, sleep=sleeps.append, seed=0)
    with pytest.raises(ServerOverloadedError):
        rc.estimate(_mk_req(seed=1), timeout=1)
    assert len(sleeps) == 2 and all(s >= 0.5 for s in sleeps)


# --------------------------------------------------------- HTTP e2e ----

def test_http_refusal_codes_round_trip():
    """The front end's typed refusal codes (504/503/Retry-After)
    reconstruct the in-process exceptions through HttpEstimateClient —
    so RetryingClient composes identically over the wire."""
    from dpcorr.serve import HttpEstimateClient, make_http_server

    srv = DpcorrServer(budget=1e6, max_delay_s=0.2, shard="off",
                       breaker_threshold=1, breaker_reset_s=30.0)
    httpd = make_http_server(srv, port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client = HttpEstimateClient(f"http://127.0.0.1:{port}",
                                timeout_s=30.0)
    try:
        # 504: deadline expired while queued (charge refunded server-side)
        with pytest.raises(DeadlineExpiredError):
            client.estimate(_mk_req(seed=1, i=0, deadline_s=1e-9))
        assert srv.ledger.spent("party-x") == 0.0
        # 500 (executed fault) → generic retriable transport error;
        # times=2 covers both traversals (batched attempt + fallback)
        # so the request fails outright instead of degrading
        _fault("point=serve.kernel,mode=fail,times=2")
        with pytest.raises(RetriableTransportError):
            client.estimate(_mk_req(seed=2, i=1))
        # ... which tripped the threshold-1 breaker → 503 with Retry-After
        with pytest.raises(CircuitOpenError) as ei:
            client.estimate(_mk_req(seed=3, i=1))
        assert ei.value.retry_after_s >= 1.0  # ceil'd whole seconds
    finally:
        httpd.shutdown()
        srv.close()
