"""Federation observability tests (ISSUE 13): the ε-provenance DAG,
the party obs endpoint, the single plan-derived trace, and the
federation console/SLO surfaces.

The hostile-input contract pinned here: a missing party view, a
tampered charge amount, a re-noised artifact and a truncated
transcript each produce a *named, typed* divergence attributing the
offending party — never a crash — while the clean run proves
exactly-once charging with total spend float-for-float equal to
``FederationPlan.optimal_eps()``.
"""

import glob
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from dpcorr.obs import recorder as obs_recorder
from dpcorr.obs import trace as obs_trace
from dpcorr.obs.audit import AuditTrail
from dpcorr.obs.endpoint import start_obs_server
from dpcorr.obs.fleet import FleetCollector, FleetSnapshot
from dpcorr.obs.metrics import Registry
from dpcorr.obs.provenance import (
    DIVERGENCE_KINDS,
    build_provenance,
    discover_federation,
)
from dpcorr.obs.recorder import FlightRecorder
from dpcorr.protocol.federation import (
    make_federation_parties,
    run_federation_inproc,
)
from dpcorr.protocol.matrix import FederationPlan
from dpcorr.serve.ledger import PrivacyLedger

N = 512


def _plan(eps=1.0, parties=None, n=N, family="ni_sign"):
    return FederationPlan(
        family=family, n=n, eps=eps,
        parties=parties or [("p0", ["a", "b"]), ("p1", ["c"]),
                            ("p2", ["d"])])


def _data(plan, rho=0.6):
    k = plan.k
    cov = np.full((k, k), rho)
    np.fill_diagonal(cov, 1.0)
    xy = np.random.default_rng(plan.seed).multivariate_normal(
        np.zeros(k), cov, size=plan.n)
    return {lab: np.asarray(xy[:, i], np.float32)
            for i, (_owner, lab) in enumerate(plan.columns())}


def _run_recorded(plan, outdir):
    """One clean federation with every record kind on disk; returns
    (transcripts, audits, journals) maps for build_provenance."""
    ledgers = {}
    for name, _cols in plan.parties:
        trail = AuditTrail(os.path.join(outdir, f"audit.{name}.jsonl"))
        ledgers[name] = PrivacyLedger(
            100.0, path=os.path.join(outdir, f"ledger.{name}.json"),
            audit=trail)
    run_federation_inproc(plan, _data(plan), ledgers=ledgers,
                          transcript_dir=outdir, journal_dir=outdir)
    transcripts, journals = {}, {}
    for path in sorted(glob.glob(os.path.join(outdir, "*.jsonl"))):
        base = os.path.basename(path)
        if base.startswith("audit."):
            continue
        transcripts.setdefault(base.split(".")[-2], []).append(path)
    for path in sorted(glob.glob(os.path.join(outdir, "journal.*.json"))):
        journals.setdefault(
            os.path.basename(path).split(".")[1], []).append(path)
    audits = {name: os.path.join(outdir, f"audit.{name}.jsonl")
              for name, _cols in plan.parties}
    return transcripts, audits, journals


@pytest.fixture(scope="module")
def clean_run(tmp_path_factory):
    """One recorded 3-party run shared by the read-only tests (the
    hostile tests mutate *copies* of its files)."""
    outdir = str(tmp_path_factory.mktemp("fedprov"))
    plan = _plan()
    return plan, outdir, _run_recorded(plan, outdir)


def _mutate_transcript(src, dstdir, fn):
    """Copy a transcript applying ``fn(entry_dict) -> entry_dict`` to
    every message line (meta lines pass through)."""
    os.makedirs(dstdir, exist_ok=True)
    dst = os.path.join(dstdir, os.path.basename(src))
    with open(src) as f, open(dst, "w") as out:
        for line in f:
            obj = json.loads(line)
            if "dir" in obj:
                obj = fn(obj)
            out.write(json.dumps(obj) + "\n")
    return dst


# ------------------------------------------------------ clean DAG ----

def test_clean_run_proves_optimum(clean_run):
    plan, _outdir, (transcripts, audits, journals) = clean_run
    prov = build_provenance(plan, transcripts, audits=audits,
                            journals=journals)
    assert prov.ok, prov.divergences
    # float-for-float at the 2fε(k-1) optimum, per party and in total
    assert prov.total_eps == plan.optimal_eps()
    for name, share in plan.party_eps().items():
        assert prov.parties[name]["spent"] == share
    # exactly-once structurally: every wire-charged artifact has
    # exactly one charge edge, at its plan venue's session
    charged_by = {}
    for src, dst, rel in prov.edges:
        if rel == "charged_by":
            charged_by.setdefault(src, []).append(dst)
    for (side, lab), venue in plan.artifact_venues().items():
        aid = f"artifact:{side}:{lab}"
        assert len(charged_by.get(aid, [])) == 1, (aid, charged_by)
    # exports are well-formed
    doc = prov.to_doc()
    json.dumps(doc)
    assert doc["ok"] and doc["eps"]["total"] == plan.optimal_eps()
    dot = prov.to_dot()
    assert dot.startswith("digraph") and "artifact:x:a" in dot
    # the postmortem query walks cell -> round -> artifacts -> charges
    i, j = plan.cells()[-1]
    story = prov.cell_story(i, j)
    assert story["cell"]["venue"] == list(plan.cell_venue(i, j))
    assert story["rounds"] and story["charges"]


def test_four_party_meta_total_eps_exact(tmp_path):
    """The ISSUE's meta-test: a clean 4-party federation's DAG carries
    total ε == FederationPlan.optimal_eps() exactly (not approx)."""
    plan = _plan(eps=1.0, n=256,
                 parties=[("p0", ["a", "b"]), ("p1", ["c"]),
                          ("p2", ["d"]), ("p3", ["e", "f"])])
    transcripts, audits, journals = _run_recorded(plan, str(tmp_path))
    prov = build_provenance(plan, transcripts, audits=audits,
                            journals=journals)
    assert prov.ok, prov.divergences
    assert prov.total_eps == plan.optimal_eps()
    assert sum(1 for _s, _d, rel in prov.edges
               if rel == "charged_by") == len(plan.artifact_venues())


def test_awkward_eps_reassociation_is_not_a_divergence(tmp_path):
    """ε=0.7 makes optimal_eps()'s single multiply differ from the
    charge-by-charge fsum in the last ulp — the DAG's expected total
    is the plan's own per-party arithmetic, so a clean run stays ok."""
    plan = _plan(eps=0.7, n=256)
    transcripts, audits, journals = _run_recorded(plan, str(tmp_path))
    prov = build_provenance(plan, transcripts, audits=audits,
                            journals=journals)
    assert prov.ok, prov.divergences
    import math
    assert prov.total_eps == math.fsum(
        plan.party_eps()[p] for p, _c in plan.parties)
    assert abs(prov.total_eps - plan.optimal_eps()) < 1e-12


# -------------------------------------------------- hostile inputs ----

def _kinds(prov):
    return {d["kind"] for d in prov.divergences}


def test_divergence_kinds_are_closed():
    assert set(DIVERGENCE_KINDS) == {
        "missing-party-view", "truncated-transcript",
        "re-noised-artifact", "double-charged-artifact",
        "tampered-charge", "eps-total-mismatch"}


def test_missing_party_view_named(clean_run):
    plan, _outdir, (transcripts, audits, _journals) = clean_run
    partial = {k: v for k, v in transcripts.items() if k != "p2"}
    prov = build_provenance(plan, partial, audits=audits)
    assert not prov.ok
    assert _kinds(prov) == {"missing-party-view"}
    assert all(d["party"] == "p2" for d in prov.divergences)


def test_tampered_charge_amount_named(clean_run, tmp_path):
    plan, _outdir, (transcripts, audits, _journals) = clean_run

    def halve(entry):
        if entry.get("dir") == "send" and entry.get("eps", 0) > 0:
            entry["eps"] = entry["eps"] / 2
        return entry

    mutated = dict(transcripts)
    mutated["p0"] = [_mutate_transcript(p, str(tmp_path), halve)
                     for p in transcripts["p0"]]
    prov = build_provenance(plan, mutated, audits=audits)
    assert not prov.ok
    assert "tampered-charge" in _kinds(prov)
    bad = [d for d in prov.divergences if d["kind"] == "tampered-charge"]
    assert bad and all(d["party"] == "p0" for d in bad)
    assert all(d.get("charge_id") for d in bad)


def test_tampered_audit_trail_named(clean_run, tmp_path):
    """The durable trail disagreeing with the transcript is attributed
    to the party whose records diverge — and the reconstructed total
    moves off the optimum."""
    plan, _outdir, (transcripts, audits, _journals) = clean_run
    forged = os.path.join(str(tmp_path), "audit.p1.jsonl")
    with open(audits["p1"]) as f, open(forged, "w") as out:
        for line in f:
            ev = json.loads(line)
            if ev.get("kind") == "charge" and ev.get("charges"):
                k = sorted(ev["charges"])[0]
                ev["charges"][k] += 0.25
            out.write(json.dumps(ev) + "\n")
    prov = build_provenance(plan, transcripts,
                            audits={**audits, "p1": forged})
    assert not prov.ok
    assert {"tampered-charge", "eps-total-mismatch"} <= _kinds(prov)
    assert all(d["party"] == "p1" for d in prov.divergences)
    assert prov.total_eps != plan.optimal_eps()


def test_renoised_artifact_names_minority_holder(clean_run, tmp_path):
    plan, _outdir, (transcripts, audits, _journals) = clean_run

    def perturb(entry):
        pay = entry.get("wire", {}).get("payload", {})
        arts = pay.get("artifacts")
        if isinstance(arts, dict) and arts:
            for group in arts.values():
                for leaf in group.values():
                    if isinstance(leaf, dict) and "b64" in leaf:
                        s = leaf["b64"]
                        leaf["b64"] = \
                            ("B" if s[0] != "B" else "C") + s[1:]
                        return entry
        return entry

    mutated = dict(transcripts)
    mutated["p1"] = [_mutate_transcript(transcripts["p1"][0],
                                        str(tmp_path), perturb)]
    prov = build_provenance(plan, mutated, audits=audits)
    assert not prov.ok
    bad = [d for d in prov.divergences
           if d["kind"] == "re-noised-artifact"]
    assert bad and bad[0]["party"] == "p1"
    assert len(bad[0]["variants"]) == 2


def test_truncated_transcript_is_typed_not_a_crash(clean_run, tmp_path):
    plan, _outdir, (transcripts, audits, _journals) = clean_run
    src = transcripts["p2"][0]
    raw = open(src).read()
    cut = os.path.join(str(tmp_path), os.path.basename(src))
    with open(cut, "w") as f:
        f.write(raw[: int(len(raw) * 0.4)])  # mid-line: unparseable tail
    mutated = dict(transcripts)
    mutated["p2"] = [cut if p == src else p for p in transcripts["p2"]]
    prov = build_provenance(plan, mutated, audits=audits)
    assert not prov.ok
    kinds = _kinds(prov)
    assert "truncated-transcript" in kinds
    assert all("p2" in (d["party"] or "") for d in prov.divergences)


def test_double_charged_artifact(clean_run, tmp_path):
    """A replayed charge in a second round — the exactly-once
    violation the DAG exists to catch."""
    plan, _outdir, (transcripts, audits, _journals) = clean_run
    src = transcripts["p0"][0]
    dst = os.path.join(str(tmp_path), os.path.basename(src))
    lines = [json.loads(ln) for ln in open(src)]
    dup = None
    for obj in lines:
        if obj.get("dir") == "send" and obj.get("eps", 0) > 0 \
                and obj.get("wire", {}).get("msg_type") == "release":
            dup = json.loads(json.dumps(obj))
            dup["wire"]["payload"]["round"] = 1
            if "charge_id" in dup:
                dup["charge_id"] = dup["charge_id"] + ":dup"
            break
    assert dup is not None
    with open(dst, "w") as f:
        for obj in lines + [dup]:
            f.write(json.dumps(obj) + "\n")
    mutated = dict(transcripts)
    mutated["p0"] = [dst if p == src else p for p in transcripts["p0"]]
    prov = build_provenance(plan, mutated)
    assert not prov.ok
    assert "double-charged-artifact" in _kinds(prov)


# ---------------------------------------------- single shared trace ----

def test_inproc_federation_is_one_trace(tmp_path):
    spool = str(tmp_path / "spans.jsonl")
    obs_trace.configure(spool)
    try:
        plan = _plan(n=256)
        run_federation_inproc(plan, _data(plan))
    finally:
        obs_trace.configure(None)
    spans = obs_trace.read_spans(spool)
    tids = {s["trace_id"] for s in spans}
    assert tids == {plan.trace_id()}
    names = {s["name"] for s in spans}
    assert {"federation.matrix", "federation.link",
            "federation.round", "federation.cell"} <= names


def test_plan_trace_id_is_deterministic_and_wire_width():
    plan = _plan()
    assert plan.trace_id() == _plan().trace_id()
    assert plan.trace_id() == plan.fed_hash()[:16]
    assert len(plan.trace_id()) == 16  # secrets.token_hex(8) width


# ---------------------------------------------- party obs endpoint ----

def test_obs_endpoint_scrape_and_trigger(tmp_path):
    registry = Registry()
    c = registry.counter("dpcorr_federation_cells_completed_total",
                         "cells", labelnames=("venue",))
    c.inc(7, venue="link")
    stats = {"kind": "federation_party", "party": "p0", "cells_done": 7}
    server, port = start_obs_server(registry, stats_fn=lambda: stats)
    rec = FlightRecorder(str(tmp_path / "dump.json"))
    obs_recorder.install(rec)
    try:
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(f"{base}/stats", timeout=5) as r:
            assert json.loads(r.read()) == stats
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
            assert r.read().decode() == registry.render()
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert json.loads(r.read()) == {"ok": True}
        # trigger: unknown reason refused, federation reasons accepted
        bad = urllib.request.Request(
            f"{base}/obs/trigger", method="POST",
            data=json.dumps({"reason": "nonsense"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(bad, timeout=5)
        assert exc.value.code == 400
        good = urllib.request.Request(
            f"{base}/obs/trigger", method="POST",
            data=json.dumps({
                "reason": "federation_scan_violation",
                "detail": {"party": "p0"}}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(good, timeout=5) as r:
            body = json.loads(r.read())
        assert body["armed"] and body["dumped"]
        assert rec.last_reason == "federation_scan_violation"
    finally:
        obs_recorder.install(None)
        server.shutdown()


def test_federation_trigger_reasons_registered():
    for reason in ("federation_unhandled", "federation_resume_refused",
                   "federation_scan_violation"):
        assert reason in obs_recorder.TRIGGER_REASONS


def test_fleet_collector_scrapes_party_binary_exact(tmp_path):
    """The ISSUE acceptance: a live FleetCollector scrape of a party
    process matches the party's own counters binary-exactly."""
    plan = _plan(n=256)
    parties = make_federation_parties(plan, _data(plan),
                                      transcript_dir=str(tmp_path))
    p0 = parties["p0"]
    server, port = start_obs_server(p0.registry,
                                    stats_fn=p0.stats_snapshot)
    try:
        from dpcorr.protocol.federation import _drive_parties

        _drive_parties(parties)
        snap = FleetCollector(
            {"p0": f"http://127.0.0.1:{port}"}).scrape()
        assert not snap.errors()
        rec = snap.instances["p0"]
        # binary-exact: the scraped exposition IS the registry render
        assert rec["exposition"] == p0.registry.render()
        stats = rec["stats"]
        assert stats["kind"] == "federation_party"
        assert stats["trace_id"] == plan.trace_id()
        assert stats["cells_done"] == len(
            plan.local_cells("p0")) + sum(
            len(r) for p, q in plan.party_links("p0")
            for r in plan.link_rounds(p, q))
        assert stats["eps"]["spent"] == plan.party_eps()["p0"]
        # the merged fleet view carries the per-party series intact
        fams = snap.families()["p0"]
        cells = fams["dpcorr_federation_cells_completed_total"]
        total = sum(v for _s, _l, v in cells.samples)
        assert total == stats["cells_done"]
    finally:
        server.shutdown()


# ----------------------------------------------- console + SLO view ----

def _canned_snapshot(registry, stats):
    return FleetSnapshot({
        "p0": {"url": "http://x", "error": None, "stats": stats,
               "exposition": registry.render()},
        "p1": {"url": "http://y", "error": "URLError: down",
               "stats": None, "exposition": None},
    })


def test_console_federation_frame(clean_run):
    from dpcorr.obs.console import render_federation_frame

    plan, _outdir, _records = clean_run
    registry = Registry()
    registry.counter("dpcorr_federation_rounds_total", "rounds",
                     labelnames=("link", "role")).inc(
        3, link="p0-p1", role="release")
    h = registry.histogram("dpcorr_federation_round_latency_seconds",
                           "rt", buckets=(0.1, 1.0))
    h.observe(0.05)
    registry.counter("dpcorr_federation_release_cache_total", "cache",
                     labelnames=("label", "outcome")).inc(
        2, label="a", outcome="hit")
    stats = {"kind": "federation_party", "instance": "p0",
             "party": "p0", "fed": plan.fed,
             "trace_id": plan.trace_id(), "cells_done": 5,
             "cells_total": 6, "links": ["p0-p1", "p0-p2"],
             "eps": {"spent": 6.0, "share": 6.0}}
    frame = render_federation_frame(_canned_snapshot(registry, stats),
                                    now=0.0)
    assert "p1" in frame and "DOWN" in frame
    assert "5/6" in frame and "6/6" in frame
    assert plan.fed in frame and plan.trace_id() in frame


def test_slo_federation_objectives_page_offending_party():
    from dpcorr.obs.fleet import parse_families
    from dpcorr.obs.slo import (
        BurnRateEngine,
        federation_eps_burn_objectives,
        federation_round_latency_objective,
    )

    plan = _plan()
    lat = federation_round_latency_objective()
    assert lat.histogram == "dpcorr_federation_round_latency_seconds"
    objectives = federation_eps_burn_objectives(plan, makespan_s=100.0)
    assert {o.name for o in objectives} == {
        f"fed-eps-burn-{p}" for p, _c in plan.parties}
    shares = plan.party_eps()
    for o in objectives:
        party = o.name.rsplit("-", 1)[1]
        assert o.eps_per_s == shares[party] / 100.0
        assert o.eps_series == "dpcorr_federation_ledger_spent_eps"

    # p0 spends its whole share in 1/100th of the makespan -> page
    obj = next(o for o in objectives if o.name.endswith("p0"))
    engine = BurnRateEngine([obj], windows=(("page", 1.0, 1.0, 14.4),))

    def fams(spent):
        registry = Registry()
        registry.gauge("dpcorr_federation_ledger_spent_eps", "eps",
                       labelnames=("ledger",)).set(spent, ledger="p0")
        return parse_families(registry.render())

    engine.observe({"p0": fams(0.0)}, at=0.0)
    engine.observe({"p0": fams(6.0)}, at=1.0)
    fired = engine.evaluate(at=1.0)
    assert [(a.instance, a.severity) for a in fired] == [("p0", "page")]


# ------------------------------------------------------ CLI surface ----

def test_cli_provenance_divergence_arms_recorder(clean_run, tmp_path,
                                                 capsys):
    """`dpcorr obs provenance` on divergent records exits 1 AND dumps
    the installed flight recorder with the federation reason —
    satellite (c)'s auto-arming on federation failure paths."""
    import argparse

    from dpcorr.__main__ import cmd_obs_provenance

    plan, outdir, (transcripts, _audits, _journals) = clean_run
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w") as f:
        json.dump({"plan": plan.to_public()}, f)
    # drop one party's transcripts into a partial dir -> divergence
    partial = tmp_path / "partial"
    partial.mkdir()
    for pname, paths in transcripts.items():
        if pname == "p2":
            continue
        for p in paths:
            (partial / os.path.basename(p)).write_text(open(p).read())
    rec = FlightRecorder(str(tmp_path / "dump.json"))
    obs_recorder.install(rec)
    try:
        args = argparse.Namespace(
            plan=plan_path, transcript_dir=str(partial),
            transcript=None, audit=None, journal_dir=None,
            out=str(tmp_path / "prov.json"), dot=None, cell=None,
            json=False)
        with pytest.raises(SystemExit) as exc:
            cmd_obs_provenance(args)
        assert exc.value.code == 1
        assert rec.last_reason == "federation_scan_violation"
    finally:
        obs_recorder.install(None)
    out = capsys.readouterr().out
    assert "missing-party-view" in out and "p2" in out
    doc = json.loads(open(tmp_path / "prov.json").read())
    assert not doc["ok"]


def test_discover_federation_groups_by_filename(clean_run, tmp_path):
    plan, outdir, _records = clean_run
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w") as f:
        json.dump({"plan": plan.to_public()}, f)
    got_plan, transcripts, audits, journals = discover_federation(
        plan_path, transcript_dir=outdir,
        audit_specs=[f"p0={outdir}/audit.p0.jsonl"],
        journal_dir=outdir)
    assert got_plan.fed == plan.fed
    assert set(transcripts) == {"p0", "p1", "p2"}
    assert all(len(v) == 2 for k, v in transcripts.items() if k != "p1")
    assert list(audits) == ["p0"]
    assert set(journals) == {"p0", "p1", "p2"}
    prov = build_provenance(got_plan, transcripts, audits=audits,
                            journals=journals)
    assert prov.ok and prov.total_eps == plan.optimal_eps()
