"""Sharded per-user budget directory (ISSUE 10): WAL-journaled shard
accounting, renewal under a scripted clock, LRU eviction/rehydration,
the four registered crash windows, corrupt-file quarantine, and the
CompositeLedger's one-atomic-charge / one-refund-path contract.

Crash windows here use raise-mode chaos plans in the current thread —
the durable state left behind is byte-identical to a process kill at
the same point (the fsynced WAL line either landed whole or not at
all); the genuine kill-and-restart proof over real processes is the
``dpcorr chaos`` sweep and test_chaos.py.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dpcorr import chaos
from dpcorr.chaos import ChaosPlan, SimulatedCrash
from dpcorr.obs.audit import AuditTrail, read_events, replay
from dpcorr.obs.budget_replay import (
    GLOBAL_KEY,
    USER_PREFIX,
    DirectoryCorruptError,
    apply_wal_entry,
    fold_levels,
    read_user_balances,
)
from dpcorr.serve.budget_dir import (
    BudgetDirectory,
    CompositeLedger,
    RenewalPolicy,
    is_reserved,
    party_view,
    user_view,
)
from dpcorr.serve.ledger import BudgetExceededError, PrivacyLedger
from dpcorr.serve.request import EstimateRequest


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    chaos.clear()
    yield
    chaos.clear()


def _dir(tmp_path, **kw):
    kw.setdefault("shards", 1)
    kw.setdefault("fsync", False)
    return BudgetDirectory(str(tmp_path / "dir"), **kw)


# ------------------------------------------------------ accounting ----
def test_charge_spent_lifetime_headroom(tmp_path):
    d = _dir(tmp_path, user_budget=1.0)
    d.charge("alice", 0.25)
    d.charge("alice", 0.25)
    d.charge("bob", 0.5)
    assert d.spent("alice") == pytest.approx(0.5)
    assert d.lifetime("alice") == pytest.approx(0.5)
    assert d.headroom("alice") == pytest.approx(0.5)
    assert d.spent("bob") == pytest.approx(0.5)
    assert d.spent("nobody") == 0.0
    assert d.headroom("nobody") == 1.0
    c = d.counters()
    assert c["charges"] == 3
    assert c["charged_eps"] == pytest.approx(1.0)


def test_charge_id_dedup_and_refund_forgets(tmp_path):
    d = _dir(tmp_path)
    d.charge("u", 0.25, charge_id="c1")
    d.charge("u", 0.25, charge_id="c1")  # resumed re-run: no-op
    assert d.spent("u") == pytest.approx(0.25)
    assert d.counters()["dedups"] == 1
    d.refund("u", 0.25, charge_id="c1")  # forgets the id
    assert d.spent("u") == 0.0
    d.charge("u", 0.25, charge_id="c1")  # genuinely new charge
    assert d.spent("u") == pytest.approx(0.25)


def test_refund_clamps_at_zero(tmp_path):
    d = _dir(tmp_path)
    d.charge("u", 0.25)
    d.refund("u", 9.0)  # stray refund over-counts, never under-counts
    assert d.spent("u") == 0.0
    assert d.lifetime("u") == 0.0


def test_negative_amounts_refused(tmp_path):
    d = _dir(tmp_path)
    with pytest.raises(ValueError):
        d.charge("u", -0.1)
    with pytest.raises(ValueError):
        d.refund("u", -0.1)


def test_refusal_is_charge_free_and_not_journaled(tmp_path):
    d = _dir(tmp_path, user_budget=0.5)
    d.charge("u", 0.5)  # landing exactly on the cap is admitted
    with pytest.raises(BudgetExceededError) as ei:
        d.charge("u", 0.25)
    assert ei.value.level == "user"
    assert ei.value.party == USER_PREFIX + "u"
    assert d.spent("u") == pytest.approx(0.5)
    assert d.counters()["refusals"] == 1
    d.close()
    # nothing about the refusal reached disk: reopen sees the admitted
    # spend only
    d2 = _dir(tmp_path, user_budget=0.5)
    assert d2.spent("u") == pytest.approx(0.5)


# --------------------------------------------------------- renewal ----
def test_renewal_resets_window_and_carries_burst(tmp_path):
    now = {"t": 1000.0}
    d = _dir(tmp_path, user_budget=0.5,
             renewal=RenewalPolicy(period_s=100.0, burst_cap=0.3),
             clock=lambda: now["t"])
    d.charge("u", 0.2)
    now["t"] = 1100.0  # one period later: window resets, 0.3 unused
    d.charge("u", 0.0)  # zero-ε touch triggers the renewal
    assert d.spent("u") == 0.0
    assert d.headroom("u") == pytest.approx(0.8)  # budget + burst
    assert d.lifetime("u") == pytest.approx(0.2)  # lifetime untouched
    d.charge("u", 0.7)  # admitted only thanks to the burst credit
    now["t"] = 1200.0
    d.charge("u", 0.0)
    # carry = min(cap, budget + burst - spend) = min(0.3, 0.1)
    assert d.headroom("u") == pytest.approx(0.6)
    assert d.counters()["renewals"] == 2


def test_renewal_long_idle_reaches_fixed_point(tmp_path):
    now = {"t": 0.0}
    d = _dir(tmp_path, user_budget=0.5,
             renewal=RenewalPolicy(period_s=100.0, burst_cap=0.3),
             clock=lambda: now["t"])
    d.charge("u", 0.4)
    now["t"] = 100.0 * 50  # 50 idle periods collapse to the fixed point
    d.charge("u", 0.0)
    assert d.spent("u") == 0.0
    assert d.headroom("u") == pytest.approx(0.8)
    assert d.counters()["renewals"] == 1


def test_renewal_survives_reopen(tmp_path):
    now = {"t": 1000.0}
    clock = lambda: now["t"]  # noqa: E731
    d = _dir(tmp_path, user_budget=0.5,
             renewal=RenewalPolicy(period_s=100.0, burst_cap=0.3),
             clock=clock)
    d.charge("u", 0.2)
    now["t"] = 1100.0
    d.charge("u", 0.0)
    d.close()
    # the "n" journal line carried the absolute renewed state
    d2 = _dir(tmp_path, user_budget=0.5,
              renewal=RenewalPolicy(period_s=100.0, burst_cap=0.3),
              clock=clock)
    assert d2.spent("u") == 0.0
    assert d2.headroom("u") == pytest.approx(0.8)
    assert d2.lifetime("u") == pytest.approx(0.2)


def test_renewal_policy_validation():
    with pytest.raises(ValueError):
        RenewalPolicy(period_s=0.0)
    with pytest.raises(ValueError):
        RenewalPolicy(burst_cap=-1.0)


def test_renewal_boundary_charge_lands_in_new_window_only(tmp_path):
    """A charge whose clock sits *exactly* on the renewal boundary
    (now == window_start + period_s) renews first and then charges: the
    spend belongs entirely to the new window, never to both. This is
    the alignment contract the stream service leans on when it pins the
    directory clock to window starts with period_s == hop_s — the epoch
    boundary IS the renewal boundary."""
    now = {"t": 1000.0}
    d = _dir(tmp_path, user_budget=0.5,
             renewal=RenewalPolicy(period_s=100.0),
             clock=lambda: now["t"])
    d.charge("u", 0.3)
    assert d.spent("u") == pytest.approx(0.3)
    now["t"] = 1100.0  # exactly w + period_s: boundary-inclusive renewal
    d.charge("u", 0.2)
    # the new window holds only the new charge — 0.3 did not leak in
    assert d.spent("u") == pytest.approx(0.2)
    assert d.headroom("u") == pytest.approx(0.3)
    # and the old window's spend was not forgotten either: lifetime
    # counts both, renewals fired exactly once
    assert d.lifetime("u") == pytest.approx(0.5)
    assert d.counters()["renewals"] == 1
    # one tick *before* the next boundary stays in the current window
    now["t"] = 1199.0
    d.charge("u", 0.1)
    assert d.spent("u") == pytest.approx(0.3)
    assert d.counters()["renewals"] == 1


def test_renewal_epoch_aligned_stream_of_window_releases(tmp_path):
    """Stream-service alignment: the directory clock steps through
    window-start epochs (0, hop, 2*hop, ...) with period_s == hop_s, so
    each release epoch maps to exactly one renewal window. Every epoch
    sees the full per-window headroom and each window's charge is
    counted exactly once (lifetime == sum of all charges)."""
    hop = 10.0
    per_window = 0.4
    now = {"t": 0.0}
    d = _dir(tmp_path, user_budget=0.5,
             renewal=RenewalPolicy(period_s=hop),
             clock=lambda: now["t"])
    for epoch in range(5):
        now["t"] = epoch * hop
        # without a boundary renewal the second epoch would already be
        # refused (0.4 + 0.4 > 0.5) — every admission past epoch 0 is
        # itself proof the charge landed in a fresh window
        d.charge("u", per_window)
        # ... and the fresh window holds exactly this epoch's charge
        assert d.spent("u") == pytest.approx(per_window)
        assert d.headroom("u") == pytest.approx(0.5 - per_window)
    assert d.lifetime("u") == pytest.approx(5 * per_window)
    assert d.counters()["renewals"] == 4  # epochs 1..4 each renewed once


# ------------------------------------------- persistence / routing ----
def test_reopen_recovers_exact_balances(tmp_path):
    d = _dir(tmp_path, shards=4)
    for i in range(40):
        d.charge(f"u{i}", 0.125, charge_id=f"c{i}")
    d.refund("u3", 0.125, charge_id="c3")
    d.close()
    d2 = _dir(tmp_path, shards=4)
    assert d2.spent("u3") == 0.0
    for i in [0, 1, 7, 39]:
        if i != 3:
            assert d2.spent(f"u{i}") == pytest.approx(0.125)
    bal = read_user_balances(str(tmp_path / "dir"))
    assert len(bal) == 40
    assert bal["u7"]["l"] == pytest.approx(0.125)


def test_shard_count_pinned_in_meta(tmp_path):
    d = _dir(tmp_path, shards=4)
    d.charge("alice", 0.1)
    idx = d.shard_index("alice")
    d.close()
    # a reopen asking for a different count adopts the pinned one —
    # re-hashing users onto a different ring would split balances
    d2 = _dir(tmp_path, shards=16)
    assert d2.n_shards == 4
    assert d2.shard_index("alice") == idx
    assert d2.spent("alice") == pytest.approx(0.1)


def test_compaction_folds_wal_into_snapshot(tmp_path):
    d = _dir(tmp_path, compact_every=1)
    d.charge("u", 0.25, charge_id="c1")
    d.charge("u", 0.25, charge_id="c2")
    assert d.counters()["compactions"] == 2
    d.close()
    snap = json.load(open(tmp_path / "dir" / "shard-0000.json"))
    assert snap["gen"] == 2
    assert snap["users"]["u"]["s"] == pytest.approx(0.5)
    assert "c2" in snap["charge_ids"]
    wal = (tmp_path / "dir" / "shard-0000.wal").read_text().splitlines()
    assert json.loads(wal[0])["gen"] == 2
    assert len(wal) == 1  # fresh after the fold
    d2 = _dir(tmp_path, compact_every=1)
    assert d2.spent("u") == pytest.approx(0.5)
    d2.charge("u", 0.25, charge_id="c2")  # snapshot kept the id
    assert d2.spent("u") == pytest.approx(0.5)


def test_eviction_and_rehydration_preserve_balances(tmp_path):
    d = _dir(tmp_path, max_resident=2)
    for i in range(8):
        d.charge(f"u{i}", 0.125)
    c = d.counters()
    assert c["evictions"] >= 6
    assert c["resident_users"] == 2
    assert c["evicted_users"] == 6
    # peek reads the spill without rehydration churn
    assert d.spent("u0") == pytest.approx(0.125)
    d.charge("u0", 0.125)  # rehydrates, then evicts someone else
    assert d.counters()["rehydrations"] == 1
    assert d.spent("u0") == pytest.approx(0.25)
    d.close()
    d2 = _dir(tmp_path, max_resident=2)  # spill is non-authoritative
    for i in range(8):
        assert d2.spent(f"u{i}") == pytest.approx(
            0.25 if i == 0 else 0.125)


# --------------------------------------------------- crash windows ----
def test_matrix_registers_budget_points():
    for p in ("budget.pre_journal", "budget.post_journal",
              "budget.mid_compaction", "budget.mid_eviction"):
        assert p in chaos.MATRIX_POINTS


@pytest.mark.parametrize("point,on_disk", [
    # killed before the WAL append: nothing durable, the re-charge
    # applies once; killed after: the line is durable, the re-charge
    # dedups — either way recovery lands on exactly one application
    ("budget.pre_journal", 0.0),
    ("budget.post_journal", 0.25),
    ("budget.mid_compaction", 0.25),
    ("budget.mid_eviction", 0.25),
])
def test_crash_window_recovers_charge_once(tmp_path, point, on_disk):
    knobs = {"compact_every": 1 if point == "budget.mid_compaction"
             else None,
             "max_resident": 0 if point == "budget.mid_eviction"
             else None}
    d = _dir(tmp_path, **knobs)
    chaos.install(ChaosPlan(point=point, hit=1, mode="raise"))
    with pytest.raises(SimulatedCrash):
        d.charge("u", 0.25, charge_id="victim")
    chaos.clear()
    assert read_user_balances(str(tmp_path / "dir")) \
        .get("u", {}).get("l", 0.0) == pytest.approx(on_disk)
    # the restart: reopen and re-issue the interrupted charge under
    # its charge_id — exactly once regardless of where the kill hit
    d2 = _dir(tmp_path, **knobs)
    d2.charge("u", 0.25, charge_id="victim")
    assert d2.spent("u") == pytest.approx(0.25)
    assert d2.lifetime("u") == pytest.approx(0.25)


def test_crash_mid_compaction_discards_stale_wal(tmp_path):
    d = _dir(tmp_path, compact_every=2)
    d.charge("u", 0.25, charge_id="c1")
    chaos.install(ChaosPlan(point="budget.mid_compaction", hit=1,
                            mode="raise"))
    with pytest.raises(SimulatedCrash):
        d.charge("u", 0.25, charge_id="c2")
    chaos.clear()
    # torn window: snapshot says gen 1, WAL still says gen 0 and holds
    # both charge lines the snapshot already folded in
    snap = json.load(open(tmp_path / "dir" / "shard-0000.json"))
    assert snap["gen"] == 1
    wal = (tmp_path / "dir" / "shard-0000.wal").read_text().splitlines()
    assert json.loads(wal[0])["gen"] == 0 and len(wal) == 3
    d2 = _dir(tmp_path, compact_every=2)  # discards, never double-applies
    assert d2.spent("u") == pytest.approx(0.5)
    d2.charge("u", 0.25, charge_id="c2")  # snapshot kept the ids too
    assert d2.spent("u") == pytest.approx(0.5)


def test_wal_only_user_keeps_window_start_across_reopen(tmp_path):
    # the 'c' line carries the window start: a user whose state lives
    # only in the WAL (never compacted, no 'n' line) must not be
    # rebuilt with w=0.0 — the first post-restart charge would see
    # ~10k elapsed periods, fire a spurious renewal that zeroes the
    # window spend, and the user could overspend the window budget
    now = {"t": 1_000_000.0}
    kw = dict(user_budget=0.5, compact_every=None,
              renewal=RenewalPolicy(period_s=100.0),
              clock=lambda: now["t"])
    d = _dir(tmp_path, **kw)
    d.charge("u", 0.4)
    d.close()
    bal = read_user_balances(str(tmp_path / "dir"))
    assert bal["u"]["w"] == pytest.approx(1_000_000.0)
    now["t"] = 1_000_050.0  # still inside the same window
    d2 = _dir(tmp_path, **kw)
    assert d2.spent("u") == pytest.approx(0.4)
    with pytest.raises(BudgetExceededError):  # 0.4 + 0.2 > 0.5
        d2.charge("u", 0.2)
    assert d2.spent("u") == pytest.approx(0.4)
    assert d2.counters()["renewals"] == 0


def test_refund_created_user_carries_window_start(tmp_path):
    now = {"t": 5000.0}
    d = _dir(tmp_path, clock=lambda: now["t"])
    d.refund("u", 1.0)  # clamps to zero, creates the user
    d.close()
    bal = read_user_balances(str(tmp_path / "dir"))
    assert bal["u"]["w"] == pytest.approx(5000.0)


def test_refused_renewal_is_trace_free(tmp_path):
    now = {"t": 1000.0}
    d = _dir(tmp_path, user_budget=0.5,
             renewal=RenewalPolicy(period_s=100.0),
             clock=lambda: now["t"])
    d.charge("u", 0.4)
    wal = tmp_path / "dir" / "shard-0000.wal"
    before = wal.read_text()
    now["t"] = 1100.0  # a renewal is due, but the charge must refuse
    with pytest.raises(BudgetExceededError) as ei:
        d.charge("u", 0.6)  # over the renewed cap of 0.5
    assert ei.value.spent == 0.0  # checked against the renewed view
    assert wal.read_text() == before  # nothing journaled, not even 'n'
    assert d.counters()["renewals"] == 0
    d.charge("u", 0.3)  # admitted: renewal rides the same append
    assert d.spent("u") == pytest.approx(0.3)
    assert d.counters()["renewals"] == 1


def test_cold_spill_dead_lines_reclaimed(tmp_path):
    d = _dir(tmp_path, max_resident=0, compact_every=None)
    for _ in range(200):  # every charge rehydrates + re-evicts "u"
        d.charge("u", 0.001)
    cold = tmp_path / "dir" / "shard-0000.cold"
    lines = cold.read_text().splitlines()
    assert len(lines) <= 40  # bounded, not one dead line per charge
    assert d.spent("u") == pytest.approx(0.2)
    assert d.counters()["rehydrations"] == 199


def test_compaction_truncates_spill(tmp_path):
    d = _dir(tmp_path, max_resident=0, compact_every=5)
    for i in range(5):
        d.charge(f"u{i}", 0.1)  # the 5th mutation compacts
    cold = tmp_path / "dir" / "shard-0000.cold"
    lines = [json.loads(ln) for ln in cold.read_text().splitlines()]
    assert len(lines) == 5  # exactly the live evicted set, no dead bytes
    assert {e["u"] for e in lines} == {f"u{i}" for i in range(5)}
    d.close()
    d2 = _dir(tmp_path, max_resident=0, compact_every=5)
    for i in range(5):
        assert d2.spent(f"u{i}") == pytest.approx(0.1)


# ---------------------------------------------- corrupt quarantine ----
def test_corrupt_snapshot_quarantined_loudly(tmp_path):
    d = _dir(tmp_path, compact_every=1)
    d.charge("u", 0.25)
    d.close()
    snap = tmp_path / "dir" / "shard-0000.json"
    snap.write_text("{not json")
    with pytest.raises(DirectoryCorruptError) as ei:
        _dir(tmp_path, compact_every=1)
    msg = str(ei.value)
    assert "corrupt" in msg and "obs budget" in msg  # actionable
    assert os.path.exists(str(snap) + ".corrupt")
    assert not os.path.exists(str(snap))


def test_truncated_wal_quarantined_loudly(tmp_path):
    d = _dir(tmp_path)
    d.charge("u", 0.25)
    d.close()
    wal = tmp_path / "dir" / "shard-0000.wal"
    with open(wal, "a") as fh:
        fh.write('{"k": "c", "u": "u", "e"')  # torn mid-line
    with pytest.raises(DirectoryCorruptError):
        _dir(tmp_path)
    assert os.path.exists(str(wal) + ".corrupt")
    assert not os.path.exists(str(wal))


def test_wal_generation_ahead_of_snapshot_is_corrupt(tmp_path):
    root = tmp_path / "dir"
    root.mkdir()
    (root / "meta.json").write_text('{"version": 1, "shards": 1}')
    (root / "shard-0000.wal").write_text('{"k": "wal", "gen": 5}\n')
    with pytest.raises(DirectoryCorruptError):
        _dir(tmp_path)


def test_stale_tmp_swept_on_open(tmp_path):
    d = _dir(tmp_path, compact_every=1)
    d.charge("u", 0.25)
    d.close()
    stale = tmp_path / "dir" / "shard-0000.json.tmp.12345"
    stale.write_text("half a snapshot that never committed")
    d2 = _dir(tmp_path, compact_every=1)
    assert not stale.exists()
    assert d2.spent("u") == pytest.approx(0.25)


def test_corrupt_spill_fails_shard_loudly_then_reopen_recovers(tmp_path):
    d = _dir(tmp_path, max_resident=0)
    d.charge("u", 0.25)
    cold = tmp_path / "dir" / "shard-0000.cold"
    cold.write_text("{torn garbage\n")
    with pytest.raises(DirectoryCorruptError):
        d.spent("u")  # the peek reads the spill
    assert os.path.exists(str(cold) + ".corrupt")
    # the shard is failed, not limping on a closed file handle: every
    # later operation re-raises the same loud quarantine error, never
    # a raw "I/O operation on closed file" ValueError
    with pytest.raises(DirectoryCorruptError):
        d.charge("v", 0.1)
    with pytest.raises(DirectoryCorruptError):
        d.headroom("u")
    d.close()  # must not raise on the already-closed spill handle
    # evicted users' authoritative state is snapshot + WAL, so a
    # restart recovers exact balances from a fresh (reset) spill
    d2 = _dir(tmp_path, max_resident=0)
    assert d2.spent("u") == pytest.approx(0.25)


def test_corrupt_meta_quarantined(tmp_path):
    root = tmp_path / "dir"
    root.mkdir()
    (root / "meta.json").write_text("{garbage")
    with pytest.raises(DirectoryCorruptError):
        _dir(tmp_path)
    assert (root / "meta.json.corrupt").exists()


# ------------------------------------------------- replay helpers ----
def test_apply_wal_entry_semantics(tmp_path):
    users, ids = {}, {}
    apply_wal_entry({"k": "c", "u": "u", "e": 0.5, "id": "a"},
                    users, ids, "wal")
    apply_wal_entry({"k": "c", "u": "u", "e": 0.5, "id": "a"},
                    users, ids, "wal")  # dedup
    assert users["u"]["s"] == pytest.approx(0.5)
    apply_wal_entry({"k": "r", "u": "u", "e": 9.0, "id": "a"},
                    users, ids, "wal")  # clamps, forgets the id
    assert users["u"]["s"] == 0.0 and "a" not in ids
    apply_wal_entry({"k": "n", "u": "u", "w": 7.0, "b": 0.3},
                    users, ids, "wal")
    assert users["u"] == {"s": 0.0, "l": 0.0, "b": 0.3, "w": 7.0}
    # creation-state-carrying entries: a WAL-only user is re-created
    # with the journaled window start and burst, not w=0, b=0
    apply_wal_entry({"k": "c", "u": "v", "e": 0.1, "id": "b",
                     "w": 50.0, "b": 0.2}, users, ids, "wal")
    assert users["v"]["w"] == 50.0
    assert users["v"]["b"] == pytest.approx(0.2)
    # a dedup'd charge does not create the user (live-path parity)
    apply_wal_entry({"k": "c", "u": "ghost", "e": 0.1, "id": "b"},
                    users, ids, "wal")
    assert "ghost" not in users
    bad_wal = tmp_path / "w.wal"
    bad_wal.write_text('{"k": "??", "u": "u"}\n')
    with pytest.raises(DirectoryCorruptError):
        apply_wal_entry({"k": "??", "u": "u"}, users, ids,
                        str(bad_wal))
    assert not bad_wal.exists()  # quarantined whole
    assert (tmp_path / "w.wal.corrupt").exists()


def test_views_and_fold_levels():
    aug = {"pa": 0.5, "pb": 0.25, USER_PREFIX + "alice": 0.75,
           GLOBAL_KEY: 0.75}
    assert party_view(aug) == {"pa": 0.5, "pb": 0.25}
    assert user_view(aug) == {"alice": 0.75}
    assert is_reserved(GLOBAL_KEY) and is_reserved(USER_PREFIX + "x")
    assert not is_reserved("party-x")
    lv = fold_levels(aug)
    assert lv["party"] == {"pa": 0.5, "pb": 0.25}
    assert lv["user"] == {"alice": 0.75}
    assert lv["global"] == {GLOBAL_KEY: 0.75}


# ------------------------------------------------ composite ledger ----
def _composite(tmp_path, budget=100.0, user_budget=1.0,
               global_budget=None, audit=None):
    led = PrivacyLedger(budget, audit=audit)
    d = BudgetDirectory(str(tmp_path / "dir"), shards=2,
                        user_budget=user_budget, fsync=False,
                        audit=audit)
    return CompositeLedger(led, d, user="alice",
                           global_budget=global_budget)


def test_augment_adds_legs_and_is_idempotent(tmp_path):
    comp = _composite(tmp_path, global_budget=10.0)
    aug = comp.augment({"pa": 0.5, "pb": 0.25})
    assert aug[USER_PREFIX + "alice"] == pytest.approx(0.75)
    assert aug[GLOBAL_KEY] == pytest.approx(0.75)
    assert comp.augment(aug) == aug  # round-trips unchanged
    assert comp.augment({"pa": 0.5}, user="bob") == {
        "pa": 0.5, USER_PREFIX + "bob": 0.5, GLOBAL_KEY: 0.5}


def test_composite_charge_lands_every_leg(tmp_path):
    comp = _composite(tmp_path, global_budget=10.0)
    comp.charge({"pa": 0.5, "pb": 0.25}, charge_id="c1")
    assert comp.ledger.spent("pa") == pytest.approx(0.5)
    assert comp.directory.spent("alice") == pytest.approx(0.75)
    assert comp.spent(USER_PREFIX + "alice") == pytest.approx(0.75)
    assert comp.ledger.spent(GLOBAL_KEY) == pytest.approx(0.75)
    comp.charge({"pa": 0.5, "pb": 0.25}, charge_id="c1")  # dedups whole
    assert comp.directory.spent("alice") == pytest.approx(0.75)


@pytest.mark.parametrize("level,kw,charges", [
    # party cap refuses: the user leg already applied is compensated
    ("party", dict(budget=0.5, user_budget=100.0), {"pa": 0.75}),
    # global cap refuses: each party leg fits, their sum does not
    ("global", dict(global_budget=0.5, user_budget=100.0),
     {"pa": 0.4, "pb": 0.4}),
    # user cap refuses before anything reaches the party ledger
    ("user", dict(user_budget=0.5), {"pa": 0.75}),
])
def test_refusal_consumes_zero_everywhere(tmp_path, level, kw, charges):
    comp = _composite(tmp_path, **kw)
    with pytest.raises(BudgetExceededError) as ei:
        comp.charge(charges, charge_id="c1")
    assert ei.value.level == level
    assert comp.directory.spent("alice") == 0.0
    for p in charges:
        assert comp.ledger.spent(p) == 0.0
    assert comp.refusals_by_level()[level] == 1
    comp.charge({"pa": 0.1}, charge_id="c1")  # compensation freed the id
    assert comp.directory.spent("alice") == pytest.approx(0.1)


def test_composite_compensates_on_non_budget_ledger_failure(tmp_path):
    comp = _composite(tmp_path)

    def boom(*a, **kw):
        raise OSError("disk full persisting the party snapshot")

    comp.ledger.charge = boom
    with pytest.raises(OSError):
        comp.charge({"pa": 0.5})
    # the user leg must not stay charged for a query that never ran —
    # server requests carry no charge_id, so nothing else would ever
    # reverse it
    assert comp.directory.spent("alice") == 0.0
    c = comp.directory.counters()
    assert c["charges"] == 1 and c["refunds"] == 1


def test_composite_simulated_crash_skips_compensation(tmp_path):
    # SimulatedCrash stands in for a process KILL: compensating after
    # it would journal refunds a real kill could never have written,
    # and the chaos exact-balance assertions rely on that fidelity.
    # The recovery story is the idempotent re-charge instead.
    comp = _composite(tmp_path)
    chaos.install(ChaosPlan(point="ledger.pre_persist", hit=1,
                            mode="raise"))
    with pytest.raises(SimulatedCrash):
        comp.charge({"pa": 0.5}, charge_id="c1")
    chaos.clear()
    assert comp.directory.spent("alice") == pytest.approx(0.5)
    comp.charge({"pa": 0.5}, charge_id="c1")  # the restart's re-issue
    assert comp.directory.spent("alice") == pytest.approx(0.5)  # dedup
    assert comp.ledger.spent("pa") == pytest.approx(0.5)


def test_refund_reverses_every_leg_from_bare_dict(tmp_path):
    comp = _composite(tmp_path, global_budget=10.0)
    comp.charge({"pa": 0.5, "pb": 0.25}, charge_id="c1")
    # the gate's transport-failure path holds only the per-party dict;
    # the one refund path re-derives the directory and global legs
    comp.refund({"pa": 0.5, "pb": 0.25}, charge_id="c1", reason="shed")
    assert comp.ledger.spent("pa") == 0.0
    assert comp.ledger.spent(GLOBAL_KEY) == 0.0
    assert comp.directory.spent("alice") == 0.0


def test_charge_request_returns_augmented_dict(tmp_path):
    comp = _composite(tmp_path)
    r = np.random.default_rng(0)
    req = EstimateRequest(family="ni_sign", x=r.normal(size=32),
                          y=r.normal(size=32), eps1=0.25, eps2=0.125,
                          party_x="pa", party_y="pb", normalise=False,
                          user="bob")
    aug = comp.charge_request(req)
    total = aug["pa"] + aug["pb"]
    assert aug[USER_PREFIX + "bob"] == pytest.approx(total)
    assert comp.directory.spent("bob") == pytest.approx(total)
    comp.refund(aug, reason="deadline")  # the coalescer's shed path
    assert comp.directory.spent("bob") == 0.0
    assert comp.ledger.spent("pa") == 0.0


def test_directory_snapshot_shape(tmp_path):
    comp = _composite(tmp_path, user_budget=0.5)
    comp.charge({"pa": 0.25})
    with pytest.raises(BudgetExceededError):
        comp.charge({"pa": 0.5})
    snap = comp.directory_snapshot()
    assert snap["shards"] == 2
    assert snap["resident_users"] == 1
    assert snap["refusals_by_level"] == {"user": 1, "party": 0,
                                         "global": 0}
    assert snap["counters"]["charged_eps"] == pytest.approx(0.25)


# ------------------------------------------------ audit / obs CLI ----
def test_audit_replay_matches_disk_balances(tmp_path):
    audit = AuditTrail(str(tmp_path / "audit.jsonl"))
    comp = _composite(tmp_path, audit=audit)
    comp.charge({"pa": 0.5}, charge_id="c1")
    comp.charge({"pa": 0.25}, charge_id="c2")
    comp.refund({"pa": 0.25}, charge_id="c2", reason="shed")
    comp.close()
    spent = replay(read_events(str(tmp_path / "audit.jsonl")))
    lv = fold_levels(spent)
    assert lv["user"]["alice"] == pytest.approx(0.5)
    assert lv["party"]["pa"] == pytest.approx(0.5)
    bal = read_user_balances(str(tmp_path / "dir"))
    assert bal["alice"]["l"] == pytest.approx(lv["user"]["alice"])


def test_obs_budget_cli_checks_directory(tmp_path):
    audit_path = str(tmp_path / "audit.jsonl")
    audit = AuditTrail(audit_path)
    comp = _composite(tmp_path, audit=audit)
    comp.charge({"pa": 0.5}, charge_id="c1")
    comp.close()
    cmd = [sys.executable, "-m", "dpcorr", "obs", "budget",
           "--audit", audit_path,
           "--budget-dir", str(tmp_path / "dir"), "--json"]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["budget_dir"]["ok"]
    assert out["budget_dir"]["users"] == 1
    # a trail line with no matching disk spend is a MISMATCH, rc 1
    audit.record("charge", {USER_PREFIX + "ghost": 1.0})
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 1
    out = json.loads(proc.stdout)
    assert not out["budget_dir"]["ok"]
    assert any(m["user"] == "ghost"
               for m in out["budget_dir"]["mismatches"])
