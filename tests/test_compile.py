"""Compile-ahead layer (ISSUE 4): single-flight dedup, AOT bit-identity,
warmup/readiness lifecycle, manifest replay, export roundtrip, obs wiring.

The contract under test, end to end: compilation happens once per
signature no matter how many threads race the miss; ahead-of-time
compilation produces the SAME bits as the lazy jit path for every
estimator family; a warmed server reports ready only once its signature
set is resident and then serves steady-state traffic with zero compiles;
and every compile is observable (``dpcorr_compile_seconds`` metric +
``kernel.compile`` span).
"""

import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from dpcorr.models.estimators.registry import FAMILIES
from dpcorr.obs import trace as obs_trace
from dpcorr.obs.metrics import Registry
from dpcorr.serve import (
    DpcorrServer,
    EstimateRequest,
    KernelCache,
    load_manifest,
    make_http_server,
    parse_warmup_spec,
    signatures_to_keys,
)
from dpcorr.serve.request import KernelKey, kernel_key
from dpcorr.utils import compile as compile_mod
from dpcorr.utils import rng


def _mk_req(n=96, family="ni_sign", seed=None, i=0, **kw):
    rs = np.random.RandomState(300 + i)
    return EstimateRequest(family, rs.randn(n).astype(np.float32),
                           rs.randn(n).astype(np.float32),
                           1.0, 0.5, seed=seed, **kw)


def _sig_for(req, b_pad=1):
    kk = kernel_key(req)
    return {"family": kk.family, "n": kk.n, "eps1": kk.eps1,
            "eps2": kk.eps2, "alpha": kk.alpha,
            "normalise": kk.normalise, "b_pad": b_pad}


def _http(srv):
    httpd = make_http_server(srv, host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _get_readyz(base):
    try:
        with urllib.request.urlopen(f"{base}/readyz") as r:
            import json

            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        import json

        return e.code, json.load(e)


# ----------------------------------------------------- single-flight ----

def test_single_flight_dedup_and_error_retry():
    """Pure-unit race: 8 threads, one build, exactly one leader; a
    failed build propagates to all waiters but clears the flight so the
    key can be rebuilt."""
    sf = compile_mod.SingleFlight()
    builds, results = [], []
    bar = threading.Barrier(8)

    def build():
        builds.append(1)
        time.sleep(0.3)  # hold the flight open while followers arrive
        return "v"

    def worker():
        bar.wait()
        results.append(sf.do("k", build))

    ts = [threading.Thread(target=worker) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(builds) == 1
    assert [v for v, _ in results] == ["v"] * 8
    assert sum(1 for _, leader in results if leader) == 1
    assert sf.inflight_count() == 0

    def boom():
        raise RuntimeError("compile died")

    with pytest.raises(RuntimeError, match="compile died"):
        sf.do("k2", boom)
    assert sf.do("k2", lambda: 7) == (7, True)  # flight cleared, retryable


def test_kernel_cache_race_one_compile_per_key():
    """ISSUE 4 acceptance (satellite a): concurrent misses on one
    signature produce exactly ONE compilation — followers wait on the
    leader's inflight build and count into ``kernel_compile_dedup``, and
    every thread gets the same executable."""
    cache = KernelCache(shard="off", mode="exact")
    compiled = []
    cache._compile_hook = lambda sig: (compiled.append(sig),
                                       time.sleep(1.0))
    kk = KernelKey("ni_sign", 64, 1.0, 0.5, 0.05, True)
    bar = threading.Barrier(8)
    fns = []

    def worker():
        bar.wait()
        fns.append(cache.get(kk, 4)[0])

    ts = [threading.Thread(target=worker) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(compiled) == 1  # the leader compiled; nobody else did
    s = cache.stats
    assert s.kernel_compiles == 1
    assert s.kernel_compile_dedup + s.kernel_hits == 7
    assert s.kernel_compile_dedup >= 1  # the 1 s hold guarantees waiters
    assert all(f is fns[0] for f in fns)
    # steady state afterwards: pure hits, no dedup, no compiles
    before = s.kernel_compile_dedup
    cache.get(kk, 4)
    assert s.kernel_compiles == 1 and s.kernel_compile_dedup == before


# ----------------------------------------------------- AOT bit-identity ----

@pytest.mark.parametrize("family", FAMILIES)
def test_aot_bit_identical_to_lazy_jit(family):
    """The AOT executable is the same HLO the lazy jit would build:
    responses must be bit-identical for every estimator family."""
    n = 64
    rs = np.random.RandomState(11)
    xs = rs.randn(3, n).astype(np.float32)
    ys = rs.randn(3, n).astype(np.float32)
    keys = jax.random.split(rng.master_key(7), 3)
    kk = KernelKey(family, n, 1.0, 0.5, 0.05, True)
    got_aot = KernelCache(shard="off", aot=True).run_batch(
        kk, keys, xs, ys)
    got_jit = KernelCache(shard="off", aot=False).run_batch(
        kk, keys, xs, ys)
    for a, b in zip(got_aot, got_jit, strict=True):
        np.testing.assert_array_equal(a, b)


# ----------------------------------------------- warmup spec / manifest ----

def test_parse_warmup_spec_and_dedup():
    sigs = parse_warmup_spec("ni_sign:500:1.0:0.5:1,3 ni_sign:500:1:0.5:4",
                             max_batch=64)
    keys = signatures_to_keys(sigs)
    # b_pads 1, 4 (3 rounds up to 4 and dedups against the explicit 4)
    assert [b for _, b in keys] == [1, 4]
    assert keys[0][0].n == 500
    auto = parse_warmup_spec("int_subg:100:1.0:1.0:auto", max_batch=8)
    assert [s["b_pad"] for s in auto] == [1, 2, 4, 8]
    with pytest.raises(ValueError, match="--warmup"):
        parse_warmup_spec("ni_sign:500", max_batch=8)


def test_load_manifest_degrades_to_cold_boot(tmp_path):
    missing = tmp_path / "none.json"
    assert load_manifest(str(missing)) == []
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert load_manifest(str(bad)) == []
    wrong = tmp_path / "wrong.json"
    wrong.write_text('{"version": 99, "signatures": []}')
    assert load_manifest(str(wrong)) == []


# ------------------------------------------------- readiness lifecycle ----

def test_readyz_lifecycle_and_zero_steady_state_compiles():
    """/readyz walks not-ready → warming → ready, and once ready the
    warm signature serves traffic with ZERO further compilations."""
    req = _mk_req(seed=1)
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off",
                       warmup=[_sig_for(req)], warmup_autostart=False)
    httpd, base = _http(srv)
    try:
        code, body = _get_readyz(base)
        assert (code, body["ready"], body["state"]) == \
            (503, False, "pending")
        assert srv.stats.kernel_compiles == 0
        srv.start_warmup()
        assert srv.wait_ready(timeout=300)
        assert srv.readiness()["state"] == "ready"
        code, body = _get_readyz(base)
        assert code == 200 and body["warmed"] == body["total"] == 1
        compiles = srv.stats.kernel_compiles
        assert compiles == 1
        got = srv.estimate(req, timeout=120)
        assert np.isfinite(got.rho_hat)
        assert srv.stats.kernel_compiles == compiles  # warm: no compile
        assert srv.stats.kernel_hits >= 1
        # the compile-ahead metrics surface in this server's exposition
        text = srv.stats.render_prometheus()
        assert "dpcorr_compile_seconds_bucket" in text
        assert 'dpcorr_compile_total{result="aot"} 1' in text
    finally:
        httpd.shutdown()
        srv.close()


def test_server_with_no_warmup_is_ready_immediately():
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        assert srv.readiness() == {"ready": True, "state": "ready",
                                   "warmed": 0, "warm_errors": 0,
                                   "total": 0, "breakers_open": False}
        assert srv.wait_ready(timeout=0.1)
    finally:
        srv.close()


def test_bad_warmup_signature_does_not_block_readiness():
    """A stale manifest entry (unknown family) must not hold readiness
    hostage: it counts as a warm error and the server still goes ready."""
    good = _sig_for(_mk_req(seed=2))
    bad = dict(good, family="nope")
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off",
                       warmup=[bad, good])
    try:
        assert srv.wait_ready(timeout=300)
        r = srv.readiness()
        assert r["ready"] and r["warm_errors"] == 1 and r["warmed"] == 1
    finally:
        srv.close()


def test_warmup_manifest_roundtrip_across_restart(tmp_path):
    """Shutdown persists the resident signature set; the next boot
    replays it and then serves the same traffic without compiling —
    with answers bit-identical across the restart."""
    manifest = str(tmp_path / "kernels.json")
    req = _mk_req(seed=5)
    srv1 = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off",
                        warmup_manifest=manifest)
    try:
        assert srv1.wait_ready(timeout=60)  # empty manifest: first boot
        r1 = srv1.estimate(req, timeout=120)
    finally:
        srv1.close()
    sigs = load_manifest(manifest)
    assert len(sigs) == 1 and sigs[0]["family"] == "ni_sign"

    srv2 = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off",
                        warmup_manifest=manifest)
    try:
        assert srv2.wait_ready(timeout=300)
        assert srv2.readiness()["total"] == 1
        compiles = srv2.stats.kernel_compiles
        assert compiles == 1  # the replayed signature, compiled at boot
        r2 = srv2.estimate(req, timeout=120)
        assert srv2.stats.kernel_compiles == compiles  # warm boot
    finally:
        srv2.close()
    assert (r1.rho_hat, r1.ci_low, r1.ci_high) == \
        (r2.rho_hat, r2.ci_low, r2.ci_high)


# ------------------------------------------------------- jax.export ----

@pytest.mark.skipif(not compile_mod.export_supported(),
                    reason="jax.export unavailable on this jax")
def test_export_roundtrip_bit_identical(tmp_path, monkeypatch):
    """A compiled program serialized by one cache is replayed by the
    next (same export_dir) and produces identical bits."""
    n = 64
    rs = np.random.RandomState(3)
    xs = rs.randn(2, n).astype(np.float32)
    ys = rs.randn(2, n).astype(np.float32)
    keys = jax.random.split(rng.master_key(9), 2)
    kk = KernelKey("int_sign", n, 1.0, 1.0, 0.05, True)

    first = KernelCache(shard="off", export_dir=str(tmp_path))
    got1 = first.run_batch(kk, keys, xs, ys)
    arts = list(tmp_path.glob("*.jaxexp"))
    assert len(arts) == 1 and arts[0].stat().st_size > 0

    loads = []
    orig = compile_mod.load_exported

    def counting_load(path):
        loads.append(path)
        return orig(path)

    monkeypatch.setattr(compile_mod, "load_exported", counting_load)
    second = KernelCache(shard="off", export_dir=str(tmp_path))
    got2 = second.run_batch(kk, keys, xs, ys)
    assert loads, "second boot never consulted the export artifact"
    for a, b in zip(got1, got2, strict=True):
        np.testing.assert_array_equal(a, b)


# ------------------------------------------------------- observability ----

def test_aot_compile_metrics_and_span(tmp_path):
    """Every compile lands in the ``dpcorr_compile_seconds`` histogram
    and emits a ``kernel.compile`` span carrying its signature."""
    import jax.numpy as jnp

    path = str(tmp_path / "spans.jsonl")
    tr = obs_trace.configure(path)
    try:
        obs = compile_mod.CompileObserver(registry=Registry(), tracer=tr)
        jfn = jax.jit(lambda x: x * 2.0)
        fn, ok = compile_mod.aot_compile(
            jfn, (jax.ShapeDtypeStruct((4,), jnp.float32),),
            signature={"kernel": "toy", "n": 4}, observer=obs)
        assert ok
        np.testing.assert_array_equal(
            np.asarray(fn(np.ones(4, np.float32))),
            np.full(4, 2.0, np.float32))
        assert obs.inflight.value() == 0
    finally:
        obs_trace.configure(None)
    text = obs.registry.render()
    assert "dpcorr_compile_seconds_bucket" in text
    assert 'dpcorr_compile_total{result="aot"} 1' in text
    spans = [s for s in obs_trace.read_spans(path)
             if s["name"] == "kernel.compile"]
    assert len(spans) == 1
    assert spans[0]["attrs"]["kernel"] == "toy"
    assert spans[0]["attrs"]["aot"] is True


def test_aot_compile_failure_falls_back_to_jit():
    """A signature that cannot lower degrades to the lazy jitted
    callable (ok=False) and counts as a jit-fallback, never raises."""
    import jax.numpy as jnp

    class Unlowerable:
        def lower(self, *a):
            raise RuntimeError("no backend for you")

        def __call__(self, x):
            return x + 1

    obs = compile_mod.CompileObserver(registry=Registry())
    fn, ok = compile_mod.aot_compile(
        Unlowerable(), (jax.ShapeDtypeStruct((2,), jnp.float32),),
        signature={"kernel": "broken"}, observer=obs)
    assert not ok
    assert fn(1) == 2  # the original callable, still usable
    assert 'dpcorr_compile_total{result="jit-fallback"} 1' \
        in obs.registry.render()
