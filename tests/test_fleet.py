"""Unit tests for the fleet telemetry plane (ISSUE 11): kind-aware
exposition parsing + federated merge (dpcorr.obs.fleet), the
multi-window burn-rate SLO engine under a scripted clock
(dpcorr.obs.slo), and the jax-free ``dpcorr obs fleet snapshot`` CLI
against a canned in-thread HTTP fleet."""

from __future__ import annotations

import json
import subprocess
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dpcorr.obs import Registry
from dpcorr.obs.fleet import (
    FleetCollector,
    MetricFamily,
    aggregate_families,
    conservation,
    families_to_flat,
    fleet_chrome_trace,
    fleet_replay,
    merge_expositions,
    merge_families,
    parse_families,
    parse_targets,
    render_families,
)
from dpcorr.obs.slo import (
    DEFAULT_WINDOWS,
    BurnRateEngine,
    Objective,
    http_trigger_hook,
)

BUCKETS = (0.1, 0.5, 1.0)


def _instance_registry(completed: int, slow: int, refused: int,
                       spent: float) -> Registry:
    """One synthetic serve-shaped instance: counters, a labelled
    counter, a latency histogram and a per-party spend gauge."""
    r = Registry()
    c = r.counter("dpcorr_serve_requests_total", "admitted")
    c.inc(completed + refused)
    ref = r.counter("dpcorr_serve_requests_refused_total", "refused",
                    labelnames=("reason",))
    if refused:
        ref.inc(refused, reason="budget")
    done = r.counter("dpcorr_serve_requests_completed_total",
                     "completed", labelnames=("mode",))
    if completed:
        done.inc(completed, mode="batched")
    h = r.histogram("dpcorr_serve_latency_seconds", "latency",
                    buckets=BUCKETS)
    for _ in range(completed - slow):
        h.observe(0.05)
    for _ in range(slow):
        h.observe(0.75)  # > 0.5: bad under the 0.5 s objective
    g = r.gauge("dpcorr_ledger_spent_eps", "spend",
                labelnames=("party",))
    g.set(spent, party="px")
    return r


# ------------------------------------------------- parse / round-trip ----

def test_parse_render_round_trip_is_exact():
    text = _instance_registry(10, 2, 1, 2.5).render()
    fams = parse_families(text)
    assert parse_families(render_families(fams)) == fams
    # the flat view agrees with the metrics-module parser's shape
    flat = families_to_flat(fams)
    assert flat["dpcorr_serve_requests_total"] == 11.0
    assert flat['dpcorr_serve_latency_seconds_bucket{le="0.5"}'] == 8.0


def test_parse_families_attaches_histogram_series():
    fams = parse_families(_instance_registry(4, 0, 0, 1.0).render())
    h = fams["dpcorr_serve_latency_seconds"]
    assert h.kind == "histogram"
    names = {s for s, _, _ in h.samples}
    assert names == {"dpcorr_serve_latency_seconds_bucket",
                     "dpcorr_serve_latency_seconds_sum",
                     "dpcorr_serve_latency_seconds_count"}


def test_parse_families_rejects_garbage():
    with pytest.raises(ValueError):
        parse_families("dpcorr_x{unclosed 1\n")


# --------------------------------------------------------------- merge ----

def _three_instances() -> dict[str, dict[str, MetricFamily]]:
    return {
        "a": parse_families(_instance_registry(10, 0, 1, 1.5).render()),
        "b": parse_families(_instance_registry(20, 0, 2, 2.5).render()),
        "c": parse_families(_instance_registry(5, 5, 0, 0.25).render()),
    }


def test_merge_labels_every_sample_and_aggregate_sums_exactly():
    merged = merge_families(_three_instances())
    flat = families_to_flat(merged)
    assert flat['dpcorr_serve_requests_total{instance="a"}'] == 11.0
    assert flat['dpcorr_serve_requests_total{instance="b"}'] == 22.0
    assert flat['dpcorr_serve_requests_total{instance="c"}'] == 5.0
    agg = families_to_flat(aggregate_families(merged))
    # counters sum exactly (integers — no tolerance)
    assert agg["dpcorr_serve_requests_total"] == 38.0
    assert agg['dpcorr_serve_requests_refused_total{reason="budget"}'] \
        == 3.0
    # cumulative histogram buckets add bucket-wise: a and b's 30 fast
    # observations land ≤ 0.5, c's 5 slow ones only at ≤ 1.0
    assert agg['dpcorr_serve_latency_seconds_bucket{le="0.5"}'] == 30.0
    assert agg['dpcorr_serve_latency_seconds_bucket{le="1"}'] == 35.0
    assert agg["dpcorr_serve_latency_seconds_count"] == 35.0
    # re-exposing the merged registry round-trips
    assert parse_families(render_families(merged)) == merged


def test_merged_exposition_is_itself_scrapeable():
    merged = merge_families(_three_instances())
    again = parse_families(render_families(merged))
    assert families_to_flat(again) == families_to_flat(merged)


def test_matching_instance_self_report_passes():
    r = Registry()
    r.gauge("dpcorr_serve_instance_info", "id",
            labelnames=("instance",)).set(1, instance="a")
    merged = merge_families(
        {"a": parse_families(r.render())})
    flat = families_to_flat(merged)
    assert flat['dpcorr_serve_instance_info{instance="a"}'] == 1.0


def test_colliding_instance_claim_refuses_loudly():
    r = Registry()
    r.gauge("dpcorr_serve_instance_info", "id",
            labelnames=("instance",)).set(1, instance="imposter")
    with pytest.raises(ValueError, match="imposter"):
        merge_families({"a": parse_families(r.render())})


def test_duplicate_instance_names_refuse():
    text = _instance_registry(1, 0, 0, 0.5).render()
    with pytest.raises(ValueError, match="duplicate"):
        merge_expositions([("a", text), ("a", text)])
    with pytest.raises(ValueError, match="duplicate"):
        parse_targets("a=http://h:1,a=http://h:2")


def test_kind_clash_across_instances_refuses():
    ra, rb = Registry(), Registry()
    ra.counter("dpcorr_thing", "as counter").inc()
    rb.gauge("dpcorr_thing", "as gauge").set(2)
    with pytest.raises(ValueError, match="already merged"):
        merge_families({"a": parse_families(ra.render()),
                        "b": parse_families(rb.render())})


# ------------------------------------------------------------ audit ε ----

def _events(n_charges: int, eps: float, refund_last: bool) -> list[dict]:
    evs = [{"kind": "charge", "charges": {"px": eps, "py": eps / 2},
            "charge_id": f"c{i}"} for i in range(n_charges)]
    if refund_last:
        evs.append({"kind": "refund",
                    "charges": {"px": eps, "py": eps / 2},
                    "charge_id": f"c{n_charges - 1}"})
    return evs


def test_fleet_replay_folds_in_sorted_instance_order():
    spools = {"b": _events(3, 0.25, False),
              "a": _events(2, 0.25, True)}
    doc = fleet_replay(spools)
    assert doc["per_instance"]["a"] == {"px": 0.25, "py": 0.125}
    assert doc["per_instance"]["b"] == {"px": 0.75, "py": 0.375}
    # the fleet fold IS the sum of the per-instance ledgers, exactly
    assert doc["fleet"] == {"px": 1.0, "py": 0.5}


def test_conservation_verdict_binary_exact():
    spools = {"a": _events(2, 0.25, False), "b": _events(4, 0.25, False)}
    ledgers = {"a": {"px": 0.5, "py": 0.25},
               "b": {"px": 1.0, "py": 0.5}}
    doc = conservation(spools, ledgers)
    assert doc["ok"] and doc["fleet_ok"]
    assert doc["fleet"] == doc["ledger_fleet"] == {"px": 1.5, "py": 0.75}
    # one instance lying by one ulp-scale epsilon breaks the gate
    ledgers["b"] = {"px": 1.0 + 2**-40, "py": 0.5}
    bad = conservation(spools, ledgers)
    assert not bad["ok"] and bad["mismatches"][0]["instance"] == "b"


# ---------------------------------------------------------- span union ----

def test_fleet_chrome_trace_one_pid_per_instance():
    def span(trace, name, ts):
        return {"trace_id": trace, "span_id": "s1", "parent_id": None,
                "name": name, "ts": ts, "dur_s": 0.01, "attrs": {}}
    doc = fleet_chrome_trace({
        "b": [span("t1", "serve.request", 2.0)],
        "a": [span("t0", "serve.request", 1.0)],
    })
    evs = doc["traceEvents"]
    meta = {e["args"]["name"]: e["pid"] for e in evs
            if e.get("name") == "process_name"}
    assert meta == {"a": 1, "b": 2}  # sorted instances, stable pids
    spans = [e for e in evs if e.get("ph") == "X"]
    assert {e["pid"] for e in spans} == {1, 2}
    assert all(e["args"]["instance"] in ("a", "b") for e in spans)


# --------------------------------------------------------- SLO engine ----

def _fams(completed: int, slow: int) -> dict[str, MetricFamily]:
    return parse_families(
        _instance_registry(completed, slow, 0, 1.0).render())


def test_latency_objective_requires_exact_bucket_bound():
    with pytest.raises(ValueError, match="bucket bound"):
        Objective(name="lat", kind="latency", target=0.05,
                  threshold_s=0.3).cumulative(_fams(4, 0))
    bad, total = Objective(
        name="lat", kind="latency", target=0.05,
        threshold_s=0.5).cumulative(_fams(10, 3))
    assert (bad, total) == (3.0, 10.0)


def test_burn_rate_engine_pages_offender_exactly_once():
    obj = Objective(name="lat", kind="latency", target=0.05,
                    threshold_s=0.5)
    paged = []
    eng = BurnRateEngine([obj], on_page=paged.append)
    eng.observe({"good": _fams(10, 0), "bad": _fams(10, 0)}, at=0.0)
    # bad instance: every new request lands slow → burn 20 > 14.4
    eng.observe({"good": _fams(40, 0), "bad": _fams(20, 10)}, at=60.0)
    fired = eng.evaluate(at=60.0)
    assert [a.instance for a in fired] == ["bad"]
    assert fired[0].severity == "page" and fired[0].previous == "ok"
    assert fired[0].burn_short == pytest.approx(20.0)
    assert eng.state("lat", "good") == "ok"
    assert [a.instance for a in paged] == ["bad"]
    # exactly-once: re-evaluating the unchanged world fires nothing
    assert eng.evaluate(at=61.0) == []
    assert [a.instance for a in paged] == ["bad"]


def test_burn_rate_engine_recovers_to_ok():
    obj = Objective(name="lat", kind="latency", target=0.05,
                    threshold_s=0.5)
    eng = BurnRateEngine([obj])
    eng.observe({"i": _fams(10, 0)}, at=0.0)
    eng.observe({"i": _fams(20, 10)}, at=60.0)
    assert [a.severity for a in eng.evaluate(at=60.0)] == ["page"]
    # a long healthy stretch: the short window's anchor moves past the
    # incident and the burn drops to ~0
    eng.observe({"i": _fams(520, 10)}, at=400.0)
    eng.observe({"i": _fams(1020, 10)}, at=800.0)
    fired = eng.evaluate(at=800.0)
    assert [a.severity for a in fired] == ["ok"]
    assert eng.state("lat", "i") == "ok"
    # transition log keeps the whole story, oldest first
    assert [a.severity for a in eng.alerts] == ["page", "ok"]


def test_error_objective_and_scripted_windows():
    obj = Objective(name="err", kind="error", target=0.1)
    eng = BurnRateEngine([obj], windows=(("page", 60.0, 120.0, 2.0),))
    r0 = parse_families(_instance_registry(10, 0, 0, 1.0).render())
    r1 = parse_families(_instance_registry(10, 0, 5, 1.0).render())
    eng.observe({"i": r0}, at=0.0)
    eng.observe({"i": r1}, at=30.0)
    fired = eng.evaluate(at=30.0)
    # 5 bad / 5 total new → burn 10 > 2 on both (partial) windows
    assert [a.severity for a in fired] == ["page"]


def test_eps_burn_objective():
    obj = Objective(name="eps", kind="eps_burn", target=1.0,
                    eps_per_s=0.01)
    eng = BurnRateEngine([obj], windows=DEFAULT_WINDOWS)
    r0 = parse_families(_instance_registry(10, 0, 0, 1.0).render())
    r1 = parse_families(_instance_registry(10, 0, 0, 100.0).render())
    eng.observe({"i": r0}, at=0.0)
    eng.observe({"i": r1}, at=60.0)
    fired = eng.evaluate(at=60.0)
    # 99 ε in 60 s against a 0.01 ε/s schedule → burn 165 ≫ 14.4
    assert [a.severity for a in fired] == ["page"]
    assert fired[0].burn_short == pytest.approx(99.0 / 0.6)


def test_http_trigger_hook_never_raises_on_dead_instance():
    hook = http_trigger_hook({"i": "http://127.0.0.1:1"}, timeout_s=0.2)
    obj = Objective(name="lat", kind="latency", target=0.05,
                    threshold_s=0.5)
    eng = BurnRateEngine([obj], on_page=hook)
    eng.observe({"i": _fams(10, 0)}, at=0.0)
    eng.observe({"i": _fams(20, 10)}, at=60.0)
    assert [a.severity for a in eng.evaluate(at=60.0)] == ["page"]


# ------------------------------------------------ collector + CLI ----

def _canned_fleet_server(exposition: str, stats: dict):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            if self.path == "/metrics":
                blob = exposition.encode()
                ctype = "text/plain"
            elif self.path == "/stats":
                blob = json.dumps(stats).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def log_message(self, *a):  # keep pytest output clean
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_collector_scrapes_and_survives_dead_instances():
    httpd = _canned_fleet_server(
        _instance_registry(7, 0, 0, 0.5).render(),
        {"requests_total": 7, "ledger": {"parties": {}}})
    try:
        port = httpd.server_address[1]
        snap = FleetCollector(
            {"up": f"http://127.0.0.1:{port}",
             "down": "http://127.0.0.1:1"}).scrape(timeout_s=5)
        assert set(snap.live()) == {"up"}
        assert "down" in snap.errors()
        flat = families_to_flat(snap.aggregate())
        assert flat["dpcorr_serve_requests_total"] == 7.0
        doc = snap.to_doc()
        assert doc["instances"]["up"]["stats"]["requests_total"] == 7
        assert doc["instances"]["down"]["error"]
    finally:
        httpd.shutdown()


def test_obs_fleet_snapshot_cli_is_jax_free(tmp_path):
    httpd = _canned_fleet_server(
        _instance_registry(3, 1, 0, 0.25).render(),
        {"requests_total": 3, "ledger": {"parties": {}}})
    out_path = str(tmp_path / "snap.json")
    try:
        port = httpd.server_address[1]
        script = (
            "import sys\n"
            "sys.modules['jax'] = None\n"  # any jax import explodes
            "sys.argv = ['dpcorr', 'obs', 'fleet', 'snapshot',"
            " '--targets', 'solo=http://127.0.0.1:%d',"
            " '--out', %r, '--json']\n"
            "from dpcorr.__main__ import main\n"
            "main()\n" % (port, out_path))
        run = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr
        doc = json.loads(run.stdout)
        assert doc["version"] == 1
        assert doc["instances"]["solo"]["error"] is None
        assert doc["aggregate"]["dpcorr_serve_requests_total"] == 3.0
        # --out wrote the identical artifact
        assert json.load(open(out_path)) == doc
    finally:
        httpd.shutdown()


def test_obs_fleet_snapshot_cli_exits_1_when_all_dead(tmp_path):
    script = (
        "import sys\n"
        "sys.argv = ['dpcorr', 'obs', 'fleet', 'snapshot',"
        " '--targets', 'x=http://127.0.0.1:1', '--timeout', '0.2']\n"
        "from dpcorr.__main__ import main\n"
        "main()\n")
    run = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=120)
    assert run.returncode == 1


# -------------------------------------------------------- fleet console ----

def test_render_fleet_frame_rows_and_aggregate():
    from dpcorr.obs.console import render_fleet_frame
    from dpcorr.obs.fleet import FleetSnapshot

    text = _instance_registry(6, 0, 0, 0.5).render()
    snap = FleetSnapshot({
        "a": {"url": "http://h:1", "error": None, "exposition": text,
              "stats": {"batched_requests": 4, "unbatched_requests": 2,
                        "queue_depth": 1, "refused": {"budget": 1},
                        "latency_s": {"p50": 0.01, "p99": 0.02},
                        "ledger": {"parties": {"px": {
                            "spent": 0.5, "budget": 2.0}}}}},
        "dead": {"url": "http://h:2", "error": "URLError: refused",
                 "exposition": None, "stats": None},
    })
    frame = render_fleet_frame(snap, now=0.0)
    assert "1/2 instances up" in frame
    assert "dead" in frame and "DOWN" in frame
    assert "px=0.5" in frame
    # the aggregate line reads the merged registry, not the stats blobs
    assert "6 done" in frame
