"""Runtime lock-witness sanitizer (dpcorr/utils/syncwatch.py) and the
``dpcorr lint --witness`` diff gate (dpcorr/analysis/witness.py).

The in-process tests drive _WatchedLock directly (the factory only
wraps locks whose creation frame is a dpcorr source file, which test
files are not) — the factory's frame filter itself is covered by
constructing a real dpcorr object after enable(). jax is never needed:
both modules are stdlib-only by design.
"""

import json
import os
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from dpcorr.analysis.cli import main as lint_main
from dpcorr.analysis.witness import run_witness_check
from dpcorr.utils import syncwatch

REPO = Path(__file__).parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "lint"


@pytest.fixture
def watch():
    syncwatch.enable()
    try:
        yield syncwatch
    finally:
        syncwatch.disable()
        syncwatch._tls.stack = []


def make_lock(site, kind="lock"):
    real = syncwatch._real_rlock() if kind == "rlock" \
        else syncwatch._real_lock()
    with syncwatch._meta:
        syncwatch._locks.setdefault(site, kind)
    return syncwatch._WatchedLock(real, site, kind)


# ------------------------------------------------------- recording ----
def test_nested_acquisition_records_one_edge(watch):
    a = make_lock("dpcorr/x.py:10")
    b = make_lock("dpcorr/x.py:20")
    with a:
        with b:
            pass
    snap = watch.snapshot()
    assert snap["edges"] == [["dpcorr/x.py:10", "dpcorr/x.py:20"]]
    assert snap["inversions"] == []
    assert snap["locks"]["dpcorr/x.py:10"] == {"kind": "lock"}
    # repeating the same ordering adds nothing
    with a:
        with b:
            pass
    assert watch.snapshot()["edges"] == snap["edges"]


def test_order_inversion_detected_live(watch, capsys):
    a = make_lock("dpcorr/x.py:10")
    b = make_lock("dpcorr/x.py:20")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    snap = watch.snapshot()
    assert len(snap["edges"]) == 2
    assert snap["inversions"] == [
        {"held": "dpcorr/x.py:20", "acquiring": "dpcorr/x.py:10",
         "thread": threading.current_thread().name}]
    assert "lock-order inversion" in capsys.readouterr().err


def test_reentrant_rlock_records_no_self_edge(watch):
    r = make_lock("dpcorr/x.py:30", kind="rlock")
    with r:
        with r:
            pass
    snap = watch.snapshot()
    assert snap["edges"] == []
    assert snap["inversions"] == []
    assert not syncwatch._held()  # push/pop stayed balanced


def test_fsync_under_lock_counted(watch, tmp_path):
    a = make_lock("dpcorr/x.py:40")
    fd = os.open(str(tmp_path / "f"), os.O_CREAT | os.O_WRONLY)
    try:
        with a:
            os.fsync(fd)  # patched while enabled
        os.fsync(fd)      # not under any watched lock: not counted
    finally:
        os.close(fd)
    assert watch.snapshot()["fsync_under_lock"] == {"dpcorr/x.py:40": 1}


def test_factory_wraps_only_dpcorr_created_locks(watch):
    # created from this (non-dpcorr) frame: passes through untouched
    plain = threading.Lock()
    assert not isinstance(plain, syncwatch._WatchedLock)
    # created inside the dpcorr package: wrapped, site = creation line
    from dpcorr.obs.metrics import Registry
    reg = Registry()
    assert isinstance(reg._lock, syncwatch._WatchedLock)
    assert reg._lock.site.startswith("dpcorr/obs/metrics.py:")


def test_enable_idempotent_and_disable_restores():
    syncwatch.enable()
    factory = threading.Lock
    syncwatch.enable()
    assert threading.Lock is factory  # second enable is a no-op
    syncwatch.disable()
    assert threading.Lock is syncwatch._real_lock
    assert os.fsync is syncwatch._real_fsync
    assert syncwatch.snapshot()["edges"] == []


def test_dump_writes_witness_artifact(watch, tmp_path):
    a = make_lock("dpcorr/x.py:10")
    b = make_lock("dpcorr/x.py:20")
    with a:
        with b:
            pass
    path = watch.dump(str(tmp_path))
    assert os.path.basename(path) == f"witness-{os.getpid()}.json"
    art = json.loads(Path(path).read_text())
    assert art["pid"] == os.getpid()
    assert art["edges"] == [["dpcorr/x.py:10", "dpcorr/x.py:20"]]
    assert art["edge_threads"] == {
        "dpcorr/x.py:10 -> dpcorr/x.py:20":
            threading.current_thread().name}
    assert not list(tmp_path.glob("*.tmp.*"))  # dump is tmp+replace


# ---------------------------------------------------- witness gate ----
# static model for the gate tests: deep/lockorder_ok.py declares locks
# at lines 9 (_a) and 10 (_b) and the one order _a -> _b.
OK_FIX = "deep/lockorder_ok.py"
SITE_A = f"{OK_FIX}:9"
SITE_B = f"{OK_FIX}:10"


def write_witness(d, edges=(), inversions=(), name="witness-1.json"):
    d.mkdir(exist_ok=True)
    (d / name).write_text(json.dumps({
        "pid": 1, "locks": {}, "edges": [list(e) for e in edges],
        "edge_threads": {}, "inversions": list(inversions),
        "fsync_under_lock": {}, "threads": ["MainThread"]}))


def test_witness_missing_dir_and_empty_dir_are_usage_errors(tmp_path):
    assert run_witness_check([OK_FIX], str(FIXTURES),
                             str(tmp_path / "nope")) == 2
    (tmp_path / "empty").mkdir()
    assert run_witness_check([OK_FIX], str(FIXTURES),
                             str(tmp_path / "empty")) == 2


def test_witness_predicted_edge_is_clean(tmp_path, capsys):
    write_witness(tmp_path, edges=[(SITE_A, SITE_B)])
    assert run_witness_check([OK_FIX], str(FIXTURES),
                             str(tmp_path)) == 0
    assert "witness: clean" in capsys.readouterr().out


def test_witness_line_slack_matches_nearby_site(tmp_path):
    # creation frame two lines below the static site: same lock
    write_witness(tmp_path, edges=[(f"{OK_FIX}:11", SITE_B)])
    assert run_witness_check([OK_FIX], str(FIXTURES),
                             str(tmp_path)) == 0


def test_witness_unpredicted_edge_fails(tmp_path, capsys):
    write_witness(tmp_path, edges=[(SITE_B, SITE_A)])  # reverse order
    assert run_witness_check([OK_FIX], str(FIXTURES),
                             str(tmp_path)) == 1
    assert "observed-but-unpredicted lock order" in \
        capsys.readouterr().out


def test_witness_unknown_site_counts_as_unpredicted(tmp_path, capsys):
    write_witness(tmp_path, edges=[("dpcorr/nowhere.py:1", SITE_B)])
    assert run_witness_check([OK_FIX], str(FIXTURES),
                             str(tmp_path), as_json=True) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["unknown_sites"] == ["dpcorr/nowhere.py:1"]
    assert not report["ok"]


def test_witness_runtime_inversion_fails(tmp_path, capsys):
    write_witness(tmp_path, edges=[(SITE_A, SITE_B)],
                  inversions=[{"held": SITE_A, "acquiring": SITE_B,
                               "thread": "T1"}])
    assert run_witness_check([OK_FIX], str(FIXTURES),
                             str(tmp_path)) == 1
    assert "runtime lock-order inversion" in capsys.readouterr().out


def test_witness_cross_process_cycle_fails(tmp_path, capsys):
    """Two witnesses, each edge individually predicted by the cyclic
    fixture's (deliberately cyclic) model — the union still cycles."""
    cyc = "deep/lockorder_cycle_bad.py"
    write_witness(tmp_path, edges=[(f"{cyc}:9", f"{cyc}:10")])
    write_witness(tmp_path, edges=[(f"{cyc}:10", f"{cyc}:9")],
                  name="witness-2.json")
    assert run_witness_check([cyc], str(FIXTURES), str(tmp_path)) == 1
    assert "observed lock-order cycle" in capsys.readouterr().out


def test_cli_witness_wiring(tmp_path, capsys):
    write_witness(tmp_path, edges=[(SITE_A, SITE_B)])
    assert lint_main(["--root", str(FIXTURES),
                      "--witness", str(tmp_path), OK_FIX]) == 0
    capsys.readouterr()
    assert lint_main(["--root", str(FIXTURES),
                      "--witness", str(tmp_path / "nope"), OK_FIX]) == 2


def test_witness_gate_is_jax_free(tmp_path):
    """`dpcorr lint --witness` end-to-end on a jax-less interpreter
    (-S): builds the full static lock model for dpcorr/ and diffs a
    witness dir, without ever importing jax."""
    write_witness(tmp_path)  # no observed edges: trivially clean
    r = subprocess.run(
        [sys.executable, "-S", "-c",
         "import sys; sys.path.insert(0, '.'); "
         "from dpcorr.analysis import cli; "
         f"rc = cli.main(['--witness', {str(tmp_path)!r}, 'dpcorr']); "
         "assert 'jax' not in sys.modules, 'witness gate pulled jax'; "
         "sys.exit(rc)"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])


def test_syncwatch_dump_survives_chaos_kill(tmp_path):
    """enable() registers the dump with chaos.on_crash, so a planned
    os._exit(42) kill still leaves a witness artifact behind."""
    code = (
        "import os\n"
        "os.environ['DPCORR_SYNCWATCH'] = '1'\n"
        f"os.environ['DPCORR_SYNCWATCH_DIR'] = {str(tmp_path)!r}\n"
        "import sys; sys.path.insert(0, '.')\n"
        "import dpcorr\n"
        "from dpcorr import chaos\n"
        "from dpcorr.obs.metrics import Registry\n"
        "c = Registry().counter('x', 'help')\n"
        "c.inc()\n"
        "plan = chaos.ChaosPlan('ledger.pre_persist', hit=1)\n"
        "chaos.install(plan)\n"
        "chaos.point('ledger.pre_persist')\n"
        "raise SystemExit('chaos point did not fire')\n")
    r = subprocess.run([sys.executable, "-S", "-c", code],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO)
    assert r.returncode == chaos_exit_code(), (r.stdout, r.stderr)
    arts = list(tmp_path.glob("witness-*.json"))
    assert len(arts) == 1
    art = json.loads(arts[0].read_text())
    assert any(site.startswith("dpcorr/obs/metrics.py:")
               for site in art["locks"])


def chaos_exit_code():
    from dpcorr import chaos
    return chaos.EXIT_CODE
