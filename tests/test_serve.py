"""Serving subsystem tests (ISSUE 1): ledger accounting, coalescer
bit-identity, kernel cache, backpressure, stats, HTTP front end, and an
in-process concurrent load drive.

The bit-identity reference is always the *direct* single-request call —
``jit(single)`` of the same ``serving_entry`` closure on the same
key-tree address — which the default ``exact`` batch engine must match
bit-for-bit (estimators.registry contract).
"""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from dpcorr.models.estimators.registry import FAMILIES, serving_entry
from dpcorr.serve import (
    BudgetExceededError,
    DpcorrServer,
    EstimateRequest,
    InProcessClient,
    KernelCache,
    PrivacyLedger,
    ServerClosedError,
    ServerOverloadedError,
    ServeStats,
    make_http_server,
    pinned_request_key,
    request_charges,
)
from dpcorr.serve.kernels import pad_batch
from dpcorr.serve.request import bucket_key, kernel_key, pad_n
from dpcorr.serve.stats import percentiles
from dpcorr.utils import rng


def _mk_req(n=96, family="ni_sign", seed=None, i=0, **kw):
    rs = np.random.RandomState(100 + i)
    return EstimateRequest(family, rs.randn(n).astype(np.float32),
                          rs.randn(n).astype(np.float32),
                          1.0, 0.5, seed=seed, **kw)


def _direct(server, req):
    """The reference answer: the plain jitted single-request program on
    the request's key-tree address (the pinned subtree — seed folded
    into stream(master, "serve/pinned"), then content-bound)."""
    single = serving_entry(req.family, req.eps1, req.eps2,
                           alpha=req.alpha, normalise=req.normalise)
    key = pinned_request_key(rng.master_key(server.seed), req, req.seed)
    return tuple(float(v) for v in jax.jit(single)(key, req.x, req.y))


# ---------------------------------------------------------------- units ----

def test_pad_n_buckets():
    assert pad_n(2) == 64          # floor
    assert pad_n(64) == 64
    assert pad_n(65) == 128
    assert pad_n(500) == 512
    assert pad_n(512) == 512
    assert pad_n(513) == 1024


def test_pad_batch():
    assert [pad_batch(b) for b in (1, 2, 3, 4, 5, 13, 16, 17)] == \
        [1, 2, 4, 4, 8, 16, 16, 32]


def test_request_validation():
    with pytest.raises(ValueError, match="unknown estimator family"):
        _mk_req(family="nope")
    with pytest.raises(ValueError, match="equal-length"):
        EstimateRequest("ni_sign", np.zeros(8, np.float32),
                        np.zeros(9, np.float32), 1.0, 1.0)
    with pytest.raises(ValueError, match="eps must be positive"):
        EstimateRequest("ni_sign", np.zeros(8, np.float32),
                        np.zeros(8, np.float32), 0.0, 1.0)
    with pytest.raises(ValueError, match="at least two"):
        EstimateRequest("ni_sign", np.zeros(1, np.float32),
                        np.zeros(1, np.float32), 1.0, 1.0)


def test_bucket_vs_kernel_key():
    a, b = _mk_req(n=400, i=0), _mk_req(n=500, i=1)
    assert bucket_key(a) == bucket_key(b)      # both pad to 512
    assert kernel_key(a) != kernel_key(b)      # exact n differs
    c = _mk_req(n=400, family="int_sign", i=2)
    assert bucket_key(a) != bucket_key(c)


# --------------------------------------------------------------- ledger ----

def test_request_charges_composition():
    # sign family + normalise: private centering doubles each side's spend
    r = _mk_req(family="ni_sign", party_x="a", party_y="b")
    assert request_charges(r) == {"a": 2.0, "b": 1.0}
    # subG families clip with data-independent bounds: spend once
    r = _mk_req(family="ni_subg", party_x="a", party_y="b")
    assert request_charges(r) == {"a": 1.0, "b": 0.5}
    # same party on both sides accumulates
    r = _mk_req(family="int_sign", party_x="a", party_y="a")
    assert request_charges(r) == {"a": 3.0}
    r = _mk_req(family="ni_sign", normalise=False, party_x="a", party_y="b")
    assert request_charges(r) == {"a": 1.0, "b": 0.5}


def test_ledger_arithmetic_and_refusal():
    led = PrivacyLedger(budget=5.0)
    led.charge({"a": 2.0, "b": 1.0})
    led.charge({"a": 2.0})
    assert led.spent("a") == pytest.approx(4.0)
    assert led.remaining("a") == pytest.approx(1.0)
    # exact landing on the cap is admitted (strict >)
    led.charge({"a": 1.0})
    assert led.remaining("a") == pytest.approx(0.0)
    with pytest.raises(BudgetExceededError) as ei:
        led.charge({"a": 1e-6})
    assert ei.value.party == "a"
    # refused charge must not partially mutate any party (all-or-nothing)
    before_b = led.spent("b")
    with pytest.raises(BudgetExceededError):
        led.charge({"b": 0.5, "a": 1.0})
    assert led.spent("b") == before_b


def test_ledger_per_party_override():
    led = PrivacyLedger(budget=100.0, per_party={"tight": 1.0})
    led.charge({"tight": 1.0, "loose": 50.0})
    with pytest.raises(BudgetExceededError):
        led.charge({"tight": 0.1})
    led.charge({"loose": 50.0})


def test_ledger_persistence_across_restart(tmp_path):
    path = str(tmp_path / "ledger.json")
    led = PrivacyLedger(budget=3.0, path=path)
    led.charge({"a": 2.0})
    # simulated crash + restart: a fresh process loads the spend table
    led2 = PrivacyLedger(budget=3.0, path=path)
    assert led2.spent("a") == pytest.approx(2.0)
    led2.charge({"a": 1.0})
    # the same query again would double-spend — must refuse
    with pytest.raises(BudgetExceededError):
        led2.charge({"a": 1.0})
    # third incarnation still sees the full spend
    led3 = PrivacyLedger(budget=3.0, path=path)
    assert led3.spent("a") == pytest.approx(3.0)
    state = json.load(open(path))
    assert state["version"] == 1 and state["spent"]["a"] == pytest.approx(3.0)


def test_ledger_persist_is_write_ahead(tmp_path):
    """The spend is on disk before charge() returns — a crash after a
    successful charge can never resurrect the budget."""
    path = str(tmp_path / "ledger.json")
    led = PrivacyLedger(budget=10.0, path=path)
    led.charge({"a": 4.0})
    on_disk = json.load(open(path))["spent"]["a"]
    assert on_disk == pytest.approx(4.0)


def test_ledger_rejects_unknown_state_version(tmp_path):
    path = tmp_path / "ledger.json"
    path.write_text(json.dumps({"version": 99, "spent": {}}))
    with pytest.raises(ValueError, match="version"):
        PrivacyLedger(budget=1.0, path=str(path))


# ---------------------------------------------------------------- stats ----

def test_percentiles_nearest_rank():
    vals = list(range(1, 101))
    p = percentiles(vals)
    assert p == {"p50": 50, "p99": 99}
    assert percentiles([]) == {}
    assert percentiles([7.0]) == {"p50": 7.0, "p99": 7.0}


def test_stats_fill_ratio_and_snapshot():
    st = ServeStats()
    assert st.batch_fill_ratio() == 0.0
    st.flushed(8, batched=True)
    st.flushed(1, batched=False)
    assert st.batch_fill_ratio() == pytest.approx(4.5)
    snap = st.snapshot(ledger_snapshot={"budget_default": 1.0,
                                        "parties": {}})
    assert snap["batches_flushed"] == 2
    assert snap["flush_size_max"] == 8
    assert snap["ledger"]["budget_default"] == 1.0


def test_serve_stats_frame():
    from dpcorr.report import serve_stats_frame

    st = ServeStats()
    st.admitted()
    st.flushed(4, batched=True)
    st.observe_latency(0.01)
    df = serve_stats_frame(st.snapshot(
        ledger_snapshot={"budget_default": 2.0,
                         "parties": {"a": {"spent": 1.0, "budget": 2.0,
                                           "remaining": 1.0}}}))
    metrics = dict(zip(df["metric"], df["value"]))
    assert metrics["requests_total"] == 1
    assert metrics["ledger.parties.a.spent"] == 1.0
    assert metrics["latency_s.p50"] == pytest.approx(0.01)


# -------------------------------------------------------------- kernels ----

def test_kernel_cache_counts_compiles_and_hits():
    cache = KernelCache(shard="off")
    kk = kernel_key(_mk_req(n=64))
    f1, _ = cache.get(kk, 4)
    f2, _ = cache.get(kk, 4)
    assert f1 is f2
    assert cache.stats.kernel_compiles == 1
    assert cache.stats.kernel_hits == 1
    # different padded width = different compiled signature
    cache.get(kk, 8)
    assert cache.stats.kernel_compiles == 2


def test_kernel_cache_rejects_bad_modes():
    with pytest.raises(ValueError, match="shard"):
        KernelCache(shard="maybe")
    with pytest.raises(ValueError, match="mode"):
        KernelCache(mode="fast")
    with pytest.raises(ValueError, match="max_kernels"):
        KernelCache(max_kernels=0)


def test_kernel_cache_lru_bounded():
    """Signatures include the exact n, so an n-sweeping client would
    grow the cache without bound; the LRU cap holds it at max_kernels
    and the live count is a stats gauge (REVIEW: low)."""
    cache = KernelCache(shard="off", max_kernels=2)
    kks = [kernel_key(_mk_req(n=64 + j)) for j in range(3)]
    for kk in kks:
        cache.get(kk, 4)
    assert len(cache._fns) == 2
    assert cache.stats.kernel_cache_size == 2
    # kks[0] was evicted (least recently used) → re-get recompiles,
    # displacing kks[1]; cache is now [kks[2], kks[0]]
    compiles = cache.stats.kernel_compiles
    cache.get(kks[0], 4)
    assert cache.stats.kernel_compiles == compiles + 1
    assert (kks[1], 4, 1) not in cache._fns
    # a hit refreshes recency: touching kks[2] makes kks[0] the LRU,
    # so the next insert evicts kks[0] and keeps kks[2]
    hits = cache.stats.kernel_hits
    cache.get(kks[2], 4)
    assert cache.stats.kernel_hits == hits + 1
    cache.get(kernel_key(_mk_req(n=200)), 4)
    assert (kks[0], 4, 1) not in cache._fns
    assert (kks[2], 4, 1) in cache._fns
    assert cache.stats.snapshot()["kernel_cache_size"] == 2


@pytest.mark.parametrize("family", FAMILIES)
def test_exact_batch_bit_identical_to_direct(family):
    """The exact engine's batched lanes — including padding truncation
    (b=5 pads to 8) — are bit-identical to jit(single) for EVERY family."""
    n, b = 96, 5
    single = serving_entry(family, 1.0, 0.5)
    js = jax.jit(single)
    cache = KernelCache(shard="off", mode="exact")
    kk = kernel_key(_mk_req(n=n, family=family))
    master = rng.master_key(11)
    rs = np.random.RandomState(3)
    xs = rs.randn(b, n).astype(np.float32)
    ys = rs.randn(b, n).astype(np.float32)
    import jax.numpy as jnp
    keys = jnp.stack([rng.design_key(master, i) for i in range(b)])
    out = cache.run_batch(kk, keys, xs, ys)
    assert out[0].shape == (b,)
    for i in range(b):
        ref = tuple(float(v) for v in js(keys[i], xs[i], ys[i]))
        got = tuple(float(out[j][i]) for j in range(3))
        assert got == ref, (family, i)


def test_vector_batch_rho_exact_and_width_invariant():
    """The vector engine: rho_hat bit-identical to direct, CI within
    1 ulp; lanes bit-identical across batch widths ≥ 2."""
    n, b = 96, 8
    single = serving_entry("ni_sign", 1.0, 0.5)
    js = jax.jit(single)
    cache = KernelCache(shard="off", mode="vector")
    kk = kernel_key(_mk_req(n=n))
    master = rng.master_key(11)
    rs = np.random.RandomState(3)
    xs = rs.randn(b, n).astype(np.float32)
    ys = rs.randn(b, n).astype(np.float32)
    import jax.numpy as jnp
    keys = jnp.stack([rng.design_key(master, i) for i in range(b)])
    full = cache.run_batch(kk, keys, xs, ys)
    for i in range(b):
        ref = tuple(float(v) for v in js(keys[i], xs[i], ys[i]))
        assert float(full[0][i]) == ref[0]
        np.testing.assert_allclose(
            [float(full[1][i]), float(full[2][i])], ref[1:], rtol=3e-7)
    # width invariance: the first two lanes served as a pair match the
    # same lanes served in the width-8 batch, bit for bit
    pair = cache.run_batch(kk, keys[:2], xs[:2], ys[:2])
    for j in range(3):
        assert float(pair[j][0]) == float(full[j][0])
        assert float(pair[j][1]) == float(full[j][1])


def test_sharded_batch_bit_identical(devices):
    """With the batch axis split over the 8-device mesh, exact-engine
    lanes still match jit(single) bit-for-bit."""
    n, b = 96, 16  # 16 % 8 == 0 → sharded path
    single = serving_entry("ni_sign", 1.0, 0.5)
    js = jax.jit(single)
    cache = KernelCache(shard="auto", mode="exact")
    kk = kernel_key(_mk_req(n=n))
    master = rng.master_key(11)
    rs = np.random.RandomState(3)
    xs = rs.randn(b, n).astype(np.float32)
    ys = rs.randn(b, n).astype(np.float32)
    import jax.numpy as jnp
    keys = jnp.stack([rng.design_key(master, i) for i in range(b)])
    shards = cache._n_shards(pad_batch(b))
    assert shards == 8
    out = cache.run_batch(kk, keys, xs, ys)
    for i in range(0, b, 3):
        ref = tuple(float(v) for v in js(keys[i], xs[i], ys[i]))
        assert tuple(float(out[j][i]) for j in range(3)) == ref


# --------------------------------------------------------------- server ----

def test_server_estimate_matches_direct_call():
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        req = _mk_req(seed=42)
        resp = srv.estimate(req)
        assert _direct(srv, req) == (resp.rho_hat, resp.ci_low, resp.ci_high)
        assert resp.seed == 42 and resp.batch_size == 1
    finally:
        srv.close()


def test_server_concurrent_load_coalesces_and_bit_matches():
    """An in-process load drive: concurrent clients, one bucket; asserts
    fill ratio > 1 and every response bit-identical to the direct call."""
    n_req, n_clients = 192, 8
    srv = DpcorrServer(budget=1e6, max_batch=32, max_delay_s=0.05,
                       max_queue=4 * n_req, shard="off")
    cli = InProcessClient(srv)
    reqs = [_mk_req(seed=i, i=i) for i in range(n_req)]
    out: dict[int, object] = {}
    lock = threading.Lock()
    per = n_req // n_clients

    def worker(c):
        futs = [(i, cli.submit(reqs[i]))
                for i in range(c * per, (c + 1) * per)]
        for i, f in futs:
            r = f.result(timeout=120)
            with lock:
                out[i] = r
    try:
        ts = [threading.Thread(target=worker, args=(c,))
              for c in range(n_clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        srv.close()
    assert len(out) == n_req
    snap = cli.stats()
    assert snap["batch_fill_ratio"] > 1.0
    assert snap["batched_requests"] > 0
    for i in (0, 7, 63, 100, n_req - 1):
        r = out[i]
        assert _direct(srv, reqs[i]) == (r.rho_hat, r.ci_low, r.ci_high), i


def test_server_refuses_over_budget_first_query():
    """The first query that would overdraw is refused; earlier ones all
    admitted — the acceptance criterion, at the server boundary.
    Distinct seeds per query: identical pinned requests would dedupe
    through the idempotency cache and never re-charge."""
    req = _mk_req(seed=1)  # ni_sign+normalise: spends 2*eps1 on party_x
    charges = request_charges(req)
    budget = 3 * charges["party-x"]
    srv = DpcorrServer(budget=1e6,
                       per_party_budget={"party-x": budget},
                       max_delay_s=0.001, shard="off")
    try:
        for s in range(3):
            srv.estimate(_mk_req(seed=s + 1))
        with pytest.raises(BudgetExceededError):
            srv.estimate(_mk_req(seed=4))
        snap = srv.stats_snapshot()
        assert snap["requests_total"] == 3
        assert snap["requests_refused_budget"] == 1
        assert snap["ledger"]["parties"]["party-x"]["remaining"] == \
            pytest.approx(0.0)
    finally:
        srv.close()


def test_server_refusal_spends_nothing():
    req = _mk_req(seed=1)
    srv = DpcorrServer(budget=1e6, per_party_budget={"party-x": 0.5},
                       max_delay_s=0.001, shard="off")
    try:
        with pytest.raises(BudgetExceededError):
            srv.submit(req)
        assert srv.ledger.spent("party-x") == 0.0
        assert srv.ledger.spent("party-y") == 0.0
    finally:
        srv.close()


def test_server_ledger_survives_restart(tmp_path):
    path = str(tmp_path / "ledger.json")
    req = _mk_req(seed=1)
    budget = 2 * request_charges(req)["party-x"]
    srv = DpcorrServer(budget=1e6, ledger_path=path,
                       per_party_budget={"party-x": budget},
                       max_delay_s=0.001, shard="off")
    srv.estimate(req)
    srv.close()  # "crash" after one answered query
    srv2 = DpcorrServer(budget=1e6, ledger_path=path,
                        per_party_budget={"party-x": budget},
                        max_delay_s=0.001, shard="off")
    try:
        # distinct seeds: a replay of seed=1 would be an idempotency
        # hit on a fresh server only if the cache persisted — it does
        # not, so use new queries to probe the reloaded ledger state
        srv2.estimate(_mk_req(seed=2))  # second query still fits
        with pytest.raises(BudgetExceededError):
            srv2.estimate(_mk_req(seed=3))  # would double-spend — refused
    finally:
        srv2.close()


def test_idempotent_replay_no_second_charge_or_launch():
    """ISSUE 7 acceptance: retrying a pinned request returns the
    ORIGINAL response object with zero additional ledger charge and
    zero additional kernel launches — proven by the obs counters, not
    just by value equality."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        r1 = srv.estimate(_mk_req(seed=7))
        spent = srv.ledger.spent("party-x")
        flushes = srv.stats.batches_flushed
        admitted = srv.stats.requests_total
        r2 = srv.estimate(_mk_req(seed=7))  # same bytes, same seed
        assert r2 is r1  # the cached object itself — byte-identical
        assert srv.ledger.spent("party-x") == pytest.approx(spent)
        assert srv.stats.batches_flushed == flushes  # no kernel ran
        assert srv.stats.requests_total == admitted  # never re-admitted
        assert srv.stats.idempotent_hits_completed == 1
    finally:
        srv.close()


def test_idempotent_inflight_duplicates_share_future():
    """A duplicate arriving while the original is still queued attaches
    to the same future: one charge, one launch, both callers answered."""
    srv = DpcorrServer(budget=1e6, max_batch=2, max_delay_s=30.0,
                       shard="off")
    try:
        f1 = srv.submit(_mk_req(seed=11))
        spent = srv.ledger.spent("party-x")
        f2 = srv.submit(_mk_req(seed=11))
        assert f2 is f1
        assert srv.stats.idempotent_hits_inflight == 1
        assert srv.ledger.spent("party-x") == pytest.approx(spent)
        # a second DISTINCT request fills the size-2 bucket → flush
        srv.submit(_mk_req(seed=12, i=1))
        assert f1.result(timeout=60) is f2.result(timeout=60)
    finally:
        srv.close()


def test_idempotency_scoped_by_charged_parties():
    """Same bytes, same seed, different billed party: a different
    ledger operation, never deduped. The content digest deliberately
    excludes party names (noise-stream binding) — the idempotency key
    must not."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        srv.estimate(_mk_req(seed=7))
        srv.estimate(_mk_req(seed=7, party_x="alice"))
        assert srv.stats.idempotent_hits_completed == 0
        assert srv.ledger.spent("party-x") > 0.0
        assert srv.ledger.spent("alice") > 0.0
    finally:
        srv.close()


def test_explicit_idempotency_key_on_assigned_stream():
    """Unpinned requests have no default retry identity (every
    submission is deliberately a fresh draw), but an explicit client
    key makes retries safe; without one, resubmission charges and
    draws again."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        r1 = srv.estimate(_mk_req(idempotency_key="job-1"))
        r2 = srv.estimate(_mk_req(idempotency_key="job-1"))
        assert r2 is r1
        spent = srv.ledger.spent("party-x")
        a = srv.estimate(_mk_req())
        b = srv.estimate(_mk_req())
        assert a.seed != b.seed  # fresh streams, not a replay
        assert srv.ledger.spent("party-x") > spent
    finally:
        srv.close()


def test_http_idempotent_retry_byte_identical():
    """The wire-level acceptance check: POSTing the same pinned request
    twice returns byte-identical bodies, with the stats endpoint
    counting one admission and one idempotent hit."""
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    httpd = make_http_server(srv, host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"
    req = _mk_req(seed=5)
    body = json.dumps({"family": "ni_sign", "x": req.x.tolist(),
                       "y": req.y.tolist(), "eps1": 1.0, "eps2": 0.5,
                       "seed": 5}).encode()

    def post():
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/estimate", data=body,
                headers={"Content-Type": "application/json"})) as r:
            assert r.status == 200
            return r.read()
    try:
        first, second = post(), post()
        assert first == second
        with urllib.request.urlopen(f"{base}/stats") as r:
            snap = json.load(r)
        assert snap["requests_total"] == 1
        assert snap["idempotent_hits_completed"] == 1
    finally:
        httpd.shutdown()
        srv.close()


def test_overload_shed_refunds_budget():
    """A 429 must not consume ε: the charge lands before the enqueue,
    so a queue-refused request gets its spend reversed — retrying
    clients under sustained overload can't drain budgets with zero
    queries served (REVIEW: medium)."""
    srv = DpcorrServer(budget=1e6, max_batch=1024, max_delay_s=30.0,
                       max_queue=2, shard="off")
    try:
        futs = [srv.submit(_mk_req(seed=i)) for i in range(2)]
        spent_before = srv.ledger.spent("party-x")
        for _ in range(3):  # repeated sheds refund every time
            with pytest.raises(ServerOverloadedError):
                srv.submit(_mk_req(seed=99))
        assert srv.ledger.spent("party-x") == pytest.approx(spent_before)
        assert srv.stats.requests_refused_overload == 3
        # admitted counter counts only successfully enqueued requests
        assert srv.stats.requests_total == 2
    finally:
        srv.close()
    # close() drains the still-queued requests as explicit refusals and
    # reverses their charges — nothing silently hangs, nothing is spent
    for f in futs:
        with pytest.raises(ServerClosedError):
            f.result(timeout=60)
    assert srv.ledger.spent("party-x") == pytest.approx(0.0)


def test_ledger_refund_reverses_and_clamps(tmp_path):
    path = str(tmp_path / "ledger.json")
    led = PrivacyLedger(budget=3.0, path=path)
    led.charge({"a": 2.0, "b": 1.0})
    led.refund({"a": 2.0})
    assert led.spent("a") == pytest.approx(0.0)
    assert led.spent("b") == pytest.approx(1.0)
    # the reversal is persisted like a charge
    led2 = PrivacyLedger(budget=3.0, path=path)
    assert led2.spent("a") == pytest.approx(0.0)
    # over-refund clamps at zero (errs toward privacy) and negative
    # refunds are rejected outright
    led.refund({"b": 5.0})
    assert led.spent("b") == 0.0
    with pytest.raises(ValueError, match="negative refund"):
        led.refund({"a": -1.0})


def test_coalescer_backpressure_sheds_load():
    # a delay window far longer than the test: nothing flushes while we
    # overfill the queue
    srv = DpcorrServer(budget=1e6, max_batch=1024, max_delay_s=30.0,
                       max_queue=4, shard="off")
    try:
        futs = [srv.submit(_mk_req(seed=i)) for i in range(4)]
        with pytest.raises(ServerOverloadedError):
            srv.submit(_mk_req(seed=99))
        assert srv.stats.requests_refused_overload == 1
    finally:
        srv.close()  # close drains: pending become refusals + refunds
    for f in futs:
        with pytest.raises(ServerClosedError):
            f.result(timeout=60)
    assert srv.ledger.spent("party-x") == pytest.approx(0.0)
    assert srv.stats.snapshot()["shed"]["closed"] == 4


def test_server_assigns_seeds_when_unpinned():
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        r1 = srv.estimate(_mk_req(seed=None, i=0))
        r2 = srv.estimate(_mk_req(seed=None, i=0))
        # distinct admission-counter seeds → distinct noise draws on
        # identical data
        assert r1.seed != r2.seed
        assert r1.rho_hat != r2.rho_hat
    finally:
        srv.close()


def test_assigned_streams_differ_across_restarts():
    """The counter restarts at 0 on every boot while the ledger does
    not — without the per-boot nonce the first unpinned query of every
    incarnation would reuse one noise stream, letting a client
    difference the noise away across restarts (REVIEW: high)."""
    req = _mk_req(seed=None, i=0)
    rhos = []
    for _ in range(2):  # two "boots" of the same configuration
        srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
        try:
            r = srv.estimate(req)
            assert r.seed == 0  # same counter seed both times ...
            rhos.append(r.rho_hat)
        finally:
            srv.close()
    assert rhos[0] != rhos[1]  # ... but independent noise streams


def test_pinned_seed_bound_to_request_content():
    """A repeated pinned seed over DIFFERENT data must draw independent
    noise (no differencing), while the identical request stays exactly
    replayable — across server incarnations."""
    a, b = _mk_req(seed=7, i=0), _mk_req(seed=7, i=1)
    # the two derived keys differ although seed and bucket coincide
    master = rng.master_key(rng.MASTER_SEED)
    ka = pinned_request_key(master, a, 7)
    kb = pinned_request_key(master, b, 7)
    assert not np.array_equal(jax.random.key_data(ka),
                              jax.random.key_data(kb))
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        ra, rb = srv.estimate(a), srv.estimate(b)
    finally:
        srv.close()
    # noise independence: identical seed, different data → the noisy
    # answers are not related by the data-only difference
    assert ra.rho_hat != rb.rho_hat
    # exact replay of the identical request survives a restart
    srv2 = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        ra2 = srv2.estimate(a)
    finally:
        srv2.close()
    assert (ra.rho_hat, ra.ci_low, ra.ci_high) == \
        (ra2.rho_hat, ra2.ci_low, ra2.ci_high)


def test_pinned_and_assigned_subtrees_disjoint():
    """A client pinning seed k and the server assigning counter seed k
    must not share a stream: the subtrees are separated by named-stream
    tags under the master key."""
    req = _mk_req(seed=3, i=0)
    master = rng.master_key(rng.MASTER_SEED)
    pinned = pinned_request_key(master, req, 3)
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        unpinned = srv._request_key(_mk_req(seed=None, i=0), 3)
    finally:
        srv.close()
    assert not np.array_equal(jax.random.key_data(pinned),
                              jax.random.key_data(unpinned))


# ----------------------------------------------------------------- HTTP ----

def test_http_endpoints_smoke():
    srv = DpcorrServer(budget=1e6,
                       per_party_budget={"tiny": 0.1},
                       max_delay_s=0.001, shard="off")
    httpd = make_http_server(srv, host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"

    def post(payload, expect):
        try:
            with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/estimate", data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})) as r:
                assert r.status == expect
                return json.load(r)
        except urllib.error.HTTPError as e:
            assert e.code == expect
            return json.load(e)

    try:
        with urllib.request.urlopen(f"{base}/healthz") as r:
            assert json.load(r) == {"ok": True}
        req = _mk_req(seed=5)
        body = {"family": "ni_sign", "x": req.x.tolist(),
                "y": req.y.tolist(), "eps1": 1.0, "eps2": 0.5, "seed": 5}
        got = post(body, 200)
        assert _direct(srv, req) == (got["rho_hat"], got["ci_low"],
                                     got["ci_high"])
        # invalid request → 400
        post({"family": "nope", "x": [1, 2], "y": [1, 2],
              "eps1": 1, "eps2": 1}, 400)
        # over-budget party → 403
        refused = post(dict(body, party_x="tiny"), 403)
        assert refused["refused"] == "budget"
        with urllib.request.urlopen(f"{base}/stats") as r:
            snap = json.load(r)
        assert snap["requests_total"] == 1
        assert snap["requests_refused_budget"] == 1
        assert "ledger" in snap
    finally:
        httpd.shutdown()
        srv.close()


# ----------------------------------------------------- telemetry (obs) ----

def test_metrics_endpoint_matches_stats():
    """ISSUE 2 acceptance: GET /metrics serves valid Prometheus text
    whose counters agree numerically with the GET /stats snapshot —
    both views read the same obs registry."""
    from dpcorr.obs import CONTENT_TYPE, parse_exposition

    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    httpd = make_http_server(srv, host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        for i in range(3):
            srv.estimate(_mk_req(seed=i, i=i), timeout=60)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as r:
            assert r.headers["Content-Type"] == CONTENT_TYPE
            text = r.read().decode()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats") as r:
            snap = json.load(r)
        series = parse_exposition(text)
        assert "# TYPE dpcorr_serve_requests_total counter" in text
        assert series["dpcorr_serve_requests_total"] == \
            snap["requests_total"]
        assert series["dpcorr_serve_batches_flushed_total"] == \
            snap["batches_flushed"]
        assert series["dpcorr_serve_kernel_compiles_total"] == \
            snap["kernel_compiles"]
        assert series["dpcorr_serve_latency_seconds_count"] == \
            snap["batched_requests"] + snap["unbatched_requests"]
        # the ledger publishes into the same registry (server wiring)
        assert series['dpcorr_ledger_events_total{kind="charge"}'] == 3.0
        assert series['dpcorr_ledger_spent_eps{party="party-x"}'] == \
            snap["ledger"]["parties"]["party-x"]["spent"]
    finally:
        httpd.shutdown()
        srv.close()


def test_snapshot_latency_histogram_additive():
    """snapshot() keeps the pre-obs keys (latency_s percentiles from
    the reservoir) and adds the bucketed histogram view."""
    st = ServeStats()
    st.observe_latency(0.003)
    st.observe_latency(0.3)
    snap = st.snapshot()
    assert snap["latency_s"]["p50"] in (0.003, 0.3)
    hist = snap["latency_histogram"]
    assert hist["count"] == 2
    assert hist["sum"] == pytest.approx(0.303)
    assert hist["buckets"]["0.005"] == 1  # cumulative: only the 3ms obs
    assert hist["buckets"]["0.5"] == 2


def test_trace_chain_links_request_to_flush(tmp_path):
    """ISSUE 2 acceptance: a single trace ID links one request's span
    chain from admission through ledger charge to kernel flush."""
    from dpcorr.obs import Tracer, read_spans

    path = str(tmp_path / "spans.jsonl")
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off",
                       tracer=Tracer(path))
    try:
        resp = srv.estimate(_mk_req(seed=0), timeout=60)
    finally:
        srv.close()
    spans = read_spans(path)
    by_name = {s["name"]: s for s in spans}
    root = by_name["serve.request"]
    chain = {s["name"] for s in spans if s["trace_id"] == root["trace_id"]}
    assert {"serve.request", "serve.admit", "serve.ledger.charge",
            "serve.enqueue", "serve.flush", "serve.kernel"} <= chain
    # tree shape: admit under root, charge under admit, flush under root
    assert by_name["serve.admit"]["parent_id"] == root["span_id"]
    assert by_name["serve.ledger.charge"]["parent_id"] == \
        by_name["serve.admit"]["span_id"]
    assert by_name["serve.flush"]["parent_id"] == root["span_id"]
    assert by_name["serve.kernel"]["parent_id"] == \
        by_name["serve.flush"]["span_id"]
    # the root closes at respond with the end-to-end latency
    assert root["attrs"]["latency_s"] == pytest.approx(resp.latency_s)
    # client thread vs coalescer flush thread, one trace across both
    assert by_name["serve.flush"]["thread"] == "dpcorr-serve-flush"
    assert root["thread"] != by_name["serve.flush"]["thread"]


def test_refused_request_span_ends_with_reason(tmp_path):
    from dpcorr.obs import Tracer, read_spans

    path = str(tmp_path / "spans.jsonl")
    srv = DpcorrServer(budget=1e6, per_party_budget={"tiny": 0.01},
                       max_delay_s=0.001, shard="off",
                       tracer=Tracer(path))
    try:
        with pytest.raises(BudgetExceededError):
            srv.submit(_mk_req(seed=0, party_x="tiny"))
    finally:
        srv.close()
    roots = [s for s in read_spans(path) if s["name"] == "serve.request"]
    assert roots and roots[0]["attrs"]["refused"] == "budget"


def test_audit_trail_replays_to_ledger_state(tmp_path):
    """ISSUE 2 acceptance: the per-party ε spend is reproducible from
    the audit trail alone — replay(trail) == ledger snapshot — and
    every event carries the request's trace ID."""
    from dpcorr.obs import Tracer, read_events, replay

    audit = str(tmp_path / "audit.jsonl")
    srv = DpcorrServer(budget=1e6, per_party_budget={"tiny": 0.01},
                       max_delay_s=0.001, shard="off",
                       tracer=Tracer(str(tmp_path / "spans.jsonl")),
                       audit=audit)
    try:
        for i in range(3):
            srv.estimate(_mk_req(seed=i, i=i), timeout=60)
        with pytest.raises(BudgetExceededError):
            srv.submit(_mk_req(seed=9, party_x="tiny"))
        ledger_snap = srv.ledger.snapshot()
    finally:
        srv.close()
    events = read_events(audit)
    assert [e["kind"] for e in events] == ["charge"] * 3 + ["refusal"]
    assert all(e["trace_id"] for e in events)
    spent = replay(events)
    assert set(spent) == set(ledger_snap["parties"])
    for p, s in spent.items():
        assert s == pytest.approx(ledger_snap["parties"][p]["spent"])
    # the refusal event names the violating party and its standing
    refusal = events[-1]
    assert refusal["party"] == "tiny" and refusal["budget"] == 0.01


def test_overload_refund_lands_in_audit():
    """A backpressure-shed request leaves a charge+refund pair sharing
    one trace ID: net-zero spend, fully auditable."""
    from dpcorr.obs import AuditTrail, replay

    trail = AuditTrail()
    # long delay + wide batch: the first request stays queued, so the
    # second overflows max_queue deterministically
    srv = DpcorrServer(budget=1e6, max_queue=1, max_batch=1024,
                       max_delay_s=30.0, shard="off", audit=trail)
    try:
        fut = srv.submit(_mk_req(seed=0, i=0))  # fills the queue
        with pytest.raises(ServerOverloadedError):
            srv.submit(_mk_req(seed=1, i=1))
    finally:
        srv.close()  # refuse-drains the queued request (second refund)
    with pytest.raises(ServerClosedError):
        fut.result(timeout=60)
    events = trail.events()
    kinds = [e["kind"] for e in events]
    assert kinds == ["charge", "charge", "refund", "refund"]
    assert events[1]["trace_id"] == events[2]["trace_id"]
    assert [e.get("reason") for e in events[2:]] == ["overload", "closed"]
    # every charge was reversed: replay lands on zero spend throughout
    spent = replay(events)
    for p, s in spent.items():
        assert s == pytest.approx(0.0)


def test_ledger_registry_publishes_spend():
    from dpcorr.obs import Registry

    r = Registry()
    led = PrivacyLedger(2.0, registry=r)
    led.charge({"a": 1.5})
    led.refund({"a": 0.5})
    with pytest.raises(BudgetExceededError):
        led.charge({"a": 1.5})
    g = r.get("dpcorr_ledger_spent_eps")
    assert g.value(party="a") == pytest.approx(1.0)
    c = r.get("dpcorr_ledger_events_total")
    assert (c.value(kind="charge"), c.value(kind="refund"),
            c.value(kind="refusal")) == (1.0, 1.0, 1.0)
