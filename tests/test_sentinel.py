"""Live invariant sentinel (dpcorr.obs.sentinel, ISSUE 17).

The contract under test, in the order it matters operationally:

1. **Chaos-clean**: every legal artifact of crash recovery — a torn
   final line, a ``dedup``-flagged replay charge, a refused
   (never-journaled) window — raises nothing.
2. **Tamper-hot**: each injected tamper class is detected on the next
   poll as a typed violation naming the offending artifact.
3. **Crash-exact itself**: a sentinel restarted from its checkpoint
   resumes at its offsets and never re-alerts on re-read.
4. A violation pages through the standard burn-rate engine and arms
   the offender's flight recorder over POST /obs/trigger.
5. The ``dpcorr obs watch`` CLI is jax-free and its exit code carries
   the verdict.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dpcorr.obs.provenance import DIVERGENCE_KINDS
from dpcorr.obs.sentinel import (
    VIOLATION_KINDS,
    Sentinel,
    Violation,
    arm_offender_hook,
)


def _wline(path, obj):
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(obj) + "\n")


def _mk_stream_workdir(root, windows=2):
    """Script the durable artifacts of a healthy stream run: per
    window one (charge, wal batch, journal entry) triple with the
    service's real shapes and id discipline."""
    wd = os.path.join(str(root), "wd")
    os.makedirs(wd, exist_ok=True)
    audit = os.path.join(wd, "audit.jsonl")
    wal = os.path.join(wd, "wal.jsonl")
    journal = os.path.join(wd, "releases.jsonl")
    for w in range(windows):
        wid = f"{w * 2000}-{(w + 1) * 2000}"
        cid = f"stream:s:{wid}"
        _wline(audit, {"seq": w, "ts": float(w), "kind": "charge",
                       "charge_id": cid,
                       "charges": {"party/x": 0.4, "party/y": 0.4},
                       "trace_id": cid})
        _wline(wal, {"seq": w + 1, "batch_id": f"b{w}",
                     "ts": w * 2.0, "rows": [[0.1, 0.2]]})
        _wline(journal, {"start": w * 2.0, "end": (w + 1) * 2.0,
                         "rows": 1, "releases": {"ni_sign": {"r": w}},
                         "charge_id": cid, "eps_window": 0.8,
                         "window_id": wid, "release_seq": w + 1})
    return wd


def _sentinel(tmp_path, wd=None, name="ck.json", **kw):
    s = Sentinel(str(tmp_path / name), **kw)
    if wd is not None:
        s.add_stream("s1", wd)
    return s


class TestTaxonomy:
    def test_kinds_extend_divergence_kinds(self):
        for k in DIVERGENCE_KINDS:
            assert k in VIOLATION_KINDS
        for k in ("conservation-drift", "double-release",
                  "wal-regression", "checkpoint-gap"):
            assert k in VIOLATION_KINDS

    def test_violation_signature_is_stable_and_kind_checked(self):
        v = Violation(kind="wal-regression", source="s", artifact="a",
                      detail="d", at=1.0)
        w = Violation(kind="wal-regression", source="s", artifact="a",
                      detail="d", at=99.0)  # time does not identify
        assert v.signature == w.signature
        with pytest.raises(AssertionError):
            Violation(kind="nope", source="s", artifact="a",
                      detail="d", at=0.0)


class TestChaosClean:
    def test_healthy_run_is_silent(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path, windows=3)
        s = _sentinel(tmp_path, wd)
        assert s.poll() == [] and s.poll() == [] and s.rc == 0

    def test_torn_tail_is_not_a_violation(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        with open(os.path.join(wd, "wal.jsonl"), "a") as f:
            f.write('{"seq": 3, "batch_id": "torn')  # crash mid-append
        s = _sentinel(tmp_path, wd)
        assert s.poll() == []
        # the torn fragment completes later — consumed, still silent
        with open(os.path.join(wd, "wal.jsonl"), "a") as f:
            f.write('3", "ts": 4.0, "rows": []}\n')
        assert s.poll() == []

    def test_dedup_replay_charge_is_not_a_violation(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        # crash-recovery re-charge: same charge_id, dedup-flagged,
        # fresh seq — exactly what the ledger writes on replay
        _wline(os.path.join(wd, "audit.jsonl"),
               {"seq": 2, "ts": 9.0, "kind": "charge",
                "charge_id": "stream:s:0-2000",
                "charges": {"party/x": 0.4, "party/y": 0.4},
                "trace_id": "t", "dedup": True})
        s = _sentinel(tmp_path, wd)
        assert s.poll() == []

    def test_refusal_event_is_not_a_violation(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        _wline(os.path.join(wd, "audit.jsonl"),
               {"seq": 2, "ts": 9.0, "kind": "refusal",
                "charges": {"party/x": 0.4}, "trace_id": "t",
                "party": "party/x", "spent": 99.0, "budget": 100.0})
        s = _sentinel(tmp_path, wd)
        assert s.poll() == []


class TestTamperDetection:
    """One typed, artifact-naming violation per injected tamper class
    — the four classes the acceptance gate names, plus the mid-file
    corruption and gap cases only a tailer can classify."""

    def _clean_sentinel(self, tmp_path, wd):
        s = _sentinel(tmp_path, wd)
        assert s.poll() == []
        return s

    def test_wal_byte_flip(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = self._clean_sentinel(tmp_path, wd)
        with open(os.path.join(wd, "wal.jsonl"), "r+b") as f:
            f.seek(3)
            f.write(b"X")
        kinds = {(v.kind, v.artifact) for v in s.poll()}
        assert ("wal-regression", os.path.join(wd, "wal.jsonl")) in kinds

    def test_duplicate_charge_line(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = self._clean_sentinel(tmp_path, wd)
        audit = os.path.join(wd, "audit.jsonl")
        with open(audit) as f:
            first = f.readline()
        with open(audit, "a") as f:
            f.write(first)
        kinds = {v.kind for v in s.poll()}
        # the duplicated line is both an un-flagged double spend and a
        # seq regression — both named, both on the trail
        assert "double-charged-artifact" in kinds
        assert "wal-regression" in kinds
        assert all(v.artifact == audit for v in s.violations)

    def test_renoised_release_substitution(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = self._clean_sentinel(tmp_path, wd)
        _wline(os.path.join(wd, "releases.jsonl"),
               {"start": 0.0, "end": 2.0, "rows": 1,
                "releases": {"ni_sign": {"r": 777}},  # re-drawn noise
                "charge_id": "stream:s:0-2000", "eps_window": 0.8,
                "window_id": "0-2000", "release_seq": 3})
        kinds = {(v.kind, v.artifact) for v in s.poll()}
        assert ("re-noised-artifact",
                os.path.join(wd, "releases.jsonl")) in kinds

    def test_identical_double_release(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = self._clean_sentinel(tmp_path, wd)
        journal = os.path.join(wd, "releases.jsonl")
        with open(journal) as f:
            first = f.readline()
        with open(journal, "a") as f:
            f.write(first)
        kinds = {v.kind for v in s.poll()}
        assert "double-release" in kinds

    def test_release_seq_rewind(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = self._clean_sentinel(tmp_path, wd)
        _wline(os.path.join(wd, "releases.jsonl"),
               {"start": 4.0, "end": 6.0, "rows": 0, "releases": {},
                "charge_id": "stream:s:4000-6000", "eps_window": 0.8,
                "window_id": "4000-6000", "release_seq": 1})
        kinds = {(v.kind, v.artifact) for v in s.poll()}
        assert ("wal-regression",
                os.path.join(wd, "releases.jsonl")) in kinds

    def test_audit_seq_gap(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = self._clean_sentinel(tmp_path, wd)
        _wline(os.path.join(wd, "audit.jsonl"),
               {"seq": 9, "ts": 9.0, "kind": "charge",
                "charge_id": "c9", "charges": {"party/x": 0.1},
                "trace_id": "t"})
        kinds = {v.kind for v in s.poll()}
        assert "checkpoint-gap" in kinds

    def test_complete_garbage_line_mid_file(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = self._clean_sentinel(tmp_path, wd)
        with open(os.path.join(wd, "wal.jsonl"), "a") as f:
            f.write("not json at all\n")  # newline: complete line
        kinds = {v.kind for v in s.poll()}
        assert "checkpoint-gap" in kinds

    def test_journal_charge_never_audited(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = self._clean_sentinel(tmp_path, wd)
        _wline(os.path.join(wd, "releases.jsonl"),
               {"start": 4.0, "end": 6.0, "rows": 0, "releases": {},
                "charge_id": "stream:s:4000-6000", "eps_window": 0.8,
                "window_id": "4000-6000", "release_seq": 3})
        assert s.poll() == []  # one-round grace for the audit append
        kinds = {v.kind for v in s.poll()}
        assert "tampered-charge" in kinds

    def test_journal_eps_disagrees_with_trail(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        wid, cid = "4000-6000", "stream:s:4000-6000"
        _wline(os.path.join(wd, "audit.jsonl"),
               {"seq": 2, "ts": 9.0, "kind": "charge",
                "charge_id": cid, "charges": {"party/x": 0.1},
                "trace_id": cid})
        _wline(os.path.join(wd, "releases.jsonl"),
               {"start": 4.0, "end": 6.0, "rows": 0, "releases": {},
                "charge_id": cid, "eps_window": 0.8,
                "window_id": wid, "release_seq": 3})
        s = _sentinel(tmp_path, wd)
        kinds = {v.kind for v in s.poll()}
        assert "eps-total-mismatch" in kinds


class TestCheckpointRestart:
    def test_restart_never_realerts(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = _sentinel(tmp_path, wd)
        s.poll()
        with open(os.path.join(wd, "wal.jsonl"), "r+b") as f:
            f.seek(3)
            f.write(b"X")
        assert {v.kind for v in s.poll()} == {"wal-regression"}
        # new process, same checkpoint: silent, rc 0
        s2 = _sentinel(tmp_path, wd)
        assert s2.poll() == [] and s2.rc == 0

    def test_restart_resumes_offsets_and_still_detects(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = _sentinel(tmp_path, wd)
        s.poll()
        s2 = _sentinel(tmp_path, wd)
        # fresh tamper after the restart is still hot
        audit = os.path.join(wd, "audit.jsonl")
        with open(audit) as f:
            first = f.readline()
        with open(audit, "a") as f:
            f.write(first)
        assert "double-charged-artifact" in {v.kind for v in s2.poll()}
        assert s2.rc == 1

    def test_checkpoint_is_fsynced_json(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = _sentinel(tmp_path, wd)
        s.poll()
        doc = json.load(open(s.checkpoint_path))
        assert doc["version"] == Sentinel.CHECKPOINT_VERSION
        assert "s1/stream" in doc["watchers"]


class TestConservation:
    def _forge(self, wd, seq):
        _wline(os.path.join(wd, "audit.jsonl"),
               {"seq": seq, "ts": 9.0, "kind": "charge",
                "charge_id": "forged", "charges": {"user/alice": 3.0},
                "trace_id": "z"})

    def test_budget_dir_drift_fires_after_debounce(self, tmp_path):
        from dpcorr.serve.budget_dir import BudgetDirectory

        wd = _mk_stream_workdir(tmp_path)
        bd = BudgetDirectory(os.path.join(wd, "budget_dir"),
                             user_budget=50.0)
        bd.charge("alice", 0.8, charge_id="c1")
        bd.close()
        _wline(os.path.join(wd, "audit.jsonl"),
               {"seq": 2, "ts": 9.0, "kind": "charge",
                "charge_id": "c1", "charges": {"user/alice": 0.8},
                "trace_id": "c1"})
        s = _sentinel(tmp_path, wd)
        assert s.poll() == [] and s.poll() == []  # folds agree
        # forge a user charge the directory never saw
        self._forge(wd, seq=3)
        assert s.poll() == []  # first mismatched observation: debounce
        kinds = {v.kind for v in s.poll()}
        assert kinds == {"conservation-drift"}
        assert any("alice" in v.artifact for v in s.violations)

    def test_scrape_drift_against_canned_metrics(self, tmp_path):
        exposition = ('# TYPE dpcorr_ledger_spent_eps gauge\n'
                      'dpcorr_ledger_spent_eps{party="party/x"} 0.8\n'
                      'dpcorr_ledger_spent_eps{party="party/y"} 0.8\n')
        httpd = _canned_server(exposition, {})
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}"
            wd = _mk_stream_workdir(tmp_path)  # trail: 0.8 + 0.8
            s = Sentinel(str(tmp_path / "ck.json"))
            s.add_stream("s1", wd, url=url)
            assert s.poll() == [] and s.poll() == []
            # forge a party charge the gauge never saw
            _wline(os.path.join(wd, "audit.jsonl"),
                   {"seq": 2, "ts": 9.0, "kind": "charge",
                    "charge_id": "forged",
                    "charges": {"party/x": 3.0}, "trace_id": "z"})
            assert s.poll() == []  # debounce
            assert {v.kind for v in s.poll()} == {"conservation-drift"}
            assert any(v.artifact == "party/x" for v in s.violations)
        finally:
            httpd.shutdown()

    def test_down_instance_is_not_drift(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        s = Sentinel(str(tmp_path / "ck.json"),
                     scrape_timeout_s=0.2)
        s.add_stream("s1", wd, url="http://127.0.0.1:1")
        assert s.poll() == [] and s.poll() == []


class TestTranscriptsAndJournals:
    def _rel(self, sess, rnd, label, group, charged):
        return {"wire": {"session": sess, "msg_type": "release",
                         "payload": {"round": rnd,
                                     "artifacts": {label: group},
                                     "charged": charged}}}

    def test_renoised_artifact_across_sessions(self, tmp_path):
        d = tmp_path / "tx"
        d.mkdir()
        _wline(str(d / "a.jsonl"),
               self._rel("s1", 0, "col0", {"noise": 1}, ["col0"]))
        s = Sentinel(str(tmp_path / "ck.json"))
        s.add_transcripts("fed", str(d))
        assert s.poll() == []
        _wline(str(d / "b.jsonl"),
               self._rel("s2", 0, "col0", {"noise": 2}, []))
        v = s.poll()
        assert [x.kind for x in v] == ["re-noised-artifact"]
        assert v[0].artifact == "col0"

    def test_double_charged_artifact_across_venues(self, tmp_path):
        d = tmp_path / "tx"
        d.mkdir()
        _wline(str(d / "a.jsonl"),
               self._rel("s1", 0, "col0", {"noise": 1}, ["col0"]))
        s = Sentinel(str(tmp_path / "ck.json"))
        s.add_transcripts("fed", str(d))
        assert s.poll() == []
        _wline(str(d / "a.jsonl"),
               self._rel("s1", 1, "col1", {"noise": 1}, ["col0"]))
        v = s.poll()
        assert [x.kind for x in v] == ["double-charged-artifact"]

    def test_corrupt_session_journal(self, tmp_path):
        d = tmp_path / "j"
        d.mkdir()
        (d / "journal.alice.json").write_text('{"version": 1}')
        s = Sentinel(str(tmp_path / "ck.json"))
        s.add_journals("fed", str(d))
        assert s.poll() == []
        (d / "journal.alice.json").write_text('{"torn')
        kinds = {v.kind for v in s.poll()}
        assert kinds == {"checkpoint-gap"}


class TestPagingAndArming:
    def test_violation_pages_burn_rate_engine(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        pages = []
        clock = [1000.0]
        s = Sentinel(str(tmp_path / "ck.json"),
                     clock=lambda: clock[0], on_page=pages.append)
        s.add_stream("s1", wd)
        for _ in range(3):
            s.poll()
            clock[0] += 1.0
        assert pages == []  # clean polls never page
        with open(os.path.join(wd, "wal.jsonl"), "r+b") as f:
            f.seek(3)
            f.write(b"X")
        for _ in range(3):
            s.poll()
            clock[0] += 1.0
        assert [a.severity for a in pages] == ["page"]
        assert pages[0].objective == "sentinel-violations"

    def test_arm_offender_hook_posts_trigger(self, tmp_path):
        seen = []
        httpd = _canned_server("", {}, posts=seen)
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}"
            hook = arm_offender_hook({"s1": url})
            hook(Violation(kind="wal-regression", source="s1",
                           artifact="a", detail="d", at=0.0))
            hook(Violation(kind="wal-regression", source="unknown",
                           artifact="a", detail="d", at=0.0))
            assert len(seen) == 1
            body = json.loads(seen[0])
            assert body["reason"] == "sentinel_violation"
            assert body["detail"]["kind"] == "wal-regression"
        finally:
            httpd.shutdown()

    def test_sentinel_violation_is_a_trigger_reason(self):
        from dpcorr.obs.recorder import TRIGGER_REASONS

        assert "sentinel_violation" in TRIGGER_REASONS


class TestStreamSLOFactories:
    def _fams(self, text):
        from dpcorr.obs.fleet import parse_families

        return parse_families(text)

    def test_watermark_lag_objective_pages_on_sustained_lag(self):
        from dpcorr.obs.metrics import Registry
        from dpcorr.obs.slo import (
            BurnRateEngine,
            stream_watermark_lag_objective,
        )

        obj = stream_watermark_lag_objective(max_lag_s=1.0)
        eng = BurnRateEngine([obj], clock=lambda: 0.0)

        def fams(lag):
            r = Registry()
            r.gauge("dpcorr_stream_watermark_lag_seconds", "l").set(lag)
            return self._fams(r.render())

        eng.observe({"s1": fams(0.5)}, at=0.0)
        eng.observe({"s1": fams(0.5)}, at=60.0)
        assert eng.evaluate(at=60.0) == []  # within budget
        eng.observe({"s1": fams(30.0)}, at=120.0)  # ≫ 14.4× budget
        fired = eng.evaluate(at=120.0)
        assert [a.severity for a in fired] == ["page"]

    def test_release_latency_objective_uses_exact_bucket(self):
        from dpcorr.obs.slo import stream_release_latency_objective

        obj = stream_release_latency_objective(threshold_s=1.0)
        assert obj.histogram == "dpcorr_stream_release_seconds"
        assert obj.kind == "latency"
        with pytest.raises(ValueError):
            stream_release_latency_objective(target=0.0)

    def test_gauge_kind_requires_threshold(self):
        from dpcorr.obs.slo import Objective

        with pytest.raises(ValueError, match="gauge"):
            Objective(name="g", kind="gauge", target=1.0)


class TestWatchCLI:
    def test_obs_watch_cli_is_jax_free_and_sets_rc(self, tmp_path):
        wd = _mk_stream_workdir(tmp_path)
        ck = str(tmp_path / "ck.json")
        script_tpl = (
            "import sys\n"
            "sys.modules['jax'] = None\n"  # any jax import explodes
            "sys.argv = ['dpcorr', 'obs', 'watch', '--checkpoint', %r,"
            " '--stream', 'ize=%s', '--once', '--json']\n"
            "from dpcorr.__main__ import main\n"
            "main()\n")
        run = subprocess.run(
            [sys.executable, "-c", script_tpl % (ck, wd)],
            capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr
        # tamper, re-run from the same checkpoint: rc 1, typed + named
        with open(os.path.join(wd, "wal.jsonl"), "r+b") as f:
            f.seek(3)
            f.write(b"X")
        run = subprocess.run(
            [sys.executable, "-c", script_tpl % (ck, wd)],
            capture_output=True, text=True, timeout=120)
        assert run.returncode == 1, run.stderr
        lines = [json.loads(line) for line in run.stdout.splitlines()
                 if line.startswith('{"violation"')]
        assert lines and lines[0]["violation"]["kind"] == "wal-regression"
        assert "wal.jsonl" in lines[0]["violation"]["artifact"]
        # third run, same checkpoint, no new tamper: silent again
        run = subprocess.run(
            [sys.executable, "-c", script_tpl % (ck, wd)],
            capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr

    def test_obs_watch_refuses_empty_watchlist(self, tmp_path):
        run = subprocess.run(
            [sys.executable, "-m", "dpcorr", "obs", "watch",
             "--checkpoint", str(tmp_path / "ck.json"), "--once"],
            capture_output=True, text=True, timeout=120)
        assert run.returncode != 0
        assert "nothing to watch" in run.stderr


def _canned_server(exposition: str, stats: dict, posts=None):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            blob = (exposition.encode() if self.path == "/metrics"
                    else json.dumps(stats).encode())
            self.send_response(200)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def do_POST(self):  # noqa: N802
            n = int(self.headers.get("Content-Length", 0))
            if posts is not None:
                posts.append(self.rfile.read(n).decode())
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
