"""dpcorr lint (docs/STATIC_ANALYSIS.md): fixture-driven rule checks,
suppression and baseline mechanics, CLI exit codes, jax-freeness, and
the meta-test that the shipped tree itself is lint-clean.

The fixture pairs under tests/fixtures/lint/ are the per-rule contract:
every `*_bad.py` line annotated with a rule id must fire exactly that
rule, every `*_ok.py` must stay silent.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from dpcorr.analysis import (
    Violation,
    apply_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)
from dpcorr.analysis.cli import main as lint_main

REPO = Path(__file__).parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "lint"


def lint_fixture(*names, rules=None):
    return run_lint(list(names), str(FIXTURES), rule_filter=rules)


def fired(violations):
    return sorted((v.rule, v.line) for v in violations)


# ------------------------------------------------------------ per rule ----
def test_rng_bad_fixture_fires_every_rng_rule():
    vs = lint_fixture("rng_bad.py")
    assert fired(vs) == [
        ("rng-key-reuse", 8),
        ("rng-literal-seed", 13),
        ("rng-raw-api", 13),  # PRNGKey is both a literal seed and raw API
        ("rng-raw-api", 17),
    ]


def test_rng_ok_fixture_is_clean():
    assert lint_fixture("rng_ok.py") == []


def test_budget_bad_fixture_fires_both_budget_rules():
    vs = lint_fixture("serve/budget_bad.py")
    assert fired(vs) == [
        ("budget-missing-refund", 12),
        ("budget-uncharged-noise", 7),
    ]


def test_budget_ok_fixture_is_clean():
    assert lint_fixture("serve/budget_ok.py") == []


def test_budget_shed_bad_fixture_fires_shed_rule():
    vs = lint_fixture("serve/budget_shed_bad.py")
    assert fired(vs) == [
        ("budget-shed-missing-refund", 12),
    ]


def test_budget_shed_ok_fixture_is_clean():
    assert lint_fixture("serve/budget_shed_ok.py") == []


def test_budget_multi_bad_fixture_fires_directory_rules():
    """The per-user directory is a budget receiver: charging it plus
    the ledger without a compensating handler is a partial-spend
    hazard, and a directory charge is expected to dominate enqueues."""
    vs = lint_fixture("serve/budget_multi_bad.py")
    assert fired(vs) == [
        ("budget-multi-charge-missing-refund", 9),
        ("budget-uncharged-noise", 14),
    ]


def test_budget_multi_ok_fixture_is_clean():
    """The CompositeLedger shape lints clean: later-receiver charge in
    a try whose handler refunds the first store."""
    assert lint_fixture("serve/budget_multi_ok.py") == []


def test_locks_bad_fixture_fires_reads_and_writes():
    vs = lint_fixture("serve/locks_bad.py")
    assert fired(vs) == [
        ("lock-unguarded-read", 15),
        ("lock-unguarded-write", 12),
        ("lock-unguarded-write", 20),  # closure escaping the guard
    ]


def test_locks_ok_fixture_is_clean():
    assert lint_fixture("serve/locks_ok.py") == []


def test_locks_scope_is_path_based():
    """The same source outside serve//obs/ is out of the lock checker's
    scope — the declaration comment alone must not fire elsewhere."""
    src = (FIXTURES / "serve" / "locks_bad.py").read_text()
    import dpcorr.analysis.core as core

    module = core.Module("x.py", "models/locks_elsewhere.py", src)
    from dpcorr.analysis.rules.locks import LockChecker

    checker = LockChecker()
    assert not checker.applies_to(module.relpath)


def test_protocol_budget_bad_fixture_fires_both_budget_rules():
    """The budget rules extend to protocol/: a channel send is an
    enqueue, so it needs a dominating charge and a refund guard."""
    vs = lint_fixture("protocol/budget_bad.py")
    assert fired(vs) == [
        ("budget-missing-refund", 13),
        ("budget-uncharged-noise", 8),
    ]


def test_protocol_budget_ok_fixture_is_clean():
    assert lint_fixture("protocol/budget_ok.py") == []


def test_stream_budget_bad_fixture_fires_both_budget_rules():
    """The budget rules extend to stream/: handing a window to the
    releaser is an enqueue, so it needs a dominating per-window charge
    and a refund guard (stream.service._release_window_locked's shape)."""
    vs = lint_fixture("stream/budget_bad.py")
    assert fired(vs) == [
        ("budget-missing-refund", 13),
        ("budget-uncharged-noise", 8),
    ]


def test_stream_budget_ok_fixture_is_clean():
    assert lint_fixture("stream/budget_ok.py") == []


def test_rawdata_bad_fixture_fires_on_aliased_columns():
    vs = lint_fixture("protocol/rawdata_bad.py")
    assert fired(vs) == [
        ("raw-column-serialize", 7),   # direct
        ("raw-column-serialize", 13),  # asarray + clip alias chain
        ("raw-column-serialize", 17),  # sign image
    ]


def test_rawdata_ok_fixture_is_clean():
    assert lint_fixture("protocol/rawdata_ok.py") == []


def test_rawdata_scope_is_path_based():
    """The same source outside protocol/ is out of the rawdata
    checker's scope (the estimators legitimately hold both columns)."""
    src = (FIXTURES / "protocol" / "rawdata_bad.py").read_text()
    from dpcorr.analysis.rules.rawdata import RawDataChecker

    assert not RawDataChecker().applies_to("models/rawdata_elsewhere.py")


def test_purity_bad_fixture_fires_both_purity_rules():
    vs = lint_fixture("purity_bad.py")
    assert fired(vs) == [
        ("jit-closure-mutation", 25),
        ("jit-closure-mutation", 31),
        ("jit-impure-call", 12),
        ("jit-impure-call", 16),
    ]


def test_purity_ok_fixture_is_clean():
    assert lint_fixture("purity_ok.py") == []


def test_sync_bad_fixture_fires_every_form():
    vs = lint_fixture("benchmarks/sync_bad.py")
    assert fired(vs) == [
        ("sync-in-loop", 9),   # np.asarray in a for body
        ("sync-in-loop", 17),  # jax.block_until_ready in a while body
        ("sync-in-loop", 23),  # jax.device_get in a comprehension
        ("sync-in-loop", 28),  # method-form x.block_until_ready()
    ]


def test_sync_ok_fixture_is_clean():
    assert lint_fixture("benchmarks/sync_ok.py") == []


def test_sync_suppressed_fixture_is_clean():
    assert lint_fixture("benchmarks/sync_suppressed_ok.py") == []


def test_sync_scope_is_path_based():
    """The same source outside the hot-path modules is out of scope —
    analysis/serving code fetches values because it needs them."""
    from dpcorr.analysis.rules.sync import SyncChecker

    checker = SyncChecker()
    assert not checker.applies_to("dpcorr/serve/kernels.py")
    assert not checker.applies_to("dpcorr/analysis/core.py")
    for hot in ("dpcorr/sim.py", "dpcorr/grid.py",
                "dpcorr/parallel/backend.py", "dpcorr/plan/executor.py",
                "dpcorr/plan/placement.py", "bench.py",
                "benchmarks/roofline.py"):
        assert checker.applies_to(hot), hot


def test_sync_plan_bad_fixture_fires():
    vs = lint_fixture("plan/sync_bad.py")
    assert fired(vs) == [
        ("sync-in-loop", 11),  # block_until_ready per dispatched unit
        ("sync-in-loop", 16),  # np.asarray in a comprehension
    ]


def test_sync_plan_ok_fixture_is_clean():
    assert lint_fixture("plan/sync_ok.py") == []


def test_sync_plan_suppressed_fixture_is_clean():
    assert lint_fixture("plan/sync_suppressed_ok.py") == []


def test_compilepath_bad_fixture_fires_every_site():
    vs = lint_fixture("compilepath_bad.py")
    assert fired(vs) == [
        ("aot-outside-compile-layer", 7),   # jitted.lower().compile()
        ("aot-outside-compile-layer", 11),  # jit(f).lower(x).compile()
        ("aot-outside-compile-layer", 15),  # with compiler_options
    ]


def test_compilepath_ok_fixture_is_clean():
    """str.lower(), re.compile() and the sanctioned aot_compile call
    are all look-alikes the chain match must not fire on."""
    assert lint_fixture("compilepath_ok.py") == []


def test_compilepath_suppressed_fixture_is_clean():
    assert lint_fixture("compilepath_suppressed_ok.py") == []


def test_compilepath_scope_excludes_only_the_compile_layer():
    from dpcorr.analysis.rules.compilepath import CompilePathChecker

    checker = CompilePathChecker()
    assert not checker.applies_to("dpcorr/utils/compile.py")
    for covered in ("dpcorr/grid.py", "dpcorr/serve/kernels.py",
                    "dpcorr/plan/executor.py", "bench.py",
                    "benchmarks/roofline.py",
                    "dpcorr/utils/roofline.py"):
        assert checker.applies_to(covered), covered


def test_metrics_bad_fixture_fires_both_telemetry_rules():
    vs = lint_fixture("serve/metrics_bad.py")
    assert fired(vs) == [
        ("metric-name-style", 10),  # unprefixed counter
        ("metric-name-style", 11),  # camelCase gauge
        ("metric-name-style", 12),  # direct-constructor form
        ("span-no-finally", 17),    # .end() outside a finally
        ("span-no-finally", 24),    # never bound at all
    ]


def test_metrics_ok_fixture_is_clean():
    assert lint_fixture("serve/metrics_ok.py") == []


def test_metrics_scope_excludes_obs_package():
    """obs/ defines the instruments — the namespace rule polices the
    producers, not the factory itself."""
    from dpcorr.analysis.rules.metrics import MetricsChecker

    checker = MetricsChecker()
    assert not checker.applies_to("dpcorr/obs/metrics.py")
    assert not checker.applies_to("dpcorr/obs/recorder.py")
    for covered in ("dpcorr/serve/stats.py", "bench.py",
                    "dpcorr/protocol/party.py"):
        assert checker.applies_to(covered), covered


# ------------------------------------------------- suppression comments ----
def test_suppression_comment_both_placements():
    assert lint_fixture("rng_suppressed_ok.py") == []


def test_suppression_is_rule_specific():
    vs = run_lint(["rng_bad.py"], str(FIXTURES))
    # the bad fixture has no ignore comments at all
    assert len(vs) == 4
    # an ignore[] for a *different* rule must not absorb the finding
    src = (FIXTURES / "rng_bad.py").read_text()
    patched = src.replace(
        "# rng-raw-api", "# dpcorr-lint: ignore[rng-key-reuse]")
    import dpcorr.analysis.core as core

    module = core.Module("rng_bad.py", "rng_bad.py", patched)
    assert not module.suppressed("rng-raw-api", 17)
    assert module.suppressed("rng-key-reuse", 17)


# ----------------------------------------------------------- rule filter ----
def test_rule_filter_restricts_families():
    vs = lint_fixture("rng_bad.py", "purity_bad.py", rules=["rng"])
    assert {v.rule for v in vs} <= {"rng-key-reuse", "rng-literal-seed",
                                    "rng-raw-api"}
    with pytest.raises(ValueError, match="unknown checker"):
        lint_fixture("rng_bad.py", rules=["nope"])


# -------------------------------------------------------------- baseline ----
def test_baseline_roundtrip_and_line_insensitivity(tmp_path):
    vs = lint_fixture("rng_bad.py")
    path = tmp_path / "baseline.json"
    write_baseline(vs, str(path))
    entries = load_baseline(str(path))
    assert len(entries) == len(vs)
    # exact refind: everything absorbed
    new, matched, stale = apply_baseline(vs, entries)
    assert (new, matched, stale) == ([], len(vs), [])
    # line numbers move (pure edit above): entries still match on code
    moved = [Violation(v.rule, v.path, v.line + 40, v.message, v.code)
             for v in vs]
    new, matched, stale = apply_baseline(moved, entries)
    assert (new, matched) == ([], len(vs))


def test_baseline_multiplicity_and_staleness():
    v = Violation("r", "p.py", 3, "m", code="x = f(k)")
    # two identical findings, one entry: the second is NEW
    new, matched, stale = apply_baseline(
        [v, v], [{"rule": "r", "path": "p.py", "code": "x = f(k)"}])
    assert matched == 1 and len(new) == 1 and stale == []
    # entry with no finding left: reported stale, never failing
    new, matched, stale = apply_baseline(
        [], [{"rule": "r", "path": "p.py", "code": "x = f(k)"}])
    assert new == [] and stale[0]["rule"] == "r"


# ------------------------------------------------------------ CLI driver ----
def test_cli_exit_codes(tmp_path, capsys):
    root = str(FIXTURES)
    assert lint_main(["--root", root, "rng_ok.py"]) == 0
    assert lint_main(["--root", root, "rng_bad.py"]) == 1
    assert lint_main(["--root", root, "no_such_file.py"]) == 2
    assert lint_main(["--root", root, "--rules", "nope", "rng_ok.py"]) == 2
    capsys.readouterr()
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("rng-key-reuse", "budget-uncharged-noise",
                 "lock-unguarded-write", "jit-impure-call"):
        assert rule in out


def test_cli_json_report(capsys):
    rc = lint_main(["--root", str(FIXTURES), "--json", "rng_bad.py"])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert {v["rule"] for v in report["new"]} == {
        "rng-key-reuse", "rng-literal-seed", "rng-raw-api"}


def test_cli_write_then_pass_then_strict_stale(tmp_path, capsys):
    root = str(FIXTURES)
    bl = tmp_path / "bl.json"
    assert lint_main(["--root", root, "--baseline", str(bl),
                      "--write-baseline", "rng_bad.py"]) == 0
    # grandfathered: gate passes
    assert lint_main(["--root", root, "--baseline", str(bl),
                      "rng_bad.py"]) == 0
    # everything fixed: stale entries warn by default, fail with --strict
    capsys.readouterr()
    assert lint_main(["--root", root, "--baseline", str(bl),
                      "rng_ok.py"]) == 0
    assert "stale" in capsys.readouterr().out
    assert lint_main(["--root", root, "--baseline", str(bl),
                      "--strict", "rng_ok.py"]) == 1


# ------------------------------------------------------------- meta-tests ----
def test_repo_is_lint_clean_modulo_baseline():
    """The shipped tree has no violations beyond the committed
    baseline — the same gate CI applies (`python -m dpcorr lint`)."""
    vs = run_lint(["dpcorr"], str(REPO))
    baseline = REPO / ".dpcorr-lint-baseline.json"
    entries = load_baseline(str(baseline)) if baseline.exists() else []
    new, _, _ = apply_baseline(vs, entries)
    assert new == [], "\n".join(v.render() for v in new)


def test_lint_is_jax_free():
    """The linter import chain and a full CLI run never touch jax —
    the CI lint job runs on a jax-less interpreter. -S skips the site
    hook that preloads jax unconditionally (see test_doctor.py)."""
    r = subprocess.run(
        [sys.executable, "-S", "-c",
         "import sys; sys.path.insert(0, '.'); "
         "from dpcorr.analysis import cli; "
         "rc = cli.main(['--root', '.', 'dpcorr/analysis']); "
         "assert 'jax' not in sys.modules, 'lint pulled jax'; "
         "sys.exit(rc)"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])


def test_module_cli_entrypoint():
    """`python -m dpcorr lint` end-to-end in the repo: exit 0."""
    r = subprocess.run([sys.executable, "-m", "dpcorr", "lint"],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
    assert "0 new violations" in r.stdout


# ---------------------------------------------------------- deep pass ----
def deep_fixture(*names, rules=None):
    return run_lint(list(names), str(FIXTURES), rule_filter=rules,
                    deep=True)


def test_lockorder_cycle_bad_fires_exactly_one_cycle():
    """The seeded two-lock deadlock: exactly ONE lock-order-cycle
    finding whose chain names both acquisition paths file:line."""
    vs = deep_fixture("deep/lockorder_cycle_bad.py", rules=["lockorder"])
    assert fired(vs) == [("lock-order-cycle", 14)]
    (v,) = vs
    assert "deep/lockorder_cycle_bad.py:14 (Pair.forward)" in v.chain
    assert "deep/lockorder_cycle_bad.py:19 (Pair.backward)" in v.chain


def test_lockorder_blocking_bad_fixture_interprocedural():
    """record() never fsyncs itself — the effect is inherited from
    _sync() through the call graph, and the chain says so."""
    vs = deep_fixture("deep/lockorder_blocking_bad.py",
                      rules=["lockorder"])
    assert fired(vs) == [("blocking-under-lock", 14)]
    (v,) = vs
    assert v.chain == (
        "deep/lockorder_blocking_bad.py:14 (Store.record)",
        "deep/lockorder_blocking_bad.py:17 (Store._sync) os.fsync")


def test_lockorder_ok_and_suppressed_fixtures_silent():
    assert deep_fixture("deep/lockorder_ok.py",
                        rules=["lockorder"]) == []
    assert deep_fixture("deep/lockorder_suppressed_ok.py",
                        rules=["lockorder"]) == []


def test_durability_bare_write_to_journal_exactly_one():
    """The seeded torn-file shape: exactly ONE durability-bare-write
    naming the offending site."""
    vs = deep_fixture("deep/journal_bad.py", rules=["durability"])
    assert fired(vs) == [("durability-bare-write", 7)]
    (v,) = vs
    assert v.chain == ("deep/journal_bad.py:7 (save_snapshot)",)


def test_durability_unsynced_ack_fixture():
    vs = deep_fixture("deep/wal_bad.py", rules=["durability"])
    assert fired(vs) == [("durability-unsynced-ack", 11)]


def test_durability_module_level_sweep_and_quarantine():
    vs = deep_fixture("deep/snapshot_bad.py", rules=["durability"])
    assert fired(vs) == [("durability-missing-quarantine", 13),
                        ("durability-missing-sweep", 13)]


def test_durability_ok_and_suppressed_fixtures_silent():
    assert deep_fixture("deep/journal_ok.py",
                        rules=["durability"]) == []
    assert deep_fixture("deep/journal_suppressed_ok.py",
                        rules=["durability"]) == []


def test_deepbudget_bad_fixture_cross_function():
    vs = deep_fixture("serve/deepbudget_bad.py", rules=["deepbudget"])
    assert fired(vs) == [("budget-deep-missing-refund", 21),
                        ("budget-deep-uncharged-enqueue", 12)]
    by_rule = {v.rule: v for v in vs}
    # both findings anchor at the caller but name the callee's enqueue
    assert "self.coalescer.submit" in \
        by_rule["budget-deep-uncharged-enqueue"].message
    assert by_rule["budget-deep-missing-refund"].chain == (
        "serve/deepbudget_bad.py:21 (Server.admit)",)


def test_deepbudget_ok_and_suppressed_fixtures_silent():
    assert deep_fixture("serve/deepbudget_ok.py",
                        rules=["deepbudget"]) == []
    assert deep_fixture("serve/deepbudget_suppressed_ok.py",
                        rules=["deepbudget"]) == []


def test_coverage_bad_fixture_registry_audit():
    vs = deep_fixture("deep/chaos_points_bad.py", rules=["coverage"])
    assert fired(vs) == [("chaos-unreachable-point", 6),
                        ("chaos-unreachable-point", 7),
                        ("chaos-unswept-point", 8)]
    orphan = [v for v in vs if v.line == 7]
    assert orphan[0].chain == (
        "deep/chaos_points_bad.py:25 (_forgotten)",)


def test_coverage_ok_and_suppressed_fixtures_silent():
    assert deep_fixture("deep/chaos_points_ok.py",
                        rules=["coverage"]) == []
    assert deep_fixture("deep/chaos_points_suppressed_ok.py",
                        rules=["coverage"]) == []


def test_repo_is_deep_lint_clean_modulo_baseline():
    """The shipped tree passes its own interprocedural pass with an
    EMPTY committed baseline — the same gate CI applies
    (`python -m dpcorr lint --deep`)."""
    vs = run_lint(["dpcorr"], str(REPO), deep=True)
    baseline = REPO / ".dpcorr-lint-baseline.json"
    entries = load_baseline(str(baseline)) if baseline.exists() else []
    new, _, _ = apply_baseline(vs, entries)
    assert new == [], "\n".join(v.render() for v in new)


def test_deep_lint_is_jax_free():
    """`dpcorr lint --deep` over the default paths on a jax-less
    interpreter (-S skips the site hook): exits 0 and never imports
    jax — the CI lint job has no jax wheel."""
    r = subprocess.run(
        [sys.executable, "-S", "-c",
         "import sys; sys.path.insert(0, '.'); "
         "from dpcorr.analysis import cli; "
         "rc = cli.main(['--deep']); "
         "assert 'jax' not in sys.modules, 'deep lint pulled jax'; "
         "sys.exit(rc)"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])


def test_cli_deep_cyclic_fixture_exits_1():
    """The CI canary: the deliberately cyclic fixture must fail the
    deep gate with exit 1 (not 0, not a crash)."""
    assert lint_main(["--root", str(FIXTURES), "--no-baseline",
                      "--deep", "deep/lockorder_cycle_bad.py"]) == 1
