"""Unit tests for the serve fleet plane (ISSUE 20): durable shard
leases under scripted clocks (grant / renew / expire / takeover,
epoch fencing, crash-at-takeover via the ``fleet.pre_lease_commit``
chaos point), the lease-gated :class:`BudgetDirectory` in fleet mode,
the jax-free front-end router against canned in-thread HTTP replicas,
and the replica supervisor against stub subprocesses. Everything here
is stdlib-only and runs without jax."""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dpcorr import chaos
from dpcorr.chaos import ChaosPlan, SimulatedCrash
from dpcorr.serve.budget_dir import BudgetDirectory
from dpcorr.serve.fleet import (
    FleetFrontend,
    LeaseKeeper,
    LeaseManager,
    ReplicaSpec,
    ShardNotOwnedError,
    Supervisor,
    lease_table,
)


class Clock:
    """A scripted wall clock shared by every lease party in a test."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _no_chaos():
    chaos.clear()
    yield
    chaos.clear()


def mgr(tmp_path, owner: str, clock: Clock, *, ttl: float = 10.0,
        n_shards: int | None = 4, **kw) -> LeaseManager:
    return LeaseManager(str(tmp_path / "leases"), owner,
                        n_shards=n_shards, ttl_s=ttl, clock=clock, **kw)


# ---------------------------------------------------------------- lease --


def test_acquire_free_shard_grants_epoch_one(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    assert a.acquire(0)
    rec = a.owner_of(0)
    assert rec["owner"] == "rep-a"
    assert rec["epoch"] == 1
    assert rec["expires_at"] == clock.t + 10.0
    assert a.owned() == [0]
    # the claim file was consumed on commit
    assert not [n for n in os.listdir(a.lease_dir) if ".claim." in n]


def test_renew_extends_expiry_without_epoch_bump(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    assert a.acquire(1)
    clock.advance(6.0)
    assert a.renew(1)
    rec = a.owner_of(1)
    assert rec["epoch"] == 1
    assert rec["expires_at"] == clock.t + 10.0
    # silent past expiry: the renew refuses instead of reviving
    clock.advance(11.0)
    assert not a.renew(1)
    assert a.owned() == []


def test_valid_lease_is_exclusive(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    b = mgr(tmp_path, "rep-b", clock)
    assert a.acquire(2)
    assert not b.acquire(2)
    rec = b.owner_of(2)
    assert rec["owner"] == "rep-a" and rec["epoch"] == 1


def test_expired_lease_taken_over_with_epoch_bump(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    b = mgr(tmp_path, "rep-b", clock)
    assert a.acquire(2)
    clock.advance(10.5)  # past a's ttl, a never renewed
    assert b.acquire(2)
    rec = b.owner_of(2)
    assert rec["owner"] == "rep-b"
    assert rec["epoch"] == 2
    assert b.snapshot()["counts"]["takeovers"] == 1


def test_restart_reclaims_own_live_lease_same_epoch(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    assert a.acquire(0)
    # same instance name rebooting before expiry: no second writer is
    # introduced, so the grant is adopted as-is
    a2 = mgr(tmp_path, "rep-a", clock)
    assert a2.acquire(0)
    assert a2.owner_of(0)["epoch"] == 1
    assert a2.snapshot()["counts"]["reclaimed"] == 1


def test_release_hands_over_without_ttl_wait(tmp_path):
    clock = Clock()
    lost: list[int] = []
    a = mgr(tmp_path, "rep-a", clock)
    a.bind(4, on_lost=lost.append)
    b = mgr(tmp_path, "rep-b", clock)
    assert a.acquire(3)
    a.release(3)
    assert lost == [3]
    # no clock advance at all — the released lease is already expired
    assert b.acquire(3)
    assert b.owner_of(3)["epoch"] == 2


def test_ensure_owned_fences_stale_holder_charge_free(tmp_path):
    clock = Clock()
    lost: list[int] = []
    a = mgr(tmp_path, "rep-a", clock)
    a.bind(4, on_lost=lost.append)
    b = mgr(tmp_path, "rep-b", clock, ttl=10.0)
    b.url = "http://b:1"
    assert a.acquire(1)
    a.ensure_owned(1)  # comfortably live: no fence
    clock.advance(10.5)
    assert b.acquire(1)  # epoch 2, b's grant
    with pytest.raises(ShardNotOwnedError) as ei:
        a.ensure_owned(1)
    assert ei.value.owner == "rep-b"
    assert ei.value.owner_url == "http://b:1"
    assert ei.value.retry_after_s is not None
    assert lost == [1]  # the shard journal was told to close
    assert a.owned() == []


def test_ensure_owned_acquires_free_shard_on_demand(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    a.ensure_owned(2)
    assert a.owned() == [2]
    with pytest.raises(ValueError):
        a.ensure_owned(4)  # out of the bound ring


def test_crash_at_pre_lease_commit_leaves_only_a_stale_claim(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    chaos.install(ChaosPlan(point="fleet.pre_lease_commit", hit=1,
                            mode="raise"))
    with pytest.raises(SimulatedCrash):
        a.acquire(0)
    chaos.clear()
    # the claim was won but no lease was ever committed — nothing is
    # half-written
    assert a.owner_of(0) is None
    claims = [n for n in os.listdir(a.lease_dir) if ".claim." in n]
    assert claims == ["shard-0000.claim.1"]
    # a live claim blocks a rival for TTL...
    b = mgr(tmp_path, "rep-b", clock)
    assert not b.acquire(0)
    # ...then is broken atomically and the shard is granted fresh
    clock.advance(10.5)
    assert b.acquire(0)
    rec = b.owner_of(0)
    assert rec["owner"] == "rep-b" and rec["epoch"] == 1
    assert not [n for n in os.listdir(a.lease_dir) if ".claim." in n]


def test_lease_table_scans_records(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    b = mgr(tmp_path, "rep-b", clock)
    assert a.acquire(0) and b.acquire(3)
    table = lease_table(a.lease_dir)
    assert sorted(table) == [0, 3]
    assert table[0]["owner"] == "rep-a"
    assert table[3]["owner"] == "rep-b"


def test_keeper_respects_target_then_rescues_orphans(tmp_path):
    clock = Clock()
    a = mgr(tmp_path, "rep-a", clock)
    b = mgr(tmp_path, "rep-b", clock)
    ka = LeaseKeeper(a, target=2, rescue_after_s=20.0)
    kb = LeaseKeeper(b, target=2, rescue_after_s=20.0)
    ka.step()
    assert len(a.owned()) == 2  # target, not the whole ring
    kb.step()
    assert len(b.owned()) == 2
    # a goes silent; b keeps heartbeating in sub-TTL steps. Expired
    # but not yet orphaned shards stay untouched (b is at target)...
    for _ in range(4):
        clock.advance(4.0)
        kb.step()
    assert len(b.owned()) == 2
    # ...until the orphan deadline passes, then b rescues them all
    for _ in range(4):
        clock.advance(4.0)
        kb.step()
    assert len(b.owned()) == 4
    table = lease_table(b.lease_dir)
    assert sorted(table) == [0, 1, 2, 3]
    assert all(rec["owner"] == "rep-b" for rec in table.values())
    # exactly a's two shards changed hands (epoch 2); b kept its own
    assert sorted(rec["epoch"] for rec in table.values()) == [1, 1, 2, 2]


# ----------------------------------------------- lease-gated directory --


def test_directory_charge_fenced_after_takeover(tmp_path):
    clock = Clock()
    root = str(tmp_path / "budget")
    la = mgr(tmp_path, "rep-a", clock, n_shards=None)
    da = BudgetDirectory(root, shards=4, user_budget=100.0,
                         clock=clock, fsync=False, lease=la)
    assert da.charge("u1", 1.0, charge_id="c1")
    shard = da.shard_index("u1")
    assert shard in la.owned()
    before = da.spent("u1")
    # a rival waits out the TTL and takes the shard over
    lb = mgr(tmp_path, "rep-b", clock, n_shards=None)
    db = BudgetDirectory(root, shards=4, user_budget=100.0,
                         clock=clock, fsync=False, lease=lb)
    clock.advance(10.5)
    lb.ensure_owned(shard)
    # the stale holder's late charge is refused charge-free, naming
    # the real owner
    with pytest.raises(ShardNotOwnedError) as ei:
        da.charge("u1", 1.0, charge_id="c2")
    assert ei.value.owner == "rep-b"
    # the new owner replayed the WAL: balance exact, and the dying
    # holder's charge_id dedups a retry instead of double-charging
    assert db.spent("u1") == before == 1.0
    # a retry of the already-applied charge dedups (False = spent
    # nothing); the refused charge retries fresh and applies
    assert db.charge("u1", 1.0, charge_id="c1") is False
    assert db.spent("u1") == 1.0
    assert db.charge("u1", 1.0, charge_id="c2") is True
    assert db.spent("u1") == 2.0


# -------------------------------------------------------------- frontend --


class _StubReplica:
    """A canned /estimate endpoint with scriptable status/headers."""

    def __init__(self, status=200, body=None, headers=(), hook=None):
        self.status = status
        self.body = body if body is not None else {"ok": True}
        self.headers = list(headers)
        self.hook = hook
        self.hits = 0
        stub = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = self.rfile.read(n)
                stub.hits += 1
                status, body = stub.status, stub.body
                if stub.hook is not None:
                    status, body = stub.hook(payload)
                blob = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                for k, v in stub.headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


def test_frontend_passes_replica_response_through():
    rep = _StubReplica(status=200, body={"estimate": 0.5})
    try:
        fe = FleetFrontend({"rep-0": rep.url})
        status, headers, payload = fe.route(b'{"user": "u"}')
        assert status == 200
        assert json.loads(payload) == {"estimate": 0.5}
        assert fe.stats()["counts"]["routed:rep-0"] == 1
    finally:
        rep.close()


def test_frontend_injects_failover_idempotency_key():
    seen: list[dict] = []

    def hook(payload):
        seen.append(json.loads(payload))
        return 200, {"ok": True}

    rep = _StubReplica(hook=hook)
    try:
        fe = FleetFrontend({"rep-0": rep.url})
        fe.route(b'{"user": "u"}')
        assert seen[0]["idempotency_key"].startswith("fe:")
        # a client-chosen identity is never overwritten
        fe.route(b'{"user": "u", "idempotency_key": "mine"}')
        assert seen[1]["idempotency_key"] == "mine"
    finally:
        rep.close()


def test_frontend_affinity_keeps_a_user_on_one_replica():
    reps = [_StubReplica() for _ in range(3)]
    try:
        fe = FleetFrontend({f"rep-{i}": r.url
                            for i, r in enumerate(reps)})
        for _ in range(6):
            status, _, _ = fe.route(b'{"user": "sticky-user"}')
            assert status == 200
        assert sorted(r.hits for r in reps) == [0, 0, 6]
    finally:
        for r in reps:
            r.close()


def test_frontend_forwards_421_and_learns_the_owner():
    owner = _StubReplica(status=200, body={"estimate": 1.0})
    refuser = _StubReplica(
        status=421, body={"refused": "not-owner", "owner": "rep-owner",
                          "owner_url": None})
    refuser.body["owner_url"] = owner.url
    try:
        fe = FleetFrontend({"rep-0": refuser.url})  # owner unknown
        status, _, payload = fe.route(b'{"user": "u"}')
        assert status == 200
        assert json.loads(payload) == {"estimate": 1.0}
        assert refuser.hits == 1 and owner.hits == 1
        s = fe.stats()
        assert s["counts"]["forwards"] == 1
        assert "rep-owner" in s["replicas"]
    finally:
        owner.close()
        refuser.close()


def test_frontend_passes_retry_after_through():
    rep = _StubReplica(status=503, body={"refused": "queue_full"},
                       headers=[("Retry-After", "7")])
    try:
        fe = FleetFrontend({"rep-0": rep.url})
        status, headers, _ = fe.route(b'{"user": "u"}')
        assert status == 503
        assert ("Retry-After", "7") in headers
    finally:
        rep.close()


def test_frontend_circuit_sidelines_a_dead_replica():
    rep = _StubReplica()
    try:
        # rep-dead points at a port nothing listens on
        fe = FleetFrontend({"rep-0": rep.url,
                            "rep-dead": "http://127.0.0.1:9"},
                           fail_threshold=2, cooldown_s=60.0)
        for _ in range(8):
            status, _, _ = fe.route(b"{}")
            assert status == 200  # the hop loop always lands on rep-0
        assert fe.stats()["counts"]["transport_errors"] == 2
        # after the threshold the breaker keeps the dead name out of
        # the candidate order entirely
        assert "rep-dead" not in fe._candidates(None)
    finally:
        rep.close()


def test_frontend_503s_when_no_replica_answers():
    fe = FleetFrontend({"rep-dead": "http://127.0.0.1:9"})
    status, headers, payload = fe.route(b'{"user": "u"}')
    assert status == 503
    assert json.loads(payload)["refused"] == "breaker"
    assert any(k == "Retry-After" for k, _ in headers)


# ------------------------------------------------------------ supervisor --

_STUB_REPLICA_SRC = """\
import json, sys, time
print(json.dumps({"serving": {"host": "127.0.0.1", "port": 45678}}))
sys.stdout.flush()
time.sleep(120)
"""


@pytest.mark.slow
def test_serve_instance_defaults_from_bound_port(tmp_path):
    """`dpcorr serve --port 0` with no --instance: the identity is
    derived from the bound ephemeral port (serve-<port>), so two
    replicas of one fleet can share an argv template without
    colliding names."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dpcorr", "serve", "--port", "0",
         "--budget", "5", "--aot", "off",
         "--ledger", str(tmp_path / "ledger.json")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env)
    try:
        deadline = time.monotonic() + 300
        banner = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline().strip()
            if not line:
                assert proc.poll() is None, "server died before banner"
                continue
            try:
                banner = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "serving" in banner:
                break
        assert banner is not None and "serving" in banner
        srv = banner["serving"]
        assert srv["instance"] == f"serve-{srv['port']}"
    finally:
        proc.terminate()
        proc.wait(timeout=30)


@pytest.mark.slow
def test_supervisor_restarts_dead_replica_with_identical_argv(tmp_path):
    ups: list[tuple[str, str]] = []
    downs: list[str] = []
    spec = ReplicaSpec(name="stub",
                       argv=[sys.executable, "-c", _STUB_REPLICA_SRC],
                       stderr_path=str(tmp_path / "stub.log"))
    argv_before = list(spec.argv)
    sup = Supervisor([spec], poll_s=0.05, backoff_s=0.05,
                     on_up=lambda n, url, b: ups.append((n, url)),
                     on_down=lambda n, rc: downs.append(n))
    sup.start()
    try:
        assert ups == [("stub", "http://127.0.0.1:45678")]
        sup.kill("stub")
        assert sup.wait_restarted("stub", 1, timeout_s=30.0)
        assert sup.restarts["stub"] == 1
        assert downs == ["stub"]
        assert len(ups) == 2  # the reboot re-announced itself
        assert sup.specs["stub"].argv == argv_before  # same argv, verbatim
    finally:
        sup.stop()
