"""N-party federation tests (ISSUE 12): the k×k matrix over
multiplexed pair sessions.

The acceptance contract, pinned here end to end:

- **bit identity** — the federation matrix equals k·(k−1)/2 independent
  two-party sessions over the same per-column key labels, on both
  transports, with any round chunking, and under fault injection;
- **ε optimum** — total spend is the column-release-reuse optimum
  ``2·f·ε·(k−1)`` (strictly less than the naive per-cell
  ``f·ε·k·(k−1)`` for k ≥ 3), each party's ledger showing exactly its
  plan share;
- **exactly-once resume** — any party killed at any federation chaos
  point resumes on restart with the identical matrix and no double
  spend;
- **the cross-pair gate** — reused releases are byte-identical across
  every pair session, and the scanner refuses divergence.
"""

import threading

import numpy as np
import pytest

from dpcorr import chaos
from dpcorr.models.estimators import split_reference as sr
from dpcorr.obs.audit import AuditTrail, read_events
from dpcorr.protocol import InProcTransport, ProtocolRefused, run_inproc
from dpcorr.protocol.federation import (
    make_federation_parties,
    run_federation_inproc,
    run_federation_tcp,
)
from dpcorr.protocol.matrix import FederationPlan, _factor
from dpcorr.protocol.messages import read_transcript
from dpcorr.protocol.scan import (
    federation_balance,
    scan_federation,
    scan_transcript,
)
from dpcorr.serve.ledger import PrivacyLedger, release_factor
from dpcorr.utils import rng

FAMILIES = ("ni_sign", "int_sign", "ni_subg", "int_subg")
N = 512


def _plan(family="ni_sign", n=N, eps=1.0, **kw):
    """The canonical 3-party / 4-column case: one local cell (p0's
    a×b), three pair links, every reuse pattern exercised."""
    return FederationPlan(
        family=family, n=n, eps=eps,
        parties=[("p0", ["a", "b"]), ("p1", ["c"]), ("p2", ["d"])], **kw)


def _data(plan, rho=0.6):
    k = plan.k
    cov = np.full((k, k), rho)
    np.fill_diagonal(cov, 1.0)
    xy = np.random.default_rng(plan.seed).multivariate_normal(
        np.zeros(k), cov, size=plan.n)
    return {lab: np.asarray(xy[:, i], np.float32)
            for i, (_owner, lab) in enumerate(plan.columns())}


def _merged(results) -> dict:
    """Union of every party's cell view, asserting bitwise agreement
    on shared cells."""
    cells: dict = {}
    for res in results.values():
        for key, val in res.cells.items():
            if key in cells:
                assert cells[key] == val, f"parties disagree on {key}"
            cells[key] = val
    return cells


# ------------------------------------------------------------ plan ----

@pytest.mark.parametrize("family", FAMILIES)
@pytest.mark.parametrize("normalise", [True, False])
def test_release_factor_pin(family, normalise):
    # matrix._factor is the jax-free mirror of serve.ledger's factor —
    # the planner's ε arithmetic must never drift from the gate's
    assert _factor(family, normalise) == release_factor(family, normalise)


def test_plan_schedule():
    plan = _plan()
    assert plan.k == 4
    assert plan.links() == (("p0", "p1"), ("p0", "p2"), ("p1", "p2"))
    assert plan.local_cells("p0") == ((0, 1),)
    assert plan.cell_venue(0, 2) == ("link", "p0", "p1")
    # one round per link by default; chunked at 1 → one cell per round
    assert len(plan.link_rounds("p0", "p1")) == 1
    assert plan.round_x_labels("p0", "p1", 0) == ("a", "b")
    chunked = _plan(max_cells_per_round=1)
    assert len(chunked.link_rounds("p0", "p1")) == 2
    # the public identity round-trips and pins the schedule
    clone = FederationPlan.from_public(plan.to_public())
    assert clone.fed_hash() == plan.fed_hash()
    assert clone.fed == plan.fed
    spec = plan.cell_spec(0, 2)
    assert (spec.key_x, spec.key_y) == ("a", "c")
    assert (spec.party_x, spec.party_y) == ("p0", "p1")


def test_plan_eps_arithmetic():
    plan = _plan()  # ni_sign normalised: f = 2
    assert plan.optimal_eps() == 2 * 2.0 * 1.0 * (plan.k - 1) == 12.0
    assert plan.naive_eps() == 2 * 2.0 * 1.0 * len(plan.cells()) == 24.0
    assert plan.optimal_eps() < plan.naive_eps()  # strict for k >= 3
    per = plan.party_eps()
    assert per == {"p0": 6.0, "p1": 4.0, "p2": 2.0}
    assert abs(sum(per.values()) - plan.optimal_eps()) < 1e-12
    # every artifact is charged at exactly one venue
    venues = plan.artifact_venues()
    assert len(venues) == 2 * (plan.k - 1)
    lc = plan.local_charges("p0")
    assert lc["artifacts"] == (("x", "a"), ("y", "b"))
    assert lc["charges"] == {"p0": 4.0}
    assert lc["charge_id"].endswith(":local")


# ---------------------------------------------------- finish batch ----

@pytest.mark.parametrize("family", FAMILIES)
def test_finish_batch_exact_is_bitwise_per_cell(family):
    plan = _plan(family=family)
    data = _data(plan)
    eps = plan.eps

    def root(lab, side):
        return rng.party_root(
            rng.column_root(rng.master_key(plan.seed), lab), side,
            "replay")

    labels_x = ["a", "b", "c"]
    rels = [sr.party_release(family, root(lab, "x"), "x", data[lab],
                             eps, eps, True) for lab in labels_x]
    keys = [root("d", "y")] * len(labels_x)
    cols = [data["d"]] * len(labels_x)
    rho, lo, hi = sr.finish_batch(family, keys, rels, cols, eps, eps)
    assert rho.shape == (3,)
    for b in range(len(labels_x)):
        r1, l1, h1 = sr.finish(family, keys[b], rels[b], cols[b], eps,
                               eps)
        assert (float(rho[b]), float(lo[b]), float(hi[b])) \
            == (float(r1), float(l1), float(h1))


def test_finish_batch_vector_engine_and_validation():
    plan = _plan()
    data = _data(plan)
    key = rng.party_root(
        rng.column_root(rng.master_key(plan.seed), "a"), "x", "replay")
    rel = sr.party_release("ni_sign", key, "x", data["a"], 1.0, 1.0,
                           True)
    fkey = rng.party_root(
        rng.column_root(rng.master_key(plan.seed), "d"), "y", "replay")
    rho, lo, hi = sr.finish_batch("ni_sign", [fkey], [rel], [data["d"]],
                                  1.0, 1.0, engine="vector")
    exact, _, _ = sr.finish_batch("ni_sign", [fkey], [rel], [data["d"]],
                                  1.0, 1.0, engine="exact")
    assert np.allclose(float(rho[0]), float(exact[0]), atol=1e-6)
    with pytest.raises(ValueError, match="engine"):
        sr.finish_batch("ni_sign", [fkey], [rel], [data["d"]], 1.0, 1.0,
                        engine="nope")
    with pytest.raises(ValueError, match="length mismatch"):
        sr.finish_batch("ni_sign", [fkey, fkey], [rel], [data["d"]],
                        1.0, 1.0)


# ----------------------------------------------------- bit identity ----

def test_matrix_bit_identical_to_independent_runs():
    plan = _plan()
    data = _data(plan)
    cells = _merged(run_federation_inproc(plan, data))
    assert sorted(cells) == [f"{i},{j}" for i, j in plan.cells()]
    for i, j in plan.cells():
        ref = run_inproc(plan.cell_spec(i, j), data[plan.label(i)],
                         data[plan.label(j)])["x"]
        got = cells[f"{i},{j}"]
        assert (got["rho_hat"], got["ci_low"], got["ci_high"]) \
            == (ref.rho_hat, ref.ci_low, ref.ci_high), (i, j)


def test_matrix_tcp_and_chunked_same_bits():
    plan = _plan()
    data = _data(plan)
    ref = _merged(run_federation_inproc(plan, data))
    assert _merged(run_federation_tcp(plan, data)) == ref
    # one cell per round: more envelopes, identical bits — chunking is
    # pure scheduling
    assert _merged(run_federation_inproc(
        _plan(max_cells_per_round=1), data)) == ref


def test_multiplexed_rounds_survive_faults():
    plan = _plan()
    data = _data(plan)
    clean = _merged(run_federation_inproc(plan, data))
    res = run_federation_inproc(
        plan, data, fault={"drop": 0.15, "duplicate": 0.15},
        timeout_s=0.2)
    assert _merged(res) == clean
    retries = sum(st["total_retries"] for r in res.values()
                  for st in r.stats.values())
    assert retries > 0, "fault arm proved nothing"


# ------------------------------------------------------------- ε ----

def test_eps_spent_at_release_reuse_optimum():
    plan = _plan()
    data = _data(plan)
    ledgers = {name: PrivacyLedger(1e6) for name, _ in plan.parties}
    res = run_federation_inproc(plan, data, ledgers=ledgers)
    for name, want in plan.party_eps().items():
        assert abs(ledgers[name].spent(name) - want) < 1e-9, name
    total = sum(ledgers[n_].spent(n_) for n_, _ in plan.parties)
    assert abs(total - plan.optimal_eps()) < 1e-9
    assert total < plan.naive_eps()
    # per-cell cost attributions sum back to the whole-matrix ε
    eps_new = sum(c["eps_new"] for r in res.values() for c in r.costs
                  if len(c["pair"]) > 1 or c["pair"] == [r.party])
    # wire cells are attributed on the finisher only; local on the owner
    attributed = sum(
        c["eps_new"] for r in res.values() for c in r.costs)
    assert abs(attributed - plan.optimal_eps()) < 1e-9, eps_new


def test_budget_refusal_before_any_release():
    plan = _plan()
    data = _data(plan)
    ledgers = {name: PrivacyLedger(0.5) for name, _ in plan.parties}
    with pytest.raises(ProtocolRefused):
        run_federation_inproc(plan, data, ledgers=ledgers,
                              timeout_s=0.2, max_retries=3,
                              recv_timeout_s=2.0)


# ----------------------------------------------------- crash-resume ----

#: Victims chosen so the point actually fires in that party: p0
#: initiates (releases on) both its links, p1 finishes p0-p1, and
#: mid_matrix fires in every party's join loop.
_VICTIMS = {"federation.pre_release": "p0",
            "federation.pre_finish": "p1",
            "federation.mid_matrix": "p2"}


@pytest.mark.parametrize("point", sorted(_VICTIMS))
def test_crash_resume_exactly_once(point, tmp_path):
    victim = _VICTIMS[point]
    plan = _plan()
    data = _data(plan)
    ref = _merged(run_federation_inproc(plan, data))

    def ledgers():
        # path-persistent: the restart reloads the exact balances,
        # like a real process would
        return {name: PrivacyLedger(
            1e6, path=str(tmp_path / f"ledger.{name}.json"))
            for name, _ in plan.parties}

    endpoints = {lk: InProcTransport() for lk in plan.links()}
    parties = make_federation_parties(
        plan, data, ledgers=ledgers(), endpoints=endpoints,
        journal_dir=str(tmp_path))
    chaos.install(chaos.ChaosPlan(point, hit=1, mode="raise",
                                  thread_name=f"party-{victim}"))
    results: dict = {}
    errors: dict = {}

    def drive(name, party):
        try:
            results[name] = party.run()
        except BaseException as e:  # SimulatedCrash is a BaseException
            errors[name] = e

    threads = {name: threading.Thread(target=drive, args=(name, p),
                                      name=f"party-{name}")
               for name, p in parties.items()}
    try:
        for t in threads.values():
            t.start()
        threads[victim].join()
    finally:
        chaos.install(None)
    assert isinstance(errors.pop(victim), chaos.SimulatedCrash)
    # restart: fresh party objects on the surviving queue pairs, same
    # journals, ledgers reloaded from disk — "rerun the same command"
    fresh = make_federation_parties(
        plan, data, ledgers=ledgers(), endpoints=endpoints,
        journal_dir=str(tmp_path))
    rerun = threading.Thread(target=drive, args=(victim, fresh[victim]),
                             name=f"party-{victim}")
    rerun.start()
    rerun.join()
    for name, t in threads.items():
        if name != victim:
            t.join()
    assert not errors, errors
    assert set(results) == {name for name, _ in plan.parties}
    assert _merged(results) == ref
    final = ledgers()
    for name, want in plan.party_eps().items():
        assert abs(final[name].spent(name) - want) < 1e-9, name


# ------------------------------------------------------------ scan ----

def _transcript_paths(plan, tmp_path):
    return {name: [str(tmp_path / f"{plan.link_session(p, q)}"
                       f".{name}.jsonl")
                   for p, q in plan.party_links(name)]
            for name, _ in plan.parties}


def test_scan_federation_clean_and_balanced(tmp_path):
    plan = _plan()
    data = _data(plan)
    audits = {name: AuditTrail(str(tmp_path / f"audit.{name}.jsonl"))
              for name, _ in plan.parties}
    ledgers = {name: PrivacyLedger(1e6, audit=audits[name])
               for name, _ in plan.parties}
    run_federation_inproc(plan, data, ledgers=ledgers,
                          transcript_dir=str(tmp_path))
    paths = _transcript_paths(plan, tmp_path)
    flat = sorted({t for ts in paths.values() for t in ts})
    assert len(flat) == 2 * len(plan.links())
    for t in flat:
        rep = scan_transcript(t)
        assert rep["ok"], (t, rep["violations"])
        assert rep["federation"] is True
    cross = scan_federation(flat)
    assert cross["ok"], cross["violations"]
    # labels that crossed a wire: a, b (p0's) and c (p1's, to p2)
    assert cross["labels"] == ["a", "b", "c"]
    for name, _ in plan.parties:
        expected_local = sum(
            plan.local_charges(name)["charges"].values())
        bal = federation_balance(
            paths[name],
            read_events(str(tmp_path / f"audit.{name}.jsonl")),
            expected_local_eps=expected_local)
        assert bal["ok"], (name, bal)
        assert abs(bal["spent"][name] - plan.party_eps()[name]) < 1e-9


def test_scan_federation_catches_renoised_release(tmp_path):
    plan = _plan()
    data = _data(plan)
    run_federation_inproc(plan, data, transcript_dir=str(tmp_path))
    flat = sorted({t for ts in _transcript_paths(plan,
                                                 tmp_path).values()
                   for t in ts})
    tampered = [read_transcript(t) for t in flat]
    hits = 0
    for e in tampered[0]:
        w = e.get("wire", {})
        if w.get("msg_type") == "release":
            arts = w["payload"]["artifacts"]
            # a re-noised (or swapped) release of column "a": its bytes
            # now diverge from every other pair session embedding "a"
            arts["a"], arts["b"] = arts["b"], arts["a"]
            hits += 1
    assert hits, "no release round found to tamper with"
    rep = scan_federation(tampered)
    assert not rep["ok"]
    rules = {v["rule"] for v in rep["violations"]}
    assert "cross-pair-release-divergence" in rules
    offending = " ".join(v["detail"] for v in rep["violations"])
    assert plan.link_session("p0", "p1") in offending


def test_chaos_cli_federation_victim_map():
    # the chaos CLI sweeps every MATRIX_POINTS × {x, y}; federation
    # points must map both roles onto a victim party so the case count
    # (2 per point) holds
    from dpcorr.__main__ import _FED_VICTIMS

    fed_points = {p for p in chaos.MATRIX_POINTS
                  if p.startswith("federation.")}
    assert set(_FED_VICTIMS) == fed_points == set(_VICTIMS)
    for mapping in _FED_VICTIMS.values():
        assert set(mapping) == {"x", "y"}
        assert set(mapping.values()) <= {"p0", "p1", "p2"}


# ---------------------------------------------------------- report ----

def test_correlation_matrix_frame():
    pytest.importorskip("pandas")
    pytest.importorskip("matplotlib")
    from dpcorr.report import correlation_matrix_frame

    plan = _plan()
    data = _data(plan)
    res = run_federation_inproc(plan, data)
    df = correlation_matrix_frame(res, plan)
    assert list(df.columns) == ["i", "j", "label_x", "label_y", "venue",
                                "rho_hat", "ci_low", "ci_high"]
    assert len(df) == len(plan.cells())
    assert df.iloc[0]["venue"] == "local@p0"
    assert set(df["venue"]) == {"local@p0", "link p0-p1", "link p0-p2",
                                "link p1-p2"}
    # one party's partial view still frames (its own cells only)
    assert len(correlation_matrix_frame(res["p2"])) \
        == len(res["p2"].cells)
    bad = dict(res["p0"].cells)
    bad["0,1"] = {"rho_hat": 0.0, "ci_low": 0.0, "ci_high": 0.0}
    with pytest.raises(ValueError, match="disagree"):
        correlation_matrix_frame({"p0": res["p0"],
                                  "bad": type(res["p0"])(
                                      party="bad", fed=plan.fed,
                                      cells=bad, eps={})})
