"""dpcorr doctor: the operational triage tool (SURVEY.md §5 failure
detection — the reference has none; this framework's tunnel runtime
needs one, docs/STATUS_r04.md wedge forensics)."""

import json
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from dpcorr.utils import doctor


def test_check_relay_detects_listener_and_refusal():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    try:
        up = doctor.check_relay(ports=(port,), timeout=2.0)
        assert up["alive"] and up["open_ports"] == [port]
    finally:
        srv.close()
    down = doctor.check_relay(ports=(port,), timeout=2.0)
    assert not down["alive"] and down["open_ports"] == []


def test_stray_scan_ignores_parented_worker(tmp_path):
    """A live-parented process whose cmdline looks exactly like a bench
    worker must NOT be flagged (the ppid==1 test is the real guard —
    flagging parented workers would let --sweep kill an in-flight
    bench run)."""
    fake = tmp_path / "bench.py"
    fake.write_text("import time\ntime.sleep(30)\n")
    p = subprocess.Popen([sys.executable, str(fake), "--worker", "tpu"])
    try:
        time.sleep(0.3)
        assert p.pid not in [s["pid"] for s in doctor.find_stray_workers()]
    finally:
        p.kill()
        p.wait()


def test_compile_cache_report(tmp_path, monkeypatch):
    monkeypatch.delenv("DPCORR_COMPILE_CACHE", raising=False)
    rep = doctor.check_compile_cache(str(tmp_path / "nope"))
    assert rep["path"] == str(tmp_path / "nope") and not rep["present"]
    assert rep["cli_path"] is None          # CLI cache is opt-in
    d = tmp_path / "cache"
    d.mkdir()
    (d / "a.bin").write_bytes(b"x" * 1000)
    (d / "b.bin").write_bytes(b"y" * 500)
    rep = doctor.check_compile_cache(str(d))
    assert rep["present"] and rep["entries"] == 2
    assert rep["mb"] == round(1500 / 1e6, 1)


def test_cache_env_consumer_semantics(monkeypatch):
    """One parse, two defaults: bench defaults ON at the per-user path,
    the dpcorr CLI stays cold unless the var names a dir; explicit
    disable tokens kill both (bench.py:179-184, __main__ opt-in)."""
    monkeypatch.delenv("DPCORR_COMPILE_CACHE", raising=False)
    assert doctor.resolve_cache_dir("bench") == doctor.DEFAULT_CACHE
    assert doctor.resolve_cache_dir("cli") is None
    monkeypatch.setenv("DPCORR_COMPILE_CACHE", "/scratch/xla")
    assert doctor.resolve_cache_dir("bench") == "/scratch/xla"
    assert doctor.resolve_cache_dir("cli") == "/scratch/xla"
    for tok in ("0", "off", "NONE"):
        monkeypatch.setenv("DPCORR_COMPILE_CACHE", tok)
        assert doctor.resolve_cache_dir("bench") is None
        assert doctor.resolve_cache_dir("cli") is None
        assert doctor.check_compile_cache()["disabled"]


def test_queue_marker_report(tmp_path):
    (tmp_path / "s1.ok").touch()
    (tmp_path / "s2.fail").write_text("wedged the tunnel 3x")
    (tmp_path / "s3.wedges").write_text("2\n")
    (tmp_path / "s1.json").write_text("{}")   # non-marker: ignored
    q = doctor.check_queue(str(tmp_path))
    assert q["ok"] == ["s1"] and q["fail"] == ["s2"]
    assert q["wedges"] == {"s3": 2}
    assert not doctor.check_queue(str(tmp_path / "gone"))["present"]


def test_diagnose_verdicts(monkeypatch, tmp_path):
    monkeypatch.setattr(doctor, "find_stray_workers", lambda: [])
    monkeypatch.setattr(doctor, "check_relay",
                        lambda ports=None, timeout=None: {
                            "alive": False, "open_ports": [],
                            "checked": [1]})
    rep = doctor.diagnose(queue_dir=str(tmp_path),
                          cache_dir=str(tmp_path))
    assert rep["verdict"].startswith("tunnel-endpoint-dead")

    # strays + dead endpoint: the endpoint condition dominates (sweeping
    # then re-probing is futile against a dead relay — the probe would
    # be skipped anyway), with the strays kept as a secondary note
    # (ADVICE r04)
    monkeypatch.setattr(doctor, "find_stray_workers",
                        lambda: [{"pid": 99999999, "cmdline": "x"}])
    rep = doctor.diagnose(queue_dir=str(tmp_path),
                          cache_dir=str(tmp_path))
    assert rep["verdict"].startswith("tunnel-endpoint-dead+stray-client")

    monkeypatch.setattr(doctor, "check_relay",
                        lambda ports=None, timeout=None: {
                            "alive": True, "open_ports": [1],
                            "checked": [1]})
    rep = doctor.diagnose(queue_dir=str(tmp_path),
                          cache_dir=str(tmp_path))
    assert rep["verdict"].startswith("stray-client")

    monkeypatch.setattr(doctor, "find_stray_workers", lambda: [])
    rep = doctor.diagnose(queue_dir=str(tmp_path),
                          cache_dir=str(tmp_path))
    assert rep["verdict"].startswith("ok")
    # text renderer covers every section without raising
    assert "verdict" in doctor.render_text(rep)


def test_queue_dir_resolution_matches_queue_script(monkeypatch):
    """doctor must read the same marker dir the queue writes
    (OUT=${TPU_R05_IN:-/tmp/tpu_r05} in tpu_r05_queue.sh), falling back
    to the r04 dir only when it exists and no r05 state does."""
    monkeypatch.delenv("TPU_R05_IN", raising=False)
    monkeypatch.delenv("TPU_R04_IN", raising=False)
    monkeypatch.setattr(doctor.os.path, "isdir", lambda p: False)
    assert doctor.default_queue_dir() == "/tmp/tpu_r05"
    monkeypatch.setenv("TPU_R05_IN", "/data/r05")
    assert doctor.default_queue_dir() == "/data/r05"
    monkeypatch.delenv("TPU_R05_IN", raising=False)
    # r05 state present -> it wins even with an r04 override set
    monkeypatch.setenv("TPU_R04_IN", "/data/r04")
    monkeypatch.setattr(doctor.os.path, "isdir",
                        lambda p: p == "/tmp/tpu_r05")
    assert doctor.default_queue_dir() == "/tmp/tpu_r05"
    # no r05 state, r04 markers exist -> legacy fallback
    monkeypatch.setattr(doctor.os.path, "isdir",
                        lambda p: p == "/data/r04")
    assert doctor.default_queue_dir() == "/data/r04"
    # an explicit TPU_R04_IN is honored even before its dir exists —
    # same rule as TPU_R05_IN (an operator override is a statement of
    # intent, not a claim the queue already ran)
    monkeypatch.setattr(doctor.os.path, "isdir", lambda p: False)
    assert doctor.default_queue_dir() == "/data/r04"
    # the *default* legacy dir still has to prove itself
    monkeypatch.delenv("TPU_R04_IN", raising=False)
    assert doctor.default_queue_dir() == "/tmp/tpu_r05"


def test_probe_skipped_when_relay_dead(monkeypatch, tmp_path):
    """--probe against a dead endpoint must not burn the 150s jax
    timeout (the same short-circuit the queue's probe applies)."""
    monkeypatch.setattr(doctor, "find_stray_workers", lambda: [])
    monkeypatch.setattr(doctor, "check_relay",
                        lambda ports=None, timeout=None: {
                            "alive": False, "open_ports": [],
                            "checked": [1]})
    monkeypatch.setattr(doctor, "probe_device", lambda timeout_s=150.0: (
        pytest.fail("probe_device must not run against a dead relay")))
    rep = doctor.diagnose(probe=True, queue_dir=str(tmp_path),
                          cache_dir=str(tmp_path))
    assert rep["device_probe"] == {"ok": False,
                                   "skipped": "relay endpoint down"}
    assert "skipped — relay endpoint down" in doctor.render_text(rep)


def test_probe_skipped_when_stray_survives(monkeypatch, tmp_path):
    """A stray that --sweep could not kill still holds the exclusive
    TPU client; probing against it can only hang to the timeout."""
    stray = [{"pid": 99999999, "cmdline": "x"}]
    monkeypatch.setattr(doctor, "find_stray_workers", lambda: stray)
    monkeypatch.setattr(doctor, "sweep_strays", lambda s: [])
    monkeypatch.setattr(doctor, "check_relay",
                        lambda ports=None, timeout=None: {
                            "alive": True, "open_ports": [1],
                            "checked": [1]})
    monkeypatch.setattr(doctor, "probe_device", lambda timeout_s=150.0: (
        pytest.fail("probe must not run while a stray holds the client")))
    rep = doctor.diagnose(probe=True, sweep=True,
                          queue_dir=str(tmp_path), cache_dir=str(tmp_path))
    assert rep["swept"] == []
    assert rep["device_probe"]["skipped"].startswith("stray client")
    assert rep["verdict"].startswith("stray-client-unkillable")


def test_cache_consumer_typo_raises():
    with pytest.raises(ValueError):
        doctor.resolve_cache_dir("Bench")


def test_lazy_package_init_keeps_doctor_jax_free():
    """dpcorr.__init__ re-exports MASTER_SEED lazily (PEP 562) so the
    doctor import chain never imports jax; pin both properties."""
    repo = Path(__file__).parent.parent
    # -S skips the axon site hook that preloads jax unconditionally —
    # the property under test is OUR import chain, not the hook's.
    # (-S also drops site-packages, so jax is unimportable here: the
    # doctor chain must survive that too.)
    r = subprocess.run(
        [sys.executable, "-S", "-c",
         "import sys; sys.path.insert(0, '.'); "
         "import dpcorr.utils.doctor; "
         "assert 'jax' not in sys.modules, 'doctor import pulled jax'"],
        capture_output=True, text=True, timeout=120, cwd=repo)
    assert r.returncode == 0, r.stderr[-500:]
    # the lazy re-export still works where jax IS importable
    import dpcorr

    assert dpcorr.MASTER_SEED == 2025


def test_doctor_cli_json(tmp_path):
    """End-to-end CLI drive: no JAX backend init without --probe (fast),
    valid one-line JSON with --json."""
    qdir = tmp_path / "no-such-queue"
    r = subprocess.run(
        [sys.executable, "-m", "dpcorr", "doctor", "--json",
         "--queue-dir", str(qdir)],
        capture_output=True, text=True, timeout=120,
        cwd=Path(__file__).parent.parent)
    assert r.returncode == 0, r.stderr[-300:]
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert "relay" in rep and "verdict" in rep
    assert rep["queue"] == {"state_dir": str(qdir), "present": False}


def test_relay_ports_env_override(monkeypatch):
    """DPCORR_RELAY_PORTS (comma-separated) overrides the baked-in relay
    port list; an unparseable or empty override falls back to the
    default instead of crashing the diagnostic tool."""
    monkeypatch.delenv("DPCORR_RELAY_PORTS", raising=False)
    assert doctor.relay_ports() == doctor.RELAY_PORTS
    monkeypatch.setenv("DPCORR_RELAY_PORTS", "9001, 9002")
    assert doctor.relay_ports() == (9001, 9002)
    monkeypatch.setenv("DPCORR_RELAY_PORTS", "not,ports")
    assert doctor.relay_ports() == doctor.RELAY_PORTS
    monkeypatch.setenv("DPCORR_RELAY_PORTS", " , ")
    assert doctor.relay_ports() == doctor.RELAY_PORTS
    # check_relay defaults route through the override
    monkeypatch.setenv("DPCORR_RELAY_PORTS", "1")  # port 1: always refused
    rep = doctor.check_relay(timeout=0.2)
    assert rep["checked"] == [1]
