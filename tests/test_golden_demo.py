"""Golden pin of the reference's demo design point (VERDICT r4 #6).

The reference's single recorded "expected output" site is the demo at
vert-cor.R:449-466 — `run_sim_one(n=2000, rho=-0.95, eps1=0.5, eps2=1,
mu=c(2,2), sigma=c(2,0.1), normalise=T, B=1000)` followed by
`print(res$summary)`. **Finding (r05): the in-source output there is
elided** — lines 461-463 read literally `#> 1 ...  (non-interactive
stats)` — so no numeric R output exists anywhere in the reference to
compare against, and this image carries no R interpreter to generate
one (`r/validate_bridge.R` + docs/R_BRIDGE.md hold the executable
recipe for an environment that does).

What CAN be pinned, is, here:

1. the exact demo config (any drift in `python -m dpcorr demo`'s
   design point would silently invalidate the comparison the R bridge
   recipe documents);
2. the summary schema — the reference's `summarise()` emits exactly
   (mse, bias, var, coverage, ci_length) per method (vert-cor.R:421-437);
3. frozen golden values of the summary at the default seed on the CPU
   test platform — a regression tripwire: any estimator-math change
   that moves the demo's output fails here first;
4. construction-level sanity: this point sits in the Laplace/clamp
   regime (√n·ε_r ≈ 0.5·√2000·... with ρ=-0.95 near the η boundary),
   so BOTH methods under-cover nominal 0.95 — matching the reference's
   construction, whose demo comment calls B=1000 a smoke count.
"""

import json

import pytest

GOLDEN = {
    "NI": {"mse": 0.03195109963417053, "bias": 0.0762525200843811,
           "var": 0.02616281434893608, "coverage": 0.906,
           "ci_length": 0.46785762906074524},
    "INT": {"mse": 0.0013850682880729437, "bias": 0.015022635459899902,
            "var": 0.0011605502804741263, "coverage": 0.891,
            "ci_length": 0.09626531600952148},
}

#: vert-cor.R:449-458, verbatim
REF_DEMO = dict(n=2000, rho=-0.95, eps1=0.5, eps2=1.0, b=1000,
                dgp="gaussian", dgp_args={"mu": (2.0, 2.0),
                                          "sigma": (2.0, 0.1)})


@pytest.fixture(scope="module")
def demo_summary():
    from dpcorr.sim import SimConfig, run_sim_one

    cfg = SimConfig(seed=2025, **REF_DEMO)
    assert cfg.normalise, "reference demo sets normalise=T"
    return run_sim_one(cfg).summary


def test_demo_schema_matches_reference_summarise(demo_summary):
    assert set(demo_summary) == {"NI", "INT"}
    for method in ("NI", "INT"):
        assert list(demo_summary[method]) == [
            "mse", "bias", "var", "coverage", "ci_length"], method


def test_demo_summary_matches_golden(demo_summary):
    """Frozen r05 CPU values at the default seed. A failure here means
    the estimator math (or the PRNG stream layout) moved the demo's
    output — either a bug or a deliberate change that must re-freeze
    these numbers WITH a changelog note. Tolerances: 1e-4 relative for
    the float stats (XLA minor-version fusion jitter), 2/B absolute for
    coverage (one boundary replication flipping)."""
    for method, stats in GOLDEN.items():
        for stat, want in stats.items():
            got = demo_summary[method][stat]
            if stat == "coverage":
                assert abs(got - want) <= 2 / REF_DEMO["b"], (method, stat)
            else:
                assert got == pytest.approx(want, rel=1e-4), (method, stat)


def test_demo_point_is_in_the_undercoverage_regime(demo_summary):
    """ρ=-0.95 at ε1=0.5 puts the demo near the η-space clamp where the
    reference's construction under-covers at finite n (the same class
    of documented finite-n behavior as the subG INT point). Pin the
    *direction* so a future 'fix' that silently recenters coverage at
    nominal — diverging from the reference's construction — trips."""
    assert 0.85 < demo_summary["NI"]["coverage"] < 0.94
    assert 0.85 < demo_summary["INT"]["coverage"] < 0.94
    # INT's interval is ~5x tighter at this design point — the
    # reference's headline qualitative contrast (interactive wins)
    assert (demo_summary["INT"]["ci_length"] * 3
            < demo_summary["NI"]["ci_length"])


def test_demo_cli_runs_the_reference_config(capsys):
    """`python -m dpcorr demo` must run exactly the reference's demo
    design point (vert-cor.R:449-458) — config drift would invalidate
    the R-bridge comparison recipe (docs/R_BRIDGE.md)."""
    from dpcorr.__main__ import main

    main(["demo", "--b", "8"])
    out = json.loads(capsys.readouterr().out)
    assert out["config"] == {"n": 2000, "rho": -0.95,
                             "eps": [0.5, 1.0], "B": 8,
                             "dgp": "gaussian",
                             "dgp_args": {"mu": [2.0, 2.0],
                                          "sigma": [2.0, 0.1]},
                             "normalise": True, "seed": 2025}
    # summary sanity at the smoke count (absorbed from test_cli's former
    # test_demo so the suite pays for one demo invocation, not two)
    for meth in ("NI", "INT"):
        assert 0.0 <= out["summary"][meth]["coverage"] <= 1.0
