"""bench.py resilience: the driver must always get rc=0 and one JSON line.

Round-1 failure mode: TPU backend init hung → bench died rc=1 with no
number. The orchestrator now runs measurements in timeout-bounded worker
subprocesses and degrades TPU → TPU-retry → CPU → zero-value JSON. These
tests pin the orchestration; the worker measurement itself is smoke-tested
via the CPU path in ``test_cpu_worker_smoke`` (marked slow).
"""

import json
import os
import subprocess
import sys

import pytest

import bench


def _run_main(monkeypatch, capsys, responses, healthy=True, pallas=True):
    """Drive bench.main() with a scripted _run_worker; return parsed JSON.

    ``pallas=True`` opts in to the pallas sibling probe (r04 default is
    opt-out; most orchestration tests predate that and script a pallas
    response, so the harness opts in for them).
    """
    calls, timeouts = [], []
    if pallas:
        monkeypatch.setenv("DPCORR_BENCH_PALLAS", "1")
    else:
        monkeypatch.delenv("DPCORR_BENCH_PALLAS", raising=False)

    def fake_run_worker(mode, timeout_s, budget_s):
        calls.append(mode)
        timeouts.append(timeout_s)
        out, err = responses[len(calls) - 1]
        return out, err

    monkeypatch.setattr(bench, "_run_worker", fake_run_worker)
    monkeypatch.setattr(bench, "_health_probe", lambda: healthy)
    monkeypatch.setattr(bench, "_sweep_stranded_clients", lambda: [])
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    import signal

    prev_sigterm = signal.getsignal(signal.SIGTERM)
    try:
        with pytest.raises(SystemExit) as exc:
            bench.main()
    finally:
        # main() installs a process-global SIGTERM handler; the pytest
        # process must not keep it beyond the test
        signal.signal(signal.SIGTERM, prev_sigterm)
    assert exc.value.code == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(line), calls, timeouts


def _good():
    return {"metric": bench.METRIC, "value": 5000.0,
            "unit": "reps/sec/chip", "vs_baseline": 1.2,
            "detail": {"path": "xla",
                       "paths": {"xla": {"reps_per_sec": 5000.0,
                                         "mse": 0.006, "coverage": 0.95,
                                         "ci_length": 0.30}}}}


def _pallas(rps=9000.0, coverage=0.95, mse=0.006, ci_length=0.30):
    return {"metric": bench.METRIC, "value": rps, "unit": "reps/sec/chip",
            "vs_baseline": 0.0,
            "detail": {"paths": {"pallas": {"reps_per_sec": rps, "mse": mse,
                                            "coverage": coverage,
                                            "ci_length": ci_length}}}}


CPU = {"metric": bench.METRIC, "value": 1700.0, "unit": "reps/sec/chip",
       "vs_baseline": 0.41, "detail": {"path": "xla"}}


def test_tpu_first_try(monkeypatch, capsys):
    out, calls, _ = _run_main(monkeypatch, capsys, [
        (_good(), None),
        (_pallas(), None),
    ])
    assert calls == ["tpu", "tpu-pallas"]
    # faster sane pallas result takes the headline
    assert out["value"] == 9000.0
    assert out["detail"]["path"] == "pallas"
    assert "degraded" not in out["detail"]
    assert "attempts" not in out["detail"]


def test_pallas_probe_failure_keeps_xla_number(monkeypatch, capsys):
    """A hung/killed pallas probe must never cost the XLA measurement."""
    out, calls, _ = _run_main(monkeypatch, capsys, [
        (_good(), None),
        (None, "tpu-pallas worker: timeout after 465s"),
    ])
    assert calls == ["tpu", "tpu-pallas"]
    assert out["value"] == 5000.0
    assert out["detail"]["path"] == "xla"
    assert "timeout" in out["detail"]["pallas_skipped"]


def test_pallas_insane_stats_rejected(monkeypatch, capsys):
    out, calls, _ = _run_main(monkeypatch, capsys, [
        (_good(), None),
        (_pallas(coverage=0.70), None),  # NaN-ish kernel: wrong coverage
    ])
    assert out["value"] == 5000.0
    assert out["detail"]["path"] == "xla"
    assert "sanity" in out["detail"]["pallas_skipped"]


def test_run_worker_reaps_on_orchestrator_death(monkeypatch):
    """A dying orchestrator must take its detached worker down with it.

    r04 incident: an external SIGTERM (queue step `timeout`) killed the
    orchestrator mid-communicate and the stranded worker held the
    exclusive TPU client for 13+ minutes — a self-inflicted tunnel wedge.
    main() converts SIGTERM to SystemExit; this pins that _run_worker's
    finally then reaps the worker's whole process group.
    """
    spawned = []
    real_popen = subprocess.Popen

    class DyingPopen(real_popen):
        def communicate(self, timeout=None):
            raise SystemExit(143)  # what main()'s SIGTERM handler raises

    def fake_popen(cmd, **kw):
        p = DyingPopen(["sleep", "60"], start_new_session=True)
        spawned.append(p)
        return p

    monkeypatch.setattr(bench.subprocess, "Popen", fake_popen)
    with pytest.raises(SystemExit):
        bench._run_worker("tpu", timeout_s=5, budget_s=1)
    (p,) = spawned
    assert p.poll() == -9  # SIGKILLed by _reap, not still sleeping


def test_main_installs_sigterm_handler(monkeypatch, capsys):
    """Orchestrator path installs the handler; _run_main restores it."""
    import signal

    prev = signal.getsignal(signal.SIGTERM)
    seen = {}

    def fake_run_worker(mode, timeout_s, budget_s):
        seen["handler"] = signal.getsignal(signal.SIGTERM)
        return _good(), None

    monkeypatch.setattr(bench, "_run_worker", fake_run_worker)
    monkeypatch.setattr(bench, "_health_probe", lambda: True)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    monkeypatch.delenv("DPCORR_BENCH_PALLAS", raising=False)
    try:
        with pytest.raises(SystemExit):
            bench.main()
        # the handler must be live while workers run
        assert callable(seen["handler"]) and seen["handler"] != signal.SIG_DFL
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_sweep_stranded_clients():
    """The sweep kills an init-reparented bench worker and nothing else.

    Spawns a real double-forked `bench.py --worker cpu` (parent exits
    immediately, so the grandchild reparents to init — the exact stranded
    state an uncatchable orchestrator death leaves behind) and asserts
    the sweep takes it down while sparing this live-parented process.
    """
    import time

    bench_py = bench.__file__
    # double-fork via an intermediate python -c that exits at once
    inter = subprocess.Popen(
        [sys.executable, "-c",
         "import subprocess, sys;"
         f"subprocess.Popen([sys.executable, {bench_py!r},"
         " '--worker', 'cpu', '--budget', '30'],"
         " stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,"
         " start_new_session=True)"])
    inter.wait()
    deadline = time.time() + 10
    stray = None
    try:
        while time.time() < deadline and stray is None:
            for pid in (int(d) for d in os.listdir("/proc") if d.isdigit()):
                try:
                    with open(f"/proc/{pid}/cmdline", "rb") as fh:
                        cmd = fh.read().decode(errors="replace")
                    with open(f"/proc/{pid}/stat") as fh:
                        ppid = int(fh.read().rsplit(")", 1)[1].split()[1])
                except (OSError, ValueError, IndexError):
                    continue
                if "--worker" in cmd and "bench.py" in cmd and ppid == 1:
                    stray = pid
                    break
            time.sleep(0.2)
        assert stray is not None, \
            "double-forked worker never reparented to init"
        swept = bench._sweep_stranded_clients()
        assert stray in swept
        time.sleep(0.5)
        # dead, or at worst a not-yet-reaped zombie; init may reap between
        # the existence check and the read, so treat a vanished /proc
        # entry as success too
        try:
            with open(f"/proc/{stray}/stat") as fh:
                assert fh.read().rsplit(")", 1)[1].split()[0] == "Z"
        except OSError:
            pass  # already reaped — swept successfully
    finally:
        if stray is not None:  # never leak the real worker on test failure
            try:
                os.kill(stray, 9)
            except (ProcessLookupError, PermissionError):
                pass


def test_pallas_opt_in_default(monkeypatch, capsys):
    """r04 default: no pallas sibling probe unless DPCORR_BENCH_PALLAS=1.

    The driver's unattended run must not spend ~8 min of tunnel exposure
    on a path that has never held the headline (see bench.py docstring).
    """
    monkeypatch.delenv("DPCORR_BENCH_PALLAS", raising=False)
    out, calls, _ = _run_main(monkeypatch, capsys, [(_good(), None)],
                              pallas=False)
    assert calls == ["tpu"]
    assert out["value"] == 5000.0
    assert "opt in" in out["detail"]["pallas_skipped"]


def test_tpu_retry_succeeds(monkeypatch, capsys):
    out, calls, _ = _run_main(monkeypatch, capsys, [
        (None, "tpu worker: timeout after 480s"),
        (_good(), None),
        (None, "tpu-pallas worker: rc=1: boom"),
    ])
    assert calls == ["tpu", "tpu", "tpu-pallas"]
    assert out["value"] == 5000.0
    assert out["detail"]["attempts"] == ["tpu worker: timeout after 480s"]


def test_cpu_fallback_degraded(monkeypatch, capsys):
    out, calls, _ = _run_main(monkeypatch, capsys, [
        (None, "tpu worker: timeout after 480s"),
        (None, "tpu worker: timeout after 300s"),
        (dict(CPU), None),
    ])
    assert calls == ["tpu", "tpu", "cpu"]
    assert out["value"] == 1700.0
    assert out["detail"]["degraded"] == "tpu-init-failed"
    assert len(out["detail"]["attempts"]) == 2


def test_health_probe_gates_tpu_attempts(monkeypatch, capsys):
    """A healthy probe earns the tpu worker its long leash; a FAILED
    probe now skips both tpu attempts outright and degrades straight to
    CPU (``degraded: "tpu-probe-failed"``, distinct from the
    attempted-and-died ``"tpu-init-failed"``) — the probe is the same
    one-matmul program a worker would run first, so attempting anyway
    only bought the old ladder's 420/200 s of guaranteed timeout. The
    probe verdict, the skip note and the relay snapshot (taken at probe
    time, not artifact time — a mid-run redial must not misattribute)
    are all recorded in the artifact."""
    out, calls, t_ok = _run_main(monkeypatch, capsys,
                                 [(_good(), None), (_pallas(), None)],
                                 healthy=True)
    assert out["detail"]["tunnel_health_probe"] == "ok"
    assert calls[0] == "tpu" and t_ok[0] >= 900
    assert "degraded" not in out["detail"]
    import dpcorr.utils.doctor as doctor_mod

    def relay(alive):
        monkeypatch.setattr(doctor_mod, "check_relay",
                            lambda ports=None, timeout=None: {
                                "alive": alive, "open_ports": [],
                                "checked": []})

    relay(True)
    out, calls, _ = _run_main(monkeypatch, capsys, [(dict(CPU), None)],
                              healthy=False)
    assert calls == ["cpu"]  # no tpu attempt at all
    assert out["detail"]["tunnel_health_probe"] == "failed"
    assert out["detail"]["relay_endpoint"] == "up"
    assert out["detail"]["degraded"] == "tpu-probe-failed"
    assert out["detail"]["attempts"] == [
        "tpu worker: skipped (health probe failed, relay up)"]
    relay(False)
    out, calls, _ = _run_main(monkeypatch, capsys, [(dict(CPU), None)],
                              healthy=False)
    assert calls == ["cpu"]
    assert out["detail"]["relay_endpoint"] == "dead"
    assert out["detail"]["degraded"] == "tpu-probe-failed"


def test_total_failure_still_valid_json(monkeypatch, capsys):
    out, calls, _ = _run_main(monkeypatch, capsys, [
        (None, "tpu worker: timeout after 480s"),
        (None, "tpu worker: rc=1: boom"),
        (None, "cpu worker: rc=1: boom"),
    ])
    assert calls == ["tpu", "tpu", "cpu"]
    assert out["value"] == 0.0 and out["vs_baseline"] == 0.0
    assert out["detail"]["degraded"] == "all-paths-failed"
    assert len(out["detail"]["attempts"]) == 3
    assert out["metric"] == bench.METRIC and out["unit"] == "reps/sec/chip"


@pytest.mark.slow
def test_health_probe_payload_rejects_cpu_platform():
    """The probe payload must exit nonzero on a CPU backend (a silent CPU
    fallback must never earn the long TPU leash): run the exact PROBE_CODE
    with the platform forced to cpu and check the platform assert fires."""
    p = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu'); "
         + bench.PROBE_CODE],
        capture_output=True, text=True, timeout=120)
    assert p.returncode != 0
    assert "AssertionError" in p.stderr


@pytest.mark.slow
def test_cpu_worker_smoke():
    """End-to-end CPU worker subprocess: valid JSON, sane statistics."""
    p = subprocess.run(
        [sys.executable, bench.os.path.abspath(bench.__file__),
         "--worker", "cpu", "--budget", "2"],
        capture_output=True, text=True, timeout=180)
    assert p.returncode == 0, p.stderr[-500:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.METRIC
    assert out["value"] > 0
    xla = out["detail"]["paths"]["xla"]
    assert 0.90 <= xla["coverage"] <= 0.99
