"""DGP tests: known-truth moments/correlations (the reference's oracle —
SURVEY.md §4 item 3)."""

import jax
import numpy as np
import pytest

from dpcorr.models.dgp import (
    gen_bernoulli,
    gen_bounded_factor,
    gen_gaussian,
    gen_mix_gaussian,
)
from dpcorr.utils import rng

KEY = rng.master_key(11)
N = 60_000


def _corr(xy):
    xy = np.asarray(xy)
    return np.corrcoef(xy[:, 0], xy[:, 1])[0, 1]


@pytest.mark.parametrize("rho", [-0.95, -0.3, 0.0, 0.5, 0.9])
def test_gaussian_corr(rho):
    xy = gen_gaussian(KEY, N, rho)
    assert abs(_corr(xy) - rho) < 0.02
    assert abs(np.asarray(xy).mean()) < 0.02


def test_gaussian_mu_sigma():
    xy = np.asarray(gen_gaussian(KEY, N, 0.4, mu=(2.0, 2.0), sigma=(2.0, 0.1)))
    np.testing.assert_allclose(xy.mean(axis=0), [2.0, 2.0], atol=0.05)
    np.testing.assert_allclose(xy.std(axis=0), [2.0, 0.1], rtol=0.03)
    assert abs(_corr(xy) - 0.4) < 0.02


@pytest.mark.parametrize("rho", [0.0, 0.3, 0.8])
def test_bernoulli(rho):
    xy = np.asarray(gen_bernoulli(KEY, N, rho))
    assert set(np.unique(xy)) <= {0.0, 1.0}
    np.testing.assert_allclose(xy.mean(axis=0), [0.5, 0.5], atol=0.02)
    assert abs(_corr(xy) - rho) < 0.02


@pytest.mark.parametrize("rho", [0.0, 0.5, 0.9])
def test_bounded_factor(rho):
    xy = np.asarray(gen_bounded_factor(KEY, N, rho))
    np.testing.assert_allclose(xy.mean(axis=0), [0.0, 0.0], atol=0.03)
    np.testing.assert_allclose(xy.var(axis=0), [1.0, 1.0], rtol=0.03)
    assert abs(_corr(xy) - rho) < 0.02
    bound = np.sqrt(3 * rho) + np.sqrt(3 * (1 - rho))
    assert np.abs(xy).max() <= bound + 1e-5


def test_mix_gaussian_clipped():
    xy = np.asarray(gen_mix_gaussian(KEY, N, 0.5))
    assert np.abs(xy).max() <= 1.0  # hard clip, ver-cor-subG.R:135
    # both components present (pi=0.5): clip means many values pinned at ±1
    assert (xy == 1.0).mean() > 0.05


def test_vmap_over_keys():
    keys = rng.rep_keys(KEY, 8)
    batch = jax.vmap(lambda k: gen_gaussian(k, 100, 0.5))(keys)
    assert batch.shape == (8, 100, 2)
    flat = np.asarray(batch).reshape(8, -1)
    assert len(np.unique(flat[:, 0])) == 8  # distinct draws per rep
