"""Environment invariants: the virtual 8-device CPU mesh must be live so
sharding paths are actually exercised (SURVEY.md §4)."""

import jax
import pytest


def test_virtual_mesh_is_live(devices):
    assert len(devices) == 8
    assert all(d.platform == "cpu" for d in devices)
    assert jax.device_count() == 8


def test_graft_entry_compiles(devices):
    """The driver compile-checks entry() single-chip; pin it here too."""
    import __graft_entry__ as g

    step, args = g.entry()
    out = jax.jit(step)(*args)
    assert out.shape[0] == 12 and out.shape[1] == 64


@pytest.mark.slow
def test_dryrun_multichip_certifies_all_families(devices):
    """dryrun_multichip(8) must assert bit-identity of the sharded detail
    vs the local run for all four families (sign, subG, streaming, fused
    streaming pair) — VERDICT r3 #4. Running it here keeps the driver's
    MULTICHIP artifact honest between rounds."""
    import __graft_entry__ as g

    g.dryrun_multichip(8)
