"""Environment invariants: the virtual 8-device CPU mesh must be live so
sharding paths are actually exercised (SURVEY.md §4)."""

import jax


def test_virtual_mesh_is_live(devices):
    assert len(devices) == 8
    assert all(d.platform == "cpu" for d in devices)
    assert jax.device_count() == 8
