"""Figure-layer smoke tests: every family renders and writes a PDF from a
small real grid + sweep output."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from dpcorr import report
from dpcorr.grid import GridConfig, run_grid


@pytest.fixture(scope="module")
def small_grid():
    gcfg = GridConfig(n_grid=(400, 800), rho_grid=(0.0, 0.5),
                      eps_pairs=((1.0, 1.0), (1.5, 0.5)), b=24)
    return run_grid(gcfg)


def test_synthetic_figures(small_grid, tmp_path):
    paths = report.render_all(grid_detail=small_grid.detail_all,
                              grid_summ=small_grid.summ_all,
                              out_dir=tmp_path, fig1_n=800,
                              fig1_eps=(1.5, 0.5), fig23_rho=0.5)
    assert len(paths) == 3
    for p in paths:
        assert p.exists() and p.stat().st_size > 2_000


def test_subg_figures(small_grid, tmp_path):
    """The distinct v2 family (ver-cor-subG.R:338-436): 4 files with the
    reference's names."""
    paths = report.render_all_subg(grid_detail=small_grid.detail_all,
                                   grid_summ=small_grid.summ_all,
                                   out_dir=tmp_path, fig1_n=800,
                                   fig1_eps=(1.5, 0.5), rho=0.5)
    assert [p.name for p in paths] == [
        "subG_fig1_mean_band.pdf", "subG_fig2a_width.pdf",
        "subG_fig2b_cov.pdf", "subG_fig3_mse.pdf"]
    for p in paths:
        assert p.exists() and p.stat().st_size > 2_000


def test_hrs_point_is_ci_midpoint(tmp_path):
    """The HRS panel point must be (ci_low_mean+ci_high_mean)/2
    (real-data-sims.R:459-461), not the mean ρ̂ — build a summary where the
    two differ wildly and check the plotted point."""
    summ = pd.DataFrame([
        {"method": m, "eps_corr": e, "rho_hat_mean": 10.0,
         "ci_low_mean": -0.4 - e, "ci_high_mean": 0.0 + e,
         "ci_low_q10": -0.5, "ci_high_q90": 0.1}
        for m in ("NI", "INT") for e in (0.25, 0.5)])
    fig = report.fig_hrs_sweep(summ, rho_np=-0.193)
    for ax in fig.axes:
        for line in ax.lines:
            ys = np.asarray(line.get_ydata(), dtype=float)
            # nothing plotted at the decoy mean ρ̂
            assert not np.any(np.isclose(ys, 10.0))
    # the NI panel's point series is the midpoint of the CI means
    pts = [line for line in fig.axes[0].lines
           if len(line.get_xdata()) == 2 and line.get_marker() == "o"]
    mids = (summ[summ.method == "NI"].sort_values("eps_corr")
            [["ci_low_mean", "ci_high_mean"]].mean(axis=1).to_numpy())
    assert any(np.allclose(np.asarray(line.get_ydata(), float), mids)
               for line in pts)


def test_hrs_figure(tmp_path):
    # synthetic sweep summary with the exact schema hrs.eps_sweep emits
    eps = np.round(np.arange(0.25, 0.66, 0.1), 10)
    rows = []
    for meth in ("NI", "INT"):
        for e in eps:
            w = 0.8 / e
            rows.append({"method": meth, "eps_corr": e,
                         "rho_hat_mean": -0.19, "ci_low_mean": -0.19 - w,
                         "ci_high_mean": -0.19 + w, "ci_low_q10": -0.19 - w,
                         "ci_high_q90": -0.19 + w})
    summ = pd.DataFrame(rows)
    p = tmp_path / "hrs.pdf"
    report.fig_hrs_sweep(summ, rho_np=-0.193, out=p)
    assert p.exists() and p.stat().st_size > 2_000


def test_serve_stats_frame_nested_ledger_and_latency():
    """serve_stats_frame flattens the full nested snapshot — multi-party
    ledger groups, reservoir percentiles AND the obs latency-histogram
    buckets — into dotted metric keys (ISSUE 2 satellite)."""
    from dpcorr.report import serve_stats_frame
    from dpcorr.serve import ServeStats

    st = ServeStats()
    st.admitted()
    st.flushed(3, batched=True)
    for v in (0.002, 0.02, 0.2):
        st.observe_latency(v)
    snap = st.snapshot(ledger_snapshot={
        "budget_default": 10.0,
        "parties": {
            "alice": {"spent": 1.5, "budget": 10.0, "remaining": 8.5},
            "bob": {"spent": 0.25, "budget": 2.0, "remaining": 1.75},
        }})
    df = serve_stats_frame(snap)
    metrics = dict(zip(df["metric"], df["value"]))
    assert metrics["ledger.parties.alice.spent"] == 1.5
    assert metrics["ledger.parties.bob.remaining"] == 1.75
    assert metrics["ledger.budget_default"] == 10.0
    assert metrics["latency_s.p50"] == 0.02
    assert metrics["latency_s.p99"] == 0.2
    # the additive histogram view flattens too (cumulative buckets)
    assert metrics["latency_histogram.count"] == 3
    assert metrics["latency_histogram.buckets.0.005"] == 1
    assert metrics["latency_histogram.buckets.0.25"] == 3
    # every leaf is scalar — nothing left as a dict cell
    assert not any(isinstance(v, dict) for v in df["value"])
