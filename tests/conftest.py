"""Test configuration: run on CPU with 8 virtual devices.

Multi-device sharding paths (SURVEY.md §4) are exercised without TPU
hardware. Note: this environment preloads JAX at interpreter startup (axon
site hook), so setting JAX_PLATFORMS via os.environ here is too late — the
config values are already captured. ``jax.config.update`` works after
import, as long as no backend has been initialized yet.
"""

import os

# Still set env for any subprocesses tests may spawn.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; the XLA_FLAGS
    # fallback above is read at backend init and yields the 8 virtual
    # devices on those versions (verified on 0.4.37)
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
