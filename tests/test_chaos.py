"""Crash-safety tests (ISSUE 7): the deterministic crash-point chaos
harness, durable protocol resume, ledger kill-recovery, and the
transport-level robustness satellites.

The in-process matrix uses raise-mode chaos plans scoped to the victim
thread: the ``SimulatedCrash`` unwinds one party exactly like a process
death (its journal, ledger file and transcript survive; its in-memory
state does not), then a fresh Party on the same journal resumes the
live session. The subprocess tests use exit-mode plans (``os._exit``)
for genuine process kills; the full TCP step-kill sweep is the slow
test and the ``dpcorr chaos`` CI job.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dpcorr import chaos
from dpcorr.chaos import ChaosPlan, SimulatedCrash
from dpcorr.obs.audit import AuditTrail, read_events, replay
from dpcorr.protocol import (
    FaultInjector,
    InProcTransport,
    JournalError,
    ProtocolSpec,
    ReliableChannel,
    SessionJournal,
    TransportError,
    ledger_balance,
    run_inproc,
    scan_transcript,
)
from dpcorr.protocol.messages import Transcript
from dpcorr.protocol.party import Party
from dpcorr.protocol.transport import tcp_accept, tcp_connect, tcp_listen
from dpcorr.obs.budget_replay import read_user_balances
from dpcorr.serve.budget_dir import BudgetDirectory, CompositeLedger
from dpcorr.serve.ledger import LedgerCorruptError, PrivacyLedger

FAMILIES = ("ni_sign", "int_sign", "ni_subg", "int_subg")


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    """Every test starts and ends with no chaos plan armed."""
    chaos.clear()
    yield
    chaos.clear()


def _columns(n=512, rho=0.6, seed=99):
    r = np.random.default_rng(seed)
    xy = r.multivariate_normal([0.0, 0.0], [[1.0, rho], [rho, 1.0]],
                               size=n)
    return (np.asarray(xy[:, 0], np.float32),
            np.asarray(xy[:, 1], np.float32))


def _bits(res):
    return (res.rho_hat, res.ci_low, res.ci_high)


# ------------------------------------------------------- chaos plans ----
def test_plan_from_spec_fields():
    p = chaos.plan_from_spec("point=gate.post_charge,hit=3,mode=raise")
    assert (p.point, p.hit, p.mode) == ("gate.post_charge", 3, "raise")
    assert chaos.plan_from_spec("point=ledger.pre_persist").hit == 1


def test_plan_from_spec_rejects_unknown_point():
    with pytest.raises(ValueError):
        chaos.plan_from_spec("point=not.a.point")


def test_plan_from_seed_is_deterministic():
    a, b = chaos.plan_from_seed(123), chaos.plan_from_seed(123)
    assert a.to_dict() == b.to_dict()
    assert a.point in chaos.MATRIX_POINTS
    assert a.role in ("x", "y")
    assert a.seed == 123
    # the recorded spec reconstructs the same concrete plan (transcript
    # replay); the seed itself is provenance, not part of the spec form
    again = chaos.plan_from_spec(a.to_spec())
    redo = {k: v for k, v in again.to_dict().items() if k != "seed"}
    orig = {k: v for k, v in a.to_dict().items() if k != "seed"}
    assert redo == orig
    # the seed FORM of the spec re-derives the identical plan AND keeps
    # the seed — the chaos driver hands this form to a seed-derived
    # victim so its transcript header records the provenance
    seeded = chaos.plan_from_spec("seed=123")
    assert seeded.to_dict() == a.to_dict()
    assert seeded.to_dict()["seed"] == 123


def test_plan_from_env(monkeypatch):
    monkeypatch.setenv("DPCORR_CHAOS", "point=gate.post_send,hit=2")
    p = chaos.plan_from_env()
    assert (p.point, p.hit) == ("gate.post_send", 2)
    monkeypatch.delenv("DPCORR_CHAOS")
    assert chaos.plan_from_env() is None


def test_point_counts_hits_and_trips():
    chaos.install(ChaosPlan(point="gate.post_charge", hit=2,
                            mode="raise"))
    chaos.point("gate.post_charge")        # hit 1: survives
    chaos.point("gate.post_send")          # different point: ignored
    with pytest.raises(SimulatedCrash):
        chaos.point("gate.post_charge")    # hit 2: trips
    chaos.clear()
    chaos.point("gate.post_charge")        # no plan: fast no-op


def test_point_scoped_to_thread_name():
    chaos.install(ChaosPlan(point="gate.post_charge", hit=1,
                            mode="raise", thread_name="victim-thread"))
    chaos.point("gate.post_charge")  # wrong thread: survives
    tripped = {}

    def victim():
        try:
            chaos.point("gate.post_charge")
        except SimulatedCrash:
            tripped["yes"] = True

    t = threading.Thread(target=victim, name="victim-thread")
    t.start()
    t.join()
    assert tripped.get("yes")


# --------------------------------------------------- session journal ----
def test_journal_roundtrip_survives_reload(tmp_path):
    path = str(tmp_path / "j.json")
    j = SessionJournal(path)
    assert j.begin("s1", "x", "hash1") is False  # fresh
    token = j.ensure_token()
    j.prepare_outbound(0, {"kind": "msg", "seq": 1}, charges={"a": 1.0},
                       charge_id="s1:x:out0")
    j.prepare_outbound(0, {"kind": "msg", "seq": 1})  # idempotent re-prepare
    j.mark_acked(0)
    j.record_inbound(1, {"kind": "msg", "seq": 1})
    j.record_inbound(1, {"ignored": "duplicate"})

    j2 = SessionJournal(path)
    assert j2.begin("s1", "x", "hash1") is True  # resumed
    assert j2.resume_token == token
    assert j2.outbound_entry(0)["acked"] is True
    assert j2.outbound_entry(0)["charge_id"] == "s1:x:out0"
    assert j2.delivered_seqs() == {1}
    assert len(j2.inbound) == 1


def test_journal_refuses_mixed_sessions(tmp_path):
    path = str(tmp_path / "j.json")
    SessionJournal(path).begin("s1", "x", "hash1")
    with pytest.raises(JournalError):
        SessionJournal(path).begin("s2", "x", "hash1")
    with pytest.raises(JournalError):
        SessionJournal(path).begin("s1", "x", "other-hash")


def test_journal_corrupt_quarantined(tmp_path):
    path = str(tmp_path / "j.json")
    with open(path, "w") as f:
        f.write("{truncated")
    with pytest.raises(JournalError):
        SessionJournal(path)
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)
    # the quarantine unblocks a fresh session at the same path
    assert SessionJournal(path).begin("s1", "y", "h") is False


# ------------------------------------------------- ledger robustness ----
def test_ledger_corrupt_snapshot_quarantined(tmp_path):
    path = str(tmp_path / "led.json")
    with open(path, "w") as f:
        f.write("{not json")
    (tmp_path / "led.json.tmp.123").write_text("stale half-write")
    with pytest.raises(LedgerCorruptError) as ei:
        PrivacyLedger(10.0, path=path)
    msg = str(ei.value)
    assert "corrupt" in msg and "obs budget" in msg  # actionable
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)
    assert not os.path.exists(str(tmp_path / "led.json.tmp.123"))
    led = PrivacyLedger(10.0, path=path)  # path reusable after quarantine
    led.charge({"a": 1.0})
    assert led.spent("a") == 1.0


def test_ledger_charge_id_dedup_and_refund_forget(tmp_path):
    trail = AuditTrail(str(tmp_path / "audit.jsonl"))
    led = PrivacyLedger(10.0, path=str(tmp_path / "led.json"), audit=trail)
    led.charge({"a": 2.0}, charge_id="c1")
    led.charge({"a": 2.0}, charge_id="c1")  # resumed re-run: no-op
    assert led.spent("a") == 2.0
    # reload sees the persisted id — dedup survives the crash boundary
    led2 = PrivacyLedger(10.0, path=str(tmp_path / "led.json"),
                         audit=trail)
    led2.charge({"a": 2.0}, charge_id="c1")
    assert led2.spent("a") == 2.0
    led2.refund({"a": 2.0}, charge_id="c1")  # forgets the id
    led2.charge({"a": 2.0}, charge_id="c1")  # genuinely new charge
    assert led2.spent("a") == 2.0
    assert replay(trail.events()) == {"a": pytest.approx(2.0)}


_KILL_SCRIPT = """\
import sys
from dpcorr import chaos
from dpcorr.obs.audit import AuditTrail
from dpcorr.serve.ledger import PrivacyLedger

plan = chaos.plan_from_env()
if plan is not None:
    chaos.install(plan)
led = PrivacyLedger(10.0, path=sys.argv[1], audit=AuditTrail(sys.argv[2]))
led.charge({"a": 1.0}, charge_id="warm")
led.charge({"a": 2.5}, charge_id="victim")
print("SURVIVED")
"""


@pytest.mark.parametrize("point,disk_spent", [
    # killed between spend and persist: disk still shows the pre-crash
    # state; killed just after persist: disk shows the post-charge
    # state (its audit line is the one that died) — never in between
    ("ledger.pre_persist", 1.0),
    ("ledger.post_persist", 3.5),
])
def test_ledger_kill_mid_charge_recovers(tmp_path, point, disk_spent):
    ledger = str(tmp_path / "led.json")
    audit = str(tmp_path / "audit.jsonl")
    env = dict(os.environ,
               DPCORR_CHAOS=f"point={point},hit=2,mode=exit")
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT, ledger, audit],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == chaos.EXIT_CODE, proc.stderr
    assert "SURVIVED" not in proc.stdout
    with open(ledger) as fh:
        state = json.load(fh)
    assert state["spent"] == {"a": pytest.approx(disk_spent)}
    # recovery: reload and re-issue the interrupted charge under its
    # charge_id — it lands exactly once regardless of where the kill hit
    led = PrivacyLedger(10.0, path=ledger, audit=AuditTrail(audit))
    led.charge({"a": 2.5}, charge_id="victim")
    assert led.spent("a") == pytest.approx(3.5)
    # the audit replay agrees, even across the persisted-but-unlogged
    # window (the re-charge's dedup event stands in for the lost line)
    assert replay(read_events(audit)) == {"a": pytest.approx(3.5)}


# ------------------------------------------------ transport satellites ----
def _free_port() -> int:
    srv, port = tcp_listen("127.0.0.1", 0)
    srv.close()
    return port


def test_tcp_connect_retries_until_listener_appears():
    port = _free_port()
    got = {}

    def listen_later():
        time.sleep(0.4)
        srv, _ = tcp_listen("127.0.0.1", port)
        got["link"] = tcp_accept(srv, timeout_s=10.0)
        srv.close()

    t = threading.Thread(target=listen_later)
    t.start()
    link = tcp_connect("127.0.0.1", port, timeout_s=15.0)
    t.join()
    link.send_bytes(b"hello")
    assert got["link"].recv_bytes(5.0) == b"hello"
    link.close()
    got["link"].close()


def test_tcp_connect_refused_error_names_address():
    port = _free_port()
    with pytest.raises(TransportError) as ei:
        tcp_connect("127.0.0.1", port, timeout_s=0.3)
    assert str(port) in str(ei.value)


def test_tcp_link_eof_error_names_peer():
    srv, port = tcp_listen("127.0.0.1", 0)
    links = {}
    t = threading.Thread(
        target=lambda: links.setdefault("y", tcp_accept(srv, 10.0)))
    t.start()
    x = tcp_connect("127.0.0.1", port, timeout_s=10.0)
    t.join()
    srv.close()
    links["y"].close()
    with pytest.raises(TransportError) as ei:
        x.recv_bytes(5.0)
    msg = str(ei.value)
    assert "closed connection" in msg and str(port) in msg
    x.close()


def test_tcp_link_mid_frame_eof_is_flagged():
    srv, port = tcp_listen("127.0.0.1", 0)
    links = {}
    t = threading.Thread(
        target=lambda: links.setdefault("y", tcp_accept(srv, 10.0)))
    t.start()
    x = tcp_connect("127.0.0.1", port, timeout_s=10.0)
    t.join()
    srv.close()
    # half a length prefix, then death: the reader must call out a
    # truncated frame, not just "closed"
    links["y"]._sock.sendall(b"\x00\x00")
    links["y"].close()
    with pytest.raises(TransportError) as ei:
        x.recv_bytes(5.0)
    assert "mid-frame" in str(ei.value)
    x.close()


def test_reliable_channel_drain_under_duplicate_storm():
    """Every frame (messages AND acks) duplicated at p=1.0: delivery
    stays exactly-once and both drains terminate cleanly."""
    pair = InProcTransport()
    mk = lambda link, seed: ReliableChannel(  # noqa: E731
        link, timeout_s=0.05, max_retries=30, backoff_base_s=0.01,
        backoff_max_s=0.05, fault=FaultInjector(duplicate=1.0, seed=seed))
    a, b = mk(pair.a, 1), mk(pair.b, 2)
    got = []

    def receiver():
        for _ in range(8):
            got.append(b.recv(timeout_s=30.0)["body"]["i"])
        b.drain()

    t = threading.Thread(target=receiver)
    t.start()
    for i in range(8):
        a.send({"i": i})
    a.drain()
    t.join(timeout=60)
    assert not t.is_alive()
    assert got == list(range(8))


# ------------------------------------------- in-process crash-resume ----
def _crash_resume(family, victim, point, tmp_path, n=512):
    """Kill ``victim`` at ``point`` mid-session, resume it from its
    journal against the still-live survivor, and assert the recovered
    session is indistinguishable from an uninterrupted one."""
    x, y = _columns(n)
    spec = ProtocolSpec(family=family, n=n, eps1=1.0, eps2=0.5,
                        session=f"cr-{family}-{victim}-{point}")
    ref = run_inproc(spec, x, y)  # the uninterrupted oracle

    pair = InProcTransport()
    links = {"x": pair.a, "y": pair.b}
    cols = {"x": x, "y": y}
    paths = {
        r: {"ledger": str(tmp_path / f"ledger-{r}.json"),
            "journal": str(tmp_path / f"journal-{r}.json"),
            "audit": str(tmp_path / f"audit-{r}.jsonl"),
            "transcript": str(tmp_path / f"transcript-{r}.jsonl")}
        for r in ("x", "y")
    }

    def mk_party(role):
        chan = ReliableChannel(links[role], timeout_s=0.1,
                               max_retries=400, backoff_base_s=0.02,
                               backoff_max_s=0.1)
        audit = AuditTrail(paths[role]["audit"])
        inner = PrivacyLedger(100.0, path=paths[role]["ledger"],
                              audit=audit)
        # per-user admission rides every gate charge, with the most
        # hostile directory knobs — evict after every touch, compact
        # after every mutation — so each release crosses ALL budget
        # crash windows (the budget.* MATRIX points fire here)
        directory = BudgetDirectory(
            str(tmp_path / f"budget-{role}"), shards=2,
            user_budget=100.0, max_resident=0, compact_every=1,
            audit=audit)
        ledger = CompositeLedger(inner, directory,
                                 user=f"user-{role}")
        return Party(role, cols[role], spec, chan, ledger,
                     transcript=Transcript(paths[role]["transcript"]),
                     recv_timeout_s=120.0,
                     journal=SessionJournal(paths[role]["journal"]))

    results, errors = {}, {}

    def drive(party):
        try:
            results[party.role] = party.run()
        except BaseException as e:  # SimulatedCrash is a BaseException
            errors[party.role] = e

    survivor = "y" if victim == "x" else "x"
    chaos.install(ChaosPlan(point=point, hit=1, mode="raise",
                            thread_name=f"party-{victim}"))
    t_survivor = threading.Thread(target=drive,
                                  args=(mk_party(survivor),),
                                  name=f"party-{survivor}")
    t_victim = threading.Thread(target=drive, args=(mk_party(victim),),
                                name=f"party-{victim}")
    try:
        t_survivor.start()
        t_victim.start()
        t_victim.join(timeout=120)
        assert not t_victim.is_alive(), f"victim never crashed at {point}"
        crash = errors.pop(victim, None)
        assert isinstance(crash, SimulatedCrash), \
            f"victim died of {crash!r}, expected SimulatedCrash"
    finally:
        chaos.clear()

    # the restart: a fresh Party (fresh channel state, ledger reloaded
    # from disk) on the same journal, same link endpoint
    t_restart = threading.Thread(target=drive, args=(mk_party(victim),),
                                 name=f"party-{victim}")
    t_restart.start()
    t_survivor.join(timeout=120)
    t_restart.join(timeout=120)
    assert not t_survivor.is_alive() and not t_restart.is_alive()
    assert not errors, errors

    for role in ("x", "y"):
        assert _bits(results[role]) == _bits(ref[role]), \
            f"role {role} diverged from the uninterrupted run"
        rep = scan_transcript(paths[role]["transcript"])
        assert rep["ok"], rep["violations"]
        bal = ledger_balance(paths[role]["transcript"],
                             read_events(paths[role]["audit"]))
        assert bal["ok"], bal
        with open(paths[role]["ledger"]) as fh:
            spent = json.load(fh)["spent"]
        for party_name, eps in spec.charges_for(role).items():
            assert spent[party_name] == pytest.approx(eps), \
                f"role {role} eps not spent exactly once"
        # the user directory recovered to the exact per-user balance:
        # one user leg per gate charge, never double-applied across
        # the crash (jax-free recovery arithmetic, same as the driver)
        want_user = sum(spec.charges_for(role).values())
        bal = read_user_balances(str(tmp_path / f"budget-{role}"))
        got_user = bal.get(f"user-{role}", {}).get("l", 0.0)
        assert got_user == pytest.approx(want_user), \
            f"role {role} user-leg balance {got_user} != {want_user}"


@pytest.mark.parametrize("victim", ["x", "y"])
@pytest.mark.parametrize("point", [p for p in chaos.MATRIX_POINTS
                                   if not p.startswith("federation.")])
def test_crash_resume_matrix_inproc(point, victim, tmp_path):
    # federation.* points never fire in a two-party session — their
    # matrix crash-resume coverage lives in tests/test_federation.py
    _crash_resume("ni_sign", victim, point, tmp_path)


@pytest.mark.parametrize("family", ["int_sign", "ni_subg", "int_subg"])
def test_crash_resume_other_families_inproc(family, tmp_path):
    _crash_resume(family, "y", "gate.post_send", tmp_path)


# --------------------------------------------------- subprocess / CLI ----
def test_chaos_cli_single_case_tcp(tmp_path):
    """One full step-kill case through the real CLI: two TCP party
    processes, exit-mode kill, restart, bit-identity + balance checks
    all enforced by the driver itself."""
    proc = subprocess.run(
        [sys.executable, "-m", "dpcorr", "chaos",
         "--families", "ni_sign", "--roles", "y",
         "--points", "gate.post_charge", "--n", "256",
         "--workdir", str(tmp_path / "chaos"),
         "--case-timeout", "120"],
        capture_output=True, text=True, timeout=420,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] and all(c["ok"] for c in report["cases"])
    case_dir = report["cases"][0]["dir"]
    # reproducibility-from-the-artifact: the victim's transcript header
    # records the armed plan
    from dpcorr.protocol import read_transcript_meta
    meta = read_transcript_meta(
        os.path.join(case_dir, "transcript.y.jsonl"))
    assert meta["chaos"]["point"] == "gate.post_charge"
    assert meta["chaos"]["mode"] == "exit"


@pytest.mark.slow
@pytest.mark.parametrize("family", FAMILIES)
def test_chaos_cli_full_matrix_tcp(tmp_path, family):
    """ISSUE 7 acceptance: every matrix crash point × both roles over
    real TCP, per estimator family — bit-identical results, balanced
    ledgers, clean transcripts."""
    proc = subprocess.run(
        [sys.executable, "-m", "dpcorr", "chaos",
         "--families", family, "--n", "256",
         "--workdir", str(tmp_path / "chaos"),
         "--case-timeout", "180"],
        capture_output=True, text=True, timeout=3600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"]
    assert len(report["cases"]) == 2 * len(chaos.MATRIX_POINTS)


# -- serve coalescer crash windows ------------------------------------
# These sweep coalescer.pre_flush / coalescer.post_flush (the two
# KNOWN_POINTS the two-party matrix never traverses): raise-mode plans
# scoped to the serve flush thread, asserting the window's ordering
# contract on each side of the kernel launch.

def _coalescer_req(seed=7, n=96):
    rs = np.random.RandomState(seed)
    from dpcorr.serve.request import EstimateRequest
    return EstimateRequest("ni_sign", rs.randn(n).astype(np.float32),
                           rs.randn(n).astype(np.float32),
                           1.0, 0.5, seed=seed)


def _run_with_flush_crash(point):
    """Arm ``point`` on the serve flush thread, run one estimate, and
    return (estimate outcome or exception, captured thread crash)."""
    from dpcorr.serve.server import DpcorrServer

    crashes = []
    prev_hook = threading.excepthook
    threading.excepthook = lambda args: crashes.append(args)
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off")
    try:
        chaos.install(ChaosPlan(point=point, hit=1, mode="raise",
                                thread_name="dpcorr-serve-flush"))
        try:
            outcome = srv.estimate(_coalescer_req(), timeout=5.0)
        except Exception as e:
            outcome = e
        srv.coalescer._thread.join(timeout=5.0)
        assert not srv.coalescer._thread.is_alive(), \
            "flush thread survived a raise-mode chaos kill"
        return outcome, crashes
    finally:
        threading.excepthook = prev_hook
        chaos.clear()
        srv.close()


def test_chaos_coalescer_pre_flush_kills_before_launch():
    """coalescer.pre_flush fires before the group is claimed: the
    pending future is never resolved (the client times out and its
    cancel wins), and the flush thread dies of SimulatedCrash."""
    from concurrent.futures import TimeoutError as FuturesTimeout

    outcome, crashes = _run_with_flush_crash("coalescer.pre_flush")
    assert isinstance(outcome, FuturesTimeout), \
        f"expected the estimate to time out, got {outcome!r}"
    assert crashes and crashes[0].exc_type is SimulatedCrash


def test_chaos_coalescer_post_flush_crashes_after_responses_land():
    """coalescer.post_flush fires after futures resolve: the client
    still gets its answer — the crash window sits strictly after
    response delivery — and only then does the flush thread die."""
    outcome, crashes = _run_with_flush_crash("coalescer.post_flush")
    assert not isinstance(outcome, Exception), f"estimate failed: {outcome!r}"
    assert outcome.rho_hat == outcome.rho_hat  # a real response (not NaN)
    assert crashes and crashes[0].exc_type is SimulatedCrash
