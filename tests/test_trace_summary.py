"""benchmarks/trace_summary.py percentile math + obs.transfer diffs.

The span-JSONL half of trace_summary reuses the serving stack's
nearest-rank percentiles so a p99 printed here means the same thing as
the ``/stats`` p99 — these tests pin that arithmetic on known sets,
the empty and single-span degenerate cases, and the transfer-counter
delta logic under genuinely concurrent pipelines (the exact scenario
the per-registry bundle exists for).
"""

import json
import threading

import pytest

from benchmarks.trace_summary import summarize_spans
from dpcorr.obs.metrics import Registry
from dpcorr.obs.transfer import TransferCounters, diff
from dpcorr.serve.stats import percentiles


def _span(name, dur_s, i=0):
    return {"name": name, "trace_id": f"t{i:04x}", "span_id": f"s{i:04x}",
            "parent_id": None, "ts": float(i), "dur_s": float(dur_s),
            "thread": "main", "attrs": {}}


# ----------------------------------------------------------- percentiles ----
def test_percentiles_known_set_1_to_100():
    vals = [float(v) for v in range(1, 101)]
    p = percentiles(vals)
    # nearest-rank on n=100: p50 is the 50th value, p99 the 99th
    assert p == {"p50": 50.0, "p99": 99.0}


def test_percentiles_order_invariant_and_small_sets():
    assert percentiles([3.0, 1.0, 2.0]) == percentiles([1.0, 2.0, 3.0])
    # n=4: rank(p50) = round(0.5*4)-1 = 1 -> second value
    assert percentiles([10.0, 20.0, 30.0, 40.0])["p50"] == 20.0
    # n=2: p99 clamps to the last value
    assert percentiles([5.0, 7.0])["p99"] == 7.0


def test_percentiles_empty_is_absent_not_zero():
    assert percentiles([]) == {}


def test_percentiles_custom_quantiles():
    p = percentiles([float(v) for v in range(1, 11)], qs=(0.1, 0.9))
    assert p == {"p10": 1.0, "p90": 9.0}


# ------------------------------------------------------- summarize_spans ----
def test_summarize_spans_known_sets():
    spans = [_span("serve.kernel", d, i)
             for i, d in enumerate(float(v) for v in range(1, 101))]
    spans += [_span("serve.admit", 0.5, 1000 + i) for i in range(3)]
    s = summarize_spans(spans)
    assert s["spans"] == 103
    k = s["names"]["serve.kernel"]
    assert (k["count"], k["p50_s"], k["p99_s"]) == (100, 50.0, 99.0)
    assert k["total_s"] == pytest.approx(5050.0)
    a = s["names"]["serve.admit"]
    assert (a["count"], a["p50_s"], a["p99_s"]) == (3, 0.5, 0.5)
    # ordered by total time descending
    assert list(s["names"]) == ["serve.kernel", "serve.admit"]


def test_summarize_spans_empty_input():
    assert summarize_spans([]) == {"spans": 0, "names": {}}


def test_summarize_spans_single_span():
    s = summarize_spans([_span("grid.point", 0.125)])
    r = s["names"]["grid.point"]
    # one sample: every percentile is that sample
    assert (r["count"], r["p50_s"], r["p99_s"]) == (1, 0.125, 0.125)
    assert s["spans"] == 1


def test_summarize_spans_top_truncates_by_total():
    spans = ([_span("big", 10.0, i) for i in range(2)]
             + [_span("small", 0.1, 10 + i) for i in range(5)])
    s = summarize_spans(spans, top=1)
    assert list(s["names"]) == ["big"]
    assert s["spans"] == 7  # the span count is pre-truncation


def test_summarize_spans_from_jsonl_file(tmp_path):
    path = tmp_path / "spans.jsonl"
    with open(path, "w", encoding="utf-8") as f:
        for i, d in enumerate((0.01, 0.02, 0.03)):
            f.write(json.dumps(_span("serve.request", d, i)) + "\n")
    s = summarize_spans(str(path))
    assert s["names"]["serve.request"]["count"] == 3


# ------------------------------------------------------ transfer counters ----
def test_transfer_diff_basic():
    tc = TransferCounters(Registry())
    before = tc.snapshot()
    tc.donated_blocks.inc(3)
    tc.fetches.inc()
    d = diff(tc.snapshot(), before)
    assert d["donated_blocks"] == 3 and d["fetches"] == 1
    assert d["reshard_mismatch"] == 0


def test_transfer_diff_tolerates_missing_before_keys():
    tc = TransferCounters(Registry())
    tc.device_puts.inc(2)
    d = diff(tc.snapshot(), {})  # an older artifact without the key
    assert d["device_put"] == 2


def test_transfer_counters_isolated_registries_under_concurrency():
    """Two pipelines with their own bundles must never cross-contaminate
    counts — the reason TransferCounters takes an explicit registry."""
    bundles = [TransferCounters(Registry()) for _ in range(4)]
    per_thread = 500

    def run(tc):
        for _ in range(per_thread):
            tc.donated_blocks.inc()
            tc.device_put_bytes.inc(128)
        tc.fetches.inc()  # one fetch at the reduction boundary

    threads = [threading.Thread(target=run, args=(tc,)) for tc in bundles]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for tc in bundles:
        snap = tc.snapshot()
        assert snap["donated_blocks"] == per_thread
        assert snap["device_put_bytes"] == per_thread * 128
        assert snap["fetches"] == 1


def test_transfer_shared_bundle_concurrent_increments_are_exact():
    """A shared bundle (the process-default shape) must count exactly
    under contention — counter increments take the metric lock."""
    tc = TransferCounters(Registry())
    before = tc.snapshot()
    n_threads, per_thread = 8, 400

    def run():
        for _ in range(per_thread):
            tc.donated_blocks.inc()

    threads = [threading.Thread(target=run) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert diff(tc.snapshot(), before)["donated_blocks"] \
        == n_threads * per_thread
