"""Two-party protocol tests (ISSUE 5): bit-identity to the monolithic
estimators, reliable-transport semantics under injected chaos, the
release gate's charge/refund discipline, transcript determinism and
auditing, and cross-party trace propagation.

The bit-identity reference is always ``jit(serving_entry(...))`` on the
same master key — the protocol (replay key layout) must reproduce it
exactly: splitting an estimator across a wire costs zero bits.
"""

import json
import os

import jax
import numpy as np
import pytest

from dpcorr.models.estimators.registry import serving_entry
from dpcorr.models.estimators.split_reference import (
    party_release,
    release_schema,
    split_estimate,
    split_roles,
)
from dpcorr.obs import trace as obs_trace
from dpcorr.obs.audit import AuditTrail
from dpcorr.protocol import (
    FaultInjector,
    InProcTransport,
    Message,
    ProtocolRefused,
    ProtocolSpec,
    ReleaseGate,
    ReliableChannel,
    TransportError,
    ledger_balance,
    read_transcript,
    run_inproc,
    run_tcp,
    scan_transcript,
)
from dpcorr.protocol.scan import wire_schema
from dpcorr.serve.ledger import PrivacyLedger
from dpcorr.utils import rng

FAMILIES = ("ni_sign", "int_sign", "ni_subg", "int_subg")


def _columns(n=1500, rho=0.6, seed=99):
    r = np.random.default_rng(seed)
    xy = r.multivariate_normal([0.0, 0.0], [[1.0, rho], [rho, 1.0]],
                               size=n)
    return (np.asarray(xy[:, 0], np.float32),
            np.asarray(xy[:, 1], np.float32))


def _monolithic(family, x, y, eps1=1.0, eps2=0.5, seed=2025,
                alpha=0.05, normalise=True):
    fn = jax.jit(serving_entry(family, eps1, eps2, alpha, normalise))
    rho, lo, hi = fn(rng.master_key(seed), x, y)
    return (float(np.float32(rho)), float(np.float32(lo)),
            float(np.float32(hi)))


def _bits(res):
    return (res.rho_hat, res.ci_low, res.ci_high)


# ----------------------------------------------------- split reference ----
def test_wire_schema_pins_release_schema():
    """scan.wire_schema is a deliberately jax-free re-derivation; this
    is the pin that stops the two from drifting silently."""
    for family in FAMILIES:
        for n in (64, 1500, 4096):
            for eps in ((1.0, 0.5), (0.25, 0.25), (5.0, 1.0)):
                assert wire_schema(family, n, *eps) == \
                    release_schema(family, n, *eps)


def test_split_roles_int_larger_eps_sends():
    assert split_roles("ni_sign", 0.1, 5.0) == ("x", "y")
    assert split_roles("int_sign", 2.0, 0.5) == ("x", "y")
    assert split_roles("int_sign", 0.5, 2.0) == ("y", "x")
    assert split_roles("int_subg", 1.0, 1.0) == ("x", "y")


@pytest.mark.parametrize("family", FAMILIES)
@pytest.mark.parametrize("eps", [(1.0, 0.5), (0.5, 2.0)])
def test_split_estimate_matches_monolithic(family, eps):
    """The factored estimator (release + finish, both jitted) is
    bit-identical to the monolithic closure — in both ε orderings, so
    the INT sender-swap re-association is covered."""
    x, y = _columns()
    key = rng.master_key(2025)
    got = split_estimate(family, key, key, x, y, *eps)
    want = _monolithic(family, x, y, *eps)
    assert tuple(float(np.float32(v)) for v in got) == want


def test_party_release_matches_schema():
    x, _ = _columns(n=900)
    key = rng.master_key(3)
    for family in FAMILIES:
        releaser, _f = split_roles(family, 1.0, 0.5)
        rel = party_release(family, key, releaser,
                            x, 1.0, 0.5, True)
        schema = release_schema(family, 900, 1.0, 0.5)
        assert set(rel) == set(schema)
        for name, want in schema.items():
            assert tuple(rel[name].shape) == want["shape"]
            assert str(rel[name].dtype) == want["dtype"]


# ------------------------------------------------------- protocol runs ----
@pytest.mark.parametrize("family", FAMILIES)
def test_protocol_inproc_bit_identical(family):
    x, y = _columns()
    spec = ProtocolSpec(family=family, n=len(x), eps1=1.0, eps2=0.5)
    res = run_inproc(spec, x, y)
    want = _monolithic(family, x, y)
    assert _bits(res["x"]) == want
    assert _bits(res["y"]) == want


def test_protocol_tcp_bit_identical():
    x, y = _columns()
    spec = ProtocolSpec(family="int_sign", n=len(x), eps1=1.0, eps2=0.5)
    res = run_tcp(spec, x, y)
    assert _bits(res["x"]) == _monolithic("int_sign", x, y)
    assert _bits(res["x"]) == _bits(res["y"])


def test_protocol_eps_order_swaps_int_sender():
    """eps2 > eps1 makes y the INT releaser; bits still match the
    monolithic estimator under the same master seed."""
    x, y = _columns()
    spec = ProtocolSpec(family="int_subg", n=len(x), eps1=0.5, eps2=2.0)
    res = run_inproc(spec, x, y)
    assert _bits(res["x"]) == _monolithic("int_subg", x, y,
                                          eps1=0.5, eps2=2.0)


def test_protocol_fault_injection_same_bits_with_retries():
    """Chaos (drops, delays, duplicates) exercises retransmission and
    dedupe but must never perturb the estimate: the fault RNG is
    stdlib, the estimator key tree is jax — disjoint by construction."""
    x, y = _columns(n=1000)
    spec = ProtocolSpec(family="ni_sign", n=len(x), eps1=1.0, eps2=0.5)
    clean = run_inproc(spec, x, y)
    fault = {"drop": 0.25, "delay_s": 0.002, "duplicate": 0.2}
    chaotic = run_inproc(spec, x, y, fault=fault, timeout_s=0.25)
    assert _bits(chaotic["x"]) == _bits(clean["x"])
    assert _bits(chaotic["y"]) == _bits(clean["y"])
    retries = sum(r.stats["total_retries"] for r in chaotic.values())
    assert retries > 0, "fault arm never retried — chaos proved nothing"


def test_protocol_hardened_mode_agrees_but_differs_from_replay():
    """The hardened key layout draws from disjoint per-party subtrees:
    both roles still agree on the result, but the bits deliberately
    differ from the replay/monolithic stream addresses."""
    x, y = _columns()
    spec = ProtocolSpec(family="ni_sign", n=len(x), eps1=1.0, eps2=0.5,
                        noise_mode="hardened")
    res = run_inproc(spec, x, y)
    assert _bits(res["x"]) == _bits(res["y"])
    assert _bits(res["x"]) != _monolithic("ni_sign", x, y)


def test_ledger_refusal_mid_protocol_no_partial_release(tmp_path):
    """The finisher's budget cannot cover its charge: the session must
    abort with a refusal, the refusing party must spend nothing, and
    no result message may exist anywhere — but the releaser's already
    -delivered release stays spent (it crossed the wire)."""
    x, y = _columns()
    spec = ProtocolSpec(family="ni_subg", n=len(x), eps1=1.0, eps2=0.5)
    lx = PrivacyLedger(100.0)
    ly = PrivacyLedger(0.2)  # y's charge is 0.5 > 0.2
    with pytest.raises(ProtocolRefused):
        run_inproc(spec, x, y, ledger_x=lx, ledger_y=ly,
                   transcript_dir=str(tmp_path))
    assert ly.snapshot()["parties"] == {}
    assert lx.snapshot()["parties"]["party-x"]["spent"] == 1.0
    for role in ("x", "y"):
        entries = read_transcript(
            str(tmp_path / f"{spec.session}.{role}.jsonl"))
        types = [e["wire"]["msg_type"] for e in entries]
        assert "result" not in types
        assert "error" in types


def test_duplicate_delivery_is_idempotent():
    """duplicate=1.0 doubles every frame; the receiver must process
    each sequence number once and re-ack the copies."""
    x, y = _columns(n=800)
    spec = ProtocolSpec(family="int_sign", n=len(x), eps1=1.0, eps2=0.5)
    clean = run_inproc(spec, x, y)
    doubled = run_inproc(spec, x, y, fault={"duplicate": 1.0})
    assert _bits(doubled["x"]) == _bits(clean["x"])


def test_transcript_replay_determinism(tmp_path):
    """Two runs of the same spec produce byte-identical wire payloads
    (canonical serialization + deterministic key tree) — transcripts
    differ only in timing fields."""
    x, y = _columns()
    spec = ProtocolSpec(family="ni_sign", n=len(x), eps1=1.0, eps2=0.5)
    dirs = [tmp_path / "a", tmp_path / "b"]
    for d in dirs:
        run_inproc(spec, x, y, transcript_dir=str(d))
    for role in ("x", "y"):
        wires = []
        for d in dirs:
            entries = read_transcript(
                str(d / f"{spec.session}.{role}.jsonl"))
            wires.append([json.dumps(e["wire"], sort_keys=True)
                          for e in entries])
        assert wires[0] == wires[1]


def test_trace_id_propagates_across_parties(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    obs_trace.configure(path)
    try:
        x, y = _columns()
        spec = ProtocolSpec(family="ni_sign", n=len(x),
                            eps1=1.0, eps2=0.5)
        res = run_inproc(spec, x, y)
    finally:
        obs_trace.configure(None)
    assert res["x"].trace_id is not None
    assert res["x"].trace_id == res["y"].trace_id
    spans = [json.loads(line) for line in open(path)]
    assert {s["trace_id"] for s in spans} == {res["x"].trace_id}
    names = {s["name"] for s in spans}
    assert "protocol.release" in names and "protocol.finish" in names


# -------------------------------------------------- transcript auditing ----
def test_scan_clean_transcript_and_ledger_balance(tmp_path):
    x, y = _columns()
    spec = ProtocolSpec(family="int_subg", n=len(x), eps1=1.0, eps2=0.5)
    trails = {"x": AuditTrail(), "y": AuditTrail()}
    run_inproc(spec, x, y,
               ledger_x=PrivacyLedger(100.0, audit=trails["x"]),
               ledger_y=PrivacyLedger(100.0, audit=trails["y"]),
               transcript_dir=str(tmp_path))
    for role in ("x", "y"):
        path = str(tmp_path / f"{spec.session}.{role}.jsonl")
        rep = scan_transcript(path, raw_x=x, raw_y=y)
        assert rep["ok"], rep["violations"]
        bal = ledger_balance(path, trails[role].events())
        assert bal["ok"], bal
    # both roles' charges sum to the serve-mode request charge
    spent = {**ledger_balance(
        str(tmp_path / f"{spec.session}.x.jsonl"),
        trails["x"].events())["spent"],
        **ledger_balance(
        str(tmp_path / f"{spec.session}.y.jsonl"),
        trails["y"].events())["spent"]}
    assert spent == {"party-x": 1.0, "party-y": 0.5}


def test_scan_flags_raw_column_on_wire(tmp_path):
    """Tamper a recorded release into the raw column: the scanner must
    flag it (the runtime no-raw-columns proof)."""
    from dpcorr.protocol.messages import encode_array

    x, y = _columns()
    spec = ProtocolSpec(family="int_sign", n=len(x), eps1=1.0, eps2=0.5)
    run_inproc(spec, x, y, transcript_dir=str(tmp_path))
    path = str(tmp_path / f"{spec.session}.x.jsonl")
    entries = read_transcript(path)
    tampered = 0
    for e in entries:
        if e["wire"]["msg_type"] == "release":
            e["wire"]["payload"]["flipped_signs"] = \
                encode_array(x, "rr_flipped_signs")
            tampered += 1
    assert tampered == 1
    rep = scan_transcript(entries, raw_x=x, raw_y=y)
    assert not rep["ok"]
    assert any(v["rule"] == "raw-column-on-wire"
               for v in rep["violations"])


def test_scan_flags_array_outside_release():
    from dpcorr.protocol.messages import encode_array

    x, _ = _columns(n=64)
    msg = Message("hello", "x", "s",
                  payload={"spec": {"family": "ni_sign", "n": 64,
                                    "eps1": 1.0, "eps2": 0.5},
                           "oops": encode_array(x, "raw")})
    entries = [{"dir": "send", "seq": 1, "eps": 0.0,
                "wire": msg.to_wire()}]
    rep = scan_transcript(entries)
    assert any(v["rule"] == "array-outside-release"
               for v in rep["violations"])


def test_protocol_transcript_frame(tmp_path):
    from dpcorr.report import protocol_transcript_frame

    x, y = _columns()
    spec = ProtocolSpec(family="ni_sign", n=len(x), eps1=1.0, eps2=0.5)
    run_inproc(spec, x, y, transcript_dir=str(tmp_path))
    df = protocol_transcript_frame(
        str(tmp_path / f"{spec.session}.x.jsonl"))
    assert list(df.columns) == ["seq", "dir", "type", "bytes",
                                "retries", "latency_s", "eps",
                                "trace_id", "ts"]
    assert list(df["type"]) == ["hello", "hello_ack", "release",
                                "result"]
    gated = df[df.eps > 0]
    assert len(gated) == 1 and gated.iloc[0]["type"] == "release"
    assert float(gated.iloc[0]["eps"]) == 2.0  # 1.0 × centering factor


# ------------------------------------------------------ gate + channel ----
class _FailingChannel:
    fault = None
    total_retries = 0

    def send(self, body):
        raise TransportError("wire down")


def test_gate_refunds_on_transport_failure():
    ledger = PrivacyLedger(10.0)
    gate = ReleaseGate(ledger)
    with pytest.raises(TransportError):
        gate.send_release(_FailingChannel(), {"k": 1},
                          {"party-x": 2.0})
    assert ledger.snapshot()["parties"]["party-x"]["spent"] == 0.0


def test_gate_charges_before_send():
    ledger = PrivacyLedger(10.0)
    gate = ReleaseGate(ledger)
    seen = {}

    class Channel:
        fault = None
        total_retries = 0

        def send(self, body):
            seen["spent_at_send"] = \
                ledger.snapshot()["parties"]["party-x"]["spent"]
            return {"seq": 1, "retries": 0, "latency_s": 0.0,
                    "bytes": 10}

    receipt = gate.send_release(Channel(), {"k": 1}, {"party-x": 2.0})
    assert seen["spent_at_send"] == 2.0  # charged *before* the wire
    assert receipt["eps"] == 2.0


def test_reliable_channel_dedupes_duplicates():
    pair = InProcTransport()
    a = ReliableChannel(pair.a, timeout_s=1.0,
                        fault=FaultInjector(duplicate=1.0, seed=5))
    b = ReliableChannel(pair.b, timeout_s=1.0)
    got = []
    for i in range(4):
        # send blocks on the ack, which b only produces on recv — so a
        # reader thread drives b while a's send waits
        import threading

        t = threading.Thread(
            target=lambda: got.append(b.recv(timeout_s=2.0)["body"]["i"]))
        t.start()
        a.send({"i": i})
        t.join()
    assert got == [0, 1, 2, 3]
    assert len(b._delivered) == 4  # each seq processed exactly once


def test_reliable_channel_times_out_without_peer():
    pair = InProcTransport()
    a = ReliableChannel(pair.a, timeout_s=0.02, max_retries=2,
                        backoff_base_s=0.01)
    with pytest.raises(TransportError):
        a.send({"dead": True})


def test_fault_injector_is_deterministic():
    plans = [FaultInjector(drop=0.3, duplicate=0.3, delay_s=0.01,
                           seed=42).plan() for _ in range(2)]
    assert plans[0] == plans[1]


# ----------------------------------------------------------- messages ----
def test_message_version_mismatch_rejected():
    wire = Message("hello", "x", "s").to_wire()
    wire["version"] = 99
    with pytest.raises(ValueError):
        Message.from_wire(wire)


def test_spec_hash_ignores_session_but_pins_params():
    a = ProtocolSpec(family="ni_sign", n=100, eps1=1.0, eps2=0.5)
    b = ProtocolSpec(family="ni_sign", n=100, eps1=1.0, eps2=0.5,
                     session="other")
    c = ProtocolSpec(family="ni_sign", n=100, eps1=1.0, eps2=0.6)
    assert a.spec_hash() == b.spec_hash()
    assert a.spec_hash() != c.spec_hash()
    assert a.session == f"sess-{a.spec_hash()[:12]}"


def test_hello_spec_mismatch_refused():
    """Different public specs on the two sides must abort in the
    handshake — before any ε is spent."""
    from dpcorr.protocol.messages import Transcript
    from dpcorr.protocol.party import Party, ProtocolError

    x, y = _columns()
    spec_x = ProtocolSpec(family="ni_sign", n=len(x), eps1=1.0,
                          eps2=0.5, session="s1")
    spec_y = ProtocolSpec(family="ni_sign", n=len(y), eps1=1.0,
                          eps2=0.6, session="s1")
    pair = InProcTransport()
    lx, ly = PrivacyLedger(100.0), PrivacyLedger(100.0)
    px = Party("x", x, spec_x, ReliableChannel(pair.a, timeout_s=2.0),
               lx, transcript=Transcript(None))
    py = Party("y", y, spec_y, ReliableChannel(pair.b, timeout_s=2.0),
               ly, transcript=Transcript(None))
    import threading

    errs = {}

    def run(p):
        try:
            p.run()
        except ProtocolError as e:
            errs[p.role] = e

    ts = [threading.Thread(target=run, args=(p,)) for p in (px, py)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert errs, "spec mismatch went unnoticed"
    assert lx.snapshot()["parties"] == {}
    assert ly.snapshot()["parties"] == {}


def test_run_tcp_writes_transcripts(tmp_path):
    x, y = _columns(n=600)
    spec = ProtocolSpec(family="ni_subg", n=len(x), eps1=1.0, eps2=0.5)
    run_tcp(spec, x, y, transcript_dir=str(tmp_path))
    files = sorted(os.listdir(tmp_path))
    assert files == [f"{spec.session}.x.jsonl",
                     f"{spec.session}.y.jsonl"]
    for f in files:
        rep = scan_transcript(str(tmp_path / f), raw_x=x, raw_y=y)
        assert rep["ok"], rep["violations"]
