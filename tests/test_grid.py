"""Grid-driver tests: design expansion, persistence/resume, aggregation,
fail-loud semantics."""

import numpy as np
import pandas as pd
import pytest

from dpcorr.grid import GridConfig, run_grid, summarize_grid


SMALL = dict(n_grid=(400, 800), rho_grid=(0.0, 0.5), eps_pairs=((1.0, 1.0),),
             b=24, seed=9)


def test_design_points_order_and_count():
    gc = GridConfig(**SMALL)
    d = gc.design_points()
    assert len(d) == 4
    # n varies fastest (reference expand.grid order, vert-cor.R:507-511)
    assert list(d["n"]) == [400, 800, 400, 800]
    assert list(d["i"]) == [0, 1, 2, 3]


def test_run_grid_local_shapes():
    res = run_grid(GridConfig(**SMALL))
    assert len(res.detail_all) == 4 * 24
    assert {"repl", "ni_hat", "int_cover", "n", "rho_true", "eps1", "eps2"} <= set(
        res.detail_all.columns)
    assert len(res.summ_all) == 8  # 4 design points x 2 methods
    assert set(res.summ_all["method"]) == {"NI", "INT"}
    assert len(res.timings) == 4


def test_grid_summaries_match_manual_groupby():
    res = run_grid(GridConfig(**SMALL))
    row = res.summ_all[(res.summ_all["method"] == "NI")
                       & (res.summ_all["n"] == 400)
                       & (res.summ_all["rho_true"] == 0.5)].iloc[0]
    sl = res.detail_all[(res.detail_all["n"] == 400)
                        & (res.detail_all["rho_true"] == 0.5)]
    np.testing.assert_allclose(row["mse"], sl["ni_se2"].mean(), rtol=1e-6)
    np.testing.assert_allclose(row["coverage"], sl["ni_cover"].mean(), rtol=1e-6)


def test_persistence_and_resume(tmp_path):
    gc = GridConfig(**SMALL, out_dir=str(tmp_path))
    res1 = run_grid(gc)
    assert len(list(tmp_path.glob("design_*.npz"))) == 4
    assert (tmp_path / "detail_all.parquet").exists()
    # resume: reruns load identical numbers from disk
    res2 = run_grid(gc)
    assert res2.timings["cached"].all()
    pd.testing.assert_frame_equal(res1.detail_all, res2.detail_all)


def test_sharded_backend_grid(devices):
    res = run_grid(GridConfig(**SMALL, backend="sharded"))
    assert len(res.detail_all) == 4 * 24


def test_bucketed_backend_bit_identical_to_local():
    """The grid-axis-vectorized backend reuses the same per-point keys, so
    every replicate value matches the local backend exactly."""
    loc = run_grid(GridConfig(**SMALL))
    buck = run_grid(GridConfig(**SMALL, backend="bucketed"))
    pd.testing.assert_frame_equal(loc.detail_all, buck.detail_all)
    assert len(buck.timings) == 2  # one row per (n, eps) bucket
    assert (buck.timings["points"] == 2).all()


def test_bucketed_precompile_bit_identical_and_flagged():
    """ISSUE 4: phase-0 AOT precompilation dispatches the same HLO the
    lazy jit path would build — per-bucket results are bit-identical
    with the knob on vs off, and the timings frame records which
    buckets rode a precompiled executable. "on" (not "auto") so the
    assertion holds on single-core CI hosts where auto backs off."""
    off = run_grid(GridConfig(**SMALL, backend="bucketed",
                              precompile="off"))
    on = run_grid(GridConfig(**SMALL, backend="bucketed",
                             precompile="on"))
    pd.testing.assert_frame_equal(off.detail_all, on.detail_all)
    assert on.timings["precompiled"].all()
    assert not off.timings["precompiled"].any()


def test_precompile_auto_matches_host_cores(monkeypatch):
    """"auto" is a host decision: active iff >= 2 CPUs are available
    (with one core the overlap has nowhere to run — GridConfig doc)."""
    import dpcorr.grid as grid_mod

    for cores, expect in ((1, False), (8, True)):
        monkeypatch.setattr(grid_mod.os, "cpu_count", lambda c=cores: c)
        res = run_grid(GridConfig(**SMALL, backend="bucketed",
                                  precompile="auto"))
        assert res.timings["precompiled"].all() == expect


def test_precompile_knob_validated():
    with pytest.raises(ValueError, match="precompile"):
        run_grid(GridConfig(**SMALL, precompile="bogus"))


def test_bucketed_sharded_bit_identical_to_local(devices):
    """Both parallel axes composed — bucket kernels with the flat
    (points × reps) axis split over the 8-device mesh — must still be
    bit-identical to the local backend (per-element keys are the
    identity; the mesh only changes layout)."""
    loc = run_grid(GridConfig(**SMALL))
    bs = run_grid(GridConfig(**SMALL, backend="bucketed-sharded"))
    pd.testing.assert_frame_equal(loc.detail_all, bs.detail_all)
    # 48 flat elements per bucket divides the 8-device mesh evenly; also
    # cover a non-divisible axis (2 × 13 = 26 → pads to 32) and a
    # smaller-than-mesh one (1 point × b=3 → pad 5 > total 3)
    for cfg_kw in (dict(SMALL, b=13),
                   dict(SMALL, b=3, rho_grid=(0.5,), eps_pairs=((1.0, 1.0),))):
        loc_odd = run_grid(GridConfig(**cfg_kw))
        bs_odd = run_grid(GridConfig(**cfg_kw, backend="bucketed-sharded"))
        pd.testing.assert_frame_equal(loc_odd.detail_all, bs_odd.detail_all)


def test_bucketed_resume_cache_interchangeable(tmp_path):
    """Bucketed and local backends share the per-point .npz cache."""
    gc_loc = GridConfig(**SMALL, out_dir=str(tmp_path))
    res1 = run_grid(gc_loc)
    gc_b = GridConfig(**SMALL, out_dir=str(tmp_path), backend="bucketed")
    res2 = run_grid(gc_b)
    assert (res2.timings["points_run"] == 0).all()  # all cache hits
    pd.testing.assert_frame_equal(res1.detail_all, res2.detail_all)


def test_unknown_backend_fails_loudly():
    with pytest.raises(RuntimeError, match="design points failed"):
        run_grid(GridConfig(**SMALL, backend="nope"))


def test_bucketed_bucket_failure_isolated(monkeypatch, tmp_path):
    """A failing bucket is recorded, the other buckets still run (their
    .npz caches land on disk), and one aggregated error is raised at the
    end — the local backend's fail-loud semantics (ADVICE round 1)."""
    from dpcorr import sim as sim_mod

    real = sim_mod._run_detail_flat

    def flaky(cfg, keys, rhos):
        if cfg.n == 400:
            raise ValueError("boom in bucket n=400")
        return real(cfg, keys, rhos)

    monkeypatch.setattr(sim_mod, "_run_detail_flat", flaky)
    gc = GridConfig(**SMALL, backend="bucketed", out_dir=str(tmp_path))
    with pytest.raises(RuntimeError, match="2/4 design points failed"):
        run_grid(gc)
    # the healthy n=800 bucket still ran and persisted its two points
    done = sorted(p.name for p in tmp_path.glob("design_*.npz"))
    assert done == ["design_00001.npz", "design_00003.npz"]


def test_summarize_grid_pure_function():
    df = pd.DataFrame({
        "n": [100] * 4, "rho_true": [0.5] * 4, "eps1": [1.0] * 4,
        "eps2": [1.0] * 4,
        "ni_hat": [0.4, 0.6, 0.5, 0.5], "ni_se2": [0.01, 0.01, 0.0, 0.0],
        "ni_cover": [1, 1, 0, 1], "ni_ci_len": [0.2] * 4,
        "int_hat": [0.5] * 4, "int_se2": [0.0] * 4,
        "int_cover": [1] * 4, "int_ci_len": [0.1] * 4,
    })
    s = summarize_grid(df)
    ni = s[s["method"] == "NI"].iloc[0]
    assert ni["coverage"] == 0.75
    np.testing.assert_allclose(ni["bias"], 0.0, atol=1e-12)


# ---- fused (Pallas) bucket selection ----

def test_fused_bucket_eligibility(monkeypatch):
    """_fused_bucket_ok gates the Pallas kernel on platform, backend,
    estimator family, DGP, mixquant mode, and batch geometry."""
    import dataclasses

    import jax

    from dpcorr import grid as g

    gc = GridConfig(**SMALL, backend="bucketed", fused="auto")
    cfg = gc.sim_config({"n": 1000, "rho": 0.5, "eps1": 1.0, "eps2": 1.0})

    # CPU platform (the test env) → never eligible
    assert not g._fused_bucket_ok(gc, cfg)

    class _FakeTpu:
        platform = "tpu"

    monkeypatch.setattr(jax, "devices", lambda *a, **k: [_FakeTpu()])
    assert g._fused_bucket_ok(gc, cfg) == "sign"
    assert not g._fused_bucket_ok(dataclasses.replace(gc, fused="off"), cfg)
    assert not g._fused_bucket_ok(
        dataclasses.replace(gc, backend="bucketed-sharded"), cfg)
    assert not g._fused_bucket_ok(gc, dataclasses.replace(cfg, dgp="bernoulli"))
    assert not g._fused_bucket_ok(
        gc, dataclasses.replace(cfg, mixquant_mode="mc"))
    # m = ceil(8/(0.05·0.05)) = 3200 > 128 lanes
    assert not g._fused_bucket_ok(
        gc, dataclasses.replace(cfg, eps1=0.05, eps2=0.05))
    # subG buckets never fuse since the r05 fused="all" retirement
    # (GridConfig.fused: measured 0.98x XLA, r02_grid_fused_subg_tpu.json)
    subg = dataclasses.replace(cfg, use_subg=True, dgp="bounded_factor")
    assert not g._fused_bucket_ok(gc, subg)
    assert not g._fused_bucket_ok(
        gc, dataclasses.replace(cfg, use_subg=True))  # gaussian + subG
    # the retired mode fails fast with the retirement citation, a typo'd
    # value with the plain message
    with pytest.raises(ValueError, match="retired"):
        g._fused_bucket_ok(dataclasses.replace(gc, fused="all"), cfg)
    with pytest.raises(ValueError, match="fused"):
        g._fused_bucket_ok(dataclasses.replace(gc, fused="bogus"), cfg)


def test_fused_auto_on_cpu_matches_off(tmp_path):
    """fused="auto" on a CPU host must be a no-op: every bucket is
    ineligible, results and caches stay bit-identical to fused="off"."""
    off = run_grid(GridConfig(**SMALL, backend="bucketed"))
    auto = run_grid(GridConfig(**SMALL, backend="bucketed", fused="auto",
                               out_dir=str(tmp_path)))
    pd.testing.assert_frame_equal(off.detail_all, auto.detail_all)
    assert not auto.timings["fused"].any()
    # cache stamps carry no fused tag → a fused="off" resume hits them
    res = run_grid(GridConfig(**SMALL, backend="bucketed",
                              out_dir=str(tmp_path)))
    assert res.timings["points_run"].sum() == 0
    pd.testing.assert_frame_equal(off.detail_all, res.detail_all)


def test_fused_dispatch_failure_falls_back_to_xla(monkeypatch):
    """If the fused kernel fails at dispatch (here: Pallas lowering is
    unavailable on CPU), the bucket must fall back to the XLA kernel and
    produce results bit-identical to fused="off"."""
    from dpcorr import grid as g

    monkeypatch.setattr(g, "_fused_bucket_ok", lambda gcfg, cfg: "sign")
    auto = run_grid(GridConfig(**SMALL, backend="bucketed", fused="auto"))
    off = run_grid(GridConfig(**SMALL, backend="bucketed"))
    pd.testing.assert_frame_equal(auto.detail_all, off.detail_all)
    assert not auto.timings["fused"].astype(bool).any()


def test_stamp_encodes_real_mc_mixquant_nsim():
    """The real-variant mc-mode nsim default moved 1000 → 2000
    (real-data-sims.R:161-164); pre-move caches must not resume into
    post-move runs, so the stamp encodes the draw count for exactly the
    configs the default touches."""
    import dataclasses

    from dpcorr import grid as g

    cfg = GridConfig(**SMALL).sim_config(
        {"n": 200, "rho": 0.0, "eps1": 1.0, "eps2": 1.0})
    mc_real = dataclasses.replace(cfg, mixquant_mode="mc",
                                  subg_variant="real", use_subg=True,
                                  dgp="bounded_factor")
    assert "mixquant_nsim=2000" in g._stamp(mc_real)
    assert "mixquant_nsim" not in g._stamp(cfg)
    assert "mixquant_nsim" not in g._stamp(
        dataclasses.replace(mc_real, mixquant_mode="det"))
    assert "mixquant_nsim" not in g._stamp(
        dataclasses.replace(mc_real, subg_variant="grid"))


def test_fused_fetch_failure_falls_back_to_xla(monkeypatch):
    """A fused kernel whose error only surfaces at the phase-2 fetch
    barrier (device execution, not lowering) must also degrade the bucket
    to the XLA kernel, bit-identical to fused="off" (ADVICE r2)."""
    from dpcorr import grid as g
    from dpcorr.ops import pallas_ni

    class _LazyBoom:
        def __array__(self, *a, **k):
            raise RuntimeError("simulated device-side kernel failure")

    monkeypatch.setattr(g, "_fused_bucket_ok", lambda gcfg, cfg: "sign")
    monkeypatch.setattr(  # dispatch succeeds; fetch (np.asarray) explodes
        pallas_ni, "sim_detail_pallas",
        lambda *a, **k: [_LazyBoom() for _ in range(12)])
    auto = run_grid(GridConfig(**SMALL, backend="bucketed", fused="auto"))
    off = run_grid(GridConfig(**SMALL, backend="bucketed"))
    pd.testing.assert_frame_equal(auto.detail_all, off.detail_all)
    assert not auto.timings["fused"].astype(bool).any()


# ---- ε-merged compile buckets (bucket_merge="eps", r05) ----

SUBG_SMALL = dict(n_grid=(400, 800), rho_grid=(0.2, 0.5),
                  eps_pairs=((0.5, 0.5), (1.0, 1.0), (1.5, 0.5)), b=48,
                  dgp="bounded_factor", use_subg=True, seed=9)


def test_bucket_merge_groups_by_n_only():
    mrg = run_grid(GridConfig(**SUBG_SMALL, backend="bucketed",
                              bucket_merge="eps"))
    assert len(mrg.timings) == 2                       # one bucket per n
    assert list(mrg.timings["merged_eps_pairs"]) == [3, 3]
    assert mrg.timings["eps1"].isna().all()            # per-pair labels gone
    # every design point still produced b replications with its own ε
    assert len(mrg.detail_all) == 12 * 48
    assert set(map(tuple, mrg.detail_all[["eps1", "eps2"]]
                   .drop_duplicates().values)) == set(SUBG_SMALL["eps_pairs"])


def test_bucket_merge_statistically_matches_off():
    """Merged buckets run the dynamic-geometry estimators — same math,
    padded noise layout. INT is stream-identical (no geometry), NI
    agrees to float-order effects; grid-level summaries must match
    tightly."""
    off = run_grid(GridConfig(**SUBG_SMALL, backend="bucketed"))
    mrg = run_grid(GridConfig(**SUBG_SMALL, backend="bucketed",
                              bucket_merge="eps"))
    s_off = off.summ_all.set_index(["method", "n", "rho_true", "eps1"])
    s_mrg = mrg.summ_all.set_index(["method", "n", "rho_true", "eps1"])
    for col, tol in (("coverage", 0.11), ("mse", None)):
        a = s_off[col].sort_index()
        b = s_mrg[col].sort_index()
        if tol is None:
            np.testing.assert_allclose(a.values, b.values, rtol=0.35)
        else:
            assert (a - b).abs().max() <= tol
    # INT rides the identical stream in both modes — exact agreement
    int_off = s_off.loc["INT"].sort_index()
    int_mrg = s_mrg.loc["INT"].sort_index()
    np.testing.assert_allclose(int_off["coverage"].values,
                               int_mrg["coverage"].values, atol=1e-6)


def test_bucket_merge_validation():
    import dataclasses as dc

    base = GridConfig(**SUBG_SMALL, backend="bucketed", bucket_merge="eps")
    with pytest.raises(ValueError, match="bucket_merge"):
        run_grid(dc.replace(base, bucket_merge="bogus"))
    with pytest.raises(ValueError, match="subG-only"):
        run_grid(GridConfig(**SMALL, backend="bucketed",
                            bucket_merge="eps"))
    with pytest.raises(ValueError, match="bucketed"):
        run_grid(dc.replace(base, backend="local"))
    with pytest.raises(ValueError, match="ε₁ ≥ ε₂|eps"):
        run_grid(dc.replace(base, eps_pairs=((0.5, 1.5),)))


def test_bucket_merge_cache_stamps_never_mix(tmp_path):
    """Merged results come from a different PRNG layout than "off" —
    their per-point npz caches carry a "|geom=dyn" stamp, so neither
    mode can silently serve the other's cached points."""
    mrg_cfg = GridConfig(**SUBG_SMALL, backend="bucketed",
                         bucket_merge="eps", out_dir=str(tmp_path))
    first = run_grid(mrg_cfg)
    again = run_grid(mrg_cfg)          # same mode -> full cache hit
    assert again.timings["points_run"].sum() == 0
    pd.testing.assert_frame_equal(first.detail_all, again.detail_all)
    off_cfg = GridConfig(**SUBG_SMALL, backend="bucketed",
                         out_dir=str(tmp_path))
    off = run_grid(off_cfg)            # stamps differ -> everything re-runs
    assert off.timings["points_run"].sum() == 12


def test_bucket_merge_composes_with_mc_mixquant():
    """The merged kernel's traced c* feeds mixquant_mc just like the
    static path's — the mc mode (the construction-faithful twin) must
    compose with bucket_merge, not only the det default."""
    import dataclasses as dc

    base = GridConfig(**{**SUBG_SMALL, "b": 24}, backend="bucketed",
                      bucket_merge="eps", mixquant_mode="mc")
    res = run_grid(base)
    assert len(res.detail_all) == 12 * 24
    cov = res.summ_all.groupby("method")["coverage"].mean()
    assert 0.7 < float(cov["INT"]) <= 1.0
    off = run_grid(dc.replace(base, bucket_merge="off"))
    a = off.summ_all.groupby("method")["coverage"].mean()
    assert abs(float(a["INT"]) - float(cov["INT"])) < 0.12
