"""Streaming (n-blocked) estimators vs the materialized ones.

Exactness tests exploit the shared key addressing: the streaming NI
estimators draw the *same* (k,)-shaped batch noise and standardization noise
as the materialized path, so on identical data (array-backed chunk_fn) they
agree to float-reduction-order tolerance. INT estimators draw per-sample
noise per chunk, so they get (a) exactness tests in regimes where that noise
is deterministic (ε_s large ⇒ keep-prob rounds to 1 in f32 / sender scale
≈ 0) and (b) statistical agreement tests on the full pipeline.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpcorr.models.dgp import gen_bounded_factor, gen_gaussian
from dpcorr.models.estimators import (
    array_chunk_fn,
    choose_n_chunk,
    ci_int_signflip,
    ci_int_signflip_stream,
    ci_int_subg,
    ci_int_subg_stream,
    ci_ni_signbatch,
    ci_ni_signbatch_stream,
    correlation_ni_subg,
    correlation_ni_subg_stream,
)
from dpcorr.models.estimators.common import batch_geometry
from dpcorr.sim import SimConfig, run_sim_one
from dpcorr.utils import rng


def _data(n, rho=0.4, seed=7, dgp=gen_gaussian):
    return dgp(rng.master_key(seed), n, jnp.float32(rho))


def _assert_close(a, b, atol=2e-5):
    for fa, fb in zip(a[:3], b[:3]):
        np.testing.assert_allclose(np.asarray(fa), np.asarray(fb),
                                   atol=atol, rtol=2e-5)
    assert (a.aux is None) == (b.aux is None)
    if a.aux is not None:
        assert set(a.aux) == set(b.aux)
        for name in a.aux:
            np.testing.assert_allclose(np.asarray(a.aux[name]),
                                       np.asarray(b.aux[name]),
                                       atol=atol, rtol=2e-5)


class TestChunkPlumbing:
    def test_choose_n_chunk_multiple_of_m(self):
        assert choose_n_chunk(10_000, 8, 1000) == 1000 - 1000 % 8
        assert choose_n_chunk(10_000, 7, 1000) == 994
        assert choose_n_chunk(100, 64, 16) == 64  # never below m

    def test_choose_n_chunk_n_below_target(self):
        """n < target: the chunk covers the whole sample in one go —
        the n+m-1 ceiling rounds a ragged n UP to a multiple of m, so
        no second chunk exists just for a sub-batch tail."""
        assert choose_n_chunk(100, 8, 65536) == 104  # ceil(100/8)*8
        assert choose_n_chunk(96, 8, 65536) == 96    # already aligned
        assert choose_n_chunk(5, 3, 65536) == 6
        # a single chunk of the returned size always covers n rows
        for n, m in ((100, 8), (97, 7), (5, 3), (65535, 64)):
            assert choose_n_chunk(n, m, 65536) >= n

    def test_choose_n_chunk_n_equals_one(self):
        """The degenerate stream: one row, batch of one."""
        assert choose_n_chunk(1, 1, 65536) == 1
        assert choose_n_chunk(1, 1, 1) == 1
        # m > n (INT families clamp m = min(m, n) before calling, but
        # the function itself must still honour the >= m floor)
        assert choose_n_chunk(1, 4, 65536) == 4

    def test_choose_n_chunk_non_dividing_counts(self):
        """target not a multiple of m: align DOWN to the m grid (a
        batch must never straddle chunks), but never below m itself."""
        assert choose_n_chunk(10**6, 48, 1000) == 960
        assert choose_n_chunk(10**6, 1000, 999) == 1000  # floor wins
        assert choose_n_chunk(10**6, 7, 10) == 7
        for target in (10, 100, 1000, 65536):
            for m in (1, 3, 7, 48, 1000):
                nc = choose_n_chunk(10**6, m, target)
                assert nc % m == 0 and nc >= m
                assert nc <= max(target, m)

    def test_array_chunk_fn_tiles_and_pads(self):
        xy = jnp.arange(20.0).reshape(10, 2)
        fn = array_chunk_fn(xy, 4)
        np.testing.assert_array_equal(np.asarray(fn(0)), np.asarray(xy[:4]))
        last = np.asarray(fn(2))
        np.testing.assert_array_equal(last[:2], np.asarray(xy[8:]))
        np.testing.assert_array_equal(last[2:], 0.0)


class TestNIExact:
    """Same data + same noise addresses ⇒ streaming ≡ materialized."""

    @pytest.mark.parametrize("normalise", [False, True])
    @pytest.mark.parametrize("n,eps1,eps2,n_chunk",
                             [(4096, 1.0, 1.0, 512),
                              (3000, 1.5, 0.5, 1024),  # m=11, ragged tail
                              (4096, 1.0, 1.0, 8192)])  # single chunk
    def test_ni_sign_matches(self, normalise, n, eps1, eps2, n_chunk):
        xy = _data(n)
        key = rng.master_key(11)
        m, _ = batch_geometry(n, eps1, eps2)
        n_chunk = choose_n_chunk(n, m, n_chunk)
        ref = ci_ni_signbatch(key, xy[:, 0], xy[:, 1], eps1, eps2,
                              normalise=normalise)
        got = ci_ni_signbatch_stream(key, array_chunk_fn(xy, n_chunk), n,
                                     eps1, eps2, normalise=normalise,
                                     n_chunk=n_chunk)
        _assert_close(got, ref)

    @pytest.mark.parametrize("n,eps1,eps2,n_chunk",
                             [(4096, 1.0, 1.0, 512), (5000, 2.0, 0.5, 640)])
    def test_ni_subg_matches(self, n, eps1, eps2, n_chunk):
        xy = _data(n, dgp=gen_bounded_factor)
        key = rng.master_key(12)
        m, _ = batch_geometry(n, eps1, eps2)
        n_chunk = choose_n_chunk(n, m, n_chunk)
        ref = correlation_ni_subg(key, xy[:, 0], xy[:, 1], eps1, eps2)
        got = correlation_ni_subg_stream(key, array_chunk_fn(xy, n_chunk), n,
                                         eps1, eps2, n_chunk=n_chunk)
        _assert_close(got, ref)

    def test_ni_sign_jit_vmap(self):
        """Streaming kernels must compose with jit+vmap (the sim path)."""
        n, n_chunk = 2048, 512
        xy = _data(n)
        fn = jax.jit(jax.vmap(lambda k: ci_ni_signbatch_stream(
            k, array_chunk_fn(xy, n_chunk), n, 1.0, 1.0, n_chunk=n_chunk)))
        out = fn(rng.rep_keys(rng.master_key(0), 8))
        assert out.rho_hat.shape == (8,)
        assert bool(jnp.all(out.ci_low <= out.ci_high))


class TestINTExactDeterministicNoise:
    def test_int_sign_matches_at_large_eps_s(self):
        """ε_s = 30 ⇒ keep-prob rounds to 1.0 in f32 ⇒ flips deterministic;
        the single receiver draw shares its key address ⇒ exact match."""
        n, n_chunk = 4096, 512
        xy = _data(n)
        key = rng.master_key(13)
        ref = ci_int_signflip(key, xy[:, 0], xy[:, 1], 30.0, 1.0,
                              normalise=False)
        got = ci_int_signflip_stream(key, array_chunk_fn(xy, n_chunk), n,
                                     30.0, 1.0, normalise=False,
                                     n_chunk=n_chunk)
        _assert_close(got, ref)

    def test_int_subg_matches_at_tiny_sender_noise(self):
        """ε_s = 1e6 ⇒ sender noise scale ~1e-6 ⇒ both paths compute the
        same clipped products to ~1e-4; central draw shares its address."""
        n, n_chunk = 4096, 512
        xy = _data(n, dgp=gen_bounded_factor)
        key = rng.master_key(14)
        ref = ci_int_subg(key, xy[:, 0], xy[:, 1], 1e6, 1.0, variant="grid")
        got = ci_int_subg_stream(key, array_chunk_fn(xy, n_chunk), n,
                                 1e6, 1.0, n_chunk=n_chunk)
        _assert_close(got, ref, atol=5e-4)


class TestFusedSubgPair:
    """subg_pair_stream generates each chunk once for both estimators;
    same key addresses ⇒ bit-identical to the two separate kernels."""

    @pytest.mark.parametrize(
        "n,eps1,eps2,n_chunk",
        [(4096, 1.0, 1.0, 512),
         (5000, 2.0, 0.5, 640),  # ragged + swapped roles
         # INT needs ceil(33/16)=3 chunks but NI only ceil(k=4/kc=2)=2 —
         # the fused loop must run the larger count (r3 review finding)
         (33, 1.0, 1.0, 16)])
    def test_pair_matches_separate_kernels(self, n, eps1, eps2, n_chunk):
        from dpcorr.models.estimators.streaming import (ci_int_subg_stream,
                                                        subg_pair_stream)

        xy = _data(n, dgp=gen_bounded_factor)
        key_ni, key_int = rng.master_key(21), rng.master_key(22)
        m, _ = batch_geometry(n, eps1, eps2)
        n_chunk = choose_n_chunk(n, m, n_chunk)
        cf = array_chunk_fn(xy, n_chunk)
        ni_sep = correlation_ni_subg_stream(key_ni, cf, n, eps1, eps2,
                                            n_chunk=n_chunk)
        int_sep = ci_int_subg_stream(key_int, cf, n, eps1, eps2,
                                     n_chunk=n_chunk)
        ni, it = subg_pair_stream(key_ni, key_int, cf, n, eps1, eps2,
                                  n_chunk=n_chunk)
        for a, b in ((ni, ni_sep), (it, int_sep)):
            for fa, fb in zip(a[:3], b[:3]):
                np.testing.assert_array_equal(np.asarray(fa),
                                              np.asarray(fb))
            assert set(a.aux) == set(b.aux)

    def test_pair_rejects_misaligned_chunk(self):
        from dpcorr.models.estimators.streaming import subg_pair_stream

        xy = _data(1000, dgp=gen_bounded_factor)
        with pytest.raises(ValueError, match="multiple of the batch size"):
            subg_pair_stream(rng.master_key(1), rng.master_key(2),
                             array_chunk_fn(xy, 100), 1000, 0.5, 0.5,
                             n_chunk=100)  # m=32 does not divide 100


class TestStatisticalAgreement:
    """Full streaming pipeline (chunkwise DGP) vs materialized, as MC
    distributions: summaries must agree within Monte-Carlo error."""

    @pytest.mark.parametrize("use_subg,dgp", [(False, "gaussian"),
                                              (True, "bounded_factor")])
    def test_sim_summaries_agree(self, use_subg, dgp):
        base = dict(n=2048, rho=0.5, eps1=1.0, eps2=1.0, b=300,
                    dgp=dgp, use_subg=use_subg, chunk_size=128)
        mat = run_sim_one(SimConfig(**base)).summary
        stm = run_sim_one(SimConfig(**base, stream_n_chunk=512)).summary
        for meth in ("NI", "INT"):
            assert abs(mat[meth]["coverage"] - stm[meth]["coverage"]) < 0.08
            assert abs(mat[meth]["bias"] - stm[meth]["bias"]) < 0.05
            assert abs(mat[meth]["ci_length"] - stm[meth]["ci_length"]) < 0.05
            # MSE within a factor of 2 (B=300 MC noise)
            assert stm[meth]["mse"] < 2.0 * mat[meth]["mse"] + 1e-3
            assert mat[meth]["mse"] < 2.0 * stm[meth]["mse"] + 1e-3

    def test_stream_smoke_large_n(self):
        """n = 10⁵ streaming smoke: runs under the default-device test env
        with only 16k rows resident per rep."""
        cfg = SimConfig(n=100_000, rho=0.3, eps1=1.0, eps2=1.0, b=4,
                        stream_n_chunk=16384, chunk_size=4)
        res = run_sim_one(cfg)
        assert np.isfinite(res.detail["ni_hat"]).all()
        assert np.isfinite(res.detail["int_hat"]).all()
        # NI at n=1e5, ε=1 should be tight around ρ
        assert abs(res.summary["NI"]["bias"]) < 0.1
