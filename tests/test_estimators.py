"""Estimator-kernel tests: determinism, edge branches, variant semantics.

Statistical acceptance (coverage vs nominal over full MC batches) lives in
test_sim.py; here we pin down the kernel-level contracts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.scipy.special import ndtri

from dpcorr.models.dgp import gen_bounded_factor, gen_gaussian
from dpcorr.models.estimators import (
    batch_geometry,
    ci_int_signflip,
    ci_int_subg,
    ci_ni_signbatch,
    correlation_int_signflip,
    correlation_ni_signbatch,
    correlation_ni_subg,
)
from dpcorr.utils import rng

KEY = rng.master_key(42)


def _data(n=2000, rho=0.5, key=KEY):
    xy = gen_gaussian(rng.stream(key, "data"), n, rho)
    return xy[:, 0], xy[:, 1]


class TestBatchGeometry:
    def test_paper_choice(self):
        # m = ceil(8/(eps1*eps2)) capped at n, k = floor(n/m) (vert-cor.R:124-126)
        assert batch_geometry(2000, 0.5, 1.0) == (16, 125)
        assert batch_geometry(2000, 1.0, 1.0) == (8, 250)
        assert batch_geometry(5, 0.1, 0.1) == (5, 1)  # m capped at n

    def test_min_k_fallback(self):
        # k<2 -> k=2, m=n//2 (real-data-sims.R:130)
        assert batch_geometry(50, 0.5, 0.5, enforce_min_k=True) == (25, 2)
        # untouched when k >= 2
        assert batch_geometry(2000, 1.0, 1.0, enforce_min_k=True) == (8, 250)

    def test_k_zero_raises(self):
        with pytest.raises(ValueError):
            batch_geometry(0, 1.0, 1.0)

    def test_f32_band_detects_real_dyn_disagreement(self):
        """f32_geometry_band predicts exactly where the traced f32 rule
        (batch_geometry_dyn) departs from the static f64 rule: ε=1.1547
        puts q=8/ε² within f32-ulp of 6, so the snap-down guard picks
        m=6 where f64 ceils to 7."""
        from dpcorr.models.estimators.common import (batch_geometry_dyn,
                                                     f32_geometry_band)

        e = 1.1547
        hits = f32_geometry_band([(e, e)], n=1000)
        assert hits == [(e, e, 7, 6)]
        assert batch_geometry(1000, e, e)[0] == 7
        assert int(batch_geometry_dyn(1000, e, e)[0]) == 6
        # ordinary pairs sit nowhere near the band
        assert f32_geometry_band([(1.0, 0.5), (1.0, 1.0)], n=1000) == []

    def test_f32_band_warns_once_per_entry_point(self, caplog):
        import dpcorr.models.estimators.common as common

        common._F32_BAND_WARNED.discard("test-entry")
        with caplog.at_level("WARNING", logger=common.__name__):
            hits = common.warn_f32_geometry_band_once(
                [(1.1547, 1.1547)], where="test-entry")
            assert hits and len(caplog.records) == 1
            common.warn_f32_geometry_band_once(
                [(1.1547, 1.1547)], where="test-entry")
            assert len(caplog.records) == 1  # logged once, found twice


class TestNiSign:
    def test_deterministic(self):
        x, y = _data()
        a = ci_ni_signbatch(KEY, x, y, 1.0, 1.0)
        b = ci_ni_signbatch(KEY, x, y, 1.0, 1.0)
        assert a == b

    def test_ci_brackets_estimate_and_is_ordered(self):
        x, y = _data()
        r = ci_ni_signbatch(KEY, x, y, 1.0, 1.0)
        assert float(r.ci_low) <= float(r.rho_hat) <= float(r.ci_high)
        assert -1.0 <= float(r.ci_low) and float(r.ci_high) <= 1.0

    def test_estimator_in_range(self):
        x, y = _data()
        r = correlation_ni_signbatch(KEY, x, y, 1.0, 1.0)
        assert abs(float(r)) <= 1.0  # sine link

    def test_approaches_truth_high_eps(self):
        # with large eps the DP noise vanishes; sign-batch estimator at large
        # n should land near the true rho
        x, y = _data(n=50_000, rho=0.6)
        vals = [
            float(correlation_ni_signbatch(rng.master_key(s), x, y, 100.0, 100.0))
            for s in range(5)
        ]
        assert abs(np.mean(vals) - 0.6) < 0.05

    def test_eta_space_clamp(self):
        # extreme rho: CI ends must stay within [-1, 1] after sine map
        x, y = _data(n=1000, rho=-0.98)
        r = ci_ni_signbatch(KEY, x, y, 2.0, 2.0)
        assert -1.0 <= float(r.ci_low) <= float(r.ci_high) <= 1.0


class TestIntSign:
    def test_sender_symmetric_core(self):
        # swapping (eps1, eps2) swaps roles but the estimator distribution is
        # the same; with the same key the result is identical because the
        # flipped product is role-symmetric (vert-cor.R:178-183)
        x, y = _data()
        a = correlation_int_signflip(KEY, x, y, 1.5, 0.5)
        b = correlation_int_signflip(KEY, x, y, 0.5, 1.5)
        np.testing.assert_allclose(float(a), float(b), rtol=1e-6)

    def test_regime_switch_static(self):
        x, y = _data(n=100)
        # sqrt(100)*0.04 = 0.4 < 0.5 -> laplace regime (vert-cor.R:294-296)
        r = ci_int_signflip(KEY, x, y, 1.0, 0.04, normalise=False)
        assert -1.0 <= float(r.ci_low) <= float(r.ci_high) <= 1.0
        # laplace width in eta space: (2/(n eps_r))*ratio*log(1/alpha)
        e_s = np.exp(1.0)
        width = (2.0 / (100 * 0.04)) * (e_s + 1) / (e_s - 1) * np.log(1 / 0.05)
        assert width > 1.0  # so the eta-interval saturates and CI = [-1, 1]
        np.testing.assert_allclose(float(r.ci_low), -1.0, atol=1e-6)
        np.testing.assert_allclose(float(r.ci_high), 1.0, atol=1e-6)

    def test_normal_regime_finite_width(self):
        x, y = _data()
        r = ci_int_signflip(KEY, x, y, 1.0, 1.0)
        assert 0.0 < float(r.ci_high - r.ci_low) < 2.0

    def test_mc_mixquant_path(self):
        x, y = _data()
        r = ci_int_signflip(KEY, x, y, 1.0, 1.0, mixquant_mode="mc")
        assert np.isfinite(float(r.ci_low)) and np.isfinite(float(r.ci_high))

    def test_bad_mode_raises(self):
        x, y = _data()
        with pytest.raises(ValueError):
            ci_int_signflip(KEY, x, y, 1.0, 1.0, mode="bogus")


class TestNiSubg:
    def test_no_sine_link(self):
        # with huge eps and clipped bounded data, estimate ~ sample corr
        xy = gen_bounded_factor(rng.stream(KEY, "bf"), 20_000, 0.5)
        x, y = xy[:, 0], xy[:, 1]
        r = correlation_ni_subg(KEY, x, y, 100.0, 100.0)
        sample = float(jnp.corrcoef(x, y)[0, 1])
        assert abs(float(r.rho_hat) - sample) < 0.05

    def test_lambda_overrides(self):
        x, y = _data()
        a = correlation_ni_subg(KEY, x, y, 1.0, 1.0)
        b = correlation_ni_subg(KEY, x, y, 1.0, 1.0, lambda_x=0.5, lambda_y=0.5)
        assert float(a.rho_hat) != float(b.rho_hat)

    def test_randomized_batches_change_result_not_distribution(self):
        x, y = _data(n=4000)
        a = correlation_ni_subg(KEY, x, y, 1.0, 1.0)
        b = correlation_ni_subg(KEY, x, y, 1.0, 1.0, randomize_batches=True)
        assert float(a.rho_hat) != float(b.rho_hat)
        # both unbiased over the data distribution (fresh data per seed; on a
        # *fixed* dataset the conditional expectations legitimately differ
        # through within-batch cross terms). eps=10 keeps the per-draw sd
        # ~0.05 so a 25-seed mean pins the bias within ~0.04.
        means = []
        for randomize in (False, True):
            vals = []
            for s in range(25):
                xs, ys = _data(n=2000, rho=0.5, key=rng.master_key(100 + s))
                vals.append(float(
                    correlation_ni_subg(rng.master_key(s), xs, ys, 10.0, 10.0,
                                        randomize_batches=randomize).rho_hat))
            means.append(np.mean(vals))
        assert abs(means[0] - 0.5) < 0.04
        assert abs(means[1] - 0.5) < 0.04

    def test_min_k_fallback_runs(self):
        x, y = _data(n=50)
        r = correlation_ni_subg(KEY, x, y, 0.5, 0.5, enforce_min_k=True)
        assert np.isfinite(float(r.rho_hat))

    def test_aux_geometry_and_lambdas(self):
        """The richer real-data return (k, m, λ_X, λ_Y)
        (real-data-sims.R:141-147) rides in ``aux``."""
        x, y = _data(n=2000)
        r = correlation_ni_subg(KEY, x, y, 1.0, 1.0,
                                lambda_x=0.7, lambda_y=0.9)
        assert (r.aux["m"], r.aux["k"]) == batch_geometry(2000, 1.0, 1.0)
        assert float(r.aux["lambda_x"]) == 0.7
        assert float(r.aux["lambda_y"]) == 0.9


class TestIntSubg:
    def test_grid_variant(self):
        xy = gen_bounded_factor(rng.stream(KEY, "bf"), 5500, 0.6)
        r = ci_int_subg(KEY, xy[:, 0], xy[:, 1], 5.0, 1.0, variant="grid")
        assert -1.0 <= float(r.ci_low) <= float(r.ci_high) <= 1.0

    def test_real_variant_with_overrides(self):
        x, y = _data()
        r = ci_int_subg(KEY, x, y, 2.0, 2.0, variant="real",
                        lambda_sender=2.0, lambda_other=2.0)
        assert np.isfinite(float(r.rho_hat))
        assert float(r.ci_high) > float(r.ci_low)

    def test_real_sd_zero_degenerate_branch(self):
        # other side identically 0 -> U = 0 -> sd(Uc) = 0 -> fixed-width
        # normal branch (real-data-sims.R:237-238)
        n = 1000
        x = jnp.asarray(np.random.default_rng(0).normal(size=n), jnp.float32)
        y = jnp.zeros((n,), jnp.float32)
        lam_r = 3.0
        eps = 1.0
        r = ci_int_subg(KEY, x, y, 2.0, eps, variant="real",
                        lambda_sender=2.0, lambda_other=2.0,
                        lambda_receiver=lam_r)
        width = float(r.ci_high - r.ci_low) / 2.0
        expected = float(ndtri(0.975)) * np.sqrt(2.0) * (2 * lam_r / (n * eps))
        np.testing.assert_allclose(width, expected, rtol=1e-4)

    def test_roles_swap(self):
        x, y = _data()
        a = ci_int_subg(KEY, x, y, 2.0, 1.0)  # x sends
        b = ci_int_subg(KEY, y, x, 1.0, 2.0)  # x still sends
        np.testing.assert_allclose(float(a.rho_hat), float(b.rho_hat), rtol=1e-5)

    def test_bad_variant_raises(self):
        x, y = _data()
        with pytest.raises(ValueError):
            ci_int_subg(KEY, x, y, 1.0, 1.0, variant="v3")

    def test_mc_nsim_defaults_per_variant(self, monkeypatch):
        """mc-mode draw counts follow the reference per variant: 1000 for
        the grid script's mixquant (ver-cor-subG.R:10), 2000 for the
        real-data script's (real-data-sims.R:161-164); explicit
        ``mixquant_nsim`` overrides both."""
        from dpcorr.models.estimators import int_subg as mod

        seen = []
        real_mc = mod.mixquant_mc

        def spy(key, c, p, nsim=1000):
            seen.append(nsim)
            return real_mc(key, c, p, nsim=nsim)

        monkeypatch.setattr(mod, "mixquant_mc", spy)
        x, y = _data(n=1000)
        ci_int_subg(KEY, x, y, 2.0, 1.0, variant="grid",
                    mixquant_mode="mc")
        ci_int_subg(KEY, x, y, 2.0, 1.0, variant="real",
                    lambda_sender=2.0, lambda_other=1.5,
                    mixquant_mode="mc")
        ci_int_subg(KEY, x, y, 2.0, 1.0, variant="real",
                    lambda_sender=2.0, lambda_other=1.5,
                    mixquant_mode="mc", mixquant_nsim=500)
        assert seen == [1000, 2000, 500]

    def test_aux_lambdas_and_delta(self):
        """λ_sender/λ_other/λ_receiver/δ extras (real-data-sims.R:244-252)."""
        x, y = _data(n=1000)
        r = ci_int_subg(KEY, x, y, 2.0, 1.0, variant="real",
                        lambda_sender=2.0, lambda_other=1.5)
        assert float(r.aux["lambda_sender"]) == 2.0
        assert float(r.aux["lambda_other"]) == 1.5
        assert float(r.aux["delta_clip"]) == 1.0 / 1000
        assert float(r.aux["lambda_receiver"]) > 0
        assert (r.aux["eps_sender"], r.aux["eps_receiver"]) == (2.0, 1.0)
        g = ci_int_subg(KEY, x, y, 2.0, 1.0, variant="grid")
        assert "delta_clip" not in g.aux and "lambda_sender" in g.aux


class TestVmapCompat:
    def test_all_estimators_vmap(self):
        x, y = _data(n=512)
        keys = rng.rep_keys(KEY, 4)
        for fn in (
            lambda k: ci_ni_signbatch(k, x, y, 1.0, 1.0),
            lambda k: ci_int_signflip(k, x, y, 1.0, 1.0),
            lambda k: correlation_ni_subg(k, x, y, 1.0, 1.0,
                                          randomize_batches=True),
            lambda k: ci_int_subg(k, x, y, 1.0, 1.0, variant="real"),
        ):
            out = jax.vmap(fn)(keys)
            assert out.rho_hat.shape == (4,)
            assert len(np.unique(np.asarray(out.rho_hat))) == 4


class TestDegenerateBatchGeometry:
    def test_ni_sign_k1_nan_ci_matches_reference_na(self):
        """m = ⌈8/(ε₁ε₂)⌉ = n ⇒ k=1 single batch: R's sd() of one value is
        NA, so the reference CI is NA and never covers (vert-cor.R:233-254
        at this geometry). Our sample_sd(ddof=1) yields NaN — same
        contract: finite point estimate, NaN CI ends."""
        n = 400
        key = rng.master_key(5)
        xy = gen_gaussian(rng.stream(key, "d"), n, jnp.float32(0.3))
        res = ci_ni_signbatch(key, xy[:, 0], xy[:, 1], 1.0, 0.02)
        assert np.isfinite(float(res.rho_hat))
        assert np.isnan(float(res.ci_low)) and np.isnan(float(res.ci_high))
        # coverage arithmetic then records False, not an error
        cover = (res.ci_low <= 0.3) & (0.3 <= res.ci_high)
        assert not bool(cover)


class TestIntSignCiModes:
    """The ci_mode knob (vert-cor.R:497-499) and the auto regime switch
    (vert-cor.R:294-296)."""

    def _run(self, n, eps_r, mode):
        key = rng.master_key(3)
        xy = gen_gaussian(rng.stream(key, "d"), n, jnp.float32(0.4))
        return ci_int_signflip(key, xy[:, 0], xy[:, 1], 1.0, eps_r,
                               mode=mode)

    def test_auto_equals_forced_regime(self):
        # √400·1.0 = 20 > 0.5 → auto ≡ normal; forced laplace differs
        auto = self._run(400, 1.0, "auto")
        normal = self._run(400, 1.0, "normal")
        lap = self._run(400, 1.0, "laplace")
        np.testing.assert_array_equal(np.asarray(auto.ci_low),
                                      np.asarray(normal.ci_low))
        assert float(auto.ci_low) != float(lap.ci_low)

    def test_auto_picks_laplace_below_threshold(self):
        # √400·0.02 = 0.4 < 0.5 → auto ≡ laplace (vert-cor.R:304-308)
        auto = self._run(400, 0.02, "auto")
        lap = self._run(400, 0.02, "laplace")
        np.testing.assert_array_equal(np.asarray(auto.ci_low),
                                      np.asarray(lap.ci_low))

    def test_laplace_width_closed_form(self):
        # fixed width (2/(nε_r))·ratio·log(1/α) in η-space, independent of
        # the data beyond ρ̂ (vert-cor.R:304-308)
        import math

        # interior interval: width_eta ≈ 0.16 < 1 − |η̂| (a saturated
        # [-1,1] CI would make this test vacuous)
        n, eps_r, alpha = 4000, 0.02, 0.05
        res = self._run(n, eps_r, "laplace")
        e_s = math.exp(1.0)
        width_eta = (2.0 / (n * eps_r)) * (e_s + 1) / (e_s - 1) \
            * math.log(1.0 / alpha)
        eta_hat = 1.0 - math.acos(float(res.rho_hat)) * 2.0 / math.pi
        lo = math.sin(math.pi / 2.0 * max(eta_hat - width_eta, -1.0))
        hi = math.sin(math.pi / 2.0 * min(eta_hat + width_eta, 1.0))
        np.testing.assert_allclose(float(res.ci_low), lo, rtol=1e-5)
        np.testing.assert_allclose(float(res.ci_high), hi, rtol=1e-5)



class TestNiSubgDynamicGeometry:
    """dynamic_geometry=True: the masked single-compile variant (r05) —
    same estimator math with (m, k) as traced data, so one compiled
    kernel serves an ε-sweep (dpcorr/hrs.py's 2-compile sweep)."""

    def test_matches_static_with_noise_silenced(self, monkeypatch):
        """With the Laplace draws zeroed, both paths are deterministic
        functions of the same clipped/permuted data and the same (m, k)
        rule — they must agree to float tolerance at several ε spanning
        very different geometries (m from 128 down to 2)."""
        from dpcorr.models.estimators import ni_subg as mod

        monkeypatch.setattr(mod, "laplace",
                            lambda key, shape, scale: jnp.zeros(shape))
        x, y = _data(n=3000)
        for eps in (0.25, 0.7, 1.0, 2.5):
            for randomize in (False, True):
                a = correlation_ni_subg(KEY, x, y, eps, eps,
                                        randomize_batches=randomize)
                b = correlation_ni_subg(KEY, x, y,
                                        jnp.float32(eps), jnp.float32(eps),
                                        randomize_batches=randomize,
                                        dynamic_geometry=True)
                for fa, fb in zip(a[:3], b[:3]):
                    np.testing.assert_allclose(float(fa), float(fb),
                                               rtol=2e-5, atol=2e-6)
                assert int(b.aux["m"]) == a.aux["m"]
                assert int(b.aux["k"]) == a.aux["k"]

    def test_distributionally_equivalent_with_noise(self):
        """With real noise the two paths draw from different stream
        layouts (padded (n,) vs exact (k,)) — same distribution, not the
        same values. Pin mean agreement over seeds at a tight-noise ε."""
        x, y = _data(n=2000, rho=0.5)
        stat, dyn = [], []
        for s in range(30):
            k = rng.master_key(500 + s)
            stat.append(float(correlation_ni_subg(
                k, x, y, 10.0, 10.0).rho_hat))
            dyn.append(float(correlation_ni_subg(
                k, x, y, jnp.float32(10.0), jnp.float32(10.0),
                dynamic_geometry=True).rho_hat))
        assert float(np.mean(stat)) == pytest.approx(float(np.mean(dyn)),
                                                     abs=0.03)
        assert float(np.std(stat)) == pytest.approx(float(np.std(dyn)),
                                                    rel=0.7)

    def test_one_compile_serves_all_eps(self):
        """The point of the variant: jitting it and calling with many ε
        values must compile exactly once."""
        x, y = _data(n=1000)

        @jax.jit
        def kern(key, eps):
            r = correlation_ni_subg(key, x, y, eps, eps,
                                    dynamic_geometry=True)
            return r.rho_hat

        for eps in (0.3, 0.5, 1.0, 1.7, 2.5):
            assert np.isfinite(float(kern(KEY, jnp.float32(eps))))
        assert kern._cache_size() == 1

    def test_f32_boundary_eps_matches_static_rule(self):
        """ε=√2 squares to just under 2 in float32, pushing 8/ε² to
        4.0000001 — without the guard the dyn rule would ceil to m=5
        where the static (float64) rule gives m=4. Also pin the tiny-ε
        overflow guard: the float clip must land at m=n, never an
        implementation-defined int32 cast."""
        import math

        x, y = _data(n=1000)
        e = math.sqrt(2.0)
        m_static, k_static = batch_geometry(1000, e, e)
        r = correlation_ni_subg(KEY, x, y, jnp.float32(e), jnp.float32(e),
                                dynamic_geometry=True)
        assert (int(r.aux["m"]), int(r.aux["k"])) == (m_static, k_static)
        tiny = correlation_ni_subg(KEY, x, y, jnp.float32(1e-5),
                                   jnp.float32(1e-5),
                                   dynamic_geometry=True)
        assert int(tiny.aux["m"]) == 1000  # clipped to n, k=1

    def test_min_k_fallback_dynamic(self):
        x, y = _data(n=50)
        r = correlation_ni_subg(KEY, x, y, jnp.float32(0.5),
                                jnp.float32(0.5), enforce_min_k=True,
                                dynamic_geometry=True)
        assert np.isfinite(float(r.rho_hat))
        assert int(r.aux["k"]) == 2


class TestIntSubgSenderParam:
    """Explicit protocol direction (r05): the reference's real-data
    script names AGE→BMI outright (real-data-sims.R:305); sender="x"/"y"
    encodes that, and is what lets ε be traced in the sweep kernels."""

    def test_explicit_sender_matches_auto_rule(self):
        x, y = _data()
        auto = ci_int_subg(KEY, x, y, 2.0, 1.0)          # larger-ε: x sends
        named = ci_int_subg(KEY, x, y, 2.0, 1.0, sender="x")
        np.testing.assert_allclose(float(auto.rho_hat),
                                   float(named.rho_hat), rtol=1e-6)

    def test_sender_overrides_eps_rule(self):
        """sender can name a direction the larger-ε rule can never
        produce (the smaller-ε side sending); the choice is slot-
        independent — naming the same physical sender from either slot
        computes the same protocol."""
        x, y = _data()
        a = ci_int_subg(KEY, x, y, 2.0, 1.0, sender="y")  # y sends at ε=1
        b = ci_int_subg(KEY, y, x, 1.0, 2.0, sender="x")  # same roles
        np.testing.assert_allclose(float(a.rho_hat), float(b.rho_hat),
                                   rtol=1e-5)
        # and it genuinely differs from what the auto rule would pick
        auto = ci_int_subg(KEY, x, y, 2.0, 1.0)           # x sends at ε=2
        assert float(a.rho_hat) != float(auto.rho_hat)

    def test_traced_eps_requires_named_sender(self):
        """With traced ε the larger-ε rule is untraceable by design —
        naming the direction is the API for sweep kernels."""
        x, y = _data(n=500)

        @jax.jit
        def kern(eps):
            return ci_int_subg(KEY, x, y, eps, eps, variant="real",
                               lambda_sender=1.0, lambda_other=1.0,
                               lambda_receiver=2.0, delta_clip=1e-3,
                               sender="x").rho_hat

        assert np.isfinite(float(kern(jnp.float32(1.0))))
        assert np.isfinite(float(kern(jnp.float32(2.0))))
        assert kern._cache_size() == 1

    def test_bad_sender_raises(self):
        x, y = _data()
        with pytest.raises(ValueError, match="sender"):
            ci_int_subg(KEY, x, y, 1.0, 1.0, sender="z")
