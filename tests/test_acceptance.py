"""Acceptance-campaign tests (BASELINE 1e-3 criterion; VERDICT r1 item 3).

Two layers:

- smoke: the campaign machinery end-to-end at tiny B (block sums match a
  direct run_sim_one summary on the same config shape);
- table: the checked-in B≥10⁶ campaign result
  (``benchmarks/results/acceptance_*.json``) must satisfy the criteria —
  det-vs-MC mixquant agreement ≤ 1e-3 and coverage within the recorded MC
  envelope of nominal. Regenerating the table is opt-in
  (``python -m dpcorr acceptance``, minutes on TPU / hours on CPU).
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from dpcorr.acceptance import POINTS, AccPoint, run_campaign

RESULTS_DIR = Path(__file__).parent.parent / "benchmarks" / "results"


def test_campaign_smoke():
    pts = (AccPoint("smoke_sign", "smoke",
                    {"n": 300, "rho": 0.3, "eps1": 1.0, "eps2": 1.0},
                    both_mixquant=True),)
    table = run_campaign(b=512, block=256, points=pts, chunk_size=256)
    [row] = table["points"]
    assert row["det"]["b"] == 512
    for meth in ("NI", "INT"):
        assert 0.0 <= row["det"][meth]["coverage"] <= 1.0
        assert row["det"][meth]["ci_length"] > 0.0
    # mixquant only enters the INT CI: NI must agree exactly under
    # common random numbers
    assert row["ni_det_mc_diff"] == 0.0
    assert "int_det_mc_diff" in row


def test_campaign_points_cover_regimes():
    """The campaign grid must keep crossing every CI regime: both INT sign
    regimes (√n·ε_r around 0.5, vert-cor.R:294-296), both estimator
    families, both mixquant modes."""
    regimes = {p.name: p for p in POINTS}
    sign = [p for p in POINTS if not p.kwargs.get("use_subg")]
    subg = [p for p in POINTS if p.kwargs.get("use_subg")]
    assert sign and subg
    assert any((p.kwargs["n"] ** 0.5
                * min(p.kwargs["eps1"], p.kwargs["eps2"])) < 0.5
               for p in sign), "no Laplace-regime point"
    assert any((p.kwargs["n"] ** 0.5
                * min(p.kwargs["eps1"], p.kwargs["eps2"])) > 0.5
               for p in sign), "no normal-regime point"
    assert any(p.both_mixquant for p in POINTS)
    assert "sign_laplace" in regimes


@pytest.mark.parametrize("path", sorted(RESULTS_DIR.glob("acceptance_*.json"))
                         or [pytest.param(None, marks=pytest.mark.skip(
                             reason="no checked-in campaign table yet"))])
def test_checked_in_table_meets_criteria(path):
    table = json.loads(Path(path).read_text())
    if table["b_per_run"] < 1_000_000:
        # reduced-B insurance artifacts (CPU twins run while the TPU
        # tunnel endpoint was dead, STATUS_r04.md) must declare
        # themselves and still carry enough reps for the MC-SE envelope
        # below to be meaningful; the envelope itself widens
        # automatically via coverage_mc_se
        assert table.get("reduced_b_note"), (
            f"{path}: b_per_run {table['b_per_run']} < 1e6 without a "
            "reduced_b_note")
        assert table["b_per_run"] >= (1 << 17)
    # two-pronged det-vs-MC criterion: strict 1e-3 agreement, or the gap
    # is attributed to the reference's own MC-quantile bias, which
    # requires (a) the exact det mode closer to nominal everywhere and
    # (b) the attribution recorded in the table
    assert table["det_mc_pass"], (
        f"det-vs-MC mixquant coverage diff {table['det_mc_max_diff']}")
    if not table["det_mc_within_1e3"]:
        assert table["det_closer_to_nominal_everywhere"]
        assert "det_mc_attribution" in table
        assert table["det_mc_max_diff"] <= 5e-3  # still small
    # NI never touches mixquant: modes must agree exactly
    for row in table["points"]:
        assert row.get("ni_det_mc_diff", 0.0) == 0.0, row["point"]
    # coverage itself: every family/point within 1e-3 + 3.5 MC SE of
    # nominal, unless the point is exempt (degenerate/clamped regime, with
    # the reason recorded) or carries a documented finite-n tolerance
    envelope = 1e-3 + 3.5 * table["coverage_mc_se"]
    for row in table["points"]:
        for meth in ("NI", "INT"):
            cov = row["det"][meth]["coverage"]
            if row.get("coverage_exempt", {}).get(meth):
                continue
            tol = row.get("coverage_tol", 0.0)
            if tol:
                assert row.get("tol_reason"), row["point"]
            assert abs(cov - table["nominal"]) <= max(envelope, tol), (
                f"{row['point']}/{meth}: coverage {cov}")


def test_fused_campaign_table_meets_criteria():
    """The fused (on-chip-PRNG Pallas) kernels' own B=2²⁰ hardware
    campaign (`benchmarks/results/r02_fused_acceptance.json`,
    benchmarks/fused_acceptance_tpu.py) must sit at nominal within the
    same 1e-3 + MC-SE envelope as the XLA table — except INT subG, whose
    construction under-covers at finite n by design (the XLA acceptance
    table's subg_factor attribution; ≈0.94 at B=10⁶ even at ε=(1,1))."""
    path = RESULTS_DIR / "r02_fused_acceptance.json"
    if not path.exists():
        pytest.skip("no fused campaign table checked in")
    table = json.loads(path.read_text())
    nominal = table["nominal"]
    fams = table["families"]
    for fam in ("sign", "subg"):
        assert fams[fam]["B"] >= 1_000_000
    def envelope(fam):
        return 1e-3 + 3.5 * fams[fam]["mc_se"]

    assert abs(fams["sign"]["coverage_NI"] - nominal) <= envelope("sign")
    assert abs(fams["sign"]["coverage_INT"] - nominal) <= envelope("sign")
    assert abs(fams["subg"]["coverage_NI"] - nominal) <= envelope("subg")
    # intrinsic finite-n under-coverage of the INT subG construction: at
    # or below nominal (within MC error above), never below the band the
    # XLA campaign measured
    assert (0.93 <= fams["subg"]["coverage_INT"]
            <= nominal + envelope("subg"))


def test_r04_second_point_resolves_margin_question():
    """VERDICT r3 #5: the r03 det/mc margin (9.28e-4 of the 1e-3 budget
    at one config) needed a second (n, ε) point to classify as noise vs
    construction. The r04 point (n=6000, ε=2.0 — the HRS ε) measured
    9.61e-4: two independent configs agreeing in sign and size, with det
    closer to nominal at both, pins it as the mc mode's small systematic
    order-statistic quantile bias — with the criterion still passing
    strictly at both points and det (the default) better-calibrated."""
    path = RESULTS_DIR / "acceptance_r04.json"
    if not path.exists():
        pytest.skip("r04 second-point artifact not landed yet")
    table = json.loads(path.read_text())
    (row,) = table["points"]
    assert row["config"]["n"] == 6000
    assert row["config"]["eps1"] == row["config"]["eps2"] == 2.0
    assert row["config"]["subg_variant"] == "real"
    assert row["det"]["b"] >= 1 << 20
    assert row["ni_det_mc_diff"] == 0.0
    assert row["int_det_mc_diff"] <= 1e-3
    # det closer to nominal than mc at this point too (the r03 pattern)
    nominal = table["nominal"]
    assert (abs(row["det"]["INT"]["coverage"] - nominal)
            <= abs(row["mc"]["INT"]["coverage"] - nominal))


def _diffs_by_reference_nsim() -> dict:
    """int_det_mc_diff values from every checked-in campaign table,
    grouped by the reference mixquant flavor the point's mc mode
    mirrors: nsim=2000 for the real-data construction
    (real-data-sims.R:161-164, ci_int_subg's variant-aware default),
    nsim=1000 for everything else (vert-cor.R:44-56). ONE classification
    rule for both attribution tests below."""
    by_nsim = {1000: [], 2000: []}
    for path in sorted(RESULTS_DIR.glob("acceptance_*.json")):
        table = json.loads(path.read_text())
        for row in table["points"]:
            if "int_det_mc_diff" not in row:
                continue
            variant = row["config"].get("subg_variant", "grid")
            use_subg = row["config"].get("use_subg", False)
            nsim = 2000 if (use_subg and variant == "real") else 1000
            by_nsim[nsim].append(float(row["int_det_mc_diff"]))
    return by_nsim


def test_det_mc_gap_scales_inversely_with_reference_nsim():
    """The decisive attribution check (r05; VERDICT r4 'what's weak' #3):
    if the det-vs-MC INT coverage gap is the MC mode's finite-nsim
    order-statistic quantile bias, it must scale ~1/nsim — the
    reference's grid scripts draw nsim=1000 (vert-cor.R:44-56), its
    real-data script nsim=2000 (real-data-sims.R:161-164), and the
    framework's mc mode reproduces each faithfully
    (``ci_int_subg``'s variant-aware default).

    Measured across every checked-in campaign table: the nsim=1000
    points (sign_normal, subg_factor — r02, B≥1e6 — plus the r05
    subg_grid_extra at the asymmetric (1.5, 0.5) pair) sit at
    1.87-2.04e-3 and the nsim=2000 points (subg_real flavor — r03/r04
    campaigns, four configs from n=1000 to n=19,433) at ~0.85-1.03e-3:
    a group-mean ratio of ~2.0 matching the nsim ratio. A det-mode
    *error* would have no reason to halve when the reference's own draw
    count doubles."""
    by_nsim = _diffs_by_reference_nsim()
    if not (by_nsim[1000] and by_nsim[2000]):
        pytest.skip("need campaign tables at both nsim flavors")
    mean1k = sum(by_nsim[1000]) / len(by_nsim[1000])
    mean2k = sum(by_nsim[2000]) / len(by_nsim[2000])
    # the claim is about the GROUP MEANS; per-point caps are loose
    # (mean + ~3 MC SE at the noisiest table, SE up to 4.3e-4 at the
    # reduced-B insurance point) so a fresh on-chip draw of the same
    # true gap cannot fail spuriously — the direction is the strict part
    assert 1.4 <= mean1k / mean2k <= 2.8, (mean1k, mean2k)
    assert all(d <= 3.2e-3 for d in by_nsim[1000])
    assert all(d <= 2.2e-3 for d in by_nsim[2000])


def test_det_mc_gap_matches_order_statistic_theory():
    """The attribution, closed in exact form (r05): the reference's
    mixquant draws nsim samples and returns ``sort(x)[ceiling(p*nsim)]``
    (vert-cor.R:44-48, ver-cor-subG.R:8-12, real-data-sims.R:161-164).
    For ANY continuous mixture CDF F, the classical uniform-order-
    statistic identity E[F(X_(k:n))] = k/(n+1) makes the CI's effective
    two-sided level 2·k/(nsim+1) − 1 instead of 2p − 1, so the det−mc
    coverage gap is PREDICTED, parameter-free:

        nsim=1000: 2·(0.975 − 975/1001)  = 1.948e-3
        nsim=2000: 2·(0.975 − 1950/2001) = 0.974e-3

    The measured group means (1.93e-3 / 0.94e-3 across seven campaign
    points) must sit within MC error of these — and the identity itself
    is cross-checked numerically against this framework's faithful
    ``mixquant_mc`` + closed-form ``mix_cdf``."""
    import math

    # 1. theory vs the checked-in campaign tables
    pred = {ns: 2.0 * (0.975 - math.ceil(0.975 * ns) / (ns + 1))
            for ns in (1000, 2000)}
    assert pred[1000] == pytest.approx(1.948e-3, abs=1e-6)
    assert pred[2000] == pytest.approx(0.974e-3, abs=1e-6)
    by_nsim = _diffs_by_reference_nsim()
    if not (by_nsim[1000] and by_nsim[2000]):
        pytest.skip("need campaign tables at both nsim flavors")
    for ns, diffs in by_nsim.items():
        mean = sum(diffs) / len(diffs)
        # per-point MC SE is ~2.1e-4 at B=2^20 (up to 4.3e-4 at the
        # reduced-B point); a 2.5e-4 band on the group mean is generous
        # against noise yet ~8x tighter than the 2x nsim-ratio check
        assert abs(mean - pred[ns]) <= 2.5e-4, (ns, mean, pred[ns])

    # 2. the identity itself, numerically: E[F(q_mc)] = k/(n+1)
    import jax

    from dpcorr.ops.mixquant import mix_cdf, mixquant_mc
    from dpcorr.utils import rng

    nsim, p, c = 1000, 0.975, 0.5
    keys = jax.random.split(rng.master_key(7), 512)
    qs = jax.vmap(lambda k: mixquant_mc(k, c, p, nsim=nsim))(keys)
    mean_level = float(mix_cdf(qs, c).mean())
    k = math.ceil(p * nsim)
    expect = k / (nsim + 1)          # 975/1001 = 0.974026
    # sd(F(X_(k))) = sqrt(k(n-k+1))/((n+1)·sqrt(n+2)) ≈ 5.0e-3; the mean
    # over 512 independent draws has SE ≈ 2.2e-4 → ±4.5 SE band
    assert abs(mean_level - expect) <= 1e-3, (mean_level, expect)
    assert mean_level < p            # the bias is DOWNWARD, always
