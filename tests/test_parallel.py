"""Sharded-backend tests on the virtual 8-device mesh (SURVEY.md §4:
multi-device paths testable on CPU)."""

import numpy as np

from dpcorr.parallel import rep_mesh, run_detail_sharded, run_summary_sharded
from dpcorr.sim import SimConfig, run_sim_one


CFG = SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=40, seed=5)


def test_mesh_spans_devices(devices):
    mesh = rep_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("rep",)


def test_sharded_detail_matches_local(devices):
    # identical keys per replication -> identical detail, independent of
    # the device layout (b=40 pads to 40, 5 reps/device)
    local = run_sim_one(CFG)
    sharded = run_detail_sharded(CFG, mesh=rep_mesh())
    for f in ("ni_hat", "int_hat", "ni_cover", "int_ci_len"):
        np.testing.assert_allclose(
            np.asarray(local.detail[f]), np.asarray(sharded.detail[f]),
            rtol=2e-5, atol=1e-7)


def test_sharded_detail_pads_nondivisible(devices):
    cfg = SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=37, seed=5)
    sharded = run_detail_sharded(cfg, mesh=rep_mesh())
    assert sharded.detail["ni_hat"].shape == (37,)
    local = run_sim_one(cfg)
    np.testing.assert_allclose(np.asarray(local.detail["ni_hat"]),
                               np.asarray(sharded.detail["ni_hat"]),
                               rtol=2e-5, atol=1e-7)


def test_summary_sharded_psum_matches_detail(devices):
    cfg = SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=37, seed=5)
    summ = run_summary_sharded(cfg, mesh=rep_mesh())
    ref = run_sim_one(cfg).summary
    for meth in ("NI", "INT"):
        for k in ("mse", "bias", "var", "coverage", "ci_length"):
            np.testing.assert_allclose(summ[meth][k], ref[meth][k],
                                       rtol=5e-4, atol=1e-6), (meth, k)


def test_sharded_detail_bit_equal_at_realistic_b(devices):
    """Same per-rep keys ⇒ the sharded detail table is *bit-identical* to
    the local one at realistic B, across every detail field — the mesh path
    changes only the layout, never the numbers (VERDICT r1 weak #7).
    b=1000 also exercises the pad mask (1000 = 8·125, then b=1001 doesn't).
    """
    from dpcorr.sim import DETAIL_FIELDS

    for b in (1000, 1001):
        cfg = SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=b, seed=5)
        local = run_sim_one(cfg)
        sharded = run_detail_sharded(cfg, mesh=rep_mesh())
        for f in DETAIL_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(local.detail[f]), np.asarray(sharded.detail[f]),
                err_msg=f"field {f} at b={b}")


def test_summary_sharded_padded_b_mask(devices):
    """run_summary_sharded's pad mask: the psum'd summary at non-divisible
    B must match the local summary (padding reps contribute exactly 0)."""
    cfg = SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=1001, seed=5)
    summ = run_summary_sharded(cfg, mesh=rep_mesh())
    ref = run_sim_one(cfg).summary
    for meth in ("NI", "INT"):
        for k in ("mse", "bias", "var", "coverage", "ci_length"):
            np.testing.assert_allclose(summ[meth][k], ref[meth][k],
                                       rtol=5e-4, atol=1e-6), (meth, k)


def test_subset_mesh(devices):
    mesh = rep_mesh(4)
    assert mesh.devices.size == 4
    summ = run_summary_sharded(CFG, mesh=mesh)
    assert 0.0 <= summ["NI"]["coverage"] <= 1.0
