"""HRS real-data pipeline tests (reference real-data-sims.R).

Ground truths: counts from the panel itself, the non-private correlation
baseline, and the reference's statistical behavior (estimates bracket
ρ_np; CI width shrinks as ε grows).
"""

from __future__ import annotations

import numpy as np
import pytest

from dpcorr import hrs


@pytest.fixture(scope="module")
def cols():
    return hrs.load_panel()


@pytest.fixture(scope="module")
def point(cols):
    return hrs.point_estimates(cols=cols)


def test_wave_missingness(cols):
    df = hrs.wave_missingness(cols)
    assert len(df) == 16  # 16 waves (SURVEY.md Appendix B)
    w2 = df[df.wave == 2].iloc[0]
    assert w2.n == 45_234
    assert w2.complete == 19_433  # drives every downstream HRS number
    assert (df.complete <= df.n).all()


def test_extract_wave(cols):
    ids, age, bmi = hrs.extract_wave(cols, "2")
    assert age.shape == bmi.shape == ids.shape == (19_433,)
    assert not np.isnan(age).any() and not np.isnan(bmi).any()


def test_standardize_moments(cols):
    """Privately standardized variables have ≈0 mean / ≈1 sd at n≈20k with
    ε=0.1 DP moments, and λ bounds are the max standardized excursion."""
    _, age, bmi = hrs.extract_wave(cols, "2")
    std = hrs.standardize(age, bmi, hrs.HrsConfig())
    az = np.asarray(std.age_z)
    assert abs(az.mean()) < 0.05
    assert abs(az.std() - 1.0) < 0.1
    # clipped data can't exceed the λ bound derived from the same moments
    assert np.abs(az).max() <= std.lam_age + 1e-5
    # non-private baseline: age-BMI correlation in wave 2 is ≈ -0.19
    assert -0.25 < std.rho_np < -0.15


def test_point_estimates(point):
    for r in (point.ni, point.int_):
        assert -1.0 <= r["ci_low"] <= r["rho_hat"] <= r["ci_high"] <= 1.0
        # at ε=2 both methods land near the non-private truth
        assert abs(r["rho_hat"] - point.std.rho_np) < 0.15
    assert point.n == 19_433
    # λ/geometry block surfaced as in the reference printout
    # (real-data-sims.R:141-147, 244-252)
    assert point.ni["lambda_x"] == pytest.approx(point.std.lam_age)
    assert point.ni["m"] * point.ni["k"] <= point.n
    assert point.int_["lambda_sender"] == pytest.approx(point.std.lam_age)
    assert point.int_["delta_clip"] == pytest.approx(1.0 / point.n)


def test_point_estimates_deterministic(cols):
    a = hrs.point_estimates(cols=cols)
    b = hrs.point_estimates(cols=cols)
    assert a.ni == b.ni and a.int_ == b.int_


def test_eps_sweep_behavior(cols):
    summ = hrs.eps_sweep(cols=cols, eps_grid=[0.3, 2.0], reps=24)
    assert set(summ.method) == {"NI", "INT"}
    assert summ.attrs["rho_np"] == pytest.approx(-0.193, abs=0.02)
    for meth in ("NI", "INT"):
        s = summ[summ.method == meth].set_index("eps_corr")
        width = s.ci_high_mean - s.ci_low_mean
        assert width[0.3] > width[2.0]  # CIs shrink with budget
        # high-ε estimates concentrate near the non-private baseline
        assert abs(s.rho_hat_mean[2.0] - summ.attrs["rho_np"]) < 0.1
    runs = summ.attrs["runs"]
    assert len(runs) == 2 * 2 * 24


def test_bootstrap(cols):
    """Row-resampled bootstrap (BASELINE.md config 4): estimates center on
    the non-private baseline and the bootstrap percentile interval covers
    it; deterministic per seed."""
    df = hrs.bootstrap(cols=cols, reps=48, chunk=16)
    assert len(df) == 48
    s = df.attrs["summary"]
    rho_np = df.attrs["rho_np"]
    for meth in ("ni", "int"):
        assert abs(s[meth]["mean"] - rho_np) < 0.15
        assert s[meth]["q025"] <= rho_np + 0.05
        assert s[meth]["q975"] >= rho_np - 0.05
        assert s[meth]["sd"] > 0.0
    df2 = hrs.bootstrap(cols=cols, reps=48, chunk=16)
    assert np.allclose(df.ni_hat, df2.ni_hat)


def test_sweep_int_kernel_ulp_identical_to_static_path(cols):
    """The single-compile INT sweep kernel takes ε as a tracer but draws
    from the same named substreams with the same math as the static
    per-ε helper — outputs agree to float32 ulp noise (≤2 ulp, from
    traced-vs-constant folding differences in the arithmetic; the
    PRNG draws themselves are bit-equal). Anything beyond ulp noise
    means the traced path's stream layout forked from the
    estimator's."""
    import jax.numpy as jnp

    cfg = hrs.HrsConfig()
    _, age, bmi = hrs.extract_wave(cols, cfg.wave)
    std = hrs.standardize(age, bmi, cfg)
    n = int(age.shape[0])
    delta = 1.0 / n
    eps = 1.3
    lam_recv = float(hrs.lambda_receiver_from_noise(
        std.lam_age, std.lam_bmi, eps, delta))
    keys = hrs.rng.rep_keys(hrs.rng.master_key(11), 4)
    kern = hrs._sweep_int_kernel(
        keys, (std.age_z, std.bmi_z), jnp.float32(eps), std.lam_age,
        std.lam_bmi, jnp.float32(lam_recv), jnp.float32(delta),
        cfg.mixquant_mode, cfg.alpha)
    import numpy as np

    for i in range(4):
        r = hrs._int_once(keys[i], std.age_z, std.bmi_z, eps, std.lam_age,
                          std.lam_bmi, lam_recv, delta, cfg.alpha,
                          cfg.mixquant_mode)
        for got, want in ((kern[0][i], r.rho_hat), (kern[1][i], r.ci_low),
                          (kern[2][i], r.ci_high)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=4e-7, atol=6e-8)
