"""benchmarks.run_all emits well-formed JSON lines (driver-facing)."""

import json

from benchmarks import run_all


def _lines(capsys):
    return [json.loads(ln) for ln in capsys.readouterr().out.splitlines()]


def test_config1_emits_json(capsys):
    run_all.config1(False, b_override=16)
    (line,) = _lines(capsys)
    assert line["config"] == 1
    assert line["value"] > 0
    assert 0.0 <= line["detail"]["ni"]["coverage"] <= 1.0


def test_config2_emits_three_eps(capsys):
    run_all.config2(False, b_override=16)
    lines = _lines(capsys)
    assert [l["detail"]["eps"] for l in lines] == [0.5, 1.0, 2.0]
    assert all(l["config"] == 2 for l in lines)


def test_main_rejects_unknown_config(capsys):
    import pytest

    with pytest.raises(SystemExit):
        run_all.main(["--config", "9"])


def _parser_flags(mod):
    """All option strings of a benchmarks script's argparse parser,
    collected by intercepting parse_args (the parser is built inside
    main(), before any heavy import)."""
    import argparse

    flags: set[str] = set()
    old_parse = argparse.ArgumentParser.parse_args

    def grab(self, args=None, namespace=None):
        for a in self._actions:
            flags.update(a.option_strings)
        raise SystemExit(0)

    argparse.ArgumentParser.parse_args = grab
    try:
        try:
            mod.main()
        except SystemExit:
            pass
    finally:
        argparse.ArgumentParser.parse_args = old_parse
    return flags


def test_queue_scripts_importable_and_documented():
    """The unattended r05 queue (tpu_r05_queue.sh) invokes these scripts
    with specific flags; an import error or a renamed flag would silently
    burn the round's first healthy-tunnel window. Pin the contract."""
    from benchmarks import acceptance_point2, multihost_scaling

    for mod, flags in ((acceptance_point2,
                        {"--n", "--eps", "--log2b", "--out", "--platform"}),
                       (multihost_scaling,
                        {"--b", "--n-hosts", "--out", "--platform"})):
        assert flags <= _parser_flags(mod), mod.__name__


def test_queue_script_invokes_real_flags():
    """Every --flag the r05 queue passes to a benchmarks/ python script
    must exist in that script's ACTUAL parser (derived live, not a
    hand-maintained list — same class of guard as
    test_backend_r_call_contract for the R seam)."""
    import re
    from pathlib import Path

    from benchmarks import acceptance_point2, grid_merge_tpu

    repo = Path(__file__).parent.parent
    sh = (repo / "benchmarks" / "tpu_r05_queue.sh").read_text()
    for script, mod in (("acceptance_point2.py", acceptance_point2),
                        ("grid_merge_tpu.py", grid_merge_tpu)):
        valid = _parser_flags(mod)
        assert valid, script
        found = 0
        for m in re.finditer(re.escape(script) + r"(.*?)(?:2>|\|)",
                             sh, re.S):
            found += 1
            used = set(re.findall(r"(--[a-z0-9-]+)", m.group(1)))
            assert used <= valid, (script, used - valid)
        assert found, f"{script} not invoked by the queue?"


def test_harvest_rejects_degraded_headline(tmp_path):
    """harvest_r05.sh must never bank a degraded CPU-fallback bench line
    as r05_tpu_headline.json (bench.py cites that file back as
    'recorded_tpu_evidence' — banking a degraded line would be circular).
    Run the real script against fixture dirs both ways."""
    import json
    import subprocess
    from pathlib import Path

    repo = Path(__file__).parent.parent
    fix_in = tmp_path / "in"
    fix_out = tmp_path / "out"
    fix_in.mkdir()
    fix_out.mkdir()
    env = {"TPU_R05_IN": str(fix_in), "TPU_R05_OUT": str(fix_out),
           "PATH": "/usr/bin:/bin"}

    degraded = {"metric": "m", "value": 2018.0, "unit": "reps/sec/chip",
                "detail": {"degraded": "tpu-init-failed",
                           "paths": {"xla": {"reps_per_sec": 2018.0}}}}
    (fix_in / "bench_default.json").write_text(json.dumps(degraded))
    subprocess.run(["bash", str(repo / "benchmarks" / "harvest_r05.sh")],
                   capture_output=True, text=True, env=env, cwd=repo)
    assert not (fix_out / "r05_tpu_headline.json").exists()

    clean = {"metric": "m", "value": 981783.0, "unit": "reps/sec/chip",
             "detail": {"device": "TPU_0",
                        "paths": {"xla": {"reps_per_sec": 981783.0}}}}
    (fix_in / "bench_default.json").write_text(json.dumps(clean))
    subprocess.run(["bash", str(repo / "benchmarks" / "harvest_r05.sh")],
                   capture_output=True, text=True, env=env, cwd=repo)
    banked = fix_out / "r05_tpu_headline.json"
    assert banked.exists()
    assert json.loads(banked.read_text())["value"] == 981783.0


def test_queue_resume_semantics(tmp_path):
    """The r05 queue's wedge-resume contract (bash functions sourced with
    a stubbed probe): ok-marked steps skip, a failure with the tunnel
    alive marks .fail and continues, a failure with the tunnel dead sets
    WEDGED and suppresses every later step; finished() requires a
    terminal marker per step. Wedges normally leave no marker (retried
    on next recovery) — EXCEPT for MOSAIC_STEPS members, where the third
    wedge on the same step trips a cap and writes .fail (the step is
    classified as the wedge's cause; see tpu_r05_queue.sh header)."""
    import subprocess
    from pathlib import Path

    repo = Path(__file__).parent.parent
    script = f"""
set -u
export TPU_R05_IN={tmp_path}
export TPU_R05_PROBE=true
source {repo}/benchmarks/tpu_r05_queue.sh

MOSAIC_STEPS="s3"              # s3 plays a Mosaic-risky step; s5 pure-XLA

run_step s1 true
run_step s2 false              # fails, probe says alive -> .fail
run_step s1 false              # .ok marker -> must skip (cmd not run)
export TPU_R05_PROBE=false
run_step s3 false              # fails, probe dead -> wedge, no marker
run_step s4 true               # suppressed by WEDGED (no marker)
echo "WEDGED=$WEDGED"
WEDGED=0                       # simulate the next recovery pass
run_step s5 false              # XLA step wedges...
WEDGED=0
run_step s5 false              # ...twice...
WEDGED=0
run_step s5 false              # ...thrice: NOT capped, still no marker
WEDGED=0
run_step s3 false              # second wedge on s3: below cap, no marker
WEDGED=0
run_step s3 false              # third wedge on s3 -> capped, .fail
echo "WEDGED2=$WEDGED"
STEP_NAMES="s1 s2"; finished && echo "fin12=yes" || echo "fin12=no"
STEP_NAMES="s1 s3"; finished && echo "fin13=yes" || echo "fin13=no"
STEP_NAMES="s1 s5"; finished && echo "fin15=yes" || echo "fin15=no"
"""
    r = subprocess.run(["bash", "-c", script], capture_output=True,
                       text=True, cwd=repo)
    assert r.returncode == 0, r.stderr[-500:]
    assert (tmp_path / "s1.ok").exists()
    assert (tmp_path / "s2.fail").exists()
    assert not (tmp_path / "s3.ok").exists()
    assert not (tmp_path / "s4.ok").exists()     # suppressed
    assert "WEDGED=1" in r.stdout
    # early wedges leave no terminal marker (retried on recovery); the
    # THIRD wedge on a MOSAIC_STEPS member trips the cap -> .fail, so a
    # deterministically-wedging Mosaic compile cannot livelock the queue
    assert (tmp_path / "s3.wedges").read_text().strip() == "3"
    assert (tmp_path / "s3.fail").exists()
    # ...but a pure-XLA step is NEVER capped: tunnel wedges during long
    # XLA runs are load-induced flakiness, not the step's fault
    assert not (tmp_path / "s5.fail").exists()
    assert not (tmp_path / "s5.wedges").exists()
    assert "WEDGED2=1" in r.stdout
    assert "fin12=yes" in r.stdout               # ok + fail = terminal
    assert "fin13=yes" in r.stdout               # capped wedge is terminal
    assert "fin15=no" in r.stdout                # uncapped wedge retried
    assert "s1: already done" in r.stdout


def test_trace_summary_on_checked_in_r04_trace():
    """PERFORMANCE.md's device-residency claims must stay reproducible
    from the committed r04 trace: `python -m benchmarks.trace_summary
    benchmarks/results/trace_r04`. Pin the shape and the headline
    facts (program-dominant window, one fusion >half of program time)
    rather than exact ms, so a future trace recapture only has to keep
    the qualitative structure."""
    from pathlib import Path

    from benchmarks.trace_summary import summarize_trace

    repo = Path(__file__).parent.parent
    s = summarize_trace(str(repo / "benchmarks" / "results" / "trace_r04"))
    assert s["window_ms"] > 0
    assert 0.5 < s["device_busy_frac"] <= 1.0
    assert s["top_ops"], "no XLA ops classified in the trace"
    # the measured shape PERFORMANCE.md cites: a single elementwise
    # fusion owns the majority of program time
    top = s["top_ops"][0]
    assert "fusion" in top["name"]
    assert top["frac_of_program"] > 0.5
    # fractions are consistent: top ops cannot exceed program time
    assert sum(op["ms"] for op in s["top_ops"]) <= s["program_ms"] * 1.01


def test_trace_summary_missing_dir_raises(tmp_path):
    import pytest

    from benchmarks.trace_summary import find_trace_file

    with pytest.raises(FileNotFoundError):
        find_trace_file(str(tmp_path))


def test_summarize_spans_roundtrip(tmp_path):
    """Span JSONL -> per-name aggregates: counts, totals and the
    serving stack's nearest-rank percentiles (ISSUE 2 satellite)."""
    from benchmarks.trace_summary import summarize_spans
    from dpcorr.obs import Tracer

    path = str(tmp_path / "spans.jsonl")
    tr = Tracer(path)
    for _ in range(5):
        with tr.span("serve.flush"):
            pass
    with tr.span("serve.kernel"):
        pass
    s = summarize_spans(path)
    assert s["spans"] == 6
    assert s["names"]["serve.flush"]["count"] == 5
    assert s["names"]["serve.kernel"]["count"] == 1
    for row in s["names"].values():
        assert 0 <= row["p50_s"] <= row["p99_s"]
        assert row["total_s"] >= row["p99_s"]

    # pre-loaded span lists skip the file read; values reduce exactly
    spans = [{"name": "a", "dur_s": d} for d in (1.0, 2.0, 3.0, 4.0)]
    s2 = summarize_spans(spans)
    assert s2["names"]["a"] == {"count": 4, "total_s": 10.0,
                                "p50_s": 2.0, "p99_s": 4.0}

    # strict input: a corrupt line fails loudly (the CI artifact gate)
    bad = tmp_path / "bad.jsonl"
    bad.write_text("{}{\n")
    import pytest

    with pytest.raises(ValueError):
        summarize_spans(str(bad))
