"""benchmarks.run_all emits well-formed JSON lines (driver-facing)."""

import json

from benchmarks import run_all


def _lines(capsys):
    return [json.loads(ln) for ln in capsys.readouterr().out.splitlines()]


def test_config1_emits_json(capsys):
    run_all.config1(False, b_override=16)
    (line,) = _lines(capsys)
    assert line["config"] == 1
    assert line["value"] > 0
    assert 0.0 <= line["detail"]["ni"]["coverage"] <= 1.0


def test_config2_emits_three_eps(capsys):
    run_all.config2(False, b_override=16)
    lines = _lines(capsys)
    assert [l["detail"]["eps"] for l in lines] == [0.5, 1.0, 2.0]
    assert all(l["config"] == 2 for l in lines)


def test_main_rejects_unknown_config(capsys):
    import pytest

    with pytest.raises(SystemExit):
        run_all.main(["--config", "9"])
