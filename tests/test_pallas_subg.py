"""Fused subG Pallas kernel vs the XLA estimators (grid variant).

Off-TPU the kernel runs under the TPU interpreter with external uniforms
(the on-chip PRNG path is validated on hardware, like the sign kernel —
tests/test_pallas_ni.py has the rationale). Acceptance is statistical:
different PRNG stream, same distributions (SURVEY.md §5 RNG).
"""

import jax
import jax.numpy as jnp
import numpy as np

from dpcorr.ops.pallas_subg import (
    n_uniform_rows_subg,
    sim_detail_subg_pallas,
    use_subg_pallas,
)
from dpcorr.sim import DETAIL_FIELDS, SimConfig, run_sim_one
from dpcorr.utils import rng

N, RHO = 1024, 0.5


def _uniforms(key, n, b, eps1=1.0, eps2=1.0):
    return jax.random.uniform(
        key, (b, n_uniform_rows_subg(n, eps1, eps2), 128),
        jnp.float32, minval=1e-7, maxval=1.0 - 1e-7)


def _detail(raw):
    return dict(zip(DETAIL_FIELDS, [np.asarray(a) for a in raw],
                    strict=True))


def _xla_summary(b, eps1=1.0, eps2=1.0):
    return run_sim_one(SimConfig(n=N, rho=RHO, eps1=eps1, eps2=eps2, b=b,
                                 dgp="bounded_factor",
                                 use_subg=True)).summary


def test_fused_subg_statistics():
    """NI/INT detail columns match the XLA subG simulator within MC error
    (ver-cor-subG.R:174-198 hot-loop body)."""
    b = 512
    u = _uniforms(rng.master_key(31), N, b)
    d = _detail(sim_detail_subg_pallas(np.arange(b, dtype=np.int32), RHO,
                                       N, 1.0, 1.0, uniforms=u))
    xla = _xla_summary(b)
    for a in d.values():
        assert np.isfinite(a).all()
    assert abs(d["ni_hat"].mean() - RHO - xla["NI"]["bias"]) < 0.05
    assert abs(d["ni_cover"].mean() - xla["NI"]["coverage"]) < 0.06
    assert 0.5 < d["ni_se2"].mean() / xla["NI"]["mse"] < 2.0
    assert abs(d["int_hat"].mean() - RHO - xla["INT"]["bias"]) < 0.05
    assert abs(d["int_cover"].mean() - xla["INT"]["coverage"]) < 0.06
    assert 0.5 < d["int_se2"].mean() / xla["INT"]["mse"] < 2.0
    # det-mixquant width is a near-deterministic function of sd(Uc)
    assert 0.9 < d["int_ci_len"].mean() / xla["INT"]["ci_length"] < 1.1
    # ρ-space clamp is ONE-SIDED per end (ver-cor-subG.R:58-59): lo is
    # floored at −1, hi capped at 1, so an estimate far outside [−1, 1]
    # yields an inverted (never-covering) interval — faithful to the
    # reference, so assert exactly that contract, not lo ≤ hi
    inverted = d["ni_low"] > d["ni_up"]
    assert (d["ni_cover"][inverted] == 0.0).all()


def test_fused_subg_per_rep_rho():
    """ρ rides per-replication for the bucketed grid's flattened axis."""
    b = 256
    rhos = np.concatenate([np.zeros(b), np.full(b, 0.8)]).astype(np.float32)
    u = _uniforms(rng.master_key(32), N, 2 * b)
    d = _detail(sim_detail_subg_pallas(np.arange(2 * b, dtype=np.int32),
                                       rhos, N, 1.0, 1.0, uniforms=u))
    assert abs(d["ni_hat"][:b].mean() - 0.0) < 0.06
    assert abs(d["ni_hat"][b:].mean() - 0.8) < 0.06
    assert abs(d["int_hat"][:b].mean() - 0.0) < 0.06
    assert abs(d["int_hat"][b:].mean() - 0.8) < 0.06


def test_fused_subg_padded_m():
    """ε = (1.5, 0.5) ⇒ m = 11 → m' = 16 padded lane groups, sender = X."""
    eps1, eps2 = 1.5, 0.5
    assert use_subg_pallas(N, eps1, eps2)
    b = 384
    u = _uniforms(rng.master_key(33), N, b, eps1, eps2)
    d = _detail(sim_detail_subg_pallas(np.arange(b, dtype=np.int32), RHO,
                                       N, eps1, eps2, uniforms=u))
    xla = _xla_summary(b, eps1, eps2)
    assert np.isfinite(d["ni_hat"]).all()
    # NI variance is large at this ε-pair (noise scale 2λ/(mε), m=11) —
    # bound the mean diff by 4·SE of the two-stream difference
    se_diff = np.sqrt(2.0 * xla["NI"]["var"] / b)
    assert abs(d["ni_hat"].mean() - RHO - xla["NI"]["bias"]) < 4 * se_diff
    assert abs(d["int_cover"].mean() - xla["INT"]["coverage"]) < 0.08
    assert 0.9 < d["int_ci_len"].mean() / xla["INT"]["ci_length"] < 1.1


def test_fused_subg_deterministic_in_uniforms():
    u = _uniforms(rng.master_key(34), N, 64)
    seeds = np.arange(64, dtype=np.int32)
    a = _detail(sim_detail_subg_pallas(seeds, RHO, N, 1.0, 1.0, uniforms=u))
    b = _detail(sim_detail_subg_pallas(seeds, RHO, N, 1.0, 1.0, uniforms=u))
    for f in DETAIL_FIELDS:
        np.testing.assert_array_equal(a[f], b[f])
