"""Simulator tests + golden statistical acceptance.

The reference validates itself statistically (SURVEY.md §4): empirical CI
coverage vs nominal 0.95 with known-truth DGPs, MSE/bias tracking. R is not
available in this image, so the acceptance here is coverage-vs-nominal
within Monte-Carlo error — the same oracle the reference's plots use
(dashed 0.95 line, vert-cor.R:687)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dpcorr.sim import DETAIL_FIELDS, SimConfig, run_sim_one
from dpcorr.utils import rng


def _coverage_bounds(b, p=0.95, z=3.5):
    se = np.sqrt(p * (1 - p) / b)
    return p - z * se, min(p + z * se, 1.0)


class TestRunSimOne:
    def test_detail_shapes_and_fields(self):
        cfg = SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=50)
        res = run_sim_one(cfg)
        assert set(res.detail) == set(DETAIL_FIELDS)
        for v in res.detail.values():
            assert v.shape == (50,)

    def test_deterministic_given_seed(self):
        cfg = SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=20, seed=7)
        a, b = run_sim_one(cfg), run_sim_one(cfg)
        np.testing.assert_array_equal(a.detail["ni_hat"], b.detail["ni_hat"])
        c = run_sim_one(SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=20, seed=8))
        assert not np.array_equal(a.detail["ni_hat"], c.detail["ni_hat"])

    def test_chunking_invariant(self):
        base = dict(n=400, rho=0.2, eps1=1.0, eps2=1.0, b=10)
        a = run_sim_one(SimConfig(**base, chunk_size=4))   # pads 10 -> 12
        b = run_sim_one(SimConfig(**base, chunk_size=100))
        np.testing.assert_allclose(
            np.asarray(a.detail["int_hat"]), np.asarray(b.detail["int_hat"]),
            rtol=1e-6)

    def test_summary_consistent_with_detail(self):
        cfg = SimConfig(n=500, rho=0.3, eps1=1.0, eps2=1.0, b=64)
        res = run_sim_one(cfg)
        d = res.detail
        np.testing.assert_allclose(
            res.summary["NI"]["coverage"], float(jnp.mean(d["ni_cover"])), rtol=1e-6)
        np.testing.assert_allclose(
            res.summary["INT"]["mse"], float(jnp.mean(d["int_se2"])), rtol=1e-6)
        rows = res.summary_rows()
        assert [r["method"] for r in rows] == ["NI", "INT"]

    def test_summary_se2_matches_hat(self):
        cfg = SimConfig(n=500, rho=0.4, eps1=1.0, eps2=1.0, b=32)
        res = run_sim_one(cfg)
        np.testing.assert_allclose(
            np.asarray(res.detail["ni_se2"]),
            (np.asarray(res.detail["ni_hat"]) - 0.4) ** 2, rtol=1e-5)


class TestGoldenCoverage:
    """Coverage within MC error of nominal 0.95 on known-truth DGPs."""

    @pytest.mark.parametrize("rho", [0.0, 0.5])
    def test_sign_pipeline_gaussian(self, rho):
        b = 400
        cfg = SimConfig(n=2000, rho=rho, eps1=1.0, eps2=1.0, b=b)
        res = run_sim_one(cfg)
        lo, hi = _coverage_bounds(b)
        for meth in ("NI", "INT"):
            cov = res.summary[meth]["coverage"]
            assert lo <= cov <= hi, (meth, rho, cov)
            assert abs(res.summary[meth]["bias"]) < 0.06

    def test_subg_real_variant_pipeline(self):
        """subg_variant='real' routes the v2 estimator pair (randomized
        batches + enforce_min_k, ci_int_subg variant='real') through the
        simulator; coverage stays statistically sane and differs from the
        grid variant (different construction)."""
        b = 400
        base = dict(n=2000, rho=0.5, eps1=1.0, eps2=1.0, b=b,
                    dgp="bounded_factor", use_subg=True)
        real = run_sim_one(SimConfig(**base, subg_variant="real"))
        grid = run_sim_one(SimConfig(**base))
        lo, hi = _coverage_bounds(b, z=4.0)
        assert lo <= real.summary["NI"]["coverage"] <= hi
        # different constructions: the v2 receiver clip
        # (lambda_receiver_from_noise ≈ 194 at these params vs the grid
        # rule's 30) reshapes the INT CI — widths must differ materially
        r_len = real.summary["INT"]["ci_length"]
        g_len = grid.summary["INT"]["ci_length"]
        assert abs(r_len - g_len) > 0.05 * g_len
        with pytest.raises(ValueError, match="streaming"):
            SimConfig(**base, subg_variant="real", stream_n_chunk=512)
        with pytest.raises(ValueError, match="subg_variant"):
            SimConfig(**base, subg_variant="bogus")

    def test_sign_pipeline_rbg_prng(self):
        """The rbg key implementation (the bench's cheap-PRNG TPU variant)
        must produce the same statistics as threefry — acceptance is
        statistical, like the R→JAX RNG switch itself (SURVEY.md §5)."""
        from dpcorr.utils import rng

        b = 400
        cfg = SimConfig(n=2000, rho=0.5, eps1=1.0, eps2=1.0, b=b)
        res = run_sim_one(cfg, key=rng.master_key(impl="rbg"))
        lo, hi = _coverage_bounds(b)
        for meth in ("NI", "INT"):
            cov = res.summary[meth]["coverage"]
            assert lo <= cov <= hi, (meth, cov)
            assert abs(res.summary[meth]["bias"]) < 0.06

    def test_subg_pipeline_bounded_factor(self):
        b = 400
        cfg = SimConfig(n=4000, rho=0.5, eps1=1.0, eps2=1.0, b=b,
                        dgp="bounded_factor", use_subg=True)
        res = run_sim_one(cfg)
        lo, hi = _coverage_bounds(b)
        for meth in ("NI", "INT"):
            cov = res.summary[meth]["coverage"]
            assert lo <= cov <= hi, (meth, cov)
            assert abs(res.summary[meth]["bias"]) < 0.06

    def test_mse_decreases_with_n(self):
        # the reference's fig3 contract: MSE falls as n grows
        mses = []
        for n in (500, 4000):
            cfg = SimConfig(n=n, rho=0.5, eps1=1.0, eps2=1.0, b=200, seed=3)
            mses.append(run_sim_one(cfg).summary["NI"]["mse"])
        assert mses[1] < mses[0]

    def test_mc_mixquant_coverage_matches_det(self):
        # Appendix A #4 substitution check: deterministic mixture quantile
        # must not shift coverage beyond MC error vs the reference's MC one
        b = 300
        base = dict(n=2000, rho=0.5, eps1=1.0, eps2=1.0, b=b)
        det = run_sim_one(SimConfig(**base, mixquant_mode="det"))
        mc = run_sim_one(SimConfig(**base, mixquant_mode="mc"))
        diff = abs(det.summary["INT"]["coverage"] - mc.summary["INT"]["coverage"])
        assert diff < 0.05, diff


def test_stress_chunk_size_policy():
    """The streaming stress path's replication width: wide on TPU,
    sequential on CPU (measured 2026-07-31: chunk 1 is 1.7x the old b//8
    rule at n=1e6 with the fused subG pair — interleaved scan states
    evict each other's cache lines)."""
    from dpcorr.sim import stress_chunk_size

    assert stress_chunk_size(256, on_tpu=False) == 1
    assert stress_chunk_size(256, on_tpu=True) == 32
    assert stress_chunk_size(8, on_tpu=True) == 8
