"""RDS reader tests: synthetic streams from a minimal in-test writer,
plus schema checks against the real HRS panel (SURVEY.md Appendix B).

The same fixtures exercise every available backend (pure-Python and, once
built, the native C++ reader) so their output contracts stay identical.
"""

from __future__ import annotations

import gzip
import struct

import numpy as np
import pytest

from dpcorr.io import rds_py

HRS_PATH = "/root/reference/hrs_long_panel.rds"


# ---------------------------------------------------------------- writer ----
class W:
    """Minimal RDS (XDR v3) writer — just enough to build test fixtures."""

    def __init__(self):
        self.out = bytearray(b"X\n")
        self.i32(3)          # version 3
        self.i32(0x040202)   # writer R 4.2.2
        self.i32(0x030500)   # min reader 3.5.0
        enc = b"UTF-8"
        self.i32(len(enc)); self.out += enc

    def i32(self, v):
        self.out += struct.pack(">i", v)

    def f64(self, v):
        self.out += struct.pack(">d", v)

    def flags(self, t, has_attr=False, has_tag=False, levels=0):
        self.i32(t | (0x200 if has_attr else 0) | (0x400 if has_tag else 0)
                 | (levels << 12))

    def charsxp(self, s):
        if s is None:
            self.flags(rds_py.CHARSXP, levels=0)
            self.i32(-1)
        else:
            b = s.encode()
            self.flags(rds_py.CHARSXP, levels=0x8)  # UTF-8 bit
            self.i32(len(b)); self.out += b

    def strsxp(self, items, has_attr=False):
        self.flags(rds_py.STRSXP, has_attr)
        self.i32(len(items))
        for s in items:
            self.charsxp(s)

    def realsxp(self, vals, has_attr=False):
        self.flags(rds_py.REALSXP, has_attr)
        self.i32(len(vals))
        for v in vals:
            if v is None:
                self.out += struct.pack(">Q", rds_py.R_NA_REAL_BITS)
            else:
                self.f64(v)

    def intsxp(self, vals, has_attr=False):
        self.flags(rds_py.INTSXP, has_attr)
        self.i32(len(vals))
        for v in vals:
            self.i32(rds_py.R_NA_INT if v is None else v)

    def sym(self, name):
        self.flags(rds_py.SYMSXP)
        self.charsxp(name)

    def attr_list(self, pairs):
        """pairs: list of (name, emit_value_callable)."""
        for i, (name, emit) in enumerate(pairs):
            self.flags(rds_py.LISTSXP, has_tag=True)
            self.sym(name)
            emit()
        self.i32(rds_py.NILVALUE_SXP)

    def nil(self):
        self.i32(rds_py.NILVALUE_SXP)

    def bytes(self):
        return bytes(self.out)


def _parse(buf: bytes):
    rd = rds_py._Reader(buf)
    rd.header()
    return rd.item()


# ------------------------------------------------------------- fixtures ----
def test_real_vector_with_na():
    w = W()
    w.realsxp([1.5, None, -2.0])
    obj = _parse(w.bytes())
    assert obj.type == rds_py.REALSXP
    assert obj.data[0] == 1.5 and obj.data[2] == -2.0
    assert rds_py.real_is_na(obj.data).tolist() == [False, True, False]


def test_int_vector_na_decode():
    w = W()
    w.intsxp([7, None, -3])
    obj = _parse(w.bytes())
    dec = rds_py.decode_int(obj.data)
    assert dec[0] == 7.0 and dec[2] == -3.0 and np.isnan(dec[1])


def test_string_vector_with_na():
    w = W()
    w.strsxp(["a", None, "ζ"])
    obj = _parse(w.bytes())
    assert obj.data == ["a", None, "ζ"]


def test_named_list_dataframe_roundtrip():
    """A 2-column tibble: x double, f factor — the HRS shape in miniature."""
    w = W()
    w.flags(rds_py.VECSXP, has_attr=True)
    w.i32(2)
    w.realsxp([1.0, 2.0, None])
    # factor column: int codes + levels + class
    w.intsxp([1, 2, 1], has_attr=True)
    w.attr_list([
        ("levels", lambda: w.strsxp(["lo", "hi"])),
        ("class", lambda: w.strsxp(["factor"])),
    ])
    # data.frame attributes
    w.attr_list([
        ("names", lambda: w.strsxp(["x", "f"])),
        ("row.names", lambda: w.intsxp([None, -3])),
        ("class", lambda: w.strsxp(["tbl_df", "tbl", "data.frame"])),
    ])
    import os
    import tempfile
    buf = w.bytes()
    with tempfile.NamedTemporaryFile(suffix=".rds", delete=False) as f:
        f.write(gzip.compress(buf))
        path = f.name
    try:
        cols = rds_py.read_rds_table(path)
    finally:
        os.unlink(path)
    assert list(cols) == ["x", "f"]
    assert cols["x"].kind == "double"
    assert np.isnan(cols["x"].values[2])
    assert cols["f"].kind == "factor"
    assert cols["f"].levels == ["lo", "hi"]
    assert cols["f"].values.tolist() == [1.0, 2.0, 1.0]


def test_symbol_reference_table():
    """The second occurrence of a symbol is a REFSXP back-reference."""
    w = W()
    w.flags(rds_py.VECSXP, has_attr=True)
    w.i32(2)
    w.realsxp([1.0], has_attr=True)
    w.attr_list([("foo", lambda: w.realsxp([9.0]))])
    w.realsxp([2.0], has_attr=True)
    # "foo" again — as a reference (index 1, packed in flags)
    w.flags(rds_py.LISTSXP, has_tag=True)
    w.i32((1 << 8) | rds_py.REFSXP)
    w.realsxp([10.0])
    w.nil()
    w.attr_list([("names", lambda: w.strsxp(["a", "b"]))])
    obj = _parse(w.bytes())
    assert obj.data[0].attr("foo").data[0] == 9.0
    assert obj.data[1].attr("foo").data[0] == 10.0


def test_altrep_compact_intseq():
    w = W()
    w.flags(rds_py.ALTREP_SXP)
    # info pairlist: class sym, package sym, type int
    w.flags(rds_py.LISTSXP, has_tag=False)
    w.sym("compact_intseq")
    w.flags(rds_py.LISTSXP)
    w.sym("base")
    w.flags(rds_py.LISTSXP)
    w.intsxp([13])
    w.nil()
    # state: c(n, start, step); attr: NULL
    w.realsxp([5.0, 10.0, 1.0])
    w.nil()
    obj = _parse(w.bytes())
    assert obj.data.tolist() == [10, 11, 12, 13, 14]


def test_altrep_wrap_real_cons_state():
    """R serializes wrap_* ALTREP state as CONS(wrapped, metadata) — a
    pairlist, not a VECSXP."""
    w = W()
    w.flags(rds_py.ALTREP_SXP)
    w.flags(rds_py.LISTSXP)
    w.sym("wrap_real")
    w.flags(rds_py.LISTSXP)
    w.sym("base")
    w.flags(rds_py.LISTSXP)
    w.intsxp([14])
    w.nil()
    # state: CONS(wrapped REALSXP, metadata INTSXP) — untagged pairlist
    w.flags(rds_py.LISTSXP)
    w.realsxp([3.5, -1.0])
    w.flags(rds_py.LISTSXP)
    w.intsxp([0, 0])
    w.nil()
    w.nil()  # attr
    obj = _parse(w.bytes())
    assert obj.type == rds_py.REALSXP
    assert obj.data.tolist() == [3.5, -1.0]


@pytest.mark.parametrize("mod", ["gzip", "bz2", "lzma"])
def test_compression_flavors(mod, tmp_path):
    """saveRDS supports gzip, bzip2, and xz compression; sniff all three."""
    import importlib

    w = W()
    w.realsxp([1.0, 2.0, 3.0])
    comp = importlib.import_module(mod)
    path = tmp_path / f"x_{mod}.rds"
    path.write_bytes(comp.compress(w.bytes()))
    obj = rds_py.read_rds(str(path))
    assert obj.data.tolist() == [1.0, 2.0, 3.0]


def test_haven_labelled_column():
    w = W()
    w.realsxp([1.0, 2.0], has_attr=True)
    w.attr_list([
        ("labels", lambda: (w.realsxp([1.0, 2.0], has_attr=True),
                            w.attr_list([("names",
                                          lambda: w.strsxp(["yes", "no"]))]))),
        ("class", lambda: w.strsxp(["haven_labelled", "vctrs_vctr", "double"])),
    ])
    col = rds_py._decode_column("h", _parse(w.bytes()))
    assert col.kind == "double"
    assert col.labels == {"yes": 1.0, "no": 2.0}


# ------------------------------------------------- real file (appendix B) ----
@pytest.fixture(scope="module")
def hrs_cols():
    return rds_py.read_rds_table(HRS_PATH)


def _columns_equal(a, b):
    assert a.kind == b.kind and a.levels == b.levels and a.label == b.label
    if (a.labels is None) != (b.labels is None):
        raise AssertionError("labels presence differs")
    if a.labels is not None:
        assert list(a.labels) == list(b.labels)
        assert np.allclose(list(a.labels.values()), list(b.labels.values()),
                           equal_nan=True)
    if a.kind == "string":
        assert a.values == b.values
    else:
        assert np.array_equal(np.asarray(a.values), np.asarray(b.values),
                              equal_nan=True)


def test_native_python_parity(hrs_cols):
    """The C++ reader and the Python reader must be byte-identical on every
    column of the real panel (same NA placement, levels, labels)."""
    from dpcorr.io import rds as rds_mod

    if rds_mod._ensure_native() is None:
        pytest.skip("native RDS reader not available")
    native = rds_mod.read_rds_table(HRS_PATH)
    assert list(native) == list(hrs_cols)
    for name in native:
        _columns_equal(native[name], hrs_cols[name])


def test_hrs_schema(hrs_cols):
    assert list(hrs_cols) == ["hhidpn", "wave", "cenreg", "cendiv", "urbrur",
                              "agey_e", "bmi", "hearte"]
    assert len(hrs_cols["wave"].values) == 723_744
    assert hrs_cols["cenreg"].kind == "factor"
    assert hrs_cols["cenreg"].levels == ["Northeast", "Midwest", "South", "West"]
    assert hrs_cols["agey_e"].kind == "double"
    assert hrs_cols["urbrur"].labels is not None


def test_hrs_wave2_complete_cases(hrs_cols):
    """Wave-2 complete-case count drives every downstream HRS number
    (real-data-sims.R:38-41)."""
    wave = np.asarray(hrs_cols["wave"].values, dtype=object)
    m = wave == "2"
    age = hrs_cols["agey_e"].values[m]
    bmi = hrs_cols["bmi"].values[m]
    ok = ~np.isnan(age) & ~np.isnan(bmi)
    assert m.sum() > 0 and 0 < ok.sum() <= m.sum()
    # sanity: plausible human ranges on complete cases
    assert 20 < np.nanmean(age[ok]) < 110
    assert 10 < np.nanmean(bmi[ok]) < 60


# ---------------------------------------------------------------- writer ----
class TestRdsWriter:
    """write_rds_table round-trips through BOTH independent readers (the
    pure-Python parser and, when buildable, the native C++ one) — the
    write-side mirror of the saveRDS contract (vert-cor.R:569)."""

    def _table(self):
        return {
            "repl": np.arange(1, 6, dtype=np.int64),
            "ni_hat": np.asarray([0.1, -0.2, np.nan, 0.4, 0.5]),
            "ni_cover": np.asarray([True, False, True, True, False]),
            "method": ["NI", "NI", None, "INT", "INT"],
            "big": np.asarray([2**40, 0, 1, -2**40, 7], dtype=np.int64),
        }

    def _check(self, cols):
        np.testing.assert_array_equal(cols["repl"].values,
                                      [1.0, 2.0, 3.0, 4.0, 5.0])
        got = cols["ni_hat"].values
        np.testing.assert_allclose(got[[0, 1, 3, 4]], [0.1, -0.2, 0.4, 0.5])
        assert np.isnan(got[2])
        np.testing.assert_array_equal(cols["ni_cover"].values,
                                      [1.0, 0.0, 1.0, 1.0, 0.0])
        assert cols["method"].values == ["NI", "NI", None, "INT", "INT"]
        # 64-bit ints overflow R's 32-bit INTSXP -> promoted to doubles
        assert cols["big"].kind == "double"
        np.testing.assert_array_equal(cols["big"].values,
                                      [2.0**40, 0.0, 1.0, -(2.0**40), 7.0])

    @pytest.mark.parametrize("compress", [True, False])
    def test_roundtrip_python_reader(self, tmp_path, compress):
        from dpcorr.io.rds_write import write_rds_table

        p = str(tmp_path / "t.rds")
        write_rds_table(p, self._table(), compress=compress)
        self._check(rds_py.read_rds_table(p))

    def test_roundtrip_native_reader(self, tmp_path):
        from dpcorr.io import rds as rds_front
        from dpcorr.io.rds_write import write_rds_table

        if rds_front._ensure_native() is None:
            pytest.skip("native reader not buildable here")
        p = str(tmp_path / "t.rds")
        write_rds_table(p, self._table())
        self._check(rds_front.read_rds_table(p))

    def test_deterministic_bytes(self, tmp_path):
        from dpcorr.io.rds_write import write_rds_table

        a, b = str(tmp_path / "a.rds"), str(tmp_path / "b.rds")
        write_rds_table(a, self._table())
        write_rds_table(b, self._table())
        assert open(a, "rb").read() == open(b, "rb").read()

    def test_object_numerics_never_stringify(self, tmp_path):
        """Plain number lists and pandas nullable columns must round-trip
        numerically (review finding): strings only for actual strings."""
        import pandas as pd

        from dpcorr.io.rds_write import write_rds_table

        p = str(tmp_path / "o.rds")
        write_rds_table(p, {
            "ints": [1, 2, 3],
            "nullable_i": pd.array([1, None, 3], dtype="Int64").to_numpy(),
            "nullable_b": pd.array([True, None, False],
                                   dtype="boolean").to_numpy(),
        })
        cols = rds_py.read_rds_table(p)
        assert cols["ints"].kind in ("integer", "double")
        np.testing.assert_array_equal(cols["ints"].values, [1.0, 2.0, 3.0])
        assert cols["nullable_i"].kind == "double"
        v = cols["nullable_i"].values
        assert v[0] == 1.0 and np.isnan(v[1]) and v[2] == 3.0
        assert cols["nullable_b"].kind == "logical"
        b = cols["nullable_b"].values
        assert b[0] == 1.0 and np.isnan(b[1]) and b[2] == 0.0
        with pytest.raises(TypeError):
            write_rds_table(str(tmp_path / "bad.rds"),
                            {"mix": ["a", object()]})

    def test_absent_numerics_are_na_real(self, tmp_path):
        """None/pd.NA in object-numeric columns must land as R's NA_real_
        payload (0x7FF00000000007A2, R arithmetic.c) so is.na() is TRUE
        and is.nan() FALSE — while a true float NaN stays a plain quiet
        NaN (advisor finding r3: the two were conflated)."""
        import struct

        import pandas as pd

        from dpcorr.io.rds_write import write_rds_table

        p = str(tmp_path / "na.rds")
        write_rds_table(p, {
            "x": [1.5, None, float("nan"), pd.NA],
        }, compress=False)
        blob = open(p, "rb").read()
        na_real = struct.pack(">Q", 0x7FF00000000007A2)
        # both absent entries carry the payload; the literal NaN does not
        assert blob.count(na_real) == 2
        # readers see all three missing entries as NaN doubles
        v = rds_py.read_rds_table(p)["x"].values
        assert v[0] == 1.5 and all(np.isnan(v[1:]))
        # raw stream order: value, NA_real_, plain NaN (not the payload),
        # NA_real_ — find the 4 doubles behind the REALSXP header
        idx = blob.index(struct.pack(">d", 1.5))
        doubles = [blob[idx + 8 * i: idx + 8 * (i + 1)] for i in range(4)]
        assert doubles[1] == na_real and doubles[3] == na_real
        assert doubles[2] != na_real and np.isnan(
            struct.unpack(">d", doubles[2])[0])

    def test_ragged_raises(self, tmp_path):
        from dpcorr.io.rds_write import write_rds_table

        with pytest.raises(ValueError, match="ragged"):
            write_rds_table(str(tmp_path / "r.rds"),
                            {"a": np.arange(3), "b": np.arange(4)})

    def test_grid_out_dir_writes_rds(self, tmp_path):
        """run_grid(out_dir=...) persists detail_all.rds alongside parquet
        and it reads back equal to the in-memory frame."""
        from dpcorr.grid import GridConfig, run_grid

        res = run_grid(GridConfig(n_grid=(200,), rho_grid=(0.0, 0.5),
                                  eps_pairs=((1.0, 1.0),), b=4,
                                  backend="bucketed",
                                  out_dir=str(tmp_path / "g")))
        cols = rds_py.read_rds_table(str(tmp_path / "g" / "detail_all.rds"))
        assert list(cols) == list(res.detail_all.columns)
        np.testing.assert_allclose(cols["ni_hat"].values,
                                   res.detail_all["ni_hat"].to_numpy(),
                                   rtol=0, atol=0)
