"""Clean fixture: pure traced functions; host effects outside traces."""

import jax
import jax.numpy as jnp


@jax.jit
def pure(x):
    return jnp.sum(x * 2.0)


def host_side(x):
    print("outside any trace", x)
    return x


def scan_body(carry, x):
    acc = {}
    acc["x"] = x  # local mutation is fine — acc is bound in-scope
    return carry + x, acc["x"]


def run(xs):
    out, ys = jax.lax.scan(scan_body, 0.0, xs)
    print("done", out)  # host side again: outside the traced body
    return out, ys
