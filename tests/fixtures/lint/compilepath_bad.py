"""compilepath bad fixture: private AOT builds outside utils/compile."""
import jax


def private_aot(fn, avals):
    jitted = jax.jit(fn)
    return jitted.lower(*avals).compile()  # aot-outside-compile-layer


def chained_inline(fn, x):
    return jax.jit(fn).lower(x).compile()  # aot-outside-compile-layer


def with_options(fn, x, opts):
    return fn.lower(x).compile(compiler_options=opts)  # aot-outside-compile-layer
