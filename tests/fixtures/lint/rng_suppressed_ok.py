"""Clean-by-suppression fixture: both comment placements."""

import jax


def inline(key):
    return jax.random.fold_in(key, 7)  # dpcorr-lint: ignore[rng-raw-api]


def standalone(key):
    # dpcorr-lint: ignore[rng-raw-api]
    return jax.random.fold_in(key, 8)


def bare_ignore(key):
    return jax.random.fold_in(key, 9)  # dpcorr-lint: ignore
