"""Violating fixture: both purity rules fire in here."""

import time

import jax

_cache = {}


@jax.jit
def stamped(x):
    return x * time.time()  # jit-impure-call


def printy(x):
    print("tracing", x)  # jit-impure-call (traced via jax.jit below)
    return x


traced = jax.jit(printy)


@jax.jit
def memoized(x):
    _cache["last"] = x  # jit-closure-mutation
    return x


def scanned(xs):
    def body(carry, x):
        _cache.update(last=x)  # jit-closure-mutation (lax.scan traces)
        return carry + x, x

    return jax.lax.scan(body, 0.0, xs)
