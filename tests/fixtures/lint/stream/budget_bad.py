"""Violating fixture: both budget rules fire on the stream release
shape (the `stream/` path segment puts this file in the checker's
scope, and `releaser.release` is an enqueue site)."""


class StreamService:
    def release_uncharged(self, window):
        self.releaser.release(window)  # budget-uncharged-noise
        self.ledger.charge(self.charges, charge_id=window.id)

    def release_no_refund(self, window):
        self.ledger.charge(self.charges, charge_id=window.id)
        self.releaser.release(window)  # budget-missing-refund
