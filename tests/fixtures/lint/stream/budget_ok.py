"""Clean fixture: the stream release shape that lints — one write-ahead
ledger charge per window, the releaser handoff refund-guarded; and a
below-admission releaser (no ledger in scope) that executes freely."""


class StreamService:
    def release_window(self, window):
        self.ledger.charge(self.charges, charge_id=window.id)
        try:
            self.releaser.release(window)
        except RuntimeError:
            self.ledger.refund(self.charges, charge_id=window.id)
            raise


class Releaser:
    def release(self, window):
        # execution layer: windows arriving here are charged by
        # contract, and no ledger is in scope
        return self.sketch(window.rows)
