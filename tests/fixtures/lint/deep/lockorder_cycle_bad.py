# The seeded deadlock: forward() takes A then B, backward() takes B
# then A. The deep pass must report exactly ONE lock-order-cycle whose
# chain names both acquisition paths file:line.
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                return 1

    def backward(self):
        with self._b:
            with self._a:
                return 2
