# Same shapes as the bad fixtures, silenced by rule-specific ignores
# (the reviewable escape hatch for by-design orderings).
import os
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()

    def record(self, fh, rec):
        with self._lock:
            fh.write(rec)
            # dpcorr-lint: ignore[blocking-under-lock] — WAL shape
            os.fsync(fh.fileno())
