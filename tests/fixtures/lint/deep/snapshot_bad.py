# tmp+fsync+replace is the right write path, but the module has no
# stale-tmp sweep and no quarantine path for torn files on recovery.
import json
import os


def persist(path, state):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(state, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
