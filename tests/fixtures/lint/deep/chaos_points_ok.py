# Healthy miniature registry: every point is instrumented on a path
# reachable from a public entrypoint and swept by the kill matrix.

KNOWN_POINTS = (
    "fix.alpha_point",
    "fix.beta_point",
)

MATRIX_POINTS = ("fix.alpha_point", "fix.beta_point")


def point(name):
    return name


def run():
    point("fix.alpha_point")
    _inner()


def _inner():
    point("fix.beta_point")
