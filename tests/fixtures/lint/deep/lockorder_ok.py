# Clean: both paths take A before B, and the fsync happens after the
# lock is released.
import os
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                return 1

    def also_forward(self, fh):
        with self._a:
            with self._b:
                staged = fh
        os.fsync(staged.fileno())
        return 3
