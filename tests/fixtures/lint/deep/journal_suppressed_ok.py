# The bad shape, silenced with the rule-specific escape hatch (a
# config artifact that is regenerated on boot, so tearing is fine).
import json


def save_cache(path, state):
    # dpcorr-lint: ignore[durability-bare-write] — rebuildable cache
    with open(path, "w") as fh:
        json.dump(state, fh)
