# Full durable discipline: fsync-before-ack appends, tmp+fsync+replace
# snapshots, a stale-tmp sweep before replacing, quarantine on load.
import json
import os


def sweep_stale_tmp(dirpath):
    for name in os.listdir(dirpath):
        if name.endswith(".tmp"):
            os.unlink(os.path.join(dirpath, name))


def append(path, rec):
    with open(path, "a") as fh:
        fh.write(rec)
        fh.flush()
        os.fsync(fh.fileno())
        return 1


def persist(path, state):
    sweep_stale_tmp(os.path.dirname(path))
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(state, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def load(path):
    try:
        with open(path) as fh:
            return json.load(fh)
    except ValueError:
        os.replace(path, path + ".corrupt")
        return None
