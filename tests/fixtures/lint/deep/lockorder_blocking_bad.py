# blocking-under-lock, interprocedurally: record() holds the lock and
# calls _sync(), whose fsync it inherits through the call graph.
import os
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()

    def record(self, fh, rec):
        with self._lock:
            fh.write(rec)
            self._sync(fh)

    def _sync(self, fh):
        os.fsync(fh.fileno())
