# Miniature chaos registry (located structurally, like the real one in
# dpcorr/chaos.py): one dead point, one orphaned point only a private
# helper instruments, one live-but-unswept point, one healthy point.

KNOWN_POINTS = (
    "fix.dead_point",
    "fix.orphan_point",
    "fix.unswept_point",
    "fix.swept_point",
)

MATRIX_POINTS = ("fix.swept_point",)


def point(name):
    return name


def run():
    point("fix.unswept_point")
    point("fix.swept_point")


def _forgotten():
    point("fix.orphan_point")
