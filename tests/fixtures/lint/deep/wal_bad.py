# durability-unsynced-ack: the early return hands the caller a sequence
# number before the fsync below it has made the record durable.
import os


def append(path, rec, fast):
    with open(path, "a") as fh:
        fh.write(rec)
        seq = 1
        if fast:
            return seq
        os.fsync(fh.fileno())
        return seq
