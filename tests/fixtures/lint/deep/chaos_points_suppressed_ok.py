# A registry entry kept on purpose (the point is wired up in a repo
# this fixture can't see), silenced at its registry line.

KNOWN_POINTS = (
    "fix.external_point",  # dpcorr-lint: ignore[chaos-unreachable-point]
)

MATRIX_POINTS = ("fix.external_point",)
