# The seeded torn-file shape: a bare write to a durable journal with
# no fsync anywhere on the path. Exactly ONE durability-bare-write.
import json


def save_snapshot(path, state):
    with open(path, "w") as fh:
        json.dump(state, fh)
