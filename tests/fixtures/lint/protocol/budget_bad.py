"""Violating fixture: the budget rules in protocol scope — a release
handed to the transport without a write-ahead charge, and one whose
transport failure could not refund."""


class Gate:
    def send_uncharged(self, channel, body):
        channel.send(body)  # budget-uncharged-noise
        self.ledger.charge(self.charges)

    def send_no_refund(self, channel, body):
        self.ledger.charge(self.charges)
        channel.send(body)  # budget-missing-refund
