"""Violating fixture: raw columns reaching wire serializers through
plain aliasing — directly, through passthrough casts/clips, and as a
sign image (still the column's data, no randomization applied)."""


def leak_direct(x, encode_array):
    return encode_array(x, "raw")  # raw-column-serialize


def leak_alias(column, np, encode_array):
    values = np.asarray(column)
    clipped = values.clip(-1.0, 1.0)
    return encode_array(clipped, "clipped")  # raw-column-serialize


def leak_sign(y, np, canonical_encode):
    return canonical_encode(np.sign(y))  # raw-column-serialize
