"""Clean fixture: the ReleaseGate shape — write-ahead charge, send
under a refund-on-transport-failure guard; and a transport-layer
helper with no ledger in scope that sends freely."""


class Gate:
    def send_release(self, channel, body, charges):
        self.ledger.charge(charges)
        try:
            return channel.send(body)
        except IOError:
            self.ledger.refund(charges)
            raise


class Channel:
    def send(self, body):
        # transport layer: bodies arriving here are charged by
        # contract, and no ledger is in scope
        self.link.send_bytes(body)
