"""Clean fixture: noise between the sample and the socket. Arithmetic
(BinOp) and reductions break taint — adding calibrated noise or
aggregating to batch means is exactly what turns a column into a
release — and rebinding a tainted alias to a noised value clears it."""


def release_noised(x, noise, encode_array):
    release = x + noise
    return encode_array(release, "noisy")


def release_rebound(col, np, lap, encode_array):
    values = np.asarray(col)
    values = values + lap
    return encode_array(values, "noisy")


def release_batched(xs, np, encode_array):
    means = np.mean(xs.reshape(-1, 8), axis=1)
    return encode_array(means, "batch_means")
