"""Clean fixture: guarded accesses, the `__init__` and `*_locked`
exemptions, and a Condition standing in for its wrapped lock."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded by: _lock
        self._n = self._initial()  # __init__ is pre-concurrency

    def bump(self):
        with self._lock:
            self._n += 1

    def peek(self):
        with self._lock:
            return self._n

    def _drain_locked(self):
        # caller holds the lock (checked at the call sites)
        return self._n

    def _initial(self):
        return 0


class Queue:
    def __init__(self):
        self._cond = threading.Condition()
        self._items = []  # guarded by: _cond

    def put(self, item):
        with self._cond:
            self._items.append(item)
            self._cond.notify()
