# Cross-function budget discipline violations that the intra-function
# rule (rules/budget.py) cannot see: the enqueue lives in a private
# helper, so only the composed (inlined) view exposes the ordering.


class Server:
    def __init__(self, ledger, coalescer):
        self.ledger = ledger
        self.coalescer = coalescer

    def estimate(self, req):
        fut = self._enqueue(req)
        self.ledger.charge(req.party, req.eps)
        return fut

    def _enqueue(self, req):
        return self.coalescer.submit(req)

    def admit(self, req):
        self.ledger.charge(req.party, req.eps)
        return self._launch(req)

    def _launch(self, req):
        return self.coalescer.submit(req)
