"""Clean fixture for ``budget-shed-missing-refund``: the shed site
routes through a refund helper, and settling a future with a
non-refusal exception (or a pre-built variable) is out of scope."""


class ServerOverloadedError(Exception):
    pass


class Coalescer:
    def _refund(self, pending, reason):
        pass

    def refuse_evicted(self, pending):
        self._refund(pending, "queue_evict")
        pending.future.set_exception(
            ServerOverloadedError("queue full"))

    def fail(self, pending, exc):
        # a variable, not a refusal constructor: execution errors are
        # answers, not sheds — the charge stands
        pending.future.set_exception(exc)

    def crash(self, pending):
        pending.future.set_exception(RuntimeError("kernel failed"))
