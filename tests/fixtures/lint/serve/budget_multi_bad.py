"""Violating fixture: multi-level admission charging two budget
receivers with no compensation path, plus a directory-receiver enqueue
with no dominating charge (the `serve/` path puts this in scope)."""


class Composite:
    def charge(self, user, charges):
        self.directory.charge(user, sum(charges.values()))
        self.ledger.charge(charges)  # budget-multi-charge-missing-refund


class Admission:
    def admit(self, req):
        self.coalescer.submit(req)  # budget-uncharged-noise
        self.directory.charge(req.user, req.eps)
