"""Clean fixture: the CompositeLedger shape — the later receiver's
charge sits in a try whose handler compensates the first store, and a
directory charge dominates the enqueue like a ledger charge does."""


class Composite:
    def charge(self, user, charges):
        total = sum(charges.values())
        try:
            self.directory.charge(user, total)
            self.ledger.charge(charges)
        except OverflowError:
            self.directory.refund(user, total)
            raise


class Admission:
    def admit(self, req):
        self.directory.charge(req.user, req.eps)
        try:
            self.coalescer.submit(req)
        except OverflowError:
            self.directory.refund(req.user, req.eps)
            raise
