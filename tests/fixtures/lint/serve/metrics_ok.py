"""Clean fixture: prefixed snake_case names, spans closed on all paths."""

from dpcorr.obs.metrics import Counter, default_registry
from dpcorr.obs.trace import tracer

registry = default_registry()


def publish():
    requests = registry.counter("dpcorr_serve_requests_total")
    depth = registry.gauge("dpcorr_serve_queue_depth")
    direct = Counter("dpcorr_serve_errors_total")
    return requests, depth, direct


def handle(req):
    with tracer().span("serve.handle"):  # context manager: always closed
        return req.run()


def handle_explicit(req):
    sp = tracer().start_span("serve.handle")
    try:
        return req.run()
    finally:
        sp.end()  # closed on every path


def unrelated_receiver(analytics):
    # a non-registry object's .counter(...) is not a metric declaration
    return analytics.counter("page_views")
