"""Violating fixture: both telemetry rules fire in here."""

from dpcorr.obs.metrics import Counter, default_registry
from dpcorr.obs.trace import tracer

registry = default_registry()


def publish():
    requests = registry.counter("requests_total")  # metric-name-style
    camel = registry.gauge("dpcorr_QueueDepth")  # metric-name-style
    direct = Counter("serve_errors_total")  # metric-name-style
    return requests, camel, direct


def handle(req):
    sp = tracer().start_span("serve.handle")  # span-no-finally
    result = req.run()
    sp.end()  # not in a finally: an exception above leaks the span
    return result


def fire_and_forget():
    tracer().start_span("serve.orphan")  # span-no-finally (never bound)
