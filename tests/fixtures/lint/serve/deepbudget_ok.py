# The composed view done right: charge strictly before the inherited
# enqueue, and the post-charge launch wrapped in a refund guard.


class Server:
    def __init__(self, ledger, coalescer):
        self.ledger = ledger
        self.coalescer = coalescer

    def admit(self, req):
        self.ledger.charge(req.party, req.eps)
        try:
            return self._launch(req)
        except Exception:
            self.ledger.refund(req.party, req.eps)
            raise

    def _launch(self, req):
        return self.coalescer.submit(req)
