"""Violating fixture: both budget rules fire in here (the `serve/`
path segment puts this file in the checker's scope)."""


class Server:
    def submit_uncharged(self, req):
        self.coalescer.submit(req)  # budget-uncharged-noise
        self.ledger.charge(req.party, req.eps)

    def submit_no_refund(self, req):
        self.ledger.charge(req.party, req.eps)
        self.coalescer.submit(req)  # budget-missing-refund
