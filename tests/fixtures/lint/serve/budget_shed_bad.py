"""Violating fixture: ``budget-shed-missing-refund`` fires — a future
is settled with a refusal exception but nothing in the function routes
through a refund."""


class ServerOverloadedError(Exception):
    pass


class Coalescer:
    def refuse_evicted(self, pending):
        pending.future.set_exception(  # budget-shed-missing-refund
            ServerOverloadedError("queue full"))
