"""Violating fixture: both lock rules fire in here."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded by: _lock

    def bump(self):
        self._n += 1  # lock-unguarded-write

    def peek(self):
        return self._n  # lock-unguarded-read

    def flush_async(self):
        with self._lock:
            def worker():
                self._n = 0  # closure escapes the guard: still a write
            return worker
