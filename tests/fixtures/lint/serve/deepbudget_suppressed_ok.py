# The bad shapes, silenced: a deliberate pre-charge enqueue (the
# speculation path refunds via a reaper, out of the linter's sight).


class Server:
    def __init__(self, ledger, coalescer):
        self.ledger = ledger
        self.coalescer = coalescer

    def estimate(self, req):
        # dpcorr-lint: ignore[budget-deep-uncharged-enqueue]
        fut = self._enqueue(req)
        self.ledger.charge(req.party, req.eps)
        return fut

    def _enqueue(self, req):
        return self.coalescer.submit(req)

    def admit(self, req):
        self.ledger.charge(req.party, req.eps)
        # dpcorr-lint: ignore[budget-deep-missing-refund]
        return self._launch(req)

    def _launch(self, req):
        return self.coalescer.submit(req)
