"""Clean fixture: write-ahead charge, refund-guarded enqueue; and a
below-admission function (no ledger in scope) that enqueues freely."""


class Server:
    def submit(self, req):
        self.ledger.charge(req.party, req.eps)
        try:
            self.coalescer.submit(req)
        except OverflowError:
            self.ledger.refund(req.party, req.eps)
            raise


class Coalescer:
    def submit(self, req):
        # execution layer: requests arriving here are charged by
        # contract, and no ledger is in scope
        self.queue.append(req)
