"""compilepath ok fixture: the legal ways to get an executable, plus
the look-alikes that must never fire."""


def through_the_layer(jitted, avals):
    from dpcorr.utils import compile as compile_mod

    fn, ok = compile_mod.aot_compile(jitted, avals)
    return fn if ok else jitted


def str_lower_is_not_aot(name: str):
    # str.lower() with no .compile() on the result's *call* — clean
    return name.lower()


def regex_compile_is_not_aot(pattern: str):
    import re

    # a bare .compile(...) whose receiver is not a .lower(...) call
    return re.compile(pattern.lower())
