"""sync-rule ok fixture under the plan layer: dispatch loops stay
asynchronous; the single fetch sits outside any loop (the
Executor.fetch shape)."""
import jax


def dispatch_all(units, args):
    outs = [u(*args) for u in units]     # async — no sync call
    return jax.block_until_ready(outs)   # ONE fetch, not in a loop
