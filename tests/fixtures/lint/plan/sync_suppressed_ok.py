"""sync-rule suppression fixture under the plan layer: a deliberate
per-unit barrier (e.g. a latency probe) carries the ignore tag."""
import jax


def probe_latency(units, args, clock):
    out = []
    for u in units:
        r = u(*args)
        jax.block_until_ready(r)  # dpcorr-lint: ignore[sync-in-loop]
        out.append(clock())
    return out
