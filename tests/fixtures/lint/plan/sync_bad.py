"""sync-rule bad fixture under the plan layer: an executor helper that
syncs per dispatched unit instead of once at the fetch boundary."""
import jax
import numpy as np


def fetch_each(units, args):
    out = []
    for u in units:
        r = u(*args)
        out.append(jax.block_until_ready(r))  # sync-in-loop
    return out


def gather_host(outs):
    return [np.asarray(o) for o in outs]  # sync-in-loop
