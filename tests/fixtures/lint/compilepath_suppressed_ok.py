"""compilepath suppression fixture: a deliberate out-of-layer build
(e.g. a one-off debugging probe) carries the ignore tag."""
import jax


def debug_probe(fn, x):
    return jax.jit(fn).lower(x).compile()  # dpcorr-lint: ignore[aot-outside-compile-layer]
