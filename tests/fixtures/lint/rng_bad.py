"""Violating fixture: every rng rule fires in here."""

import jax


def two_draws(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))  # rng-key-reuse
    return a + b


def literal_seed():
    return jax.random.PRNGKey(42)  # rng-literal-seed + rng-raw-api


def raw_fold(key):
    return jax.random.fold_in(key, 3)  # rng-raw-api
