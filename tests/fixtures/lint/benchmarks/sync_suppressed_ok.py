"""sync-rule suppression fixture: deliberate barriers are annotated."""
import numpy as np


def fetch_phase(raw):
    # dpcorr-lint: ignore[sync-in-loop]
    return [np.asarray(a) for a in raw]


def drain_latency(blocks, clock):
    out = []
    for b in blocks:
        # measuring per-block sync latency IS this loop's job
        out.append(np.asarray(b))  # dpcorr-lint: ignore[sync-in-loop]
    return out
