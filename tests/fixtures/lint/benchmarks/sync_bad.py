"""sync-rule bad fixture: per-iteration host syncs in rep loops."""
import jax
import numpy as np


def drain_each(blocks):
    out = []
    for b in blocks:
        out.append(np.asarray(b))  # sync-in-loop
    return out


def wait_each(queue):
    total = 0.0
    while queue:
        x = queue.pop()
        jax.block_until_ready(x)  # sync-in-loop
        total += 1.0
    return total


def comp_fetch(blocks):
    return [jax.device_get(b) for b in blocks]  # sync-in-loop


def method_sync(blocks):
    for b in blocks:
        b.block_until_ready()  # sync-in-loop (method form)
