"""sync-rule ok fixture: async dispatch, one fetch at the boundary."""
import jax
import numpy as np


def pipeline(step, blocks):
    acc = None
    for b in blocks:
        acc = step(b) if acc is None else acc + step(b)  # async dispatch
    # the reduction boundary: one sync, outside every loop
    return np.asarray(jax.block_until_ready(acc))


def closure_is_not_an_iteration(blocks):
    # a helper *defined* in a loop body only syncs where it is called
    fetchers = []
    for b in blocks:
        fetchers.append(lambda b=b: np.asarray(b))
    return fetchers
