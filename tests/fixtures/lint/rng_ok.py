"""Clean fixture: the sanctioned key-handling patterns."""

import jax

from dpcorr.utils import rng


def split_draws(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (3,))
    b = jax.random.uniform(k2, (3,))
    return a + b


def named_streams(key):
    x = jax.random.normal(rng.stream(key, "x"), (3,))
    y = jax.random.normal(rng.stream(key, "y"), (3,))
    return x + y


def rebind(key):
    a = jax.random.normal(key, ())
    key = rng.stream(key, "second")
    b = jax.random.normal(key, ())
    return a + b


def exclusive_branches(key, flag):
    if flag:
        return jax.random.normal(key, ())
    else:
        return jax.random.laplace(key, ())


def early_return_guard(key, flag):
    if flag:
        return jax.random.normal(key, ())
    return jax.random.laplace(key, ())


def configured_seed(seed):
    return rng.master_key(seed)
