"""CLI smoke tests (`python -m dpcorr …`, SURVEY.md entry points)."""

import json

import pytest

from dpcorr.__main__ import main


def _run_json(capsys, argv):
    main(argv)
    return json.loads(capsys.readouterr().out)


# (the demo CLI's config + summary contract is pinned exactly in
# tests/test_golden_demo.py::test_demo_cli_runs_the_reference_config —
# one invocation, one source of truth)


def test_demo_subg(capsys):
    out = _run_json(capsys, ["demo-subg", "--b", "8"])
    assert out["config"]["n"] == 5500
    assert "NI" in out["summary"]


def test_stress(capsys):
    out = _run_json(capsys, ["stress", "--n", "20000", "--b", "4",
                             "--n-chunk", "4096", "--family", "sign"])
    assert out["n"] == 20000 and out["family"] == "sign"
    assert out["reps_per_sec_incl_compile"] > 0
    assert 0.0 <= out["summary"]["NI"]["coverage"] <= 1.0


def test_bad_command():
    with pytest.raises(SystemExit):
        main(["not-a-command"])


def test_unsupported_backend_rejected():
    """Commands only advertise backends they implement: `stress` has no
    bucketed path and `demo` is local-only — both are argparse errors, not
    silently-ignored flags (ADVICE round 1)."""
    with pytest.raises(SystemExit):
        main(["stress", "--backend", "bucketed", "--n", "1000", "--b", "2"])
    with pytest.raises(SystemExit):
        main(["demo", "--backend", "sharded", "--b", "2"])
