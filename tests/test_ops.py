"""Unit tests for DP primitives: noise moments, clipping, λ rules, mixquant,
standardization — the closed-form checks SURVEY.md §4 mandates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.integrate
import scipy.stats

from dpcorr.ops import (
    clip,
    clip_sym,
    dp_mean,
    dp_sd,
    lambda_from_priv,
    lambda_int_n,
    lambda_n,
    lambda_receiver_from_noise,
    laplace,
    mixquant,
    mixquant_mc,
    priv_standardize,
    standardize_dp,
)
from dpcorr.ops.mixquant import mix_cdf
from dpcorr.utils import rng


KEY = rng.master_key()


class TestRng:
    def test_deterministic(self):
        a = laplace(rng.master_key(7), (5,), 1.0)
        b = laplace(rng.master_key(7), (5,), 1.0)
        np.testing.assert_array_equal(a, b)

    def test_streams_differ(self):
        k = rng.master_key()
        a = laplace(rng.stream(k, "x"), (5,), 1.0)
        b = laplace(rng.stream(k, "y"), (5,), 1.0)
        assert not np.allclose(a, b)

    def test_rep_keys_distinct(self):
        keys = rng.rep_keys(KEY, 100)
        data = jax.vmap(lambda k: jax.random.normal(k, ()))(keys)
        assert len(np.unique(np.asarray(data))) == 100

    def test_design_key_folding(self):
        k1 = rng.design_key(KEY, 1)
        k2 = rng.design_key(KEY, 2)
        assert not np.array_equal(jax.random.key_data(k1), jax.random.key_data(k2))


class TestLaplace:
    def test_moments(self):
        x = np.asarray(laplace(KEY, (200_000,), 3.0))
        # mean 0, var = 2·scale²
        assert abs(x.mean()) < 0.05
        np.testing.assert_allclose(x.var(), 2 * 3.0**2, rtol=0.02)

    def test_scale_broadcast(self):
        scales = jnp.array([1.0, 2.0, 4.0])
        x = laplace(KEY, (50_000, 3), scales)
        v = np.asarray(x).var(axis=0)
        np.testing.assert_allclose(v, 2 * np.asarray(scales) ** 2, rtol=0.05)


class TestClip:
    def test_clip(self):
        x = jnp.array([-5.0, 0.0, 5.0])
        np.testing.assert_array_equal(clip(x, -1.0, 2.0), [-1.0, 0.0, 2.0])
        np.testing.assert_array_equal(clip_sym(x, 1.5), [-1.5, 0.0, 1.5])

    def test_idempotent(self):
        x = jax.random.normal(KEY, (100,))
        once = clip_sym(x, 0.7)
        np.testing.assert_array_equal(once, clip_sym(once, 0.7))


class TestLambdas:
    def test_lambda_n(self):
        # min(2η√log n, 2√3) — ver-cor-subG.R:1
        for n, eta in [(100, 1.0), (10_000, 0.5), (50, 2.0)]:
            expected = min(2 * eta * np.sqrt(np.log(n)), 2 * np.sqrt(3))
            np.testing.assert_allclose(float(lambda_n(n, eta)), expected, rtol=1e-6)

    def test_lambda_int_n(self):
        lam_s, lam_r = lambda_int_n(5000, eta_s=1.0, eta_r=2.0, eps_s=0.5)
        np.testing.assert_allclose(
            float(lam_s), min(2 * np.sqrt(np.log(5000)), 2 * np.sqrt(3)), rtol=1e-6
        )
        np.testing.assert_allclose(
            float(lam_r), 5 * 2.0 * min(np.log(5000), 6) / 0.5, rtol=1e-6
        )

    def test_lambda_from_priv(self):
        val = float(lambda_from_priv(45.0, 90.0, 70.0, 10.0))
        np.testing.assert_allclose(val, max(abs(45 - 70), abs(90 - 70)) / 10.0, rtol=1e-6)

    def test_lambda_receiver(self):
        lam = float(lambda_receiver_from_noise(2.0, 3.0, 0.5, 0.01))
        b_s = 2 * 2.0 / 0.5
        np.testing.assert_allclose(lam, (2.0 + b_s * np.log(100)) * 3.0, rtol=1e-4)


class TestMixquant:
    @pytest.mark.parametrize("c", [0.01, 0.1, 0.5, 1.0, 3.0, 10.0])
    def test_cdf_against_numeric_convolution(self, c):
        xs = np.linspace(-4 - 4 * c, 4 + 4 * c, 9)
        for x in xs:
            num, _ = scipy.integrate.quad(
                lambda l: 0.5 * np.exp(-abs(l)) * scipy.stats.norm.cdf(x - c * l),
                -60, 60, limit=400,
            )
            got = float(mix_cdf(x, c))
            assert abs(got - num) < 2e-5, (x, c, got, num)

    def test_quantile_inverts_cdf(self):
        for c in [0.05, 0.3, 1.0, 5.0]:
            for p in [0.6, 0.9, 0.975, 0.999]:
                q = float(mixquant(c, p))
                np.testing.assert_allclose(float(mix_cdf(q, c)), p, atol=2e-5)

    def test_c_zero_limit_is_normal_quantile(self):
        np.testing.assert_allclose(
            float(mixquant(1e-6, 0.975)), scipy.stats.norm.ppf(0.975), atol=1e-3
        )

    def test_mc_matches_deterministic(self):
        # Mean of the reference's noisy MC order statistic should approach the
        # deterministic quantile (Appendix A #4 substitution check).
        c, p = 0.8, 0.975
        keys = jax.random.split(rng.master_key(3), 400)
        qs = jax.vmap(lambda k: mixquant_mc(k, c, p, nsim=1000))(keys)
        det = float(mixquant(c, p))
        assert abs(float(jnp.mean(qs)) - det) < 0.05

    def test_symmetry(self):
        # median is 0 for the symmetric mixture
        assert abs(float(mixquant(1.3, 0.5))) < 1e-4


class TestStandardize:
    def test_priv_standardize_low_noise(self):
        x = jax.random.normal(KEY, (20_000,)) * 2.0 + 5.0
        z = np.asarray(priv_standardize(rng.stream(KEY, "ps"), x, eps_norm=1e6, l_raw=20.0))
        assert abs(z.mean()) < 0.02
        np.testing.assert_allclose(z.std(), 1.0, atol=0.02)

    def test_dp_mean_clips(self):
        # with huge eps (no noise), dp_mean == mean of clipped values
        x = jnp.array([-100.0, 0.0, 100.0])
        m = float(dp_mean(KEY, x, -1.0, 1.0, 1e9))
        np.testing.assert_allclose(m, 0.0, atol=1e-5)

    def test_dp_sd_floor_at_zero(self):
        # constant data with moderate noise can drive var negative; sd must be >= 0
        x = jnp.ones((50,))
        for s in range(20):
            _, sd = dp_sd(rng.master_key(s), x, 0.0, 2.0, 0.5, 0.5)
            assert float(sd) >= 0.0

    def test_standardize_dp(self):
        x = jnp.array([0.0, 5.0, 10.0])
        z = np.asarray(standardize_dp(x, 5.0, 2.0, 0.0, 10.0))
        np.testing.assert_allclose(z, [-2.5, 0.0, 2.5], atol=1e-6)

    def test_standardize_dp_sd_floor(self):
        z = standardize_dp(jnp.array([1.0]), 0.0, 0.0, -5.0, 5.0)
        assert np.isfinite(float(z[0]))


class TestPrivCenter:
    def test_sign_identity_with_full_standardize(self):
        """priv_center is the sign-only shortcut: same key ⇒ identical
        signs as the full priv_standardize (σ>0 never flips a sign), which
        is what makes the estimator switch output-identical."""
        from dpcorr.models.dgp import gen_gaussian
        from dpcorr.ops import priv_center, priv_standardize

        key = rng.master_key(77)
        xy = gen_gaussian(rng.stream(key, "d"), 4096, jnp.float32(0.3))
        x = 2.0 + 1.7 * xy[:, 0]
        kz = rng.stream(key, "z")
        full = priv_standardize(kz, x, 1.0, 2.5)
        cent = priv_center(kz, x, 1.0, 2.5)
        np.testing.assert_array_equal(np.sign(np.asarray(full)),
                                      np.sign(np.asarray(cent)))


def test_pallas_seeds_contract():
    """Key-tree-derived on-chip seed words: (n, 2) int32, deterministic
    per key, distinct across design points, and collision-free in the
    2-word space at campaign scale (the 1-word birthday problem was a
    real defect — rng.pallas_seeds docstring)."""
    import numpy as np

    k0 = rng.design_key(rng.master_key(), 0)
    k1 = rng.design_key(rng.master_key(), 1)
    s0 = np.asarray(rng.pallas_seeds(k0, 4096))
    assert s0.shape == (4096, 2) and s0.dtype == np.int32
    np.testing.assert_array_equal(s0, np.asarray(rng.pallas_seeds(k0, 4096)))
    assert not np.array_equal(s0, np.asarray(rng.pallas_seeds(k1, 4096)))
    # all 2-word seeds unique within a draw (2^64 space)
    pairs = {tuple(row) for row in s0.tolist()}
    assert len(pairs) == 4096
