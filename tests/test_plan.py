"""The plan/executor layer (dpcorr.plan) and its first mesh consumer.

Three contracts:

1. **Placement/executor mechanics** — resolution, preshard counting,
   Prepared fallback on off-signature dispatch, the multihost seam.
2. **Mesh bit-identity** — ``sim.RepBlockPipeline`` under
   ``placement="mesh"`` produces per-rep outputs **bitwise identical**
   to the local placement for all four estimator families at mesh
   sizes 2 and 4 (8 virtual devices via conftest), and its reduced
   sums are tolerance-equal (a different reduction tree rounds
   differently — documented, not hidden).
3. **Single fetch** — one mesh ``run()`` increments the transfer
   fetch counter exactly once, proven against a private counter
   bundle; plus the sketch tree-reduce merge is bitwise equal to the
   monolithic release.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpcorr import plan as plan_mod
from dpcorr import sim
from dpcorr.obs import transfer as transfer_mod
from dpcorr.obs.metrics import Registry
from dpcorr.parallel.mesh import rep_mesh
from dpcorr.utils import rng

BLOCK_REPS = 16
CHUNK = 4

#: the four estimator families, as (name, SimConfig) — two configs
#: cover all four since each runs an NI and an INT estimator
FAMILY_CFGS = {
    "sign": sim.SimConfig(n=192, rho=0.35, eps1=1.0, eps2=1.0,
                          use_subg=False),
    "subg": sim.SimConfig(n=192, rho=0.35, eps1=2.0, eps2=1.5,
                          use_subg=True),
}


def _rep_fn(cfg):
    rho = jnp.float32(cfg.rho)

    def rep(k):
        row = sim._one_rep(k, rho, cfg)
        return (row[0], row[1], row[8], row[9])  # ni_hat, int_hat, covers

    return rep


def _pipe(cfg, placement="local", mesh=None, counters=None, aot=True):
    return sim.RepBlockPipeline(
        _rep_fn(cfg), 4, key=rng.master_key(7), block_reps=BLOCK_REPS,
        chunk_size=CHUNK, family="plan-test", placement=placement,
        mesh=mesh, counters=counters, aot=aot)


def _own_counters():
    return transfer_mod.TransferCounters(Registry())


# ------------------------------------------------------- placements ----
def test_resolve_placement_names_and_passthrough():
    lp = plan_mod.resolve_placement("local")
    assert lp.name == "local" and lp.device_count == 1
    assert lp.mesh_shape() is None
    mp = plan_mod.resolve_placement("mesh", mesh=rep_mesh(2))
    assert mp.name == "mesh" and mp.device_count == 2
    assert mp.mesh_shape() == {"rep": 2}
    assert plan_mod.resolve_placement(mp) is mp
    assert plan_mod.resolve_placement(None).name == "local"
    with pytest.raises(ValueError):
        plan_mod.resolve_placement("quantum")


def test_mesh_placement_pads_to_device_multiple():
    mp = plan_mod.MeshPlacement(rep_mesh(4))
    assert mp.pad(1) == 4 and mp.pad(4) == 4 and mp.pad(5) == 8
    assert plan_mod.LocalPlacement().pad(5) == 5


def test_multihost_is_a_seam_not_an_implementation():
    mh = plan_mod.resolve_placement("multihost")
    assert mh.device_count == 0
    with pytest.raises(NotImplementedError, match="init_distributed"):
        mh.data_sharding()
    with pytest.raises(NotImplementedError):
        mh.pad(8)


def test_preshard_counts_placements():
    ctr = _own_counters()
    ex = plan_mod.Executor("mesh", mesh=rep_mesh(2), counters=ctr)
    x = np.arange(8, dtype=np.float32)
    (placed,) = ex.preshard((x,))
    assert placed.sharding.is_equivalent_to(
        ex.placement.data_sharding(), placed.ndim)
    assert ctr.snapshot()["device_put"] >= 1
    # already-placed arrays pass through without a second put
    before = ctr.snapshot()["device_put"]
    ex.preshard((placed,))
    assert ctr.snapshot()["device_put"] == before


# --------------------------------------------------------- executor ----
def test_prepared_falls_back_on_off_signature_dispatch():
    ex = plan_mod.Executor("local", counters=_own_counters())
    jf = jax.jit(lambda x: x * 2.0)
    unit = ex.prepare(("t", "double"), jf,
                      (jax.ShapeDtypeStruct((4,), jnp.float32),))
    assert unit.aot_ok
    ok = ex.dispatch(unit, (jnp.ones((4,), jnp.float32),))
    off = ex.dispatch(unit, (jnp.ones((6,), jnp.float32),))  # wrong shape
    np.testing.assert_array_equal(np.asarray(ok), 2.0 * np.ones(4))
    np.testing.assert_array_equal(np.asarray(off), 2.0 * np.ones(6))


def test_executor_unit_cache_and_evict():
    ex = plan_mod.Executor("local", counters=_own_counters())
    jf = jax.jit(lambda x: x + 1.0)
    args = (jax.ShapeDtypeStruct((2,), jnp.float32),)
    u1 = ex.prepare(("t", "inc"), jf, args)
    u2 = ex.prepare(("t", "inc"), jf, args)
    assert u1 is u2
    ex.evict(("t", "inc"))
    u3 = ex.prepare(("t", "inc"), jf, args)
    assert u3 is not u1


def test_fetch_counts_exactly_one():
    ctr = _own_counters()
    ex = plan_mod.Executor("local", counters=ctr)
    out = ex.fetch(jnp.arange(3))
    assert ctr.snapshot()["fetches"] == 1
    np.testing.assert_array_equal(np.asarray(out), [0, 1, 2])


# ---------------------------------------------- mesh rep pipeline ------
def test_mesh_rejects_indivisible_block_reps():
    with pytest.raises(ValueError, match="split evenly"):
        sim.RepBlockPipeline(
            _rep_fn(FAMILY_CFGS["sign"]), 4, key=rng.master_key(7),
            block_reps=10, chunk_size=CHUNK, placement="mesh",
            mesh=rep_mesh(4), aot=False)


@pytest.mark.parametrize("fam", sorted(FAMILY_CFGS))
@pytest.mark.parametrize("n_dev", [2, 4])
def test_mesh_block_detail_bitwise_equals_local(fam, n_dev):
    """4 estimator families x {mesh(2), mesh(4)}: the sharded program's
    per-rep outputs are byte-for-byte the local placement's."""
    cfg = FAMILY_CFGS[fam]
    local = _pipe(cfg, aot=False)
    mesh = _pipe(cfg, placement="mesh", mesh=rep_mesh(n_dev),
                 counters=_own_counters(), aot=False)
    for a, b in zip(local.block_detail(0), mesh.block_detail(0)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


@pytest.mark.parametrize("fam", sorted(FAMILY_CFGS))
def test_mesh_run_sums_match_local_to_tolerance(fam):
    cfg = FAMILY_CFGS[fam]
    s_local, n_local = _pipe(cfg, counters=_own_counters()).run(2)
    s_mesh, n_mesh = _pipe(cfg, placement="mesh", mesh=rep_mesh(4),
                           counters=_own_counters()).run(2)
    assert n_local == n_mesh == 2 * BLOCK_REPS
    for a, b in zip(s_local, s_mesh):
        assert a == pytest.approx(b, rel=1e-5, abs=1e-5)


def test_mesh_run_is_single_fetch_and_donating():
    """The transfer proof: one run = one fetch, n_blocks donated
    dispatches, no reshard mismatches — on a counter bundle owned by
    this test alone."""
    ctr = _own_counters()
    pipe = _pipe(FAMILY_CFGS["sign"], placement="mesh", mesh=rep_mesh(4),
                 counters=ctr)
    before = ctr.snapshot()
    pipe.run(3)
    delta = transfer_mod.diff(ctr.snapshot(), before)
    assert delta.get("fetches") == 1, delta
    assert delta.get("donated_blocks") == 3, delta
    assert not delta.get("reshard_mismatch"), delta
    assert pipe.donation_engaged is True


def test_mesh_reduced_sums_deterministic_across_runs():
    cfg = FAMILY_CFGS["sign"]
    a, _ = _pipe(cfg, placement="mesh", mesh=rep_mesh(4),
                 counters=_own_counters()).run(2)
    b, _ = _pipe(cfg, placement="mesh", mesh=rep_mesh(4),
                 counters=_own_counters()).run(2)
    assert a == b  # exact: same shards, same ascending host fold


def test_mesh_resume_addresses_match_local():
    """start_block > 0 keygen lands at the same global key addresses
    sharded as unsharded (rep_keys_slice contract)."""
    cfg = FAMILY_CFGS["sign"]
    local = _pipe(cfg, counters=_own_counters())
    mesh = _pipe(cfg, placement="mesh", mesh=rep_mesh(2),
                 counters=_own_counters())
    for a, b in zip(local.block_detail(3), mesh.block_detail(3)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_rep_keys_slice_bitwise_matches_full_stream():
    key = rng.design_key(rng.master_key(3), jnp.uint32(5))
    full = rng.key_data(rng.rep_keys(key, 12))
    for start, n in ((0, 12), (4, 4), (8, 4)):
        part = rng.key_data(rng.rep_keys_slice(key, start, n))
        assert np.asarray(part).tobytes() == \
            np.asarray(full[start:start + n]).tobytes()


# -------------------------------------------------- sketch tree merge --
def test_sketch_tree_merge_bitwise_equals_monolithic():
    from dpcorr.stream import sketch as sk

    params = sk.ReleaseParams(family="ni_sign", eps1=1.0, eps2=1.0,
                              target_chunk=64)
    xy = np.random.default_rng(0).normal(size=(300, 2)).astype(np.float32)
    wkey = sk.window_key(rng.master_key(11), "w-tree")
    grid = sk.grid_for(params, xy.shape[0])
    assert grid.n_chunks >= 3  # the tree has real shape

    pass_a = sk.tree_merge([
        sk.sketch_window(xy, params, wkey, "pass_a", chunk_ids=[c])
        for c in range(grid.n_chunks)])
    moments = sk.moments_for_window(pass_a, params, grid, wkey)
    shards = [sk.sketch_window(xy, params, wkey, "estimate",
                               chunk_ids=[c], moments=moments)
              for c in range(grid.n_chunks)]
    tree = sk.release_from_sketch(sk.tree_merge(shards), params, wkey)
    mono = sk.release_window(xy, params, wkey)
    assert tree == mono  # dict equality over floats == bitwise


def test_sketch_tree_merge_rejects_empty():
    from dpcorr.stream import sketch as sk

    with pytest.raises(ValueError):
        sk.tree_merge([])


@pytest.mark.parametrize("n_dev", [2, 4])
def test_release_window_mesh_placement_bitwise_equals_monolithic(n_dev):
    """The placement-routed finalize path (stream service under
    ``--placement mesh``): release_window(placement=MeshPlacement)
    splits chunks round-robin across devices and tree-merges — the
    release record must equal the monolithic one bitwise."""
    from dpcorr.stream import sketch as sk

    params = sk.ReleaseParams(family="ni_sign", eps1=1.0, eps2=1.0,
                              target_chunk=64)
    xy = np.random.default_rng(3).normal(size=(300, 2)).astype(np.float32)
    wkey = sk.window_key(rng.master_key(12), "w-place")
    grid = sk.grid_for(params, xy.shape[0])
    assert grid.n_chunks > n_dev // 2  # the split has real shape

    mp = plan_mod.MeshPlacement(rep_mesh(n_dev))
    shards = sk.placement_shards(mp, grid.n_chunks)
    # a partition: disjoint, complete, one shard per device (capped
    # by the chunk count), dealt round-robin
    assert len(shards) == min(n_dev, grid.n_chunks)
    assert sorted(c for s in shards for c in s) == \
        list(range(grid.n_chunks))

    meshed = sk.release_window(xy, params, wkey, placement=mp)
    mono = sk.release_window(xy, params, wkey)
    assert meshed == mono  # dict equality over floats == bitwise


def test_release_window_rejects_shards_and_placement():
    from dpcorr.stream import sketch as sk

    params = sk.ReleaseParams(family="ni_sign", eps1=1.0, eps2=1.0,
                              target_chunk=64)
    xy = np.zeros((32, 2), dtype=np.float32)
    wkey = sk.window_key(rng.master_key(12), "w-both")
    with pytest.raises(ValueError, match="not both"):
        sk.release_window(xy, params, wkey, shards=[[0]],
                          placement=plan_mod.LocalPlacement())
