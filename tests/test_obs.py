"""Unit tests for the telemetry layer (dpcorr.obs; docs/OBSERVABILITY.md):
metrics registry + Prometheus exposition, span tracer + Chrome export,
and the privacy-budget audit trail with its replay arithmetic."""

from __future__ import annotations

import json
import math
import threading

import pytest

from dpcorr.obs import (
    LATENCY_BUCKETS,
    AuditTrail,
    Registry,
    Tracer,
    parse_exposition,
    read_events,
    read_spans,
    replay,
    timeline,
    to_chrome_trace,
)
from dpcorr.obs import trace as obs_trace


# -------------------------------------------------------------- metrics ----

def test_counter_and_gauge_basics():
    r = Registry()
    c = r.counter("t_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("t_gauge")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value() == 3.0


def test_labelled_counter_children():
    r = Registry()
    c = r.counter("t_refused_total", labelnames=("reason",))
    c.inc(reason="budget")
    c.inc(3, reason="overload")
    assert c.value(reason="budget") == 1.0
    assert c.value(reason="overload") == 3.0
    assert c.value(reason="never") == 0.0
    with pytest.raises(ValueError):  # undeclared label set
        c.inc(party="x")


def test_registry_idempotent_reregistration():
    r = Registry()
    a = r.counter("t_total")
    assert r.counter("t_total") is a
    with pytest.raises(ValueError):  # same name, different kind
        r.gauge("t_total")


def test_metric_name_validation():
    r = Registry()
    for bad in ("", "9lead", "has-dash", "has space"):
        with pytest.raises(ValueError):
            r.counter(bad)


def test_histogram_buckets_cumulative():
    r = Registry()
    h = r.histogram("t_lat_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(5.555)
    # cumulative: each bound counts everything at or below it
    assert snap["buckets"] == {"0.01": 1, "0.1": 2, "1.0": 3}
    samples = dict((f"{n}{lab}", v) for n, lab, v in h.samples())
    assert samples['t_lat_seconds_bucket{le="+Inf"}'] == 4.0
    assert samples["t_lat_seconds_count"] == 4.0


def test_histogram_rejects_bad_buckets():
    r = Registry()
    with pytest.raises(ValueError):
        r.histogram("t_h", buckets=())
    with pytest.raises(ValueError):
        r.histogram("t_h2", buckets=(-1.0, 1.0))


def test_render_parse_roundtrip():
    r = Registry()
    c = r.counter("t_req_total", "requests", labelnames=("mode",))
    c.inc(7, mode="batched")
    g = r.gauge("t_depth", "queue depth")
    g.set(3)
    h = r.histogram("t_lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    text = r.render()
    assert "# TYPE t_req_total counter" in text
    assert "# HELP t_depth queue depth" in text
    series = parse_exposition(text)
    assert series['t_req_total{mode="batched"}'] == 7.0
    assert series["t_depth"] == 3.0
    assert series['t_lat_seconds_bucket{le="0.1"}'] == 1.0
    assert series['t_lat_seconds_bucket{le="+Inf"}'] == 1.0
    assert series["t_lat_seconds_sum"] == 0.05


def test_label_value_escaping():
    r = Registry()
    c = r.counter("t_esc_total", labelnames=("p",))
    c.inc(p='a"b\\c\nd')
    text = r.render()
    assert '{p="a\\"b\\\\c\\nd"}' in text


def test_registry_thread_safety_concurrent_increments():
    """The ISSUE 2 smoke: concurrent increments lose no counts — the
    flush thread, many client threads and a scraper all mutate these."""
    r = Registry()
    c = r.counter("t_conc_total", labelnames=("who",))
    h = r.histogram("t_conc_lat", buckets=LATENCY_BUCKETS)
    n_threads, per_thread = 8, 2000

    def worker(w):
        for _ in range(per_thread):
            c.inc(who=str(w % 2))
            h.observe(0.01)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = c.value(who="0") + c.value(who="1")
    assert total == n_threads * per_thread
    assert h.snapshot()["count"] == n_threads * per_thread


# ---------------------------------------------------------------- spans ----

def test_disabled_tracer_is_free():
    tr = Tracer(None)
    sp = tr.start_span("x")
    assert sp is obs_trace._NULL_SPAN
    assert sp.context is None and sp.trace_id is None
    sp.set(a=1)
    sp.end()  # all no-ops
    with tr.span("y") as sp2:
        assert sp2 is obs_trace._NULL_SPAN


def test_span_jsonl_roundtrip_and_parenting(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    tr = Tracer(path)
    with tr.span("outer", n=4000) as outer:
        with tr.span("inner") as inner:
            inner.set(device_s=0.5)
        assert obs_trace.current_span() is outer
    spans = {s["name"]: s for s in read_spans(path)}
    assert spans["inner"]["trace_id"] == spans["outer"]["trace_id"]
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["outer"]["parent_id"] is None
    assert spans["outer"]["attrs"] == {"n": 4000}
    assert spans["inner"]["attrs"] == {"device_s": 0.5}
    assert spans["inner"]["dur_s"] <= spans["outer"]["dur_s"]


def test_span_error_stamped(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    tr = Tracer(path)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    (sp,) = read_spans(path)
    assert sp["attrs"]["error"] == "RuntimeError"


def test_explicit_cross_thread_parent(tmp_path):
    """The coalescer pattern: a root span's context rides a queue and
    the flush thread parents its span explicitly."""
    path = str(tmp_path / "spans.jsonl")
    tr = Tracer(path)
    root = tr.start_span("request")

    def flush():
        sp = tr.start_span("flush", parent=root.context)
        sp.end()

    t = threading.Thread(target=flush)
    t.start()
    t.join()
    root.end()
    spans = {s["name"]: s for s in read_spans(path)}
    assert spans["flush"]["trace_id"] == spans["request"]["trace_id"]
    assert spans["flush"]["parent_id"] == spans["request"]["span_id"]
    assert spans["flush"]["thread"] != spans["request"]["thread"]


def test_read_spans_rejects_bad_line(tmp_path):
    path = tmp_path / "bad.jsonl"
    path.write_text('{"name": "a", "dur_s": 0.1}\nnot json\n')
    with pytest.raises(ValueError, match="bad.jsonl:2"):
        read_spans(str(path))
    path.write_text('{"no": "span fields"}\n')
    with pytest.raises(ValueError, match="not a span"):
        read_spans(str(path))


def test_chrome_trace_export(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    tr = Tracer(path)
    with tr.span("a", n=1):
        with tr.span("b"):
            pass
    doc = to_chrome_trace(path)
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["name"] for e in events} == {"a", "b"}
    assert all(e["ts"] > 0 and e["dur"] >= 0 for e in events)
    assert meta and meta[0]["name"] == "thread_name"
    a = next(e for e in events if e["name"] == "a")
    assert a["args"]["n"] == 1 and a["args"]["trace_id"]


def test_configure_installs_process_tracer(tmp_path):
    path = str(tmp_path / "global.jsonl")
    tr = obs_trace.configure(path)
    try:
        assert obs_trace.tracer() is tr
        with obs_trace.tracer().span("g"):
            pass
    finally:
        obs_trace.configure(None)
    assert not obs_trace.tracer().enabled
    assert [s["name"] for s in read_spans(path)] == ["g"]


# ---------------------------------------------------------------- audit ----

def test_audit_memory_and_kinds():
    trail = AuditTrail()
    ev = trail.record("charge", {"a": 1.0}, trace_id="t1", extra=7)
    assert ev["seq"] == 0 and ev["kind"] == "charge"
    assert ev["charges"] == {"a": 1.0} and ev["trace_id"] == "t1"
    assert ev["extra"] == 7
    trail.record("refund", {"a": 0.5})
    with pytest.raises(ValueError):
        trail.record("spend", {"a": 1.0})
    assert [e["seq"] for e in trail.events()] == [0, 1]


def test_audit_file_append_and_seq_resume(tmp_path):
    path = str(tmp_path / "audit.jsonl")
    t1 = AuditTrail(path)
    t1.record("charge", {"a": 1.0})
    t1.close()
    t2 = AuditTrail(path)  # restart: seq continues past the tail
    t2.record("refusal", {"a": 9.0}, party="a", spent=1.0, budget=2.0)
    t2.close()
    events = read_events(path)
    assert [e["seq"] for e in events] == [0, 1]
    assert events[1]["party"] == "a"


def test_read_events_rejects_bad_lines(tmp_path):
    p = tmp_path / "a.jsonl"
    p.write_text('{"kind": "charge", "charges": {}}\n{"kind": "nope"}\n')
    with pytest.raises(ValueError, match="not an audit event"):
        read_events(str(p))


def test_replay_and_timeline_arithmetic():
    events = [
        {"seq": 0, "ts": 1.0, "kind": "charge", "charges": {"a": 2.0},
         "trace_id": "t0"},
        {"seq": 1, "ts": 2.0, "kind": "refusal",
         "charges": {"a": 99.0, "b": 1.0}, "trace_id": "t1"},
        {"seq": 2, "ts": 3.0, "kind": "charge",
         "charges": {"a": 1.0, "b": 0.5}, "trace_id": "t2"},
        {"seq": 3, "ts": 4.0, "kind": "refund", "charges": {"b": 2.0},
         "trace_id": "t3"},
    ]
    spent = replay(events)
    assert spent == {"a": 3.0, "b": 0.0}  # refund clamps at zero
    rows = timeline(events)
    assert [r["kind"] for r in rows] == ["charge", "refusal", "charge",
                                         "refund"]
    assert rows[1]["spent_after"]["a"] == 2.0  # refusal spends nothing
    assert rows[3]["spent_after"]["b"] == 0.0
    only_b = timeline(events, party="b")
    assert [r["seq"] for r in only_b] == [1, 2, 3]


# ------------------------------------------------------------------ CLI ----

def _budget_cli(argv, capsys):
    from dpcorr.__main__ import main

    main(argv)
    return capsys.readouterr().out


def test_obs_budget_cli_replays_trail(tmp_path, capsys):
    path = str(tmp_path / "audit.jsonl")
    trail = AuditTrail(path)
    trail.record("charge", {"a": 2.0, "b": 1.0}, trace_id="t0")
    trail.record("refund", {"b": 1.0}, trace_id="t1")
    trail.record("refusal", {"a": 50.0}, trace_id="t2", party="a",
                 spent=2.0, budget=3.0)
    trail.close()

    out = json.loads(_budget_cli(
        ["obs", "budget", "--audit", path, "--json"], capsys))
    assert out["events"] == 3
    assert out["spent"] == {"a": 2.0, "b": 0.0}
    assert [r["trace_id"] for r in out["timeline"]] == ["t0", "t1", "t2"]

    text = _budget_cli(["obs", "budget", "--audit", path], capsys)
    assert "refusal" in text and "replayed spend" in text

    only_a = json.loads(_budget_cli(
        ["obs", "budget", "--audit", path, "--party", "a", "--json"],
        capsys))
    assert only_a["spent"] == {"a": 2.0}
    assert [r["seq"] for r in only_a["timeline"]] == [0, 2]


def test_obs_chrome_cli(tmp_path, capsys):
    from dpcorr.__main__ import main

    spans = str(tmp_path / "spans.jsonl")
    tr = Tracer(spans)
    with tr.span("a"):
        pass
    out = str(tmp_path / "chrome.json")
    main(["obs", "chrome", "--trace", spans, "--out", out])
    doc = json.load(open(out))
    assert any(e.get("name") == "a" for e in doc["traceEvents"])


def test_parse_exposition_special_values():
    assert parse_exposition('x 1\ny{le="+Inf"} +Inf\n# comment\n') == {
        "x": 1.0, 'y{le="+Inf"}': math.inf}
