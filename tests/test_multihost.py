"""Multi-host grid fan-out tests (SURVEY.md §2.3 DCN fan-out; VERDICT r1
missing #5): deterministic bucket partition, and a real 2-worker-process
run that must be bit-identical to the single-host grid."""

from __future__ import annotations

import numpy as np
import pytest

from dpcorr.grid import GridConfig, run_grid
from dpcorr.parallel.multihost import grid_slice, run_grid_multihost

GCFG = dict(n_grid=(200, 300), rho_grid=(0.0, 0.5),
            eps_pairs=((1.0, 1.0), (2.0, 1.0)), b=8)


class TestGridSlice:
    def test_partition_is_exact(self):
        design = GridConfig(**GCFG).design_points()
        for n_hosts in (1, 2, 3, 5):
            got = [grid_slice(design, h, n_hosts) for h in range(n_hosts)]
            ids = sorted(i for s in got for i in s.i)
            assert ids == sorted(design.i)  # disjoint and complete

    def test_hosts_own_whole_buckets(self):
        design = GridConfig(**GCFG).design_points()
        buckets = [set(map(tuple, s[["n", "eps1", "eps2"]].values))
                   for s in (grid_slice(design, h, 2) for h in range(2))]
        assert buckets[0] and buckets[1]
        assert not (buckets[0] & buckets[1])

    def test_bad_host_id(self):
        design = GridConfig(**GCFG).design_points()
        with pytest.raises(ValueError):
            grid_slice(design, 2, 2)


def test_multihost_matches_single_host(tmp_path, monkeypatch):
    monkeypatch.setenv("DPCORR_HOST_PLATFORM", "cpu")
    gcfg = GridConfig(**GCFG, backend="bucketed",
                      out_dir=str(tmp_path / "mh"))
    res = run_grid_multihost(gcfg, n_hosts=2)
    ref = run_grid(GridConfig(**GCFG))  # single host, no cache
    assert list(res.detail_all.columns) == list(ref.detail_all.columns)
    for col in ref.detail_all.columns:
        np.testing.assert_array_equal(res.detail_all[col].to_numpy(),
                                      ref.detail_all[col].to_numpy(),
                                      err_msg=col)


def test_multihost_local_backend_honored(tmp_path, monkeypatch):
    """gcfg.backend != 'bucketed' must run the per-point path in each
    worker (not silently the bucketed one) and still merge bit-identically."""
    monkeypatch.setenv("DPCORR_HOST_PLATFORM", "cpu")
    small = dict(GCFG, n_grid=(200,), rho_grid=(0.0, 0.5),
                 eps_pairs=((1.0, 1.0),))
    gcfg = GridConfig(**small, backend="local",
                      out_dir=str(tmp_path / "mh_local"))
    res = run_grid_multihost(gcfg, n_hosts=2)
    ref = run_grid(GridConfig(**small))
    for col in ref.detail_all.columns:
        np.testing.assert_array_equal(res.detail_all[col].to_numpy(),
                                      ref.detail_all[col].to_numpy(),
                                      err_msg=col)


def test_multihost_requires_out_dir():
    with pytest.raises(ValueError, match="out_dir"):
        run_grid_multihost(GridConfig(**GCFG), n_hosts=2)


def _jax_supports_multiprocess_cpu() -> bool:
    # jax < 0.5 CPU backends reject cross-process computations outright
    # ("Multiprocess computations aren't implemented on the CPU
    # backend") — the local 2-process cluster rehearsal needs the CPU
    # collectives stack that ships with newer jax
    import jax

    return tuple(int(x) for x in jax.__version__.split(".")[:2]) >= (0, 5)


@pytest.mark.skipif(not _jax_supports_multiprocess_cpu(),
                    reason="multiprocess CPU collectives unimplemented "
                           "in this jax's CPU backend")
def test_distributed_cluster_matches_single_host(tmp_path, monkeypatch):
    """VERDICT r2 #7: the fan-out over a *real* ``jax.distributed``
    runtime — a local 2-process CPU cluster (2 virtual devices per worker,
    4 global) where each worker derives its slice from
    ``jax.process_index()``/``process_count()``, runs the sharded bucketed
    backend over its local mesh, and rank 0 merges after the global
    barrier. Results must be bit-identical to the plain single-host grid."""
    monkeypatch.setenv("DPCORR_HOST_PLATFORM", "cpu")
    gcfg = GridConfig(**GCFG, backend="bucketed-sharded",
                      out_dir=str(tmp_path / "dist"))
    res = run_grid_multihost(gcfg, n_hosts=2, distributed=True,
                             local_device_count=2)
    hosts = sorted(res.timings.attrs["hosts"], key=lambda r: r["host_id"])
    assert [h["host_id"] for h in hosts] == [0, 1]
    assert all(h["process_count"] == 2 for h in hosts)
    assert all(h["global_devices"] == 4 for h in hosts)
    assert all(h["local_devices"] == 2 for h in hosts)
    assert [h["merged"] for h in hosts] == [True, False]
    ref = run_grid(GridConfig(**GCFG))  # single host, no cache
    for col in ref.detail_all.columns:
        np.testing.assert_array_equal(res.detail_all[col].to_numpy(),
                                      ref.detail_all[col].to_numpy(),
                                      err_msg=col)
