"""Flight recorder, cost attribution and the ops console (ISSUE 9).

The recorder's contract: always-on bounded rings, atomic dumps on the
failure triggers, and a dump from which ``dpcorr obs`` tooling rebuilds
one request's span chain + cost record + ε trail with no jax import.
The tests mirror that split: ring/dump mechanics (pure), trigger wiring
(chaos raise mode, module-level install), reconstruction ordering,
cost arithmetic, the jax-free CLI, the console renderer, and one
end-to-end pass through a live server.
"""

import http.server
import json
import subprocess
import sys
import threading

import pytest

from dpcorr import chaos
from dpcorr.obs import trace as obs_trace
from dpcorr.obs.audit import AuditTrail
from dpcorr.obs.console import render_frame, run_top
from dpcorr.obs.cost import CostRecord, CostRegistry, ExemplarStore
from dpcorr.obs.recorder import (
    FlightRecorder,
    install,
    read_dump,
    reconstruct,
    trigger,
)


def _span(name, i=0, trace="t0001", parent=None, ts=None):
    return {"name": name, "trace_id": trace, "span_id": f"s{i:04x}",
            "parent_id": parent, "ts": float(i if ts is None else ts),
            "dur_s": 0.001, "thread": "main", "attrs": {}}


# ------------------------------------------------------- rings + dumps ----
def test_rings_are_bounded_per_kind():
    rec = FlightRecorder("/tmp/unused.json", capacity=4)
    for i in range(10):
        rec.record_span(_span("s", i))
        rec.record_audit({"seq": i})
        rec.record_log({"message": str(i)})
    snap = rec.snapshot("cli")
    assert [sp["span_id"] for sp in snap["spans"]] == \
        [f"s{i:04x}" for i in range(6, 10)]
    assert [ev["seq"] for ev in snap["audit"]] == [6, 7, 8, 9]
    assert len(snap["logs"]) == 4


def test_capacity_must_be_positive():
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder("/tmp/unused.json", capacity=0)


def test_tracer_and_audit_observers_feed_the_rings(tmp_path):
    rec = FlightRecorder(str(tmp_path / "d.json"))
    tr = obs_trace.Tracer()
    tr.add_observer(rec.record_span)
    with tr.span("outer"):
        with tr.span("inner"):
            pass
    trail = AuditTrail()
    trail.add_observer(rec.record_audit)
    trail.record("charge", {"px": 2.0}, trace_id="tabc")
    snap = rec.snapshot("cli")
    assert [sp["name"] for sp in snap["spans"]] == ["inner", "outer"]
    assert snap["audit"][0]["charges"] == {"px": 2.0}


def test_logging_handler_feeds_the_log_ring(tmp_path):
    import logging

    rec = FlightRecorder(str(tmp_path / "d.json"))
    rec.attach_logging("dpcorr.test_ring")
    try:
        logging.getLogger("dpcorr.test_ring.sub").warning("queue %d", 7)
    finally:
        rec.detach_logging("dpcorr.test_ring")
    logs = rec.snapshot("cli")["logs"]
    assert logs and logs[-1]["message"] == "queue 7"
    assert logs[-1]["level"] == "WARNING"


def test_dump_roundtrip_and_reason_history(tmp_path):
    path = str(tmp_path / "rec" / "dump.json")  # parent dir is created
    rec = FlightRecorder(path)
    rec.record_span(_span("serve.request"))
    assert rec.dump("breaker_open", family="ni_sign") == path
    rec.dump("brownout_exit")
    doc = read_dump(path)
    assert doc["reason"] == "brownout_exit"  # newest incident wins
    assert rec.reasons == ["breaker_open", "brownout_exit"]
    assert rec.last_reason == "brownout_exit"
    assert rec.dumps == 2
    assert doc["spans"][0]["name"] == "serve.request"


def test_read_dump_is_strict(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("{\"version\": 1, \"truncated")
    with pytest.raises(json.JSONDecodeError):
        read_dump(str(p))
    p.write_text("[1, 2]")
    with pytest.raises(ValueError, match="not a JSON object"):
        read_dump(str(p))
    p.write_text(json.dumps({"version": 99, "reason": "x"}))
    with pytest.raises(ValueError, match="version"):
        read_dump(str(p))
    doc = {"version": 1, "reason": "cli", "ts": 0.0, "spans": [],
           "audit": [], "logs": [], "metrics": {}}  # no "costs"
    p.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="costs"):
        read_dump(str(p))


# ------------------------------------------------------------- triggers ----
def test_trigger_without_installed_recorder_is_noop():
    install(None)
    assert trigger("breaker_open") is None


def test_trigger_dumps_installed_recorder_and_never_raises(tmp_path):
    rec = FlightRecorder(str(tmp_path / "d.json"))
    install(rec)
    try:
        assert trigger("breaker_open", family="ni_sign") is not None
        assert read_dump(str(tmp_path / "d.json"))["detail"] == \
            {"family": "ni_sign"}
        # an unwritable path must not let the trigger raise into the
        # failure path that called it
        blocker = tmp_path / "flat"
        blocker.write_text("")
        install(FlightRecorder(str(blocker / "x" / "d.json")))
        assert trigger("brownout_enter") is None
    finally:
        install(None)


def test_chaos_raise_mode_crash_dumps_before_propagating(tmp_path):
    path = str(tmp_path / "chaos.json")
    rec = FlightRecorder(path)
    rec.record_span(_span("gate.charge"))
    hook = lambda point: rec.dump("chaos", point=point)  # noqa: E731
    chaos.on_crash(hook)
    chaos.install(chaos.ChaosPlan("gate.post_charge", hit=1, mode="raise"))
    try:
        with pytest.raises(chaos.SimulatedCrash):
            chaos.point("gate.post_charge")
    finally:
        chaos.clear()
        chaos.remove_crash_hook(hook)
    doc = read_dump(path)
    assert doc["reason"] == "chaos"
    assert doc["detail"] == {"point": "gate.post_charge"}
    assert doc["spans"][0]["name"] == "gate.charge"


# -------------------------------------------------------- reconstruction ----
def test_reconstruct_orders_parents_before_children():
    spans = [
        _span("serve.kernel", 4, parent="s0003"),
        _span("serve.request", 1, parent=None),
        _span("serve.flush", 3, parent="s0001"),
        _span("serve.admit", 2, parent="s0001"),
        _span("other.request", 9, trace="t9999"),
    ]
    dump = {"spans": spans, "audit": [
        {"kind": "charge", "charges": {"px": 2.0}, "trace_id": "t0001"},
        {"kind": "refund", "charges": {"px": 2.0}, "trace_id": "t0001"},
    ], "costs": {"t0001": {"kernel_s": 0.0}}}
    story = reconstruct(dump, "t0001")
    names = [sp["name"] for sp in story["spans"]]
    assert names[0] == "serve.request"
    assert names.index("serve.flush") < names.index("serve.kernel")
    assert "other.request" not in names
    assert story["cost"] == {"kernel_s": 0.0}
    assert story["eps_net"] == {"px": 0.0}  # charge fully refunded


def test_reconstruct_surfaces_orphans_last():
    spans = [
        _span("serve.request", 1, parent=None),
        _span("serve.kernel", 5, parent="sFFFF"),  # parent evicted
    ]
    story = reconstruct({"spans": spans, "audit": [], "costs": {}},
                        "t0001")
    assert [sp["name"] for sp in story["spans"]] == \
        ["serve.request", "serve.kernel"]


# ---------------------------------------------------------------- costs ----
def test_cost_record_arithmetic_and_clamp():
    c = CostRecord("t0001")
    c.charge({"px": 2.0, "py": 1.0})
    c.refund({"px": 2.0}, "expired")
    c.set_queue_wait(0.25)
    c.add_kernel(0.003)
    c.add_compile_wait(1.5)
    d = c.to_dict()
    assert d["eps_net"] == {"px": 0.0, "py": 1.0}
    assert d["queue_wait_s"] == 0.25
    assert d["kernel_s"] == 0.003
    assert d["compile_wait_s"] == 1.5
    assert "refund:expired" in d["events"]


def test_cost_registry_is_bounded_lru():
    reg = CostRegistry(capacity=3)
    for i in range(5):
        reg.new(f"t{i}")
    assert reg.get("t0") is None and reg.get("t1") is None
    assert set(reg.to_dict()) == {"t2", "t3", "t4"}
    agg = reg.aggregate()
    assert agg["records"] == 3


def test_exemplar_store_links_buckets_to_traces():
    ex = ExemplarStore(buckets=(0.1, 1.0))
    ex.record(0.05, "tfast")
    ex.record(0.5, "tslow")
    ex.record(0.07, None)  # no trace: must not clobber
    snap = ex.snapshot()
    assert snap["0.1"]["trace_id"] == "tfast"
    assert snap["1.0"]["trace_id"] == "tslow"


# ------------------------------------------------------------------ CLI ----
def test_obs_dump_cli_is_jax_free(tmp_path):
    path = str(tmp_path / "dump.json")
    rec = FlightRecorder(path)
    rec.record_span(_span("serve.request", 1, parent=None))
    rec.record_span(_span("serve.kernel", 2, parent="s0001"))
    rec.dump("breaker_open")
    script = (
        "import sys\n"
        "sys.modules['jax'] = None\n"  # any jax import now explodes
        "sys.argv = ['dpcorr', 'obs', 'dump', %r, '--trace-id', 't0001',"
        " '--json']\n"
        "from dpcorr.__main__ import main\n"
        "main()\n" % path)
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    story = json.loads(out.stdout)
    assert [sp["name"] for sp in story["spans"]] == \
        ["serve.request", "serve.kernel"]


# -------------------------------------------------------------- console ----
CANNED_STATS = {
    "queue_depth": 3, "flush_ewma_s": 0.004,
    "breaker": {"open": 1, "half_open": 0,
                "tripped_buckets": {"ni_sign/n=128": "open"}},
    "brownout_active": True,
    "slo": {"burn_rate": 0.125, "window_requests": 64, "slo_s": 0.25,
            "window_s": 60.0},
    "kernel_compiles": 2, "kernel_hits": 30, "kernel_compile_dedup": 1,
    "kernel_cache_size": 2,
    "latency_s": {"p50": 0.003, "p99": 0.031},
    "exemplars": {"0.05": {"trace_id": "tdead", "value": 0.031}},
    "costs": {"records": 32, "kernel_s": 0.08, "queue_wait_s": 1.2,
              "compile_wait_s": 4.0},
    "requests_total": 40, "refused": {"budget": 2}, "shed": {},
    "requests_failed": 1,
    "ledger": {"parties": {"px": {"spent": 9.0, "budget": 100.0},
                           "py": 3.0}},
}


def test_render_frame_shows_the_operator_story():
    frame = render_frame(CANNED_STATS, {}, now=0.0)
    assert "queue depth" in frame and "     3" in frame
    assert "1 open" in frame and "ni_sign/n=128" in frame
    assert "brownout    : ACTIVE" in frame
    assert "12.50%" in frame          # slo burn
    assert "trace=tdead" in frame     # exemplar link
    assert "px=9" in frame            # top-ε principal
    assert "2 refused" in frame


class _CannedHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path == "/stats":
            body = json.dumps(CANNED_STATS).encode()
            ctype = "application/json"
        elif self.path == "/metrics":
            body = b"dpcorr_serve_queue_depth 3\n"
            ctype = "text/plain"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def test_run_top_once_against_canned_server():
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                            _CannedHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        lines: list[str] = []
        rc = run_top(f"http://127.0.0.1:{httpd.server_address[1]}",
                     once=True, out=lines.append)
        assert rc == 0
        assert "brownout    : ACTIVE" in "\n".join(lines)
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_run_top_once_unreachable_server_fails():
    rc = run_top("http://127.0.0.1:9", once=True, out=lambda s: None)
    assert rc == 1


# ----------------------------------------------------------- end-to-end ----
@pytest.mark.slow
def test_server_cost_records_and_dump_reconstruction(tmp_path):
    from dpcorr.serve.request import EstimateRequest
    from dpcorr.serve.server import DpcorrServer

    path = str(tmp_path / "flight.json")
    srv = DpcorrServer(budget=1e6, max_delay_s=0.001, shard="off",
                       audit=AuditTrail())
    rec = FlightRecorder(path)
    srv.attach_recorder(rec)
    try:
        req = EstimateRequest(family="ni_sign", n=64, eps1=1.0, eps2=1.0,
                              seed=7, parties=("e2e-x", "e2e-y"))
        resp = srv.estimate(req, timeout=300)
        assert resp.cost is not None
        assert resp.cost["kernel_s"] >= 0.0
        assert resp.cost["eps_net"] == {"e2e-x": 2.0, "e2e-y": 1.0}
        snap = srv.stats_snapshot()
        assert snap["costs"]["records"] == 1
        rec.dump("cli")
    finally:
        srv.close()
        install(None)
    story = reconstruct(read_dump(path), resp.trace_id)
    names = [sp["name"] for sp in story["spans"]]
    assert names[0] == "serve.request" and "serve.kernel" in names
    assert story["cost"]["trace_id"] == resp.trace_id
    assert story["eps_net"] == {"e2e-x": 2.0, "e2e-y": 1.0}
