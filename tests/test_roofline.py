"""Roofline/MFU accounting units (docs/PERFORMANCE.md "MFU / roofline")."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dpcorr.utils.profiling import Throughput
from dpcorr.utils.roofline import (CPU_CORE, TPU_V5E, analytic_rep_model,
                                   peaks_for, summarize, xla_cost)


def test_analytic_model_scales_with_n():
    a = analytic_rep_model(10_000, 1.0, 1.0)
    b = analytic_rep_model(20_000, 1.0, 1.0)
    assert b["flops_per_rep"] == 2 * a["flops_per_rep"]
    assert b["bytes_per_rep_floor"] == 2 * a["bytes_per_rep_floor"]
    # batch geometry matches the estimators' (m = ceil(8/(e1 e2)) cap n)
    assert a["batch_geometry"] == {"m": 8, "k": 1250}


def test_summarize_math_and_bound():
    s = summarize(1e6, 2e6, 1.6e5, TPU_V5E)
    np.testing.assert_allclose(s["achieved_flops_per_sec"], 2e12)
    np.testing.assert_allclose(s["achieved_bytes_per_sec"], 1.6e11)
    assert s["bound"] == "vpu"  # 2e12/3.9e12 > 1.6e11/8.19e11
    assert 0 < s["pct_of_vpu_peak"] < 100
    hbm_bound = summarize(1e6, 1e3, 1e6, TPU_V5E)
    assert hbm_bound["bound"] == "hbm"


def test_peaks_for_platforms():
    assert peaks_for("tpu") is TPU_V5E
    assert peaks_for("axon") is TPU_V5E
    assert peaks_for("cpu") is CPU_CORE


def test_xla_cost_counts_a_known_matmul():
    """cost_analysis of an (m,k)@(k,n) matmul must report ~2mkn flops."""
    m = k = n = 256

    def f(a, b):
        return a @ b

    a = jnp.ones((m, k), jnp.float32)
    b = jnp.ones((k, n), jnp.float32)
    cost = xla_cost(jax.jit(f), a, b)
    assert cost["flops"] >= 2 * m * k * n * 0.9
    assert cost["bytes"] >= (m * k + k * n + m * n) * 4 * 0.9


def test_throughput_utilization_wiring():
    tp = Throughput(n_devices=2)
    tp.add(2000)
    tp.seconds = 1.0
    u = tp.utilization(1e6, 1e5, platform="cpu")
    np.testing.assert_allclose(u["reps_per_sec"], 1000.0)  # per chip
    np.testing.assert_allclose(u["achieved_flops_per_sec"], 1e9)
    assert u["peaks"]["name"] == "cpu-core"
