"""The dpcorr stream subsystem: mergeable sketches, event-time
windows, WAL/journal durability, and the crash-exact release sequence.

The load-bearing properties, each pinned here:

- **Sketch associativity** — ``release_window`` is *bitwise* identical
  under every shard partition of the chunk grid (merge is a disjoint
  dict union; the fold is one fixed-order reduction).
- **Crash exactness** — a ``SimulatedCrash`` at each registered stream
  chaos point, followed by recovery + full client re-send, yields a
  byte-identical release feed and exactly-once ε (idempotent per-window
  charge ids). The subprocess/kill -9 form of the same gate lives in
  ``benchmarks/stream_load.py`` and the CI stream-smoke job.
- **Durability discipline** — the WAL/journal tolerate exactly one torn
  tail line and quarantine anything worse.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from dpcorr import chaos
from dpcorr.obs.console import render_stream_frame
from dpcorr.serve.ledger import release_factor
from dpcorr.stream import sketch
from dpcorr.stream.http import make_stream_http_server
from dpcorr.stream.service import (
    StreamOverloadedError,
    StreamService,
    window_charges,
)
from dpcorr.stream.sketch import ReleaseParams, SketchState, release_window
from dpcorr.stream.wal import IngestWAL, ReleaseJournal, StreamCorruptError
from dpcorr.stream.windows import (
    LateRecordError,
    WindowManager,
    WindowSpec,
)
from dpcorr.utils.rng import master_key

FAMILIES = ("ni_sign", "ni_subg", "int_sign", "int_subg")


def _rows(n, seed=0):
    r = np.random.default_rng(seed)
    return np.clip(r.normal(size=(n, 2)), -3.0, 3.0).astype(np.float32)


# ---------------------------------------------------------- windows ----
class TestWindowSpec:
    def test_tumbling_spans(self):
        spec = WindowSpec(size_s=10.0)
        assert spec.spans_for(25.0) == [(20.0, 30.0)]
        assert spec.spans_for(20.0) == [(20.0, 30.0)]  # half-open start
        assert spec.hop_s == 10.0

    def test_sliding_spans(self):
        spec = WindowSpec(size_s=10.0, slide_s=5.0)
        assert spec.spans_for(12.0) == [(5.0, 15.0), (10.0, 20.0)]
        # near the origin the negative-start spans are clipped away
        assert spec.spans_for(3.0) == [(0.0, 10.0)]
        assert spec.hop_s == 5.0

    def test_window_id_is_millisecond_exact(self):
        assert WindowSpec.window_id((7.5, 17.5)) == "7500-17500"
        assert WindowSpec.window_id((0.0, 10.0)) == "0-10000"

    def test_validation(self):
        with pytest.raises(ValueError):
            WindowSpec(size_s=0.0)
        with pytest.raises(ValueError):
            WindowSpec(size_s=10.0, slide_s=11.0)  # slide > size
        with pytest.raises(ValueError):
            WindowSpec(size_s=10.0, late_s=-1.0)
        with pytest.raises(ValueError):
            WindowSpec(size_s=10.0).spans_for(-1.0)


class TestWindowManager:
    def test_heartbeat_advances_watermark_without_windows(self):
        m = WindowManager(WindowSpec(size_s=10.0))
        assert m.admit(42.0, []) == []
        assert m.watermark == 42.0
        assert not m.windows

    def test_late_refusal_counts_and_raises(self):
        m = WindowManager(WindowSpec(size_s=10.0))
        m.admit(20.0, [(1.0, 2.0)])
        with pytest.raises(LateRecordError) as ei:
            m.admit(5.0, [(1.0, 2.0)])
        assert ei.value.watermark == 20.0
        assert m.late_refused == 1
        # an old-ts heartbeat is harmless: nothing to admit
        m.admit(5.0, [])
        assert m.watermark == 20.0

    def test_bounded_lateness_admits_between_watermark_and_max(self):
        m = WindowManager(WindowSpec(size_s=10.0, late_s=5.0))
        m.admit(20.0, [(0.0, 0.0)])
        assert m.watermark == 15.0
        m.admit(16.0, [(0.0, 0.0)])  # late but inside the bound
        with pytest.raises(LateRecordError):
            m.admit(14.0, [(0.0, 0.0)])

    def test_closable_is_watermark_gated_and_ordered(self):
        m = WindowManager(WindowSpec(size_s=10.0))
        m.admit(5.0, [(0.0, 0.0)])
        assert [w.id for w in m.closable()] == []  # watermark == 5 < 10
        m.admit(15.0, [(0.0, 0.0)])
        # watermark 15 passed the first window's end but not the second
        assert [w.id for w in m.closable()] == ["0-10000"]
        m.admit(25.0, [])
        assert [w.id for w in m.closable()] == ["0-10000", "10000-20000"]
        m.close("0-10000")
        assert [w.id for w in m.closable()] == ["10000-20000"]

    def test_closed_span_skip_still_feeds_open_siblings(self):
        """Recovery replay: rows whose earlier (journaled) span is
        closed must still land in the open sliding siblings."""
        m = WindowManager(WindowSpec(size_s=10.0, slide_s=5.0))
        m.close("5000-15000")
        hit = m.admit(12.0, [(1.0, 1.0)])
        assert hit == ["10000-20000"]
        assert m.reclosed_skips == 1
        assert "5000-15000" not in m.windows  # never resurrected


# --------------------------------------------------------- sketches ----
class TestSketchAssociativity:
    @pytest.mark.parametrize("family", FAMILIES)
    def test_shard_split_is_bitwise_identical(self, family):
        """The tentpole determinism claim: every partition of the chunk
        set releases the same bytes as the monolithic pass."""
        n = 600
        xy = _rows(n, seed=3)
        params = ReleaseParams(family, 0.9, 0.7, normalise=True,
                               target_chunk=128)
        grid = sketch.grid_for(params, n)
        assert grid.n_chunks >= 3, "need a real multi-chunk grid"
        wkey = sketch.window_key(master_key(77), "0-10000")
        ref = json.dumps(release_window(xy, params, wkey), sort_keys=True)
        ids = list(range(grid.n_chunks))
        partitions = [
            [ids[0::2], ids[1::2]],             # even/odd
            [ids[:1], ids[1:]],                 # head/tail
            [[c] for c in reversed(ids)],       # singletons, reversed
        ]
        for shards in partitions:
            got = json.dumps(release_window(xy, params, wkey,
                                            shards=shards),
                             sort_keys=True)
            assert got == ref, f"shard split {shards} diverged"

    def test_normalise_off_single_pass(self):
        n = 400
        xy = _rows(n, seed=4)
        params = ReleaseParams("ni_sign", 1.0, 1.0, normalise=False,
                               target_chunk=128)
        wkey = sketch.window_key(master_key(5), "w")
        ref = json.dumps(release_window(xy, params, wkey), sort_keys=True)
        grid = sketch.grid_for(params, n)
        ids = list(range(grid.n_chunks))
        got = json.dumps(
            release_window(xy, params, wkey, shards=[ids[1:], ids[:1]]),
            sort_keys=True)
        assert got == ref


class TestSketchState:
    def _sketches(self):
        xy = _rows(200, seed=9)
        params = ReleaseParams("int_subg", 1.0, 0.5, target_chunk=64)
        wkey = sketch.window_key(master_key(1), "w")
        grid = sketch.grid_for(params, 200)
        ids = list(range(grid.n_chunks))
        a = sketch.sketch_window(xy, params, wkey, chunk_ids=ids[0::2])
        b = sketch.sketch_window(xy, params, wkey, chunk_ids=ids[1::2])
        return a, b, params, wkey, grid

    def test_merge_order_invariant(self):
        a, b, params, wkey, _ = self._sketches()
        ab = json.dumps(sketch.release_from_sketch(a.merge(b), params,
                                                   wkey), sort_keys=True)
        ba = json.dumps(sketch.release_from_sketch(b.merge(a), params,
                                                   wkey), sort_keys=True)
        assert ab == ba

    def test_merge_rejects_meta_mismatch(self):
        a, _, params, wkey, _ = self._sketches()
        other = sketch.sketch_window(
            _rows(200, seed=9),
            ReleaseParams("int_subg", 2.0, 0.5, target_chunk=64), wkey)
        with pytest.raises(ValueError, match="different windows"):
            a.merge(other)

    def test_merge_rejects_conflicting_chunk(self):
        a, b, *_ = self._sketches()
        evil = SketchState(b.meta, dict(b.chunks))
        some = next(iter(evil.chunks))
        evil.chunks[some] = ((123.0,), (456.0,))
        merged = a.merge(b)
        with pytest.raises(ValueError, match="conflicting stats"):
            merged.merge(evil)

    def test_overlapping_identical_chunks_merge_fine(self):
        a, b, *_ = self._sketches()
        # recomputing the same chunk on two shards is legal
        assert a.merge(b).chunks == a.merge(b).merge(b).chunks

    def test_dict_roundtrip_preserves_bytes(self):
        a, b, params, wkey, _ = self._sketches()
        merged = a.merge(b)
        back = SketchState.from_dict(
            json.loads(json.dumps(merged.to_dict())))
        assert json.dumps(
            sketch.release_from_sketch(back, params, wkey),
            sort_keys=True) == json.dumps(
            sketch.release_from_sketch(merged, params, wkey),
            sort_keys=True)

    def test_incomplete_fold_refuses(self):
        a, _, params, wkey, _ = self._sketches()
        with pytest.raises(ValueError, match="incomplete"):
            sketch.release_from_sketch(a, params, wkey)

    def test_window_key_rejects_empty_id(self):
        with pytest.raises(ValueError):
            sketch.window_key(master_key(0), "")


# ------------------------------------------------------- durability ----
class TestIngestWAL:
    def test_append_replay_seq_continuity(self, tmp_path):
        p = str(tmp_path / "wal.jsonl")
        w = IngestWAL(p, fsync=False)
        assert w.append("b1", 1.0, [[1.0, 2.0]]) == 1
        assert w.append("b2", 2.0, []) == 2
        w.close()
        w2 = IngestWAL(p, fsync=False)
        recs = list(w2.replay())
        assert [r["batch_id"] for r in recs] == ["b1", "b2"]
        assert w2.append("b3", 3.0, []) == 3  # continues past replayed

    def test_torn_tail_is_dropped_silently(self, tmp_path):
        p = str(tmp_path / "wal.jsonl")
        w = IngestWAL(p, fsync=False)
        w.append("b1", 1.0, [])
        w.close()
        with open(p, "a") as fh:
            fh.write('{"seq": 2, "batch_id": "to')  # kill mid-append
        recs = list(IngestWAL(p, fsync=False).replay())
        assert [r["batch_id"] for r in recs] == ["b1"]

    def test_midfile_corruption_quarantines_and_raises(self, tmp_path):
        p = str(tmp_path / "wal.jsonl")
        w = IngestWAL(p, fsync=False)
        w.append("b1", 1.0, [])
        w.append("b2", 2.0, [])
        w.close()
        lines = open(p).read().splitlines()
        lines[0] = "NOT JSON"
        with open(p, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        with pytest.raises(StreamCorruptError):
            list(IngestWAL(p, fsync=False).replay())
        assert not os.path.exists(p)  # moved aside, not half-read
        assert any(f.startswith("wal.jsonl.corrupt")
                   for f in os.listdir(tmp_path))

    def test_compact_keeps_selected(self, tmp_path):
        p = str(tmp_path / "wal.jsonl")
        w = IngestWAL(p, fsync=False)
        for i in range(4):
            w.append(f"b{i}", float(i), [])
        w.compact(lambda r: r["batch_id"] in ("b2", "b3"))
        recs = list(IngestWAL(p, fsync=False).replay())
        assert [r["batch_id"] for r in recs] == ["b2", "b3"]


class TestReleaseJournal:
    def test_idempotent_append_and_seq(self, tmp_path):
        p = str(tmp_path / "rel.jsonl")
        j = ReleaseJournal(p, fsync=False)
        e1 = j.append("w1", {"rows": 3})
        assert e1["release_seq"] == 1
        again = j.append("w1", {"rows": 999})  # replayed release
        assert again == e1
        e2 = j.append("w2", {"rows": 5})
        assert e2["release_seq"] == 2
        j.close()
        j2 = ReleaseJournal(p, fsync=False)
        assert [e["window_id"] for e in j2.entries()] == ["w1", "w2"]
        assert "w1" in j2 and j2.get("w1")["rows"] == 3


# ---------------------------------------------------------- service ----
def _service(workdir, **kw):
    defaults = dict(
        spec=WindowSpec(size_s=10.0), families=("ni_sign",),
        eps1=0.8, eps2=0.8, normalise=False, budget=10.0, seed=7,
        fsync=False)
    defaults.update(kw)
    return StreamService(str(workdir), **defaults)


def _feed(sv, batches):
    """Send every batch, swallowing refusals the way a client would."""
    acks = []
    for bid, ts, rows in batches:
        try:
            acks.append(sv.ingest(bid, ts, rows))
        except (LateRecordError, StreamOverloadedError):
            acks.append(None)
    return acks


BATCHES = [
    ("b1", 1.0, [[0.5, 0.4], [-0.2, 0.3], [1.0, -1.0], [0.1, 0.2]]),
    ("b2", 4.0, [[0.3, 0.3], [-0.4, -0.5], [0.8, 0.9], [-1.0, 0.7]]),
    ("b3", 12.0, [[0.2, -0.2], [0.6, 0.5], [-0.7, -0.6], [0.9, 0.1]]),
    ("hb", 50.0, []),  # far-future heartbeat closes everything
]


class TestStreamService:
    def test_release_eps_and_feed(self, tmp_path):
        sv = _service(tmp_path)
        acks = _feed(sv, BATCHES)
        assert acks[-1]["released"]  # the heartbeat closed windows
        feed = sv.releases()
        assert [e["window_id"] for e in feed] == ["0-10000",
                                                 "10000-20000"]
        per = sv.per_window_charges
        assert per == {"party/x": 0.8, "party/y": 0.8}
        snap = sv.ledger.snapshot()
        for p in ("party/x", "party/y"):
            assert snap["parties"][p]["spent"] == pytest.approx(
                2 * per[p])
        e = feed[0]
        assert e["rows"] == 8 and e["eps_window"] == pytest.approx(1.6)
        assert e["charge_id"] == "stream:stream:0-10000"
        assert set(e["releases"]) == {"ni_sign"}
        assert {"rho", "lo", "hi"} <= set(e["releases"]["ni_sign"])
        # the subscribe cursor works
        assert [x["window_id"] for x in sv.releases(since=1)] == [
            "10000-20000"]
        sv.close()

    def test_dedup_is_free(self, tmp_path):
        sv = _service(tmp_path)
        sv.ingest("b1", 1.0, [[0.1, 0.2]])
        ack = sv.ingest("b1", 1.0, [[0.1, 0.2]])
        assert ack["deduped"] and ack["seq"] is None
        assert sv.stats()["seen_batches"] == 1
        sv.close()

    def test_refuse_before_release_spends_nothing(self, tmp_path):
        sv = _service(tmp_path, budget=0.5)  # < the 0.8 window charge
        _feed(sv, BATCHES)
        st = sv.stats()
        assert st["released"] == 0
        assert st["refused"] == ["0-10000", "10000-20000"]
        snap = sv.ledger.snapshot()
        assert snap["parties"] == {} or all(
            v["spent"] == 0.0 for v in snap["parties"].values())
        sv.close()

    def test_overload_backpressure(self, tmp_path):
        sv = _service(tmp_path, max_pending_rows=6)
        sv.ingest("b1", 1.0, [[0.0, 0.0]] * 5)
        with pytest.raises(StreamOverloadedError) as ei:
            sv.ingest("b2", 2.0, [[0.0, 0.0]] * 5)
        assert ei.value.retry_after_s > 0.0
        # the refused batch was NOT recorded: re-send succeeds later
        assert "b2" not in sv._seen
        sv.close()

    def test_late_refusal_maps_through(self, tmp_path):
        sv = _service(tmp_path)
        sv.ingest("b1", 100.0, [[0.0, 0.0]])
        with pytest.raises(LateRecordError):
            sv.ingest("b2", 5.0, [[0.0, 0.0]])
        assert sv.stats()["late_refused"] == 1
        sv.close()

    def test_stats_shape(self, tmp_path):
        sv = _service(tmp_path)
        st = sv.stats()
        assert st["eps_per_window"] == {"party/x": 0.8, "party/y": 0.8}
        assert st["watermark"] is None
        assert st["window"]["size_s"] == 10.0
        assert "dpcorr_stream_rows_total" in sv.render_metrics()
        sv.close()


class TestCrashExactRecovery:
    """SimulatedCrash at each stream chaos point; recovery + full
    client re-send must reproduce the reference feed byte-for-byte and
    spend each window's ε exactly once."""

    def _run_reference(self, workdir):
        sv = _service(workdir)
        _feed(sv, BATCHES)
        feed = json.dumps(sv.releases(), sort_keys=True)
        spent = {p: v["spent"]
                 for p, v in sv.ledger.snapshot()["parties"].items()}
        sv.close()
        return feed, spent

    @pytest.mark.parametrize("point,hit", [
        ("stream.mid_window", 1),   # first batch in WAL, not acked
        ("stream.mid_window", 3),   # mid-stream
        ("stream.pre_release", 1),  # window closable, nothing charged
        ("stream.post_journal", 1),  # journaled, not closed
    ])
    def test_crash_then_recover_bit_identical(self, tmp_path, point, hit):
        ref_feed, ref_spent = self._run_reference(tmp_path / "ref")
        work = tmp_path / "crash"
        chaos.install(chaos.ChaosPlan(point, hit=hit, mode="raise"))
        try:
            sv = _service(work)
            crashed = False
            for bid, ts, rows in BATCHES:
                try:
                    sv.ingest(bid, ts, rows)
                except chaos.SimulatedCrash:
                    crashed = True
                    break
            assert crashed, f"plan {point}#{hit} never fired"
        finally:
            chaos.clear()
        # recovery process: fresh service over the same workdir, client
        # re-sends EVERYTHING (acked batches dedup via the WAL seen-set)
        sv2 = _service(work)
        _feed(sv2, BATCHES)
        assert json.dumps(sv2.releases(), sort_keys=True) == ref_feed
        spent = {p: v["spent"]
                 for p, v in sv2.ledger.snapshot()["parties"].items()}
        assert spent == pytest.approx(ref_spent)  # exactly-once ε
        sv2.close()

    def test_post_journal_recovery_serves_from_journal(self, tmp_path):
        """A window journaled but not closed is NOT recomputed: the
        recovered feed entry is the journal's object, same release_seq,
        and the charge dedups."""
        work = tmp_path / "w"
        chaos.install(chaos.ChaosPlan("stream.post_journal", hit=1,
                                      mode="raise"))
        try:
            sv = _service(work)
            with pytest.raises(chaos.SimulatedCrash):
                _feed_raise(sv, BATCHES)
        finally:
            chaos.clear()
        journal_before = json.dumps(
            ReleaseJournal(str(work / "releases.jsonl"),
                           fsync=False).entries(), sort_keys=True)
        sv2 = _service(work)
        _feed(sv2, BATCHES)
        after = [e for e in sv2.releases()
                 if e["window_id"] == "0-10000"]
        assert json.dumps(after, sort_keys=True) == journal_before
        sv2.close()


def _feed_raise(sv, batches):
    for bid, ts, rows in batches:
        try:
            sv.ingest(bid, ts, rows)
        except (LateRecordError, StreamOverloadedError):
            pass


# ----------------------------------------------------------- charge ----
class TestWindowCharges:
    def test_matches_release_factor(self):
        got = window_charges(["ni_sign", "int_subg"], 0.4, 0.4, True,
                             "party/x", "party/y")
        want = 0.4 * release_factor("ni_sign", True) \
            + 0.4 * release_factor("int_subg", True)
        assert got == {"party/x": pytest.approx(want),
                       "party/y": pytest.approx(want)}
        assert want == pytest.approx(1.2)  # 2x sign + 1x subg

    def test_no_normalise_no_premium(self):
        got = window_charges(["ni_sign"], 0.4, 0.3, False, "x", "y")
        assert got == {"x": pytest.approx(0.4), "y": pytest.approx(0.3)}

    def test_asymmetric_parties_not_merged(self):
        got = window_charges(["int_sign"], 1.0, 0.5, False, "x", "y")
        assert got["x"] == pytest.approx(1.0)
        assert got["y"] == pytest.approx(0.5)


# ------------------------------------------------------------- chaos ----
class TestChaosRegistration:
    def test_stream_points_registered_not_in_matrix(self):
        for p in ("stream.pre_release", "stream.mid_window",
                  "stream.post_journal"):
            assert p in chaos.KNOWN_POINTS
            assert p not in chaos.MATRIX_POINTS  # the 2-party sweep
            chaos.ChaosPlan(p)  # constructible


# -------------------------------------------------------------- http ----
@pytest.fixture
def http_stream(tmp_path):
    sv = _service(tmp_path, max_pending_rows=64)
    srv = make_stream_http_server(sv, host="127.0.0.1", port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base, sv
    srv.shutdown()
    srv.server_close()
    t.join(timeout=5)
    sv.close()


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, dict(resp.headers), json.loads(
                resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=10) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


class TestStreamHTTP:
    def test_ingest_release_subscribe(self, http_stream):
        base, _sv = http_stream
        code, _, ack = _post(base, "/ingest", {
            "batch_id": "b1", "ts": 1.0,
            "rows": [[0.1, 0.2], [0.3, -0.4], [0.5, 0.6]]})
        assert code == 200 and ack["ok"] and ack["seq"] == 1
        code, _, ack = _post(base, "/ingest",
                             {"batch_id": "hb", "ts": 50.0})
        assert code == 200 and ack["released"] == ["0-10000"]
        code, _, body = _get(base, "/releases?since=0")
        feed = json.loads(body)["releases"]
        assert [e["window_id"] for e in feed] == ["0-10000"]
        code, _, body = _get(base, "/releases?since=1")
        assert json.loads(body)["releases"] == []

    def test_dedup_over_http(self, http_stream):
        base, _ = http_stream
        _post(base, "/ingest", {"batch_id": "b", "ts": 1.0,
                                "rows": [[0.0, 0.0]]})
        code, _, ack = _post(base, "/ingest",
                             {"batch_id": "b", "ts": 1.0,
                              "rows": [[0.0, 0.0]]})
        assert code == 200 and ack["deduped"]

    def test_late_is_400_with_watermark(self, http_stream):
        base, _ = http_stream
        _post(base, "/ingest", {"batch_id": "b1", "ts": 100.0,
                                "rows": [[0.0, 0.0]]})
        code, _, err = _post(base, "/ingest",
                             {"batch_id": "b2", "ts": 5.0,
                              "rows": [[0.0, 0.0]]})
        assert code == 400
        assert err["refused"] == "late" and err["watermark"] == 100.0

    def test_overload_is_429_with_retry_after(self, http_stream):
        base, _ = http_stream
        _post(base, "/ingest", {"batch_id": "b1", "ts": 1.0,
                                "rows": [[0.0, 0.0]] * 60})
        code, headers, err = _post(
            base, "/ingest", {"batch_id": "b2", "ts": 2.0,
                              "rows": [[0.0, 0.0]] * 10})
        assert code == 429 and err["refused"] == "overload"
        assert int(headers["Retry-After"]) >= 1

    def test_invalid_body_is_400(self, http_stream):
        base, _ = http_stream
        code, _, err = _post(base, "/ingest", {"ts": 1.0})
        assert code == 400 and "invalid ingest body" in err["error"]

    def test_stats_metrics_healthz_and_404(self, http_stream):
        base, _ = http_stream
        code, _, body = _get(base, "/stats")
        assert code == 200
        assert json.loads(body)["stream_id"] == "stream"
        code, headers, body = _get(base, "/metrics")
        assert code == 200 and b"dpcorr_stream_rows_total" in body
        assert headers["Content-Type"].startswith("text/plain")
        assert _get(base, "/healthz")[0] == 200
        assert _get(base, "/nope")[0] == 404

    def test_trigger_validates_reason(self, http_stream):
        base, _ = http_stream
        code, _, err = _post(base, "/obs/trigger",
                             {"reason": "not_a_reason"})
        assert code == 400 and "unknown trigger reason" in err["error"]


# ----------------------------------------------------------- console ----
class TestStreamConsole:
    def test_render_stream_frame_canned(self):
        stats = {
            "stream_id": "s1", "families": ["ni_sign", "int_subg"],
            "window": {"size_s": 10.0, "slide_s": 5.0, "late_s": 2.0},
            "watermark": 48.0, "open_windows": 2, "pending_rows": 37,
            "eps_per_window": {"party/x": 1.2, "party/y": 1.2},
            "released": 4, "refused": ["w9"], "late_refused": 3,
            "seen_batches": 11,
            "ledger": {"budget_default": 10.0, "parties": {
                "party/x": {"spent": 4.8, "budget": 10.0,
                            "remaining": 5.2}}},
        }
        metrics = {
            "dpcorr_stream_rows_total": 123.0,
            'dpcorr_stream_batches_total{kind="overload"}': 2.0,
            "dpcorr_stream_release_seconds_count": 4.0,
            "dpcorr_stream_release_seconds_sum": 0.8,
        }
        frame = render_stream_frame(stats, metrics, now=0.0)
        assert "s1" in frame and "ni_sign,int_subg" in frame
        assert "slide 5s" in frame and "late bound 2s" in frame
        assert "4 released" in frame and "1 refused" in frame
        assert "123 rows" in frame and "2 overload" in frame
        assert "3 late refused" in frame
        assert "200.00 ms mean over 4 windows" in frame
        assert "party/x" in frame

    def test_render_stream_frame_shows_watermark_lag(self):
        stats = {"stream_id": "s1", "families": ["ni_sign"],
                 "window": {"size_s": 10.0, "late_s": 0.0},
                 "watermark": 48.0, "watermark_lag_s": 7.25,
                 "open_windows": 0, "pending_rows": 0,
                 "eps_per_window": {}, "released": 0, "refused": [],
                 "late_refused": 0, "seen_batches": 0, "ledger": {}}
        frame = render_stream_frame(stats, {}, now=0.0)
        assert "lag 7.2s" in frame
        # older /stats without the key falls back to the gauge
        del stats["watermark_lag_s"]
        frame = render_stream_frame(
            stats, {"dpcorr_stream_watermark_lag_seconds": 3.0},
            now=0.0)
        assert "lag 3.0s" in frame

    def test_render_stream_frame_empty_window_table(self):
        # a just-started stream: no watermark, nothing released —
        # every line must still render (no KeyError, no math on None)
        stats = {"stream_id": "s1", "families": ["ni_sign"],
                 "window": {"size_s": 10.0, "late_s": 0.0},
                 "watermark": None, "open_windows": 0,
                 "pending_rows": 0, "eps_per_window": {},
                 "released": 0, "refused": [], "late_refused": 0,
                 "seen_batches": 0, "ledger": {}}
        frame = render_stream_frame(stats, {}, now=0.0)
        assert "watermark   : —   lag —" in frame
        assert "0 released" in frame and "0 batches" in frame
        assert "release     :" not in frame  # no windows → no mean

    def test_run_stream_top_down_target_rc_1(self, capsys):
        from dpcorr.obs.console import run_stream_top

        rc = run_stream_top("http://127.0.0.1:1", once=True)
        assert rc == 1
        assert "cannot scrape" in capsys.readouterr().out

    def test_run_stream_top_once_rc_0(self, http_stream, capsys):
        from dpcorr.obs.console import run_stream_top

        base, sv = http_stream
        _post(base, "/ingest", {"batch_id": "b1", "ts": 5.0,
                                "rows": [[0.1, 0.2]]})
        rc = run_stream_top(base, once=True)
        assert rc == 0
        out = capsys.readouterr().out
        assert "dpcorr obs top --stream" in out
        assert "watermark" in out and "lag" in out

    def test_retry_after_attribute(self):
        e = StreamOverloadedError(1.5)
        assert e.retry_after_s == 1.5 and "retry after" in str(e)
