"""R-bridge tests: the Python half of the reticulate seam."""

from __future__ import annotations

import numpy as np

from dpcorr import rbridge


def test_run_design_rows_schema():
    rows = [{"n": 400, "rho": 0.0, "eps1": 1.0, "eps2": 1.0},
            {"n": 600, "rho": 0.5, "eps1": 1.5, "eps2": 0.5}]
    df = rbridge.run_design_rows(rows, b=16)
    assert len(df) == 32
    assert list(df.columns[:1]) == ["repl"]
    for col in ("ni_hat", "int_hat", "ni_cover", "int_cover",
                "n", "rho_true", "eps1", "eps2"):
        assert col in df.columns
    assert sorted(df.n.unique()) == [400, 600]
    assert df.repl.max() == 16
    assert df.ni_cover.isin([0.0, 1.0]).all()


def test_run_design_rows_bucketed_bit_identical():
    """backend='bucketed' (the grid fast path, now reachable from R) must
    be bit-identical to the local path row for row (VERDICT r1 weak #6)."""
    rows = [{"n": 400, "rho": 0.0, "eps1": 1.0, "eps2": 1.0},
            {"n": 400, "rho": 0.5, "eps1": 1.0, "eps2": 1.0},
            {"n": 600, "rho": 0.5, "eps1": 1.5, "eps2": 0.5}]
    local = rbridge.run_design_rows(rows, b=16)
    buck = rbridge.run_design_rows(rows, b=16, backend="bucketed")
    assert list(local.columns) == list(buck.columns)
    for col in local.columns:
        np.testing.assert_array_equal(local[col].to_numpy(),
                                      buck[col].to_numpy(), err_msg=col)


def _r_call_kwargs(r_src: str, fn: str) -> set[str]:
    """Keyword names used in ``bridge$<fn>(...)`` calls inside backend.R."""
    import re

    m = re.search(rf"bridge\${fn}\((.*?)\)\n", r_src, re.S)
    assert m, f"backend.R never calls bridge${fn}"
    return set(re.findall(r"(\w+)\s*=", m.group(1)))


def test_backend_r_call_contract():
    """No R runtime in the image, so pin the reticulate call contract the
    executable way available: every keyword backend.R passes must be a real
    parameter of the Python function it calls."""
    import inspect
    from pathlib import Path

    r_src = (Path(__file__).parent.parent / "r" / "backend.R").read_text()
    for fn, py in (("run_design_rows", rbridge.run_design_rows),
                   ("run_hrs_sweep", rbridge.run_hrs_sweep)):
        params = set(inspect.signature(py).parameters)
        used = _r_call_kwargs(r_src, fn)
        assert used <= params, f"{fn}: backend.R passes {used - params}"


def test_frame_feeds_reference_downstream_unchanged():
    """The strongest R-free check of SURVEY.md §7 step 6: the bridge frame
    must contain every column the reference's own data.table summaries
    read (vert-cor.R:575-597), and running that exact grouped-summary
    recipe over it must work and produce coverage in [0,1]. (The remaining
    gap — executing backend.R under a real R/reticulate runtime — is
    environment-gated: no R interpreter exists in this image and installs
    are not allowed; docs/STATUS_r03.md records the gate.)"""
    rows = [{"n": 400, "rho": 0.0, "eps1": 1.0, "eps2": 1.0},
            {"n": 400, "rho": 0.5, "eps1": 1.0, "eps2": 1.0}]
    df = rbridge.run_design_rows(rows, b=16)
    # columns consumed by summ_INT / summ_NI (vert-cor.R:575-593)
    consumed = {"int_se2", "int_hat", "int_cover", "int_ci_len",
                "ni_se2", "ni_hat", "ni_cover", "ni_ci_len",
                "n", "rho_true", "eps1", "eps2"}
    assert consumed <= set(df.columns)
    # the reference's recipe, transliterated: group by design, mean metrics
    g = df.groupby(["n", "rho_true", "eps1", "eps2"])
    summ = g.agg(mse=("ni_se2", "mean"),
                 coverage=("ni_cover", "mean"),
                 ci_len=("ni_ci_len", "mean")).reset_index()
    summ["bias"] = (g["ni_hat"].mean().to_numpy()
                    - g["rho_true"].mean().to_numpy())
    assert len(summ) == 2
    assert summ.coverage.between(0, 1).all()
    assert np.isfinite(summ.mse).all()


def test_run_design_rows_deterministic():
    rows = [{"n": 300, "rho": 0.3, "eps1": 1.0, "eps2": 1.0}]
    a = rbridge.run_design_rows(rows, b=8)
    b = rbridge.run_design_rows(rows, b=8)
    assert np.allclose(a.ni_hat, b.ni_hat)
    # different master seed → different draws
    c = rbridge.run_design_rows(rows, b=8, seed=7)
    assert not np.allclose(a.ni_hat, c.ni_hat)


def test_fused_validation_fail_fast():
    """The bridge mirrors run_grid's fused fail-fast contract (a typo'd
    or non-bucketed fused request must raise, not silently run XLA)."""
    import pytest

    from dpcorr.rbridge import run_design_rows

    rows = [{"n": 400, "rho": 0.5, "eps1": 1.0, "eps2": 1.0}]
    with pytest.raises(ValueError, match="fused"):
        run_design_rows(rows, b=4, backend="local", fused="auto")
    with pytest.raises(ValueError, match="fused"):
        run_design_rows(rows, b=4, backend="bucketed", fused="Auto")
