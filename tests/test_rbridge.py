"""R-bridge tests: the Python half of the reticulate seam."""

from __future__ import annotations

import numpy as np

from dpcorr import rbridge


def test_run_design_rows_schema():
    rows = [{"n": 400, "rho": 0.0, "eps1": 1.0, "eps2": 1.0},
            {"n": 600, "rho": 0.5, "eps1": 1.5, "eps2": 0.5}]
    df = rbridge.run_design_rows(rows, b=16)
    assert len(df) == 32
    assert list(df.columns[:1]) == ["repl"]
    for col in ("ni_hat", "int_hat", "ni_cover", "int_cover",
                "n", "rho_true", "eps1", "eps2"):
        assert col in df.columns
    assert sorted(df.n.unique()) == [400, 600]
    assert df.repl.max() == 16
    assert df.ni_cover.isin([0.0, 1.0]).all()


def test_run_design_rows_bucketed_bit_identical():
    """backend='bucketed' (the grid fast path, now reachable from R) must
    be bit-identical to the local path row for row (VERDICT r1 weak #6)."""
    rows = [{"n": 400, "rho": 0.0, "eps1": 1.0, "eps2": 1.0},
            {"n": 400, "rho": 0.5, "eps1": 1.0, "eps2": 1.0},
            {"n": 600, "rho": 0.5, "eps1": 1.5, "eps2": 0.5}]
    local = rbridge.run_design_rows(rows, b=16)
    buck = rbridge.run_design_rows(rows, b=16, backend="bucketed")
    assert list(local.columns) == list(buck.columns)
    for col in local.columns:
        np.testing.assert_array_equal(local[col].to_numpy(),
                                      buck[col].to_numpy(), err_msg=col)


def _r_call_kwargs(r_src: str, fn: str) -> set[str]:
    """Keyword names used in ``bridge$<fn>(...)`` calls inside backend.R."""
    import re

    m = re.search(rf"bridge\${fn}\((.*?)\)\n", r_src, re.S)
    assert m, f"backend.R never calls bridge${fn}"
    return set(re.findall(r"(\w+)\s*=", m.group(1)))


def test_backend_r_call_contract():
    """No R runtime in the image, so pin the reticulate call contract the
    executable way available: every keyword backend.R passes must be a real
    parameter of the Python function it calls."""
    import inspect
    from pathlib import Path

    r_src = (Path(__file__).parent.parent / "r" / "backend.R").read_text()
    for fn, py in (("run_design_rows", rbridge.run_design_rows),
                   ("run_hrs_sweep", rbridge.run_hrs_sweep)):
        params = set(inspect.signature(py).parameters)
        used = _r_call_kwargs(r_src, fn)
        assert used <= params, f"{fn}: backend.R passes {used - params}"


def test_frame_feeds_reference_downstream_unchanged():
    """The strongest R-free check of SURVEY.md §7 step 6: the bridge frame
    must contain every column the reference's own data.table summaries
    read (vert-cor.R:575-597), and running that exact grouped-summary
    recipe over it must work and produce coverage in [0,1]. (The remaining
    gap — executing backend.R under a real R/reticulate runtime — is
    environment-gated: no R interpreter exists in this image and installs
    are not allowed; docs/STATUS_r03.md records the gate.)"""
    rows = [{"n": 400, "rho": 0.0, "eps1": 1.0, "eps2": 1.0},
            {"n": 400, "rho": 0.5, "eps1": 1.0, "eps2": 1.0}]
    df = rbridge.run_design_rows(rows, b=16)
    # columns consumed by summ_INT / summ_NI (vert-cor.R:575-593)
    consumed = {"int_se2", "int_hat", "int_cover", "int_ci_len",
                "ni_se2", "ni_hat", "ni_cover", "ni_ci_len",
                "n", "rho_true", "eps1", "eps2"}
    assert consumed <= set(df.columns)
    # the reference's recipe, transliterated: group by design, mean metrics
    g = df.groupby(["n", "rho_true", "eps1", "eps2"])
    summ = g.agg(mse=("ni_se2", "mean"),
                 coverage=("ni_cover", "mean"),
                 ci_len=("ni_ci_len", "mean")).reset_index()
    summ["bias"] = (g["ni_hat"].mean().to_numpy()
                    - g["rho_true"].mean().to_numpy())
    assert len(summ) == 2
    assert summ.coverage.between(0, 1).all()
    assert np.isfinite(summ.mse).all()


def test_run_design_rows_deterministic():
    rows = [{"n": 300, "rho": 0.3, "eps1": 1.0, "eps2": 1.0}]
    a = rbridge.run_design_rows(rows, b=8)
    b = rbridge.run_design_rows(rows, b=8)
    assert np.allclose(a.ni_hat, b.ni_hat)
    # different master seed → different draws
    c = rbridge.run_design_rows(rows, b=8, seed=7)
    assert not np.allclose(a.ni_hat, c.ni_hat)


def test_fused_validation_fail_fast():
    """The bridge mirrors run_grid's fused fail-fast contract (a typo'd
    or non-bucketed fused request must raise, not silently run XLA)."""
    import pytest

    from dpcorr.rbridge import run_design_rows

    rows = [{"n": 400, "rho": 0.5, "eps1": 1.0, "eps2": 1.0}]
    with pytest.raises(ValueError, match="fused"):
        run_design_rows(rows, b=4, backend="local", fused="auto")
    with pytest.raises(ValueError, match="fused"):
        run_design_rows(rows, b=4, backend="bucketed", fused="Auto")


def test_validate_bridge_python_half(tmp_path):
    """The R-free executable slice of r/validate_bridge.R (VERDICT r3 #6):
    run the helper subprocess exactly as the R script does, re-read its
    detail_all.rds, and diff it against the in-process bridge frame — the
    same comparison the R side performs after reticulate marshalling."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).parent.parent
    out = tmp_path / "detail_all.rds"
    rc = subprocess.run(
        [sys.executable, str(repo / "r" / "validate_bridge_helper.py"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=600)
    assert rc.returncode == 0, rc.stderr[-800:]
    assert out.exists()

    sys.path.insert(0, str(repo / "r"))
    try:
        import validate_bridge_helper as helper
    finally:
        sys.path.pop(0)
    bridge_df = helper.run_validation_grid()
    assert len(bridge_df) == len(helper.ROWS) * helper.B

    from dpcorr.io import rds_py

    cols = rds_py.read_rds_table(str(out))
    assert set(cols) == set(map(str, bridge_df.columns))
    for name in ("ni_hat", "int_hat", "ni_cover", "int_ci_len", "n",
                 "rho_true"):
        np.testing.assert_array_equal(
            np.asarray(cols[name].values, dtype=np.float64),
            np.asarray(bridge_df[name], dtype=np.float64), name)


def test_validate_bridge_r_script_wellformed():
    """Smoke-parse r/validate_bridge.R without an R runtime: balanced
    delimiters outside strings/comments, the helper it invokes exists,
    and the columns its summary recipe names are real bridge columns."""
    from pathlib import Path

    repo = Path(__file__).parent.parent
    src = (repo / "r" / "validate_bridge.R").read_text()

    depth = {"(": 0, "[": 0, "{": 0}
    close_of = {")": "(", "]": "[", "}": "{"}
    in_str: str | None = None
    for line in src.splitlines():
        for ch in line:
            if in_str:
                if ch == in_str:
                    in_str = None
                continue
            if ch in "'\"":
                in_str = ch
            elif ch == "#":
                break
            elif ch in depth:
                depth[ch] += 1
            elif ch in close_of:
                depth[close_of[ch]] -= 1
                assert depth[close_of[ch]] >= 0, f"unbalanced {ch}: {line}"
        assert in_str is None, f"unterminated string on: {line}"
    assert all(v == 0 for v in depth.values()), f"unbalanced: {depth}"

    assert (repo / "r" / "validate_bridge_helper.py").exists()
    assert "validate_bridge_helper.py" in src
    # the aggregate() recipe only names real detail columns
    sys_cols = {"ni_cover", "int_cover", "n", "rho_true", "eps1", "eps2"}
    frame = rbridge.run_design_rows(
        [{"n": 200, "rho": 0.1, "eps1": 1.0, "eps2": 1.0}], b=2)
    assert sys_cols <= set(map(str, frame.columns)) | {"n"}


def test_run_design_rows_bucket_merge_subg():
    """bucket_merge='eps' through the R seam: statistically the same
    frame shape; eps_pairs are derived from the ROWS (not GridConfig's
    defaults) so validation and the merged kernel's k_pad see the real ε
    set. Non-bucketed backends reject the knob."""
    import pytest

    rows = [{"n": 400, "rho": 0.5, "eps1": 1.0, "eps2": 1.0},
            {"n": 400, "rho": 0.5, "eps1": 1.5, "eps2": 0.5},
            {"n": 600, "rho": 0.2, "eps1": 1.0, "eps2": 1.0}]
    df = rbridge.run_design_rows(rows, b=16, dgp="bounded_factor",
                                 use_subg=True, backend="bucketed",
                                 bucket_merge="eps")
    assert len(df) == 3 * 16
    assert df.ni_hat.notna().all()  # the k_pad NaN tripwire never fired
    assert df.ni_cover.isin([0.0, 1.0]).all()
    with pytest.raises(ValueError, match="bucketed"):
        rbridge.run_design_rows(rows, b=4, use_subg=True,
                                dgp="bounded_factor", bucket_merge="eps")
    # sign-family rows reject the subG-only knob via validate_bucket_merge
    sign_rows = [{"n": 400, "rho": 0.5, "eps1": 1.0, "eps2": 1.0}]
    with pytest.raises(ValueError, match="subG-only"):
        rbridge.run_design_rows(sign_rows, b=4, backend="bucketed",
                                bucket_merge="eps")
