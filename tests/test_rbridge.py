"""R-bridge tests: the Python half of the reticulate seam."""

from __future__ import annotations

import numpy as np

from dpcorr import rbridge


def test_run_design_rows_schema():
    rows = [{"n": 400, "rho": 0.0, "eps1": 1.0, "eps2": 1.0},
            {"n": 600, "rho": 0.5, "eps1": 1.5, "eps2": 0.5}]
    df = rbridge.run_design_rows(rows, b=16)
    assert len(df) == 32
    assert list(df.columns[:1]) == ["repl"]
    for col in ("ni_hat", "int_hat", "ni_cover", "int_cover",
                "n", "rho_true", "eps1", "eps2"):
        assert col in df.columns
    assert sorted(df.n.unique()) == [400, 600]
    assert df.repl.max() == 16
    assert df.ni_cover.isin([0.0, 1.0]).all()


def test_run_design_rows_deterministic():
    rows = [{"n": 300, "rho": 0.3, "eps1": 1.0, "eps2": 1.0}]
    a = rbridge.run_design_rows(rows, b=8)
    b = rbridge.run_design_rows(rows, b=8)
    assert np.allclose(a.ni_hat, b.ni_hat)
    # different master seed → different draws
    c = rbridge.run_design_rows(rows, b=8, seed=7)
    assert not np.allclose(a.ni_hat, c.ni_hat)
