"""Fused Pallas NI sign-batch kernel vs the XLA estimator.

Off-TPU the kernel runs under the TPU interpreter, whose pltpu.prng_* stubs
return zeros — so these tests drive the external-uniforms path, which
exercises everything except the on-chip PRNG (validated on real TPU by the
bench). Acceptance is statistical (different PRNG stream ⇒ no bitwise
comparison; SURVEY.md §5 RNG).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpcorr.ops.pallas_ni import (
    n_uniform_rows,
    ni_sign_pallas,
    use_ni_sign_pallas,
)
from dpcorr.sim import SimConfig, run_sim_one
from dpcorr.utils import rng

N, B, RHO = 1024, 512, 0.5


def _uniforms(key, n, b, eps1=1.0, eps2=1.0):
    return jax.random.uniform(key, (b, n_uniform_rows(n, eps1, eps2), 128),
                              jnp.float32, minval=1e-7, maxval=1.0 - 1e-7)


@pytest.fixture(scope="module")
def pallas_result():
    u = _uniforms(rng.master_key(3), N, B)
    return ni_sign_pallas(np.arange(B, dtype=np.int32), RHO, N, 1.0, 1.0,
                          uniforms=u)


def test_uniform_bits_no_sign_extension():
    """int32 PRNG bits with the sign bit set must still yield (0,1) uniforms
    (the on-chip generator returns int32; a bare shift sign-extends)."""
    from dpcorr.ops.pallas_ni import _uniform

    bits = jnp.asarray([-1, -(2**31), -123456789, 0, 1, 2**31 - 1], jnp.int32)
    u = np.asarray(_uniform(bits))
    assert (u > 0.0).all() and (u < 1.0).all()
    # Box–Muller log and the Laplace log1p(-2|u-1/2|) must both stay finite
    assert np.isfinite(np.log(u)).all()
    assert np.isfinite(np.log1p(-2.0 * np.abs(u - 0.5))).all()


def test_applicability():
    assert use_ni_sign_pallas(10_000, 1.0, 1.0)   # m=8 (dense layout)
    assert use_ni_sign_pallas(10_000, 1.5, 0.5)   # m=11 → m'=16 (padded)
    assert use_ni_sign_pallas(10_000, 0.5, 0.5)   # m=32
    assert not use_ni_sign_pallas(10_000, 0.1, 0.1)  # m=800 > 128
    assert not use_ni_sign_pallas(40, 0.5, 0.5)      # k=1 (m capped at n)
    with pytest.raises(ValueError, match="m <= 128"):
        ni_sign_pallas(np.arange(4, dtype=np.int32), 0.5, 1000, 0.1, 0.1)


def test_padded_layout_m11_statistics():
    """ε=(1.5,0.5) ⇒ m=11, m'=16 — the reference's own awkward ε-pair
    (vert-cor.R:488-494). Padded-lane-group layout must reproduce the XLA
    estimator's statistics within MC error."""
    eps1, eps2 = 1.5, 0.5
    b = 512
    u = _uniforms(rng.master_key(11), N, b, eps1, eps2)
    res = ni_sign_pallas(np.arange(b, dtype=np.int32), RHO, N, eps1, eps2,
                         uniforms=u)
    r = np.asarray(res.rho_hat)
    cover = np.mean((RHO >= np.asarray(res.ci_low))
                    & (RHO <= np.asarray(res.ci_high)))
    xla = run_sim_one(SimConfig(n=N, rho=RHO, eps1=eps1, eps2=eps2,
                                b=b)).summary["NI"]
    assert np.isfinite(r).all()
    assert abs(r.mean() - RHO - xla["bias"]) < 0.06
    assert abs(cover - xla["coverage"]) < 0.06
    mse = ((r - RHO) ** 2).mean()
    assert 0.5 < mse / xla["mse"] < 2.0


def test_statistics_match_xla(pallas_result):
    """Mean/MSE/coverage agree with the XLA estimator within MC error."""
    r = np.asarray(pallas_result.rho_hat)
    cover = np.mean((RHO >= np.asarray(pallas_result.ci_low))
                    & (RHO <= np.asarray(pallas_result.ci_high)))
    xla = run_sim_one(SimConfig(n=N, rho=RHO, eps1=1.0, eps2=1.0,
                                b=B)).summary["NI"]
    assert abs(r.mean() - RHO - xla["bias"]) < 0.03
    assert abs(cover - xla["coverage"]) < 0.05
    mse = ((r - RHO) ** 2).mean()
    assert 0.5 < mse / xla["mse"] < 2.0


def test_ci_ordering_and_range(pallas_result):
    lo, hi = (np.asarray(pallas_result.ci_low),
              np.asarray(pallas_result.ci_high))
    assert (lo <= hi).all()
    assert (lo >= -1.0).all() and (hi <= 1.0).all()


def test_deterministic_in_uniforms():
    u = _uniforms(rng.master_key(9), N, 64)
    seeds = np.arange(64, dtype=np.int32)
    a = ni_sign_pallas(seeds, RHO, N, 1.0, 1.0, uniforms=u)
    b = ni_sign_pallas(seeds, RHO, N, 1.0, 1.0, uniforms=u)
    np.testing.assert_array_equal(np.asarray(a.rho_hat),
                                  np.asarray(b.rho_hat))


def test_unnormalised_path():
    u = _uniforms(rng.master_key(5), N, 256)
    res = ni_sign_pallas(np.arange(256, dtype=np.int32), RHO, N, 1.0, 1.0,
                         normalise=False, uniforms=u)
    r = np.asarray(res.rho_hat)
    # data is already standard here, so estimates still center on ρ
    assert abs(r.mean() - RHO) < 0.05


def test_layout_invariants_reference_grid():
    """Every (n, ε) the reference grid can produce (vert-cor.R:488-494)
    must yield a Mosaic-aligned layout: rows a multiple of 8 (full
    sublane tiles), m' a power of two dividing 128, and enough positions
    for the k·m batch elements plus the leftover tail."""
    from dpcorr.ops.pallas_ni import LANES, _layout, use_ni_sign_pallas

    n_grid = (1000, 1500, 2500, 4000, 6000, 9000, 10_000)
    eps_pairs = ((0.5, 0.5), (1.0, 1.0), (1.5, 0.5))
    for n in n_grid:
        for e1, e2 in eps_pairs:
            assert use_ni_sign_pallas(n, e1, e2), (n, e1, e2)
            m, m_pad, k, leftover, rows = _layout(n, e1, e2)
            assert rows % 8 == 0
            assert m_pad & (m_pad - 1) == 0 and LANES % m_pad == 0
            assert m <= m_pad <= 2 * m
            assert k * m + leftover == n
            assert rows * LANES >= k * m_pad + leftover
            # uniform-row accounting matches the kernel's take() sequence
            from dpcorr.ops.pallas_ni import n_uniform_rows

            assert n_uniform_rows(n, e1, e2) == 4 * rows + 8


# ---- fused NI+INT simulation kernel (sim_detail_pallas) ----

def _uniforms_int(key, n, b, eps1=1.0, eps2=1.0):
    return jax.random.uniform(
        key, (b, n_uniform_rows(n, eps1, eps2, compute_int=True), 128),
        jnp.float32, minval=1e-7, maxval=1.0 - 1e-7)


def test_fused_sim_detail_statistics():
    """The fused whole-replication kernel (NI + INT on one in-kernel draw,
    the hot-loop body vert-cor.R:392-419) must reproduce the XLA
    simulator's detail statistics within MC error."""
    from dpcorr.ops.pallas_ni import sim_detail_pallas
    from dpcorr.sim import DETAIL_FIELDS

    b = 512
    u = _uniforms_int(rng.master_key(21), N, b)
    raw = sim_detail_pallas(np.arange(b, dtype=np.int32), RHO, N, 1.0, 1.0,
                            uniforms=u)
    d = dict(zip(DETAIL_FIELDS, [np.asarray(a) for a in raw], strict=True))
    xla = run_sim_one(SimConfig(n=N, rho=RHO, eps1=1.0, eps2=1.0,
                                b=b)).summary
    for a in d.values():
        assert np.isfinite(a).all()
    assert abs(d["ni_hat"].mean() - RHO - xla["NI"]["bias"]) < 0.05
    assert abs(d["ni_cover"].mean() - xla["NI"]["coverage"]) < 0.06
    assert abs(d["int_hat"].mean() - RHO - xla["INT"]["bias"]) < 0.05
    assert abs(d["int_cover"].mean() - xla["INT"]["coverage"]) < 0.06
    assert 0.5 < d["int_se2"].mean() / xla["INT"]["mse"] < 2.0
    # det-mixquant CI width is a near-deterministic function of η̂ —
    # the two PRNG streams must land on the same construction
    assert 0.9 < d["int_ci_len"].mean() / xla["INT"]["ci_length"] < 1.1
    assert (d["int_low"] <= d["int_up"]).all()
    assert (d["ni_low"] <= d["ni_up"]).all()


def test_fused_sim_detail_per_rep_rho():
    """ρ rides per-replication (the bucketed grid flattens points × reps):
    reps at ρ=0 and ρ=0.8 inside one call must center on their own ρ."""
    from dpcorr.ops.pallas_ni import sim_detail_pallas
    from dpcorr.sim import DETAIL_FIELDS

    b = 256
    rhos = np.concatenate([np.zeros(b), np.full(b, 0.8)]).astype(np.float32)
    u = _uniforms_int(rng.master_key(22), N, 2 * b)
    raw = sim_detail_pallas(np.arange(2 * b, dtype=np.int32), rhos,
                            N, 1.0, 1.0, uniforms=u)
    d = dict(zip(DETAIL_FIELDS, [np.asarray(a) for a in raw], strict=True))
    assert abs(d["ni_hat"][:b].mean() - 0.0) < 0.05
    assert abs(d["ni_hat"][b:].mean() - 0.8) < 0.05
    assert abs(d["int_hat"][:b].mean() - 0.0) < 0.06
    assert abs(d["int_hat"][b:].mean() - 0.8) < 0.06


def test_fused_int_laplace_regime():
    """√n·ε_r ≤ 0.5 switches the INT CI to the pure-Laplace tail bound
    (vert-cor.R:294-308); the fused kernel must land in the same regime
    and produce the same (η-deterministic) width as the XLA path."""
    from dpcorr.ops.pallas_ni import sim_detail_pallas, use_ni_sign_pallas
    from dpcorr.sim import DETAIL_FIELDS

    eps1, eps2 = 5.0, 0.015   # m=107 ≤ 128; √1024·0.015 = 0.48 < 0.5
    assert use_ni_sign_pallas(N, eps1, eps2)
    b = 384
    u = _uniforms_int(rng.master_key(23), N, b, eps1, eps2)
    raw = sim_detail_pallas(np.arange(b, dtype=np.int32), RHO, N,
                            eps1, eps2, uniforms=u)
    d = dict(zip(DETAIL_FIELDS, [np.asarray(a) for a in raw], strict=True))
    xla = run_sim_one(SimConfig(n=N, rho=RHO, eps1=eps1, eps2=eps2,
                                b=b)).summary["INT"]
    assert np.isfinite(d["int_hat"]).all()
    assert 0.9 < d["int_ci_len"].mean() / xla["ci_length"] < 1.1
    # coverage SE ≈ 0.018 per stream at b=384 → |diff| bound ≈ 3·√2·SE
    assert abs(d["int_cover"].mean() - xla["coverage"]) < 0.08


def test_ndtri_gauss_variant_statistics():
    """The inverse-CDF normal sampler (gauss="ndtri") is exact like
    Box-Muller and consumes the same uniform planes — estimates must match
    the default variant's statistics within MC error."""
    b = 512
    u = _uniforms(rng.master_key(41), N, b)
    bm = ni_sign_pallas(np.arange(b, dtype=np.int32), RHO, N, 1.0, 1.0,
                        uniforms=u)
    nd = ni_sign_pallas(np.arange(b, dtype=np.int32), RHO, N, 1.0, 1.0,
                        gauss="ndtri", uniforms=u)
    r_bm, r_nd = np.asarray(bm.rho_hat), np.asarray(nd.rho_hat)
    assert np.isfinite(r_nd).all()
    assert abs(r_nd.mean() - r_bm.mean()) < 0.03
    assert 0.5 < r_nd.var() / r_bm.var() < 2.0
    cov_bm = np.mean((RHO >= np.asarray(bm.ci_low))
                     & (RHO <= np.asarray(bm.ci_high)))
    cov_nd = np.mean((RHO >= np.asarray(nd.ci_low))
                     & (RHO <= np.asarray(nd.ci_high)))
    assert abs(cov_nd - cov_bm) < 0.06


def test_ndtri_inline_properties():
    """The in-kernel inverse-normal-CDF (scalar-literal Acklam polynomial)
    must agree with jax.scipy.special.ndtri over the kernel's uniform
    range, be antisymmetric, and be monotone."""
    from jax.scipy.special import ndtri as ndtri_ref

    from dpcorr.ops.pallas_ni import _ndtri_inline

    u = np.linspace(2.0**-24, 1.0 - 2.0**-24, 200_001).astype(np.float32)
    mine = np.asarray(_ndtri_inline(jnp.asarray(u)))
    ref = np.asarray(ndtri_ref(jnp.asarray(u)))
    assert np.isfinite(mine).all()
    # f32 cancellation near the central/tail seam bounds the error ~3e-4
    assert np.abs(mine - ref).max() < 5e-4
    sym = np.asarray(_ndtri_inline(jnp.asarray(1.0 - u)))
    assert np.abs(mine + sym).max() < 5e-4
    # monotone up to the f32 discontinuity at the central/tail seam
    # (measured −2.7e-4 at u≈0.9757, same order as the accuracy bound)
    assert (np.diff(mine) >= -5e-4).all()
