"""RepBlockPipeline (r08): bit-identity A/B, donation, autotuner, gate.

The tentpole's contract is that the donated, pre-sharded, chained-key
executor is a pure *mechanical* change: block ``i`` of the pipeline
produces bitwise the same per-rep outputs as the plain
``chunked_vmap`` path from the same key addresses, for all four
estimator families, in f32 and (via a subprocess, because
``JAX_ENABLE_X64`` is process-global) f64. The per-rep tables are
compared exactly — ``assert_array_equal``, never ``allclose``. The
``run()`` accumulators are the one place a tolerance appears: XLA
fuses the in-kernel ``o.sum()`` into the block program and may
reassociate it relative to a detached sum over the materialized
table, so they are checked to a few ulps instead.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from dpcorr import sim
from dpcorr.obs.metrics import Registry
from dpcorr.obs.transfer import TransferCounters
from dpcorr.utils import geometry, rng

BLOCK = 16
CHUNK = 4

#: one config per estimator family pair: "sign" exercises
#: ci_ni_signbatch + ci_int_signflip, the subG configs exercise
#: correlation_ni_subg + ci_int_subg in both variants — together the
#: four families the bit-identity acceptance names.
CFGS = {
    "sign": sim.SimConfig(n=200, rho=0.35, eps1=1.0, eps2=0.5,
                          b=BLOCK, chunk_size=CHUNK),
    "subg-grid": sim.SimConfig(n=400, rho=0.5, eps1=1.0, eps2=1.0,
                               b=BLOCK, chunk_size=CHUNK,
                               dgp="bounded_factor", use_subg=True),
    "subg-real": sim.SimConfig(n=400, rho=0.5, eps1=1.0, eps2=1.0,
                               b=BLOCK, chunk_size=CHUNK,
                               dgp="bounded_factor", use_subg=True,
                               subg_variant="real"),
}


def _pipeline_for(cfg, key, **kw):
    cfg_norho = dataclasses.replace(cfg, rho=0.0, seed=0)
    rho = jnp.float32(cfg.rho)
    return sim.RepBlockPipeline(
        lambda k: sim._one_rep(k, rho, cfg_norho),
        len(sim.DETAIL_FIELDS), key=key, block_reps=cfg.b,
        chunk_size=cfg.chunk_size, family="test", **kw)


# ------------------------------------------------------------------
# Bit-identity A/B: pipeline block vs the plain chunked_vmap path
# ------------------------------------------------------------------

class TestBitIdentity:
    @pytest.mark.parametrize("name", sorted(CFGS))
    def test_block_matches_plain_path_exactly(self, name):
        cfg = CFGS[name]
        key = rng.master_key()
        pipe = _pipeline_for(cfg, key, aot=False,
                             counters=TransferCounters(Registry()))
        cfg_norho = dataclasses.replace(cfg, rho=0.0, seed=0)
        for i in (0, 3):  # a non-zero index catches design_key drift
            plain = sim._run_detail_core(cfg_norho, rng.design_key(key, i),
                                         jnp.float32(cfg.rho))
            piped = pipe.block_detail(i)
            for f, a, b in zip(sim.DETAIL_FIELDS, plain, piped,
                               strict=True):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    err_msg=f"{name}: pipeline diverged on {f} "
                            f"(block {i})")

    def test_run_sums_match_replayed_reduction(self):
        """run()'s donated accumulators match the same reduction
        replayed block-by-block from block_detail. Same math, but XLA
        fuses the in-kernel sum into the block program and may
        reassociate it, so this is ulp-tight allclose, not equality —
        the exactness contract lives on the per-rep tables above."""
        cfg = CFGS["sign"]
        key = rng.master_key()
        pipe = _pipeline_for(cfg, key, aot=False,
                             counters=TransferCounters(Registry()))
        n_blocks = 3
        sums, n_reps = pipe.run(n_blocks)
        assert n_reps == n_blocks * cfg.b
        acc = [jnp.zeros((), jnp.float32)] * len(sim.DETAIL_FIELDS)
        for i in range(n_blocks):
            outs = pipe.block_detail(i)
            acc = [a + o.sum() for a, o in zip(acc, outs, strict=True)]
        np.testing.assert_allclose(
            np.asarray(sums), np.asarray([float(a) for a in acc]),
            rtol=1e-6, err_msg="accumulators drifted past reassociation")

    def test_f64_bit_identity_subprocess(self):
        """Same A/B under JAX_ENABLE_X64 (process-global, so a
        subprocess), sign + both subG variants, f64 accumulators."""
        script = r"""
import dataclasses
import jax
import jax.numpy as jnp
import numpy as np
from dpcorr import sim
from dpcorr.obs.metrics import Registry
from dpcorr.obs.transfer import TransferCounters
from dpcorr.utils import rng

assert jax.config.jax_enable_x64
for cfg in [
    sim.SimConfig(n=200, rho=0.35, eps1=1.0, eps2=0.5, b=8, chunk_size=4),
    sim.SimConfig(n=400, rho=0.5, eps1=1.0, eps2=1.0, b=8, chunk_size=4,
                  dgp="bounded_factor", use_subg=True),
    sim.SimConfig(n=400, rho=0.5, eps1=1.0, eps2=1.0, b=8, chunk_size=4,
                  dgp="bounded_factor", use_subg=True,
                  subg_variant="real"),
]:
    key = rng.master_key()
    cfg_norho = dataclasses.replace(cfg, rho=0.0, seed=0)
    rho = jnp.float32(cfg.rho)
    pipe = sim.RepBlockPipeline(
        lambda k: sim._one_rep(k, rho, cfg_norho),
        len(sim.DETAIL_FIELDS), key=key, block_reps=cfg.b,
        chunk_size=cfg.chunk_size, family="test-f64", aot=False,
        counters=TransferCounters(Registry()), acc_dtype=jnp.float64)
    plain = sim._run_detail_core(cfg_norho, rng.design_key(key, 0), rho)
    piped = pipe.block_detail(0)
    for f, a, b in zip(sim.DETAIL_FIELDS, plain, piped, strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"f64 diverged on {f}")
    sums, _ = pipe.run(2)
    acc = [jnp.zeros((), jnp.float64)] * len(sim.DETAIL_FIELDS)
    for i in range(2):
        outs = pipe.block_detail(i)
        acc = [x + o.sum().astype(jnp.float64)
               for x, o in zip(acc, outs)]
    # ulp-tight: the fused in-kernel sum may reassociate, and some
    # detail columns (cover, anything rho-anchored) stay f32 under
    # x64, so the bound is f32 ulps (see the f32 accumulator test)
    np.testing.assert_allclose(np.asarray(sums),
                               np.asarray([float(a) for a in acc]),
                               rtol=1e-6)
print("F64_BIT_IDENTITY_OK")
"""
        env = dict(os.environ, JAX_ENABLE_X64="1", JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=600,
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))))
        assert r.returncode == 0, r.stdout + r.stderr
        assert "F64_BIT_IDENTITY_OK" in r.stdout


# ------------------------------------------------------------------
# Donation engages (and the transfer counters prove the overlap shape)
# ------------------------------------------------------------------

class TestDonation:
    def test_donation_engages_single_fetch(self):
        cfg = CFGS["sign"]
        counters = TransferCounters(Registry())
        pipe = _pipeline_for(cfg, rng.master_key(), counters=counters)
        assert pipe.aot_ok is True
        # AOT lowering already showed the runtime's hand: a decline
        # warning there would have latched False
        assert pipe.donation_engaged is True
        before = counters.snapshot()
        sums, n_reps = pipe.run(3)
        assert pipe.donation_engaged is True
        d = {k: v - before[k] for k, v in counters.snapshot().items()}
        assert d["donated_blocks"] == 3
        assert d["donation_unused"] == 0
        assert d["fetches"] == 1  # ONE host sync per run()
        assert all(np.isfinite(s) for s in sums)

    def test_chunk_size_floored_to_bit_safe_width(self):
        cfg = CFGS["sign"]
        pipe = sim.RepBlockPipeline(
            lambda k: (jnp.zeros(()),), 1, key=rng.master_key(),
            block_reps=cfg.b, chunk_size=1, family="floor", aot=False,
            counters=TransferCounters(Registry()))
        assert pipe.chunk_size == geometry.CHUNK_FLOOR


# ------------------------------------------------------------------
# chunked_vmap tail-split: no more full-chunk pad waste
# ------------------------------------------------------------------

class TestChunkedVmapTail:
    def _fn(self, x):
        return (jnp.sin(x) * 2.0 + 1.0, jnp.exp(-x))

    @pytest.mark.parametrize("b,chunk", [(13, 5), (9, 4), (8, 4), (1, 4),
                                         (5, 8)])
    def test_tail_rows_bitwise_equal_full_vmap(self, b, chunk):
        xs = jnp.linspace(-1.0, 2.0, b)
        ref = jax.vmap(self._fn)(xs)
        got = sim.chunked_vmap(self._fn, xs, chunk)
        for r, g in zip(ref, got, strict=True):
            assert g.shape == r.shape
            np.testing.assert_array_equal(np.asarray(r), np.asarray(g))

    def test_tuple_args_tail(self):
        xs = jnp.linspace(0.0, 1.0, 7)
        ys = jnp.linspace(2.0, 3.0, 7)
        fn = lambda x, y: (x * y,)
        ref = jax.vmap(fn)(xs, ys)
        got = sim.chunked_vmap(fn, (xs, ys), 3)
        np.testing.assert_array_equal(np.asarray(ref[0]),
                                      np.asarray(got[0]))


# ------------------------------------------------------------------
# Autotuner: deterministic given a scripted clock; pins outrank
# ------------------------------------------------------------------

def _scripted_clock(durations):
    """Each timed() probe calls the clock twice; consecutive entries of
    ``durations`` become the measured interval of consecutive probes."""
    seq = iter(durations)
    state = {"t": 0.0, "d": None}

    def clock():
        if state["d"] is None:
            state["d"] = next(seq)
        else:
            state["t"] += state["d"]
            state["d"] = None
        return state["t"]

    return clock


def _null_runner(chunk, block):
    return lambda: None


def _boom_runner(chunk, block):
    raise AssertionError("probe must not run")


LADDER = ((2, 4, 8), (100, 200))


@pytest.fixture()
def clean_geometry(monkeypatch):
    monkeypatch.setattr(geometry, "_MEMO", {})
    monkeypatch.setenv("DPCORR_GEOMETRY_CACHE", "0")
    monkeypatch.delenv("DPCORR_BENCH_CHUNK", raising=False)
    monkeypatch.delenv("DPCORR_BENCH_BLOCK_REPS", raising=False)


class TestAutotune:
    # probe order: chunks (2, 4, 8) at block 100, then blocks (100, 200)
    # at the winning chunk — 5 intervals
    DUR = [0.30, 0.10, 0.20, 0.10, 0.18]

    def test_deterministic_given_clock(self, clean_geometry):
        runs = [
            geometry.autotune("det", 10, _null_runner, device_kind="cpu",
                              ladder=LADDER, clock=_scripted_clock(self.DUR),
                              use_cache=False, force=True)
            for _ in range(2)
        ]
        assert runs[0] == runs[1]
        geo = runs[0]
        assert (geo.chunk_size, geo.block_reps) == (4, 200)
        assert geo.source == "autotune"
        # 200 reps in 0.18 s, exactly
        assert geo.reps_per_sec == pytest.approx(200 / 0.18)

    def test_ties_break_toward_earlier_ladder_entry(self, clean_geometry):
        # all chunks equal; blocks equal per-rep (0.2/100 == 0.4/200)
        clock = _scripted_clock([0.1, 0.1, 0.1, 0.2, 0.4])
        geo = geometry.autotune("tie", 10, _null_runner, device_kind="cpu",
                                ladder=LADDER, clock=clock,
                                use_cache=False, force=True)
        assert (geo.chunk_size, geo.block_reps) == (2, 100)

    def test_env_pin_outranks_probe(self, clean_geometry, monkeypatch):
        monkeypatch.setenv("DPCORR_BENCH_CHUNK", "16")
        monkeypatch.setenv("DPCORR_BENCH_BLOCK_REPS", "512")
        geo = geometry.autotune("pin", 10, _boom_runner, device_kind="cpu",
                                ladder=LADDER, use_cache=False)
        assert (geo.chunk_size, geo.block_reps, geo.source) == \
            (16, 512, "pinned")
        assert geometry.lookup("pin", 10).source == "pinned"

    def test_env_pin_false_ignores_pin(self, clean_geometry, monkeypatch):
        monkeypatch.setenv("DPCORR_BENCH_CHUNK", "16")
        monkeypatch.setenv("DPCORR_BENCH_BLOCK_REPS", "512")
        geo = geometry.autotune("nopin", 10, _null_runner,
                                device_kind="cpu", ladder=LADDER,
                                clock=_scripted_clock(self.DUR),
                                use_cache=False, force=True,
                                env_pin=False)
        assert geo.source == "autotune"
        assert (geo.chunk_size, geo.block_reps) == (4, 200)
        # lookup honors the same opt-out: memo, not the pin
        assert geometry.lookup("nopin", 10, env_pin=False) == geo

    def test_pinned_chunk_floored(self, clean_geometry, monkeypatch):
        monkeypatch.setenv("DPCORR_BENCH_CHUNK", "1")
        geo = geometry.autotune("floorpin", 10, _boom_runner,
                                device_kind="cpu", use_cache=False)
        assert geo.chunk_size == geometry.CHUNK_FLOOR

    def test_probe_failure_degrades_to_ladder_default(self,
                                                      clean_geometry):
        def broken(chunk, block):
            def run():
                raise RuntimeError("device fell over")
            return run

        geo = geometry.autotune("broken", 10, broken, device_kind="cpu",
                                ladder=LADDER, use_cache=False, force=True)
        assert geo.source == "default"
        assert (geo.chunk_size, geo.block_reps) == (8, 200)


# ------------------------------------------------------------------
# Regression gate
# ------------------------------------------------------------------

def _measured(value, kind="cpu"):
    return {"metric": bench.METRIC, "value": value,
            "detail": {"device_kind": kind} if kind else {}}


class TestGateCheck:
    LKG = {"metric": bench.METRIC, "value": 1000.0, "device_kind": "cpu"}

    def test_above_floor_passes(self):
        ok, reason = bench.gate_check(_measured(900.0), self.LKG, 0.85)
        assert ok and "0.900x" in reason

    def test_below_floor_fails(self):
        ok, reason = bench.gate_check(_measured(700.0), self.LKG, 0.85)
        assert not ok and reason.startswith("REGRESSION")

    def test_device_kind_mismatch_passes_with_note(self):
        ok, reason = bench.gate_check(_measured(10.0, kind="tpu"),
                                      self.LKG, 0.85)
        assert ok and "mismatch" in reason

    def test_zero_value_artifact_fails(self):
        # the all-paths-failed artifact stamps value 0 — must gate red
        ok, _ = bench.gate_check(_measured(0.0), self.LKG, 0.85)
        assert not ok

    def test_missing_measured_kind_still_compared(self):
        ok, _ = bench.gate_check(_measured(0.0, kind=None), self.LKG, 0.85)
        assert not ok

    def test_missing_baseline_bootstraps(self):
        ok, reason = bench.gate_check(_measured(1.0), None, 0.85)
        assert ok and "bootstrap" in reason

    def test_foreign_metric_baseline_passes(self):
        ok, _ = bench.gate_check(
            _measured(1.0), {"metric": "other_metric", "value": 9e9}, 0.85)
        assert ok

    def test_unusable_baseline_value_passes(self):
        ok, _ = bench.gate_check(_measured(1.0),
                                 {"metric": bench.METRIC, "value": 0}, 0.85)
        assert ok

    def test_floor_env_parsing(self, monkeypatch):
        monkeypatch.setenv("DPCORR_BENCH_GATE_FLOOR", "0.5")
        assert bench._gate_floor() == 0.5
        monkeypatch.setenv("DPCORR_BENCH_GATE_FLOOR", "not-a-float")
        assert bench._gate_floor() == bench.GATE_FLOOR_DEFAULT


class TestGateCli:
    def _run_gate(self, monkeypatch, capsys, artifact_path, lkg_path,
                  extra_env=None):
        for k, v in (extra_env or {}).items():
            monkeypatch.setenv(k, v)
        monkeypatch.setattr(sys, "argv",
                            ["bench.py", "--gate-measured",
                             str(artifact_path), "--lkg", str(lkg_path)])
        prev = signal.getsignal(signal.SIGTERM)
        try:
            with pytest.raises(SystemExit) as exc:
                bench.main()
        finally:
            # main() installs a process-global SIGTERM handler
            signal.signal(signal.SIGTERM, prev)
        out = json.loads(capsys.readouterr().out)
        return exc.value.code, out

    @pytest.fixture()
    def lkg(self, tmp_path):
        p = tmp_path / "lkg.json"
        p.write_text(json.dumps({"metric": bench.METRIC, "value": 1000.0,
                                 "device_kind": "cpu"}))
        return p

    def _artifact(self, tmp_path, value):
        p = tmp_path / "measured.json"
        p.write_text(json.dumps(_measured(value)))
        return p

    def test_regression_exits_1(self, monkeypatch, capsys, tmp_path, lkg):
        code, out = self._run_gate(monkeypatch, capsys,
                                   self._artifact(tmp_path, 100.0), lkg)
        assert code == 1
        assert out["detail"]["gate"]["ok"] is False
        assert "REGRESSION" in out["detail"]["gate"]["reason"]

    def test_healthy_exits_0_and_stamps_gate(self, monkeypatch, capsys,
                                             tmp_path, lkg):
        code, out = self._run_gate(monkeypatch, capsys,
                                   self._artifact(tmp_path, 990.0), lkg)
        assert code == 0
        gate = out["detail"]["gate"]
        assert gate["ok"] is True
        assert gate["lkg_value"] == 1000.0
        assert gate["floor"] == bench.GATE_FLOOR_DEFAULT

    def test_derated_floor_env(self, monkeypatch, capsys, tmp_path, lkg):
        # the CI job's derate: 100/1000 fails at 0.85 but passes at 0.05
        code, out = self._run_gate(monkeypatch, capsys,
                                   self._artifact(tmp_path, 100.0), lkg,
                                   {"DPCORR_BENCH_GATE_FLOOR": "0.05"})
        assert code == 0
        assert out["detail"]["gate"]["floor"] == 0.05

    def test_missing_lkg_bootstraps(self, monkeypatch, capsys, tmp_path):
        code, out = self._run_gate(monkeypatch, capsys,
                                   self._artifact(tmp_path, 1.0),
                                   tmp_path / "absent.json")
        assert code == 0
        assert "bootstrap" in out["detail"]["gate"]["reason"]
