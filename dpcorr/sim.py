"""Monte-Carlo simulator (reference layer L3).

``run_sim_one`` replaces both reference versions (SURVEY.md Appendix A #1):
the Gaussian-only v1 (vert-cor.R:356-444, ``mu``/``sigma`` args, sign
estimators) and the pluggable v2 (ver-cor-subG.R:159-222, ``dgp``/
``use_subg``). The B-replication loop — the reference's hot loop
(vert-cor.R:392-419) — becomes one ``jit(vmap(one_rep))`` kernel: every
replication generates its own data in-kernel from a folded key, runs the NI
and INT estimators, and emits per-rep metrics; nothing but the (B, ·) metric
table ever leaves the device.

For large B the replication axis is blocked with ``lax.map`` over chunks so
B × n never has to fit in HBM at once (SURVEY.md §5 long-context analogue).
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp

from dpcorr.models import dgp as dgp_mod
from dpcorr.models.estimators import (
    ci_int_signflip,
    ci_int_subg,
    ci_ni_signbatch,
    correlation_ni_subg,
)
from dpcorr.utils import rng
from dpcorr.utils.geometry import CHUNK_FLOOR

log = logging.getLogger("dpcorr.sim")


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """One design point. Replaces the reference's script-global knobs
    (SURVEY.md §5 config) with a typed object.

    ``dgp`` is a name from :data:`dpcorr.models.dgp.DGPS` or a callable
    ``f(key, n, rho, **dgp_args)``. v1 semantics: ``dgp="gaussian"`` with
    ``dgp_args={"mu": .., "sigma": ..}``, ``use_subg=False``. v2 semantics:
    ``dgp="bounded_factor"``, ``use_subg=True``.
    """

    n: int
    rho: float
    eps1: float
    eps2: float
    b: int = 1000
    alpha: float = 0.05
    dgp: str | Callable = "gaussian"
    dgp_args: Any = ()
    use_subg: bool = False
    #: which subG estimator pair runs under ``use_subg``: "grid" is the
    #: synthetic-grid pair (sequential batches, se with Laplace term —
    #: ver-cor-subG.R:25-108); "real" is the real-data pair (randomized
    #: batches + k≥2 fallback, receiver-λ from noise, sampling-only se,
    #: δ_clip=1/n — real-data-sims.R:115-252)
    subg_variant: str = "grid"
    #: sub-Gaussian norm parameters feeding the λ_n clip rules
    #: (ver-cor-subG.R:28-31); ignored by the sign estimators
    eta1: float = 1.0
    eta2: float = 1.0
    ci_mode: str = "auto"
    normalise: bool = True
    mixquant_mode: str = "det"
    seed: int = rng.MASTER_SEED
    chunk_size: int = 4096  # max replications resident in HBM at once
    #: if set, run the streaming (n-blocked) estimators with ~this many rows
    #: resident per replication — the stress-scale path for n ≥ ~10⁵
    #: (BASELINE.md config 5; SURVEY.md §5 long-context analogue)
    stream_n_chunk: int | None = None

    def __post_init__(self):
        if self.subg_variant not in ("grid", "real"):
            raise ValueError(f"subg_variant must be 'grid' or 'real', "
                             f"got {self.subg_variant!r}")
        if self.stream_n_chunk and self.use_subg \
                and self.subg_variant == "real":
            # randomized batch assignment needs a global permutation of all
            # n rows — fundamentally not n-blockable (streaming.py)
            raise ValueError("subg_variant='real' is not available on the "
                             "streaming path")
        # The config is a static jit argument, so it must be hashable:
        # normalize dgp_args (dict or items) to a sorted items tuple,
        # recursively — nested lists arrive from JSON round-trips
        # (multihost worker specs, R bridge) and must freeze too.
        def freeze(v):
            if isinstance(v, Mapping):
                return tuple(sorted((k, freeze(x)) for k, x in v.items()))
            if isinstance(v, (list, tuple)):
                return tuple(freeze(x) for x in v)
            return v

        object.__setattr__(self, "dgp_args", freeze(self.dgp_args))

    def dgp_fn(self) -> Callable:
        fn = dgp_mod.DGPS[self.dgp] if isinstance(self.dgp, str) else self.dgp
        return partial(fn, **dict(self.dgp_args))


#: detail-table columns, in the reference's order (vert-cor.R:367-385)
DETAIL_FIELDS = (
    "ni_hat", "int_hat", "ni_se2", "int_se2",
    "ni_low", "ni_up", "int_low", "int_up",
    "ni_cover", "int_cover", "ni_ci_len", "int_ci_len",
)


def _one_rep(key: jax.Array, rho: jax.Array, cfg: SimConfig,
             eps=None, k_pad: int | None = None) -> tuple:
    """One Monte-Carlo replication: generate → estimate → metrics.

    The body of the reference's hot loop (vert-cor.R:392-419,
    ver-cor-subG.R:174-198), as a pure function of the rep key. ``rho`` is
    traced (not baked into the compilation cache) so one compiled kernel
    serves a whole ρ-sweep at fixed (n, ε) — the grid's shape bucket.

    ``eps``: optional traced ``(ε₁, ε₂)`` pair overriding the config's
    static values — the ε-merged bucket mode (``GridConfig.bucket_merge``):
    the subG estimators run with in-kernel masked batch geometry and an
    explicit protocol direction, so one compiled kernel serves every
    ε-pair at a given n. subG families only (the sign estimators keep
    static geometry), and the caller must guarantee ε₁ ≥ ε₂ (the named
    ``sender="x"`` then matches the larger-ε rule the static path applies).
    """
    if eps is not None and not cfg.use_subg:
        raise ValueError("traced-eps replication (bucket_merge) is only "
                         "supported for the subG families")
    if eps is not None and cfg.stream_n_chunk:
        # the streaming body's chunk geometry is static — silently
        # running it at the cfg's placeholder ε would compute every
        # point at the wrong privacy budget
        raise ValueError("traced-eps replication (bucket_merge) does not "
                         "compose with the streaming path")
    if cfg.stream_n_chunk:
        ni, it = _one_rep_streaming(key, rho, cfg)
        return _metrics_row(ni, it, rho)

    xy = cfg.dgp_fn()(rng.stream(key, "dgp"), cfg.n, rho)
    x, y = xy[:, 0], xy[:, 1]

    if cfg.use_subg:
        real = cfg.subg_variant == "real"
        e1, e2 = (cfg.eps1, cfg.eps2) if eps is None else eps
        ni = correlation_ni_subg(rng.stream(key, "ni"), x, y, e1,
                                 e2, eta1=cfg.eta1, eta2=cfg.eta2,
                                 alpha=cfg.alpha,
                                 randomize_batches=real,
                                 enforce_min_k=real,
                                 dynamic_geometry=eps is not None,
                                 k_pad=k_pad)
        it = ci_int_subg(rng.stream(key, "int"), x, y, e1, e2,
                         eta1=cfg.eta1, eta2=cfg.eta2,
                         alpha=cfg.alpha, variant=cfg.subg_variant,
                         mixquant_mode=cfg.mixquant_mode,
                         sender="x" if eps is not None else None)
    else:
        ni = ci_ni_signbatch(rng.stream(key, "ni"), x, y, cfg.eps1, cfg.eps2,
                             alpha=cfg.alpha, normalise=cfg.normalise)
        it = ci_int_signflip(rng.stream(key, "int"), x, y, cfg.eps1, cfg.eps2,
                             alpha=cfg.alpha, mode=cfg.ci_mode,
                             normalise=cfg.normalise,
                             mixquant_mode=cfg.mixquant_mode)

    return _metrics_row(ni, it, rho)


def _metrics_row(ni, it, rho) -> tuple:
    """Per-rep metrics in DETAIL_FIELDS order (vert-cor.R:401-417)."""

    def metrics(r):
        cover = ((rho >= r.ci_low) & (rho <= r.ci_high)).astype(jnp.float32)
        return (r.rho_hat - rho) ** 2, cover, r.ci_high - r.ci_low

    ni_se2, ni_cover, ni_len = metrics(ni)
    int_se2, int_cover, int_len = metrics(it)
    return (ni.rho_hat, it.rho_hat, ni_se2, int_se2,
            ni.ci_low, ni.ci_high, it.ci_low, it.ci_high,
            ni_cover, int_cover, ni_len, int_len)


def _one_rep_streaming(key: jax.Array, rho: jax.Array, cfg: SimConfig):
    """Streaming replication body: the same generate → estimate pipeline
    with the n axis blocked into ``cfg.stream_n_chunk``-row chunks that are
    regenerated from folded keys instead of held in HBM (stress path,
    BASELINE.md config 5)."""
    from dpcorr.models.estimators import streaming as st
    from dpcorr.models.estimators.common import batch_geometry

    m, _ = batch_geometry(cfg.n, cfg.eps1, cfg.eps2)
    n_chunk = st.choose_n_chunk(cfg.n, m, cfg.stream_n_chunk)
    chunk_fn = st.dgp_chunk_fn(cfg.dgp_fn(), rng.stream(key, "dgp"),
                               n_chunk, rho)
    if cfg.use_subg:
        # one fused pass: the chunk is generated once for both estimators
        # (bit-identical to the separate kernels — same key addresses);
        # halves the dominant DGP/PRNG work at stress scale (config 5)
        ni, it = st.subg_pair_stream(
            rng.stream(key, "ni"), rng.stream(key, "int"), chunk_fn,
            cfg.n, cfg.eps1, cfg.eps2, eta1=cfg.eta1, eta2=cfg.eta2,
            alpha=cfg.alpha, mixquant_mode=cfg.mixquant_mode,
            n_chunk=n_chunk)
    else:
        # pass A depends only on the data — compute once, share across both
        # estimators (each still draws its own standardization noise)
        sums = (st.clipped_moment_sums(chunk_fn, cfg.n, n_chunk)
                if cfg.normalise else None)
        ni = st.ci_ni_signbatch_stream(
            rng.stream(key, "ni"), chunk_fn, cfg.n, cfg.eps1, cfg.eps2,
            alpha=cfg.alpha, normalise=cfg.normalise, n_chunk=n_chunk,
            moment_sums=sums)
        it = st.ci_int_signflip_stream(
            rng.stream(key, "int"), chunk_fn, cfg.n, cfg.eps1, cfg.eps2,
            alpha=cfg.alpha, mode=cfg.ci_mode, normalise=cfg.normalise,
            mixquant_mode=cfg.mixquant_mode, n_chunk=n_chunk,
            moment_sums=sums)
    return ni, it


def stress_chunk_size(b: int, on_tpu: bool) -> int:
    """Replication vmap width for the streaming stress path (BASELINE
    config 5, ``stream_n_chunk`` set). A TPU wants wide blocks — (chunk,
    65536, 2) f32 at chunk=32 is ~17 MB resident per ``lax.map`` step,
    nowhere near HBM. On CPU the opposite: vmapping even a few
    replications interleaves their n-chunk scan states and evicts each
    other's cache lines, so sequential reps win — measured 2026-07-31 at
    n=10⁶ with the fused subG pair: chunk 1 → 31.9 reps/sec, 2 → 30.1,
    4 → 22.0, 8 → 20.8, 32 (the previous b//8 policy at b=256) → ~16.
    The pre-r04 ``b//8`` rule was tuned against the separate streaming
    kernels; the fused pair's single-pass state is exactly what a core's
    cache can hold once."""
    return min(b, 32) if on_tpu else 1


#: log-once flag for the chunked_vmap tail-split notice
_TAIL_SPLIT_LOGGED = False


def chunked_vmap(fn: Callable, args, chunk_size: int):
    """``vmap(fn)`` over axis 0, blocked into ``lax.map`` chunks.

    Keeps at most ``chunk_size`` replications' intermediates live in HBM.
    ``args`` is one array (→ ``fn(x)``) or a tuple of same-length arrays
    mapped together (→ ``fn(*xs)``, e.g. per-element (key, ρ) pairs for
    the bucketed grid).

    A non-multiple tail runs as its OWN narrower ``vmap`` row rather than
    being padded up to a full chunk and truncated (the pre-r08 policy,
    which wasted up to ``chunk_size - 1`` replications per call — at
    B=250, chunk=4096 it computed 4096 reps and threw 3846 away, skewing
    reps/sec at small B). Bit-safety: every vmap width ≥ 2 produces
    bitwise-identical per-rep outputs for all four estimator families,
    but width 1 lowers differently (measured, r08 —
    ``utils.geometry.CHUNK_FLOOR``), so a lone tail element is padded up
    to width 2 and truncated: one wasted rep instead of ``chunk - 1``.
    """
    global _TAIL_SPLIT_LOGGED
    is_tuple = isinstance(args, tuple)
    tree = args if is_tuple else (args,)
    b = jax.tree.leaves(tree)[0].shape[0]
    chunk = min(chunk_size, b)
    n_full, tail = divmod(b, chunk)

    def mapped(t, rows, width):
        blocked = jax.tree.map(
            lambda a: a.reshape(rows, width, *a.shape[1:]), t)
        out = jax.lax.map(lambda tt: jax.vmap(fn)(*tt), blocked)
        return jax.tree.map(
            lambda a: a.reshape(rows * width, *a.shape[2:]), out)

    if not tail:
        return mapped(tree, n_full, chunk)

    width = max(tail, CHUNK_FLOOR)
    if not _TAIL_SPLIT_LOGGED:
        _TAIL_SPLIT_LOGGED = True
        log.info(
            "chunked_vmap tail-split: B=%d at chunk=%d runs a width-%d "
            "tail row (%d padded reps; the old full-chunk pad wasted %d)",
            b, chunk, width, width - tail, chunk - tail)
    head = jax.tree.map(lambda a: a[: n_full * chunk], tree)
    tl = jax.tree.map(lambda a: a[n_full * chunk:], tree)
    if width != tail:  # only tail == 1: replicate the one element to 2
        tl = jax.tree.map(
            lambda a: jnp.concatenate([a] * width), tl)
    t_out = jax.tree.map(lambda a: a[:tail], mapped(tl, 1, width))
    if not n_full:
        return t_out
    h_out = mapped(head, n_full, chunk)
    return jax.tree.map(
        lambda h, t: jnp.concatenate([h, t]), h_out, t_out)


def _detail_from_keys(cfg: SimConfig, keys: jax.Array, rho: jax.Array):
    """The one replication-batch body every backend runs: chunked vmap of
    ``_one_rep`` over explicit per-rep keys at one traced ρ. Local, sharded
    detail, and psum-summary paths all delegate here — the
    bit-identity-across-backends contract is this function being the
    single source of truth."""
    return chunked_vmap(lambda k: _one_rep(k, rho, cfg), keys, cfg.chunk_size)


@partial(jax.jit, static_argnums=(0,))
def _run_detail_core(cfg: SimConfig, key: jax.Array, rho: jax.Array):
    return _detail_from_keys(cfg, rng.rep_keys(key, cfg.b), rho)


@partial(jax.jit, static_argnums=(0,))
def _run_detail_flat(cfg_norho: SimConfig, keys: jax.Array, rhos: jax.Array):
    """Batched-design-point kernel: per-element (key, ρ) pairs, flattened
    over (points × replications) — the grid-axis vectorization used by the
    bucketed grid backend (ρ is traced, so every design point in a
    (n, ε)-shape bucket shares this one compiled kernel *invocation*, not
    just its cache entry)."""
    return chunked_vmap(lambda k, r: _one_rep(k, r, cfg_norho),
                        (keys, rhos), cfg_norho.chunk_size)


@partial(jax.jit, static_argnums=(0, 5))
def _run_detail_flat_eps(cfg_noeps: SimConfig, keys: jax.Array,
                         rhos: jax.Array, eps1s: jax.Array,
                         eps2s: jax.Array, k_pad: int | None = None):
    """ε-merged bucket kernel: like :func:`_run_detail_flat` but ε is a
    per-element traced operand too, so ONE compiled kernel serves every
    (ρ, ε) design point at a given n (``GridConfig.bucket_merge="eps"``;
    subG families only — see :func:`_one_rep`). ``k_pad``: static pad
    bound for the per-batch vectors (common.k_pad_for)."""
    return chunked_vmap(
        lambda k, r, e1, e2: _one_rep(k, r, cfg_noeps, eps=(e1, e2),
                                      k_pad=k_pad),
        (keys, rhos, eps1s, eps2s), cfg_noeps.chunk_size)


def _run_detail(cfg: SimConfig, key: jax.Array):
    # Normalize rho (traced instead) and seed (host-side only: it feeds the
    # key derivation, never the kernel) out of the static cache key, so a
    # ρ-sweep / reseeded rerun reuses one compiled kernel.
    cfg_norho = dataclasses.replace(cfg, rho=0.0, seed=0)
    return _run_detail_core(cfg_norho, key, jnp.float32(cfg.rho))


class RepBlockPipeline:
    """Donated, pre-sharded, overlapped replication-block executor.

    The reduction-shaped hot loop (bench headline, power sweeps) as
    chained fixed-size blocks with an explicit ``(key_data, accumulators)``
    carry:

    - **donation** — ``donate_argnums=(0, 1)``: the per-block key buffer
      and the accumulator scalars are donated to XLA *and the kernel
      returns the next block's keys*, so the uint32 key buffer aliases
      in→out and is reused in place instead of round-tripping an
      allocation per block. Typed PRNG-key avals are never donatable on
      this jax, so raw ``rng.key_data`` crosses the jit boundary exactly
      as in the ``jax.export`` contract (``utils.compile``) and is
      rewrapped inside.
    - **pre-sharding** — every operand and result is pinned to one
      explicit sharding (``utils.compile.host_sharding``): degenerate on
      a 1-device CPU host, and the machinery a TPU chain needs so
      chained blocks never reshard between dispatches.
    - **overlap** — the next block's keys are generated ON DEVICE inside
      block *i*'s program (double-buffered keygen with no host
      round-trip), dispatch is async, and the host syncs exactly once
      per :meth:`run`, at the reduction boundary
      (``dpcorr_transfer_fetches_total``).

    ``rep_fn(key) -> tuple[out_len]`` is the per-replication body; each
    output is sum-reduced into its accumulator. Bit-identity contract:
    block *i* runs ``chunked_vmap(rep_fn)`` over
    ``rng.rep_keys(rng.design_key(key, i), block_reps)`` — the same key
    addresses and the same chunked math as the un-donated path, pinned
    by :meth:`block_detail` and tests/test_pipeline.py for all four
    estimator families.

    **Mesh placement** (``placement="mesh"``, the plan layer's first
    mesh consumer — ``dpcorr.plan``): the rep axis is sharded ``P("rep")``
    over ``parallel.mesh.rep_mesh`` and the donated key/acc carry stays
    *per-shard* — each device folds its next-block keys at the global
    replication addresses (``rng.rep_keys_slice``) and keeps one
    accumulator lane, so no cross-device communication happens until the
    single host fetch at the reduction boundary. Per-rep outputs
    (:meth:`block_detail`, which runs the genuinely sharded program) are
    **bitwise identical** to the local placement for every chunk width —
    the geometry invariance measured in r08. The reduced sums fold the
    per-shard lanes on the host in fixed ascending shard order
    (float64): deterministic for a given mesh size, tolerance-equal (not
    bitwise) to the single-device sequential sum because a different
    reduction tree rounds differently.
    """

    def __init__(self, rep_fn: Callable, out_len: int, *, key: jax.Array,
                 block_reps: int, chunk_size: int, family: str = "custom",
                 device=None, counters=None, aot: bool = True,
                 observer=None, impl: str | None = None,
                 acc_dtype=jnp.float32, profiler=None,
                 placement: str = "local", mesh=None):
        from dpcorr import plan as plan_mod
        from dpcorr.obs import transfer as transfer_mod

        #: optional obs.prof.BlockProfiler — strictly opt-in: every use
        #: sits behind ``is not None`` so the unprofiled path costs
        #: nothing and performs the same single host sync per run()
        self.profiler = profiler
        self.rep_fn = rep_fn
        self.out_len = int(out_len)
        self.block_reps = int(block_reps)
        self.chunk_size = max(int(chunk_size), CHUNK_FLOOR)
        self.family = family
        #: PRNG impl the raw key words are rewrapped with inside the
        #: kernel; None = the process default (``rng.impl_tag``). The
        #: bench's ``xla_rbg`` path passes "rbg" with a matching root key.
        self.impl = impl
        self.acc_dtype = acc_dtype
        self._key = key
        self._counters = counters if counters is not None \
            else transfer_mod.default_counters()
        self._observer = observer
        self.placement = plan_mod.resolve_placement(placement, mesh=mesh,
                                                    device=device)
        if self.placement.name not in ("local", "mesh"):
            raise ValueError(
                f"RepBlockPipeline supports 'local' and 'mesh' "
                f"placements, got {self.placement.name!r}")
        if self.placement.name == "mesh":
            n_dev = self.placement.device_count
            if self.block_reps % n_dev != 0:
                raise ValueError(
                    f"block_reps={self.block_reps} must split evenly "
                    f"over the {n_dev}-device mesh: the donated carry "
                    "is per-shard (equal key-buffer and accumulator "
                    "lanes on every device)")
            self._build_mesh_kernels()
        else:
            self._build_local_kernels()
        self._blk = self._blk_jit
        #: None until the runtime has shown its hand; then True iff no
        #: donation-decline warning was observed
        self.donation_engaged: bool | None = None
        self.aot_ok: bool | None = None
        if aot:
            acc_avals = tuple(
                jax.ShapeDtypeStruct(self._acc_shape, self.acc_dtype)
                for _ in range(self.out_len))
            # the key-data aval is derived from THIS pipeline's keygen
            # (not the process-default impl): an "rbg" root carries 4
            # uint32 words where threefry carries 2
            kd_aval = jax.eval_shape(
                lambda i: rng.key_data(rng.rep_keys(
                    rng.design_key(self._key, i), self.block_reps)),
                jax.ShapeDtypeStruct((), jnp.uint32))
            with transfer_mod.donation_watch(self._counters) as w:
                unit = self._executor().prepare(
                    ("rep_block", self.family, self.placement.name,
                     self.block_reps, self.chunk_size, self.out_len,
                     id(self.rep_fn)),
                    self._blk_jit,
                    (kd_aval, acc_avals,
                     jax.ShapeDtypeStruct((), jnp.uint32)),
                    signature={"kernel": "rep_block",
                               "family": self.family,
                               "placement": self.placement.name,
                               "devices": self.placement.device_count,
                               "block_reps": self.block_reps,
                               "chunk_size": self.chunk_size},
                    cache=False)
                self.aot_ok = unit.aot_ok
                if unit.aot_ok:
                    self._blk = unit.fn
            if w.declined:
                # decline warnings fire at lowering — the first-dispatch
                # watch would never see this one
                self.donation_engaged = False
            elif self.aot_ok:
                self.donation_engaged = True

    def _executor(self):
        """The plan executor this pipeline compiles and fetches
        through (lazy: observer wiring stays per-pipeline)."""
        from dpcorr import plan as plan_mod

        if getattr(self, "_plan_ex", None) is None:
            self._plan_ex = plan_mod.Executor(
                self.placement, counters=self._counters,
                observer=self._observer)
        return self._plan_ex

    def _build_local_kernels(self):
        """Today's single-device kernels, bit-identical: one explicit
        device sharding for every operand, scalar accumulators."""
        self.sharding = self.placement.data_sharding()
        sh = self.sharding
        self._acc_shape = ()
        self._acc_sharding = sh

        def _body(key_data, acc, i):
            keys = rng.keys_from_data(key_data, self.impl)
            outs = chunked_vmap(self.rep_fn, keys, self.chunk_size)
            # the NEXT block's keys are produced on-device as part of
            # THIS block's program: the uint32 carry aliases in→out
            # (that is what makes it donatable at all — donation needs a
            # matching-shape output) and keygen overlaps the rep math
            nxt = rng.key_data(rng.rep_keys(
                rng.design_key(self._key, i + jnp.uint32(1)),
                self.block_reps))
            return nxt, tuple(a + o.sum()
                              for a, o in zip(acc, outs, strict=True))

        self._blk_jit = jax.jit(_body, donate_argnums=(0, 1),
                                in_shardings=sh, out_shardings=sh)
        self._keygen = jax.jit(
            lambda i: rng.key_data(rng.rep_keys(
                rng.design_key(self._key, i), self.block_reps)),
            out_shardings=sh)

    def _build_mesh_kernels(self):
        """Mesh kernels: the same body per shard under ``shard_map``,
        with per-shard keygen at global replication addresses and one
        accumulator lane per device. Matching in/out shardings on every
        carry leaf keep donation valid and stop jit from inserting a
        resharding copy between chained blocks."""
        from jax.sharding import PartitionSpec as P

        try:  # jax >= 0.5 re-exports shard_map at top level
            from jax import shard_map
        except ImportError:  # jax 0.4.x: experimental, same semantics
            from jax.experimental.shard_map import shard_map

        mesh = self.placement.mesh
        rep_sh = self.placement.data_sharding()
        repl_sh = self.placement.replicated_sharding()
        self.sharding = rep_sh
        n_dev = self.placement.device_count
        per = self.block_reps // n_dev
        self._acc_shape = (n_dev,)
        self._acc_sharding = rep_sh

        def _shard_body(key_data, acc, i):
            # local view: key_data (per, words), acc leaves (1,) lanes
            keys = rng.keys_from_data(key_data, self.impl)
            outs = chunked_vmap(self.rep_fn, keys, self.chunk_size)
            # per-shard keygen at GLOBAL replication addresses: shard s
            # folds exactly the (key, index) pairs rows [s·per, (s+1)·per)
            # of the local placement's rep_keys would — per-rep
            # bit-identity by construction, no communication
            s = jax.lax.axis_index("rep")
            nxt = rng.key_data(rng.rep_keys_slice(
                rng.design_key(self._key, i + jnp.uint32(1)),
                s * per, per))
            return nxt, tuple(a + o.sum()
                              for a, o in zip(acc, outs, strict=True))

        body = shard_map(_shard_body, mesh=mesh,
                         in_specs=(P("rep"), P("rep"), P()),
                         out_specs=(P("rep"), P("rep")))
        self._blk_jit = jax.jit(body, donate_argnums=(0, 1),
                                in_shardings=(rep_sh, rep_sh, repl_sh),
                                out_shardings=(rep_sh, rep_sh))
        # initial keygen: the full key vector, landed pre-sharded
        self._keygen = jax.jit(
            lambda i: rng.key_data(rng.rep_keys(
                rng.design_key(self._key, i), self.block_reps)),
            out_shardings=rep_sh)

    def _call(self, key_data, acc, i):
        try:
            return self._blk(key_data, acc, i)
        except TypeError:
            if self._blk is self._blk_jit:
                raise
            # AOT executables are strict about call signatures; degrade
            # once to the identical-HLO lazy jit
            log.warning("rep_block AOT executable rejected the call "
                        "signature; falling back to lazy jit")
            self._blk = self._blk_jit
            self.donation_engaged = None
            return self._blk(key_data, acc, i)

    def _dispatch(self, key_data, acc, i):
        if self.donation_engaged is None:
            from dpcorr.obs import transfer as transfer_mod

            with transfer_mod.donation_watch(self._counters) as w:
                out = self._call(key_data, acc, i)
                jax.block_until_ready(out[1])  # surface the warning now
            self.donation_engaged = not w.declined
            return out
        return self._call(key_data, acc, i)

    def run(self, n_blocks: int, *, start_block: int = 0):
        """Run ``n_blocks`` chained blocks; returns ``(sums, n_reps)``
        with ``sums`` the tuple of float accumulator totals. Exactly one
        host sync, at the reduction boundary."""
        acc = tuple(jnp.zeros(self._acc_shape, self.acc_dtype,
                              device=self._acc_sharding)
                    for _ in range(self.out_len))
        cur = self._keygen(jnp.uint32(start_block))
        prof = self.profiler
        pstate = None if prof is None else prof.run_start(
            family=self.family, block_reps=self.block_reps,
            n_blocks=int(n_blocks), start_block=int(start_block),
            counters=self._counters)
        for i in range(start_block, start_block + int(n_blocks)):
            cur, acc = self._dispatch(cur, acc, jnp.uint32(i))
            self._counters.donated_blocks.inc()
            if pstate is not None:
                # cadence-bounded profiler sync — NEVER taken when no
                # profiler is attached (the ≤3% A/B gate's invariant)
                prof.block_boundary(pstate, i - start_block, acc)
        acc = jax.block_until_ready(acc)
        self._counters.fetches.inc()
        if pstate is not None:
            prof.run_end(pstate)
        return (tuple(self._reduce_host(a) for a in acc),
                int(n_blocks) * self.block_reps)

    def _reduce_host(self, a) -> float:
        """Collapse one fetched accumulator leaf to a float. Local: the
        scalar itself. Mesh: fold the per-shard lanes in fixed ascending
        shard order (float64 on the host) — deterministic for a given
        mesh size; tolerance-equal, not bitwise, to the single-device
        sequential sum (different reduction tree, different rounding)."""
        if self._acc_shape == ():
            return float(a)
        total = 0.0
        for v in a:  # ascending shard index — never a set/dict order
            total += float(v)
        return total

    def cost_summary(self) -> dict:
        """XLA cost analysis of the compiled block kernel, normalized
        per replication: ``{flops, bytes, flops_per_rep, bytes_per_rep}``
        (empty when AOT fell back to lazy jit or the backend offers no
        analysis). Feeds measured arithmetic intensity into bench
        artifacts and ``benchmarks/roofline.py``."""
        if not self.aot_ok or self._blk is self._blk_jit:
            return {}
        from dpcorr.obs import hlo as obs_hlo

        cost = obs_hlo.cost_summary(self._blk)
        if not cost:
            return {}
        out = dict(cost)
        if self.block_reps > 0:
            if "flops" in cost:
                out["flops_per_rep"] = cost["flops"] / self.block_reps
            if "bytes" in cost:
                out["bytes_per_rep"] = cost["bytes"] / self.block_reps
        return out

    def block_detail(self, i: int = 0):
        """Un-reduced per-rep outputs of block ``i`` — the verification
        hook the bit-identity A/B tests compare against the plain
        (un-donated, un-presharded) path: same key addresses, same
        chunked math, so equality is exact, not approximate. Under mesh
        placement this runs the *genuinely sharded* program (the same
        ``shard_map`` body the hot loop executes), so the comparison
        certifies the sharded math, not a single-device re-derivation."""
        keys = rng.rep_keys(rng.design_key(self._key, i), self.block_reps)
        if self._acc_shape != ():
            return self._sharded_detail_fn()(
                jax.device_put(keys, self.sharding))
        fn = jax.jit(
            lambda k: chunked_vmap(self.rep_fn, k, self.chunk_size))
        return fn(keys)

    def _sharded_detail_fn(self):
        """Cached jit of the per-shard chunked map under ``shard_map`` —
        the mesh analogue of block_detail's plain jit (typed PRNG keys
        pass through ``P("rep")`` specs; proven in parallel.backend)."""
        if getattr(self, "_detail_sharded", None) is None:
            from jax.sharding import PartitionSpec as P

            try:
                from jax import shard_map
            except ImportError:
                from jax.experimental.shard_map import shard_map

            body = shard_map(
                lambda k: chunked_vmap(self.rep_fn, k, self.chunk_size),
                mesh=self.placement.mesh,
                in_specs=P("rep"), out_specs=P("rep"))
            self._detail_sharded = jax.jit(
                body, in_shardings=self.sharding,
                out_shardings=self.sharding)
        return self._detail_sharded


def summarize(detail: Mapping[str, jax.Array], rho: float):
    """Reference summary rows (vert-cor.R:421-443): per method
    mse, bias, var, coverage, ci_length."""
    out = {}
    for meth in ("ni", "int"):
        est = detail[f"{meth}_hat"]
        out[meth.upper()] = {
            "mse": float(jnp.mean(detail[f"{meth}_se2"])),
            "bias": float(jnp.mean(est) - rho),
            "var": float(jnp.var(est, ddof=1)),
            "coverage": float(jnp.mean(detail[f"{meth}_cover"])),
            "ci_length": float(jnp.mean(detail[f"{meth}_ci_len"])),
        }
    return out


@dataclasses.dataclass
class SimResult:
    """``detail``: dict of (B,) arrays (reference's replicate data.frame);
    ``summary``: {"NI": {...}, "INT": {...}} (reference's 2-row summary)."""

    detail: dict
    summary: dict
    config: SimConfig

    def summary_rows(self):
        """Summary as a list of flat dicts, one per method — the shape the
        aggregation layer (grid driver / pandas) consumes."""
        return [{"method": m, **v} for m, v in self.summary.items()]


def run_sim_one(cfg: SimConfig, key: jax.Array | None = None) -> SimResult:
    """Run one design point: B replications of (generate → NI + INT →
    metrics) as a single compiled kernel."""
    if key is None:
        key = rng.master_key(cfg.seed)
    raw = _run_detail(cfg, key)
    detail = dict(zip(DETAIL_FIELDS, raw, strict=True))
    return SimResult(detail, summarize(detail, cfg.rho), cfg)
