"""Synthetic data-generating processes (reference layer L0).

Each DGP is ``f(key, n, rho, ...) -> (n, 2) array`` — pure, keyed, static-
shaped, so one ``vmap`` over keys evaluates a whole replication batch. The
reference's ``MASS::mvrnorm`` (LAPACK eigendecomposition) is replaced by the
closed-form 2×2 Cholesky factor — exact for the bivariate case and MXU-
friendly (SURVEY.md §2.4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dpcorr.ops.noise import clip_sym
from dpcorr.utils.rng import stream


def _bvn(key, n, rho, mu, sigma, dtype=jnp.float32):
    """Bivariate normal via 2×2 Cholesky: X = μ₁+σ₁Z₁,
    Y = μ₂+σ₂(ρZ₁+√(1−ρ²)Z₂)."""
    z = jax.random.normal(key, (n, 2), dtype)
    rho = jnp.asarray(rho, dtype)
    x = mu[0] + sigma[0] * z[:, 0]
    y = mu[1] + sigma[1] * (rho * z[:, 0] + jnp.sqrt(1.0 - rho * rho) * z[:, 1])
    return jnp.stack([x, y], axis=1)


def gen_gaussian(key: jax.Array, n: int, rho, mu=(0.0, 0.0), sigma=(1.0, 1.0)) -> jax.Array:
    """Bivariate Gaussian with corr ρ and per-coordinate (μ, σ).

    Reference: ``gen_gaussian`` (vert-cor.R:64-73) and the general-Σ
    ``mvrnorm`` in ``run_sim_one`` v1 (vert-cor.R:389-394).
    """
    return _bvn(key, n, rho, jnp.asarray(mu, jnp.float32), jnp.asarray(sigma, jnp.float32))


def gen_bernoulli(key: jax.Array, n: int, rho) -> jax.Array:
    """Correlated Bernoulli(0.5) pair with Corr(X,Y)=ρ via conditional
    inversion: p11 = ¼+ρ/4, p01 = ¼−ρ/4 (vert-cor.R:78-98).

    Note the reference defines this DGP but never wires it into a driver
    (SURVEY.md Appendix A #7) — for good reason: the sign estimators'
    arcsine link ρ = sin(πη/2) assumes Gaussianity (vert-cor.R:150-153),
    so on Bernoulli data they are misspecified (measured at n=2000,
    ρ=0.3, ε=(1,1), B=4096: NI bias +0.14, coverage 0.88; INT coverage
    0.41). It is wired here (bench configs 2-3) as a robustness probe,
    not a calibrated setting."""
    rho = jnp.asarray(rho, jnp.float32)
    u = jax.random.uniform(stream(key, "bernoulli/u"), (n,), jnp.float32)
    v = jax.random.uniform(stream(key, "bernoulli/v"), (n,), jnp.float32)
    p11 = 0.25 + rho / 4.0
    p01 = 0.25 - rho / 4.0
    x = (u < 0.5).astype(jnp.float32)
    # P(Y=1|X=0) = p01/0.5, P(Y=1|X=1) = p11/0.5
    thresh = jnp.where(x == 1.0, p11 / 0.5, p01 / 0.5)
    y = (v < thresh).astype(jnp.float32)
    return jnp.stack([x, y], axis=1)


def gen_mix_gaussian(key: jax.Array, n: int, rho,
                     mu0=(0.0, 0.0), sigma0=(1.0, 1.0),
                     mu1=(3.0, 3.0), sigma1=(2.0, 0.5),
                     pi_mix=0.5) -> jax.Array:
    """Two-component Gaussian mixture, rows i.i.d., output hard-clipped to
    [−1, 1] (ver-cor-subG.R:115-136 — the clip at :135 is deliberate and
    makes realized correlation ≠ nominal ρ, SURVEY.md Appendix A #8).

    The reference stacks the two component blocks and shuffles rows; drawing
    a per-row label is distribution-identical and stays static-shaped.
    """
    labels = jax.random.bernoulli(stream(key, "mix_gaussian/labels"), pi_mix, (n,))
    out0 = _bvn(stream(key, "mix_gaussian/comp0"), n, rho, jnp.asarray(mu0, jnp.float32),
                jnp.asarray(sigma0, jnp.float32))
    out1 = _bvn(stream(key, "mix_gaussian/comp1"), n, rho, jnp.asarray(mu1, jnp.float32),
                jnp.asarray(sigma1, jnp.float32))
    out = jnp.where(labels[:, None], out1, out0)
    return clip_sym(out, 1.0)


def gen_bounded_factor(key: jax.Array, n: int, rho) -> jax.Array:
    """Bounded common-factor DGP: X = U+E₁, Y = U+E₂ with
    U ~ Unif[±√(3ρ)], Eᵢ ~ Unif[±√(3(1−ρ))] ⇒ mean 0, var 1, corr ρ
    (ver-cor-subG.R:141-154)."""
    rho = jnp.asarray(rho, jnp.float32)
    c_u = jnp.sqrt(3.0 * rho)
    c_e = jnp.sqrt(3.0 * (1.0 - rho))
    u = jax.random.uniform(stream(key, "bounded_factor/U"), (n,), jnp.float32, -1.0, 1.0) * c_u
    e1 = jax.random.uniform(stream(key, "bounded_factor/E1"), (n,), jnp.float32, -1.0, 1.0) * c_e
    e2 = jax.random.uniform(stream(key, "bounded_factor/E2"), (n,), jnp.float32, -1.0, 1.0) * c_e
    return jnp.stack([u + e1, u + e2], axis=1)


DGPS = {
    "gaussian": gen_gaussian,
    "bernoulli": gen_bernoulli,
    "mix_gaussian": gen_mix_gaussian,
    "bounded_factor": gen_bounded_factor,
}
