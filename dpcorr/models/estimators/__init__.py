"""DP correlation estimators and CI constructors (reference layer L2).

Four families (SURVEY.md §2.2):

- A. :mod:`ni_sign`  — non-interactive sign-batch (Gaussian), sine link.
- B. :mod:`int_sign` — one-round interactive randomized-response, sine link.
- C. :mod:`ni_subg`  — non-interactive clipped-batch (sub-Gaussian), no link.
- D. :mod:`int_subg` — interactive clipped (local-DP sender + central-DP
  receiver), with the grid (v1) and real-data (v2) variants exposed as
  explicit parameters per the duplication ledger (SURVEY.md Appendix A).

Each family also has a streaming (n-blocked) variant in :mod:`streaming`
for stress-scale n where the sample vectors must never materialize in HBM.

Every estimator is a pure function ``f(key, x, y, eps1, eps2, ...) ->
result`` with static batch geometry, so ``jax.vmap`` over keys evaluates a
full Monte-Carlo replication batch as one fused kernel.
"""

from dpcorr.models.estimators.common import (  # noqa: F401
    CorrResult,
    batch_geometry,
)
from dpcorr.models.estimators.int_sign import (  # noqa: F401
    ci_int_signflip,
    correlation_int_signflip,
)
from dpcorr.models.estimators.int_subg import ci_int_subg  # noqa: F401
from dpcorr.models.estimators.ni_sign import (  # noqa: F401
    ci_ni_signbatch,
    correlation_ni_signbatch,
)
from dpcorr.models.estimators.ni_subg import correlation_ni_subg  # noqa: F401
from dpcorr.models.estimators.registry import (  # noqa: F401
    FAMILIES,
    serving_entry,
)
from dpcorr.models.estimators.streaming import (  # noqa: F401
    array_chunk_fn,
    choose_n_chunk,
    ci_int_signflip_stream,
    ci_int_subg_stream,
    ci_ni_signbatch_stream,
    correlation_ni_subg_stream,
    dgp_chunk_fn,
    subg_pair_stream,
)
