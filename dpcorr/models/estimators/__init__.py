"""DP correlation estimators and CI constructors (reference layer L2).

Four families (SURVEY.md §2.2):

- A. :mod:`ni_sign`  — non-interactive sign-batch (Gaussian), sine link.
- B. :mod:`int_sign` — one-round interactive randomized-response, sine link.
- C. :mod:`ni_subg`  — non-interactive clipped-batch (sub-Gaussian), no link.
- D. :mod:`int_subg` — interactive clipped (local-DP sender + central-DP
  receiver), with the grid (v1) and real-data (v2) variants exposed as
  explicit parameters per the duplication ledger (SURVEY.md Appendix A).

Each family also has a streaming (n-blocked) variant in :mod:`streaming`
for stress-scale n where the sample vectors must never materialize in HBM.

Every estimator is a pure function ``f(key, x, y, eps1, eps2, ...) ->
result`` with static batch geometry, so ``jax.vmap`` over keys evaluates a
full Monte-Carlo replication batch as one fused kernel.
"""

import importlib

# Lazy re-exports (PEP 562): :mod:`families` in this package is jax-free
# and feeds serve-side request validation; an eager estimator import here
# would load jax into every process that only wants the family *names*
# (the fleet front end, lease keeper, jax-free benchmark drivers).
_EXPORTS = {
    "CorrResult": "common",
    "batch_geometry": "common",
    "ci_int_signflip": "int_sign",
    "correlation_int_signflip": "int_sign",
    "ci_int_subg": "int_subg",
    "ci_ni_signbatch": "ni_sign",
    "correlation_ni_signbatch": "ni_sign",
    "correlation_ni_subg": "ni_subg",
    "FAMILIES": "families",
    "serving_entry": "registry",
    "array_chunk_fn": "streaming",
    "choose_n_chunk": "streaming",
    "ci_int_signflip_stream": "streaming",
    "ci_int_subg_stream": "streaming",
    "ci_ni_signbatch_stream": "streaming",
    "correlation_ni_subg_stream": "streaming",
    "dgp_chunk_fn": "streaming",
    "subg_pair_stream": "streaming",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    value = getattr(
        importlib.import_module(f"{__name__}.{mod}"), name)
    globals()[name] = value  # cache: resolve each name once
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
