"""Streaming (n-blocked) estimator kernels for stress-scale sample sizes.

The materialized estimators hold the full (n,) sample vectors in HBM; under
``vmap`` over thousands of resident replications that is n × B_chunk floats
— fine at the reference's n ≤ 12,000 (ver-cor-subG.R:245) but not at the
stress config's n = 10⁶ (BASELINE.md config 5). These variants are the
SURVEY.md §5 "long-context" answer: a ``lax.fori_loop`` over n-chunks whose
body *regenerates* its chunk of data from a folded key (rematerialization —
trade RNG FLOPs for HBM, the ``jax.checkpoint`` idea applied to data) and
accumulates sufficient statistics, so per replication only O(n_chunk + k)
values are ever live.

What streams, per estimator:

- NI sign-batch / NI sub-Gaussian: per-batch means over m consecutive points
  (vert-cor.R:131-140). Batch noise is still drawn as one ``(k,)`` vector
  with the *same key address and call shape* as the materialized path, then
  sliced per chunk — so given identical data the streaming estimate equals
  the materialized one bit-for-bit up to float reduction order (k = n/m is
  small: 500 KB at n=10⁶). Accumulated: Σ T_j, Σ T_j².
- INT sign-flip: Σ of randomized-response cores (vert-cor.R:186-191);
  per-sample flips are drawn per chunk from a folded key. The single
  receiver Laplace draw keeps its materialized key address.
- INT sub-Gaussian (grid variant): Σ Uc, Σ Uc² of the clipped products
  (ver-cor-subG.R:87-97); per-sample sender noise per chunk.

DP standardization (``normalise=True``) needs global clipped moments before
any batch can be processed, so those estimators make **two passes**: pass A
accumulates Σ clip(x), Σ clip(x)² (the sums inside ``priv_standardize``,
vert-cor.R:322-348), pass B re-generates the same chunks (same keys) and
streams the batches. Identical key addressing means the standardization
noise matches the materialized path exactly.

Chunk protocol: ``chunk_fn(c) -> (n_chunk, 2)`` must return rows
[c·n_chunk, (c+1)·n_chunk) of an (effectively) infinite i.i.d. sample; rows
past n are masked out. ``n_chunk`` must be a multiple of the batch size m
(use :func:`choose_n_chunk`) so batch boundaries never straddle chunks.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from dpcorr.models.estimators import int_sign, int_subg  # submodules, not pkg re-exports
from dpcorr.models.estimators.common import CorrResult, batch_geometry
from dpcorr.ops.lambdas import lambda_int_n, lambda_n
from dpcorr.ops.noise import clip_sym, laplace
from dpcorr.ops.standardize import priv_moments_from_sums
from dpcorr.utils.rng import chunk_key, stream

ChunkFn = Callable[[jax.Array], jax.Array]  # c -> (n_chunk, 2)


def choose_n_chunk(n: int, m: int, target: int = 65536) -> int:
    """Largest multiple of m that is ≤ max(target, m): the resident-rows
    budget per replication, aligned so batches never straddle chunks."""
    return max(m, (min(target, n + m - 1) // m) * m)


def array_chunk_fn(xy: jax.Array, n_chunk: int) -> ChunkFn:
    """Chunk view of a materialized (n, 2) array (zero-padded tail) — used
    by the exactness tests and by HRS-sized fixed datasets."""
    n = xy.shape[0]
    n_chunks = -(-n // n_chunk)
    padded = jnp.pad(xy, ((0, n_chunks * n_chunk - n), (0, 0)))

    def chunk_fn(c):
        return jax.lax.dynamic_slice(padded, (c * n_chunk, 0), (n_chunk, 2))

    return chunk_fn


def dgp_chunk_fn(dgp_fn: Callable, key: jax.Array, n_chunk: int, rho) -> ChunkFn:
    """Chunkwise DGP: chunk c is generated from ``fold_in(key, c)``. Rows
    are i.i.d., so the chunked sample is distribution-identical to one
    ``dgp_fn(key, n, rho)`` call (the draws differ — SURVEY.md §5 RNG:
    acceptance is statistical, and the streaming key-tree is itself
    deterministic)."""

    def chunk_fn(c):
        return dgp_fn(chunk_key(key, c), n_chunk, rho)

    return chunk_fn


# ------------------------------------------------------------ pass A ----
def clipped_moment_sums(chunk_fn: ChunkFn, n: int, n_chunk: int,
                        l_raw=None):
    """Public pass-A entry: the (Σ clip, Σ clip²) sums both sign estimators
    standardize from. Compute once per replication and pass to both via
    ``moment_sums=`` — the sums depend only on the data, not the estimator
    (each still draws its own standardization noise, as the reference's
    separate ``priv_standardize`` calls do, vert-cor.R:211-215, 268-273).
    Default clip is the call sites' L = √(2·log n) (vert-cor.R:212, 269)."""
    if l_raw is None:
        l_raw = math.sqrt(2.0 * math.log(n))
    return _clipped_moment_sums(chunk_fn, n, n_chunk, l_raw)


def _clipped_moment_sums(chunk_fn: ChunkFn, n: int, n_chunk: int, l_raw):
    """Σ clip(·, ±l_raw) and Σ clip(·)² per column over the first n rows —
    the sufficient statistics of ``priv_standardize`` (vert-cor.R:334-341)."""
    n_chunks = -(-n // n_chunk)

    # lax.map (not a carried fori_loop): per-chunk partials are *varying*
    # values under shard_map's vma check, while a scalar carry seeded with a
    # replicated 0 would be rejected; C scalars of stacked partials are free.
    def chunk_stats(c):
        xy = clip_sym(chunk_fn(c), l_raw)
        w = ((c * n_chunk + jnp.arange(n_chunk)) < n).astype(xy.dtype)[:, None]
        return jnp.sum(xy * w, axis=0), jnp.sum(xy * xy * w, axis=0)

    s1c, s2c = jax.lax.map(chunk_stats, jnp.arange(n_chunks))
    return jnp.sum(s1c, axis=0), jnp.sum(s2c, axis=0)


def _priv_moments(std_key: jax.Array, s1, s2, n: int, eps_norm, l_raw):
    """(μ_priv, 1/σ_priv) from streamed sums, via the same shared core (and
    hence noise scales + key addresses) as ``priv_standardize``."""
    mu, var = priv_moments_from_sums(std_key, s1, s2, n, eps_norm, l_raw)
    return mu, 1.0 / jnp.sqrt(var)


def _standardizers(key: jax.Array, chunk_fn: ChunkFn, n: int, n_chunk: int,
                   eps1, eps2, ns: str, sums=None):
    """Pass A + per-column transforms (clip → center → scale), matching
    ``priv_standardize`` with clip L = √(2·log n) (vert-cor.R:212, 269)."""
    l_clip = math.sqrt(2.0 * math.log(n))
    s1, s2 = (_clipped_moment_sums(chunk_fn, n, n_chunk, l_clip)
              if sums is None else sums)
    mu_x, inv_x = _priv_moments(stream(key, f"{ns}/std_x"), s1[0], s2[0],
                                n, eps1, l_clip)
    mu_y, inv_y = _priv_moments(stream(key, f"{ns}/std_y"), s1[1], s2[1],
                                n, eps2, l_clip)
    tx = lambda v: (clip_sym(v, l_clip) - mu_x) * inv_x
    ty = lambda v: (clip_sym(v, l_clip) - mu_y) * inv_y
    return tx, ty


# ------------------------------------------------------------ NI core ----
def _ni_batch_noise(key_x: jax.Array, key_y: jax.Array, k: int,
                    scale_x, scale_y, pad_to: int):
    """The materialized-shape ``(k,)`` batch-noise draws, zero-padded to
    the chunk grid — one source for the separate and fused kernels so the
    key addresses and call shapes can never diverge."""
    lap_x = jnp.pad(laplace(key_x, (k,), scale_x), (0, pad_to - k))
    lap_y = jnp.pad(laplace(key_y, (k,), scale_y), (0, pad_to - k))
    return lap_x, lap_y


def _ni_chunk_stats(xy, c, tx: Callable, ty: Callable, m: int, kc: int,
                    k: int, lap_x, lap_y):
    """One chunk's NI contribution (vert-cor.R:131-153 /
    ver-cor-subG.R:40-52): kc batch means of the transformed columns plus
    the sliced batch noise; batches past k are masked to 0 (a chunk past
    the last batch contributes exact zeros)."""
    xb = tx(xy[:, 0]).reshape(kc, m).mean(axis=1)
    yb = ty(xy[:, 1]).reshape(kc, m).mean(axis=1)
    b0 = c * kc
    xt = xb + jax.lax.dynamic_slice(lap_x, (b0,), (kc,))
    yt = yb + jax.lax.dynamic_slice(lap_y, (b0,), (kc,))
    t = jnp.where(b0 + jnp.arange(kc) < k, m * xt * yt, 0.0)
    return jnp.sum(t), jnp.sum(t * t)


def _ni_from_sums(st, st2, k: int):
    """(η̂, sd(T_j)) from the accumulated Σ T_j, Σ T_j² (sample sd with
    denominator k−1, as R's sd)."""
    eta_hat = st / k
    var_t = jnp.maximum((st2 - k * eta_hat * eta_hat) / max(k - 1, 1), 0.0)
    return eta_hat, jnp.sqrt(var_t)


def _ni_stream(key_x: jax.Array, key_y: jax.Array, chunk_fn: ChunkFn,
               tx: Callable, ty: Callable, m: int, k: int,
               scale_x, scale_y, n_chunk: int):
    """Streamed batch pipeline; returns (η̂, sd(T_j)). Composed from the
    shared pieces above so it stays bit-identical to the fused pair."""
    kc = n_chunk // m
    n_chunks = -(-k // kc)
    lap_x, lap_y = _ni_batch_noise(key_x, key_y, k, scale_x, scale_y,
                                   n_chunks * kc)

    def chunk_stats(c):
        return _ni_chunk_stats(chunk_fn(c), c, tx, ty, m, kc, k,
                               lap_x, lap_y)

    st_c, st2_c = jax.lax.map(chunk_stats, jnp.arange(n_chunks))
    return _ni_from_sums(jnp.sum(st_c), jnp.sum(st2_c), k)


def _ni_subg_interval(eta_hat, s_t, k: int, m: int, lam1, lam2,
                      alpha: float) -> CorrResult:
    """NI subG normal CI tail (ver-cor-subG.R:51-59): no sine link,
    ρ-space clamp; shared by the separate and fused kernels."""
    rho_hat = eta_hat
    se = s_t / jnp.sqrt(float(k))
    crit = ndtri(1.0 - alpha / 2.0)
    lo = jnp.maximum(rho_hat - crit * se, -1.0)
    hi = jnp.minimum(rho_hat + crit * se, 1.0)
    aux = {"k": k, "m": m, "lambda_x": lam1, "lambda_y": lam2}
    return CorrResult(rho_hat, lo, hi, aux)


def ci_ni_signbatch_stream(key: jax.Array, chunk_fn: ChunkFn, n: int,
                           eps1: float, eps2: float, alpha: float = 0.05,
                           normalise: bool = True,
                           n_chunk: int = 65536,
                           moment_sums=None) -> CorrResult:
    """Streaming NI sign-batch estimate + CI ≡ :func:`ci_ni_signbatch`
    (vert-cor.R:204-255) without materializing the n-vectors."""
    m, k = batch_geometry(n, eps1, eps2)
    if n_chunk % m:
        # chunk_fn's chunking is baked into its closure, so silently
        # re-rounding here would desync from it — the caller must align
        # (use choose_n_chunk) before building chunk_fn.
        raise ValueError(
            f"n_chunk={n_chunk} must be a multiple of the batch size m={m} "
            f"(use choose_n_chunk(n, m, target))")
    if normalise:
        sx, sy = _standardizers(key, chunk_fn, n, n_chunk, eps1, eps2,
                                "ni_sign", sums=moment_sums)
        tx = lambda v: jnp.sign(sx(v))
        ty = lambda v: jnp.sign(sy(v))
    else:
        tx = ty = jnp.sign
    eta_hat, s_eta = _ni_stream(
        stream(key, "ni_sign/lap_x"), stream(key, "ni_sign/lap_y"),
        chunk_fn, tx, ty, m, k, 2.0 / (m * eps1), 2.0 / (m * eps2), n_chunk)
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)
    half = ndtri(1.0 - alpha / 2.0) * s_eta / jnp.sqrt(float(k))
    # η-space clamp THEN sine map (vert-cor.R:249-254)
    lo = jnp.sin(jnp.pi / 2.0 * jnp.maximum(eta_hat - half, -1.0))
    hi = jnp.sin(jnp.pi / 2.0 * jnp.minimum(eta_hat + half, 1.0))
    return CorrResult(rho_hat, lo, hi)


def correlation_ni_subg_stream(key: jax.Array, chunk_fn: ChunkFn, n: int,
                               eps1: float, eps2: float,
                               eta1: float = 1.0, eta2: float = 1.0,
                               alpha: float = 0.05,
                               n_chunk: int = 65536) -> CorrResult:
    """Streaming NI clipped-batch ≡ the grid variant of
    :func:`correlation_ni_subg` (ver-cor-subG.R:25-62): sequential batches,
    λ from ``lambda_n`` (randomized batches need a global permutation and
    stay on the materialized path)."""
    m, k = batch_geometry(n, eps1, eps2)
    if n_chunk % m:
        raise ValueError(
            f"n_chunk={n_chunk} must be a multiple of the batch size m={m} "
            f"(use choose_n_chunk(n, m, target))")
    lam1 = lambda_n(n, eta1)
    lam2 = lambda_n(n, eta2)
    eta_hat, s_t = _ni_stream(
        stream(key, "ni_subg/lap_x"), stream(key, "ni_subg/lap_y"),
        chunk_fn, lambda v: clip_sym(v, lam1), lambda v: clip_sym(v, lam2),
        m, k, 2.0 * lam1 / (m * eps1), 2.0 * lam2 / (m * eps2), n_chunk)
    return _ni_subg_interval(eta_hat, s_t, k, m, lam1, lam2, alpha)


# ----------------------------------------------------------- INT sign ----
def ci_int_signflip_stream(key: jax.Array, chunk_fn: ChunkFn, n: int,
                           eps1: float, eps2: float, alpha: float = 0.05,
                           mode: str = "auto", normalise: bool = True,
                           mixquant_mode: str = "det",
                           n_chunk: int = 65536,
                           moment_sums=None) -> CorrResult:
    """Streaming INT sign-flip ≡ :func:`ci_int_signflip`
    (vert-cor.R:260-317): Σ core accumulated per chunk, per-sample flips
    from per-chunk folded keys, CI via the shared interval constructor."""
    if normalise:
        sx, sy = _standardizers(key, chunk_fn, n, n_chunk, eps1, eps2,
                                "int_sign", sums=moment_sums)
    else:
        sx = sy = lambda v: v

    eps_s, eps_r = max(eps1, eps2), min(eps1, eps2)  # vert-cor.R:170-172
    e_s = math.exp(eps_s)
    p_keep = e_s / (e_s + 1.0)
    est_key = stream(key, "int_sign/est")
    flip_base = stream(est_key, "int_sign/flips")
    n_chunks = -(-n // n_chunk)

    def chunk_stats(c):
        xy = chunk_fn(c)
        s = jax.random.bernoulli(chunk_key(flip_base, c), p_keep,
                                 (n_chunk,))
        core = ((2.0 * s.astype(jnp.float32) - 1.0)
                * jnp.sign(sx(xy[:, 0])) * jnp.sign(sy(xy[:, 1])))
        w = (c * n_chunk + jnp.arange(n_chunk)) < n
        return jnp.sum(jnp.where(w, core, 0.0))

    sum_core = jnp.sum(jax.lax.map(chunk_stats, jnp.arange(n_chunks)))
    scale_z = 2.0 * (e_s + 1.0) / (n * (e_s - 1.0) * eps_r)
    z = laplace(stream(est_key, "int_sign/lap_z"), (), scale_z)
    eta_hat = (e_s + 1.0) / (n * (e_s - 1.0)) * sum_core + z
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)
    return int_sign.interval_from_rho(key, rho_hat, n, eps_s, eps_r, alpha,
                                      mode, mixquant_mode)


# -------------------------------------------------- INT subG pieces ----
def _int_subg_roles(n: int, eps1, eps2, eta1, eta2):
    """Sender selection + λ pair (ver-cor-subG.R:76-81, lambda_INT_n) —
    shared by the separate and fused kernels."""
    sender_is_x = eps1 >= eps2
    eps_s, eps_r = (eps1, eps2) if sender_is_x else (eps2, eps1)
    eta_s, eta_r = (eta1, eta2) if sender_is_x else (eta2, eta1)
    lam_s, lam_r = lambda_int_n(n, eta_s=eta_s, eta_r=eta_r, eps_s=eps_s)
    return sender_is_x, eps_s, eps_r, lam_s, lam_r


def _int_subg_chunk_stats(xy, c, noise_base, sender_is_x: bool, lam_s,
                          lam_r, eps_s, n: int, n_chunk: int):
    """One chunk's INT contribution (ver-cor-subG.R:87-97): per-sample
    sender noise from the per-chunk folded key, clipped products, rows
    past n masked to 0."""
    xs = xy[:, 0] if sender_is_x else xy[:, 1]
    xo = xy[:, 1] if sender_is_x else xy[:, 0]  # v1: other NOT clipped
    noise = laplace(chunk_key(noise_base, c), (n_chunk,),
                    2.0 * lam_s / eps_s)
    uc = clip_sym((clip_sym(xs, lam_s) + noise) * xo, lam_r)
    w = (c * n_chunk + jnp.arange(n_chunk)) < n
    uc = jnp.where(w, uc, 0.0)
    return jnp.sum(uc), jnp.sum(uc * uc)


def _int_subg_interval(key: jax.Array, s1, s2, n: int, eps_s, eps_r,
                       lam_s, lam_r, alpha: float,
                       mixquant_mode: str) -> CorrResult:
    """INT subG estimate + grid-variant CI tail from the accumulated
    Σ Uc, Σ Uc² (ver-cor-subG.R:95-104); the central draw and the CI keep
    their materialized key addresses."""
    mean_uc = s1 / n
    central_scale = 2.0 * lam_r / (n * eps_r)
    rho_hat = mean_uc + laplace(stream(key, "int_subg/lap_recv"), (),
                                central_scale)
    var_uc = jnp.maximum((s2 - n * mean_uc * mean_uc) / (n - 1), 0.0)
    aux = {"lambda_sender": lam_s, "lambda_receiver": lam_r,
           "eps_sender": eps_s, "eps_receiver": eps_r}
    return int_subg.grid_interval(key, rho_hat, jnp.sqrt(var_uc), n, eps_r,
                                  central_scale, alpha,
                                  mixquant_mode)._replace(aux=aux)


# ------------------------------------------------- fused subG pair ----
def subg_pair_stream(key_ni: jax.Array, key_int: jax.Array,
                     chunk_fn: ChunkFn, n: int,
                     eps1: float, eps2: float,
                     eta1: float = 1.0, eta2: float = 1.0,
                     alpha: float = 0.05, mixquant_mode: str = "det",
                     n_chunk: int = 65536):
    """Both subG estimators in ONE pass over the chunks.

    The separate streaming kernels each re-generate the full n-row sample
    from ``chunk_fn`` — at the stress shape (n=10⁶, BASELINE.md config 5)
    that doubles the dominant PRNG/DGP work per replication. This fused
    pass generates each chunk once and accumulates the NI batch sums
    (Σ T_j, Σ T_j²) and the INT product sums (Σ Uc, Σ Uc²) side by side.

    Bit-identity contract: every noise draw keeps the *same key address
    and call shape* as in :func:`correlation_ni_subg_stream` /
    :func:`ci_int_subg_stream` (which themselves match the materialized
    estimators), and per-chunk accumulation order is unchanged, so the
    returned pair is bit-identical to calling the two separate streaming
    kernels — pinned by ``tests/test_streaming.py``.

    Returns ``(CorrResult_ni, CorrResult_int)``.
    """
    m, k = batch_geometry(n, eps1, eps2)
    if n_chunk % m:
        raise ValueError(
            f"n_chunk={n_chunk} must be a multiple of the batch size m={m} "
            f"(use choose_n_chunk(n, m, target))")
    # NI setup (as correlation_ni_subg_stream). The INT side needs
    # ceil(n/n_chunk) chunks; the NI side only ceil(k/kc) ≤ that (k·m ≤ n
    # and kc = n_chunk/m) — so the fused loop runs the larger count and
    # NI's mask zeroes the extra chunks' contributions exactly. The noise
    # arrays are padded to the larger grid so the slices stay in bounds.
    lam1 = lambda_n(n, eta1)
    lam2 = lambda_n(n, eta2)
    kc = n_chunk // m
    n_chunks = -(-n // n_chunk)
    lap_x, lap_y = _ni_batch_noise(
        stream(key_ni, "ni_subg/lap_x"), stream(key_ni, "ni_subg/lap_y"),
        k, 2.0 * lam1 / (m * eps1), 2.0 * lam2 / (m * eps2), n_chunks * kc)
    tx = lambda v: clip_sym(v, lam1)
    ty = lambda v: clip_sym(v, lam2)
    # INT setup (as ci_int_subg_stream)
    sender_is_x, eps_s, eps_r, lam_s, lam_r = _int_subg_roles(
        n, eps1, eps2, eta1, eta2)
    noise_base = stream(key_int, "int_subg/lap_sender")

    def chunk_stats(c):
        xy = chunk_fn(c)  # generated ONCE for both estimators
        ni_t = _ni_chunk_stats(xy, c, tx, ty, m, kc, k, lap_x, lap_y)
        int_u = _int_subg_chunk_stats(xy, c, noise_base, sender_is_x,
                                      lam_s, lam_r, eps_s, n, n_chunk)
        return ni_t + int_u

    st_c, st2_c, s1c, s2c = jax.lax.map(chunk_stats, jnp.arange(n_chunks))

    eta_hat, s_t = _ni_from_sums(jnp.sum(st_c), jnp.sum(st2_c), k)
    ni = _ni_subg_interval(eta_hat, s_t, k, m, lam1, lam2, alpha)
    it = _int_subg_interval(key_int, jnp.sum(s1c), jnp.sum(s2c), n, eps_s,
                            eps_r, lam_s, lam_r, alpha, mixquant_mode)
    return ni, it


# ----------------------------------------------------------- INT subG ----
def ci_int_subg_stream(key: jax.Array, chunk_fn: ChunkFn, n: int,
                       eps1: float, eps2: float,
                       eta1: float = 1.0, eta2: float = 1.0,
                       alpha: float = 0.05, mixquant_mode: str = "det",
                       n_chunk: int = 65536) -> CorrResult:
    """Streaming INT clipped (grid variant) ≡ ``ci_int_subg(variant="grid")``
    (ver-cor-subG.R:67-108): Σ Uc, Σ Uc² accumulated per chunk; per-sample
    sender noise from per-chunk folded keys; one central draw at the
    materialized key address. Composed from the same pieces as the fused
    pair so the two stay bit-identical."""
    sender_is_x, eps_s, eps_r, lam_s, lam_r = _int_subg_roles(
        n, eps1, eps2, eta1, eta2)
    noise_base = stream(key, "int_subg/lap_sender")
    n_chunks = -(-n // n_chunk)

    def chunk_stats(c):
        return _int_subg_chunk_stats(chunk_fn(c), c, noise_base,
                                     sender_is_x, lam_s, lam_r, eps_s,
                                     n, n_chunk)

    s1c, s2c = jax.lax.map(chunk_stats, jnp.arange(n_chunks))
    return _int_subg_interval(key, jnp.sum(s1c), jnp.sum(s2c), n, eps_s,
                              eps_r, lam_s, lam_r, alpha, mixquant_mode)
