"""B. One-round interactive sign-flip (randomized response) estimator + CI.

Reference: ``correlation_INT_signflip`` (vert-cor.R:164-195) and
``ci_INT_signflip`` (vert-cor.R:260-317). Math (SURVEY.md §2.2-B):

1. Sender = side with the larger ε (static at trace time).
2. Randomized response on the sender's signs with keep-prob
   p = e^{ε_s}/(e^{ε_s}+1); core_i = (2S_i−1)·sign(X_i)·sign(Y_i).
3. Receiver debiases and adds one Laplace draw:
   η̂ = (e^{ε_s}+1)/(n(e^{ε_s}−1))·Σcore + Lap(2(e^{ε_s}+1)/(n(e^{ε_s}−1)ε_r)).
4. ρ̂ = sin(π·η̂/2).
5. CI: η̂ recovered via (2/π)·asin(ρ̂); σ²_η = 1 − ((e^{ε_s}−1)/(e^{ε_s}+1))²η̂²;
   regime switch at √n·ε_r > 0.5 — the normal regime widths use the
   Gaussian+Laplace mixture quantile, the Laplace regime a pure-Laplace tail
   bound; both act in η-space, clamped there, then sine-mapped.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from dpcorr.models.estimators.common import CorrResult
from dpcorr.ops.mixquant import mixquant
from dpcorr.ops.noise import laplace
from dpcorr.ops.standardize import priv_center
from dpcorr.utils.rng import stream


def correlation_int_signflip(key: jax.Array, x: jax.Array, y: jax.Array,
                             eps1: float, eps2: float) -> jax.Array:
    """Point estimator ρ̂ (vert-cor.R:164-195). Inputs pre-standardized.

    The flipped product (2S−1)·sign(X)·sign(Y) is symmetric in the roles, so
    only the (ε_s, ε_r) assignment depends on the sender choice
    (vert-cor.R:178-183).
    """
    n = x.shape[0]
    eps_s, eps_r = max(eps1, eps2), min(eps1, eps2)  # vert-cor.R:170-172
    e_s = math.exp(eps_s)
    p_keep = e_s / (e_s + 1.0)
    s = jax.random.bernoulli(stream(key, "int_sign/flips"), p_keep, (n,))
    core = (2.0 * s.astype(jnp.float32) - 1.0) * jnp.sign(x) * jnp.sign(y)
    scale_z = 2.0 * (e_s + 1.0) / (n * (e_s - 1.0) * eps_r)
    z = laplace(stream(key, "int_sign/lap_z"), (), scale_z)
    eta_hat = (e_s + 1.0) / (n * (e_s - 1.0)) * jnp.sum(core) + z
    return jnp.sin(jnp.pi * eta_hat / 2.0)


def interval_from_rho(key: jax.Array, rho_hat: jax.Array, n: int,
                      eps_s: float, eps_r: float, alpha: float,
                      mode: str, mixquant_mode: str) -> CorrResult:
    """CI construction given ρ̂ (vert-cor.R:281-317), shared by the
    materialized and streaming estimators. ``key`` is the CI-level key (the
    mixquant MC substream hangs off it)."""
    e_s = math.exp(eps_s)
    ratio = (e_s + 1.0) / (e_s - 1.0)
    # η̂ back out of ρ̂: 1 − (2/π)·acos(ρ̂) ≡ (2/π)·asin(ρ̂) (vert-cor.R:281)
    eta_hat = 1.0 - jnp.arccos(rho_hat) * 2.0 / jnp.pi
    sigma_eta2 = 1.0 - (1.0 / ratio) ** 2 * eta_hat**2  # vert-cor.R:284
    se_norm_eta = jnp.sqrt(sigma_eta2) * ratio / jnp.sqrt(float(n))

    if mode == "auto":  # static switch (vert-cor.R:294-296)
        mode = "normal" if math.sqrt(n) * eps_r > 0.5 else "laplace"

    if mode == "normal":  # Case 1 in §4.1.1 (vert-cor.R:298-302)
        cstar = 2.0 / (jnp.sqrt(n * sigma_eta2) * eps_r)
        if mixquant_mode == "mc":
            from dpcorr.ops.mixquant import mixquant_mc

            q = mixquant_mc(stream(key, "int_sign/mixquant"), cstar, 1.0 - alpha / 2.0)
        else:
            q = mixquant(cstar, 1.0 - alpha / 2.0)
        width_eta = q * se_norm_eta
    elif mode == "laplace":  # Case 2 (vert-cor.R:303-308)
        width_eta = (2.0 / (n * eps_r)) * ratio * math.log(1.0 / alpha)
    else:
        raise ValueError(f"mode must be auto|normal|laplace, got {mode!r}")

    lo = jnp.sin(jnp.pi / 2.0 * jnp.maximum(eta_hat - width_eta, -1.0))
    hi = jnp.sin(jnp.pi / 2.0 * jnp.minimum(eta_hat + width_eta, 1.0))
    return CorrResult(rho_hat, lo, hi)


def ci_int_signflip(key: jax.Array, x: jax.Array, y: jax.Array,
                    eps1: float, eps2: float, alpha: float = 0.05,
                    mode: str = "auto", normalise: bool = True,
                    mixquant_mode: str = "det") -> CorrResult:
    """Estimate + CI (vert-cor.R:260-317).

    ``mode``: "auto" switches normal/laplace at √n·ε_r > 0.5
    (vert-cor.R:294-296) — static per design point. ``mixquant_mode``:
    "det" uses the closed-form quantile; "mc" reproduces the reference's
    per-CI 1000-draw order statistic (vert-cor.R:302).
    """
    n = x.shape[0]
    if normalise:
        l_clip = jnp.sqrt(2.0 * jnp.log(float(n)))
        # center-only: this estimator consumes signs, and
        # sign((x−μ)/σ) ≡ sign(x−μ) — see priv_center
        x = priv_center(stream(key, "int_sign/std_x"), x, eps1, l_clip)
        y = priv_center(stream(key, "int_sign/std_y"), y, eps2, l_clip)

    eps_s, eps_r = max(eps1, eps2), min(eps1, eps2)
    rho_hat = correlation_int_signflip(stream(key, "int_sign/est"), x, y, eps1, eps2)
    return interval_from_rho(key, rho_hat, n, eps_s, eps_r, alpha, mode,
                             mixquant_mode)
