"""Uniform batchable entry points over the four estimator families.

The serving layer (``dpcorr.serve``) batches concurrent requests from
*different* clients into one ``vmap`` launch, so it needs every family
behind ONE signature it can vmap without per-family plumbing:

    single(key, x, y) -> (rho_hat, ci_low, ci_high)

``serving_entry`` closes over everything that is static per compile
bucket (family, ε-pair, α, normalise) and drops ``CorrResult.aux`` —
the documented pre-vmap-boundary contract (common.CorrResult: aux is
host-side extras, never crosses a vmap).

Bit-reproducibility contract (measured on CPU 2026-08-05, all four
families, n ∈ {137, 500, 1024, 10000}; pinned by tests/test_serve.py):

- ``jax.lax.map`` over ``single`` (the serving layer's default
  ``exact`` batch engine) is **bit-identical** to ``jit(single)`` on
  every lane — the scalar program is compiled once and looped, so
  batching cannot change results. Holds under ``shard_map`` over the
  ``rep`` mesh too.
- ``jit(vmap(single))`` (the ``vector`` engine): ``rho_hat`` is
  bit-identical to the direct call for every family; the CI endpoints
  can differ by 1 ulp (~6e-8, data- and n-dependent) because XLA's
  vectorized codegen reassociates the CI arithmetic differently from
  the scalar program. Lanes ARE bit-identical across batch widths ≥ 2,
  so within the vector engine coalescing still never changes results —
  only the scalar/vector boundary differs.

ε is a *static* closure argument here (one compiled kernel per ε-pair
bucket): the interactive families branch on concrete ε floats at trace
time (sender selection, normal/laplace CI switch), so a traced-ε merged
serving kernel would need the same explicit-direction treatment as the
HRS sweep (``ci_int_subg(sender=...)``) — future work, noted in
docs/SERVING.md.
"""

from __future__ import annotations

from typing import Callable

import jax

from dpcorr.models.estimators.int_sign import ci_int_signflip
from dpcorr.models.estimators.int_subg import ci_int_subg
from dpcorr.models.estimators.ni_sign import ci_ni_signbatch
from dpcorr.models.estimators.ni_subg import correlation_ni_subg

# Re-exported from the jax-free families module (serve.request and
# the fleet front end import the names without loading estimators).
from dpcorr.models.estimators.families import FAMILIES  # noqa: F401,E402


def serving_entry(family: str, eps1: float, eps2: float,
                  alpha: float = 0.05,
                  normalise: bool = True) -> Callable:
    """The uniform single-request callable for one compile bucket.

    ``normalise`` applies to the sign families only (private centering
    before the sign transform, vert-cor.R:211-215); the subG families
    clip with data-independent λ_n bounds instead and ignore it.
    """
    if family not in FAMILIES:
        raise ValueError(f"unknown estimator family {family!r}; "
                         f"expected one of {FAMILIES}")

    if family == "ni_sign":
        def single(key: jax.Array, x: jax.Array, y: jax.Array):
            r = ci_ni_signbatch(key, x, y, eps1, eps2, alpha=alpha,
                                normalise=normalise)
            return r.rho_hat, r.ci_low, r.ci_high
    elif family == "int_sign":
        def single(key: jax.Array, x: jax.Array, y: jax.Array):
            r = ci_int_signflip(key, x, y, eps1, eps2, alpha=alpha,
                                normalise=normalise)
            return r.rho_hat, r.ci_low, r.ci_high
    elif family == "ni_subg":
        def single(key: jax.Array, x: jax.Array, y: jax.Array):
            r = correlation_ni_subg(key, x, y, eps1, eps2, alpha=alpha)
            return r.rho_hat, r.ci_low, r.ci_high
    else:  # int_subg
        def single(key: jax.Array, x: jax.Array, y: jax.Array):
            r = ci_int_subg(key, x, y, eps1, eps2, alpha=alpha)
            return r.rho_hat, r.ci_low, r.ci_high
    return single
