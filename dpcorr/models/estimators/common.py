"""Shared estimator plumbing: batch geometry, result container, R-compatible
sample sd."""

from __future__ import annotations

import logging
import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

log = logging.getLogger(__name__)


class CorrResult(NamedTuple):
    """Point estimate + CI.

    ``aux`` carries the per-variant extras the real-data reference functions
    return beyond the CI — batch geometry (k, m), λ thresholds, δ
    (real-data-sims.R:141-147, 244-252) — as a dict of scalars; ``None`` for
    variants without extras. Dropped before any vmap boundary (the MC
    simulator consumes only the three array fields).
    """

    rho_hat: jax.Array
    ci_low: jax.Array
    ci_high: jax.Array
    aux: Any = None


def batch_geometry(n: int, eps1: float, eps2: float,
                   enforce_min_k: bool = False) -> tuple[int, int]:
    """(m, k): batch size m = ⌈8/(ε₁ε₂)⌉ capped at n, k = ⌊n/m⌋ full batches.

    The paper's optimal batch design (vert-cor.R:124-126, ver-cor-subG.R:37-38).
    ``enforce_min_k`` adds the real-data fallback: if k < 2 then k = 2,
    m = ⌊n/2⌋ (real-data-sims.R:130). Static per design point — shapes are
    known at trace time, which is what keeps the kernels jit-compilable.
    """
    if n < 1:
        raise ValueError(f"Need at least one observation, got n={n}")
    m = math.ceil(8.0 / (eps1 * eps2))
    m = min(m, n)
    k = n // m
    if enforce_min_k and k < 2:
        k, m = 2, n // 2
    if k < 1:
        raise ValueError(
            f"Need at least one full batch: n={n}, m={m} (vert-cor.R:127)")
    return m, k


def batch_geometry_dyn(n: int, eps1, eps2,
                       enforce_min_k: bool = False):
    """Traced (m, k) int32 scalars for :func:`batch_geometry`'s rule —
    the ε values may be JAX tracers, so ONE compiled kernel can serve an
    entire ε-sweep (m and k become *data*, not program structure; the
    HRS sweep's 23 per-ε compiles collapse to one, `dpcorr/hrs.py`).
    ``n`` stays static: it is the physical array length and every shape
    in the masked kernel derives from it."""
    if n < 1:
        raise ValueError(f"Need at least one observation, got n={n}")
    q = 8.0 / (jnp.asarray(eps1, jnp.float32)
               * jnp.asarray(eps2, jnp.float32))
    # two float32 guards the static (float64) path never needs:
    # - the (1 - 1e-6) factor absorbs f32 round-UP at integer
    #   boundaries (e.g. ε=√2 squares to just under 2 in f32, making
    #   q = 4.0000001 and ceil jump to 5 where the static rule gives 4);
    #   a genuine fractional q is never 1e-6-close to an integer at
    #   these magnitudes, so only rounding artifacts snap down
    # - clipping BEFORE the int cast bounds q while still a float: at
    #   tiny ε₁ε₂ the unclipped f32 value can exceed int32 range, where
    #   astype would be implementation-defined instead of m=n
    m = jnp.clip(jnp.ceil(q * (1.0 - 1e-6)), 1.0, n).astype(jnp.int32)
    k = n // m
    if enforce_min_k:
        fallback = k < 2
        k = jnp.where(fallback, 2, k)
        m = jnp.where(fallback, n // 2, m)
    return m, k


#: entry points that have already warned about the f32 geometry band
#: (one warning per entry point per process, not per design row)
_F32_BAND_WARNED: set[str] = set()


def f32_geometry_band(eps_pairs, n: int | None = None) -> list[tuple]:
    """ε pairs where the traced-f32 rule (:func:`batch_geometry_dyn`)
    picks a different batch size m than the static f64 rule
    (:func:`batch_geometry`).

    The dyn kernel evaluates ``ceil(q·(1−1e-6))`` on an f32
    ``q = 8/(ε₁ε₂)``, so any pair whose q lands within ~1e-6 of an
    integer from *below* in f64 but not in f32 (or vice versa) sits in a
    disagreement band where the two paths choose adjacent m — a real,
    designed-in property of the snap-down guard (see
    :func:`batch_geometry_dyn`), not a bug, but one that silently
    changes (m, k) and hence the estimate when a design is moved between
    the static and merged/swept backends. Returns
    ``[(eps1, eps2, m_static, m_dyn), ...]`` (empty = no band hits);
    ``n`` applies the m ≤ n cap when known.
    """
    import numpy as np

    hits = []
    for eps1, eps2 in eps_pairs:
        m64 = math.ceil(8.0 / (float(eps1) * float(eps2)))
        q32 = np.float32(8.0) / (np.float32(eps1) * np.float32(eps2))
        m32 = int(math.ceil(float(np.float32(q32 * np.float32(1.0 - 1e-6)))))
        if n is not None:
            m64, m32 = min(m64, n), min(m32, n)
        if m64 != m32:
            hits.append((float(eps1), float(eps2), m64, m32))
    return hits


def warn_f32_geometry_band_once(eps_pairs, n: int | None = None,
                                where: str = "eps-sweep") -> list[tuple]:
    """Log-once guard for the f32/f64 m-disagreement band, called at the
    entry points that mix the two geometry paths (grid ε-merge
    validation, HRS ε-sweep). Returns the band hits so callers can act
    on them; logs at most one warning per ``where`` per process."""
    hits = f32_geometry_band(eps_pairs, n=n)
    if hits and where not in _F32_BAND_WARNED:
        _F32_BAND_WARNED.add(where)
        log.warning(
            "%s: %d ε pair(s) sit in the ~1e-6 f32/f64 batch-geometry "
            "band — the traced (f32) rule picks a different m than the "
            "static (f64) rule, e.g. eps=(%.6g,%.6g): m_static=%d vs "
            "m_dyn=%d. Estimates from the merged/swept path will differ "
            "from the static path for these pairs (adjacent batch "
            "design, both valid).",
            where, len(hits), hits[0][0], hits[0][1], hits[0][2],
            hits[0][3])
    return hits


def k_pad_for(n: int, eps_products) -> int:
    """Static upper bound on k = ⌊n/m⌋ over a known set of ε₁·ε₂
    products — the padded length for the dynamic-geometry estimator's
    per-batch vectors. m = ⌈8/(ε₁ε₂)⌉ is decreasing in the product, so
    the largest product gives the smallest m and hence the largest k.

    The bound must hold against the m the KERNEL computes, not the f64
    rule: the in-kernel f32 path (:func:`batch_geometry_dyn`) evaluates
    ``ceil(q·(1−1e-6))`` on an f32 q that can sit up to ~1.2e-6
    relative BELOW the f64 q — for a genuinely fractional q within 1e-6
    above an integer (e.g. 4.0000005) the kernel legitimately lands one
    m lower than f64 ceil, making k one bucket-row larger. So the bound
    uses the guard-consistent lower envelope ``ceil(q·(1−2e-6))``;
    without it a too-small pad would silently truncate live batches
    (the kernel also carries a NaN tripwire for that invariant). The
    floor of 2 covers the ``enforce_min_k`` fallback."""
    q_max = 8.0 / max(eps_products)
    m_lower = min(n, max(1, math.ceil(q_max * (1.0 - 2e-6))))
    return max(2, n // m_lower)


def batch_means_dyn(v: jax.Array, m, k, out_len: int | None = None) -> jax.Array:
    """Masked equivalent of :func:`batch_means` for traced (m, k): means
    of the k consecutive batches of size m over the first k·m entries,
    returned padded to ``out_len`` (default n; pass :func:`k_pad_for`'s
    static bound when the ε set is known — an 8× smaller pad for the
    reference subG grid). Entry j is meaningful only for j < k — mask
    downstream with ``arange(out_len) < k``.

    Because batches are CONSECUTIVE, batch sums are differences of the
    prefix sum at the batch boundaries — cumsum + two traced-index
    gathers. This vectorizes cleanly under ``vmap`` even when (m, k)
    differ per batch element (the ε-merged grid bucket), where a
    ``segment_sum`` formulation degenerates into per-element scatters
    (measured 1.8× whole-grid slowdown on CPU). Cost: prefix-sum
    differencing re-rounds each batch sum at the prefix magnitude
    (~n·ulp absolute, ~1e-4 relative at n≈2·10⁴) — orders of magnitude
    below the per-batch Laplace noise this feeds, and covered by the
    noise-silenced parity test's tolerance."""
    n = v.shape[0]
    csum = jnp.cumsum(v)
    j = jnp.arange(n if out_len is None else out_len)
    hi = jnp.clip((j + 1) * m - 1, 0, n - 1)
    lo = j * m - 1  # -1 for batch 0 → contributes 0
    lo_val = jnp.where(lo < 0, 0.0, csum[jnp.clip(lo, 0, n - 1)])
    return (csum[hi] - lo_val) / m


def sample_sd(x: jax.Array) -> jax.Array:
    """R's ``sd``: denominator n−1."""
    return jnp.std(x, ddof=1)


def batch_means(v: jax.Array, k: int, m: int) -> jax.Array:
    """Means of k consecutive batches of size m over the first k·m entries
    (vert-cor.R:131-140; the ``matrix(..., byrow=TRUE)`` + ``rowMeans`` form
    at ver-cor-subG.R:41-45)."""
    return v[: k * m].reshape(k, m).mean(axis=1)
