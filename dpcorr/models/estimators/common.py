"""Shared estimator plumbing: batch geometry, result container, R-compatible
sample sd."""

from __future__ import annotations

import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class CorrResult(NamedTuple):
    """Point estimate + CI.

    ``aux`` carries the per-variant extras the real-data reference functions
    return beyond the CI — batch geometry (k, m), λ thresholds, δ
    (real-data-sims.R:141-147, 244-252) — as a dict of scalars; ``None`` for
    variants without extras. Dropped before any vmap boundary (the MC
    simulator consumes only the three array fields).
    """

    rho_hat: jax.Array
    ci_low: jax.Array
    ci_high: jax.Array
    aux: Any = None


def batch_geometry(n: int, eps1: float, eps2: float,
                   enforce_min_k: bool = False) -> tuple[int, int]:
    """(m, k): batch size m = ⌈8/(ε₁ε₂)⌉ capped at n, k = ⌊n/m⌋ full batches.

    The paper's optimal batch design (vert-cor.R:124-126, ver-cor-subG.R:37-38).
    ``enforce_min_k`` adds the real-data fallback: if k < 2 then k = 2,
    m = ⌊n/2⌋ (real-data-sims.R:130). Static per design point — shapes are
    known at trace time, which is what keeps the kernels jit-compilable.
    """
    if n < 1:
        raise ValueError(f"Need at least one observation, got n={n}")
    m = math.ceil(8.0 / (eps1 * eps2))
    m = min(m, n)
    k = n // m
    if enforce_min_k and k < 2:
        k, m = 2, n // 2
    if k < 1:
        raise ValueError(
            f"Need at least one full batch: n={n}, m={m} (vert-cor.R:127)")
    return m, k


def batch_geometry_dyn(n: int, eps1, eps2,
                       enforce_min_k: bool = False):
    """Traced (m, k) int32 scalars for :func:`batch_geometry`'s rule —
    the ε values may be JAX tracers, so ONE compiled kernel can serve an
    entire ε-sweep (m and k become *data*, not program structure; the
    HRS sweep's 23 per-ε compiles collapse to one, `dpcorr/hrs.py`).
    ``n`` stays static: it is the physical array length and every shape
    in the masked kernel derives from it."""
    if n < 1:
        raise ValueError(f"Need at least one observation, got n={n}")
    q = 8.0 / (jnp.asarray(eps1, jnp.float32)
               * jnp.asarray(eps2, jnp.float32))
    # two float32 guards the static (float64) path never needs:
    # - the (1 - 1e-6) factor absorbs f32 round-UP at integer
    #   boundaries (e.g. ε=√2 squares to just under 2 in f32, making
    #   q = 4.0000001 and ceil jump to 5 where the static rule gives 4);
    #   a genuine fractional q is never 1e-6-close to an integer at
    #   these magnitudes, so only rounding artifacts snap down
    # - clipping BEFORE the int cast bounds q while still a float: at
    #   tiny ε₁ε₂ the unclipped f32 value can exceed int32 range, where
    #   astype would be implementation-defined instead of m=n
    m = jnp.clip(jnp.ceil(q * (1.0 - 1e-6)), 1.0, n).astype(jnp.int32)
    k = n // m
    if enforce_min_k:
        fallback = k < 2
        k = jnp.where(fallback, 2, k)
        m = jnp.where(fallback, n // 2, m)
    return m, k


def batch_means_dyn(v: jax.Array, m, k) -> jax.Array:
    """Masked equivalent of :func:`batch_means` for traced (m, k): means
    of the k consecutive batches of size m over the first k·m entries,
    returned padded to length n (entry j is meaningful only for j < k —
    mask downstream with ``arange(n) < k``). Element i contributes to
    batch i//m when i < k·m and to a discard bucket otherwise, so the
    per-batch sums keep the static path's consecutive-element order."""
    n = v.shape[0]
    idx = jnp.arange(n)
    seg = jnp.where(idx < k * m, idx // m, n)
    sums = jax.ops.segment_sum(v, seg, num_segments=n + 1)
    return sums[:n] / m


def sample_sd(x: jax.Array) -> jax.Array:
    """R's ``sd``: denominator n−1."""
    return jnp.std(x, ddof=1)


def batch_means(v: jax.Array, k: int, m: int) -> jax.Array:
    """Means of k consecutive batches of size m over the first k·m entries
    (vert-cor.R:131-140; the ``matrix(..., byrow=TRUE)`` + ``rowMeans`` form
    at ver-cor-subG.R:41-45)."""
    return v[: k * m].reshape(k, m).mean(axis=1)
