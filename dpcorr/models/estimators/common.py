"""Shared estimator plumbing: batch geometry, result container, R-compatible
sample sd."""

from __future__ import annotations

import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class CorrResult(NamedTuple):
    """Point estimate + CI.

    ``aux`` carries the per-variant extras the real-data reference functions
    return beyond the CI — batch geometry (k, m), λ thresholds, δ
    (real-data-sims.R:141-147, 244-252) — as a dict of scalars; ``None`` for
    variants without extras. Dropped before any vmap boundary (the MC
    simulator consumes only the three array fields).
    """

    rho_hat: jax.Array
    ci_low: jax.Array
    ci_high: jax.Array
    aux: Any = None


def batch_geometry(n: int, eps1: float, eps2: float,
                   enforce_min_k: bool = False) -> tuple[int, int]:
    """(m, k): batch size m = ⌈8/(ε₁ε₂)⌉ capped at n, k = ⌊n/m⌋ full batches.

    The paper's optimal batch design (vert-cor.R:124-126, ver-cor-subG.R:37-38).
    ``enforce_min_k`` adds the real-data fallback: if k < 2 then k = 2,
    m = ⌊n/2⌋ (real-data-sims.R:130). Static per design point — shapes are
    known at trace time, which is what keeps the kernels jit-compilable.
    """
    if n < 1:
        raise ValueError(f"Need at least one observation, got n={n}")
    m = math.ceil(8.0 / (eps1 * eps2))
    m = min(m, n)
    k = n // m
    if enforce_min_k and k < 2:
        k, m = 2, n // 2
    if k < 1:
        raise ValueError(
            f"Need at least one full batch: n={n}, m={m} (vert-cor.R:127)")
    return m, k


def sample_sd(x: jax.Array) -> jax.Array:
    """R's ``sd``: denominator n−1."""
    return jnp.std(x, ddof=1)


def batch_means(v: jax.Array, k: int, m: int) -> jax.Array:
    """Means of k consecutive batches of size m over the first k·m entries
    (vert-cor.R:131-140; the ``matrix(..., byrow=TRUE)`` + ``rowMeans`` form
    at ver-cor-subG.R:41-45)."""
    return v[: k * m].reshape(k, m).mean(axis=1)
